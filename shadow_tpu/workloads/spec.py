"""The scenario DSL: declarative, seeded workload specs (jax-free).

A *scenario* declares a fleet size, a window budget, and a list of
*pattern instances* — each a parameterized traffic shape over a
contiguous, non-overlapping range of hosts:

- ``ring_allreduce`` — the collective step structure of data-parallel
  training: ``steps`` ring hops per round, each participant sending one
  ``bytes`` chunk to its ring successor and advancing when the chunk
  from its predecessor lands (default ``steps = 2*(count-1)``, the
  reduce-scatter + all-gather hop count).
- ``all_to_all``     — expert/sequence-parallel shuffles: ``count-1``
  phases of a shifted permutation, host ``i`` sending to
  ``(i+1+s) mod count`` in phase ``s``.
- ``incast``         — the classic fan-in hotspot: ``count-1`` sources
  send to one sink, which acknowledges each round with a tiny control
  reply (closed-loop, so the event population stays bounded).
- ``rpc_fanout``     — request/response fan-out: a root sends
  ``req_bytes`` requests to ``count-1`` children; each child replies
  (``resp_bytes``) after a seeded per-(child, round) think time.
- ``onoff``          — per-host heavy-tail on/off CBR: bursts of
  ``burst`` packets to a seeded peer, OFF periods drawn from a bounded
  Pareto at compile time.

Everything random (peers, think times, off periods) is drawn by the
COMPILER from a numpy generator seeded with (scenario seed, pattern
index) — the device generator is purely table-driven, so the scenario
``fingerprint`` (and the traffic it produces) is a pure function of
(spec, seed). This module must stay importable without jax: configs are
parsed and validated on hosts that never touch the device plane.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

#: int32 virtual-time budget shared with the device plane
#: (path latency + window length < ~2.1 s, tpu/plane.py dtype discipline)
_I32_TIME_BUDGET = 2**31 - 1
#: the wire-size budget (SL506 input-domain registry,
#: analysis/ranges.py `BYTES_BUDGET` — pinned equal by
#: tests/test_ranges.py): capacity-scaled prefix sums over packet
#: bytes (the token-gate cumsum, per-window byte counters) must stay
#: inside int32, so one message caps at 16 MiB
_MAX_BYTES = 2**24

PATTERN_KINDS = ("ring_allreduce", "all_to_all", "incast", "rpc_fanout",
                 "onoff", "serve")


class ScenarioError(ValueError):
    """A scenario spec failed validation (the workload plane's
    ConfigError twin — raised at parse time, never mid-run)."""


def _req_int(raw: dict, key: str, where: str, *, default=None,
             lo: int = 0, hi: int = 2**31 - 1) -> int:
    v = raw.get(key, default)
    if v is None:
        raise ScenarioError(f"{where}: {key} is required")
    if isinstance(v, bool) or not isinstance(v, int):
        raise ScenarioError(f"{where}: {key} expected an integer, "
                            f"got {v!r}")
    if not (lo <= v <= hi):
        raise ScenarioError(f"{where}: {key}={v} out of range "
                            f"[{lo}, {hi}]")
    return v


def _req_float(raw: dict, key: str, where: str, *, default=None,
               lo: float = 0.0, hi: float = 1e12) -> float:
    v = raw.get(key, default)
    if v is None:
        raise ScenarioError(f"{where}: {key} is required")
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise ScenarioError(f"{where}: {key} expected a number, got {v!r}")
    if not (lo <= float(v) <= hi):
        raise ScenarioError(f"{where}: {key}={v} out of range "
                            f"[{lo}, {hi}]")
    return float(v)


@dataclass(frozen=True)
class PatternSpec:
    """One pattern instance over hosts [first, first + count)."""

    kind: str
    first: int
    count: int
    rounds: int
    bytes: int
    # rpc_fanout only
    resp_bytes: int = 64
    think_ns: int = 0
    think_jitter_ns: int = 0
    # onoff only
    burst: int = 0
    gap_ns: int = 0
    on_hold_ns: int = 0
    off_mean_ns: int = 0
    off_alpha: float = 1.5
    # serve only: open-loop arrival process (diurnal rate curve x
    # bounded-Pareto burst sizes) from `count - servers` clients
    # fanning into the first `servers` hosts of the range
    servers: int = 1
    mean_gap_ns: int = 0
    diurnal_period_ns: int = 0
    diurnal_amp: float = 0.0
    burst_cap: int = 8
    burst_alpha: float = 1.4

    def hosts(self) -> range:
        return range(self.first, self.first + self.count)

    def as_dict(self) -> dict:
        d = {"kind": self.kind, "first": self.first, "count": self.count,
             "rounds": self.rounds, "bytes": self.bytes}
        if self.kind == "rpc_fanout":
            d.update(resp_bytes=self.resp_bytes, think_ns=self.think_ns,
                     think_jitter_ns=self.think_jitter_ns)
        if self.kind == "onoff":
            d.update(burst=self.burst, gap_ns=self.gap_ns,
                     on_hold_ns=self.on_hold_ns,
                     off_mean_ns=self.off_mean_ns,
                     off_alpha=self.off_alpha)
        if self.kind == "serve":
            d.update(servers=self.servers, mean_gap_ns=self.mean_gap_ns,
                     diurnal_period_ns=self.diurnal_period_ns,
                     diurnal_amp=self.diurnal_amp,
                     burst_cap=self.burst_cap,
                     burst_alpha=self.burst_alpha)
        return d


@dataclass(frozen=True)
class ComputeSpec:
    """The scenario's ``compute:`` block — the per-host service model
    (`tpu/compute.py`): ``op`` names an entry of the checked-in
    op-timing table (`workloads/op_timings.json`, validated at compile
    time), ``queue_cap`` bounds the FIFO service queue."""

    op: str
    queue_cap: int = 64

    def as_dict(self) -> dict:
        return {"op": self.op, "queue_cap": self.queue_cap}


@dataclass(frozen=True)
class ServeSpec:
    """The scenario's ``serve:`` block — SLO targets for the recorded
    request-sojourn percentiles (docs/workloads.md "SLO record
    schema"). Targets are optional; when present the record carries a
    per-quantile ``met`` verdict next to the measured value."""

    p99_ns: Optional[int] = None
    p999_ns: Optional[int] = None

    def as_dict(self) -> dict:
        d: dict = {}
        if self.p99_ns is not None:
            d["p99_ns"] = self.p99_ns
        if self.p999_ns is not None:
            d["p999_ns"] = self.p999_ns
        return d


@dataclass(frozen=True)
class ScenarioSpec:
    """A validated scenario: fleet shape + pattern instances.

    ``transport`` selects how pattern sends reach the wire:
    ``direct`` (default) emits raw packets and credits phases on raw
    deliveries — the lossless contract; ``flows`` routes every send
    through the device flow plane (`tpu/flows.py`: cwnd/RTO/go-back-N
    retransmit), phases credit ACKED in-order segments, and the
    scenario may declare a non-zero uniform ``loss_p`` — the lossy
    half of the corpus (docs/robustness.md "Flow plane")."""

    name: str
    family: str  # the headline pattern family (corpus bookkeeping)
    seed: int
    n_hosts: int
    windows: int
    window_ns: int
    egress_cap: int
    ingress_cap: int
    transport: str = "direct"  # direct | flows
    loss_p: float = 0.0  # uniform path-loss probability
    compute: Optional[ComputeSpec] = None  # per-host service model
    serve: Optional[ServeSpec] = None  # SLO targets for the record
    patterns: tuple[PatternSpec, ...] = field(default_factory=tuple)

    def as_dict(self) -> dict:
        d = {
            "name": self.name, "family": self.family, "seed": self.seed,
            "hosts": self.n_hosts, "windows": self.windows,
            "window_ns": self.window_ns, "egress_cap": self.egress_cap,
            "ingress_cap": self.ingress_cap,
            "patterns": [p.as_dict() for p in self.patterns],
        }
        # non-default transport/loss/compute/serve keys only: the
        # canonical serialization (and therefore every existing
        # fingerprint) must not change under a default-valued new
        # field
        if self.transport != "direct":
            d["transport"] = self.transport
        if self.loss_p:
            d["loss_p"] = self.loss_p
        if self.compute is not None:
            d["compute"] = self.compute.as_dict()
        if self.serve is not None:
            d["serve"] = self.serve.as_dict()
        return d


def _parse_pattern(raw: Any, idx: int, n_hosts: int) -> PatternSpec:
    where = f"scenario.patterns[{idx}]"
    if not isinstance(raw, dict):
        raise ScenarioError(f"{where}: expected a mapping, got "
                            f"{type(raw).__name__}")
    kind = raw.get("kind")
    if kind not in PATTERN_KINDS:
        raise ScenarioError(
            f"{where}: kind expected one of {'|'.join(PATTERN_KINDS)}, "
            f"got {kind!r}")
    known = {"kind", "first", "count", "rounds", "bytes"}
    if kind == "rpc_fanout":
        known |= {"resp_bytes", "think_ns", "think_jitter_ns"}
    if kind == "onoff":
        known |= {"burst", "gap_ns", "on_hold_ns", "off_mean_ns",
                  "off_alpha"}
    if kind == "serve":
        known |= {"servers", "mean_gap_ns", "diurnal_period_ns",
                  "diurnal_amp", "burst_cap", "burst_alpha"}
    unknown = set(map(str, raw)) - known
    if unknown:
        raise ScenarioError(
            f"{where}: unknown {kind} option(s) {sorted(unknown)}")
    first = _req_int(raw, "first", where, default=0, lo=0,
                     hi=n_hosts - 1)
    min_count = 1 if kind == "onoff" else 2
    count = _req_int(raw, "count", where, default=n_hosts - first,
                     lo=min_count, hi=n_hosts - first)
    rounds = _req_int(raw, "rounds", where, default=1, lo=1, hi=4096)
    nbytes = _req_int(raw, "bytes", where, default=1400, lo=1,
                      hi=_MAX_BYTES)
    kw: dict = {}
    if kind == "rpc_fanout":
        kw["resp_bytes"] = _req_int(raw, "resp_bytes", where, default=64,
                                    lo=1, hi=_MAX_BYTES)
        kw["think_ns"] = _req_int(raw, "think_ns", where, default=0,
                                  lo=0, hi=_I32_TIME_BUDGET // 4)
        kw["think_jitter_ns"] = _req_int(
            raw, "think_jitter_ns", where, default=0, lo=0,
            hi=_I32_TIME_BUDGET // 4)
    if kind == "onoff":
        kw["burst"] = _req_int(raw, "burst", where, default=4, lo=1,
                               hi=256)
        kw["gap_ns"] = _req_int(raw, "gap_ns", where, default=100_000,
                                lo=0, hi=_I32_TIME_BUDGET // 4)
        # cross-field: the last burst lane's delay is (burst-1)*gap_ns
        # and must fit the int32 delay table (per-field bounds alone
        # admit 255 * I32/4, which overflows at compile)
        if (kw["burst"] - 1) * kw["gap_ns"] > _I32_TIME_BUDGET // 4:
            raise ScenarioError(
                f"{where}: (burst-1)*gap_ns = "
                f"{(kw['burst'] - 1) * kw['gap_ns']} ns exceeds the "
                f"int32 emission-delay budget "
                f"({_I32_TIME_BUDGET // 4} ns); shrink burst or gap_ns")
        kw["on_hold_ns"] = _req_int(raw, "on_hold_ns", where,
                                    default=0, lo=0,
                                    hi=_I32_TIME_BUDGET // 4)
        kw["off_mean_ns"] = _req_int(raw, "off_mean_ns", where,
                                     default=5_000_000, lo=1,
                                     hi=_I32_TIME_BUDGET // 4)
        kw["off_alpha"] = _req_float(raw, "off_alpha", where,
                                     default=1.5, lo=1.01, hi=10.0)
    if kind == "serve":
        kw["servers"] = _req_int(raw, "servers", where, default=1,
                                 lo=1, hi=count - 1)
        kw["mean_gap_ns"] = _req_int(raw, "mean_gap_ns", where,
                                     default=5_000_000, lo=1,
                                     hi=_I32_TIME_BUDGET // 4)
        kw["diurnal_period_ns"] = _req_int(
            raw, "diurnal_period_ns", where, default=0, lo=0,
            hi=_I32_TIME_BUDGET)
        kw["diurnal_amp"] = _req_float(raw, "diurnal_amp", where,
                                       default=0.0, lo=0.0, hi=0.95)
        kw["burst_cap"] = _req_int(raw, "burst_cap", where, default=8,
                                   lo=1, hi=64)
        kw["burst_alpha"] = _req_float(raw, "burst_alpha", where,
                                       default=1.4, lo=1.01, hi=10.0)
        if kw["diurnal_amp"] > 0 and kw["diurnal_period_ns"] == 0:
            raise ScenarioError(
                f"{where}: diurnal_amp={kw['diurnal_amp']} needs a "
                "non-zero diurnal_period_ns (a rate curve with no "
                "period is a constant)")
    return PatternSpec(kind=kind, first=first, count=count,
                       rounds=rounds, bytes=nbytes, **kw)


def parse_scenario(raw: Any, *, seed: Optional[int] = None) -> ScenarioSpec:
    """Parse + validate a scenario mapping (the body of a standalone
    scenario YAML's ``scenario:`` key, or a ``workload:`` config
    block's inline scenario). `seed` overrides the spec's own."""
    if isinstance(raw, dict) and set(raw) == {"scenario"}:
        raw = raw["scenario"]
    if not isinstance(raw, dict):
        raise ScenarioError(
            f"scenario: expected a mapping, got {type(raw).__name__}")
    known = {"name", "family", "seed", "hosts", "windows", "window_ns",
             "egress_cap", "ingress_cap", "patterns", "transport",
             "loss_p", "compute", "serve"}
    unknown = set(map(str, raw)) - known
    if unknown:
        raise ScenarioError(f"scenario: unknown option(s) "
                            f"{sorted(unknown)}")
    name = raw.get("name")
    if not isinstance(name, str) or not name:
        raise ScenarioError("scenario: name is required (a non-empty "
                            "string)")
    n_hosts = _req_int(raw, "hosts", "scenario", lo=2, hi=1 << 20)
    spec_seed = seed if seed is not None else _req_int(
        raw, "seed", "scenario", default=1, lo=0)
    windows = _req_int(raw, "windows", "scenario", default=64, lo=1,
                       hi=1 << 16)
    window_ns = _req_int(raw, "window_ns", "scenario",
                         default=10_000_000, lo=1_000,
                         hi=_I32_TIME_BUDGET // 4)
    egress_cap = _req_int(raw, "egress_cap", "scenario", default=16,
                          lo=1, hi=1 << 16)
    ingress_cap = _req_int(raw, "ingress_cap", "scenario", default=32,
                           lo=1, hi=1 << 16)
    transport = raw.get("transport", "direct")
    if transport not in ("direct", "flows"):
        raise ScenarioError(
            f"scenario: transport expected direct|flows, got "
            f"{transport!r}")
    loss_p = _req_float(raw, "loss_p", "scenario", default=0.0,
                        lo=0.0, hi=0.9)
    if loss_p > 0 and transport != "flows":
        # the lossless caveat, now ENFORCED instead of documented: the
        # direct phase machine has no retransmit layer, so a lost
        # dependency would stall a collective forever
        raise ScenarioError(
            f"scenario: loss_p={loss_p} requires `transport: flows` — "
            "direct-transport phases credit raw deliveries and have "
            "no retransmit layer, so any loss stalls the scenario "
            "(docs/robustness.md 'Flow plane')")
    if transport == "flows" and window_ns < 1_000_000:
        raise ScenarioError(
            f"scenario: `transport: flows` needs window_ns >= 1ms "
            f"(got {window_ns}): the flow plane's RTO clock advances "
            "in whole milliseconds per window (tpu/flows.py)")
    compute = None
    raw_compute = raw.get("compute")
    if raw_compute is not None:
        if not isinstance(raw_compute, dict):
            raise ScenarioError(
                f"scenario.compute: expected a mapping, got "
                f"{type(raw_compute).__name__}")
        unknown = set(map(str, raw_compute)) - {"op", "queue_cap"}
        if unknown:
            raise ScenarioError(f"scenario.compute: unknown option(s) "
                                f"{sorted(unknown)}")
        op = raw_compute.get("op")
        if not isinstance(op, str) or not op:
            raise ScenarioError(
                "scenario.compute: op is required (a non-empty name "
                "from workloads/op_timings.json)")
        compute = ComputeSpec(
            op=op,
            queue_cap=_req_int(raw_compute, "queue_cap",
                               "scenario.compute", default=64, lo=1,
                               hi=4096))
    serve_spec = None
    raw_serve = raw.get("serve")
    if raw_serve is not None:
        if not isinstance(raw_serve, dict):
            raise ScenarioError(
                f"scenario.serve: expected a mapping, got "
                f"{type(raw_serve).__name__}")
        unknown = set(map(str, raw_serve)) - {"p99_ns", "p999_ns"}
        if unknown:
            raise ScenarioError(f"scenario.serve: unknown option(s) "
                                f"{sorted(unknown)}")
        targets = {}
        for key in ("p99_ns", "p999_ns"):
            if raw_serve.get(key) is not None:
                targets[key] = _req_int(raw_serve, key,
                                        "scenario.serve", lo=1,
                                        hi=_I32_TIME_BUDGET)
        serve_spec = ServeSpec(**targets)
    raw_patterns = raw.get("patterns")
    if not isinstance(raw_patterns, list) or not raw_patterns:
        raise ScenarioError("scenario: patterns must be a non-empty "
                            "list")
    patterns = tuple(_parse_pattern(p, i, n_hosts)
                     for i, p in enumerate(raw_patterns))
    if any(p.kind == "serve" for p in patterns):
        # open-loop arrivals are meaningless without the service model
        # they are measured against, and the server tier's single
        # aggregate-dep phase is only deterministic when credits come
        # from the flow plane's ACKED in-order count
        if transport != "flows":
            raise ScenarioError(
                "scenario: serve patterns require `transport: flows` — "
                "server phases credit ACKED in-order segments, not raw "
                "deliveries (docs/workloads.md 'Serving load')")
        if compute is None:
            raise ScenarioError(
                "scenario: serve patterns require a `compute:` block — "
                "the open-loop arrival process is measured against the "
                "host service model (docs/workloads.md 'Serving load')")
    # host ranges must not overlap: each host carries exactly one phase
    # program (the compiler's phase axis is per-host, docs/workloads.md)
    claimed: dict[int, int] = {}
    for i, p in enumerate(patterns):
        for h in p.hosts():
            if h in claimed:
                raise ScenarioError(
                    f"scenario.patterns[{i}]: host {h} already claimed "
                    f"by patterns[{claimed[h]}] — pattern host ranges "
                    f"must be disjoint")
            claimed[h] = i
    family = raw.get("family", patterns[0].kind)
    if family not in PATTERN_KINDS:
        raise ScenarioError(
            f"scenario: family expected one of "
            f"{'|'.join(PATTERN_KINDS)}, got {family!r}")
    return ScenarioSpec(
        name=name, family=family, seed=spec_seed, n_hosts=n_hosts,
        windows=windows, window_ns=window_ns, egress_cap=egress_cap,
        ingress_cap=ingress_cap, transport=transport, loss_p=loss_p,
        compute=compute, serve=serve_spec, patterns=patterns)


def load_scenario_file(path: str, *,
                       seed: Optional[int] = None) -> ScenarioSpec:
    with open(path) as fh:
        raw = yaml.safe_load(fh)
    return parse_scenario(raw, seed=seed)


def scenario_fingerprint(spec: ScenarioSpec) -> str:
    """sha256 over the canonical spec serialization — a pure function
    of (spec, seed), pinned by tests: two parses of the same YAML (or
    the same spec built programmatically) fingerprint identically, and
    any field change (including the seed) changes it. The corpus
    runner stores it next to each golden digest so a digest mismatch
    distinguishes 'the scenario changed' from 'determinism broke'."""
    blob = json.dumps(spec.as_dict(), sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()
