"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the real
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this environment's sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon (the real-TPU tunnel), so the env var is already cached —
`jax.config.update` is the only override that still works here. Using the
tunnel from tests would be both slow (every dispatch crosses it) and wrong
(bench.py owns the real chip).
"""

import os
import random as _pyrandom

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import numpy as _np  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _np_state_equal(a, b) -> bool:
    # ('MT19937', keys ndarray, pos, has_gauss, cached_gaussian)
    return (a[0] == b[0] and _np.array_equal(a[1], b[1])
            and tuple(a[2:]) == tuple(b[2:]))


@pytest.fixture(autouse=True)
def _global_rng_guard(request):
    """Fail any test that mutates the hidden global RNG streams.

    The determinism contract (shadowlint SL102, docs/determinism.md)
    routes every simulation draw through the seeded streams in
    shadow_tpu/core/rng.py — the global `random` / `np.random` states
    must stay untouched so results can never depend on test order or
    import side effects. Opt out (e.g. to test an external library's
    seeding) with @pytest.mark.allow_global_rng.
    """
    if request.node.get_closest_marker("allow_global_rng"):
        yield
        return
    py_state = _pyrandom.getstate()
    np_state = _np.random.get_state()
    yield
    offenders = []
    if _pyrandom.getstate() != py_state:
        offenders.append("random")
    if not _np_state_equal(_np.random.get_state(), np_state):
        offenders.append("np.random")
    if offenders:
        pytest.fail(
            f"test mutated the global {' and '.join(offenders)} state; "
            "draw from the seeded streams in shadow_tpu/core/rng.py (or "
            "a local np.random.default_rng(seed)) instead — see "
            "docs/determinism.md (SL102)", pytrace=False)
