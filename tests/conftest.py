"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding paths are
exercised without TPU hardware (the driver separately dry-runs the real
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this environment's sitecustomize imports jax at interpreter start with
JAX_PLATFORMS=axon (the real-TPU tunnel), so the env var is already cached —
`jax.config.update` is the only override that still works here. Using the
tunnel from tests would be both slow (every dispatch crosses it) and wrong
(bench.py owns the real chip).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
