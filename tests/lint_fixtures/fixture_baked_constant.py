"""SL205 seeded violation: a 360 KB constant captured into the graph
instead of passed as a kernel argument (re-uploaded per compile)."""


def trace():
    import jax
    import numpy as np

    big = np.ones((300, 300), np.float32)  # 360 KB > 256 KiB limit
    return jax.make_jaxpr(lambda x: x + big)(np.float32(1.0))
