"""Negative fixture: hazard-adjacent but rule-clean code. Never imported."""

import numpy as np


def clean(hosts, seed):
    rng = np.random.default_rng(seed)
    order = sorted(set(hosts), key=id)
    draws = [rng.random() for _ in order]
    return dict(zip(order, draws))
