"""SL501 seeded violation for the compute plane: a deliberately-broken
mini window kernel that lets the compute plane's busy clock leak into
the delivery timestamps — the exact class of bug the FULL-invisibility
obligation ``window_step[compute]`` exists to catch (a compute plane
that back-pressures the wire inside the kernel instead of composing
through ``compute.gate_credits`` in the runner). `spec()` returns the
InvisibilitySpec; the proof must FAIL naming both ends of the flow:
``compute.busy_rel`` -> the delivered ``deliver_rel`` output leaf."""

from typing import NamedTuple


class MiniState(NamedTuple):
    clock: object  # jax.Array at trace time


class MiniCompute(NamedTuple):
    busy_rel: object


def _build():
    import jax.numpy as jnp

    def broken_step(state, compute):
        # BAD: service backlog delays the wire's delivery instants —
        # compute presence now perturbs simulation results
        delivered = {
            "deliver_rel": state.clock + compute.busy_rel,
            "mask": jnp.ones((4,), bool),
        }
        new_state = state._replace(clock=state.clock + 1)
        new_compute = compute._replace(
            busy_rel=compute.busy_rel + 10)
        return new_state, delivered, new_compute

    state = MiniState(jnp.zeros((4,), jnp.int32))
    compute = MiniCompute(jnp.zeros((4,), jnp.int32))
    return broken_step, (state, compute)


def spec():
    from shadow_tpu.analysis.proofs import InvisibilitySpec

    return InvisibilitySpec(
        "broken_step[compute-leak]", "tests.lint_fixtures",
        _build, tainted_args={1: "compute"},
        protected=lambda idx, path: idx < 2)
