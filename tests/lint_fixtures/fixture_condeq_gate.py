"""SL505 seeded violation: a deliberately-broken cond gate whose
"idle" fast branch is NOT the identity — it bumps a counter the merge
branch leaves alone, so the gate changes a bit, not just speed. The
prover must FAIL naming the first diverging output leaf
(`state.counter`) and the lattice point that exposed it."""

from typing import NamedTuple

import numpy as np


class MiniRing(NamedTuple):
    vals: object  # jax.Array at trace time
    counter: object


def _build():
    import jax
    import jax.numpy as jnp

    def gated_step(state, new_vals, valid):
        def merge(st):
            return st._replace(
                vals=jnp.where(valid, new_vals, st.vals))

        def idle(st):
            # BAD: the gated branch mutates state — on an entry-free
            # window the cond is no longer bitwise-invisible
            return st._replace(counter=st.counter + 1)

        return jax.lax.cond(valid.any(), merge, idle, state)

    state = MiniRing(jnp.zeros((4,), jnp.int32),
                     jnp.zeros((4,), jnp.int32))
    return gated_step, (state, jnp.zeros((4,), jnp.int32),
                        jnp.zeros((4,), bool))


def _lattice():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    pts = []
    for _ in range(6):
        state = MiniRing(
            jnp.asarray(rng.integers(0, 100, 4), jnp.int32),
            jnp.asarray(rng.integers(0, 100, 4), jnp.int32))
        # gated domain: no valid entries
        pts.append((state, jnp.zeros((4,), jnp.int32),
                    jnp.zeros((4,), bool)))
    state = MiniRing(jnp.zeros((4,), jnp.int32),
                     jnp.zeros((4,), jnp.int32))
    pts.append((state, jnp.ones((4,), jnp.int32),
                jnp.ones((4,), bool)))
    return pts


def obligation():
    from shadow_tpu.analysis.condeq import GateObligation

    return GateObligation(
        "broken_gate[counter-bump]", "tests.lint_fixtures", _build,
        gate_value=False, lattice=_lattice,
        out_names=lambda: ["state.vals", "state.counter"],
        min_gated=4)
