"""SL202 seeded violation: a dtype round-trip (int32 -> float32 ->
int32) — the jaxpr signature of weak-type churn, the classic
silent-recompile trigger."""


def trace():
    import jax
    import jax.numpy as jnp
    import numpy as np

    def churn(x):
        return x.astype(jnp.float32).astype(jnp.int32)

    return jax.make_jaxpr(churn)(np.zeros((4,), np.int32))
