"""SL701 seeded violation: a cross-world reduce inside an ensemble step.

The step normalizes each world's load vector by the ENSEMBLE-wide mean
— ``jnp.mean(loads)`` reduces over axis 0, which is the world axis, so
world b's output depends on every other world's state. The provenance
walk must flag the ``reduce_sum`` (and the broadcast of its result back
across worlds) as operations that cross the world axis.
"""

import jax.numpy as jnp


def build():
    def ensemble_step(loads):
        # BAD: the mean is taken over ALL worlds, then broadcast back —
        # worlds are no longer isolated.
        return loads / jnp.mean(loads)

    w = 4
    return ensemble_step, (jnp.arange(w * 8, dtype=jnp.float32).reshape(w, 8),)
