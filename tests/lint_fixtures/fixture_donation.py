"""SL503 seeded violations: buffer-donation hazards around the
tpu.donating_jit wrapper (docs/performance.md donation contract).
Linted as shadow_tpu/tpu/fixture_donation.py by test_shadowlint.py."""

import jax

from shadow_tpu.tpu import donating_jit

step = donating_jit(lambda st, d: st)
verify = donating_jit(lambda st, rows: st, donate_argnums=(0, 1))

# a conditional wrapper pick still marks the decorated def as donating
wrap = jax.jit if object() is None else donating_jit


@wrap
def chain(state, rids):
    return state


def drive_bad(state, deltas):
    out = step(state, deltas)
    total = state.n_sent.sum()  # violation: donated `state` read back
    return out, total


def drive_rebind_ok(state, deltas):
    state = step(state, deltas)  # consume-and-rebind: the sanctioned shape
    return state.n_sent.sum()


def drive_chain_bad(state, rids):
    out = chain(state, rids)
    print(state)  # violation: read after donation to the @wrap chain
    state = out
    return state


def drive_rows_bad(state, rows):
    state = verify(state, rows)
    return rows.sum()  # violation: arg 1 was donated too


def drive_suppressed(state, deltas):
    out = step(state, deltas)
    # shadowlint: disable=SL503 -- cpu-only diagnostic path (fixture)
    return out, state.n_sent.sum()


def raw_jit_bad(fn):
    return jax.jit(fn, donate_argnums=(0,))  # violation: bypasses wrapper


def donating_jit_lookalike_ok(fn):
    # a def NAMED donating_jit may forward donate_argnums (it IS the
    # wrapper pattern); this one is just named differently and clean
    return jax.jit(fn)
