"""SL601/SL602 seeded violation: a kernel that deliberately
materializes an [N, CE, CE] intermediate between two fusions — the
exact pairwise-rank blow-up the rank->place->egress fusion work
(ROADMAP-4) exists to remove. The producer fusion writes the cube,
the sort re-reads it, and a budget that pins ``big_boundaries: 0``
(or any tampered cost scalar) must fail naming the entry, the HLO op
pair, and the budget-vs-actual delta.

`entry()` returns the CostEntry; `budget(**overrides)` builds the
ledger document the checker is pointed at (defaults to the kernel's
LIVE costs, so a test perturbs exactly one number and every other
metric stays within tolerance).
"""

N, CE = 8, 8


def build():
    import jax
    import jax.numpy as jnp

    def kernel(x):
        # fusion 1 writes the [N, CE, CE] pairwise cube; the sort
        # cannot fuse with it, so the cube MATERIALIZES between them
        cube = jnp.exp(x)[:, :, None] * jnp.exp(x)[:, None, :]
        ranked = jax.lax.sort(cube, dimension=2)
        # fusion 2 re-reads the sorted cube
        return (ranked * 2).sum(axis=(1, 2))

    return kernel, (jnp.ones((N, CE), jnp.float32),)


def entry():
    from shadow_tpu.analysis.costmodel import CostEntry

    return CostEntry("tests.lint_fixtures:fusion_break", N, CE, build)


def budget(**overrides):
    """A cost_budgets.json document for the fixture entry: live costs
    with `overrides` applied (e.g. big_boundaries=0 to seed the SL602
    violation, or flops=<10x> to seed the SL601 drift)."""
    from shadow_tpu.analysis.costmodel import (_DEFAULT_TOLERANCE,
                                               _platform, entry_costs)

    metrics = dict(entry_costs(entry())["metrics"])
    metrics.update(overrides)
    return {
        "version": 1,
        "tolerance": _DEFAULT_TOLERANCE,
        "platforms": {_platform(): {
            "tests.lint_fixtures:fusion_break": metrics}},
    }
