"""SL203 seeded violation: a pure_callback inside a jitted kernel —
the device blocks on the host mid-window."""


def trace():
    import jax
    import numpy as np

    def cb(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct((), np.int32), x)

    return jax.make_jaxpr(cb)(np.int32(1))
