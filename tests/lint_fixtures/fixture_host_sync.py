"""SL603 seeded violation: per-iteration host syncs inside a driver
loop — a ``float()`` on a device value per window, a ``.item()`` tally,
a ``jax.device_get`` in the body, and a ``block_until_ready`` heartbeat
— exactly the per-window D2H stalls the chained driver exists to
amortize to chain ends. The clean shapes below (teardown reads outside
the loop, values already pulled through ONE device_get, numpy-on-host
arithmetic) must NOT fire.

Linted AS IF it were a driver module (relpath ``bench.py``) by
tests/test_costmodel.py.
"""

import jax
import numpy as np


def drive(state, windows, step):
    total = 0.0
    for w in range(windows):
        state, delivered, metrics = step(state, w)
        # violation: a blocking per-window materialization
        total += float(delivered.sum())
        # violation: a per-window counter read
        if metrics.events.item() > 0:
            pass
        # violation: a per-window device pull
        snap = jax.device_get(state.n_sent)  # noqa: F841
        # violation: a per-window pipeline flush
        jax.block_until_ready(state)
        # violation, then suppressed: the comment form works here too
        # shadowlint: disable=SL603 -- fixture: sanctioned debug read
        probe = np.asarray(delivered)  # noqa: F841
    return state, total


def drain_after(state, windows, step):
    """The sanctioned pattern: syncs at the chain end, not per
    iteration."""
    for w in range(windows):
        state, _delivered, _metrics = step(state, w)
    jax.block_until_ready(state)  # teardown flush: outside the loop
    return float(jax.device_get(state.n_sent).sum())


def digest(trees):
    """One pull, host loop after: the digest_pytrees shape."""
    total = 0
    for leaf in jax.tree.leaves(jax.device_get(trees)):
        arr = np.asarray(leaf)  # host value (device_get'd iterable)
        total += int(arr.sum())
    return total
