"""SL506 seeded violation: a kernel whose int32 arithmetic admits
wraparound under its registered input domains — the deliver clamp is
computed WITHOUT the latency budget, so `tsend + latency` can exceed
I32_MAX (exactly the bug class plane.make_params' latency clamp
exists to rule out). The range analysis must FAIL naming the op and
its computed interval."""

I32 = 2**31 - 1


def build():
    import jax.numpy as jnp

    def kernel(tsend, latency, window_ns):
        # BAD: latency is seeded to the FULL positive int32 domain
        # (no make_params budget), so the add wraps for late sends
        deliver = jnp.maximum(tsend + latency, window_ns)
        return deliver

    n = 4
    return kernel, (jnp.zeros((n,), jnp.int32),
                    jnp.zeros((n,), jnp.int32), jnp.int32(0))


def spec():
    from shadow_tpu.analysis.ranges import RangeSpec

    return RangeSpec(
        key="tests.lint_fixtures:unbudgeted_deliver",
        arg_names=["tsend", "latency", "window_ns"],
        domains={
            "tsend": (0, I32 // 4, "send times within the window"),
            "latency": (0, I32, "UNBUDGETED path latency — the seeded "
                                "violation"),
            "window_ns": (0, I32 // 4, "window budget"),
        })
