"""SL402 fixture: Python asserts inside vs outside kernel bodies.
Never imported.

Linted under a synthetic shadow_tpu/tpu/ path.
"""

import jax
import jax.numpy as jnp

from shadow_tpu.tpu import donating_jit


@jax.jit
def decorated_kernel(x):
    assert x.shape[0] > 0  # violation: assert in a jit-decorated body
    return x + 1


def wrapped_kernel(x):
    assert x.dtype == jnp.int32  # violation: fn passed to donating_jit
    return x * 2


_k = donating_jit(wrapped_kernel)


def chain(x):
    def body(c):
        assert c is not None  # violation: while_loop body
        return c - 1

    def cond(c):
        return c.sum() > 0

    return jax.lax.while_loop(cond, body, x)


def host_side_driver(batch):
    # NOT a kernel: host-side shape validation before dispatch is fine
    assert len(batch) > 0
    return decorated_kernel(jnp.asarray(batch))


def trace_time_check(cap: int):
    # NOT an assert: the sanctioned trace-time static check
    if cap <= 0:
        raise ValueError("capacity must be positive")
    return jnp.zeros((cap,), jnp.int32)
