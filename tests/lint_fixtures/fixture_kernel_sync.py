"""SL301 fixture: host syncs inside vs outside kernel bodies. Never
imported.

Linted under a synthetic shadow_tpu/tpu/ path.
"""

import jax
import jax.numpy as jnp

from shadow_tpu.tpu import donating_jit


@jax.jit
def decorated_kernel(x):
    y = x + 1
    jax.device_get(y)  # violation: sync inside a jit-decorated body
    return y


def wrapped_kernel(x):
    x.block_until_ready()  # violation: fn is passed to donating_jit below
    return x * 2


_k = donating_jit(wrapped_kernel)


def chain(x):
    def body(c):
        jax.device_get(c)  # violation: while_loop body
        return c - 1

    def cond(c):
        return c.sum() > 0

    return jax.lax.while_loop(cond, body, x)


_lam = jax.jit(lambda x: jax.device_get(x))  # violation: lambda under jit


def release_barrier(state):
    # NOT a kernel: the sanctioned sync point outside jitted code
    return jax.device_get(state)


def profiler_loop(fn, args):
    out = fn(*args)
    jax.block_until_ready(out)  # NOT a kernel: measurement harness
    return out


def plain_math(x):
    return jnp.where(x > 0, x, 0)
