"""SL204 seeded violation: a debug callback inside a scan body — one
host round trip per iteration."""


def trace():
    import jax
    import numpy as np

    def loop(x):
        def body(c, _):
            jax.debug.print("tick {}", c)
            return c + 1, c

        return jax.lax.scan(body, x, None, length=3)

    return jax.make_jaxpr(loop)(np.int32(0))
