"""SL104 fixture: mutable default arguments. Never imported."""

import collections


def list_default(xs=[]):  # line 6: violation
    return xs


def dict_default(*, opts={}):  # line 10: violation (kw-only)
    return opts


def set_and_call_defaults(seen=set(), extra=dict()):  # line 14: 2 violations
    return seen, extra


def deque_default(q=collections.deque()):  # line 18: violation
    return q


def allowed(xs=None, n=3, name="x", pair=(1, 2)):
    if xs is None:
        xs = []
    return xs, n, name, pair
