"""SL502 seeded violation: a kernel whose live op census carries one
MORE scatter than its checked-in budget — the "someone reintroduced a
per-column scatter" regression the ledger catches without a bench.
`entry()` returns an AuditEntry-shaped object and `BUDGET` is the
ledger the fixture kernel must be diffed against (it budgets 1
scatter; the kernel performs 2)."""

#: the checked-in budget the fixture kernel EXCEEDS by one scatter
BUDGET = {"scatter-add": 1, "sort": 1}


def build():
    import jax.numpy as jnp
    import numpy as np

    def kernel(vals, dst):
        n = vals.shape[0]
        order = jnp.sort(vals)
        acc = jnp.zeros((n,), jnp.int32).at[dst].add(order)
        # the regression: a second scatter pass that should have been
        # folded into the first
        acc = acc.at[dst].add(vals)
        return acc

    return kernel, (jnp.asarray(np.arange(8), jnp.int32),
                    jnp.asarray(np.arange(8) % 4, jnp.int32))


def entry():
    from shadow_tpu.analysis.jaxpr_audit import AuditEntry

    return AuditEntry("extra_scatter", "tests.lint_fixtures", build)
