"""SL102 fixture: global-stream randomness. Never imported."""

import random
import random as _rnd

import numpy as np


def violations():
    a = random.random()  # line 10: violation
    b = _rnd.randint(0, 7)  # line 11: violation (alias)
    random.seed(42)  # line 12: violation (reseeding the hidden stream)
    c = np.random.rand(3)  # line 14: violation (legacy global)
    np.random.shuffle([1, 2])  # line 15: violation
    return a, b, c


def allowed():
    rng = np.random.default_rng(7)  # seeded generator: allowed
    gen = np.random.Generator(np.random.PCG64(3))  # allowed
    return rng.integers(0, 10), gen
