"""SL702 seeded violation: a non-injective per-world key derivation.

The chain folds ``seed * 2`` into the root key. Multiplication by an
even constant is not injective mod 2**32 over the declared seed domain
(0, 2**31 - 1): seeds b and b + 2**31 collide after the wrap, so two
worlds would draw the same RNG stream. The fold-chain prover must
demote the seed at the ``mul`` and report the obligation unproved.
"""

import jax
import jax.numpy as jnp

from shadow_tpu.analysis.batchdim import RngObligation


def obligation():
    def build():
        root = jax.random.key(0)

        def fn(seed):
            # BAD: seed * 2 wraps mod 2**32 — worlds collide pairwise.
            return jax.random.fold_in(root, seed * 2)

        return fn, (jnp.int32(0),), 0, (0, 2**31 - 1)

    return RngObligation("tests.lint_fixtures:doubled_seed", build)
