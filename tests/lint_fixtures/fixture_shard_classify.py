"""SL504 seeded classification subject: one kernel mixing the three
shard classes — a row-local sort/gather (host axis batched), a
cross-host routing-style scatter keyed by computed destination ids, a
host-axis reduction, and a replicated-table lookup that must NOT count
as cross-host."""

import numpy as np

#: trace-time constant table (replicates under shard_map)
TABLE = np.arange(64, dtype=np.int32)


def build():
    import jax.numpy as jnp

    def kernel(vals, idx, dst):
        n, c = vals.shape
        local = jnp.take_along_axis(
            jnp.sort(vals, axis=1), idx, axis=1)  # row-local
        looked = jnp.asarray(TABLE)[jnp.clip(local, 0, 63)]  # table
        routed = jnp.zeros((n,), jnp.int32).at[
            dst.reshape(-1)].add(looked.reshape(-1),
                                 mode="drop")  # cross-host scatter
        return routed, looked.sum(axis=0)  # host-axis reduction

    n, c = 4, 8
    return kernel, (jnp.zeros((n, c), jnp.int32),
                    jnp.zeros((n, c), jnp.int32),
                    jnp.zeros((n, c), jnp.int32))
