"""SL401 fixture: swallowed broad exceptions vs acceptable handlers."""

import logging

log = logging.getLogger(__name__)


def swallow_exception():
    try:
        risky()
    except Exception:  # BAD: broad + pure swallow
        pass


def swallow_base_exception_tuple():
    try:
        risky()
    except (ValueError, BaseException):  # BAD: tuple containing broad
        ...


def bare_no_reraise():
    try:
        risky()
    except:  # noqa: E722  BAD: bare, no raise/log anywhere in body
        cleanup()


def bare_with_reraise():
    try:
        risky()
    except:  # noqa: E722  OK: re-raises
        cleanup()
        raise


def broad_but_logged():
    try:
        risky()
    except Exception:  # OK: not a pure swallow (and it logs)
        log.warning("risky failed", exc_info=True)


def broad_but_handled():
    try:
        risky()
    except Exception as e:  # OK: error is transported, not dropped
        record(e)


def narrow_swallow_ok():
    try:
        risky()
    except OSError:  # OK: narrow type, deliberate judgement call
        pass


def risky():
    raise ValueError


def cleanup():
    pass


def record(e):
    return e
