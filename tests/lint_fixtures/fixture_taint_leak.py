"""SL501 seeded violation: a deliberately-broken mini plane kernel
whose telemetry counter is wired BACK into simulation state — the
exact class of bug the presence-invisibility theorem exists to catch
(a plane that is no longer bitwise-invisible). `spec()` returns the
InvisibilitySpec; the proof must FAIL naming both ends of the flow:
`metrics.pkts` -> the sim counter output leaf."""

from typing import NamedTuple


class MiniState(NamedTuple):
    counter: object  # jax.Array at trace time
    clock: object


class MiniMetrics(NamedTuple):
    pkts: object


def _build():
    import jax.numpy as jnp

    def broken_step(state, metrics):
        # BAD: the metrics counter leaks into the sim-state counter —
        # presence of the plane now changes simulation results
        new_state = state._replace(
            counter=state.counter + metrics.pkts,
            clock=state.clock + 1)
        new_metrics = metrics._replace(pkts=metrics.pkts + 1)
        return new_state, new_metrics

    state = MiniState(jnp.zeros((4,), jnp.int32),
                      jnp.zeros((4,), jnp.int32))
    metrics = MiniMetrics(jnp.zeros((4,), jnp.int32))
    return broken_step, (state, metrics)


def spec():
    from shadow_tpu.analysis.proofs import InvisibilitySpec

    return InvisibilitySpec(
        "broken_step[metrics-leak]", "tests.lint_fixtures",
        _build, tainted_args={1: "metrics"},
        protected=lambda idx, path: idx < 1)
