"""SL405 fixture: host-side float()/.item() reads of device telemetry
arrays outside the harvest boundary (each BAD line is one finding)."""

import numpy as np


def bad_reads(metrics, state, hist):
    a = float(metrics.pkts_out.sum())  # BAD: float() on a metrics leaf
    b = metrics.drop_loss.sum().item()  # BAD: .item() on a metrics leaf
    c = float(state.n_out[0])  # BAD: transport telemetry counter
    d = hist.hist_delivery_ns.sum().item()  # BAD: histogram leaf
    e = float(metrics.windows)  # BAD: scalar telemetry leaf
    return a, b, c, d, e


def ok_reads(metrics, totals, weights):
    # host-side numpy on ALREADY-DRAINED totals is fine: no device read
    f = float(np.asarray(totals["pkts_out"]).sum())
    # float()/.item() on non-telemetry values is out of scope
    g = float(weights[0])
    h = weights.sum().item()
    # item with arguments is indexing sugar on a container, not a sync
    i = totals.item if hasattr(totals, "item") else None
    return f, g, h, i


def justified(metrics):
    # teardown-only diagnostic pull, documented:
    # shadowlint: disable=SL405 -- teardown diagnostic, run already over
    return float(metrics.events)
