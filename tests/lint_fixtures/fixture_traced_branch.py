"""SL105 fixture: Python branches on traced values. Never imported.

Linted under a synthetic shadow_tpu/tpu/ path.
"""

import jax
import jax.numpy as jnp
import numpy as np


def kernel(x, mask):
    if jnp.any(mask):  # line 12: violation
        x = x + 1
    while x.sum() > 0:  # line 14: violation (method reduction)
        x = x - 1
    y = x * 2 if jnp.all(mask) else x  # line 16: violation (ternary)
    assert jnp.max(x) < 100  # line 17: violation
    return y


def allowed(x, mask, rr_enabled):
    if rr_enabled:  # static python switch: fine
        x = x + 1
    if int(jax.device_get(mask.any())):  # explicit sync: fine
        x = x + 2
    host = np.asarray([1, 2, 3])
    if host.max() > 2:  # host-side numpy local: fine
        x = x + 3
    return jnp.where(mask, x, 0)  # data-dependent select: fine
