"""SL103 fixture: unordered iteration feeding scheduling. Never imported."""


def violations(hosts):
    pending = {h for h in hosts}  # building a set is fine
    for h in pending:  # line 6: violation (local inferred set-typed)
        h.execute()
    for h in set(hosts):  # line 8: violation
        h.execute()
    for h in list({1, 2, 3}):  # line 10: violation (wrapper preserves
        print(h)  # the lack of order)
    names = [n for n in frozenset(hosts)]  # line 12: violation
    other = pending | {object()}
    for h in other:  # line 14: violation (set | set)
        h.execute()
    return names


def allowed(hosts):
    pending = set(hosts)
    for h in sorted(pending, key=id):  # sorted: deterministic
        h.execute()
    if "x" in pending:  # membership is order-free
        pass
    for h in hosts:  # plain list
        h.execute()
    ordered = {h: 1 for h in hosts}
    for h in ordered:  # dicts are insertion-ordered
        h.execute()
