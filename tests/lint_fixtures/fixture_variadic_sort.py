"""SL403 fixture: variadic sorts past the sort-diet payload budget."""

import jax


def _row_sort(*arrays, keys: int):
    return jax.lax.sort(arrays, dimension=1, is_stable=True, num_keys=keys)


def fat_flat_sort(a, b, c, d, e, f):
    # 6 operands, 2 keys -> 4 payload: the variadic anti-pattern
    return jax.lax.sort((a, b, c, d, e, f), dimension=0, is_stable=True,
                        num_keys=2)


def fat_row_sort(a, b, c, d, e, f):
    # the wrapper counts too: 6 operands, 1 key -> 5 payload
    return _row_sort(a, b, c, d, e, f, keys=1)


def lean_flat_sort(a, b, c, d):
    # 4 operands, 1 key -> 3 payload: exactly at the budget, clean
    return jax.lax.sort((a, b, c, d), dimension=0, is_stable=True,
                        num_keys=1)


def suppressed_sort(a, b, c, d, e, f):
    # shadowlint: disable=SL403 -- legacy parity reference (fixture)
    return jax.lax.sort((a, b, c, d, e, f), dimension=0, is_stable=True,
                        num_keys=1)


def uncountable_sorts(packed, extras, col, arrays, k):
    # starred operands / computed key counts are not statically
    # countable and must be skipped, not guessed at
    one = jax.lax.sort((packed, *extras, col), dimension=1, num_keys=1)
    two = jax.lax.sort(arrays, dimension=0, num_keys=1)
    three = _row_sort(packed, col, keys=k)
    return one, two, three
