"""SL703 seeded violations: census drift plus refusal-hygiene rot.

Three findings are seeded across ``entries()`` / ``refusals()``:

1. ``world_count_unroll`` is a batched kernel whose graph is unrolled
   at the Python level over the world count — the primitive census
   grows with W, so the jaxpr is not world-count-stable (per-world
   behavior depends on how many worlds ride along).
2. ``refusals()`` carries a stale key naming no audited entry — the
   kernel it refused was renamed and the refusal never cleaned up.
3. ``lazy_refusal`` is refused with a whitespace rationale — a refusal
   is a registered engineering decision, not a skip.
"""

import jax.numpy as jnp

from shadow_tpu.analysis.batchdim import BatchEntry


def entries():
    def unroll_build_w(w):
        def build():
            def stepped(x):
                # BAD: Python-level unroll over the world count — the
                # graph (and its census) grows with W.
                y = x
                for _ in range(x.shape[0]):
                    y = y + 1.0
                return y

            return stepped, (jnp.zeros((w, 4)),)

        return build

    def plain_build_w(w):
        def build():
            def bump(x):
                return x + 1.0

            return bump, (jnp.zeros((w, 4)),)

        return build

    return [
        BatchEntry("tests.lint_fixtures:world_count_unroll", unroll_build_w),
        BatchEntry("tests.lint_fixtures:lazy_refusal", plain_build_w),
    ]


def refusals():
    return {
        # BAD: no audited entry by this key.
        "tests.lint_fixtures:ghost_kernel[pallas]": "ref: manual grid",
        # BAD: rationale-free refusal on a real (fixture) entry.
        "tests.lint_fixtures:lazy_refusal": "   ",
    }
