"""SL101 fixture: wall-clock reads, including suppressed and malformed.

Linted by tests/test_shadowlint.py under a synthetic shadow_tpu/ path;
never imported.
"""

import time
import time as _walltime
from datetime import datetime
from time import perf_counter_ns as _perf_ns


def violations():
    a = time.time()  # line 14: violation
    b = _walltime.monotonic()  # line 15: violation (module alias)
    c = _perf_ns()  # line 16: violation (from-import alias)
    d = datetime.now()  # line 17: violation
    return a, b, c, d


def suppressed_ok():
    return time.monotonic()  # shadowlint: disable=SL101 -- test justification


def suppressed_on_previous_line():
    # shadowlint: disable=SL101 -- justified on the preceding line
    return time.monotonic_ns()


def malformed_suppression():
    return time.perf_counter()  # shadowlint: disable=SL101
