"""SL201 seeded violation: a float64 value in the traced graph (the
device plane is int32/float32 by contract). `trace()` returns the
closed jaxpr the audit walks — the x64 leak needs the enable_x64
context at trace time, exactly how a stray config flip leaks one into
production graphs."""


def trace():
    import jax
    import numpy as np
    from jax.experimental import enable_x64

    with enable_x64():
        return jax.make_jaxpr(lambda x: x * np.float64(2.0))(
            np.float64(1.0))
