"""Unit tests for analysis/batchdim — the SL701–703 world-axis proofs.

Three layers:

* synthetic SL701 vectors: tiny jaxprs with a known world-axis story
  (clean per-world math stays clean; cross-world reduces, slices, and
  shared-operand scatters fire);
* the SL702 fold-chain prover on known-good and known-bad derivations;
* SL703 refusal hygiene on injected entries/refusals.

One REAL registry entry (``window_step[lean]`` at W=2) is proved in
tier-1 as the smoke link between the synthetic vectors and the full
``check_all_batch`` sweep, which is @slow (CI runs it unfiltered in
the gating proof step).
"""

import jax
import jax.numpy as jnp
import pytest

from shadow_tpu.analysis import batchdim, jaxpr_audit


def _axis_findings(fn, *args, w=None):
    w = args[0].shape[0] if w is None else w
    closed = jax.make_jaxpr(fn)(*args)
    return batchdim.world_axis_findings(closed, "test:synthetic", w)


# -- SL701 synthetic vectors: clean cases ----------------------------------

def test_per_world_elementwise_and_row_reduce_clean():
    def f(x):
        return jnp.sum(x * 2.0 + 1.0, axis=1)

    findings, row = _axis_findings(f, jnp.ones((2, 4)))
    assert not findings and row["proved"]
    # the world axis survives to the output at dim 0
    assert row["out_world_dims"] == [0]


def test_vmapped_shared_table_gather_clean():
    """Per-world indices into a world-FREE (closed-over) table: reads
    from shared constants are fine; only shared WRITES cross worlds."""
    table = jnp.arange(16.0)

    def per_world(idx):
        return table[idx]

    findings, row = _axis_findings(
        jax.vmap(per_world), jnp.zeros((3, 5), jnp.int32))
    assert not findings and row["proved"]


def test_vmapped_per_world_gather_and_scatter_clean():
    """Batched gather/scatter with DECLARED operand batching dims is
    structurally per-world regardless of index values."""
    def per_world(state, idx, upd):
        read = state[idx]
        return state.at[idx].add(upd), read

    findings, row = _axis_findings(
        jax.vmap(per_world),
        jnp.zeros((2, 8)), jnp.zeros((2, 3), jnp.int32), jnp.ones((2, 3)))
    assert not findings and row["proved"]


def test_vmapped_static_slice_set_clean():
    """``x.at[:, 0].set(v)`` under vmap lowers to a window-dim scatter
    (world-free scalar indices, world axis in update_window_dims) —
    the shape floweng's run_windows hits, and it must stay clean."""
    def per_world(x, v):
        return x.at[:, 0].set(v)

    findings, row = _axis_findings(
        jax.vmap(per_world), jnp.zeros((2, 4, 3)), jnp.ones((2, 4)))
    assert not findings and row["proved"]


# -- SL701 synthetic vectors: firing cases ---------------------------------

def test_cross_world_reduce_fires():
    def f(x):
        return x / jnp.mean(x)  # ensemble-wide mean

    findings, row = _axis_findings(f, jnp.ones((2, 4)))
    assert findings and not row["proved"]
    assert any("reduces over the world axis" in f_.message
               for f_ in findings)


def test_world_indexing_fires():
    def f(x):
        return x[0]  # world 0 singled out

    findings, _row = _axis_findings(f, jnp.ones((2, 4)))
    assert findings
    assert all(f_.rule == "SL701" for f_ in findings)


def test_scan_over_world_axis_fires():
    def f(x):
        def body(c, row):
            return c + row, c

        return jax.lax.scan(body, jnp.zeros(4), x)

    findings, _row = _axis_findings(f, jnp.ones((2, 4)))
    assert any("iterates OVER the world axis" in f_.message
               for f_ in findings)


def test_scatter_into_shared_operand_fires():
    """Per-world indices scattered into a world-FREE accumulator: the
    classic shared-histogram bug. No declared batching dims here, so
    the walk must flag the shared write."""
    def f(idx, upd):
        shared = jnp.zeros(8)
        dnums = jax.lax.ScatterDimensionNumbers(
            update_window_dims=(), inserted_window_dims=(0,),
            scatter_dims_to_operand_dims=(0,))
        return jax.lax.scatter_add(
            shared, idx[:, :, None], upd, dnums)

    findings, _row = _axis_findings(
        f, jnp.zeros((2, 3), jnp.int32), jnp.ones((2, 3)))
    assert any("world-SHARED operand" in f_.message for f_ in findings)


def test_findings_carry_source_location():
    def f(x):
        return jnp.sum(x, axis=0)

    findings, _ = _axis_findings(f, jnp.ones((2, 4)))
    assert findings
    # op + provenance: SL701 findings name a file:line when jax records one
    assert findings[0].rule == "SL701"
    assert "`reduce_sum`" in findings[0].message


# -- SL702: the fold-chain prover ------------------------------------------

def _rng_ob(name, fn_of_seed, domain=(0, 2**31 - 1)):
    def build():
        return fn_of_seed, (jnp.int32(0),), 0, domain

    return batchdim.RngObligation(name, build)


def test_identity_fold_proves():
    root = jax.random.key(0)
    findings, row = batchdim.prove_fold_chain(_rng_ob(
        "t:identity", lambda s: jax.random.fold_in(root, s)))
    assert not findings and row["ok"]
    assert any(step["prim"] == "random_fold_in" and step["status"] == "inj"
               for step in row["chain"])


def test_offset_fold_proves():
    """seed + const is a bijection mod 2**32 — injectivity survives."""
    root = jax.random.key(7)
    findings, row = batchdim.prove_fold_chain(_rng_ob(
        "t:offset", lambda s: jax.random.fold_in(root, s + 17)))
    assert not findings and row["ok"]


def test_real_world_key_obligation_proves():
    (ob,) = [o for o in batchdim.rng_obligations()
             if o.name == "shadow_tpu.tpu.elastic:world_key"]
    findings, row = batchdim.prove_fold_chain(ob)
    assert not findings and row["ok"]
    assert row["seed_domain"] == [0, 2**31 - 1]


def test_even_mul_fold_fires():
    root = jax.random.key(0)
    findings, row = batchdim.prove_fold_chain(_rng_ob(
        "t:doubled", lambda s: jax.random.fold_in(root, s * 2)))
    assert findings and not row["ok"]
    assert "mul" in findings[0].message


def test_modulo_fold_fires_naming_rem():
    """seed % 4 collapses the domain; the prover must name the `rem`
    inside the pjit it lowers under, not give up at the call."""
    root = jax.random.key(0)
    findings, row = batchdim.prove_fold_chain(_rng_ob(
        "t:mod4", lambda s: jax.random.fold_in(root, s % 4)))
    assert findings and not row["ok"]
    assert "rem" in findings[0].message


# -- SL703: census stability + refusal hygiene -----------------------------

def _entry(key, fn_of_w):
    def build_w(w):
        def build():
            fn, args = fn_of_w(w)
            return fn, args

        return build

    return batchdim.BatchEntry(key, build_w)


def test_stable_entry_passes_census():
    e = _entry("t:stable", lambda w: (lambda x: x + 1.0,
                                      (jnp.zeros((w, 4)),)))
    findings, rows, _refs = batchdim.check_vmap_census([e], refusals={})
    assert not findings
    assert rows == [{"entry": "t:stable", "ok": True,
                     "world_counts": list(batchdim.BATCH_WORLD_COUNTS),
                     "ops": rows[0]["ops"]}]


def test_world_count_unroll_fires_census_drift():
    def fn_of_w(w):
        def f(x):
            y = x
            for _ in range(w):  # graph grows with W
                y = y + 1.0
            return y

        return f, (jnp.zeros((w, 4)),)

    findings, _rows, _refs = batchdim.check_vmap_census(
        [_entry("t:unroll", fn_of_w)], refusals={})
    assert any("not world-count-stable" in f.message for f in findings)


def test_stale_and_empty_refusals_fire():
    e = _entry("t:refused", lambda w: (lambda x: x, (jnp.zeros((w, 2)),)))
    findings, _rows, refs = batchdim.check_vmap_census(
        [e], refusals={"t:ghost": "why", "t:refused": "  "})
    msgs = " | ".join(f.message for f in findings)
    assert "stale vmap refusal" in msgs
    assert "without a written rationale" in msgs
    # the refused entry is excused from the census sweep either way
    assert {r["entry"] for r in refs} == {"t:refused"}


def test_checked_in_refusals_are_pallas_only():
    """The real refusal surface: exactly the two pallas entries, each
    with a non-empty rationale (refusals are decisions, not skips)."""
    assert set(batchdim.VMAP_REFUSALS) == {
        "shadow_tpu.tpu.plane:window_step[pallas]",
        "shadow_tpu.tpu.plane:window_step[pallas_fused]",
    }
    assert all(r.strip() for r in batchdim.VMAP_REFUSALS.values())


# -- real entries ----------------------------------------------------------

def test_window_step_lean_proves_at_w2():
    """Tier-1 smoke proof on the flagship kernel: the lean window step,
    vmapped over two worlds, is world-isolated (shares the trace cache
    with the gating sweep, so this also pins the cache key shape)."""
    (entry,) = [e for e in batchdim.batch_entries()
                if e.key == "shadow_tpu.tpu.plane:window_step[lean]"]
    closed = jaxpr_audit.traced(
        f"{entry.key}@vmapW2", entry.build_w(2))[0]
    findings, row = batchdim.world_axis_findings(closed, entry.key, 2)
    assert not findings, [f.message for f in findings]
    assert row["proved"] and row["batched_ops"]


@pytest.mark.slow
def test_check_all_batch_clean_tree_wide():
    """The full gating sweep: every registered entry proves SL701 at
    W=2, the census is stable at W=2/W=3, both refusals are written,
    and the RNG obligation proves — zero active findings."""
    findings, report = batchdim.check_all_batch()
    active = [f for f in findings if not f.suppressed]
    assert not active, [str(f) for f in active]
    s = report["summary"]
    # summary.entries counts non-refused axis rows; all must prove
    assert s["entries"] >= 28 and s["refused"] == 2
    assert s["proved"] == s["entries"]
    assert all(r["ok"] for r in report["rng"])
