"""The shared chained-window driver: parity, boundaries, unification.

Pins the PR-11 driver-loop contract (docs/performance.md "The driver
loop", docs/determinism.md "Chain length is bitwise-invisible"):

- K windows driven through `tpu.elastic.drive_chained_windows` (the
  scan-chain default loop) end bitwise-identical to K single-window
  `window_step` calls — canonical state, delivered streams, metrics,
  guards accumulators, and histogram buckets — across the
  rr × aqm × no_loss compile matrix, including an elastic growth
  event mid-chain;
- the chain partition (chain_len, boundaries, resume offsets) is
  invisible to every digest;
- `plane.chain_windows` threads its presence switches without
  perturbing the stream;
- bench.py, tools/chaos_smoke.py, and the scenario corpus runner all
  route through the ONE driver (the inspect-source gate, so the three
  loops cannot silently fork again).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.guards import make_guards  # noqa: E402
from shadow_tpu.telemetry import make_histograms, make_metrics  # noqa: E402
from shadow_tpu.tpu import elastic, profiling  # noqa: E402
from shadow_tpu.tpu.plane import chain_windows, window_step  # noqa: E402
from shadow_tpu.workloads.phold import respawn_batch  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N = 32
K = 6


def _world(egress_cap=8, ingress_cap=16):
    return profiling.build_world(N, n_nodes=8, egress_cap=egress_cap,
                                 ingress_cap=ingress_cap, seed=3,
                                 warmup_windows=1)


def _leaves_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def _build_round_fn(params, rng_root, window, *, rr, aqm, no_loss):
    def round_fn(carry, rid):
        state, metrics, guards, hist = carry
        shift = jnp.where(rid == 0, jnp.int32(0), window)
        out = window_step(state, params, rng_root, shift, window,
                          rr_enabled=rr, router_aqm=aqm,
                          no_loss=no_loss, metrics=metrics,
                          guards=guards, hist=hist)
        state, delivered, _next = out[:3]
        rest = list(out[3:])
        if metrics is not None:
            metrics = rest.pop(0)
        if guards is not None:
            guards = rest.pop(0)
        if hist is not None:
            hist = rest.pop(0)
        return (state, metrics, guards, hist), delivered["mask"].sum(
            dtype=jnp.int32)
    return round_fn


@pytest.mark.slow  # CI's shared-driver gate runs this file unfiltered
@pytest.mark.parametrize("rr,aqm,no_loss",
                         [(False, False, False), (True, False, False),
                          (False, True, False), (True, True, True)])
def test_chained_matches_single_window_matrix(rr, aqm, no_loss):
    """K chained windows == K single-window dispatches, bitwise:
    canonical state, per-window delivered counts, metrics, guards
    accumulators, and histogram buckets, across rr × aqm × no_loss."""
    world = _world()
    params, rng_root, window = (world["params"], world["rng_root"],
                                world["window"])
    round_fn = _build_round_fn(params, rng_root, window,
                               rr=rr, aqm=aqm, no_loss=no_loss)

    # reference: one dispatch per window (the PR-10 driver shape)
    step = jax.jit(lambda c, r: round_fn(c, r))
    carry_ref = (world["state"], make_metrics(N), make_guards(N),
                 make_histograms(N))
    counts_ref = []
    for r in range(K):
        carry_ref, ndel = step(carry_ref, jnp.int32(r))
        counts_ref.append(int(ndel))

    # the chained default loop: all K windows in one scan dispatch
    @jax.jit
    def chain(state, metrics, guards, hist, rids, _pr):
        carry, counts = jax.lax.scan(
            round_fn, (state, metrics, guards, hist), rids)
        return carry, counts

    def chain_fn(state, extras, rids, pr):
        metrics, guards, hist, _counts = extras
        (state, metrics, guards, hist), counts = chain(
            state, metrics, guards, hist, rids, pr)
        return state, (metrics, guards, hist, counts), 0, 0

    state, extras = elastic.drive_chained_windows(
        world["state"], (make_metrics(N), make_guards(N),
                         make_histograms(N), None), chain_fn,
        n_rounds=K, chain_len=K)
    metrics, guards, hist, counts = extras

    ref_state, ref_metrics, ref_guards, ref_hist = carry_ref
    assert _leaves_equal(elastic.canonical_state(state),
                         elastic.canonical_state(ref_state))
    assert _leaves_equal(metrics, ref_metrics)
    assert _leaves_equal(guards, ref_guards)
    assert _leaves_equal(hist, ref_hist)
    assert [int(c) for c in np.asarray(counts)] == counts_ref


@pytest.mark.slow  # CI's shared-driver gate runs this file unfiltered
def test_chain_partition_is_bitwise_invisible():
    """chain_len 1 / 2 / K (and a ragged boundary set) all produce the
    identical final state — the chain is a dispatch schedule, not a
    semantic unit (docs/determinism.md)."""
    world = _world()
    params, rng_root, window = (world["params"], world["rng_root"],
                                world["window"])
    round_fn = _build_round_fn(params, rng_root, window,
                               rr=False, aqm=False, no_loss=False)

    @jax.jit
    def chain(state, rids):
        carry, _ = jax.lax.scan(round_fn, (state, None, None, None),
                                rids)
        return carry[0]

    def chain_fn(state, extras, rids, _pr):
        return chain(state, rids), extras, 0, 0

    outs = []
    for chain_len, boundaries in ((1, ()), (2, ()), (K, ()),
                                  (K, (1, 4))):
        state, _ = elastic.drive_chained_windows(
            world["state"], (), chain_fn, n_rounds=K,
            chain_len=chain_len, boundaries=boundaries)
        outs.append(state)
    for other in outs[1:]:
        assert _leaves_equal(outs[0], other)


def test_chain_spans_absolute_alignment():
    # resumed partitions must continue the absolute grid (the elastic
    # growth-decision unit), not restart relative to the resume point
    assert elastic.chain_spans(10, 4) == [(0, 4), (4, 8), (8, 10)]
    assert elastic.chain_spans(10, 4, start_round=5) == [(5, 8), (8, 10)]
    assert elastic.chain_spans(10, 4, boundaries=(6,)) == [
        (0, 4), (4, 6), (6, 8), (8, 10)]
    assert elastic.chain_spans(3, 8) == [(0, 3)]
    # a resume at or past the horizon runs NOTHING (the unguarded cut
    # set would invert into a span past the requested end)
    assert elastic.chain_spans(10, 4, start_round=10) == []
    assert elastic.chain_spans(10, 4, start_round=16) == []


@pytest.mark.slow  # growth mid-chain parity (~12s); CI's
# shared-driver gate runs this file unfiltered
def test_elastic_growth_mid_chain_matches_preprovisioned():
    """A PHOLD chain started on deliberately tiny rings under the
    elastic policy grows mid-chain (snapshot + re-execute per CHAIN)
    and ends canonically identical to a run pre-provisioned at the
    final capacity, with zero committed drops."""
    def phold_chain_fn(world):
        params, rng_root, window = (world["params"], world["rng_root"],
                                    world["window"])

        def round_fn(carry, rid):
            state, spawn_seq, eg, inn = carry
            state0 = state
            shift = jnp.where(rid == 0, jnp.int32(0), window)
            state, delivered, _next = window_step(
                state, params, rng_root, shift, window,
                rr_enabled=False)
            inn = inn + (state.n_overflow_dropped
                         - state0.n_overflow_dropped)
            state1 = state
            mask, dst, nbytes, seq, ctrl = respawn_batch(
                delivered, spawn_seq, rid, N, state.in_src.shape[1])
            from shadow_tpu.tpu import ingest_rows

            state = ingest_rows(state, dst, nbytes, seq, seq, ctrl,
                                valid=mask)
            eg = eg + (state.n_overflow_dropped
                       - state1.n_overflow_dropped)
            return (state, spawn_seq
                    + mask.sum(axis=1, dtype=jnp.int32), eg, inn), None

        @jax.jit
        def chain(state, spawn_seq, rids):
            zeros = jnp.zeros((N,), jnp.int32)
            carry, _ = jax.lax.scan(
                round_fn, (state, spawn_seq, zeros, zeros), rids)
            return carry

        def chain_fn(state, extras, rids, _pr):
            (state, spawn_seq, eg, inn) = chain(state, extras[0], rids)
            return state, (spawn_seq,), eg, inn

        return chain_fn

    # elastic from tiny rings: the 4 seed packets per host fit the
    # egress exactly (drops at world build would be committed before
    # the driver runs), the deliberately tiny ingress overflows
    # mid-chain and must grow
    tiny = profiling.build_world(N, n_nodes=8, egress_cap=4,
                                 ingress_cap=4, seed=3,
                                 warmup_windows=0)
    policy = elastic.RingPolicy(mode="elastic", max_doublings=4,
                                egress_cap=4, ingress_cap=4,
                                plane="test")
    spawn0 = jnp.full((N,), 10_000, jnp.int32)
    state_el, (spawn_el,) = elastic.drive_chained_windows(
        tiny["state"], (spawn0,), phold_chain_fn(tiny), n_rounds=K,
        chain_len=K, policy=policy, window_ns=int(tiny["window"]))
    growths = [e for e in policy.trajectory.events
               if e.get("kind") == "capacity-growth"]
    assert growths, "tiny rings never grew — dead test"
    assert int(np.asarray(state_el.n_overflow_dropped).sum()) == 0
    final_ce, final_ci = elastic.ring_dims(state_el)

    # pre-provisioned twin at the final capacity, single-window driven
    pre = profiling.build_world(N, n_nodes=8, egress_cap=final_ce,
                                ingress_cap=final_ci, seed=3,
                                warmup_windows=0)
    state_pre, (spawn_pre,) = elastic.drive_chained_windows(
        pre["state"], (spawn0,), phold_chain_fn(pre), n_rounds=K,
        chain_len=1)
    assert _leaves_equal(elastic.canonical_state(state_el),
                         elastic.canonical_state(state_pre))
    assert np.array_equal(np.asarray(spawn_el), np.asarray(spawn_pre))


@pytest.mark.slow  # presence-switch parity sweep (~9s); CI's
# shared-driver gate runs this file unfiltered
def test_chain_windows_presence_switches_are_invisible():
    """The while_loop idle chain with metrics/guards threaded ends in
    the same state as the bare chain, and the accumulators count every
    chained window (the jaxpr-audited carry variants)."""
    world = _world()
    params, rng_root = world["params"], world["rng_root"]
    w = jnp.int32(1_000_000)
    horizon = jnp.int32(200_000_000)

    base = jax.jit(lambda st: chain_windows(
        st, params, rng_root, jnp.int32(0), w, w, horizon, horizon,
        rr_enabled=False))(world["state"])
    st_b, dl_b, off_b, next_b, n_b = base

    withm = jax.jit(lambda st, m, g: chain_windows(
        st, params, rng_root, jnp.int32(0), w, w, horizon, horizon,
        rr_enabled=False, metrics=m, guards=g))(
        world["state"], make_metrics(N), make_guards(N))
    st_m, dl_m, off_m, next_m, n_m, metrics, guards = withm

    assert _leaves_equal((st_b, dl_b, off_b, next_b, n_b),
                         (st_m, dl_m, off_m, next_m, n_m))
    assert int(np.asarray(metrics.windows)) == int(np.asarray(n_m))
    from shadow_tpu.guards import summarize

    assert summarize(guards)["clean"]


def test_three_drivers_route_through_the_shared_loop():
    """bench.py, tools/chaos_smoke.py, and workloads/runner.py must
    all drive their windows through
    `tpu.elastic.drive_chained_windows` — the inspect-source gate that
    keeps the three loops from forking again (each hand-rolled its own
    attempt/snapshot/grow loop before PR 11)."""
    for rel in ("bench.py", os.path.join("tools", "chaos_smoke.py"),
                os.path.join("shadow_tpu", "workloads", "runner.py")):
        with open(os.path.join(REPO, rel)) as fh:
            src = fh.read()
        assert "drive_chained_windows" in src, (
            f"{rel} no longer routes through the shared chained-window "
            f"driver (tpu/elastic.drive_chained_windows)")
        assert "run_elastic_window" not in src.replace(
            "drive_chained_windows", ""), (
            f"{rel} grew a direct run_elastic_window loop again — "
            f"route it through drive_chained_windows")


def test_unpack_planes_shapes_and_mismatch():
    """The shared presence-output unpacker every driver uses: lead
    splits, declaration-order plane outputs, the bare-NamedTuple-state
    return of a plane-less ingest_rows (NetPlaneState IS a tuple — the
    exact-type check is the trap), and a loud mismatch."""
    from shadow_tpu.tpu import make_params, make_state
    from shadow_tpu.tpu.plane import unpack_planes

    params = make_params(np.full((4, 4), 5, np.int32),
                         np.zeros((4, 4), np.float32),
                         np.full((4,), 1_000, np.int64))
    state = make_state(4, egress_cap=4, ingress_cap=4, params=params)

    # bare state (ingest_rows, no planes): NOT unpacked as a tuple
    (st,), m, g, h, fr = unpack_planes(state, n_lead=1)
    assert st is state and (m, g, h, fr) == (None, None, None, None)

    # subset presence in declaration order, n_lead=3 (window_step)
    lead, m, g, h, fr = unpack_planes(
        ("s", "d", "n", "M", "H"), metrics="yes", hist="yes")
    assert lead == ("s", "d", "n") and (m, h) == ("M", "H")
    assert g is None and fr is None

    # unclaimed outputs fail loudly, never silently mis-assign
    with pytest.raises(TypeError, match="unclaimed"):
        unpack_planes(("s", "d", "n", "M", "H"), metrics="yes")


def test_fused_kernel_chain_parity():
    """K windows through the chained driver with kernel='pallas_fused'
    == the XLA reference, bitwise (the fused pipeline's interpret-mode
    contract under the default loop)."""
    world = _world(egress_cap=8, ingress_cap=16)
    params, rng_root, window = (world["params"], world["rng_root"],
                                world["window"])

    def make_chain_fn(kernel):
        def round_fn(carry, rid):
            state = carry
            shift = jnp.where(rid == 0, jnp.int32(0), window)
            state, delivered, _next = window_step(
                state, params, rng_root, shift, window,
                rr_enabled=False, kernel=kernel)
            return state, delivered["mask"].sum(dtype=jnp.int32)

        @jax.jit
        def chain(state, rids):
            return jax.lax.scan(round_fn, state, rids)

        def chain_fn(state, extras, rids, _pr):
            state, counts = chain(state, rids)
            return state, (counts,), 0, 0
        return chain_fn

    out = {}
    for kernel in ("xla", "pallas_fused"):
        state, (counts,) = elastic.drive_chained_windows(
            world["state"], (None,), make_chain_fn(kernel),
            n_rounds=K, chain_len=3)
        out[kernel] = (state, counts)
    assert _leaves_equal(out["xla"], out["pallas_fused"])
