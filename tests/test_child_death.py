"""Process-death robustness: a managed binary that dies without running its
shim destructor (SIGKILL, crash) must not deadlock the simulation.

Parity: reference `src/main/utility/childpid_watcher.rs` +
`managed_thread.rs:444-447` — the pidfd watcher closes the IPC channel
writer on child death, so a simulator thread blocked in recv wakes with
WriterIsClosed and the process is reaped as signal-killed.
"""

import os
import shutil
import signal
import time

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager
from shadow_tpu.process.process import ProcessState

SH = shutil.which("sh")


@pytest.mark.skipif(SH is None, reason="no sh binary")
def test_self_sigkill_does_not_deadlock():
    """The binary SIGKILLs itself: the kill syscall is passed through
    natively and the process dies while the simulator is blocked waiting
    for its next syscall — the deadlock scenario from round 1."""
    cfg = load_config_str(
        f"""
general: {{stop_time: 10s, seed: 3}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {SH}, args: ["-c", "kill -9 $$"], start_time: 1s,
       expected_final_state: {{signaled: 9}}}}
"""
    )
    start = time.monotonic()
    stats = Manager(cfg).run()
    wall = time.monotonic() - start
    assert stats.process_failures == [], stats.process_failures
    assert wall < 30.0  # and in particular: it finished at all


SLEEP = shutil.which("sleep")


@pytest.mark.skipif(SLEEP is None or SH is None, reason="needs sleep + sh")
def test_external_sigkill_mid_sleep_marks_process_killed():
    """SIGKILL arrives from outside while the binary is parked on a
    simulated sleep: the watcher closes the channel, the pending wakeup
    reply fails harmlessly, and the sim finishes with the process KILLED."""
    cfg = load_config_str(
        f"""
general: {{stop_time: 20s, seed: 4}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {SLEEP}, args: ["8"], start_time: 1s,
       expected_final_state: {{signaled: 9}}}}
"""
    )
    mgr = Manager(cfg)
    host = mgr.hosts_by_name["box"]

    # at sim t=2s (while sleep(8) is parked on its condition), SIGKILL the
    # native process from a host task — the simulation's own timeline
    from shadow_tpu.core.event import TaskRef

    def assassin(h):
        (proc,) = h.processes
        os.kill(proc.proc.pid, signal.SIGKILL)

    host.schedule_task_at(TaskRef(assassin, "assassin"), 2 * 10**9)

    start = time.monotonic()
    stats = Manager.run(mgr)
    wall = time.monotonic() - start
    assert stats.process_failures == [], stats.process_failures
    (proc,) = host.processes
    assert proc.state == ProcessState.KILLED
    assert proc.kill_signal == signal.SIGKILL
    assert wall < 30.0
