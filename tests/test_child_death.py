"""Process-death robustness: a managed binary that dies without running its
shim destructor (SIGKILL, crash) must not deadlock the simulation.

Parity: reference `src/main/utility/childpid_watcher.rs` +
`managed_thread.rs:444-447` — the pidfd watcher closes the IPC channel
writer on child death, so a simulator thread blocked in recv wakes with
WriterIsClosed and the process is reaped as signal-killed.
"""

import os
import shutil
import signal
import time

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager
from shadow_tpu.process.process import ProcessState

SH = shutil.which("sh")


@pytest.mark.skipif(SH is None, reason="no sh binary")
def test_self_sigkill_does_not_deadlock():
    """The binary SIGKILLs itself: the kill syscall is passed through
    natively and the process dies while the simulator is blocked waiting
    for its next syscall — the deadlock scenario from round 1."""
    cfg = load_config_str(
        f"""
general: {{stop_time: 10s, seed: 3}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {SH}, args: ["-c", "kill -9 $$"], start_time: 1s,
       expected_final_state: {{signaled: 9}}}}
"""
    )
    start = time.monotonic()
    stats = Manager(cfg).run()
    wall = time.monotonic() - start
    assert stats.process_failures == [], stats.process_failures
    assert wall < 30.0  # and in particular: it finished at all


ACCEPT_FOREVER_C = r"""
#include <netinet/in.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>
int main(void) {
    int ls = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons(7070);
    a.sin_addr.s_addr = INADDR_ANY;
    if (bind(ls, (struct sockaddr *)&a, sizeof a)) return 1;
    if (listen(ls, 4)) return 2;
    accept(ls, 0, 0); /* parks forever on a simulated condition */
    return 3;
}
"""

CC = shutil.which("gcc") or shutil.which("cc")


@pytest.mark.skipif(CC is None, reason="no C compiler")
def test_sigkill_while_parked_on_untimed_condition(tmp_path):
    """The binary is parked on a blocking accept() — an untimed
    SysCallCondition, nobody in recv_from_shim — when SIGKILL arrives.
    The watcher's posted reap task must still mark it killed and close its
    simulated sockets (round-2 review finding)."""
    import subprocess

    src = tmp_path / "acceptor.c"
    src.write_text(ACCEPT_FOREVER_C)
    binary = tmp_path / "acceptor"
    subprocess.run([CC, "-O1", "-o", str(binary), str(src)], check=True)

    cfg = load_config_str(
        f"""
general: {{stop_time: 20s, seed: 5}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s, expected_final_state: {{signaled: 9}}}}
  ticker:
    network_node_id: 0
    processes:
    - {{path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
"""
    )
    mgr = Manager(cfg)
    host = mgr.hosts_by_name["box"]
    from shadow_tpu.core.event import TaskRef

    def assassin(h):
        (proc,) = h.processes
        os.kill(proc.proc.pid, signal.SIGKILL)
        # wait (without reaping) until the kernel marks it dead, so the
        # death is observable before the simulation fast-forwards to its
        # end — an external kill is wall-asynchronous by nature
        os.waitid(os.P_PID, proc.proc.pid, os.WEXITED | os.WNOWAIT)

    host.schedule_task_at(TaskRef(assassin, "assassin"), 3 * 10**9)
    start = time.monotonic()
    stats = mgr.run()
    wall = time.monotonic() - start
    assert stats.process_failures == [], stats.process_failures
    (proc,) = host.processes
    assert proc.state == ProcessState.KILLED
    assert proc.kill_signal == signal.SIGKILL
    assert wall < 30.0


SLEEP = shutil.which("sleep")


@pytest.mark.skipif(SLEEP is None or SH is None, reason="needs sleep + sh")
def test_external_sigkill_mid_sleep_marks_process_killed():
    """SIGKILL arrives from outside while the binary is parked on a
    simulated sleep: the watcher closes the channel, the pending wakeup
    reply fails harmlessly, and the sim finishes with the process KILLED."""
    cfg = load_config_str(
        f"""
general: {{stop_time: 20s, seed: 4}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {SLEEP}, args: ["8"], start_time: 1s,
       expected_final_state: {{signaled: 9}}}}
"""
    )
    mgr = Manager(cfg)
    host = mgr.hosts_by_name["box"]

    # at sim t=2s (while sleep(8) is parked on its condition), SIGKILL the
    # native process from a host task — the simulation's own timeline
    from shadow_tpu.core.event import TaskRef

    def assassin(h):
        (proc,) = h.processes
        os.kill(proc.proc.pid, signal.SIGKILL)

    host.schedule_task_at(TaskRef(assassin, "assassin"), 2 * 10**9)

    start = time.monotonic()
    stats = Manager.run(mgr)
    wall = time.monotonic() - start
    assert stats.process_failures == [], stats.process_failures
    (proc,) = host.processes
    assert proc.state == ProcessState.KILLED
    assert proc.kill_signal == signal.SIGKILL
    assert wall < 30.0
