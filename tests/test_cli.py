"""CLI + output-artifact tests: processed-config, sim-stats.json, pcap,
exit codes, determinism harness (parity: reference `src/test/cli`,
`src/test/config`, determinism CI)."""

import json
import os
import struct
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG = """
general: {{stop_time: 5s, seed: 11, data_directory: {data_dir}}}
network: {{graph: {{type: 1_gbit_switch}}}}
host_defaults:
  pcap_enabled: true
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
  client:
    network_node_id: 0
    processes:
    - {{path: udp-client, args: ["server", "9000", "3", "50"], start_time: 2s}}
"""


def run_cli(args, cwd):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "shadow_tpu", *args],
        cwd=cwd, env=env, capture_output=True, text=True,
    )


def write_config(tmp_path, name="sim.yaml"):
    cfg = tmp_path / name
    cfg.write_text(CONFIG.format(data_dir=str(tmp_path / "data")))
    return cfg


def test_cli_run_and_artifacts(tmp_path):
    cfg = write_config(tmp_path)
    proc = run_cli([str(cfg)], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr
    data = tmp_path / "data"
    stats = json.loads((data / "sim-stats.json").read_text())
    assert stats["process_failures"] == []
    assert stats["packets_sent"] == 6  # 3 pings + 3 echoes
    assert stats["hosts"]["client"]["packets_out"] == 3
    assert (data / "processed-config.yaml").exists()
    pcap = (data / "hosts" / "client" / "eth0.pcap").read_bytes()
    magic, = struct.unpack("<I", pcap[:4])
    assert magic == 0xA1B2C3D4


def test_cli_refuses_existing_data_dir(tmp_path):
    cfg = write_config(tmp_path)
    (tmp_path / "data").mkdir()
    proc = run_cli([str(cfg)], cwd=tmp_path)
    assert proc.returncode == 1
    assert "exists" in proc.stderr
    proc = run_cli([str(cfg), "--force"], cwd=tmp_path)
    assert proc.returncode == 0, proc.stderr


def test_cli_exit_code_on_process_failure(tmp_path):
    cfg = tmp_path / "bad.yaml"
    cfg.write_text(
        """
general: {stop_time: 2s, seed: 1, data_directory: %s}
network: {graph: {type: 1_gbit_switch}}
hosts:
  a:
    network_node_id: 0
    processes:
    - {path: udp-echo-server, args: ["1"], start_time: 1s,
       expected_final_state: {exited: 0}}
"""
        % (tmp_path / "data2")
    )
    proc = run_cli([str(cfg)], cwd=tmp_path)
    assert proc.returncode == 1
    assert "process failure" in proc.stderr or "process failure" in proc.stdout


def test_cli_show_config(tmp_path):
    cfg = write_config(tmp_path)
    proc = run_cli([str(cfg), "--show-config"], cwd=tmp_path)
    assert proc.returncode == 0
    parsed = json.loads(proc.stdout)
    assert parsed["general"]["seed"] == 11
    assert "server" in parsed["hosts"]


def test_cli_exit_codes_documented():
    """The exit-code contract (docs/robustness.md): 0 ok, 1 simulation
    failure, 2 config error, 3 watchdog abort, 4 unhandled crash,
    5 guard abort."""
    from shadow_tpu import cli

    assert (cli.EXIT_OK, cli.EXIT_SIM_FAILURE, cli.EXIT_CONFIG,
            cli.EXIT_WATCHDOG, cli.EXIT_CRASH,
            cli.EXIT_GUARD) == (0, 1, 2, 3, 4, 5)


def test_cli_config_error_exit_code(tmp_path):
    cfg = tmp_path / "bad.yaml"
    cfg.write_text("general: {stop_time: 2s}\nbogus_section: {}\n")
    proc = run_cli([str(cfg)], cwd=tmp_path)
    assert proc.returncode == 2
    assert "config error" in proc.stderr


def test_cli_bad_fault_event_exit_code(tmp_path):
    """A bad `faults:` event dies as a ConfigError (exit 2) at Manager
    build, never as a mid-run traceback."""
    cfg = tmp_path / "badfault.yaml"
    cfg.write_text(
        """
general: {stop_time: 2s, data_directory: %s}
network: {graph: {type: 1_gbit_switch}}
faults:
  events:
    - {at: 1s, kind: host_crash, host: no-such-host}
hosts:
  a: {network_node_id: 0}
"""
        % (tmp_path / "dataf")
    )
    proc = run_cli([str(cfg)], cwd=tmp_path)
    assert proc.returncode == 2
    assert "not a configured host" in proc.stderr


def test_cli_crash_exit_code(tmp_path):
    """An unhandled error inside the run is exit 4 (distinct from the
    simulation-failure exit 1), with the traceback on stderr."""
    cfg = tmp_path / "crash.yaml"
    cfg.write_text(
        """
general: {stop_time: 2s, data_directory: %s}
network: {graph: {type: gml, file: /nonexistent/topology.gml}}
hosts:
  a: {network_node_id: 0}
"""
        % (tmp_path / "datac")
    )
    proc = run_cli([str(cfg)], cwd=tmp_path)
    assert proc.returncode == 4
    assert "Traceback" in proc.stderr


def test_cli_resume_on_round_loop_run_refused(tmp_path):
    cfg = write_config(tmp_path)
    proc = run_cli([str(cfg), "--resume", str(tmp_path / "nope")],
                   cwd=tmp_path)
    assert proc.returncode == 2
    assert "flow-engine" in proc.stderr


def test_determinism_harness(tmp_path):
    cfg = write_config(tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "compare_runs.py"), str(cfg)],
        env=env, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "DETERMINISTIC" in proc.stdout


def test_determinism_scheduler_matrix(tmp_path):
    """Artifacts must be identical across schedulers and thread counts
    (reference determinism2, src/test/determinism/CMakeLists.txt:8-24)."""
    cfg = write_config(tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "compare_runs.py"),
         str(cfg), "--matrix"],
        env=env, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "DETERMINISTIC" in proc.stdout
