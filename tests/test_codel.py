from shadow_tpu.core import simtime
from shadow_tpu.net.packet import Packet, PacketStatus, Protocol
from shadow_tpu.net.router import CoDelQueue, Router, INTERVAL, TARGET

MS = simtime.MILLISECOND


def _pkt(n=1200):
    return Packet(Protocol.UDP, ("11.0.0.1", 1), ("11.0.0.2", 2), b"x" * n)


def test_fifo_below_target():
    q = CoDelQueue()
    pkts = [_pkt() for _ in range(10)]
    for p in pkts:
        q.push(p, 0)
    out = [q.pop(5 * MS) for _ in range(10)]
    assert out == pkts  # FIFO, no drops below target
    assert q.pop(5 * MS) is None
    assert q.dropped_count == 0


def test_small_queue_never_drops():
    # standing delay above target but <= MTU bytes stored: good state
    q = CoDelQueue()
    p = _pkt(100)
    q.push(p, 0)
    assert q.pop(500 * MS) is p
    assert q.dropped_count == 0


def test_drops_after_sustained_delay():
    q = CoDelQueue()
    # Keep >MTU bytes stored and standing delay >TARGET for over an INTERVAL.
    for i in range(60):
        q.push(_pkt(), i)  # all enqueued ~t=0
    popped, dropped_seen = [], q.dropped_count
    # Pop slowly: one packet every 25ms starting at t=20ms (delay > 10ms TARGET)
    t = 20 * MS
    while len(q):
        p = q.pop(t)
        if p is not None:
            popped.append(p)
        t += 25 * MS
    assert q.dropped_count > 0, "sustained over-target delay must trigger drops"
    assert len(popped) + q.dropped_count == 60
    # dropped packets carry the ROUTER_DROPPED status
    assert all(
        PacketStatus.ROUTER_DROPPED not in p.statuses for p in popped
    )


def test_recovery_resets_to_store_mode():
    q = CoDelQueue()
    for i in range(60):
        q.push(_pkt(), 0)
    t = 20 * MS
    while len(q):
        q.pop(t)
        t += 25 * MS
    assert q.dropped_count > 0
    # now a fresh, fast-drained queue: no more drops
    before = q.dropped_count
    for i in range(10):
        q.push(_pkt(), t)
    for i in range(10):
        assert q.pop(t + 1 * MS) is not None
    assert q.dropped_count == before


def test_router_device():
    now = [0]
    sent = []
    r = Router("11.0.0.1", sent.append, lambda: now[0])
    assert r.get_address() == "11.0.0.1"
    assert r.pop() is None
    p = _pkt()
    r.route_incoming_packet(p)
    assert r.inbound_len() == 1
    assert r.pop() is p
    out = _pkt()
    r.push(out)  # outward: forwarded to the send hook
    assert sent == [out]
