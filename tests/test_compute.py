"""Compute plane + serving workload family (docs/workloads.md
"Serving load & the compute plane").

Covers:
- the presence contract at the kernel boundary: a world stepped with
  the compute plane threaded is bitwise-identical on sim state and
  the delivered stream to its no-compute twin (the runtime counterpart
  of the SL501 FULL-invisibility obligation `window_step[compute]`);
- presence-off parity at the corpus level: every pre-compute scenario
  fingerprint is pinned byte-for-byte (spec.as_dict emits `compute:` /
  `serve:` only when non-default);
- the seeded arrival process: compile-determinism of the serve family,
  end-to-end record determinism, and exact served/queued count pins;
- bounded-FIFO semantics: closed-form completion, queue-overflow
  refusal (tail trim) with exact counter pins, the queue_cap >= 1
  refusal, and credit gating;
- the service-table drift guard: the checked-in op-timing table is
  content-addressed (sha256 pin) and unknown ops refuse at compile;
- the analysis registry: the compute entries are registered across
  SL2xx/SL501/SL601 with checked-in budgets, and a seeded compute
  leak actually FIRES the invisibility checker.

Heavy full-corpus cases are @slow (the serving-corpus CI step runs
them unfiltered).
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from shadow_tpu.workloads import (ScenarioError, compile_program,
                                  load_scenario_file, parse_scenario,
                                  program_digest, scenario_fingerprint)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")
MS = 1_000_000

#: sha256 of the checked-in op-timing table (workloads/op_timings.json)
#: — the service-table drift guard. Any byte change to the table shifts
#: every serve-family program digest, so it must be a DELIBERATE,
#: golden-regenerating edit: update this pin and scenarios/GOLDEN.json
#: in the same commit.
OP_TIMINGS_SHA256 = \
    "1de31c94fae9adac33a52cc5402ab770a023fba2524bb476a86c2a6be04bc0fe"

#: scenario fingerprints of the PRE-compute corpus, pinned from
#: scenarios/GOLDEN.json at the commit that introduced the compute
#: plane: `compute:`/`serve:` default to absent in spec.as_dict, so
#: these may never move when the subsystem evolves.
PRE_COMPUTE_FINGERPRINTS = {
    "all_to_all.yaml":
        "94704010235100e64355918f4aa55703fdeed6a8bb47753b5c5bf9185cde5e71",
    "incast.yaml":
        "6fb9653e49ad596d3c746ff8dbc191e8861e9f8964d6444d7181e33cfef1030e",
    "incast_lossy.yaml":
        "dc51e65ccd762121ca6e7df6210c650d1a9d6b5bc4754269641c8b5391f8933d",
    "mixed.yaml":
        "1a5fcc4eec3f4bb3c5f9c569230c0c67c73f863ad7af3ae2fe8bc0cf0b3277bf",
    "onoff.yaml":
        "fcc4df627ee1fc12664fb457ea6c60e0cc732266ca4f0188b3c1307a14e546c0",
    "ring_allreduce.yaml":
        "55f3787aecb8fb022e5592b9ddb2e0e05509e8e818e7d7cbd81b333ca088b6a1",
    "rpc_fanout.yaml":
        "7bab9cc091be8e0e529399a625e5774659307327bd89550d80a3dd7ef18cc67c",
    "rpc_fanout_lossy.yaml":
        "90922436e925e86a5c723ba5a2aa39159ed1be80a5e43d1c96c58c60f2d94e93",
}


def _serve_raw(**kw):
    raw = {"name": "serve-mini", "hosts": 6, "windows": 48,
           "window_ns": 5 * MS, "egress_cap": 8, "ingress_cap": 32,
           "transport": "flows", "seed": 3,
           "compute": {"op": "embed_lookup", "queue_cap": 8},
           "serve": {"p99_ns": 50 * MS},
           "patterns": [{"kind": "serve", "count": 6, "servers": 1,
                         "rounds": 2, "bytes": 512,
                         "mean_gap_ns": 1 * MS, "burst_cap": 2,
                         "burst_alpha": 1.4}]}
    raw.update(kw)
    return raw


# -- presence parity at the kernel boundary --------------------------------


def test_compute_presence_bitwise_invisible():
    """Twin worlds, 4 windows: one threads the compute plane through
    window_step, the twin does not. Sim state and the delivered dict
    must match bitwise — the compute plane reads deliveries, it never
    back-pressures the wire inside the kernel (credit gating composes
    in the runner, `compute.gate_credits`)."""
    import jax
    import jax.numpy as jnp

    from shadow_tpu.tpu import compute as cm
    from shadow_tpu.tpu import profiling
    from shadow_tpu.tpu.plane import window_step

    world = profiling.build_world(32, warmup_windows=0)
    params, key, window = world["params"], world["rng_root"], \
        world["window"]
    ct = cm.make_compute_tables(np.full((32, 1), 25_000, np.int32),
                                queue_cap=16)

    def run(with_compute):
        state = profiling.build_world(32, warmup_windows=0)["state"]
        cs = cm.make_compute_state(ct) if with_compute else None

        @jax.jit
        def step(st, cs, sh):
            out = window_step(st, params, key, sh, window,
                              rr_enabled=False,
                              compute=((ct, cs) if with_compute
                                       else None))
            if with_compute:
                return out[0], out[1], out[3]
            return out[0], out[1], None

        last_d = None
        for r in range(4):
            state, last_d, cs = step(
                state, cs, jnp.int32(0 if r == 0 else int(window)))
        return state, last_d, cs

    a_state, a_d, _ = run(False)
    b_state, b_d, cs = run(True)
    for name, la, lb in zip(a_state._fields, a_state, b_state):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), name
    for k in a_d:
        assert np.array_equal(np.asarray(a_d[k]), np.asarray(b_d[k])), k
    # the plane was actually live on the twin, not compiled out
    assert int(np.asarray(cs.n_served).sum()) > 0


def test_pre_compute_corpus_fingerprints_pinned():
    """Presence-off parity at the corpus level: every existing
    scenario's fingerprint is byte-unchanged by the compute subsystem
    (spec.as_dict emits `compute:`/`serve:` only when set)."""
    for fname, want in sorted(PRE_COMPUTE_FINGERPRINTS.items()):
        spec = load_scenario_file(os.path.join(REPO, "scenarios", fname))
        assert scenario_fingerprint(spec) == want, fname
        assert "compute" not in spec.as_dict()
        assert "serve" not in spec.as_dict()


# -- spec + compile refusals -----------------------------------------------


def test_serve_spec_validation():
    with pytest.raises(ScenarioError, match="transport: flows"):
        parse_scenario(_serve_raw(transport="direct"))
    raw = _serve_raw()
    del raw["compute"]
    with pytest.raises(ScenarioError, match="compute"):
        parse_scenario(raw)
    with pytest.raises(ScenarioError, match="diurnal"):
        parse_scenario(_serve_raw(patterns=[
            {**_serve_raw()["patterns"][0], "diurnal_amp": 0.5}]))
    with pytest.raises(ScenarioError, match="op"):
        compile_program(parse_scenario(_serve_raw(
            compute={"op": "not_a_real_op", "queue_cap": 8})))


def test_queue_cap_refusal():
    from shadow_tpu.tpu import compute as cm

    with pytest.raises(ValueError, match="queue_cap"):
        cm.make_compute_tables(np.zeros((4, 1), np.int32), queue_cap=0)
    with pytest.raises(ScenarioError, match="queue_cap"):
        parse_scenario(_serve_raw(
            compute={"op": "embed_lookup", "queue_cap": 0}))


def test_service_table_drift_guard():
    """The op-timing table is content-addressed: compile-time service
    costs come ONLY from the checked-in file, and this pin makes any
    edit a deliberate golden-regenerating change."""
    from shadow_tpu.workloads import serve

    assert serve.op_timings_digest() == OP_TIMINGS_SHA256
    # cost formula on the checked-in entries: fixed + per_kib * ceil
    assert serve.op_service_ns("embed_lookup", 512) == 1800 + 120
    assert serve.op_service_ns("embed_lookup", 1025) == 1800 + 2 * 120
    assert serve.op_service_ns("attn_decode", 1024) == 21000 + 310
    with pytest.raises(ScenarioError, match="op timing table"):
        serve.op_service_ns("not_a_real_op", 64)


# -- bounded FIFO semantics ------------------------------------------------


def _delivered(n, ci, mask):
    import jax.numpy as jnp

    return {"mask": jnp.asarray(mask),
            "src": jnp.zeros((n, ci), jnp.int32),
            "seq": jnp.asarray(
                np.tile(np.arange(ci, dtype=np.int32), (n, 1))),
            "sock": jnp.zeros((n, ci), jnp.int32),
            "bytes": jnp.full((n, ci), 512, jnp.int32),
            "deliver_rel": jnp.zeros((n, ci), jnp.int32)}


def test_queue_overflow_tail_trim_and_gating():
    """8 simultaneous arrivals into a 4-deep queue at 4 ms service in a
    10 ms window: 2 complete, 4 wait, the LAST 2 are refused — and the
    credit gate releases exactly the served count."""
    import jax.numpy as jnp

    from shadow_tpu.tpu import compute as cm

    ct = cm.make_compute_tables(np.full((2, 1), 4 * MS, np.int32),
                                queue_cap=4)
    cs = cm.make_compute_state(ct)
    mask = np.zeros((2, 8), bool)
    mask[0, :] = True
    cs2 = cm.compute_step(ct, cs, _delivered(2, 8, mask),
                          jnp.int32(0), jnp.int32(10 * MS))
    assert np.asarray(cs2.n_served).tolist() == [2, 0]
    assert np.asarray(cs2.n_overflow).tolist() == [2, 0]
    assert np.asarray(cs2.q_depth).tolist() == [4, 0]
    # refused arrivals never enter the backlog: busy ends when the 6
    # admitted requests drain, not the 8 offered
    assert int(np.asarray(cs2.busy_rel)[0]) == 6 * 4 * MS
    cs3, got = cm.gate_credits(
        cs2, jnp.asarray(np.array([8, 0], np.int32)))
    assert np.asarray(got).tolist() == [2, 0]
    assert np.asarray(cs3.n_granted).tolist() == [2, 0]
    # the gate is cumulative: re-offering grants nothing new until
    # more service completes
    _, again = cm.gate_credits(cs3,
                               jnp.asarray(np.array([8, 0], np.int32)))
    assert np.asarray(again).tolist() == [0, 0]


def test_zero_service_host_passes_credits_through():
    """svc == 0 rows (clients, emission-only phases) serve instantly:
    every arrival completes in its own window with no backlog, so the
    credit gate passes the raw counts through bitwise-unchanged."""
    import jax.numpy as jnp

    from shadow_tpu.tpu import compute as cm

    ct = cm.make_compute_tables(np.zeros((3, 1), np.int32), queue_cap=4)
    cs = cm.make_compute_state(ct)
    mask = np.zeros((3, 8), bool)
    mask[0, :5] = True
    mask[2, :7] = True
    cs2 = cm.compute_step(ct, cs, _delivered(3, 8, mask),
                          jnp.int32(0), jnp.int32(10 * MS))
    assert np.asarray(cs2.n_served).tolist() == [5, 0, 7]
    assert np.asarray(cs2.n_queued).tolist() == [0, 0, 0]
    raw = jnp.asarray(np.array([5, 0, 7], np.int32))
    _, got = cm.gate_credits(cs2, raw)
    assert np.asarray(got).tolist() == [5, 0, 7]


# -- seeded arrival process ------------------------------------------------


def test_serve_compile_deterministic_and_seeded():
    a = compile_program(parse_scenario(_serve_raw()))
    b = compile_program(parse_scenario(_serve_raw()))
    assert program_digest(a) == program_digest(b)
    assert a.compute_service_ns is not None
    assert a.compute_service_ns.dtype == np.int32
    # a different seed draws a different arrival process
    c = compile_program(parse_scenario(_serve_raw(seed=4)))
    assert program_digest(a) != program_digest(c)
    # the table is folded into the digest: same arrivals, different
    # op => different program
    d = compile_program(parse_scenario(_serve_raw(
        compute={"op": "attn_decode", "queue_cap": 8})))
    assert program_digest(a) != program_digest(d)


def test_serve_record_deterministic_with_exact_counts():
    """End-to-end: the mini serve scenario double-runs byte-identical,
    completes, and pins its exact served/queued counts (the seeded
    arrival process is part of the determinism contract)."""
    from shadow_tpu.workloads import runner

    a = runner.run_scenario(parse_scenario(_serve_raw()))
    b = runner.run_scenario(parse_scenario(_serve_raw()))
    assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                      sort_keys=True)
    assert a["all_done"]
    assert a["compute"] == {"op": "embed_lookup", "queue_cap": 8,
                            "served": 24, "queued": 12, "overflow": 0}
    soj = a["slo"]["sojourn_ns"]
    assert all(soj[q] >= 0 for q in ("p50", "p90", "p99", "p999"))
    assert soj["p999"] >= soj["p99"] >= soj["p50"]
    assert a["slo"]["targets"]["p99"]["met"] is True


@pytest.mark.slow
@pytest.mark.parametrize("fname,served,queued", [
    ("serve_diurnal.yaml", 186, 112),
    ("serve_burst_lossy.yaml", 297, 203),
])
def test_serve_corpus_entry_pins(fname, served, queued):
    """The checked-in serving corpus against GOLDEN.json plus exact
    arrival-count pins — >0 queued proves the SLO histograms measure
    real contention, not an idle queue."""
    from shadow_tpu.workloads import runner

    spec = load_scenario_file(os.path.join(REPO, "scenarios", fname))
    rec = runner.run_scenario(spec)
    golden = runner.load_golden(
        os.path.join(REPO, "scenarios", "GOLDEN.json"))
    assert runner.check_against_golden(
        [rec], {rec["name"]: golden[rec["name"]]}) == []
    assert rec["all_done"]
    assert rec["compute"]["served"] == served
    assert rec["compute"]["queued"] == queued
    assert rec["compute"]["overflow"] == 0
    assert queued > 0
    for q in ("p99", "p999"):
        t = rec["slo"]["targets"][q]
        assert t["measured_ns"] <= t["target_ns"], (q, t)


# -- analysis registry -----------------------------------------------------


def test_compute_entries_registered_with_budgets():
    """The compute plane is on every proof surface: SL2xx audit
    entries, the SL501 obligation, the SL601 cost entry — and both
    budget ledgers carry the checked-in rows (regenerating budgets can
    never silently drop them)."""
    from shadow_tpu.analysis import costmodel, jaxpr_audit, proofs

    names = {f"{e.module}:{e.name}"
             for e in jaxpr_audit.default_entries()}
    assert "shadow_tpu.tpu.plane:window_step[compute]" in names
    assert "shadow_tpu.tpu.plane:chain_windows[compute]" in names
    assert "shadow_tpu.tpu.compute:compute_step" in names
    specs = {s.name for s in proofs.invisibility_specs()}
    assert "window_step[compute]" in specs
    cost_keys = {e.key for e in costmodel.default_cost_entries()}
    assert "shadow_tpu.tpu.plane:window_step[compute]" in cost_keys
    with open(os.path.join(
            REPO, "shadow_tpu", "analysis", "op_budgets.json"),
            encoding="utf-8") as fh:
        budgets = json.load(fh)["budgets"]
    for key in ("shadow_tpu.tpu.plane:window_step[compute]",
                "shadow_tpu.tpu.plane:chain_windows[compute]",
                "shadow_tpu.tpu.compute:compute_step"):
        assert key in budgets, key
    with open(os.path.join(
            REPO, "shadow_tpu", "analysis", "cost_budgets.json"),
            encoding="utf-8") as fh:
        cost = json.load(fh)["platforms"]
    assert any("shadow_tpu.tpu.plane:window_step[compute]" in v
               for v in cost.values())


def test_compute_leak_fixture_fires_sl501():
    """The obligation has teeth: a seeded compute->wire leak (busy
    clock added to delivery instants) FAILS the invisibility proof
    naming both ends of the flow."""
    import importlib.util

    from shadow_tpu.analysis import proofs

    spec = importlib.util.spec_from_file_location(
        "fixture_compute_leak",
        os.path.join(FIXTURES, "fixture_compute_leak.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    findings = proofs.check_invisibility(mod.spec())
    assert findings and findings[0].rule == "SL501"
    assert "busy_rel" in findings[0].message
    assert "deliver_rel" in findings[0].message


@pytest.mark.slow
def test_compute_invisibility_proof_holds():
    """The real kernel passes its SL501 obligation (the gating CI proof
    step runs the full surface; this pins the compute spec alone)."""
    from shadow_tpu.analysis import proofs

    spec = [s for s in proofs.invisibility_specs()
            if s.name == "window_step[compute]"]
    assert len(spec) == 1
    assert proofs.check_invisibility(spec[0]) == []
