"""The SL505 branch-equivalence prover (analysis/condeq.py):

- every registered gate on the REAL tree proves (the acceptance
  gate), with the expected mode per gate — the ident-vs-sort gates
  structurally (sorted-predicate + selection witness), the idle gates
  exhaustively with non-vacuous gated-domain coverage;
- the prover engine's pieces: canonical syntactic equality, the
  sortedness-predicate pattern matcher, the selection witness's
  refusal to bless arithmetic on coded data, duplicate-operand
  coding;
- the deliberately-broken fixture gate FAILS naming the first
  diverging output leaf and the lattice point;
- a vacuous lattice (never exercising the gated domain) is an error,
  not a pass.
"""

import importlib.util
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from shadow_tpu.analysis import condeq  # noqa: E402

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def _load_fixture(name: str):
    spec = importlib.util.spec_from_file_location(
        name.removesuffix(".py"), os.path.join(FIXTURES, name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- the real tree ---------------------------------------------------------

#: the proof modes the registered gates are EXPECTED to close under —
#: a gate silently degrading from structural to exhaustive (or a new
#: gate arriving unregistered) changes this table on purpose
EXPECTED_MODES = {
    "ingest_rows[gate_idle]": "exhaustive",
    "_compact_ingress[ordered]": "structural",
    "_egress_order[fifo-ordered]": "structural",
    "flow_recv[idle]": "exhaustive",
    "flow_emit[idle]": "exhaustive",
}


def test_gate_surface_covers_the_registered_contracts():
    names = {o.name for o in condeq.gate_obligations()}
    assert names == set(EXPECTED_MODES), names ^ set(EXPECTED_MODES)


@pytest.mark.slow  # re-proves the full gate surface (~5s); the CI
# proof gate runs the identical proofs via shadowlint --only SL505,
# and CI's proof-suite step runs this file UNFILTERED (the
# EXPECTED_MODES pin stays gating there)
@pytest.mark.parametrize(
    "obl", condeq.gate_obligations(), ids=lambda o: o.name)
def test_gate_proves_on_the_real_tree(obl):
    proof = condeq.check_gate(obl)
    assert proof.ok, f"{obl.name}: {proof.detail}"
    assert proof.mode == EXPECTED_MODES[obl.name], \
        (obl.name, proof.mode, proof.detail)
    if proof.mode == "exhaustive":
        # the fallback must not be vacuous: the lattice has to land in
        # the gated domain well past the floor
        assert proof.gated_points >= obl.min_gated, proof


@pytest.mark.slow  # a second full gate sweep; CI proof-suite step
# runs it unfiltered
def test_real_gates_report_serializes():
    findings, proofs = condeq.check_all_gates()
    assert findings == []
    js = [p.to_json() for p in proofs]
    assert all(g["ok"] for g in js)
    assert {g["mode"] for g in js} == {"structural", "exhaustive"}


# -- the engine ------------------------------------------------------------

def test_syntactic_mode_on_identical_branches():
    """Branches that differ only by dead code canonicalize equal."""
    def fn(p, x):
        def a(v):
            return v + 1

        def b(v):
            _dead = v * 3  # noqa: F841 — dead on purpose
            return v + 1

        return jax.lax.cond(p, a, b, x)

    obl = condeq.GateObligation(
        "syntactic", "tests", lambda: (fn, (True, jnp.int32(1))),
        gate_value=True)
    proof = condeq.check_gate(obl)
    assert proof.ok and proof.mode == "syntactic", proof


def test_sorted_assumption_pattern_matcher():
    """The predicate pattern `(k[:, :-1] <= k[:, 1:]).all()` marks the
    operand sorted along axis 1; an unrelated predicate marks nothing."""
    def gate(k, x):
        ordered = (k[:, :-1] <= k[:, 1:]).all()
        return jax.lax.cond(ordered, lambda ops: ops[1],
                            lambda ops: ops[1] * 1, (k, x))

    closed = jax.make_jaxpr(gate)(
        jnp.zeros((3, 4), jnp.uint32), jnp.zeros((3, 4), jnp.int32))
    _, eqn = condeq._find_gate(closed)
    assumptions = condeq._sorted_assumptions(closed.jaxpr, eqn)
    assert assumptions and all(ax == 1 for ax in assumptions.values())

    def gate2(k, x):
        return jax.lax.cond(k.sum() > 0, lambda ops: ops[1],
                            lambda ops: ops[1] * 1, (k, x))

    closed2 = jax.make_jaxpr(gate2)(
        jnp.zeros((3, 4), jnp.int32), jnp.zeros((3, 4), jnp.int32))
    _, eqn2 = condeq._find_gate(closed2)
    assert condeq._sorted_assumptions(closed2.jaxpr, eqn2) == {}


def test_witness_rejects_arithmetic_on_coded_data():
    """A branch that ADDS to operand data is not a selection circuit:
    the structural path must refuse (fall back), never bless it."""
    def fn(p, x):
        return jax.lax.cond(p, lambda v: v, lambda v: v + 0, x)

    closed = jax.make_jaxpr(fn)(True, jnp.zeros((4,), jnp.int32))
    _, eqn = condeq._find_gate(closed)
    ok, detail = condeq._structural_proof(eqn, closed.jaxpr)
    assert ok is None and "add" in detail


def test_structural_witness_failure_is_a_finding():
    """Two pure-selection branches that select DIFFERENT elements must
    fail structurally (not fall back): a reversing 'identity'."""
    def fn(k, x):
        ordered = (k[:, :-1] <= k[:, 1:]).all()

        def ident(ops):
            return ops[1]

        def rev(ops):
            return ops[1][:, ::-1]  # selects different elements

        return jax.lax.cond(ordered, ident, rev, (k, x))

    obl = condeq.GateObligation(
        "rev-gate", "tests",
        lambda: (fn, (jnp.zeros((3, 4), jnp.uint32),
                      jnp.zeros((3, 4), jnp.int32))),
        gate_value=True)
    proof = condeq.check_gate(obl)
    assert not proof.ok and proof.mode == "failed"
    assert proof.findings and proof.findings[0].rule == "SL505"


def test_duplicate_operands_share_codes():
    """jax does not dedup the branch closures' operand union; the same
    parent value at two positions must carry identical witness codes
    (the bug that made the real ident-vs-sort gates 'diverge')."""
    def fn(k, x):
        ordered = (k[:, :-1] <= k[:, 1:]).all()

        def ident(_ops):
            return x  # closure capture -> its own operand slot

        def sort_branch(ops):
            order = jax.lax.sort((ops[0], jnp.broadcast_to(
                jnp.arange(4, dtype=jnp.int32), (3, 4))),
                dimension=1, is_stable=True, num_keys=1)[1]
            return jnp.take_along_axis(x, order, axis=1)

        return jax.lax.cond(ordered, ident, sort_branch, (k, x))

    obl = condeq.GateObligation(
        "dup-operands", "tests",
        lambda: (fn, (jnp.zeros((3, 4), jnp.uint32),
                      jnp.zeros((3, 4), jnp.int32))),
        gate_value=True)
    proof = condeq.check_gate(obl)
    assert proof.ok and proof.mode == "structural", proof


# -- failure reporting -----------------------------------------------------

def test_broken_fixture_gate_fails_naming_the_leaf():
    fixture = _load_fixture("fixture_condeq_gate.py")
    proof = condeq.check_gate(fixture.obligation())
    assert not proof.ok and proof.mode == "failed"
    [finding] = proof.findings
    assert finding.rule == "SL505"
    assert "state.counter" in finding.message  # the diverging leaf
    assert "state.vals" not in finding.message  # the clean leaf
    assert "lattice point" in finding.message


def test_vacuous_lattice_is_an_error():
    """A lattice that never exercises the gated domain proves nothing
    and must FAIL, not pass silently."""
    fixture = _load_fixture("fixture_condeq_gate.py")
    obl = fixture.obligation()
    gated = [p for p in obl.lattice()
             if not bool(np.asarray(p[2]).any())]
    ref_only = [p for p in obl.lattice()
                if bool(np.asarray(p[2]).any())]
    assert gated and ref_only  # sanity on the fixture lattice
    obl2 = condeq.GateObligation(
        obl.name, obl.module, obl.build, gate_value=obl.gate_value,
        lattice=lambda: ref_only, out_names=obl.out_names,
        min_gated=obl.min_gated)
    proof = condeq.check_gate(obl2)
    assert not proof.ok and "vacuous" in proof.detail
