import pytest

from shadow_tpu.core import config, simtime
from shadow_tpu.core.config import (
    ConfigError,
    FinalState,
    LogLevel,
    QDiscMode,
    load_config_str,
    to_processed_dict,
)

BASIC = """
general:
  stop_time: 10s
  model_unblocked_syscall_latency: true
network:
  graph:
    type: 1_gbit_switch
hosts:
  server:
    network_node_id: 0
    processes:
    - path: python3
      args: -m http.server 80
      start_time: 3s
      expected_final_state: running
  client1: &client_host
    network_node_id: 0
    processes:
    - path: curl
      args: -s server
      start_time: 5s
  client2: *client_host
  client3: *client_host
"""


def test_basic_file_transfer_shape():
    cfg = load_config_str(BASIC)
    assert cfg.general.stop_time == 10 * simtime.SECOND
    assert cfg.general.model_unblocked_syscall_latency is True
    assert cfg.network.graph.type == "1_gbit_switch"
    assert set(cfg.hosts) == {"server", "client1", "client2", "client3"}
    srv = cfg.hosts["server"].processes[0]
    assert srv.path == "python3"
    assert srv.args == ["-m", "http.server", "80"]
    assert srv.start_time == 3 * simtime.SECOND
    assert srv.expected_final_state.kind == FinalState.RUNNING
    # YAML anchors give clients identical process lists
    assert cfg.hosts["client2"].processes[0].path == "curl"


def test_inline_gml_and_bare_seconds():
    cfg = load_config_str(
        """
general:
  stop_time: 300
network:
  graph:
    type: gml
    inline: "graph []"
hosts:
  a: {network_node_id: 0}
"""
    )
    assert cfg.general.stop_time == 300 * simtime.SECOND
    assert cfg.network.graph.inline == "graph []"


def test_overrides_win_over_file():
    cfg = load_config_str(BASIC, overrides={"general": {"seed": 99, "stop_time": "5s"}})
    assert cfg.general.seed == 99
    assert cfg.general.stop_time == 5 * simtime.SECOND
    # untouched fields keep file/default values
    assert cfg.general.model_unblocked_syscall_latency is True


def test_extension_keys_ignored():
    cfg = load_config_str(
        """
x-anchors:
  common: {foo: 1}
general:
  stop_time: 1s
hosts:
  a: {network_node_id: 0}
"""
    )
    assert "a" in cfg.hosts


def test_unknown_keys_rejected():
    with pytest.raises(ConfigError, match="unknown option"):
        load_config_str("general: {stop_time: 1s, frobnicate: 2}\nhosts: {a: {}}")
    with pytest.raises(ConfigError, match="unknown top-level"):
        load_config_str("general: {stop_time: 1s}\nbogus: {}\nhosts: {a: {}}")


def test_required_fields():
    with pytest.raises(ConfigError, match="stop_time"):
        load_config_str("hosts: {a: {}}")
    with pytest.raises(ConfigError, match="at least one host"):
        load_config_str("general: {stop_time: 1s}")


def test_expected_final_state_forms():
    cfg = load_config_str(
        """
general: {stop_time: 1s}
hosts:
  a:
    processes:
    - {path: /bin/true, expected_final_state: {exited: 3}}
    - {path: /bin/kill, expected_final_state: {signaled: 9}}
"""
    )
    p0, p1 = cfg.hosts["a"].processes
    assert (p0.expected_final_state.kind, p0.expected_final_state.value) == (FinalState.EXITED, 3)
    assert (p1.expected_final_state.kind, p1.expected_final_state.value) == (FinalState.SIGNALED, 9)


def test_experimental_and_host_defaults():
    cfg = load_config_str(
        """
general: {stop_time: 1s, log_level: debug}
experimental:
  runahead: 5ms
  interface_qdisc: round-robin
  use_dynamic_runahead: true
host_defaults:
  pcap_enabled: true
hosts:
  a:
    bandwidth_down: 100 Mbit
    bandwidth_up: 50 Mbit
"""
    )
    assert cfg.general.log_level == LogLevel.DEBUG
    assert cfg.experimental.runahead == 5 * simtime.MILLISECOND
    assert cfg.experimental.interface_qdisc == QDiscMode.ROUND_ROBIN
    assert cfg.host_defaults.pcap_enabled is True
    assert cfg.hosts["a"].bandwidth_down == 10**8
    assert cfg.hosts["a"].bandwidth_up == 5 * 10**7


def test_graph_validation():
    with pytest.raises(ConfigError, match="exactly one"):
        load_config_str(
            "general: {stop_time: 1s}\nnetwork: {graph: {type: gml}}\nhosts: {a: {}}"
        )
    with pytest.raises(ConfigError, match="unknown type"):
        load_config_str(
            "general: {stop_time: 1s}\nnetwork: {graph: {type: petersen}}\nhosts: {a: {}}"
        )


def test_processed_config_roundtrip():
    cfg = load_config_str(BASIC)
    d = to_processed_dict(cfg)
    assert d["general"]["stop_time"] == 10 * simtime.SECOND
    assert d["hosts"]["server"]["processes"][0]["path"] == "python3"
    # must be YAML-serializable
    import yaml

    yaml.safe_dump(d)


def test_hostname_validation():
    with pytest.raises(ConfigError, match="invalid hostname"):
        load_config_str("general: {stop_time: 1s}\nhosts: {'bad host!': {}}")
    cfg = load_config_str("general: {stop_time: 1s}\nhosts: {'lossy.tcpserver.echo': {}}")
    assert "lossy.tcpserver.echo" in cfg.hosts


def test_plane_kernel_flag_validates():
    """experimental.plane_kernel accepts xla/pallas and rejects loudly."""
    assert config.ConfigOptions().experimental.plane_kernel == "xla"
    cfg = load_config_str(
        BASIC.replace("general:",
                      "experimental:\n  plane_kernel: pallas\ngeneral:"))
    assert cfg.experimental.plane_kernel == "pallas"
    with pytest.raises(ConfigError, match="plane_kernel"):
        load_config_str(
            BASIC.replace("general:",
                          "experimental:\n  plane_kernel: cuda\ngeneral:"))
    # pallas_fused: accepted, and additionally needs a power-of-two
    # ingress ring (the in-kernel compaction bitonic)
    cfg = load_config_str(
        BASIC.replace("general:",
                      "experimental:\n  plane_kernel: pallas_fused\n"
                      "general:"))
    assert cfg.experimental.plane_kernel == "pallas_fused"
    with pytest.raises(ConfigError, match="power-of-two ingress"):
        load_config_str(
            BASIC.replace("general:",
                          "experimental:\n  plane_kernel: pallas_fused\n"
                          "  tpu_ingress_cap: 6\ngeneral:"))


def test_workload_block_yaml11_spellings():
    """The `workload:` block survives YAML 1.1's bare off/on-as-bool at
    BOTH levels — the whole block and the scenario field — like
    telemetry.sink and strace_logging_mode (docs/workloads.md)."""
    # block level: `workload: off` parses as boolean False
    cfg = load_config_str(BASIC.replace("general:", "workload: off\ngeneral:"))
    assert cfg.workload.enabled is False
    assert cfg.workload.scenario is None
    cfg = load_config_str(BASIC.replace("general:", "workload: on\ngeneral:"))
    assert cfg.workload.enabled is True
    # field level: `scenario: off` -> the "off" sentinel, `scenario: on`
    # -> None ("enabled at the default path")
    cfg = load_config_str(BASIC.replace(
        "general:", "workload:\n  enabled: true\n  scenario: off\ngeneral:"))
    assert cfg.workload.scenario == "off"
    cfg = load_config_str(BASIC.replace(
        "general:", "workload:\n  enabled: true\n  scenario: on\ngeneral:"))
    assert cfg.workload.scenario is None


def test_workload_block_fields_validate():
    cfg = load_config_str(BASIC.replace(
        "general:",
        "workload:\n  scenario: scenarios/incast.yaml\n  seed: 3\ngeneral:"))
    assert cfg.workload.scenario == "scenarios/incast.yaml"
    assert cfg.workload.seed == 3
    assert cfg.workload.enabled is False
    with pytest.raises(ConfigError, match="workload.seed"):
        load_config_str(BASIC.replace(
            "general:", "workload:\n  seed: -1\ngeneral:"))
    with pytest.raises(ConfigError, match="unknown option"):
        load_config_str(BASIC.replace(
            "general:", "workload:\n  bogus: 1\ngeneral:"))
    with pytest.raises(ConfigError, match="scenario"):
        load_config_str(BASIC.replace(
            "general:", "workload:\n  scenario: 7\ngeneral:"))
