"""shadowcost (SL601/SL602/SL603) coverage: the HLO boundary census on
synthetic kernels, the seeded fixtures firing each rule with the
entry + op pair + delta named, watermark extrapolation catching a
super-linear temp, the host-sync fence semantics (loops vs teardown,
device_get-derived host values, suppressions, the allow registry), the
canonical double-regen byte-identity of BOTH ledgers, and the
checked-in cost ledger's consistency with the registry. The full
compiled-surface acceptance sweep is @slow (the CI proof gate runs it
unfiltered on every build via `shadowlint --only ...,SL601,SL602`)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.analysis import costmodel  # noqa: E402
from shadow_tpu.analysis.costmodel import (  # noqa: E402
    CostEntry, check_cost_budgets, check_host_sync,
    check_host_sync_source, check_watermarks, cost_budget_path,
    default_cost_entries, fusion_boundaries, write_cost_budgets,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def _load_fixture(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name.removesuffix(".py"), os.path.join(FIXTURES, name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(tmp_path, doc):
    path = tmp_path / "cost_budgets.json"
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return str(path)


# -- the HLO census substrate ---------------------------------------------


def test_fusion_boundaries_on_synthetic_kernel():
    """A sort between two fusions materializes its operand and its
    output; both show up with correct shapes/bytes, ranked
    largest-first, and tiny values stay below the threshold."""
    def f(x):
        y = jnp.exp(x) + 1.0          # fusion 1
        s = jax.lax.sort(y, dimension=1)
        return (s * 2.0).sum()        # fusion 2

    comp = jax.jit(f).lower(jnp.ones((16, 32), jnp.float32)).compile()
    bounds = fusion_boundaries(comp.as_text(), 16 * 32)
    assert bounds, "no boundaries found around an unfusable sort"
    assert all(b["bytes"] >= 16 * 32 * 4 for b in bounds)
    assert any("f32[16,32]" in s for b in bounds for s in b["shapes"])
    assert [b["bytes"] for b in bounds] == sorted(
        (b["bytes"] for b in bounds), reverse=True)
    # a sky-high threshold filters everything
    assert fusion_boundaries(comp.as_text(), 10**9) == []


def test_output_only_values_are_not_boundaries():
    """A value that only reaches the root tuple is an OUTPUT — no
    fusion can elide it, so it must not appear in the worklist."""
    def f(x):
        return jnp.exp(x), jnp.tanh(x)  # both results are outputs

    comp = jax.jit(f).lower(jnp.ones((16, 32), jnp.float32)).compile()
    assert fusion_boundaries(comp.as_text(), 16 * 32) == []


# -- the seeded fixtures --------------------------------------------------


def test_fixture_fires_sl602_naming_pair_and_delta(tmp_path):
    mod = _load_fixture("fixture_fusion_break.py")
    path = _write(tmp_path, mod.budget(big_boundaries=0))
    findings, deltas = check_cost_budgets(path, entries=[mod.entry()])
    f602 = [f for f in findings if f.rule == "SL602"]
    assert f602, [str(f) for f in findings]
    msg = str(f602[0])
    assert "tests.lint_fixtures:fusion_break" in msg  # the entry
    assert "->" in msg and ("sort" in msg or "fusion" in msg)  # op pair
    assert "0 budgeted" in msg  # budget-vs-actual
    assert deltas and "big_boundaries" in deltas[0]["delta"]
    table = costmodel.format_cost_delta(deltas)
    assert "big_boundaries" in table and "fusion_break" in table


def test_fixture_fires_sl601_on_cost_drift(tmp_path):
    mod = _load_fixture("fixture_fusion_break.py")
    live = mod.budget()["platforms"][costmodel._platform()][
        "tests.lint_fixtures:fusion_break"]
    path = _write(tmp_path, mod.budget(flops=live["flops"] * 10 + 999))
    findings, deltas = check_cost_budgets(path, entries=[mod.entry()])
    f601 = [f for f in findings if f.rule == "SL601"]
    assert f601 and "flops" in str(f601[0])
    assert "fusion_break" in str(f601[0])
    assert deltas[0]["delta"]["flops"]["actual"] == live["flops"]


def test_fixture_passes_against_its_own_live_budget(tmp_path):
    mod = _load_fixture("fixture_fusion_break.py")
    path = _write(tmp_path, mod.budget())
    findings, deltas = check_cost_budgets(path, entries=[mod.entry()])
    assert findings == [] and deltas == []


def test_missing_platform_and_missing_entry_fail(tmp_path):
    mod = _load_fixture("fixture_fusion_break.py")
    doc = mod.budget()
    doc["platforms"] = {"nonexistent-platform": {}}
    findings, _ = check_cost_budgets(_write(tmp_path, doc),
                                     entries=[mod.entry()])
    assert any("no cost budgets for platform" in f.message
               for f in findings)
    doc2 = mod.budget()
    doc2["platforms"][costmodel._platform()] = {}
    findings, _ = check_cost_budgets(_write(tmp_path, doc2),
                                     entries=[mod.entry()])
    assert any("has no budget" in f.message for f in findings)


def test_infra_failures_tag_both_budget_rules(tmp_path):
    """A ledger the fence could not check must fail a `--only SL602`
    run too: missing file / platform / entry findings carry BOTH
    rules, so rule filtering can never turn a dead gate green."""
    mod = _load_fixture("fixture_fusion_break.py")
    findings, _ = check_cost_budgets(str(tmp_path / "nope.json"),
                                     entries=[mod.entry()])
    assert {f.rule for f in findings} == {"SL601", "SL602"}
    doc = mod.budget()
    doc["platforms"] = {"nonexistent-platform": {}}
    findings, _ = check_cost_budgets(_write(tmp_path, doc),
                                     entries=[mod.entry()])
    assert {f.rule for f in findings} == {"SL601", "SL602"}


def test_within_zero_budget_zero_actual_passes():
    """An exact match passes under ANY band shape — a rel-only band
    on a zero budget (transcendentals on cpu) must not fail 0 vs 0."""
    assert costmodel._within(0, 0, {"rel": 0.25})
    assert costmodel._within(0, 0, {})
    assert not costmodel._within(0, 5, {"rel": 0.25})


def test_report_worklist_is_complete():
    """The artifact's cross-entry worklist carries EVERY boundary
    (the no-silent-caps rule); only the per-entry head is bounded."""
    mod = _load_fixture("fixture_fusion_break.py")
    report = costmodel.build_cost_report(entries=[mod.entry()])
    section = report["entries"][0]
    assert len(report["fusion_worklist"]) == section["boundaries_total"]
    assert len(section["boundaries"]) <= costmodel._WORKLIST_PER_ENTRY


def test_tolerance_bands_absorb_small_drift(tmp_path):
    """A metric within the rel OR abs band passes; the band is read
    from the ledger document, not hardcoded."""
    mod = _load_fixture("fixture_fusion_break.py")
    live = mod.budget()["platforms"][costmodel._platform()][
        "tests.lint_fixtures:fusion_break"]
    doc = mod.budget(flops=int(live["flops"] * 1.1))  # within 25% rel
    findings, _ = check_cost_budgets(_write(tmp_path, doc),
                                     entries=[mod.entry()])
    assert findings == []
    doc = mod.budget(fusions=live["fusions"] + 2)  # at the abs band
    findings, _ = check_cost_budgets(_write(tmp_path, doc),
                                     entries=[mod.entry()])
    assert findings == []


# -- watermark extrapolation ----------------------------------------------


def _quad_entry(n):
    def build():
        def kernel(x):
            m = x[:, None] * x[None, :]          # [n, n]: quadratic
            return jax.lax.sort(m, dimension=1).sum()

        return kernel, (jnp.ones((n,), jnp.float32),)

    return build


def test_watermark_catches_superlinear_temp():
    entry = CostEntry("tests.lint_fixtures:quad_temp", 128, 1,
                      _quad_entry(128),
                      scale_n=256, scale_build=_quad_entry(256))
    findings, rows = check_watermarks([entry])
    assert findings and findings[0].rule == "SL601"
    assert "super-linearly" in findings[0].message
    assert rows[0]["temp2_bytes"] > rows[0]["linear_bound_bytes"]


def test_watermark_passes_linear_temp():
    def lin(n):
        def build():
            def kernel(x):
                return jax.lax.sort(jnp.exp(x), dimension=0).sum()

            return kernel, (jnp.ones((n * 64,), jnp.float32),)

        return build

    entry = CostEntry("tests.lint_fixtures:lin_temp", 4, 1, lin(4),
                      scale_n=8, scale_build=lin(8))
    findings, rows = check_watermarks([entry])
    assert findings == [] and rows[0]["ok"]


# -- SL603: the host-sync fence -------------------------------------------


def _line_of(source, needle):
    for i, text in enumerate(source.splitlines(), start=1):
        if needle in text:
            return i
    raise AssertionError(f"{needle!r} not in fixture")


def test_sl603_fixture_fires_and_clean_shapes_pass():
    with open(os.path.join(FIXTURES, "fixture_host_sync.py"),
              encoding="utf-8") as fh:
        src = fh.read()
    findings = check_host_sync_source(src, "bench.py")
    active = {f.line for f in findings if not f.suppressed}
    assert active == {
        _line_of(src, "float(delivered.sum())"),
        _line_of(src, "metrics.events.item()"),
        _line_of(src, "jax.device_get(state.n_sent)"),
        _line_of(src, "jax.block_until_ready(state)"),
    }
    # the comment-suppressed np.asarray carries its justification
    sup = [f for f in findings if f.suppressed]
    assert len(sup) == 1
    assert sup[0].justification == "fixture: sanctioned debug read"
    # drain_after/digest (teardown + device_get-derived) stay clean
    for needle in ("jax.block_until_ready(state)  # teardown",
                   "float(jax.device_get(state.n_sent).sum())",
                   "arr = np.asarray(leaf)"):
        assert _line_of(src, needle) not in active, needle


def test_sl603_registry_allow_suppresses_with_justification():
    src = ("import numpy as np\n"
           "def run_elastic_window(state, attempt):\n"
           "    while True:\n"
           "        out, ovf = attempt(state)\n"
           "        if int(np.asarray(ovf).sum()) == 0:\n"
           "            return out\n")
    findings = check_host_sync_source(src, "shadow_tpu/tpu/elastic.py")
    assert findings and all(f.suppressed for f in findings)
    assert all("elastic capacity policy" in f.justification
               for f in findings)
    # the same code under a DIFFERENT function name is NOT sanctioned
    findings = check_host_sync_source(
        src.replace("run_elastic_window", "sneaky_loop"),
        "shadow_tpu/tpu/elastic.py")
    assert findings and not any(f.suppressed for f in findings)


def test_sl603_while_test_counts_as_loop():
    src = ("import jax\n"
           "def spin(x):\n"
           "    while jax.device_get(x) > 0:\n"
           "        x = x - 1\n")
    findings = check_host_sync_source(src, "bench.py")
    assert [f.rule for f in findings] == ["SL603"]


def test_sl603_def_inside_loop_is_not_per_iteration():
    """A function DEFINED in a loop runs later — its body is a fresh
    sync context (the chain_fn/on_chain closure pattern)."""
    src = ("import jax\n"
           "def drive(chunks):\n"
           "    fns = []\n"
           "    for c in chunks:\n"
           "        def on_chain(r1, state, extras):\n"
           "            return jax.device_get(state)\n"
           "        fns.append(on_chain)\n"
           "    return fns\n")
    assert check_host_sync_source(src, "bench.py") == []


def test_sl603_comprehension_is_a_loop():
    """A flagged `for` rewritten as a comprehension must not dodge the
    fence; host-derived comp targets stay exempt like For targets."""
    src = ("import jax\n"
           "def drive(deliveries):\n"
           "    return [float(d.sum()) for d in deliveries]\n")
    findings = check_host_sync_source(src, "bench.py")
    assert [f.rule for f in findings] == ["SL603"]
    src_host = ("import jax\n"
                "import numpy as np\n"
                "def digest(trees):\n"
                "    return [np.asarray(leaf)\n"
                "            for leaf in jax.device_get(trees)]\n")
    assert check_host_sync_source(src_host, "bench.py") == []


def test_sl603_block_until_ready_result_is_still_device():
    """block_until_ready returns the DEVICE array (only flushed): a
    later per-iteration read of it must still fire."""
    src = ("import jax\n"
           "def drive(arr, windows):\n"
           "    arr = jax.block_until_ready(arr)\n"
           "    total = 0.0\n"
           "    for w in range(windows):\n"
           "        total += float(arr.sum())\n"
           "    return total\n")
    findings = check_host_sync_source(src, "bench.py")
    assert [f.line for f in findings] == [_line_of(src.rstrip("\n"),
                                                   "float(arr.sum())")]


def test_sl603_int_is_deliberately_not_netted():
    """The documented hole: bare int() on a device scalar slips the
    lexical net (in-tree device reads all spell the pull as
    device_get/np.asarray/.item()/float(), which are caught; netting
    int() costs ~6 false positives on host coercions per sweep). This
    test pins the DECISION — if the tree ever grows an int()-on-device
    idiom, revisit costmodel._MATERIALIZERS."""
    src = ("def drive(delivered, windows):\n"
           "    total = 0\n"
           "    for w in range(windows):\n"
           "        total += int(delivered.sum())\n"
           "    return total\n")
    assert check_host_sync_source(src, "bench.py") == []


def test_sl603_tree_clean_or_justified():
    """The four driver-loop modules report zero active findings; every
    allow carries a written rationale (the fix-or-allow contract)."""
    findings = check_host_sync()
    active = [str(f) for f in findings if not f.suppressed]
    assert active == [], "\n".join(active)
    assert all(f.justification for f in findings if f.suppressed)
    # the elastic overflow readback IS allowed (not silently absent):
    # the registry entry is load-bearing, not decorative
    assert any("elastic.py" in f.path for f in findings if f.suppressed)


def test_sl603_driver_module_list_matches_tree():
    """Every fenced module exists; a rename breaks the fence loudly
    (check_host_sync reports the missing file as a finding)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in costmodel.DRIVER_MODULES:
        assert os.path.exists(os.path.join(repo, rel)), rel
    missing = costmodel.check_host_sync(repo_root="/nonexistent")
    assert len(missing) == len(costmodel.DRIVER_MODULES)
    assert all("cannot check" in f.message for f in missing)


# -- canonical ledgers (satellite: byte-stable regen) ---------------------


def test_cost_budgets_double_regen_byte_identical(tmp_path):
    mod = _load_fixture("fixture_fusion_break.py")
    p1, p2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    write_cost_budgets(p1, entries=[mod.entry()])
    write_cost_budgets(p2, entries=[mod.entry()])
    b1, b2 = open(p1, "rb").read(), open(p2, "rb").read()
    assert b1 == b2
    assert b1.endswith(b"\n") and not b1.endswith(b"\n\n")
    # regen ON TOP of an existing file is also byte-stable
    write_cost_budgets(p1, entries=[mod.entry()])
    assert open(p1, "rb").read() == b1
    # keys are canonically sorted at every level
    doc = json.loads(b1)
    for section in doc["platforms"].values():
        assert list(section) == sorted(section)
        for metrics in section.values():
            assert list(metrics) == sorted(metrics)


def test_cost_budgets_regen_preserves_other_platforms(tmp_path):
    mod = _load_fixture("fixture_fusion_break.py")
    path = str(tmp_path / "c.json")
    write_cost_budgets(path, entries=[mod.entry()])
    doc = json.load(open(path))
    doc["platforms"]["tpu-imaginary"] = {"some:entry": {"flops": 1}}
    with open(path, "w") as fh:
        json.dump(doc, fh)
    write_cost_budgets(path, entries=[mod.entry()])
    doc2 = json.load(open(path))
    assert doc2["platforms"]["tpu-imaginary"] == {
        "some:entry": {"flops": 1}}


def test_op_budgets_double_regen_byte_identical(tmp_path):
    from shadow_tpu.analysis import proofs

    mod = _load_fixture("fixture_op_budget.py")
    entry = mod.entry()
    p1, p2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    proofs.write_op_budgets(p1, entries=[entry])
    proofs.write_op_budgets(p2, entries=[entry])
    b1, b2 = open(p1, "rb").read(), open(p2, "rb").read()
    assert b1 == b2
    assert b1.endswith(b"\n") and not b1.endswith(b"\n\n")
    doc = json.loads(b1)
    assert list(doc["budgets"]) == sorted(doc["budgets"])


# -- the checked-in ledger ------------------------------------------------


def test_checked_in_cost_ledger_is_consistent():
    """Registry keys == ledger keys for this platform (no compile:
    pure file/registry consistency), tolerance bands present, the
    file byte-matches a canonical re-dump of itself."""
    path = cost_budget_path()
    assert os.path.exists(path), "cost_budgets.json not checked in"
    raw = open(path, "rb").read()
    doc = json.loads(raw)
    assert set(doc["platforms"]["cpu"]) == {
        e.key for e in default_cost_entries()}
    for metrics in doc["platforms"]["cpu"].values():
        assert set(metrics) == {"flops", "bytes_accessed",
                                "transcendentals", "fusions",
                                "big_boundaries"}
    assert set(doc["tolerance"]) >= {"flops", "bytes_accessed",
                                     "fusions", "big_boundaries"}
    redump = json.dumps(doc, indent=2, sort_keys=True) + "\n"
    assert raw.decode() == redump, \
        "ledger not canonical: regen with --write-cost-budgets"


def test_watermark_pairs_cover_window_step_and_chain():
    keyed = {e.key: e for e in default_cost_entries()}
    assert keyed["shadow_tpu.tpu.plane:window_step[lean]"].scale_build
    assert keyed["shadow_tpu.tpu.plane:chain_windows"].scale_build


def test_real_entry_passes_checked_in_budget():
    """Fast canary against the REAL ledger: the cheapest registered
    entry compiles and lands inside its checked-in band (the full
    surface runs in the CI proof gate and the @slow sweep)."""
    entry = [e for e in default_cost_entries()
             if e.key.endswith("ingest_rows[planes]")][0]
    findings, deltas = check_cost_budgets(entries=[entry])
    findings = [f for f in findings
                if f.path == entry.key or entry.key in f.message]
    assert findings == [], [str(f) for f in findings]


def test_worklist_names_the_rank_place_materialization():
    """The acceptance handoff: window_step's ranked worklist leads
    with the routing-stage materializations ROADMAP-4 targets — the
    stacked [6, N, CE] place-payload gather and the routing flat
    sort."""
    entry = [e for e in default_cost_entries()
             if e.key.endswith("window_step[lean]")][0]
    bounds = costmodel.entry_costs(entry)["boundaries"]
    head = bounds[:3]
    assert any("s32[6,4,8]" in s for b in head for s in b["shapes"]), \
        [b["shapes"] for b in head]
    assert any("sort" in b["producer"] for b in head), \
        [b["producer"] for b in head]


@pytest.mark.slow
def test_full_surface_clean_and_watermarks_linear():
    """The acceptance sweep: every registered entry within its
    checked-in band on this platform, both watermark pairs linear.
    @slow (compiles the full surface); the CI proof gate runs the
    same check unfiltered on every build."""
    findings, _ = check_cost_budgets()
    assert [str(f) for f in findings] == []
    wm_findings, rows = check_watermarks()
    assert wm_findings == [] and all(r["ok"] for r in rows)
    assert len(rows) == 2


# -- report + compare_runs ------------------------------------------------


def test_cost_report_shape(tmp_path):
    mod = _load_fixture("fixture_fusion_break.py")
    report = costmodel.build_cost_report(entries=[mod.entry()])
    assert report["platform"] == costmodel._platform()
    assert report["entries"][0]["entry"] == \
        "tests.lint_fixtures:fusion_break"
    assert report["fusion_worklist"], "fixture cube not in worklist"
    assert report["fusion_worklist"][0]["bytes"] >= 8 * 8 * 8 * 4
    assert {"modules", "active", "allowed"} <= set(report["host_sync"])
    assert report["summary"]["host_sync_active"] == 0


def test_compare_runs_cost_delta(tmp_path, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import compare_runs

    def rec(platform, flops):
        return {
            "platform": platform,
            "entries": [{"entry": "plane:window_step[lean]",
                         "metrics": {"flops": flops,
                                     "bytes_accessed": 1000,
                                     "fusions": 10,
                                     "big_boundaries": 4}}],
        }

    a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    json.dump(rec("cpu", 100), open(a, "w"))
    json.dump(rec("cpu", 80), open(b, "w"))
    assert compare_runs.main(["--cost", a, b]) == 0
    out = capsys.readouterr().out
    assert "flops" in out and "window_step[lean]" in out
    assert "MEANINGLESS" not in out and "WARNING" not in out
    # mismatched platform keys: the loud banner (the bench lesson)
    json.dump(rec("tpu", 80), open(b, "w"))
    assert compare_runs.main(["--cost", a, b]) == 0
    out = capsys.readouterr().out
    assert "WARNING" in out and "platform" in out
