"""The simulated-CPU oversubscription model.

Parity: reference `src/main/host/cpu.rs:8-95` (frequency scaling, precision
rounding nearest-ties-up, threshold gating) wired through `Host::execute`'s
event-deferral path (`host.rs:821-849`) and constructed per host by the
Manager with the machine's raw frequency (`manager.rs:565,826-830`).
"""

from shadow_tpu.core import simtime
from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.event import TaskRef
from shadow_tpu.core.manager import Manager, _raw_cpu_frequency_khz
from shadow_tpu.host.cpu import Cpu

MS = simtime.MILLISECOND
US = simtime.MICROSECOND

CONFIG = """
general:
  stop_time: 1s
  seed: 7
network:
  graph:
    type: 1_gbit_switch
hosts:
  alpha:
    network_node_id: 0
"""


def test_disabled_model_reports_zero_delay():
    cpu = Cpu(1_000_000, 1_000_000, None, 200)
    cpu.update_time(0)
    cpu.add_delay(50 * MS)
    assert cpu.delay() == 0  # threshold None = model off (`cpu.rs:83`)


def test_threshold_gates_delay():
    cpu = Cpu(1_000_000, 1_000_000, 10 * US, None)
    cpu.update_time(0)
    cpu.add_delay(9 * US)
    assert cpu.delay() == 0  # below threshold
    cpu.add_delay(2 * US)
    assert cpu.delay() == 11 * US  # raw backlog once over threshold
    # time advancing consumes the backlog
    cpu.update_time(11 * US)
    assert cpu.delay() == 0


def test_frequency_ratio_scales_charges():
    # native CPU twice as fast as the simulated one: native time doubles
    cpu = Cpu(1_000_000, 2_000_000, 0, None)
    cpu.update_time(0)
    cpu.add_delay(5 * US)
    assert cpu.delay() == 10 * US


def test_precision_rounds_nearest_ties_up():
    cpu = Cpu(1_000_000, 1_000_000, 0, 200)
    cpu.update_time(0)
    cpu.add_delay(299)  # 299 % 200 = 99 < 100 -> down to 200
    assert cpu.delay() == 200
    cpu.add_delay(100)  # 100 * 2 == 200 -> ties round up to 200
    assert cpu.delay() == 400


def test_manager_wires_cpu_into_hosts():
    mgr = Manager(load_config_str(CONFIG))
    host = mgr.hosts[0]
    assert host.cpu is not None
    assert host.cpu.threshold is None  # default: model off, deterministic


def test_config_knobs_reach_the_host():
    cfg = load_config_str(CONFIG + """
experimental:
  cpu_threshold: 10000
  cpu_precision: 500
""")
    host = Manager(cfg).hosts[0]
    assert host.cpu.threshold == 10000
    assert host.cpu._precision == 500


def test_oversubscribed_cpu_defers_events():
    """`host.rs:821-849`: with unapplied delay over the threshold, a due
    event is pushed into the future instead of executing now."""
    mgr = Manager(load_config_str(CONFIG + """
experimental:
  cpu_threshold: 1000000
"""))
    host = mgr.hosts[0]
    fired = []
    host.schedule_task_at(TaskRef(lambda h: fired.append(h.now()), "probe"),
                          1 * MS)
    host.cpu.update_time(0)
    host.cpu.add_delay(5 * MS)  # way over the 1ms threshold
    host.execute(2 * MS)
    assert fired == []  # deferred past the window
    host.execute(10 * MS)
    assert len(fired) == 1
    assert fired[0] >= 5 * MS  # ran only after the backlog drained


def test_raw_frequency_detection_positive():
    assert _raw_cpu_frequency_khz() > 0


def test_managed_binary_charges_cpu_time(tmp_path):
    """End-to-end: native execution time of a managed binary lands on the
    simulated CPU when the model is enabled (`process.rs:465-482`)."""
    import shutil
    import subprocess

    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        import pytest
        pytest.skip("no C compiler")
    c = tmp_path / "burn.c"
    c.write_text(
        "volatile long x; int main(void){"
        "for (long i = 0; i < 20000000; i++) x += i; return 0; }")
    binary = tmp_path / "burn"
    subprocess.run([cc, "-O0", "-o", str(binary), str(c)], check=True)
    cfg = load_config_str(f"""
general: {{stop_time: 5s, seed: 3}}
experimental:
  cpu_threshold: 1000000
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: [], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    mgr = Manager(cfg)
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    host = mgr.hosts[0]
    # the busy loop's native wall time was charged to the simulated CPU
    assert host.cpu._time_cursor > 0
