"""The SL5xx dataflow-proof machinery (analysis/dataflow.py + proofs.py):

- engine unit semantics: straight-line propagation, cond/while implicit
  flows WITH the branch-invariant passthrough refinement, scan carry
  fixpoints, pjit descent, conservative unknown-primitive handling;
- SL501: every invisibility theorem over the REAL kernels holds (the
  acceptance gate), the taint is not vacuous (plane outputs ARE
  tainted), and the deliberately-broken fixture kernel fails naming
  both ends of the illegal flow;
- SL502: the checked-in op-budget ledger matches the live tree, and a
  fixture kernel with one extra scatter fails with a per-primitive
  delta;
- SL504: the shardability report is non-empty for the routing exchange
  and EMPTY for row-local stages, and the mixed fixture kernel
  classifies each op correctly (including the replicated-table
  exemption).
"""

import importlib.util
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from shadow_tpu.analysis import proofs  # noqa: E402
from shadow_tpu.analysis.dataflow import (  # noqa: E402
    leaf_paths, op_census, propagate_taint, shard_census,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def _load_fixture(name: str):
    spec = importlib.util.spec_from_file_location(
        name.removesuffix(".py"), os.path.join(FIXTURES, name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _labels(fn, args, tainted: dict[int, str]):
    closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
    in_labels = []
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_flatten(a)[0])
        pre = tainted.get(i)
        in_labels.extend(leaf_paths(a, prefix=pre) if pre else [None] * n)
    return (propagate_taint(closed, in_labels), leaf_paths(out_shape))


# -- engine semantics ------------------------------------------------------

def test_straight_line_taint_and_clean():
    def fn(a, b):
        return a + 1, b * 2, a + b

    out, _ = _labels(fn, (jnp.int32(1), jnp.int32(2)), {0: "t"})
    assert out[0] == "t" and out[1] is None and out[2] == "t"


def test_cond_implicit_flow_taints_all_outputs():
    def fn(p, x):
        return jax.lax.cond(p > 0, lambda v: v + 1, lambda v: v - 1, x)

    out, _ = _labels(fn, (jnp.int32(1), jnp.int32(2)), {0: "t"})
    assert out[0] == "t"  # tainted predicate, clean operand


def test_cond_passthrough_is_branch_invariant():
    """An operand returned verbatim by BOTH branches stays clean even
    under a tainted predicate — the ingest_rows gate_idle shape."""
    def fn(p, x, y):
        def yes(ops):
            return ops[0] + 1, ops[1]

        def no(ops):
            return ops[0] - 1, ops[1]

        return jax.lax.cond(p > 0, yes, no, (x, y))

    out, _ = _labels(fn, (jnp.int32(1), jnp.int32(2), jnp.int32(3)),
                     {0: "t"})
    assert out[0] == "t"  # computed differently per branch
    assert out[1] is None  # verbatim passthrough in both branches


def test_while_fixpoint_carries_taint_across_slots():
    """Taint flowing between carry slots needs the fixpoint: slot 1
    reads slot 0 only on later iterations."""
    def fn(a, b):
        def body(c):
            x, y, n = c
            return x, y + x, n + 1

        def cond(c):
            return c[2] < 3

        return jax.lax.while_loop(cond, body, (a, b, jnp.int32(0)))

    out, _ = _labels(fn, (jnp.int32(1), jnp.int32(2)), {0: "t"})
    assert out[0] == "t" and out[1] == "t"  # b absorbed a's taint
    assert out[2] is None  # the counter never sees it


def test_while_tainted_predicate_spares_passthrough_carry():
    def fn(t, x, y):
        def body(c):
            n, keep, acc = c
            return n + 1, keep, acc + 1

        def cond(c):
            return c[0] < t  # TAINTED trip count

        return jax.lax.while_loop(cond, body, (jnp.int32(0), x, y))

    out, _ = _labels(fn, (jnp.int32(3), jnp.int32(1), jnp.int32(2)),
                     {0: "t"})
    assert out[0] == "t" and out[2] == "t"  # iteration-count dependent
    assert out[1] is None  # verbatim carry: 0 or N iterations, same value


def test_scan_carry_and_ys():
    def fn(a, xs):
        def body(c, x):
            return c + x, c

        return jax.lax.scan(body, a, xs)

    out, _ = _labels(fn, (jnp.int32(0), jnp.zeros(3, jnp.int32)),
                     {1: "xs"})
    assert out[0] == "xs" and out[1] == "xs"


def test_vmap_broadcast_carries_taint():
    """Batched jaxprs (the SL701 ensemble surface) route shared
    operands through vmap-introduced `broadcast_in_dim`s: taint on the
    unbatched arg must survive the broadcast into every world's lane,
    and a clean batched arg must stay clean beside it."""
    def per_world(x, shared):
        return x * 2.0, x + shared

    fn = jax.vmap(per_world, in_axes=(0, None))
    out, _ = _labels(fn, (jnp.ones((2, 3)), jnp.ones(3)), {1: "t"})
    assert out[0] is None  # world-local product never touches `shared`
    assert out[1] == "t"   # broadcast_in_dim propagated the taint


def test_vmap_batched_scan_carry_taint():
    """vmap over a scanned body batches the carry: the per-world seed's
    taint must flow through the batched carry into both the final
    carry and the stacked ys, while the clean per-world xs stay
    clean in the untouched output slot."""
    def per_world(seed, xs):
        def body(c, x):
            return c + x, c

        return jax.lax.scan(body, seed, xs)

    fn = jax.vmap(per_world)
    out, _ = _labels(
        fn, (jnp.zeros(2, jnp.int32), jnp.zeros((2, 3), jnp.int32)),
        {0: "seed"})
    assert out[0] == "seed" and out[1] == "seed"

    # and the converse: clean seed, tainted xs — the batched carry
    # absorbs xs-taint across iterations exactly as in the solo scan
    out, _ = _labels(
        fn, (jnp.zeros(2, jnp.int32), jnp.zeros((2, 3), jnp.int32)),
        {1: "xs"})
    assert out[0] == "xs" and out[1] == "xs"


def test_pjit_descent_keeps_precision():
    inner = jax.jit(lambda x, y: (x + 1, y))

    def fn(a, b):
        return inner(a, b)

    out, _ = _labels(fn, (jnp.int32(1), jnp.int32(2)), {0: "t"})
    assert out[0] == "t" and out[1] is None


def test_custom_jvp_descent_keeps_precision():
    """`custom_jvp_call` carries its primal body as `call_jaxpr` and
    inlines 1:1 — the analysis must descend (a clean operand stays
    clean) instead of falling back to all-outputs-tainted."""
    @jax.custom_jvp
    def f(x, y):
        return x * 2.0, y

    @f.defjvp
    def f_jvp(primals, tangents):
        return f(*primals), (tangents[0] * 2.0, tangents[1])

    def fn(a, b):
        return f(a, b)

    out, _ = _labels(fn, (jnp.float32(1), jnp.float32(2)), {0: "t"})
    assert out[0] == "t"
    assert out[1] is None  # descent, not the conservative fallback


def test_custom_vjp_descent_keeps_precision():
    """`custom_vjp_call_jaxpr` spells its body `fun_jaxpr`; same
    descent contract. The second output passes the clean operand
    through a genuinely-mixing first output."""
    @jax.custom_vjp
    def g(x, y):
        return x + 0.0 * x, y

    def g_fwd(x, y):
        return g(x, y), None

    def g_bwd(res, ct):
        return ct

    g.defvjp(g_fwd, g_bwd)

    def fn(a, b):
        return g(a, b)

    out, _ = _labels(fn, (jnp.float32(1), jnp.float32(2)), {0: "t"})
    assert out[0] == "t" and out[1] is None


def test_scan_closed_over_const_feeds_carry():
    """A traced value closed over by the scan body enters as a
    num_consts operand, NOT a carry: its taint must still reach the
    carry through the body (and an untouched ys stays clean)."""
    def fn(k, a, xs):
        scale = k * 2  # closed over by the body -> scan const

        def body(c, x):
            return c + scale, x

        return jax.lax.scan(body, a, xs)

    out, _ = _labels(
        fn, (jnp.int32(3), jnp.int32(0), jnp.zeros(3, jnp.int32)),
        {0: "k"})
    assert out[0] == "k"  # carry absorbed the closed-over const
    assert out[1] is None  # ys = xs passthrough, untouched


def test_scan_const_taint_needs_no_carry_seed():
    """Fixpoint sanity for the const-into-carry flow: the carry starts
    CLEAN and only the closed-over const is tainted — one body pass
    must already propagate it (the carry fixpoint may not converge to
    the clean initial value)."""
    def fn(k, a, xs):
        def body(c, x):
            return jnp.where(x > 0, c + k, c), c

        return jax.lax.scan(body, a, xs)

    out, _ = _labels(
        fn, (jnp.int32(3), jnp.int32(0), jnp.zeros(3, jnp.int32)),
        {0: "k"})
    assert out[0] == "k" and out[1] == "k"


def test_leaf_paths_namedtuples_and_dicts():
    from shadow_tpu.tpu import plane

    state = plane.make_state(2, egress_cap=4, ingress_cap=4)
    paths = leaf_paths(state, prefix="state")
    assert "state.eg_dst" in paths and "state.rng_counter" in paths
    flat = len(jax.tree_util.tree_flatten(state)[0])
    assert len(paths) == flat
    d = {"mask": jnp.zeros(2), "src": jnp.zeros(2)}
    assert leaf_paths((d, jnp.int32(0)))[:2] == ["[0]['mask']",
                                                "[0]['src']"]


# -- SL501: the invisibility theorems --------------------------------------

def test_spec_surface_covers_the_three_kernels_and_planes():
    names = {s.name for s in proofs.invisibility_specs()}
    for required in ("window_step[metrics]", "window_step[guards]",
                     "window_step[hist]", "window_step[flightrec]",
                     "window_step[metrics+guards+hist+flightrec]",
                     "chain_windows[metrics]", "chain_windows[guards]",
                     "chain_windows[workload+metrics+guards]",
                     "ingest_rows[metrics+guards+hist+flightrec]",
                     "workload_step[append-only]",
                     "window_step[flows]", "flow_step[append-only]"):
        assert required in names, required


@pytest.mark.parametrize(
    "spec", proofs.invisibility_specs(), ids=lambda s: s.name)
def test_invisibility_theorem_holds(spec):
    findings = proofs.check_invisibility(spec)
    assert findings == [], "\n".join(f.message for f in findings)


def test_taint_is_not_vacuous():
    """The plane OUTPUTS must be tainted — a propagation bug that loses
    all taint would make every theorem pass vacuously."""
    spec = next(s for s in proofs.invisibility_specs()
                if s.name == "window_step[metrics]")
    fn, args = spec.build()
    out_labels, out_paths = _labels(fn, args, spec.tainted_args)
    tainted = [p for p, l in zip(out_paths, out_labels) if l is not None]
    assert tainted, "no output leaf tainted: the engine lost the taint"
    # ...and ONLY the metrics output (index 3) is
    assert all(p.startswith("[3]") for p in tainted), tainted


def test_broken_fixture_kernel_fails_named():
    """The deliberately-broken kernel (plane counter wired back into
    sim state) is reported with the offending output leaf AND the
    sourcing plane leaf named."""
    fixture = _load_fixture("fixture_taint_leak.py")
    findings = proofs.check_invisibility(fixture.spec())
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "SL501"
    assert "metrics.pkts" in f.message  # the source
    assert "[0].counter" in f.message  # the offending output leaf
    assert "[0].clock" not in f.message  # the untouched leaf stays clean


def test_workload_append_only_rejects_an_ingress_write():
    """The relaxed workload theorem still has teeth: a generator that
    writes the ingress ring fails."""
    from shadow_tpu.tpu import plane

    state = plane.make_state(4, egress_cap=8, ingress_cap=8)
    ws = jnp.zeros((4,), jnp.int32)

    def bad_generator(ws, state):
        return state._replace(
            in_seq=state.in_seq + ws[:, None]), ws + 1

    spec = proofs.InvisibilitySpec(
        "bad_generator", "tests", lambda: (bad_generator, (ws, state)),
        tainted_args={0: "ws"}, protected=proofs._workload_protected)
    findings = proofs.check_invisibility(spec)
    assert len(findings) == 1 and "in_seq" in findings[0].message


# -- SL502: the op-budget ledger -------------------------------------------

@pytest.mark.slow  # re-derives every budget from the tree (~13s);
# CI's prover-suites step runs this file unfiltered
def test_checked_in_budgets_match_the_tree():
    """The acceptance gate: analysis/op_budgets.json is current. On
    drift, regenerate with `python tools/shadowlint.py
    --write-op-budgets` and justify the delta in the PR."""
    findings, deltas = proofs.check_op_budgets()
    assert findings == [], (
        "\n".join(f"{f.path}: {f.message}" for f in findings)
        + "\n" + proofs.format_budget_delta(deltas))


def test_extra_scatter_fails_the_budget(tmp_path):
    fixture = _load_fixture("fixture_op_budget.py")
    entry = fixture.entry()
    ledger = tmp_path / "budgets.json"
    ledger.write_text(json.dumps({
        "version": 1,
        "budgets": {f"{entry.module}:{entry.name}": fixture.BUDGET},
    }))
    findings, deltas = proofs.check_op_budgets(str(ledger), [entry])
    assert len(findings) == 1 and findings[0].rule == "SL502"
    assert "scatter-add" in findings[0].message
    [delta] = deltas
    assert delta["delta"]["scatter-add"] == {"budget": 1, "actual": 2}
    table = proofs.format_budget_delta(deltas)
    assert "scatter-add" in table and "+1" in table


def test_budget_detects_unbudgeted_and_stale_entries(tmp_path):
    fixture = _load_fixture("fixture_op_budget.py")
    entry = fixture.entry()
    ledger = tmp_path / "budgets.json"
    ledger.write_text(json.dumps(
        {"version": 1, "budgets": {"gone:entry": {"sort": 1}}}))
    findings, _ = proofs.check_op_budgets(str(ledger), [entry])
    msgs = sorted(f.message for f in findings)
    assert len(findings) == 2
    assert "no op budget" in msgs[0] and "no longer audited" in msgs[1]


def test_census_counts_nested_bodies_once():
    def fn(x):
        def body(c, _):
            return jnp.sort(c), None

        return jax.lax.scan(body, x, None, length=5)

    census = op_census(jax.make_jaxpr(fn)(jnp.zeros(4, jnp.int32)))
    assert census["sort"] == 1 and census["scan"] == 1


# -- SL504: shardability ----------------------------------------------------

@pytest.fixture(scope="module")
def shard_report():
    """One report for all SL504 tests: building it traces every audit
    entry (~seconds), so share it across the module."""
    return proofs.build_shard_report()


def test_shard_report_routing_vs_rowlocal(shard_report):
    """Acceptance: cross-host primitives non-empty for the routing
    exchange, EMPTY for row-local stages."""
    report = shard_report
    sections = report["sections"]
    for routing in ("shadow_tpu.tpu.plane:routing_rank",
                    "shadow_tpu.tpu.plane:routing_place"):
        assert sections[routing]["cross_host"], routing
    for rowlocal in ("shadow_tpu.tpu.codel:codel_drain",
                     "shadow_tpu.tpu.codel:router_drain",
                     "shadow_tpu.tpu.tcp:tcp_event_step",
                     "shadow_tpu.tpu.tcp:tcp_pull_step"):
        assert sections[rowlocal]["cross_host"] == [], (
            rowlocal, sections[rowlocal]["cross_host"])
    assert report["summary"]["cross_host_ops"] > 0


def test_shard_classifier_on_mixed_fixture():
    fixture = _load_fixture("fixture_shard_classify.py")
    fn, args = fixture.build()
    census = shard_census(jax.make_jaxpr(fn)(*args))
    cross_prims = [oc["primitive"] for oc in census["cross_host"]]
    assert "scatter-add" in cross_prims  # the routing-style exchange
    assert "reduce_sum" in cross_prims  # the host-axis reduction
    # the constant-table gather must NOT be cross-host...
    assert "gather" not in cross_prims
    # ...it lands in host_local along with the row sort + row gather
    assert census["host_local"].get("sort", 0) >= 1
    assert census["host_local"].get("gather", 0) >= 2


def test_pallas_entries_report_opaque_kernels(shard_report):
    pallas = shard_report["sections"][
        "shadow_tpu.tpu.plane:window_step[pallas_fused]"]
    assert len(pallas["opaque"]) == 2  # the two fused kernels
    assert all(o["primitive"] == "pallas_call" for o in pallas["opaque"])
