"""Elastic capacity plane tests (docs/robustness.md "Elastic capacity",
docs/determinism.md "Growth is bitwise-invisible"):

- the parity matrix (rr x aqm x no_loss): a run that starts with tiny
  rings and grows on demand ends canonically bitwise-identical to a run
  pre-provisioned at the final capacity — same delivered stream, same
  counters, clean guards on both;
- grow_state migrates columns/sentinels bitwise and refuses to shrink;
- strict mode raises CapacityError with per-host blame; fixed mode
  records a structured once-per-run drop event; an exhausted growth
  budget commits the overflowing attempt loudly;
- plane checkpoints store ring dims and restore across a resize
  (CE=32 -> CE=64) with digest-verified state equivalence;
- the device transport grows its in-flight rings without perturbing
  the packet-status trace (sync + mirrored), and promotes drops to
  CapacityError under strict;
- the flow engine's queue-slot re-runs land in the unified capacity
  trajectory; strict refuses them;
- the `capacity:` config block and the pallas power-of-two egress
  validation parse/fail at config time.
"""

import hashlib
import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from shadow_tpu.core.capacity import (CapacityError,  # noqa: E402
                                      CapacityTrajectory, RingPolicy,
                                      next_pow2)
from shadow_tpu.core.config import (ConfigError,  # noqa: E402
                                    load_config_str)
from shadow_tpu.guards import make_guards, summarize  # noqa: E402
from shadow_tpu.tpu import elastic, profiling  # noqa: E402
from shadow_tpu.tpu.plane import (ingest, make_params,  # noqa: E402
                                  make_state, window_step)

MS = 1_000_000
N = 24


def _assert_trees_equal(a, b):
    for name, la, lb in zip(a._fields, a, b):
        la_l = jax.tree.leaves(la)
        lb_l = jax.tree.leaves(lb)
        for x, y in zip(la_l, lb_l):
            assert np.array_equal(np.asarray(x), np.asarray(y)), name


def _params(rr=False):
    rng = np.random.default_rng(3)
    m = 4
    lat = rng.integers(1 * MS, 30 * MS, size=(m, m)).astype(np.int64)
    lat = np.minimum(lat, lat.T)
    loss = np.full((m, m), 0.02, np.float32)
    host_node = (np.arange(N) % m).astype(np.int32)
    qdisc_rr = (np.arange(N) % 2 == 0) if rr else None
    return make_params(
        lat, loss, np.full((N,), 1_000_000_000, np.int64),
        host_node=host_node, qdisc_rr=qdisc_rr,
        down_bw_bps=np.full((N,), 500_000_000, np.int64))


def _init_state(params, ce, ci):
    return make_state(N, egress_cap=ce, ingress_cap=ci, params=params,
                      initial_tokens=np.asarray(params.tb_cap))


def _batches(n_windows, per_window=96, seed=7):
    """Capacity-independent injection batches: flat [B] arrays whose
    content never references a ring shape."""
    rng = np.random.default_rng(seed)
    out, seq0 = [], 0
    for _ in range(n_windows):
        src = rng.integers(0, N, per_window).astype(np.int32)
        dst = rng.integers(0, N, per_window).astype(np.int32)
        seq = np.arange(seq0, seq0 + per_window, dtype=np.int32)
        seq0 += per_window
        out.append((src, dst,
                    np.full(per_window, 1200, np.int32),
                    seq.copy(), seq,
                    np.zeros(per_window, bool)))
    return out


def _drive(params, state, batches, *, rr, aqm, no_loss, policy=None,
           guards=None, expect_clean=False):
    """Run len(batches) windows of ingest + window_step under the
    capacity policy; returns (state, delivered-stream, guards).
    The delivered stream collects masked entries in presentation order
    — the capacity-independent witness of what the hosts saw."""
    key = jax.random.key(5)
    window = jnp.int32(10 * MS)
    step = jax.jit(lambda st, sh, g: window_step(
        st, params, key, sh, window, rr_enabled=rr, router_aqm=aqm,
        no_loss=no_loss, guards=g))
    stream = []
    shift = jnp.int32(0)
    for w, (src, dst, nbytes, prio, seq, ctrl) in enumerate(batches):
        def attempt(st, _g=guards, _sh=shift, _b=(src, dst, nbytes, prio,
                                                  seq, ctrl)):
            bsrc, bdst, bbytes, bprio, bseq, bctrl = map(jnp.asarray, _b)
            st1 = ingest(st, bsrc, bdst, bbytes, bprio, bseq, bctrl)
            eg = st1.n_overflow_dropped - st.n_overflow_dropped
            res = step(st1, _sh, _g)
            if _g is not None:
                st2, deliv, _nx, g2 = res
            else:
                st2, deliv, _nx = res
                g2 = None
            inn = st2.n_overflow_dropped - st1.n_overflow_dropped
            return (st2, deliv, g2), eg, inn

        if policy is None:
            out, eg, inn = attempt(state)
            if expect_clean:
                assert int(np.asarray(eg).sum()) == 0
                assert int(np.asarray(inn).sum()) == 0
        else:
            out, _ = elastic.run_elastic_window(
                state, attempt, policy, time_ns=(w + 1) * 10 * MS)
        state, deliv, guards = out
        mask = np.asarray(deliv["mask"])
        cols = {k: np.asarray(deliv[k]) for k in
                ("src", "seq", "deliver_rel", "bytes")}
        rows, lanes = np.nonzero(mask)
        stream.append([
            (int(r), int(cols["src"][r, c]), int(cols["seq"][r, c]),
             int(cols["deliver_rel"][r, c]), int(cols["bytes"][r, c]))
            for r, c in zip(rows, lanes)])
        shift = window
    return state, stream, guards


# -- the headline: elastic == pre-provisioned, bitwise --------------------

@pytest.mark.slow  # 4-cell growth-parity matrix (~62s); stays GATING
# in CI's tier-1-overflow unfiltered step
@pytest.mark.parametrize("rr,aqm,no_loss", [
    (False, False, False),
    (True, False, False),
    (False, True, False),
    (True, True, True),
])
def test_elastic_parity_matrix(rr, aqm, no_loss):
    """A run that starts at (CE=4, CI=6) and grows on demand ends
    canonically bitwise-identical to a run pre-provisioned at the
    final capacity: same live state, same delivered stream, clean
    guards on both, and at least one growth actually happened."""
    params = _params(rr=rr)
    batches = _batches(6)
    policy = RingPolicy(mode="elastic", max_doublings=4,
                        egress_cap=4, ingress_cap=6)
    s_el, d_el, g_el = _drive(
        params, _init_state(params, 4, 6), batches, rr=rr, aqm=aqm,
        no_loss=no_loss, policy=policy, guards=make_guards(N))
    assert len(policy.trajectory.growth_events()) >= 1, \
        policy.trajectory.events
    cef, cif = policy.egress_cap, policy.ingress_cap
    s_pre, d_pre, g_pre = _drive(
        params, elastic.grow_state(_init_state(params, 4, 6), cef, cif),
        batches, rr=rr, aqm=aqm, no_loss=no_loss, policy=None,
        guards=make_guards(N), expect_clean=True)
    assert d_el == d_pre
    _assert_trees_equal(elastic.canonical_state(s_el),
                        elastic.canonical_state(s_pre))
    assert summarize(g_el)["clean"], summarize(g_el)
    assert summarize(g_pre)["clean"]
    # guard accumulators match too: re-executed attempts were restored
    # from the snapshot, never double-counted
    _assert_trees_equal(g_el, g_pre)


def test_elastic_zero_ring_drops():
    """The committed elastic stream never contains a ring-full drop
    (the overflowing attempts were discarded)."""
    params = _params()
    policy = RingPolicy(mode="elastic", max_doublings=4,
                        egress_cap=4, ingress_cap=6)
    s, _d, _g = _drive(params, _init_state(params, 4, 6), _batches(6),
                       rr=False, aqm=False, no_loss=False, policy=policy)
    assert int(np.asarray(s.n_overflow_dropped).sum()) == 0
    assert len(policy.trajectory.growth_events()) >= 1


# -- grow_state / canonical_state ----------------------------------------

def test_grow_state_matches_preprovisioned_fresh_world():
    world = profiling.build_world(16, warmup_windows=0, egress_cap=4,
                                  ingress_cap=8)
    grown = elastic.grow_state(world["state"], 8, 16)
    big = profiling.build_world(16, warmup_windows=0, egress_cap=8,
                                ingress_cap=16)["state"]
    _assert_trees_equal(grown, big)  # raw bitwise, not just canonical
    assert elastic.ring_dims(grown) == (8, 16)


def test_grow_state_noop_and_shrink_refused():
    st = make_state(4, egress_cap=8, ingress_cap=8)
    assert elastic.grow_state(st, 8, 8) is st
    with pytest.raises(ValueError, match="shrink"):
        elastic.grow_state(st, 4, 8)


def test_next_pow2():
    assert [next_pow2(v) for v in (1, 2, 3, 5, 8, 9)] == \
        [1, 2, 4, 8, 8, 16]


# -- policy modes ---------------------------------------------------------

def test_strict_mode_raises_with_blame():
    params = _params()
    policy = RingPolicy(mode="strict", egress_cap=4, ingress_cap=6)
    with pytest.raises(CapacityError) as ei:
        _drive(params, _init_state(params, 4, 6), _batches(4),
               rr=False, aqm=False, no_loss=False, policy=policy)
    assert "strict" in str(ei.value)
    assert ei.value.blame  # per-host indices named


def test_fixed_mode_records_drop_once_and_commits():
    params = _params()
    policy = RingPolicy(mode="fixed", egress_cap=4, ingress_cap=6)
    s, _d, _g = _drive(params, _init_state(params, 4, 6), _batches(4),
                       rr=False, aqm=False, no_loss=False, policy=policy)
    assert int(np.asarray(s.n_overflow_dropped).sum()) > 0
    drops = [e for e in policy.trajectory.events
             if e["kind"] == "capacity-drop"]
    assert drops and elastic.ring_dims(s) == (4, 6)
    # once-per-run per ring, not once per window
    assert len([e for e in drops if e["ring"] == "egress"]) <= 1


def test_exhausted_budget_commits_with_drops():
    params = _params()
    policy = RingPolicy(mode="elastic", max_doublings=0,
                        egress_cap=4, ingress_cap=6)
    s, _d, _g = _drive(params, _init_state(params, 4, 6), _batches(4),
                       rr=False, aqm=False, no_loss=False, policy=policy)
    assert int(np.asarray(s.n_overflow_dropped).sum()) > 0
    assert any(e["kind"] == "capacity-exhausted"
               for e in policy.trajectory.events)
    assert not policy.trajectory.growth_events()


# -- recompile discipline -------------------------------------------------

def test_growth_recompiles_are_log2_bounded():
    """The PR-1 recompile-counter harness over grown ring shapes: ONE
    compile per (CE, CI) shape, every same-shape window a cache hit —
    so an elastic run pays at most 1 + growth-events compiles."""
    from shadow_tpu.analysis.recompile import CompileCounter

    counter = CompileCounter(
        window_step,
        static_argnames=("rr_enabled", "router_aqm", "no_loss"))
    params = _params()
    base = _init_state(params, 4, 6)
    key = jax.random.key(5)
    for ce, ci in [(4, 6), (8, 8), (16, 16)]:
        counter.expect(1)  # first sight of this ring shape
        st = elastic.grow_state(base, ce, ci)
        for r in range(3):
            st, _d, _n = counter(
                st, params, key, np.int32(0 if r == 0 else 10 * MS),
                np.int32(10 * MS), rr_enabled=False, router_aqm=False,
                no_loss=False)
    assert counter.unexpected_misses == 0, counter.log


# -- respawn workload is capacity-independent -----------------------------

def test_respawn_batch_capacity_independent():
    """The PHOLD respawn seq rank counts DUE lanes, not columns — the
    same delivered entries at different ring widths (due lanes sit at
    the row tail) must respawn identical (dst, seq) packets."""
    spawn_seq = jnp.asarray([100, 200], jnp.int32)

    def deliv(ci, due_per_row=(2, 1)):
        mask = np.zeros((2, ci), bool)
        src = np.zeros((2, ci), np.int32)
        seq = np.zeros((2, ci), np.int32)
        for r, k in enumerate(due_per_row):
            for j in range(k):
                c = ci - k + j  # tail lanes
                mask[r, c] = True
                src[r, c] = r + 3
                seq[r, c] = 50 + 10 * r + j
        return {"mask": jnp.asarray(mask), "src": jnp.asarray(src),
                "seq": jnp.asarray(seq)}

    outs = []
    for ci in (4, 8):
        mask, dst, _b, seq, _c = profiling.respawn_batch(
            deliv(ci), spawn_seq, jnp.int32(2), 16, ci)
        m = np.asarray(mask)
        outs.append((np.asarray(dst)[m].tolist(),
                     np.asarray(seq)[m].tolist()))
    assert outs[0] == outs[1]


# -- checkpoint/restore across a resize -----------------------------------

def _digest(*trees):
    h = hashlib.sha256()
    for tree in trees:
        for leaf in jax.tree.leaves(jax.device_get(tree)):
            arr = np.asarray(leaf)
            h.update(str(arr.dtype).encode())
            h.update(arr.tobytes())
    return h.hexdigest()


def test_plane_checkpoint_restores_into_grown_rings(tmp_path):
    from shadow_tpu.faults import (load_plane_checkpoint,
                                   save_plane_checkpoint)

    world = profiling.build_world(16, warmup_windows=2, egress_cap=32,
                                  ingress_cap=32)
    state = world["state"]
    key_data = jax.random.key_data(world["rng_root"])
    path = str(tmp_path / "ck")
    save_plane_checkpoint(path, state=state, clock_ns=123,
                          rng_key_data=key_data)
    from shadow_tpu.faults.checkpoint import load_checkpoint

    meta, _arrays = load_checkpoint(path)
    assert meta["ring_dims"] == {"egress_cap": 32, "ingress_cap": 32}

    # restore a CE=32 checkpoint into a CE=64/CI=64 world: digest must
    # equal growing the live state directly
    restored = load_plane_checkpoint(path, state_template=state,
                                     grow_to=(64, 64))
    assert elastic.ring_dims(restored["state"]) == (64, 64)
    assert _digest(restored["state"]) == \
        _digest(elastic.grow_state(state, 64, 64))
    # and the grown world steps identically to the directly-grown one
    out_a = window_step(restored["state"], world["params"],
                        world["rng_root"], jnp.int32(10 * MS),
                        world["window"], rr_enabled=False)
    out_b = window_step(elastic.grow_state(state, 64, 64),
                        world["params"], world["rng_root"],
                        jnp.int32(10 * MS), world["window"],
                        rr_enabled=False)
    assert _digest(out_a[0]) == _digest(out_b[0])


def test_plane_checkpoint_grow_to_refuses_shrink(tmp_path):
    from shadow_tpu.faults import (load_plane_checkpoint,
                                   save_plane_checkpoint)

    world = profiling.build_world(8, warmup_windows=0, egress_cap=16,
                                  ingress_cap=16)
    path = str(tmp_path / "ck")
    save_plane_checkpoint(path, state=world["state"], clock_ns=0,
                          rng_key_data=jax.random.key_data(
                              world["rng_root"]))
    with pytest.raises(ValueError, match="shrink"):
        load_plane_checkpoint(path, state_template=world["state"],
                              grow_to=(8, 16))


# -- config block ---------------------------------------------------------

BASE_CFG = """
general: {stop_time: 1s}
network: {graph: {type: 1_gbit_switch}}
hosts: {h0: {network_node_id: 0}}
"""


def test_capacity_config_block_parses():
    cfg = load_config_str(BASE_CFG + "capacity: {mode: elastic, "
                                     "max_doublings: 5}")
    assert cfg.capacity.mode == "elastic"
    assert cfg.capacity.max_doublings == 5
    # defaults
    cfg = load_config_str(BASE_CFG)
    assert cfg.capacity.mode == "fixed"
    assert cfg.capacity.max_doublings == 3


def test_capacity_config_validation():
    with pytest.raises(ConfigError, match="capacity.mode"):
        load_config_str(BASE_CFG + "capacity: {mode: rubber}")
    with pytest.raises(ConfigError, match="max_doublings"):
        load_config_str(BASE_CFG + "capacity: {max_doublings: -1}")
    with pytest.raises(ConfigError, match="unknown option"):
        load_config_str(BASE_CFG + "capacity: {bounce: 1}")


def test_pallas_non_pow2_egress_cap_is_config_error():
    """plane_kernel: pallas + a non-power-of-two egress cap used to die
    at trace time deep in pallas_egress; it must be a clear ConfigError
    at parse time (elastic growth keeps power-of-two, so an elastic run
    never loses pallas eligibility)."""
    with pytest.raises(ConfigError, match="power-of-two"):
        load_config_str(
            BASE_CFG + "experimental: {plane_kernel: pallas, "
                       "tpu_egress_cap: 20}")
    cfg = load_config_str(
        BASE_CFG + "experimental: {plane_kernel: pallas, "
                   "tpu_egress_cap: 32}")
    assert cfg.experimental.tpu_egress_cap == 32
    with pytest.raises(ConfigError, match="tpu_ingress_cap"):
        load_config_str(BASE_CFG + "experimental: {tpu_ingress_cap: 0}")


# -- trajectory record ----------------------------------------------------

def test_trajectory_record_shapes():
    t = CapacityTrajectory("elastic")
    ev = t.record_growth(time_ns=5, ring="egress", from_cap=4, to_cap=8,
                         overflow=3, plane="test")
    assert ev["kind"] == "capacity-growth" and ev["to"] == 8
    t.record_drop(time_ns=9, ring="ingress", cap=8, overflow=2,
                  plane="test", exhausted=True)
    assert [e["kind"] for e in t.events] == \
        ["capacity-growth", "capacity-exhausted"]
    assert t.as_dict()["mode"] == "elastic"
    assert len(t.growth_events()) == 1


def test_harvester_annotations_and_trace_instants(tmp_path):
    import json

    from shadow_tpu.telemetry import TelemetryHarvester, export

    sink = str(tmp_path / "hb.jsonl")
    h = TelemetryHarvester(interval_ns=MS, sink=sink)
    h.note_event({"kind": "capacity-growth", "time_ns": 17,
                  "ring": "egress", "from": 4, "to": 8})
    h.tick(MS, device={"pkts_out": np.asarray([1, 2], np.int32)})
    h.finalize()
    lines = [json.loads(line) for line in open(sink)]
    sims = [r for r in lines if r["type"] == "sim"]
    assert sims and sims[0]["annotations"][0]["kind"] == \
        "capacity-growth"
    trace_path = str(tmp_path / "trace.json")
    export.write_perfetto_trace(h.heartbeats, trace_path)
    trace = json.load(open(trace_path))
    instants = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert instants and instants[0]["name"] == "capacity-growth"


# -- device transport: growth never perturbs the packet trace -------------

TRANSPORT_CFG = """
general: {{stop_time: 20s, seed: 1}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{use_tpu_transport: true, tpu_transport_mode: {mode},
               tpu_ingress_cap: {cap}}}
{capacity}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: http-server, args: ["80", "131072"], start_time: 2s,
       expected_final_state: running}}
  client1:
    network_node_id: 0
    processes:
    - {{path: http-client, args: ["server", "80"], start_time: 3s}}
"""


def _run_transport(mode, cap, capacity=""):
    from shadow_tpu.core.manager import Manager
    from shadow_tpu.net import packet as packet_mod

    trace = []

    def hook(packet, status):
        from shadow_tpu.core import worker as worker_mod

        host = worker_mod.current_host()
        trace.append((host.name if host else None,
                      host.now() if host else -1, int(status),
                      packet.src, packet.dst, packet.payload_size()))

    cfg = load_config_str(TRANSPORT_CFG.format(
        mode=mode, cap=cap, capacity=capacity))
    mgr = Manager(cfg)
    old = packet_mod.status_trace_hook
    packet_mod.status_trace_hook = hook
    try:
        stats = mgr.run()
    finally:
        packet_mod.status_trace_hook = old
    return trace, stats, mgr


@pytest.mark.parametrize("mode", ["sync", "mirrored"])
def test_transport_elastic_growth_trace_parity(mode):
    """An elastic transport run started at tpu_ingress_cap=2 grows its
    in-flight rings and produces the EXACT packet-status event stream
    of a pre-provisioned run — with growth events recorded and zero
    mirror divergence."""
    t_big, s_big, _ = _run_transport(mode, 256)
    t_el, s_el, mgr = _run_transport(
        mode, 2, "capacity: {mode: elastic, max_doublings: 8}")
    assert s_big.process_failures == [] and s_el.process_failures == []
    assert t_big == t_el and len(t_big) > 100
    growths = [e for e in s_el.capacity_events
               if e["kind"] == "capacity-growth"]
    assert growths and growths[0]["ring"] == "transport-ingress"
    assert mgr.transport._ingress_cap > 2
    assert mgr.transport.divergence_count == 0
    assert s_big.capacity_events == []  # pre-provisioned: clean record


def test_transport_strict_raises_capacity_error():
    with pytest.raises(CapacityError, match="ingress-capacity"):
        _run_transport("sync", 2, "capacity: {mode: strict}")


def test_transport_top_level_strict_promotes_fixed_drops():
    """Top-level `strict: true` with the default fixed capacity mode
    also refuses silent ring drops (the satellite promotion)."""
    with pytest.raises(CapacityError):
        _run_transport("sync", 2, "strict: true")


# -- flow engine: the unified trajectory ----------------------------------

FLOW_GML = """\
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        node [ id 1 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "5 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "40 ms" packet_loss 0.002 ]
        edge [ source 1 target 1 latency "5 ms" packet_loss 0.0 ]
      ]
"""


def _flow_cfg(extra=""):
    return (
        "general: {stop_time: 30s, seed: 1}\n"
        "experimental: {use_flow_engine: true}\n" + extra +
        "network:\n  graph:\n    type: gml\n    inline: |\n" + FLOW_GML +
        "hosts:\n"
        "  server:\n    network_node_id: 0\n    processes:\n"
        "    - {path: tgen-server, args: ['8888'], start_time: 1s,\n"
        "       expected_final_state: running}\n"
        "  client0:\n    network_node_id: 1\n    processes:\n"
        "    - {path: tgen-client, args: ['server', '8888', '20000',"
        " '1'], start_time: 2s}\n")


def _poison_first_attempt(monkeypatch, drops=3):
    from shadow_tpu.tpu import floweng

    calls = []
    real_make = floweng.make_flow_world
    real_results = floweng.flow_results

    def fake_make(lat, size, **kw):
        calls.append(kw.get("queue_slots"))
        return real_make(lat, size, **kw)

    def fake_results(world):
        res = real_results(world)
        if len(calls) == 1:
            res = dict(res)
            res["queue_drops"] = drops
        return res

    monkeypatch.setattr(floweng, "make_flow_world", fake_make)
    monkeypatch.setattr(floweng, "flow_results", fake_results)
    return calls


@pytest.mark.slow  # Manager-driven flow-engine run + poisoned rerun
# (~35s); stays GATING in CI's flow-engine-slow step (tier-1 budget)
def test_flowplan_ring_rerun_lands_in_trajectory(monkeypatch):
    from shadow_tpu.core.manager import Manager

    calls = _poison_first_attempt(monkeypatch)
    cfg = load_config_str(_flow_cfg())
    stats = Manager(cfg).run()
    assert calls == [256, 512]
    growths = [e for e in stats.capacity_events
               if e["kind"] == "capacity-growth"]
    assert growths == [{
        "kind": "capacity-growth", "time_ns": 30_000_000_000,
        "ring": "flow-queue", "from": 256, "to": 512, "overflow": 3,
        "plane": "floweng", "bucket_window_us": growths[0][
            "bucket_window_us"]}]
    assert stats.process_failures == []


@pytest.mark.slow  # Manager-driven flow-engine run (~22s); stays
# GATING in CI's tier-1-overflow unfiltered step
def test_flowplan_strict_refuses_ring_drops(monkeypatch):
    from shadow_tpu.core.manager import Manager

    _poison_first_attempt(monkeypatch)
    cfg = load_config_str(_flow_cfg("capacity: {mode: strict}\n"))
    with pytest.raises(CapacityError, match="flow engine"):
        Manager(cfg).run()


# -- chaos_smoke kill -> resume with growth mid-run (subprocess) ----------

@pytest.mark.slow
def test_chaos_smoke_kill_resume_parity_across_growth(tmp_path):
    """A killed-and-resumed elastic chaos run (growth events before the
    kill) finishes bitwise-identical to the uninterrupted one, growth
    history and all."""
    import json
    import subprocess

    repo = os.path.join(os.path.dirname(__file__), "..")
    base = [sys.executable, os.path.join(repo, "tools", "chaos_smoke.py"),
            "--hosts", "32", "--windows", "16", "--capacity", "elastic",
            "--egress-cap", "4", "--ingress-cap", "8"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    full = subprocess.run(base, capture_output=True, text=True, env=env,
                          cwd=repo)
    assert full.returncode == 0, full.stderr
    full_out = json.loads(full.stdout)
    assert full_out["capacity"]["growth_events"] >= 1

    ckpt_dir = str(tmp_path / "ckpts")
    killed = subprocess.run(
        base + ["--checkpoint-dir", ckpt_dir, "--checkpoint-every", "6",
                "--kill-at", "10"],
        capture_output=True, text=True, env=env, cwd=repo)
    assert killed.returncode == 137, killed.stderr
    resumed = subprocess.run(
        base + ["--resume", os.path.join(ckpt_dir, "ckpt-000000000006")],
        capture_output=True, text=True, env=env, cwd=repo)
    assert resumed.returncode == 0, resumed.stderr
    res_out = json.loads(resumed.stdout)
    assert res_out["state_digest"] == full_out["state_digest"]
    assert res_out["capacity"]["final"] == full_out["capacity"]["final"]
