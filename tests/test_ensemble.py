"""Worlds-parity: the runtime witness behind the SL701/702 proofs.

`drive_ensemble` batches W independent worlds into one program. The
SL701 world-isolation proof says no primitive in the batched jaxpr
crosses the world axis, and SL702 says the per-world RNG streams are
disjoint — so world b of a W-world run IS the solo run of world b, by
theorem. This file pins that claim at runtime: per-world slices of the
ensemble's final canonical state are digest-identical to solo
`drive_chained_windows` twins driven with the same `world_key`, and
every world stays live (>0 events).

The W=2 case is tier-1; the 8-world GATING case is @slow and runs
unfiltered in CI's worlds-parity step.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.tpu import (ingest_rows, profiling, unpack_planes,  # noqa: E402
                            window_step)
from shadow_tpu.tpu import elastic  # noqa: E402
from shadow_tpu.workloads.phold import respawn_batch  # noqa: E402
from shadow_tpu.workloads.runner import digest_pytrees  # noqa: E402

N = 32
M = 8
ROUNDS = 12
CHAIN_LEN = 4
SPAWN_BASE = 10_000


def _world():
    return profiling.build_world(N, n_nodes=M, egress_cap=8,
                                 ingress_cap=16, seed=3,
                                 warmup_windows=1)


def _make_chain_fn(params, window):
    """The per-world PHOLD chain — the SAME function is handed solo to
    `drive_chained_windows` and batched to `drive_ensemble` (that
    identity is the whole point of the parity claim)."""
    def chain_fn(state, extras, rids, _pr):
        key, spawn_seq, total = extras

        def round_fn(carry, round_idx):
            state, spawn_seq = carry
            shift = jnp.where(round_idx == 0, jnp.int32(0), window)
            out = window_step(state, params, key, shift, window,
                              rr_enabled=False)
            (state, delivered, _nx), _m, _g, _h, _fr = \
                unpack_planes(out)
            mask, new_dst, nbytes, seq_vals, ctrl = respawn_batch(
                delivered, spawn_seq, round_idx, N,
                state.in_src.shape[1])
            out = ingest_rows(state, new_dst, nbytes, seq_vals,
                              seq_vals, ctrl, valid=mask)
            (state,), _m, _g, _h, _fr = unpack_planes(out, n_lead=1)
            spawn_seq = spawn_seq + mask.sum(axis=1, dtype=jnp.int32)
            return (state, spawn_seq), mask.sum(dtype=jnp.int32)

        (state, spawn_seq), nd = jax.lax.scan(
            round_fn, (state, spawn_seq), rids)
        zeros = jnp.zeros((N,), jnp.int32)
        return state, (key, spawn_seq, total + nd.sum()), zeros, zeros

    return chain_fn


def _solo_run(world, chain_fn, key):
    extras = (key, jnp.full((N,), SPAWN_BASE, jnp.int32),
              jnp.zeros((), jnp.int32))
    state, extras = elastic.drive_chained_windows(
        world["state"], extras, chain_fn, n_rounds=ROUNDS,
        chain_len=CHAIN_LEN)
    return state, extras


def _ensemble_run(world, chain_fn, keys, w):
    stacked = jax.tree.map(lambda x: jnp.stack([x] * w),
                           world["state"])
    extras = (keys,
              jnp.full((w, N), SPAWN_BASE, jnp.int32),
              jnp.zeros((w,), jnp.int32))
    return elastic.drive_ensemble(stacked, extras, chain_fn,
                                  n_rounds=ROUNDS, chain_len=CHAIN_LEN)


def _world_slice(tree, b):
    return jax.tree.map(lambda x: x[b], tree)


def _parity(w):
    world = _world()
    chain_fn = _make_chain_fn(world["params"], world["window"])
    keys = elastic.world_keys(world["rng_root"],
                              jnp.arange(w, dtype=jnp.int32))
    states, extras = _ensemble_run(world, chain_fn, keys, w)
    totals = np.asarray(jax.device_get(extras[2]), np.int64)

    # every world is LIVE: spawned events and a non-degenerate run
    assert (totals > 0).all(), totals

    digests = []
    for b in range(w):
        solo_state, solo_extras = _solo_run(world, chain_fn, keys[b])
        ens = digest_pytrees(
            elastic.canonical_state(_world_slice(states, b)),
            _world_slice(extras[1], b), _world_slice(extras[2], b))
        solo = digest_pytrees(
            elastic.canonical_state(solo_state),
            solo_extras[1], solo_extras[2])
        assert ens == solo, f"world {b}/{w} diverged from its solo twin"
        digests.append(ens)

    # and the worlds actually SEPARATE: the per-world `world_key` fold
    # gives every world a distinct trajectory (pairwise-distinct
    # digests) — parity green with aliased digests would mean the
    # SL702 premise is broken at the call site
    assert len(set(digests)) == w, digests
    return totals


@pytest.mark.slow  # 2-world ensemble + solo twins (~18s); CI's
# worlds-parity gate runs this file unfiltered
def test_worlds_parity_w2():
    """Tier-1: both worlds of a 2-world ensemble match their solo
    twins bitwise in canonical digest, and the two trajectories are
    distinct."""
    _parity(2)


@pytest.mark.slow  # CI's worlds-parity gate runs this file unfiltered
def test_worlds_parity_w8_gating():
    """The GATING case: all 8 worlds of an 8-world run digest-match
    their solo twins and every world processes >0 events."""
    totals = _parity(8)
    assert len(totals) == 8
