"""Duplicate/ordering edge cases surfaced by review: same-object duplicates
and post-pop duplicate keys must be caught, not silently processed."""

import pytest

from shadow_tpu.core.event import Event, EventQueue, TaskRef


def test_same_object_pushed_twice_caught_at_pop():
    q = EventQueue()
    e = Event.new_packet(100, "pkt", src_host_id=1, src_event_id=1)
    q.push(e)
    q.push(e)  # identity-equal: push-time comparison cannot distinguish
    q.pop()
    with pytest.raises(AssertionError, match="duplicate"):
        q.pop()


def test_duplicate_key_after_pop_caught():
    q = EventQueue()
    q.push(Event.new_packet(100, "p1", src_host_id=1, src_event_id=1))
    q.pop()
    q.push(Event.new_packet(100, "p2", src_host_id=1, src_event_id=1))
    with pytest.raises(AssertionError, match="duplicate"):
        q.pop()


def test_equal_key_distinct_payloads_caught_at_push():
    q = EventQueue()
    q.push(Event.new_packet(100, "a", src_host_id=1, src_event_id=1))
    with pytest.raises(AssertionError, match="duplicate event sort key"):
        q.push(Event.new_packet(100, "b", src_host_id=1, src_event_id=1))


def test_array_like_payloads_do_not_break_comparisons():
    import numpy as np

    q = EventQueue()
    q.push(Event.new_packet(100, np.array([1, 2]), src_host_id=1, src_event_id=1))
    q.push(Event.new_packet(100, np.array([1, 2]), src_host_id=2, src_event_id=1))
    assert q.pop().key[0] == 1
    assert q.pop().key[0] == 2


def test_bare_rate_numbers():
    from shadow_tpu.core import units

    assert units.parse_bits_per_sec(10**9) == 10**9
    assert units.parse_bits_per_sec("500") == 500
