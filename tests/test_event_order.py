import pytest

from shadow_tpu.core.event import Event, EventQueue, TaskRef


def _task():
    return TaskRef(lambda host: None)


def test_time_order():
    q = EventQueue()
    q.push(Event.new_local(200, _task(), event_id=1))
    q.push(Event.new_local(100, _task(), event_id=2))
    q.push(Event.new_local(150, _task(), event_id=3))
    assert [q.pop().time for _ in range(3)] == [100, 150, 200]


def test_packet_before_local_at_equal_time():
    # Parity: reference event.rs:102-110 — deliberate, affects determinism.
    q = EventQueue()
    q.push(Event.new_local(100, _task(), event_id=1))
    q.push(Event.new_packet(100, "pkt", src_host_id=9, src_event_id=5))
    first, second = q.pop(), q.pop()
    assert first.is_packet and not second.is_packet


def test_packet_tiebreak_by_src_host_then_event_id():
    # Parity: event.rs:131-155.
    q = EventQueue()
    q.push(Event.new_packet(100, "c", src_host_id=2, src_event_id=1))
    q.push(Event.new_packet(100, "b", src_host_id=1, src_event_id=7))
    q.push(Event.new_packet(100, "a", src_host_id=1, src_event_id=3))
    assert [q.pop().payload for _ in range(3)] == ["a", "b", "c"]


def test_local_tiebreak_by_event_id():
    # Parity: event.rs:163-184.
    q = EventQueue()
    q.push(Event.new_local(100, TaskRef(lambda h: None, "second"), event_id=12))
    q.push(Event.new_local(100, TaskRef(lambda h: None, "first"), event_id=4))
    assert q.pop().payload.name == "first"
    assert q.pop().payload.name == "second"


def test_monotonic_pop_assert():
    # Parity: event_queue.rs:36-39 — pushing into the past after popping is a bug.
    q = EventQueue()
    q.push(Event.new_local(100, _task(), event_id=1))
    assert q.pop().time == 100
    q.push(Event.new_local(50, _task(), event_id=2))
    with pytest.raises(AssertionError):
        q.pop()


def test_duplicate_sort_key_is_loud():
    # Two events with an identical sort key violate the per-host uniqueness
    # invariant; the queue must surface that, not a cryptic TypeError.
    q = EventQueue()
    q.push(Event.new_packet(100, "a", src_host_id=1, src_event_id=1))
    with pytest.raises(AssertionError, match="duplicate event sort key"):
        q.push(Event.new_packet(100, "b", src_host_id=1, src_event_id=1))


def test_next_time_and_len():
    q = EventQueue()
    assert q.next_time() is None
    assert not q
    q.push(Event.new_local(42, _task(), event_id=1))
    assert q.next_time() == 42
    assert len(q) == 1
