"""execve(2) for managed processes: image replacement with simulator
identity preserved.

Parity: reference `handler/unistd.rs:777` execve_common — pid and fd
table survive, CLOEXEC descriptors drop, caught dispositions reset,
exec'd code runs under the same interposition plane.
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")


def _compile(tmp_path, name, src):
    c = tmp_path / f"{name}.c"
    c.write_text(src)
    binary = tmp_path / name
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    return str(binary)


def _run(binary, args=(), expect="{exited: 0}", stop="30s"):
    arglist = ", ".join(f'"{a}"' for a in args)
    cfg = load_config_str(f"""
general: {{stop_time: {stop}, seed: 3}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: [{arglist}], start_time: 1s,
       expected_final_state: {expect}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


HELPER_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char **argv) {
    /* argv[1]: expected env marker; argv[2] (optional): inherited fd */
    const char *marker = getenv("SHADOW_EXEC_MARKER");
    if (!marker || strcmp(marker, argv[1])) return 60;
    if (argc > 2) {
        /* the pre-exec UDP socket must still exist, still bound */
        int fd = atoi(argv[2]);
        struct sockaddr_in a;
        socklen_t alen = sizeof a;
        if (getsockname(fd, (struct sockaddr *)&a, &alen)) return 61;
        if (ntohs(a.sin_port) != 7200) return 62;
    }
    /* and the simulated clock keeps ticking for the new image */
    struct timespec ts = {0, 50000000};
    nanosleep(&ts, 0);
    return 7;
}
"""


EXEC_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
    /* a bound UDP socket without CLOEXEC must survive the exec */
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons(7200);
    a.sin_addr.s_addr = INADDR_ANY;
    if (bind(fd, (struct sockaddr *)&a, sizeof a)) return 70;
    char fdbuf[16];
    snprintf(fdbuf, sizeof fdbuf, "%d", fd);
    char *args[] = {argv[1], "42", fdbuf, 0};
    char *envp[] = {"SHADOW_EXEC_MARKER=42", 0};
    execve(argv[1], args, envp);
    return 71; /* exec returned: failure */
}
"""


FORK_EXEC_C = r"""
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

int main(int argc, char **argv) {
    pid_t child = fork();
    if (child < 0) return 75;
    if (child == 0) {
        char *args[] = {argv[1], "m1", 0};
        char *envp[] = {"SHADOW_EXEC_MARKER=m1", 0};
        execve(argv[1], args, envp);
        _exit(76);
    }
    int status;
    if (waitpid(child, &status, 0) != child) return 77;
    if (!WIFEXITED(status) || WEXITSTATUS(status) != 7)
        return 100 + (WIFEXITED(status) ? WEXITSTATUS(status) : 99);
    return 0;
}
"""


BAD_EXEC_C = r"""
#include <errno.h>
#include <unistd.h>

int main(void) {
    char *args[] = {"nope", 0};
    char *envp[] = {0};
    if (execve("/nonexistent/binary", args, envp) != -1 || errno != ENOENT)
        return 78;
    return 0; /* exec failure returns and the process continues */
}
"""


def test_execve_replaces_image_keeps_identity(tmp_path):
    """The exec'd image runs under the sim with the same virtual process:
    env passes through, the non-CLOEXEC socket survives with its binding,
    and the final state reflects the NEW image's exit."""
    helper = _compile(tmp_path, "xhelper", HELPER_C)
    execer = _compile(tmp_path, "xexec", EXEC_C)
    _run(execer, args=[helper], expect="{exited: 7}")


def test_fork_then_exec_waitpid_roundtrip(tmp_path):
    """fork + execve + waitpid — THE process-spawning idiom."""
    helper = _compile(tmp_path, "xhelper2", HELPER_C)
    forker = _compile(tmp_path, "xforker", FORK_EXEC_C)
    _run(forker, args=[helper])


def test_execve_failure_returns_enoent(tmp_path):
    _run(_compile(tmp_path, "xbad", BAD_EXEC_C))


def test_execve_enoexec_returns_to_caller(tmp_path):
    """A file with the exec bit but no valid format (no ELF magic, no
    shebang) must fail with ENOEXEC BEFORE the old image is torn down —
    the caller continues."""
    import os

    junk = tmp_path / "junk"
    junk.write_text("just text, no shebang\n")
    os.chmod(junk, 0o755)
    src = r"""
#include <errno.h>
#include <unistd.h>
int main(int argc, char **argv) {
    char *args[] = {argv[1], 0};
    char *envp[] = {0};
    if (execve(argv[1], args, envp) != -1 || errno != ENOEXEC) return 79;
    return 0;
}
"""
    binary = _compile(tmp_path, "xjunk", src)
    _run(binary, args=[str(junk)])


def test_execve_null_argv_envp(tmp_path):
    """execve(path, NULL, NULL) is legal on Linux: empty vectors."""
    helper = _compile(tmp_path, "xnull_t", r"""
int main(void) { return 7; }
""")
    src = r"""
#include <unistd.h>
int main(int argc, char **argv) {
    execve(argv[1], 0, 0);
    return 71;
}
"""
    binary = _compile(tmp_path, "xnull", src)
    _run(binary, args=[helper], expect="{exited: 7}")


def test_system3_shells_out(tmp_path):
    """system(3) = fork + execve("/bin/sh", "-c", ...) + waitpid: the
    whole chain runs under the simulation, including a shell script
    child (shebang exec)."""
    script = tmp_path / "hello.sh"
    script.write_text("#!/bin/sh\nexit 5\n")
    import os

    os.chmod(script, 0o755)
    src = r"""
#include <stdlib.h>
#include <sys/wait.h>

int main(int argc, char **argv) {
    int rc = system(argv[1]);
    if (!WIFEXITED(rc) || WEXITSTATUS(rc) != 5) return 96;
    rc = system("exit 3");
    if (!WIFEXITED(rc) || WEXITSTATUS(rc) != 3) return 97;
    return 0;
}
"""
    binary = _compile(tmp_path, "xsystem", src)
    _run(binary, args=[str(script)])


def test_system3_of_nonexistent_returns_127(tmp_path):
    """system("/nonexistent") must return 127<<8 (the shell's exec
    failure) without harming the calling process."""
    src = r"""
#include <stdlib.h>
#include <sys/wait.h>
int main(void) {
    int rc = system("/nonexistent/definitely-not-here");
    if (!WIFEXITED(rc) || WEXITSTATUS(rc) != 127) return 98;
    return 0;
}
"""
    binary = _compile(tmp_path, "xsys404", src)
    _run(binary)


PY = shutil.which("python3")


@pytest.mark.skipif(PY is None, reason="no python3")
def test_python_subprocess_run_with_pipes(tmp_path):
    """CPython's subprocess: vfork-based fork_exec, pipe redirection via
    dup2-onto-stdio (low-fd shadowing), newfstatat/lseek probes on
    virtual fds, waitpid(-1) — the whole popen stack in simulated time."""
    script = tmp_path / "runner.py"
    script.write_text(
        "import subprocess, sys\n"
        "r = subprocess.run(['/bin/echo', 'hello-child'],"
        " capture_output=True, text=True)\n"
        "assert r.returncode == 0 and r.stdout.strip() == 'hello-child',"
        " (r.returncode, r.stdout)\n"
        "r2 = subprocess.run(['/bin/sh', '-c', 'exit 4'])\n"
        "assert r2.returncode == 4, r2.returncode\n"
        "print('subprocess OK')\n")
    cfg = load_config_str(f"""
general: {{stop_time: 60s, seed: 3}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {PY}, args: ["{script}"], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


SPAWN_FA_C = r"""
#include <errno.h>
#include <spawn.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

extern char **environ;

int main(void) {
    int p[2];
    if (pipe(p)) return 80;
    posix_spawn_file_actions_t fa;
    posix_spawn_file_actions_init(&fa);
    posix_spawn_file_actions_adddup2(&fa, p[1], 1);
    posix_spawn_file_actions_addclose(&fa, p[0]);
    posix_spawn_file_actions_addclose(&fa, p[1]);
    pid_t pid;
    char *argv[] = {"echo", "spawned", 0};
    if (posix_spawn(&pid, "/bin/echo", &fa, 0, argv, environ)) return 81;
    /* the PARENT's pipe fds must be untouched by the child's actions */
    close(p[1]);
    char buf[64];
    long n = read(p[0], buf, sizeof buf);
    if (n <= 0) return 82; /* parent's read end died: table corrupted */
    if (strncmp(buf, "spawned", 7)) return 83;
    /* EOF after the child exits and all writers close */
    n = read(p[0], buf, sizeof buf);
    if (n != 0) return 84;
    int status;
    if (waitpid(pid, &status, 0) != pid) return 85;
    if (!WIFEXITED(status) || WEXITSTATUS(status) != 0) return 86;
    /* spawn failure: error reported via the spawn return, parent fine */
    if (posix_spawn(&pid, "/nonexistent/xyz", 0, 0, argv, environ) == 0) {
        if (waitpid(pid, &status, 0) != pid) return 87;
        if (!WIFEXITED(status) || WEXITSTATUS(status) != 127) return 88;
    }
    return 0;
}
"""


def test_posix_spawn_file_actions(tmp_path):
    """posix_spawn with adddup2/addclose file actions: the helper's fd
    mutations land on ITS copied table (vfork copies the fd table), the
    parent's pipe survives, the child's stdout is captured through the
    simulated pipe, and a failed spawn reports 127 via waitpid."""
    c = tmp_path / "spawnfa.c"
    c.write_text(SPAWN_FA_C)
    binary = tmp_path / "spawnfa"
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    _run(str(binary))
