"""execve(2) for managed processes: image replacement with simulator
identity preserved.

Parity: reference `handler/unistd.rs:777` execve_common — pid and fd
table survive, CLOEXEC descriptors drop, caught dispositions reset,
exec'd code runs under the same interposition plane.
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")


def _compile(tmp_path, name, src):
    c = tmp_path / f"{name}.c"
    c.write_text(src)
    binary = tmp_path / name
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    return str(binary)


def _run(binary, args=(), expect="{exited: 0}", stop="30s"):
    arglist = ", ".join(f'"{a}"' for a in args)
    cfg = load_config_str(f"""
general: {{stop_time: {stop}, seed: 3}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: [{arglist}], start_time: 1s,
       expected_final_state: {expect}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


HELPER_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char **argv) {
    /* argv[1]: expected env marker; argv[2] (optional): inherited fd */
    const char *marker = getenv("SHADOW_EXEC_MARKER");
    if (!marker || strcmp(marker, argv[1])) return 60;
    if (argc > 2) {
        /* the pre-exec UDP socket must still exist, still bound */
        int fd = atoi(argv[2]);
        struct sockaddr_in a;
        socklen_t alen = sizeof a;
        if (getsockname(fd, (struct sockaddr *)&a, &alen)) return 61;
        if (ntohs(a.sin_port) != 7200) return 62;
    }
    /* and the simulated clock keeps ticking for the new image */
    struct timespec ts = {0, 50000000};
    nanosleep(&ts, 0);
    return 7;
}
"""


EXEC_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
    /* a bound UDP socket without CLOEXEC must survive the exec */
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons(7200);
    a.sin_addr.s_addr = INADDR_ANY;
    if (bind(fd, (struct sockaddr *)&a, sizeof a)) return 70;
    char fdbuf[16];
    snprintf(fdbuf, sizeof fdbuf, "%d", fd);
    char *args[] = {argv[1], "42", fdbuf, 0};
    char *envp[] = {"SHADOW_EXEC_MARKER=42", 0};
    execve(argv[1], args, envp);
    return 71; /* exec returned: failure */
}
"""


FORK_EXEC_C = r"""
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

int main(int argc, char **argv) {
    pid_t child = fork();
    if (child < 0) return 75;
    if (child == 0) {
        char *args[] = {argv[1], "m1", 0};
        char *envp[] = {"SHADOW_EXEC_MARKER=m1", 0};
        execve(argv[1], args, envp);
        _exit(76);
    }
    int status;
    if (waitpid(child, &status, 0) != child) return 77;
    if (!WIFEXITED(status) || WEXITSTATUS(status) != 7)
        return 100 + (WIFEXITED(status) ? WEXITSTATUS(status) : 99);
    return 0;
}
"""


BAD_EXEC_C = r"""
#include <errno.h>
#include <unistd.h>

int main(void) {
    char *args[] = {"nope", 0};
    char *envp[] = {0};
    if (execve("/nonexistent/binary", args, envp) != -1 || errno != ENOENT)
        return 78;
    return 0; /* exec failure returns and the process continues */
}
"""


def test_execve_replaces_image_keeps_identity(tmp_path):
    """The exec'd image runs under the sim with the same virtual process:
    env passes through, the non-CLOEXEC socket survives with its binding,
    and the final state reflects the NEW image's exit."""
    helper = _compile(tmp_path, "xhelper", HELPER_C)
    execer = _compile(tmp_path, "xexec", EXEC_C)
    _run(execer, args=[helper], expect="{exited: 7}")


def test_fork_then_exec_waitpid_roundtrip(tmp_path):
    """fork + execve + waitpid — THE process-spawning idiom."""
    helper = _compile(tmp_path, "xhelper2", HELPER_C)
    forker = _compile(tmp_path, "xforker", FORK_EXEC_C)
    _run(forker, args=[helper])


def test_execve_failure_returns_enoent(tmp_path):
    _run(_compile(tmp_path, "xbad", BAD_EXEC_C))


def test_execve_enoexec_returns_to_caller(tmp_path):
    """A file with the exec bit but no valid format (no ELF magic, no
    shebang) must fail with ENOEXEC BEFORE the old image is torn down —
    the caller continues."""
    import os

    junk = tmp_path / "junk"
    junk.write_text("just text, no shebang\n")
    os.chmod(junk, 0o755)
    src = r"""
#include <errno.h>
#include <unistd.h>
int main(int argc, char **argv) {
    char *args[] = {argv[1], 0};
    char *envp[] = {0};
    if (execve(argv[1], args, envp) != -1 || errno != ENOEXEC) return 79;
    return 0;
}
"""
    binary = _compile(tmp_path, "xjunk", src)
    _run(binary, args=[str(junk)])


def test_execve_null_argv_envp(tmp_path):
    """execve(path, NULL, NULL) is legal on Linux: empty vectors."""
    helper = _compile(tmp_path, "xnull_t", r"""
int main(void) { return 7; }
""")
    src = r"""
#include <unistd.h>
int main(int argc, char **argv) {
    execve(argv[1], 0, 0);
    return 71;
}
"""
    binary = _compile(tmp_path, "xnull", src)
    _run(binary, args=[helper], expect="{exited: 7}")


def test_system3_shells_out(tmp_path):
    """system(3) = fork + execve("/bin/sh", "-c", ...) + waitpid: the
    whole chain runs under the simulation, including a shell script
    child (shebang exec)."""
    script = tmp_path / "hello.sh"
    script.write_text("#!/bin/sh\nexit 5\n")
    import os

    os.chmod(script, 0o755)
    src = r"""
#include <stdlib.h>
#include <sys/wait.h>

int main(int argc, char **argv) {
    int rc = system(argv[1]);
    if (!WIFEXITED(rc) || WEXITSTATUS(rc) != 5) return 96;
    rc = system("exit 3");
    if (!WIFEXITED(rc) || WEXITSTATUS(rc) != 3) return 97;
    return 0;
}
"""
    binary = _compile(tmp_path, "xsystem", src)
    _run(binary, args=[str(script)])


def test_system3_of_nonexistent_returns_127(tmp_path):
    """system("/nonexistent") must return 127<<8 (the shell's exec
    failure) without harming the calling process."""
    src = r"""
#include <stdlib.h>
#include <sys/wait.h>
int main(void) {
    int rc = system("/nonexistent/definitely-not-here");
    if (!WIFEXITED(rc) || WEXITSTATUS(rc) != 127) return 98;
    return 0;
}
"""
    binary = _compile(tmp_path, "xsys404", src)
    _run(binary)
