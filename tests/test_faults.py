"""Fault plane correctness: determinism contracts, checkpoint/restore,
watchdog, self-healing (docs/robustness.md).

The load-bearing guarantees, per ISSUE acceptance:

- faults=None is bitwise-identical to the pre-fault plane, and NEUTRAL
  FaultArrays are bitwise-identical to faults=None, across the
  rr x aqm x no_loss matrix (the tests/test_plane_sortdiet.py pattern);
- a fault schedule is a pure function of (config, seed): two compiles
  are byte-identical, two Manager runs of the same faulted config are
  result-identical;
- checkpoint -> restore -> continue is bitwise-identical to an
  uninterrupted run (device plane), and corrupt checkpoints are
  REFUSED, not half-loaded;
- the round watchdog converts a wedged managed process into a
  structured WatchdogError with per-host blame within the timeout,
  after SIGKILLing the wedged native process so the round can finish;
- the Pallas kernel degrades to XLA on failure and the run completes.
"""

import os
import subprocess
import time as _walltime

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.core.config import (ConfigError, FaultsOptions,  # noqa: E402
                                    load_config_str)
from shadow_tpu.faults import (CheckpointError, KernelFallback,  # noqa: E402
                               WatchdogError, compile_schedule,
                               load_checkpoint, load_plane_checkpoint,
                               neutral_faults, prune_checkpoints,
                               retry_transient, save_plane_checkpoint,
                               write_checkpoint)
from shadow_tpu.faults.watchdog import HostBlame, RoundWatchdog  # noqa: E402
from shadow_tpu.telemetry import make_metrics  # noqa: E402
from shadow_tpu.tpu import ingest, make_params, make_state  # noqa: E402
from shadow_tpu.tpu.plane import window_step  # noqa: E402

MS = 1_000_000
N = 8


def busy_world(rr_mix=True):
    """The telemetry-test busy world: starved buckets, real loss, mixed
    qdiscs — every fault-gate path gets exercised."""
    rng = np.random.default_rng(7)
    lat = rng.integers(1 * MS, 20 * MS, size=(N, N)).astype(np.int32)
    loss = np.full((N, N), 0.3, np.float32)
    qrr = (np.arange(N) % 2 == 0) if rr_mix else np.zeros(N, bool)
    params = make_params(lat, loss, np.full((N,), 80_000, np.int64),
                         qdisc_rr=qrr, down_bw_bps=np.full((N,), 400_000))
    state = make_state(N, egress_cap=8, ingress_cap=8, params=params,
                       initial_tokens=np.asarray(params.tb_cap))
    b = 48
    state = ingest(
        state,
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(100, 1500, b), jnp.int32),
        jnp.asarray(rng.integers(0, 6, b), jnp.int32),
        jnp.arange(b, dtype=jnp.int32),
        jnp.asarray(rng.integers(0, 3, b) == 0),
        sock=jnp.asarray(rng.integers(0, 40, b), jnp.int32),
    )
    return state, params


def run_windows(state, params, *, windows=4, faults=None, **kw):
    key = jax.random.key(3)
    if faults is not None:
        step = jax.jit(lambda s, f, sh: window_step(
            s, params, key, sh, jnp.int32(10 * MS), faults=f, **kw))
    else:
        step = jax.jit(lambda s, sh: window_step(
            s, params, key, sh, jnp.int32(10 * MS), **kw))
    shift = jnp.int32(0)
    out = []
    for _ in range(windows):
        if faults is not None:
            state, delivered, nxt = step(state, faults, shift)
        else:
            state, delivered, nxt = step(state, shift)
        out.append((state, delivered, nxt))
        shift = jnp.int32(10 * MS)
    return out


# -- parity: faults=None == neutral masks, bitwise, across the matrix ----

@pytest.mark.parametrize("rr_enabled", [False, True])
@pytest.mark.parametrize("router_aqm", [False, True])
@pytest.mark.parametrize("no_loss", [False, True])
def test_neutral_faults_bitwise_invisible(rr_enabled, router_aqm, no_loss):
    state, params = busy_world(rr_mix=rr_enabled)
    kw = dict(rr_enabled=rr_enabled, router_aqm=router_aqm,
              no_loss=no_loss)
    with_f = run_windows(state, params, faults=neutral_faults(N, N), **kw)
    without = run_windows(state, params, **kw)
    for w, ((sa, da, na), (sb, db, nb)) in enumerate(zip(with_f, without)):
        for la, lb in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), (kw, w)
        for k in da:
            assert np.array_equal(np.asarray(da[k]),
                                  np.asarray(db[k])), (kw, w, k)
        assert int(na) == int(nb), (kw, w)
    assert int(np.asarray(with_f[-1][0].n_fault_dropped).sum()) == 0


# -- fault semantics on device -------------------------------------------

def test_crashed_host_neither_sends_nor_receives():
    state, params = busy_world()
    f = neutral_faults(N, N)._replace(
        host_alive=jnp.asarray(np.arange(N) != 0))
    runs = run_windows(state, params, faults=f, windows=3,
                       rr_enabled=True, router_aqm=False, no_loss=False)
    final = runs[-1][0]
    fd = np.asarray(final.n_fault_dropped)
    # host 0 had queued egress: purged and counted against it; packets
    # routed toward it count against it too
    assert fd.sum() > 0
    assert int(np.asarray(final.n_sent)[0]) == 0
    # nothing in this run is ever misattributed to the loss sample for
    # the crashed host's purge
    neutral = run_windows(state, params, faults=neutral_faults(N, N),
                          windows=3, rr_enabled=True, router_aqm=False,
                          no_loss=False)
    assert int(np.asarray(final.n_loss_dropped).sum()) <= \
        int(np.asarray(neutral[-1][0].n_loss_dropped).sum())


def test_corruption_drops_are_fault_not_loss():
    state, params = busy_world()
    kw = dict(rr_enabled=True, router_aqm=False, no_loss=False, windows=3)
    neutral = run_windows(state, params, faults=neutral_faults(N, N), **kw)
    f = neutral_faults(N, N)._replace(
        corrupt_p=jnp.full((N,), 0.999, jnp.float32))
    corrupted = run_windows(state, params, faults=f, **kw)
    sn, sc = neutral[0][0], corrupted[0][0]
    # the corruption stream is independent: the FIRST window's loss
    # draws are identical (same rng_counter start), so n_loss_dropped
    # matches bitwise while fault drops appear
    assert np.array_equal(np.asarray(sn.n_loss_dropped),
                          np.asarray(sc.n_loss_dropped))
    assert int(np.asarray(sc.n_fault_dropped).sum()) > 0
    assert int(np.asarray(sn.n_fault_dropped).sum()) == 0


def test_latency_degradation_delays_delivery():
    state, params = busy_world()
    kw = dict(rr_enabled=True, router_aqm=False, no_loss=True, windows=1)
    base = run_windows(state, params, faults=neutral_faults(N, N), **kw)
    f = neutral_faults(N, N)._replace(
        lat_mult=jnp.full((N, N), 8, jnp.int32))
    slow = run_windows(state, params, faults=f, **kw)
    # deliveries in the first window shrink (or stay) when every path
    # is 8x slower, and pending deliver times move later
    d_base = int(np.asarray(base[0][1]["mask"]).sum())
    d_slow = int(np.asarray(slow[0][1]["mask"]).sum())
    assert d_slow <= d_base
    assert int(slow[0][2]) >= int(base[0][2])


def test_bandwidth_division_throttles_egress():
    state, params = busy_world()
    kw = dict(rr_enabled=True, router_aqm=False, no_loss=True, windows=2)
    base = run_windows(state, params, faults=neutral_faults(N, N), **kw)
    f = neutral_faults(N, N)._replace(
        bw_div=jnp.full((N,), 64, jnp.int32))
    # start from an empty bucket so the degraded REFILL is what gates
    state2 = state._replace(tb_balance=jnp.zeros((N,), jnp.int32))
    throttled = run_windows(state2, params, faults=f, **kw)
    assert int(np.asarray(throttled[-1][0].n_sent).sum()) < \
        int(np.asarray(base[-1][0].n_sent).sum())


def test_pallas_kernel_refuses_faults():
    state, params = busy_world(rr_mix=False)
    with pytest.raises(ValueError, match="pallas"):
        window_step(state, params, jax.random.key(0), jnp.int32(0),
                    jnp.int32(10 * MS), rr_enabled=False, kernel="pallas",
                    faults=neutral_faults(N, N))


# -- schedule compile: seeded, deterministic, validated ------------------

HOSTS = [f"h{i}" for i in range(6)]


def _opts(**kw):
    return FaultsOptions(**kw)


def compile_(opts, seed=11, n_nodes=4):
    return compile_schedule(opts, host_names=HOSTS, n_nodes=n_nodes,
                            seed=seed, stop_time_ns=10_000 * MS)


def test_schedule_compile_deterministic():
    opts = _opts(
        events=[{"at": "1s", "kind": "host_crash", "host": "h1"},
                {"at": "2s", "kind": "host_reboot", "host": "h1"}],
        random={"host_crashes": {"count": 3, "window": ["1s", "8s"],
                                 "downtime": "500ms"},
                "iface_flaps": {"count": 2, "window": ["2s", "9s"],
                                "downtime": "250ms"}})
    a, b = compile_(opts), compile_(opts)
    assert a.fingerprint() == b.fingerprint()
    assert [e.__dict__ for e in a.events] == [e.__dict__ for e in b.events]
    c = compile_(opts, seed=12)
    assert c.fingerprint() != a.fingerprint()
    # explicit events don't move with the seed — only generator draws do
    explicit = [e for e in c.events if e.time_ns == 1_000 * MS]
    assert any(e.kind == "host_crash" and e.host == "h1" for e in explicit)


def test_schedule_masks_evolve():
    opts = _opts(events=[
        {"at": "1s", "kind": "host_crash", "host": "h2"},
        {"at": "2s", "kind": "host_reboot", "host": "h2"},
        {"at": "1s", "kind": "link_degrade", "src_node": 0, "dst_node": 1,
         "latency_mult": 4, "until": "3s"},
        {"at": "1s", "kind": "corrupt_burst", "host": "h0", "p": 0.25,
         "duration": "1s"},
        {"at": "1s", "kind": "host_degrade", "host": "h3",
         "bandwidth_div": 2, "duration": "500ms"},
    ])
    s = compile_(opts)
    s.advance(1_000 * MS)
    assert not s.host_alive[2]
    assert s.lat_mult[0, 1] == 4 and s.lat_mult[1, 0] == 4  # symmetric
    assert s.corrupt_p[0] == pytest.approx(0.25)
    assert s.bw_div[3] == 2
    s.advance(2_000 * MS)
    assert s.host_alive[2]
    assert s.corrupt_p[0] == 0.0
    assert s.bw_div[3] == 1
    assert s.lat_mult[0, 1] == 4
    s.advance(3_000 * MS)
    assert s.lat_mult[0, 1] == 1
    assert s.remaining == 0


def test_device_arrays_are_isolated_from_schedule_mutation():
    """jnp.asarray may zero-copy alias a numpy buffer on CPU; the
    schedule mutates its masks in place on the next advance(), so the
    uploaded FaultArrays MUST be private copies (this was an observed
    cross-process nondeterminism bug, fixed in faults/plane.py)."""
    opts = _opts(events=[
        {"at": "1s", "kind": "host_crash", "host": "h2"},
        {"at": "2s", "kind": "host_reboot", "host": "h2"}])
    s = compile_(opts)
    s.advance(1_000 * MS)
    arrays = s.device_arrays()
    before = np.asarray(arrays.host_alive).copy()
    s.advance(2_000 * MS)  # mutates s.host_alive in place
    assert np.array_equal(np.asarray(arrays.host_alive), before)


@pytest.mark.parametrize("bad,msg", [
    ([{"at": "1s", "kind": "meteor", "host": "h0"}], "unknown kind"),
    ([{"kind": "host_crash", "host": "h0"}], "missing required"),
    ([{"at": "1s", "kind": "host_crash", "host": "nope"}],
     "not a configured host"),
    ([{"at": "1s", "kind": "corrupt_burst", "host": "h0", "p": 1.5,
       "duration": "1s"}], "probability"),
    ([{"at": "1s", "kind": "corrupt_burst", "host": "h0", "p": 0.5}],
     "requires duration"),
    ([{"at": "1s", "kind": "link_degrade", "src_node": 0, "dst_node": 1,
       "latency_mult": 0}], "latency_mult"),
    ([{"at": "1s", "kind": "host_crash", "host": "h0", "bogus": 1}],
     "unknown field"),
    ([{"at": "0s", "kind": "host_crash", "host": "h0"}], "at > 0"),
])
def test_schedule_validation_errors(bad, msg):
    with pytest.raises(ConfigError, match=msg):
        compile_(_opts(events=bad))


def test_faults_config_block_parses():
    cfg = load_config_str("""
general: {stop_time: 5s, seed: 3}
network: {graph: {type: 1_gbit_switch}}
faults:
  watchdog: 30s
  device_retries: 2
  checkpoint: {interval: 2s, keep: 3}
  events:
    - {at: 1s, kind: host_crash, host: a}
hosts:
  a: {network_node_id: 0}
""")
    assert cfg.faults.watchdog == 30 * 1_000_000_000
    assert cfg.faults.device_retries == 2
    assert cfg.faults.checkpoint.interval == 2 * 1_000_000_000
    assert cfg.faults.checkpoint.keep == 3
    assert cfg.faults.any_injection()


def test_faults_config_validation():
    base = ("general: {stop_time: 5s}\n"
            "network: {graph: {type: 1_gbit_switch}}\n"
            "hosts: {a: {network_node_id: 0}}\n")
    with pytest.raises(ConfigError, match="watchdog"):
        load_config_str(base + "faults: {watchdog: 0s}")
    with pytest.raises(ConfigError, match="keep"):
        load_config_str(base + "faults: {checkpoint: {keep: 0}}")
    with pytest.raises(ConfigError, match="interval"):
        load_config_str(base + "faults: {checkpoint: {interval: 0s}}")


# -- checkpoints: atomic, checksummed, bitwise restore -------------------

def test_checkpoint_roundtrip_and_checksum_guard(tmp_path):
    path = str(tmp_path / "ck")
    meta = {"kind": "plane", "clock_ns": 5}
    arrays = {"a": np.arange(10, dtype=np.int32),
              "b": np.ones((3, 3), np.float32)}
    write_checkpoint(path, meta=meta, arrays=arrays)
    m2, a2 = load_checkpoint(path)
    assert m2 == meta
    assert np.array_equal(a2["a"], arrays["a"])
    # corrupt one payload byte -> refused loudly
    target = os.path.join(path, "arrays.npz")
    blob = bytearray(open(target, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(target, "wb").write(bytes(blob))
    with pytest.raises(CheckpointError, match="checksum mismatch"):
        load_checkpoint(path)


def test_checkpoint_overwrite_is_atomic(tmp_path):
    path = str(tmp_path / "ck")
    write_checkpoint(path, meta={"kind": "flow", "v": 1}, arrays={})
    write_checkpoint(path, meta={"kind": "flow", "v": 2}, arrays={})
    meta, _ = load_checkpoint(path)
    assert meta["v"] == 2
    assert not [e for e in os.listdir(tmp_path)
                if ".tmp-" in e or ".old-" in e]


def test_checkpoint_prune(tmp_path):
    for i in range(5):
        write_checkpoint(str(tmp_path / f"ckpt-{i:012d}"),
                         meta={"kind": "manager"}, arrays={})
    os.makedirs(tmp_path / "ckpt-x.tmp-123")
    prune_checkpoints(str(tmp_path), keep=2)
    left = sorted(os.listdir(tmp_path))
    assert left == ["ckpt-000000000003", "ckpt-000000000004"]


def test_plane_checkpoint_resume_bitwise(tmp_path):
    """Kill/resume parity: run 8 faulted windows; snapshot after 4;
    restore and run the rest; final state bitwise == uninterrupted."""
    state0, params = busy_world()
    key = jax.random.key(3)
    f_live = neutral_faults(N, N)._replace(
        host_alive=jnp.asarray(np.arange(N) != 1),
        corrupt_p=jnp.full((N,), 0.2, jnp.float32))

    def advance(state, metrics, windows, first_shift):
        step = jax.jit(lambda s, m, fa, sh: window_step(
            s, params, key, sh, jnp.int32(10 * MS), rr_enabled=True,
            faults=fa, metrics=m))
        shift = first_shift
        for _ in range(windows):
            state, _d, _n, metrics = step(state, metrics, f_live, shift)
            shift = jnp.int32(10 * MS)
        return state, metrics

    full_s, full_m = advance(state0, make_metrics(N), 8, jnp.int32(0))
    half_s, half_m = advance(state0, make_metrics(N), 4, jnp.int32(0))
    path = str(tmp_path / "mid")
    save_plane_checkpoint(
        path, state=half_s, clock_ns=4 * 10 * MS,
        rng_key_data=jax.random.key_data(key), faults=f_live,
        metrics=half_m, extra_arrays={"cursor": np.int64(4)})
    restored = load_plane_checkpoint(
        path, state_template=half_s, faults_template=f_live,
        metrics_template=half_m)
    assert int(restored["extra"]["cursor"]) == 4
    res_s, res_m = advance(restored["state"], restored["metrics"], 4,
                           jnp.int32(10 * MS))
    for la, lb in zip(jax.tree.leaves(full_s), jax.tree.leaves(res_s)):
        assert np.array_equal(np.asarray(la), np.asarray(lb))
    for la, lb in zip(jax.tree.leaves(full_m), jax.tree.leaves(res_m)):
        assert np.array_equal(np.asarray(la), np.asarray(lb))


# -- self-healing: retry + kernel fallback -------------------------------

def test_retry_transient_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of HBM")
        return "ok"

    assert retry_transient(flaky, attempts=3, backoff_s=0.001) == "ok"
    assert len(calls) == 3


def test_retry_transient_never_retries_real_bugs():
    calls = []

    def buggy():
        calls.append(1)
        raise ValueError("RESOURCE_EXHAUSTED looks transient but is not")

    with pytest.raises(ValueError):
        retry_transient(buggy, attempts=3, backoff_s=0.001)
    assert len(calls) == 1


def test_backoff_schedule_seed_pinned():
    """The exact delay floats for one (seed, what): the schedule is a
    pure function of its arguments, so these values are FROZEN — a
    drift here means retry timing silently changed for every run."""
    from shadow_tpu.faults.healing import backoff_schedule

    got = backoff_schedule(4, base_s=0.05, cap_s=2.0, jitter=0.5,
                           seed=0, what="device dispatch")
    assert got == backoff_schedule(4, base_s=0.05, cap_s=2.0,
                                   jitter=0.5, seed=0,
                                   what="device dispatch")
    assert len(got) == 4
    for k, d in enumerate(got):
        unjittered = min(2.0, 0.05 * 2.0 ** k)
        # jitter only SHAVES: (1 - 0.5) * base <= delay <= base
        assert unjittered * 0.5 <= d <= unjittered
    # pin the first draw to 12 decimal places: sha256("0|device
    # dispatch|0")[:8] mapped to [0,1) is a frozen constant
    assert round(got[0], 12) == round(0.045871920679567496, 12)


def test_backoff_schedule_seed_and_what_sensitivity():
    from shadow_tpu.faults.healing import backoff_schedule

    base = backoff_schedule(3, seed=0)
    assert backoff_schedule(3, seed=1) != base
    assert backoff_schedule(3, seed=0, what="checkpoint write") != base


def test_backoff_schedule_cap_and_zero_jitter():
    from shadow_tpu.faults.healing import backoff_schedule

    # jitter=0 is the pure capped exponential, exactly
    got = backoff_schedule(8, base_s=0.05, cap_s=0.4, jitter=0.0)
    assert got == (0.05, 0.1, 0.2, 0.4, 0.4, 0.4, 0.4, 0.4)
    assert backoff_schedule(0) == ()
    with pytest.raises(ValueError, match="attempts"):
        backoff_schedule(-1)
    with pytest.raises(ValueError, match="jitter"):
        backoff_schedule(2, jitter=1.5)


def test_retry_transient_sleeps_the_pinned_schedule(monkeypatch):
    """The sleeps retry_transient performs ARE the backoff_schedule
    floats, in order — no other randomness sneaks in."""
    from shadow_tpu.faults import healing

    slept = []
    monkeypatch.setattr(healing._walltime, "sleep", slept.append)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise RuntimeError("UNAVAILABLE: link reset")
        return "ok"

    assert retry_transient(flaky, attempts=3, backoff_s=0.05,
                           cap_s=2.0, jitter=0.5, seed=7) == "ok"
    want = healing.backoff_schedule(3, base_s=0.05, cap_s=2.0,
                                    jitter=0.5, seed=7)
    assert tuple(slept) == want[:3]


def test_retry_config_wires_cap_jitter_seed_to_transport():
    """faults.retry_cap / retry_jitter / the seed convention reach the
    transport attrs the Manager dispatches through."""
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str("""
general: {stop_time: 1s, seed: 9}
network: {graph: {type: 1_gbit_switch}}
experimental: {scheduler: serial, use_tpu_transport: true}
faults: {device_retries: 2, retry_backoff: 10ms, retry_cap: 3s,
         retry_jitter: 0.25}
hosts: {a: {network_node_id: 0, processes: []}}
""")
    mgr = Manager(cfg)
    tr = mgr.transport
    assert tr.retry_attempts == 2
    assert tr.retry_backoff_s == pytest.approx(0.01)
    assert tr.retry_cap_s == pytest.approx(3.0)
    assert tr.retry_jitter == pytest.approx(0.25)
    assert tr.retry_seed == 9  # faults.seed unset -> general.seed


def test_retry_cap_below_backoff_refused():
    with pytest.raises(ConfigError, match="retry_cap"):
        load_config_str("""
general: {stop_time: 1s}
network: {graph: {type: 1_gbit_switch}}
faults: {retry_backoff: 2s, retry_cap: 1s}
hosts: {a: {network_node_id: 0, processes: []}}
""")


def test_kernel_fallback_demotes_pallas_to_xla(caplog):
    import logging

    def build(kernel):
        if kernel == "pallas":
            def boom(*a):
                raise RuntimeError("no TPU: mosaic lowering failed")
            return boom
        return lambda x: x + 1

    fb = KernelFallback("pallas", build)
    with caplog.at_level(logging.ERROR, logger="shadow_tpu.faults"):
        assert fb(41) == 42
    assert fb.fell_back and fb.kernel == "xla"
    assert any("falling back" in r.message for r in caplog.records)
    # sticky: later calls go straight to xla
    assert fb(1) == 2


def test_kernel_fallback_disabled_reraises():
    def build(kernel):
        def boom(*a):
            raise RuntimeError("kaput")
        return boom

    fb = KernelFallback("pallas", build, enabled=False)
    with pytest.raises(RuntimeError, match="kaput"):
        fb()


# -- the round watchdog ---------------------------------------------------

def test_watchdog_converts_wedge_into_structured_error():
    """A round wedged on a live native process: the watchdog fires
    within the timeout, collects blame, SIGKILLs the wedged pid, the
    round completes, and the strike carries the blame."""
    dummy = subprocess.Popen(["sleep", "300"])
    try:
        def collect(_round_start):
            return [HostBlame("hostA", ["hostA.wedge.0"], [dummy.pid],
                              [dummy.pid])]

        wd = RoundWatchdog(0.3, collect)
        t0 = _walltime.monotonic()
        with wd.guard(round_start_ns=123):
            # the "round": blocked until the wedged process dies —
            # exactly what a worker stuck in recv_from_shim does
            dummy.wait(timeout=30)
        assert wd.strike is not None
        elapsed = _walltime.monotonic() - t0
        assert elapsed < 10  # fired within the timeout, not the 300s
        err = wd.strike
        assert isinstance(err, WatchdogError)
        assert err.killed == [dummy.pid]
        assert "hostA" in str(err)
        assert "wedge" in str(err)
    finally:
        if dummy.poll() is None:
            dummy.kill()
        dummy.wait()


def test_watchdog_disarms_on_healthy_round():
    fired = []
    wd = RoundWatchdog(0.2, lambda t: fired.append(t) or [])
    with wd.guard(round_start_ns=1):
        pass
    _walltime.sleep(0.35)
    assert not fired and wd.strike is None


def test_watchdog_timeout_must_be_positive():
    for bad in (0, -1.5):
        with pytest.raises(ValueError, match="positive"):
            RoundWatchdog(bad, lambda t: [])


def test_kill_blamed_skips_dead_pids_kills_live_ones():
    """A blamed pid that already exited (raced its own death) is
    skipped silently; the live wedged one is SIGKILLed and reported."""
    from shadow_tpu.faults.watchdog import kill_blamed

    dead = subprocess.Popen(["true"])
    dead.wait()  # reaped: its pid no longer resolves
    live = subprocess.Popen(["sleep", "300"])
    try:
        blame = [HostBlame("hostA", ["hostA.gone.0"], [dead.pid],
                           []),
                 HostBlame("hostB", ["hostB.wedge.0"], [live.pid],
                           [live.pid])]
        killed = kill_blamed(blame)
        assert killed == [live.pid]
        live.wait(timeout=10)  # really dead, not just signalled
    finally:
        if live.poll() is None:
            live.kill()
            live.wait()


def test_watchdog_blame_collection_failure_still_strikes():
    """A collect_blame that itself dies must not lose the strike: the
    round still fails structured, attributed as 'no live blame'."""

    def broken(_round_start):
        raise RuntimeError("process table scan exploded")

    wd = RoundWatchdog(0.15, broken)
    wd.arm(round_start_ns=77)
    deadline = _walltime.monotonic() + 10
    while wd.strike is None and _walltime.monotonic() < deadline:
        _walltime.sleep(0.02)
    assert isinstance(wd.strike, WatchdogError)
    assert wd.strike.killed == []
    assert "no live blame" in str(wd.strike)
    assert wd.strike.round_start_ns == 77


def _manager_watchdog_sim(monkeypatch):
    """A Manager round wedged by an app that spins until a real native
    process dies; a stub managed-process entry routes the watchdog's
    blame (and SIGKILL) at that pid."""
    from shadow_tpu import apps as app_registry
    from shadow_tpu.core.manager import Manager

    dummy = subprocess.Popen(["sleep", "300"])

    def wedge(api):
        # poll()/wait() reap the child; a bare os.kill(pid, 0) probe
        # would see the SIGKILLed zombie as alive forever
        while dummy.poll() is None:
            _walltime.sleep(0.02)  # wall block, like a wedged shim read
        return 0
        yield  # pragma: no cover - makes this a generator function

    monkeypatch.setitem(app_registry.APP_REGISTRY, "wedge-app", wedge)
    cfg = load_config_str("""
general: {stop_time: 3s, seed: 5}
network: {graph: {type: 1_gbit_switch}}
experimental: {scheduler: serial}
faults: {watchdog: 1s}
hosts:
  a:
    network_node_id: 0
    processes:
    - {path: wedge-app, start_time: 1s, expected_final_state: running}
""")
    mgr = Manager(cfg)

    class StubProc:
        is_alive = True
        proc = dummy

    mgr._respawn_by_host["a"].append(("a.wedge.native", None,
                                     {"proc": StubProc()}, None))
    return mgr, dummy


def test_manager_watchdog_end_to_end(monkeypatch):
    mgr, dummy = _manager_watchdog_sim(monkeypatch)
    try:
        with pytest.raises(WatchdogError) as ei:
            mgr.run()
        assert "a.wedge.native" in str(ei.value)
        assert dummy.pid in ei.value.killed
        assert dummy.poll() is not None  # the wedged native was killed
    finally:
        if dummy.poll() is None:
            dummy.kill()
        dummy.wait()


# -- Manager-level fault injection ----------------------------------------

FAULT_SIM = """
general: {{stop_time: 5s, seed: 11}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{scheduler: serial}}
faults:
  events:
    - {{at: 3s, kind: host_crash, host: server}}
    - {{at: 4s, kind: host_reboot, host: server}}
    - {{at: 1500ms, kind: corrupt_burst, host: client, p: 1.0,
       duration: 4s}}
{extra}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
  client:
    network_node_id: 0
    processes:
    - {{path: udp-client, args: ["server", "9000", "5", "50"],
       start_time: 2s, expected_final_state: running}}
"""


def _run_fault_sim(extra=""):
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(FAULT_SIM.format(extra=extra))
    mgr = Manager(cfg)
    stats = mgr.run()
    return mgr, stats


def test_manager_fault_sim_injects_and_recovers():
    mgr, stats = _run_fault_sim()
    hosts = mgr.host_stats()
    # the corruption burst (p=1.0 from 1.5s) eats the client's pings:
    # bucketed as FAULT drops — in the tracker counters AND the final
    # SimStats — never in the wire-loss packets_dropped
    assert hosts["client"]["packets_dropped_fault"] > 0
    assert stats.packets_dropped_fault > 0
    assert stats.packets_dropped == 0  # no wire loss on this graph
    # the server crashed at 3s and its respawn left it RUNNING when the
    # expected-final-state check ran (before teardown): both processes
    # met their expectations, so the fault round-trip recovered fully
    assert stats.process_failures == []


def test_manager_fault_sim_deterministic():
    _m1, s1 = _run_fault_sim()
    _m2, s2 = _run_fault_sim()
    a, b = s1.as_dict(), s2.as_dict()
    a.pop("wall_seconds"), b.pop("wall_seconds")
    assert a == b
    assert _m1.host_stats() == _m2.host_stats()
    assert _m1.fault_schedule.fingerprint() == \
        _m2.fault_schedule.fingerprint()


def test_manager_periodic_and_emergency_checkpoints(tmp_path):
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(FAULT_SIM.format(
        extra=f"  checkpoint: {{interval: 1s, directory: "
              f"{tmp_path / 'ck'}, keep: 2}}"))
    mgr = Manager(cfg)
    mgr.run()
    names = sorted(os.listdir(tmp_path / "ck"))
    assert names and all(n.startswith("ckpt-") for n in names)
    assert len(names) <= 2  # pruned to keep
    meta, _arrays = load_checkpoint(str(tmp_path / "ck" / names[-1]))
    assert meta["kind"] == "manager" and meta["resumable"] is False
    assert "server" in meta["hosts"]

    # crash path: wedge the scheduler to raise mid-run -> emergency
    cfg2 = load_config_str(FAULT_SIM.format(
        extra=f"  checkpoint: {{directory: {tmp_path / 'ck2'}}}"))
    mgr2 = Manager(cfg2)
    orig = mgr2.scheduler.run_round
    calls = []

    def boom(active, end):
        if calls:
            raise RuntimeError("injected crash")
        calls.append(1)
        return orig(active, end)

    mgr2.scheduler.run_round = boom
    with pytest.raises(RuntimeError, match="injected crash"):
        mgr2.run()
    meta, _ = load_checkpoint(str(tmp_path / "ck2" / "emergency"))
    assert meta["reason"] == "emergency"


def test_round_loop_resume_refused():
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(FAULT_SIM.format(extra=""))
    mgr = Manager(cfg)
    mgr.resume_from = "/nonexistent/ckpt"
    with pytest.raises(ConfigError, match="flow-engine"):
        mgr.run()
