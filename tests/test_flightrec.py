"""Histogram + flight-recorder correctness: the presence parity matrix
(sim state, metrics, AND guards bitwise-unchanged across rr x aqm x
no_loss, plus faults-on and workload-on worlds), deterministic sampling,
trace-ring overwrite/growth semantics, percentile math, the harvester's
2-D histogram emission, transport histograms, config parsing, Manager
warnings, and double-run byte-stability of heartbeats/hops/trace.json
with sampling on (docs/observability.md "Distributions and the flight
recorder")."""

import json
import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.telemetry import (TelemetryHarvester,  # noqa: E402
                                  make_flightrec, make_histograms,
                                  make_metrics)
from shadow_tpu.telemetry import flightrec as frmod  # noqa: E402
from shadow_tpu.telemetry import histo  # noqa: E402
from shadow_tpu.telemetry.flightrec import FlightRecorder  # noqa: E402
from shadow_tpu.tpu import (ingest, ingest_rows, make_params,  # noqa: E402
                            make_state)
from shadow_tpu.tpu.plane import window_step  # noqa: E402

MS = 1_000_000
N = 8


def busy_world(rr_mix=True):
    """The telemetry-test busy world: starved buckets, real loss, mixed
    qdiscs (tests/test_telemetry.py) — every histogram/hop path gets
    exercised."""
    rng = np.random.default_rng(7)
    lat = rng.integers(1 * MS, 20 * MS, size=(N, N)).astype(np.int32)
    loss = np.full((N, N), 0.3, np.float32)
    qrr = (np.arange(N) % 2 == 0) if rr_mix else np.zeros(N, bool)
    params = make_params(lat, loss, np.full((N,), 80_000, np.int64),
                         qdisc_rr=qrr, down_bw_bps=np.full((N,), 400_000))
    state = make_state(N, egress_cap=8, ingress_cap=8, params=params,
                       initial_tokens=np.asarray(params.tb_cap))
    b = 48
    state = ingest(
        state,
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(100, 1500, b), jnp.int32),
        jnp.asarray(rng.integers(0, 6, b), jnp.int32),
        jnp.arange(b, dtype=jnp.int32),
        jnp.asarray(rng.integers(0, 3, b) == 0),
        sock=jnp.asarray(rng.integers(0, 40, b), jnp.int32),
    )
    return state, params


def run_windows(state, params, *, windows=4, metrics=None, guards=None,
                hist=None, fr=None, faults=None, **kw):
    key = jax.random.key(3)

    @jax.jit
    def step(state, metrics, guards, hist, fr, shift):
        out = window_step(state, params, key, shift, jnp.int32(10 * MS),
                          metrics=metrics, guards=guards, hist=hist,
                          flightrec=fr, faults=faults, **kw)
        state, delivered, nxt = out[:3]
        rest = list(out[3:])
        if metrics is not None:
            metrics = rest.pop(0)
        if guards is not None:
            guards = rest.pop(0)
        if hist is not None:
            hist = rest.pop(0)
        if fr is not None:
            fr = rest.pop(0)
        return state, delivered, nxt, metrics, guards, hist, fr

    shift = jnp.int32(0)
    out = []
    for _ in range(windows):
        state, delivered, nxt, metrics, guards, hist, fr = step(
            state, metrics, guards, hist, fr, shift)
        out.append((state, delivered, nxt))
        shift = jnp.int32(10 * MS)
    return out, metrics, guards, hist, fr


def assert_tree_equal(a, b, ctx=""):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), ctx


# -- bucket/percentile units ----------------------------------------------


def test_bucket_index_is_exact_integer_log2():
    vals = jnp.asarray([0, 1, 2, 3, 4, 7, 8, 1023, 1024,
                        2**24, 2**24 + 1, 2**30, 2**31 - 1], jnp.int32)
    got = np.asarray(histo.bucket_index(vals)).tolist()
    want = [0, 0, 1, 1, 2, 2, 3, 9, 10, 24, 24, 30, 30]
    assert got == want
    # negative / zero observations land in bucket 0, never wrap
    assert np.asarray(histo.bucket_index(
        jnp.asarray([-5, -(2**31) + 1], jnp.int32))).tolist() == [0, 0]


def test_percentiles_upper_bounds():
    counts = np.zeros(histo.HIST_BUCKETS, np.int64)
    counts[10] = 90  # 90 obs in [1024, 2048)
    counts[20] = 10  # 10 obs in [2^20, 2^21)
    assert histo.percentile(counts, 0.5) == 2048
    assert histo.percentile(counts, 0.9) == 2048
    assert histo.percentile(counts, 0.99) == 1 << 21
    assert histo.percentiles(counts) == {
        "p50": 2048, "p90": 2048, "p99": 1 << 21, "p999": 1 << 21}
    assert histo.percentile(np.zeros(32, np.int64), 0.99) == 0


def test_accum_helpers_count_correctly():
    h = jnp.zeros((2, histo.HIST_BUCKETS), jnp.int32)
    bucket = jnp.asarray([[0, 3, 3], [1, 1, 1]], jnp.int32)
    mask = jnp.asarray([[True, True, False], [True, True, True]])
    rowwise = np.asarray(histo.accum_rows(h, bucket, mask))
    assert rowwise[0, 0] == 1 and rowwise[0, 3] == 1
    assert rowwise[1, 1] == 3
    rows = jnp.asarray([[1, 1, 0], [0, 0, 0]], jnp.int32)
    scat = np.asarray(histo.accum_scatter(h, rows, bucket, mask))
    assert scat[1, 0] == 1 and scat[1, 3] == 1  # attributed to row 1
    assert scat[0, 1] == 3
    depth = np.asarray(histo.accum_depth(h, jnp.asarray([5, 0],
                                                        jnp.int32)))
    assert depth[0, 2] == 1 and depth[1, 0] == 1


# -- sampling determinism -------------------------------------------------


def test_sampling_mask_deterministic_and_shape_independent():
    fr = make_flightrec(11, sample_every=4, ring=64)
    src = jnp.arange(64, dtype=jnp.int32) % 8
    seq = jnp.arange(64, dtype=jnp.int32)
    m1 = np.asarray(frmod.sample_mask(fr, src, seq))
    m2 = np.asarray(frmod.sample_mask(fr, src.reshape(8, 8),
                                      seq.reshape(8, 8))).reshape(-1)
    assert np.array_equal(m1, m2)  # independent of batch shape
    # a subset sees the same verdicts: pure function of (seed, src, seq)
    m3 = np.asarray(frmod.sample_mask(fr, src[10:20], seq[10:20]))
    assert np.array_equal(m1[10:20], m3)
    # ~1/K of packets tagged (loose: it's a hash)
    assert 4 <= m1.sum() <= 32
    # a different seed samples a different set
    fr2 = make_flightrec(12, sample_every=4, ring=64)
    assert not np.array_equal(
        m1, np.asarray(frmod.sample_mask(fr2, src, seq)))


def test_make_flightrec_validates():
    with pytest.raises(ValueError):
        make_flightrec(0, sample_every=0)
    with pytest.raises(ValueError):
        make_flightrec(0, ring=0)
    with pytest.raises(ValueError):
        frmod.grow_ring(make_flightrec(0, ring=8), 8)


# -- trace-ring semantics -------------------------------------------------


def _mk_events(n, base, valid=None):
    return (jnp.full((n,), frmod.HOP_ROUTED, jnp.int32),
            jnp.arange(n, dtype=jnp.int32),
            jnp.arange(base, base + n, dtype=jnp.int32),
            jnp.zeros((n,), jnp.int32),
            jnp.full((n,), 7, jnp.int32),
            jnp.ones((n,), bool) if valid is None else valid)


def test_ring_overwrite_is_counted_loudly(caplog):
    import logging

    rec = make_flightrec(1, sample_every=1, ring=16)
    rec = frmod.record_events(rec, *_mk_events(24, 0))
    rcd = FlightRecorder(window_ns=1)
    with caplog.at_level(logging.ERROR, logger="shadow_tpu.telemetry"):
        rcd.tick(rec)
        rcd.drain()
    assert rcd.recorded == 16 and rcd.overwritten == 8
    assert [h["seq"] for h in rcd.hops] == list(range(8, 24))
    assert any("overflowed" in r.getMessage() for r in caplog.records)
    assert rcd.want_growth()


def test_ring_growth_preserves_entries_and_continues():
    rec = make_flightrec(1, sample_every=1, ring=16)
    rec = frmod.record_events(rec, *_mk_events(10, 0))
    rcd = FlightRecorder(window_ns=1)
    rcd.tick(rec)
    rec = frmod.grow_ring(rec, 64)
    rec = frmod.record_events(rec, *_mk_events(10, 100))
    rcd.tick(rec)
    rcd.finalize()
    assert rcd.overwritten == 0
    assert [h["seq"] for h in rcd.hops] == \
        list(range(0, 10)) + list(range(100, 110))


def test_ring_wraps_across_windows():
    rec = make_flightrec(1, sample_every=1, ring=8)
    for w in range(3):
        rec = frmod.record_events(rec, *_mk_events(5, 10 * w))
        rec = frmod.advance_window(rec)
    rcd = FlightRecorder(window_ns=100)
    rcd.tick(rec)
    rcd.finalize()
    assert rcd.overwritten == 7
    assert [h["seq"] for h in rcd.hops] == [12, 13, 14, 20, 21, 22,
                                            23, 24]
    # t_ns decodes from (win, t_rel) on the driver's fixed cadence
    assert [h["t_ns"] for h in rcd.hops] == [107, 107, 107, 207, 207,
                                             207, 207, 207]


def test_masked_and_empty_windows_are_noops():
    rec = make_flightrec(1, sample_every=1, ring=8)
    k, s, q, d, t, _ = _mk_events(5, 0)
    out = frmod.record_events(rec, k, s, q, d, t,
                              jnp.zeros((5,), bool))
    assert int(out.cursor) == 0
    assert_tree_equal(out.ev_seq, rec.ev_seq)
    # partial mask keeps only masked events, in layout order
    out = frmod.record_events(
        rec, k, s, q, d, t,
        jnp.asarray([True, False, True, False, True]))
    rcd = FlightRecorder(window_ns=1)
    rcd.tick(out)
    rcd.finalize()
    assert [h["seq"] for h in rcd.hops] == [0, 2, 4]


# -- presence parity matrix ----------------------------------------------


@pytest.mark.parametrize("rr_enabled", [False, True])
@pytest.mark.parametrize("router_aqm", [False, True])
@pytest.mark.parametrize("no_loss", [False, True])
def test_trace_presence_bitwise_invisible(rr_enabled, router_aqm,
                                          no_loss):
    """hist + flightrec threaded must leave sim state, delivered sets,
    next-event scalars, metrics, AND guards accumulators bitwise
    unchanged across the qdisc matrix."""
    from shadow_tpu.guards import make_guards

    state, params = busy_world(rr_mix=rr_enabled)
    kw = dict(rr_enabled=rr_enabled, router_aqm=router_aqm,
              no_loss=no_loss)
    with_t, m_a, g_a, hist, fr = run_windows(
        state, params, metrics=make_metrics(N), guards=make_guards(N),
        hist=make_histograms(N),
        fr=make_flightrec(5, sample_every=2, ring=256), **kw)
    without, m_b, g_b, _h, _f = run_windows(
        state, params, metrics=make_metrics(N), guards=make_guards(N),
        **kw)
    for w, ((sa, da, na), (sb, db, nb)) in enumerate(zip(with_t,
                                                         without)):
        assert_tree_equal(sa, sb, (kw, w))
        for k in da:
            assert np.array_equal(np.asarray(da[k]),
                                  np.asarray(db[k])), (kw, w, k)
        assert int(na) == int(nb), (kw, w)
    assert_tree_equal(m_a, m_b, kw)  # metrics untouched by hist/fr
    assert_tree_equal(g_a, g_b, kw)  # guards untouched too
    from shadow_tpu.guards import summarize

    assert summarize(g_a)["clean"]
    # and the observability actually observed something
    assert int(np.asarray(hist.hist_qdepth).sum()) > 0
    assert int(fr.cursor) > 0


def test_trace_presence_invisible_with_faults_on():
    from shadow_tpu.faults.plane import FaultArrays

    state, params = busy_world()
    alive = np.ones(N, bool)
    alive[3] = False  # an active crash: the fault-drop hop path runs
    faults = FaultArrays(
        host_alive=jnp.asarray(alive),
        link_up=jnp.ones((N,), bool),
        lat_mult=jnp.full((N, N), 2, jnp.int32),
        bw_div=jnp.ones((N,), jnp.int32),
        corrupt_p=jnp.full((N,), 0.2, jnp.float32),
    )
    with_t, m_a, _g, hist, fr = run_windows(
        state, params, metrics=make_metrics(N),
        hist=make_histograms(N),
        fr=make_flightrec(5, sample_every=1, ring=1024), faults=faults)
    without, m_b, _g2, _h, _f = run_windows(
        state, params, metrics=make_metrics(N), faults=faults)
    for (sa, da, na), (sb, db, nb) in zip(with_t, without):
        assert_tree_equal(sa, sb)
    assert_tree_equal(m_a, m_b)
    assert int(np.asarray(m_a.drop_fault).sum()) > 0
    rcd = FlightRecorder(window_ns=10 * MS)
    rcd.tick(fr)
    rcd.finalize()
    kinds = {h["kind"] for h in rcd.hops}
    assert "drop_fault" in kinds  # injected losses carry their taxonomy
    # destination-blocked drops (the crashed host ate the route) record
    # a hop too — a sampled packet never silently vanishes while
    # metrics.drop_fault counts it
    assert any(h["kind"] == "drop_fault" and h["dst"] == 3
               for h in rcd.hops)


def test_trace_presence_invisible_in_workload_world():
    from shadow_tpu.workloads import load_scenario_file, runner

    spec = load_scenario_file(os.path.join(
        os.path.dirname(__file__), "..", "scenarios", "incast.yaml"))
    plain = runner.run_scenario(spec, histograms=False)
    traced = runner.run_scenario(spec, histograms=True, sample_every=4)
    assert traced["canonical_digest"] == plain["canonical_digest"]
    assert traced["latency"]["delivery_ns"]["p99"] > 0
    assert traced["flight_recorder"]["recorded_hops"] > 0
    assert traced["flight_recorder"]["overwritten"] == 0


def test_hops_pair_routed_with_delivered():
    state, params = busy_world()
    _runs, _m, _g, _h, fr = run_windows(
        state, params, windows=6,
        fr=make_flightrec(5, sample_every=1, ring=4096))
    rcd = FlightRecorder(window_ns=10 * MS)
    rcd.tick(fr)
    rcd.finalize()
    flows = frmod.hop_flows(rcd.hops)
    paired = [
        g for g in flows.values()
        if {"routed", "delivered"} <= {h["kind"] for h in g}]
    assert paired, "no packet recorded both ends of its flight"
    for g in paired:
        routed = next(h for h in g if h["kind"] == "routed")
        delivered = next(h for h in g if h["kind"] == "delivered")
        assert delivered["t_ns"] >= routed["t_ns"]
        assert routed["dst"] == delivered["dst"]


# -- ingest_rows hooks ----------------------------------------------------


def test_ingest_rows_records_ingest_hops_and_depth():
    # EMPTY rings: every appended entry is accepted, so every sampled
    # one records an ingest hop (the overflow case is the next test)
    _state, params = busy_world()
    state = make_state(N, egress_cap=8, ingress_cap=8, params=params,
                       initial_tokens=np.asarray(params.tb_cap))
    K = 4
    dst = jnp.zeros((N, K), jnp.int32)
    nb = jnp.full((N, K), 100, jnp.int32)
    seq = jnp.arange(N * K, dtype=jnp.int32).reshape(N, K)
    valid = jnp.ones((N, K), bool)
    ctrl = jnp.zeros((N, K), bool)
    out = ingest_rows(state, dst, nb, seq, seq, ctrl, valid,
                      hist=make_histograms(N),
                      flightrec=make_flightrec(5, sample_every=1,
                                               ring=256))
    st2, hist, fr = out
    ref = ingest_rows(state, dst, nb, seq, seq, ctrl, valid)
    assert_tree_equal(st2, ref)
    assert int(np.asarray(hist.hist_qdepth).sum()) == N
    rcd = FlightRecorder(window_ns=10 * MS)
    rcd.tick(fr)
    rcd.finalize()
    assert rcd.recorded == N * K
    assert all(h["kind"] == "ingest" for h in rcd.hops)


def test_ingest_rows_overflow_drops_record_no_phantom_hops():
    """Overflow-dropped batch entries never entered the ring, so they
    record NO ingest hop — a phantom hop would read as 'queued'."""
    state, params = busy_world()  # 48 seeded packets over 8 hosts, CE=8
    K = 12
    dst = jnp.zeros((N, K), jnp.int32)
    nb = jnp.full((N, K), 100, jnp.int32)
    seq = (jnp.arange(N * K, dtype=jnp.int32).reshape(N, K) + 1000)
    valid = jnp.ones((N, K), bool)
    ctrl = jnp.zeros((N, K), bool)
    from shadow_tpu.telemetry import make_metrics as _mm

    st2, metrics, fr = ingest_rows(
        state, dst, nb, seq, seq, ctrl, valid, metrics=_mm(N),
        flightrec=make_flightrec(5, sample_every=1, ring=1024))
    dropped = int(np.asarray(metrics.drop_ring_full).sum())
    assert dropped > 0  # the batch really overflowed
    accepted = N * K - dropped
    rcd = FlightRecorder(window_ns=10 * MS)
    rcd.tick(fr)
    rcd.finalize()
    assert rcd.recorded == accepted
    # and the hops are exactly the per-row accepted PREFIXES (the
    # merge keeps new entries in column order after the existing ones)
    occ = np.asarray(state.eg_valid.sum(axis=1))
    want = {(r, int(seq[r, c])) for r in range(N)
            for c in range(max(0, min(K, 8 - occ[r])))}
    got = {(h["src"], h["seq"]) for h in rcd.hops}
    assert got == want


# -- harvester emission of 2-D histogram leaves ---------------------------


def test_harvester_emits_histograms_per_host_and_fleet():
    def hist_arrays(scale):
        h = np.zeros((2, histo.HIST_BUCKETS), np.int32)
        h[0, 3] = 2 * scale
        h[1, 5] = 1 * scale
        return {"hist_delivery_ns": h}

    h = TelemetryHarvester(interval_ns=MS, sink=None,
                           host_names=["a", "b"])
    h.tick(1 * MS, device=hist_arrays(1))
    h.tick(2 * MS, device=hist_arrays(2))
    h.finalize()
    sims = [r for r in h.heartbeats if r["type"] == "sim"]
    hosts = [r for r in h.heartbeats if r["type"] == "host"]
    assert sims[0]["hist"]["hist_delivery_ns"][3] == 2
    assert sims[0]["hist"]["hist_delivery_ns"][5] == 1
    # cumulative totals, delta-unwrapped like every modular counter
    assert sims[1]["hist"]["hist_delivery_ns"][3] == 4
    a0 = next(r for r in hosts if r["host"] == "a")
    assert a0["hist"]["hist_delivery_ns"][3] == 2
    from shadow_tpu.telemetry import export

    summary = export.summarize(h.heartbeats)
    assert summary["percentiles"]["delivery_ns"]["p50"] == 16
    per_host = export.host_percentiles(h.heartbeats)
    assert per_host["b"]["delivery_ns"]["p99"] == 64


def test_perfetto_trace_gains_percentile_tracks_and_flows(tmp_path):
    def hist_arrays(scale):
        h = np.zeros((2, histo.HIST_BUCKETS), np.int32)
        h[0, 3] = 2 * scale
        return {"hist_delivery_ns": h,
                "pkts_out": np.asarray([1, 2], np.int32)}

    h = TelemetryHarvester(interval_ns=MS, sink=None,
                           host_names=["a", "b"])
    h.tick(1 * MS, device=hist_arrays(1))
    h.tick(2 * MS, device=hist_arrays(3))
    h.finalize()
    hops = [
        {"kind": "routed", "src": 0, "seq": 5, "dst": 1, "win": 0,
         "t_ns": 1000},
        {"kind": "delivered", "src": 0, "seq": 5, "dst": 1, "win": 0,
         "t_ns": 9000},
    ]
    from shadow_tpu.telemetry import export

    path = str(tmp_path / "trace.json")
    info = export.write_perfetto_trace(h.heartbeats, path, hops=hops)
    assert info["flows_plotted"] == 1
    trace = json.load(open(path))
    events = trace["traceEvents"]
    pct = [e for e in events if e["ph"] == "C"
           and e["name"] == "delivery_ns"]
    assert len(pct) == 2 and pct[0]["args"]["p99"] == 16
    phases = {e["ph"] for e in events}
    assert {"s", "f", "X"} <= phases  # a cross-host flow span loads
    s_ev = next(e for e in events if e["ph"] == "s")
    f_ev = next(e for e in events if e["ph"] == "f")
    assert s_ev["id"] == f_ev["id"]
    assert s_ev["pid"] == 1 and f_ev["pid"] == 2  # src row -> dst row
    assert trace["otherData"]["flows_plotted"] == 1


def test_flow_cap_is_loud(tmp_path):
    hops = []
    for i in range(4):
        hops.append({"kind": "routed", "src": 0, "seq": i, "dst": 1,
                     "win": 0, "t_ns": 1000 + i})
    # ingest-only groups are never plottable and must not count as
    # "dropped by the cap" wherever they fall in iteration order
    hops.append({"kind": "ingest", "src": 0, "seq": 99, "dst": 1,
                 "win": 0, "t_ns": 1})
    hops.append({"kind": "ingest", "src": 9, "seq": 0, "dst": 1,
                 "win": 0, "t_ns": 1})
    from shadow_tpu.telemetry import export

    path = str(tmp_path / "trace.json")
    info = export.write_perfetto_trace([], path, hops=hops, max_flows=2)
    assert info["flows_plotted"] == 2
    assert info["flows_dropped_by_cap"] == 2
    assert json.load(open(path))["otherData"]["flows_dropped_by_cap"] == 2
    info = export.write_perfetto_trace([], path, hops=hops, max_flows=8)
    assert info["flows_plotted"] == 4
    assert info["flows_dropped_by_cap"] == 0


# -- transport histograms -------------------------------------------------


class _StubHost:
    def __init__(self, hid):
        self.host_id = hid
        self.node_id = 0
        self.delivered = []

    def push_packet_event(self, packet, t, src_id, seq):
        self.delivered.append((packet, t, src_id, seq))


class _StubRouting:
    latency_ns = np.asarray([[1_000_000]], np.int64)

    def node_index(self, node_id):
        return 0


def test_transport_histograms_accumulate_and_stay_invisible():
    from shadow_tpu.tpu.transport import DeviceTransport

    def run(enable):
        hosts = [_StubHost(1), _StubHost(2)]
        tr = DeviceTransport(hosts, _StubRouting(), {}, mode="sync",
                             egress_cap=8, ingress_cap=8)
        if enable:
            tr.enable_histograms()
        tr.release(0, 1000)
        tr.capture(hosts[0], hosts[1], "pkt-a", now_ns=0, seq=1,
                   round_end_ns=1000, deliver_ns=1_000_000)
        tr.finish_round(0, 1000)
        tr.release(1000, 2_000_001)
        return hosts, tr

    hosts_on, tr_on = run(True)
    hosts_off, _tr_off = run(False)
    assert [h.delivered for h in hosts_on] == \
        [h.delivered for h in hosts_off]  # bitwise-invisible delivery
    arrs = tr_on.histogram_arrays()
    assert set(arrs) == {"hist_delivery_ns", "hist_qdepth"}
    lat = np.asarray(arrs["hist_delivery_ns"])
    # one packet, ~1ms latency -> bucket 19 ([2^19, 2^20) ns), dest row
    assert lat[1, 19] == 1 and lat.sum() == 1
    assert np.asarray(arrs["hist_qdepth"]).sum() > 0
    assert _tr_off.histogram_arrays() == {}


# -- config + manager -----------------------------------------------------


BASE_CFG = ("general:\n  stop_time: 1s\n"
            "network:\n  graph:\n    type: 1_gbit_switch\n"
            "hosts:\n  a:\n    network_node_id: 0\n")


def test_flight_recorder_config_block_parses():
    from shadow_tpu.core.config import ConfigError, load_config_str

    cfg = load_config_str(BASE_CFG)
    assert not cfg.telemetry.histograms
    assert not cfg.telemetry.flight_recorder.enabled
    assert cfg.telemetry.flight_recorder.sample_every == 64
    cfg = load_config_str(
        BASE_CFG + "telemetry:\n  enabled: true\n  histograms: true\n"
                   "  flight_recorder:\n    enabled: true\n"
                   "    sample_every: 16\n    ring: 512\n")
    assert cfg.telemetry.histograms
    assert cfg.telemetry.flight_recorder.enabled
    assert cfg.telemetry.flight_recorder.sample_every == 16
    assert cfg.telemetry.flight_recorder.ring == 512
    # YAML 1.1 bare off/on coerce like the workload block
    cfg = load_config_str(
        BASE_CFG + "telemetry:\n  flight_recorder: off\n")
    assert not cfg.telemetry.flight_recorder.enabled
    cfg = load_config_str(
        BASE_CFG + "telemetry:\n  flight_recorder: on\n")
    assert cfg.telemetry.flight_recorder.enabled
    with pytest.raises(ConfigError):
        load_config_str(
            BASE_CFG + "telemetry:\n  flight_recorder:\n"
                       "    sample_every: 0\n")
    with pytest.raises(ConfigError):
        load_config_str(
            BASE_CFG + "telemetry:\n  flight_recorder:\n    ring: 0\n")
    with pytest.raises(ConfigError):
        load_config_str(
            BASE_CFG + "telemetry:\n  flight_recorder:\n    bogus: 1\n")


def test_manager_warns_on_flight_recorder(caplog):
    import logging

    from shadow_tpu.core.config import ConfigError, load_config_str
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(
        BASE_CFG + "telemetry:\n  enabled: true\n"
                   "  flight_recorder: on\n")
    with caplog.at_level(logging.WARNING, logger="shadow_tpu.manager"):
        Manager(cfg)
    assert any("flight_recorder" in r.getMessage()
               for r in caplog.records)
    cfg = load_config_str(
        BASE_CFG + "strict: true\n"
                   "telemetry:\n  enabled: true\n"
                   "  flight_recorder: on\n")
    with pytest.raises(ConfigError):
        Manager(cfg)


def test_manager_warns_on_histograms_without_transport(caplog):
    import logging

    from shadow_tpu.core.config import load_config_str
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(
        BASE_CFG + "telemetry:\n  enabled: true\n  histograms: true\n")
    mgr = Manager(cfg)
    with caplog.at_level(logging.WARNING, logger="shadow_tpu.manager"):
        mgr.run()
    assert any("histograms" in r.getMessage() for r in caplog.records)


# -- double-run byte-stability through a real driver ----------------------


def _chaos(argv):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import chaos_smoke

    return chaos_smoke.main(argv)


@pytest.mark.slow  # two chaos_smoke CLI runs (~11s); stays GATING in
# CI's tier-1-overflow unfiltered step
def test_chaos_smoke_telemetry_byte_stable(tmp_path, capsys):
    """The chaos driver with --telemetry + --sample-every: two
    identical runs produce byte-identical heartbeats, hops, and
    trace.json; the JSON reports recorded hops and latency
    percentiles; the digest equals a telemetry-off run's."""
    outs = []
    for d in ("t1", "t2"):
        rc = _chaos(["--hosts", "16", "--windows", "6",
                     "--harvest-every", "3",
                     "--telemetry", str(tmp_path / d),
                     "--sample-every", "2", "--guards", "warn"])
        assert rc == 0
        outs.append(json.loads(
            capsys.readouterr().out.strip().splitlines()[-1]))
    for name in ("heartbeats.jsonl", "hops.jsonl", "trace.json"):
        a = (tmp_path / "t1" / name).read_bytes()
        b = (tmp_path / "t2" / name).read_bytes()
        assert a == b, f"{name} not byte-stable"
        assert a, f"{name} empty"
    tel = outs[0]["telemetry"]
    assert tel["flight_recorder"]["recorded_hops"] > 0
    assert tel["latency"]["delivery_ns"]["p99"] > 0
    assert outs[0]["guards"]["clean"]
    rc = _chaos(["--hosts", "16", "--windows", "6"])
    assert rc == 0
    plain = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert plain["state_digest"] == outs[0]["state_digest"]
