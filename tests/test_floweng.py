"""Device flow engine: live tgen-shaped TCP transfers stepped entirely
on device (`shadow_tpu.tpu.floweng`), validated flow-for-flow against
the CPU `TcpConnection` pair driver.

The TCP state machine itself is the proven-bitwise kernel
(tests/test_tpu_tcp.py trace replay); these tests validate the DRIVER —
windowed PDES event selection, the wire rings, the app model — at the
flow level: exact byte delivery, clean teardown, completion times in the
same ballpark as the CPU pair driver for identical latency/size, and
bitwise determinism across runs.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from shadow_tpu.tpu import floweng
from shadow_tpu.tpu import tcp as dtcp

MS = 1000  # us per ms


def run_flows(latencies_ms, sizes, sim_ms, window_ms=None, starts_ms=None):
    lat = np.asarray(latencies_ms) * MS
    if window_ms is None:
        window_ms = min(latencies_ms)
    starts = None if starts_ms is None else np.asarray(starts_ms) * MS
    world = floweng.make_flow_world(lat, np.asarray(sizes),
                                    start_us=starts)
    world, events = floweng.run_windows(world, sim_ms // window_ms,
                                        window_ms * MS)
    return floweng.flow_results(world), np.asarray(events)


@pytest.mark.slow  # 4s-sim engine run (~18s); stays GATING in CI's
# tier-1-overflow unfiltered step
def test_single_flow_completes_cleanly():
    res, events = run_flows([20], [200_000], sim_ms=4_000)
    assert res["bytes_read"].tolist() == [200_000]
    assert res["queue_drops"] == 0
    assert res["saturated_windows"] == 0
    done = res["complete_us"][0]
    # physical lower bound: SYN + SYN|ACK + first data = 3 one-way trips,
    # then ~size/MSS segments window-paced over a 40 ms RTT
    assert 3 * 20 * MS < done < 2_000 * MS
    # both ends tore down: writer in CLOSED or TIME_WAIT, reader CLOSED
    a, b = int(res["states"][0]), int(res["states"][1])
    assert a in (dtcp.CLOSED, dtcp.TIME_WAIT)
    assert b in (dtcp.CLOSED, dtcp.TIME_WAIT)
    # windows after completion go quiet (no event churn at the tail)
    assert events[-1] <= 1


@pytest.mark.slow  # engine run + CPU pair harness (~21s); stays GATING
# in CI's tier-1-overflow unfiltered step
def test_flow_completion_tracks_cpu_pair_driver():
    """Same latency + size through the CPU TcpConnection pair harness:
    the device flow must finish within 2x of the CPU completion time
    (identical TCP machine; app pacing differs slightly) and use a
    comparable number of segments."""
    from test_tpu_tcp import transfer_scenario

    size = 150_000
    a, b = transfer_scenario(20 * 1_000_000, seed=3, size=size, chunk=65536)
    # CPU completion: the READ event where b's cumulative reaches size
    got, t_done = 0, None
    for t, kind, f, exp in b.rec.events:
        if kind == dtcp.EV_READ and exp and exp > 0:
            got += exp
            if got >= size:
                t_done = t
                break
    assert t_done is not None
    cpu_us = t_done // 1000

    res, _ = run_flows([20], [size], sim_ms=4_000)
    dev_us = int(res["complete_us"][0])
    assert res["bytes_read"].tolist() == [size]
    assert dev_us < 2 * cpu_us, (dev_us, cpu_us)
    assert cpu_us < 4 * dev_us, (dev_us, cpu_us)


@pytest.mark.slow  # two full 3s-sim engine runs (~27s); stays GATING
# in CI's flow-engine-slow step (tier-1 runtime budget)
def test_flow_world_is_deterministic():
    r1, e1 = run_flows([20, 35, 50], [100_000, 65_536, 32_768],
                       sim_ms=3_000)
    r2, e2 = run_flows([20, 35, 50], [100_000, 65_536, 32_768],
                       sim_ms=3_000)
    assert r1["complete_us"].tolist() == r2["complete_us"].tolist()
    assert r1["segments"] == r2["segments"]
    assert e1.tolist() == e2.tolist()


@pytest.mark.slow  # 48-flow engine run (~20s); stays GATING in CI's
# tier-1-overflow unfiltered step
def test_many_heterogeneous_flows_complete():
    rng = np.random.default_rng(5)
    F = 48
    lats = rng.integers(20, 120, F).tolist()
    sizes = rng.integers(10_000, 150_000, F)
    starts = rng.integers(0, 500, F).tolist()
    res, _ = run_flows(lats, sizes, sim_ms=12_000, window_ms=20,
                       starts_ms=starts)
    assert res["bytes_read"].tolist() == sizes.tolist()
    assert (res["complete_us"] < np.int64(12_000) * MS).all()
    assert res["queue_drops"] == 0
    assert res["saturated_windows"] == 0
    assert res["retransmits"] <= F  # lossless wire: only spurious RTOs


@pytest.mark.slow  # the saturating + clean twin runs (~53s, the
# single heaviest tier-1 test); stays GATING in CI's flow-engine-slow
# step (tier-1 runtime budget)
def test_saturated_window_rerun_matches_unsaturated():
    """VERDICT r4 #9: a step cap that truncates windows must not distort
    results. run_to_completion re-runs from the initial world with a
    doubled cap until no window saturates; the final results must be
    IDENTICAL to a run that never saturated."""
    lats = np.array([20, 25, 30]) * MS
    sizes = np.array([120_000, 90_000, 60_000])

    def run(cap):
        world = floweng.make_flow_world(lats, sizes)
        # sched_batch/pull_cap 1 so a fused step carries one event — a
        # 1-step window cap then genuinely truncates mid-burst
        return floweng.run_to_completion(
            world, 20 * MS, max_sim_s=8.0, chunk_windows=25,
            probe_every=2, max_events_per_window=cap,
            sched_batch=1, pull_cap=1)

    # tiny cap: the first runs MUST saturate and trigger retries
    w_tiny, _, retries_tiny = run(1)
    assert retries_tiny > 0
    w_big, _, retries_big = run(512)
    assert retries_big == 0
    r_tiny = floweng.flow_results(w_tiny)
    r_big = floweng.flow_results(w_big)
    assert r_tiny["saturated_windows"] == 0  # the final run is clean
    assert r_tiny["complete_us"].tolist() == r_big["complete_us"].tolist()
    assert r_tiny["bytes_read"].tolist() == r_big["bytes_read"].tolist()
    assert r_tiny["segments"] == r_big["segments"]
