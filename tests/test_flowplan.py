"""Manager -> device flow engine integration (`core/flowplan.py`,
`experimental.use_flow_engine`): a YAML tgen workload compiles to a
flow plan, executes on the flow engine (CPU backend here, same code
path as TPU), and reconciles into SimStats. Cross-validated against
the full CPU object plane on an identical config.

Reference analogue: tgen throughput tests driven from shadow.yaml
(`/root/reference/src/test/tgen/README.md:1-20`).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.flowplan import FlowPlanError, compile_flow_plan
from shadow_tpu.core.manager import Manager

GML = """\
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        node [ id 1 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "5 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "40 ms" packet_loss 0.002 ]
        edge [ source 1 target 1 latency "5 ms" packet_loss 0.0 ]
      ]
"""


def tgen_cfg(n_clients=3, size=50_000, use_flow_engine=True,
             stop="30s") -> str:
    hosts = ["  server:\n    network_node_id: 0\n    processes:\n"
             "    - {path: tgen-server, args: ['8888'], start_time: 1s,\n"
             "       expected_final_state: running}"]
    for i in range(n_clients):
        hosts.append(
            f"  client{i}:\n    network_node_id: 1\n    processes:\n"
            f"    - {{path: tgen-client, args: ['server', '8888', "
            f"'{size}', '1'], start_time: {2 + i}s}}"
        )
    flag = ("experimental: {use_flow_engine: true}\n"
            if use_flow_engine else "")
    return (f"general: {{stop_time: {stop}, seed: 1}}\n" + flag +
            "network:\n  graph:\n    type: gml\n    inline: |\n" + GML +
            "hosts:\n" + "\n".join(hosts))


def test_flow_plan_compiles():
    cfg = load_config_str(tgen_cfg())
    mgr = Manager(cfg)
    plan = compile_flow_plan(cfg, mgr.routing)
    assert len(plan.size) == 3
    assert (plan.size == 50_000).all()
    assert (plan.latency_us == 40_000).all()
    assert np.allclose(plan.loss, 0.002)
    assert plan.window_us <= 25_000
    assert plan.start_us.tolist() == [2_000_000, 3_000_000, 4_000_000]


def test_flow_plan_rejects_non_tgen():
    cfg = load_config_str(
        "general: {stop_time: 10s, seed: 1}\n"
        "experimental: {use_flow_engine: true}\n"
        "network:\n  graph: {type: 1_gbit_switch}\n"
        "hosts:\n  h:\n    network_node_id: 0\n    processes:\n"
        "    - {path: http-server, args: ['80'], start_time: 1s}\n")
    mgr = Manager(cfg)
    with pytest.raises(FlowPlanError, match="http-server"):
        compile_flow_plan(cfg, mgr.routing)


@pytest.mark.slow  # full flow-engine sim (~22s); stays GATING in CI's
# tier-1-overflow unfiltered step
def test_manager_runs_on_flow_engine():
    cfg = load_config_str(tgen_cfg())
    stats = Manager(cfg).run()
    assert stats.process_failures == []
    assert stats.packets_sent > 3 * 50_000 // 1448  # at least the data segs
    assert stats.sim_time_ns == 30_000_000_000
    complete = stats.flow_complete_us
    # transfers start at 2/3/4 s and need >= 2 RTTs of 80 ms
    assert (complete > np.array([2, 3, 4]) * 1_000_000 + 160_000).all()
    assert (complete < 30_000_000).all()


@pytest.mark.slow  # full CPU-object-plane sim (~21s); stays GATING in
# CI's tier-1-overflow unfiltered step
def test_flow_engine_tracks_cpu_plane():
    """Same YAML through the full CPU object plane: flow completion
    times (server streams size bytes, client reads them) must land in
    the same ballpark — the flow engine models the same TCP machine
    over the same path latency, so completions should agree within 2x
    of the transfer tail past connect."""
    cfg_flow = load_config_str(tgen_cfg(n_clients=2, size=80_000))
    s_flow = Manager(cfg_flow).run()
    assert s_flow.process_failures == []

    cfg_cpu = load_config_str(
        tgen_cfg(n_clients=2, size=80_000, use_flow_engine=False))
    s_cpu = Manager(cfg_cpu).run()
    assert s_cpu.process_failures == []
    # CPU plane records no per-flow completion; compare through packet
    # economy instead: both planes moved the same payload, so segment
    # counts sit within 2x (ack cadence and loss draws differ)
    assert 0.5 < s_flow.packets_sent / max(s_cpu.packets_sent, 1) < 2.0


@pytest.mark.slow  # full engine run to stop_time (~19s); stays GATING
# in CI's tier-1-overflow unfiltered step
def test_incomplete_flow_fails_run():
    """A transfer that cannot finish by stop_time must surface as a
    process failure (the client expected exited(0))."""
    cfg = load_config_str(tgen_cfg(n_clients=1, size=50_000_000,
                                   stop="3s"))
    stats = Manager(cfg).run()
    assert len(stats.process_failures) == 1
    name, why = stats.process_failures[0]
    assert "client0" in name and "transfer" in why


@pytest.mark.slow  # two directed-path engine sims (~23s); stays GATING
# in CI's tier-1-overflow unfiltered step
def test_flow_plan_asymmetric_directed_paths():
    """Directed graphs may price each direction differently; each lane
    must carry its own direction's latency/loss (r5 review finding)."""
    gml = """\
      graph [
        directed 1
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        node [ id 1 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "5 ms" packet_loss 0.0 ]
        edge [ source 1 target 1 latency "5 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "30 ms" packet_loss 0.001 ]
        edge [ source 1 target 0 latency "90 ms" packet_loss 0.01 ]
      ]
"""
    cfg_text = (
        "general: {stop_time: 30s, seed: 1}\n"
        "experimental: {use_flow_engine: true}\n"
        "network:\n  graph:\n    type: gml\n    inline: |\n" + gml +
        "hosts:\n"
        "  server:\n    network_node_id: 0\n    processes:\n"
        "    - {path: tgen-server, args: ['8888'], start_time: 1s,\n"
        "       expected_final_state: running}\n"
        "  client0:\n    network_node_id: 1\n    processes:\n"
        "    - {path: tgen-client, args: ['server', '8888', '40000', '1'],"
        " start_time: 2s}\n")
    cfg = load_config_str(cfg_text)
    mgr = Manager(cfg)
    plan = compile_flow_plan(cfg, mgr.routing)
    assert plan.latency_us.tolist() == [90_000]  # client(node1)->server
    assert plan.latency_back_us.tolist() == [30_000]
    assert np.allclose(plan.loss, 0.01)
    assert np.allclose(plan.loss_back, 0.001)
    # and the whole thing runs
    stats = Manager(cfg).run()
    assert stats.process_failures == []


def test_flow_plan_rejects_stop_time_past_int32_us():
    """stop_time beyond the int32 microsecond domain used to silently
    wrap on device (advisor r5 medium finding); it must refuse loudly."""
    cfg = load_config_str(tgen_cfg(n_clients=1, stop="2150s"))
    mgr = Manager(cfg)
    with pytest.raises(FlowPlanError, match="int32 microsecond"):
        compile_flow_plan(cfg, mgr.routing)


def test_flow_plan_rejects_client_start_past_int32_us():
    cfg_text = tgen_cfg(n_clients=1).replace("start_time: 2s",
                                             "start_time: 2148s")
    cfg = load_config_str(cfg_text)
    mgr = Manager(cfg)
    with pytest.raises(FlowPlanError, match="client0.*int32 microsecond"):
        compile_flow_plan(cfg, mgr.routing)


@pytest.mark.slow  # drives a full flow-engine sim twice (~29s);
# stays GATING in CI's flow-engine-slow step (tier-1 runtime budget)
def test_ring_drops_rerun_bucket_with_doubled_queue_slots(monkeypatch):
    """Nonzero engine ring-capacity queue_drops must trigger the same
    re-run discipline as step-cap saturation: a fresh bucket run with
    doubled queue_slots (advisor r5 finding — ring drops are an engine
    artifact, distinct from modeled wire drops)."""
    from shadow_tpu.tpu import floweng

    slots_used = []
    real_make = floweng.make_flow_world
    real_results = floweng.flow_results

    def fake_make(lat, size, **kw):
        slots_used.append(kw.get("queue_slots"))
        return real_make(lat, size, **kw)

    def fake_results(world):
        res = real_results(world)
        if len(slots_used) == 1:  # poison only the first attempt
            res = dict(res)
            res["queue_drops"] = 3
        return res

    monkeypatch.setattr(floweng, "make_flow_world", fake_make)
    monkeypatch.setattr(floweng, "flow_results", fake_results)
    cfg = load_config_str(tgen_cfg(n_clients=1, size=20_000))
    stats = Manager(cfg).run()
    assert slots_used == [256, 512]
    assert stats.process_failures == []
