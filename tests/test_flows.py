"""Flow plane tests (tpu/flows.py, docs/robustness.md "Flow plane"):

- RTO twin parity at the clip boundaries: the device estimator helpers
  (`tpu/tcp.py` `_rtt_update`/`_rtt_backoff`/`_set_rto`) against the
  CPU `tcp/rtt.RttEstimator` — RTO_MIN/RTO_MAX clamps, the srtt==0
  first-sample fallback, backoff saturation at RTO_MAX — the edges the
  bitwise-parity contract (`_rto_from_estimate`'s twin comment) pins.
- flow state-machine units over synthetic delivered dicts: in-order
  credit, out-of-order buffering + hole-fill release, duplicate
  re-ack, cumulative-ack cwnd advance, RTO expiry -> go-back-N with
  exponential backoff and counted retransmissions.
- presence: all-inactive flow tables threaded through window_step are
  bitwise-invisible (state + metrics); pallas kernels refuse flows;
  unpack_planes grows the flows slot; chain_windows threads the plane
  and refuses the workload+flows combo.
- scenario integration (slow): a lossy `transport: flows` incast
  completes all phases deterministically with >0 retransmits; at
  loss_p=0 the flows run matches the direct run's phase completions;
  the flight recorder links drop_loss -> retransmit -> delivered.
- config: the `flows:` block (bare off/on, validation) and the
  Manager unsupported-combo warn / strict ConfigError.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.tcp.rtt import (RTO_INIT_MS, RTO_MAX_MS, RTO_MIN_MS,
                                RttEstimator)
from shadow_tpu.tpu import flows, plane
from shadow_tpu.tpu import tcp as dtcp

MS = 1_000_000
WINDOW = jnp.int32(10 * MS)


def _mini_world(n=4, loss=0.0, ce=8, ci=8):
    params = plane.make_params(
        np.full((n, n), 1_000_000, np.int64),
        np.full((n, n), loss, np.float32),
        np.full(n, 1_000_000_000, np.int64))
    state = plane.make_state(n, egress_cap=ce, ingress_cap=ci,
                             params=params)
    return state, params, jax.random.key(0)


def _delivered(n=4, ci=8, entries=()):
    """Synthetic delivered dict; entries = (row, src, seq, sock)."""
    d = {
        "mask": np.zeros((n, ci), bool),
        "src": np.zeros((n, ci), np.int32),
        "seq": np.zeros((n, ci), np.int32),
        "sock": np.zeros((n, ci), np.int32),
        "bytes": np.zeros((n, ci), np.int32),
        "deliver_rel": np.zeros((n, ci), np.int32),
    }
    slot = {}
    for row, src, seq, sock in entries:
        c = slot.get(row, 0)
        slot[row] = c + 1
        d["mask"][row, c] = True
        d["src"][row, c] = src
        d["seq"][row, c] = seq
        d["sock"][row, c] = sock
    return {k: jnp.asarray(v) for k, v in d.items()}


# -- RTO twin parity at the clip boundaries -------------------------------


def _device_est(k=1):
    return flows.make_flow_state(k)


def _dev_update(fs, rtt_ms):
    return jax.vmap(dtcp._rtt_update, in_axes=(0, None))(
        fs, jnp.int32(rtt_ms))


def _dev_fields(fs):
    return (int(fs.srtt_ms[0]), int(fs.rttvar_ms[0]), int(fs.rto_ms[0]),
            int(fs.backoff_count[0]))


def _cpu_fields(est):
    return (est.srtt_ms, est.rttvar_ms, est.rto_ms, est.backoff_count)


def test_rto_twin_first_sample_fallback():
    # srtt == 0 means "no measurement yet": the first sample seeds
    # srtt = rtt, rttvar = rtt // 2 in BOTH twins, and reset_backoff
    # before any sample restores RTO_INIT, never 0
    est, fs = RttEstimator(), _device_est()
    assert _dev_fields(fs) == _cpu_fields(est) == (0, 0, RTO_INIT_MS, 0)
    est.update(300)
    fs = _dev_update(fs, 300)
    assert _dev_fields(fs) == _cpu_fields(est)
    assert est.srtt_ms == 300 and est.rttvar_ms == 150

    est2, fs2 = RttEstimator(), _device_est()
    est2.backoff()
    fs2 = jax.vmap(dtcp._rtt_backoff)(fs2)
    est2.reset_backoff()
    fs2 = jax.vmap(dtcp._rtt_reset_backoff)(fs2)
    assert _dev_fields(fs2) == _cpu_fields(est2)
    assert est2.rto_ms == RTO_INIT_MS


def test_rto_twin_min_clip():
    # a tiny (even non-positive) sample floors at 1 ms and the RTO
    # clips at RTO_MIN via the Linux mdev floor
    for rtt in (0, 1, 3):
        est, fs = RttEstimator(), _device_est()
        est.update(rtt)
        fs = _dev_update(fs, rtt)
        assert _dev_fields(fs) == _cpu_fields(est)
        assert est.rto_ms >= RTO_MIN_MS


def test_rto_twin_max_clip():
    # a huge sample clips the RTO at RTO_MAX in both twins
    est, fs = RttEstimator(), _device_est()
    est.update(10 * RTO_MAX_MS)
    fs = _dev_update(fs, 10 * RTO_MAX_MS)
    assert _dev_fields(fs) == _cpu_fields(est)
    assert est.rto_ms == RTO_MAX_MS


def test_rto_twin_backoff_saturation():
    # exponential backoff saturates at RTO_MAX and STAYS there; a
    # post-saturation reset restores the estimate-derived RTO
    est, fs = RttEstimator(), _device_est()
    est.update(250)
    fs = _dev_update(fs, 250)
    for i in range(14):
        est.backoff()
        fs = jax.vmap(dtcp._rtt_backoff)(fs)
        assert _dev_fields(fs) == _cpu_fields(est), f"step {i}"
    assert est.rto_ms == RTO_MAX_MS
    est.backoff()
    fs = jax.vmap(dtcp._rtt_backoff)(fs)
    assert est.rto_ms == RTO_MAX_MS
    assert _dev_fields(fs) == _cpu_fields(est)
    est.reset_backoff()
    fs = jax.vmap(dtcp._rtt_reset_backoff)(fs)
    assert _dev_fields(fs) == _cpu_fields(est)
    assert est.rto_ms < RTO_MAX_MS


def test_rto_twin_random_trace_parity():
    # a seeded mixed op trace stays field-identical end to end
    rng = np.random.default_rng(7)
    est, fs = RttEstimator(), _device_est()
    for i in range(60):
        op = rng.integers(0, 3)
        if op == 0:
            rtt = int(rng.integers(1, 5000))
            est.update(rtt)
            fs = _dev_update(fs, rtt)
        elif op == 1:
            est.backoff()
            fs = jax.vmap(dtcp._rtt_backoff)(fs)
        else:
            est.reset_backoff()
            fs = jax.vmap(dtcp._rtt_reset_backoff)(fs)
        assert _dev_fields(fs) == _cpu_fields(est), f"op {i}"


# -- flow state-machine units ---------------------------------------------


def _one_flow(stream=0):
    ft = flows.make_flow_tables([0], [1], [1400])
    fs = flows.make_flow_state(1)
    if stream:
        fs = fs._replace(stream_len=jnp.array([stream], jnp.int32))
    return ft, fs


def test_flow_recv_in_order_credit():
    ft, fs = _one_flow()
    dtag = int(flows.data_tag(np.int32(0)))
    d = _delivered(entries=[(1, 0, 0, dtag), (1, 0, 1, dtag),
                            (1, 0, 2, dtag)])
    fs2, credits = flows.flow_recv(ft, fs, d, WINDOW)
    assert int(fs2.rcv_nxt[0]) == 3
    assert bool(fs2.ack_pending[0])
    assert np.asarray(credits).tolist() == [0, 3, 0, 0]
    # the clock advanced one window in ms
    assert int(fs2.clock_ms[0]) == 10


def test_flow_recv_buffers_out_of_order_and_releases_on_hole_fill():
    ft, fs = _one_flow()
    dtag = int(flows.data_tag(np.int32(0)))
    # seq 1, 2 arrive first: buffered, no credit (hole at 0)
    fs2, credits = flows.flow_recv(
        ft, fs, _delivered(entries=[(1, 0, 1, dtag), (1, 0, 2, dtag)]),
        WINDOW)
    assert int(fs2.rcv_nxt[0]) == 0
    assert np.asarray(credits).sum() == 0
    assert bool(fs2.ack_pending[0])  # dup/OOO still re-arms the ack
    # the hole fills: the buffered run releases in one window
    fs3, credits = flows.flow_recv(
        ft, fs2, _delivered(entries=[(1, 0, 0, dtag)]), WINDOW)
    assert int(fs3.rcv_nxt[0]) == 3
    assert np.asarray(credits).tolist() == [0, 3, 0, 0]
    # bitmap shifted clean: bit 0 False again
    assert not bool(fs3.rcv_bits[0, 0])


def test_flow_recv_duplicate_rearms_ack_without_credit():
    ft, fs = _one_flow()
    fs = fs._replace(rcv_nxt=jnp.array([2], jnp.int32))
    dtag = int(flows.data_tag(np.int32(0)))
    fs2, credits = flows.flow_recv(
        ft, fs, _delivered(entries=[(1, 0, 0, dtag)]), WINDOW)
    assert int(fs2.rcv_nxt[0]) == 2
    assert np.asarray(credits).sum() == 0
    assert bool(fs2.ack_pending[0])


def test_flow_recv_foreign_traffic_is_inert():
    # untagged (sock 0/1) and endpoint-mismatched packets never touch
    # flow state — the all-inactive presence guarantee's mechanism
    ft, fs = _one_flow()
    dtag = int(flows.data_tag(np.int32(0)))
    d = _delivered(entries=[
        (1, 0, 5, 0),       # untagged
        (1, 0, 6, 1),       # reserved
        (2, 0, 0, dtag),    # wrong destination row
        (1, 3, 0, dtag),    # wrong source
    ])
    fs2, credits = flows.flow_recv(ft, fs, d, WINDOW)
    assert int(fs2.rcv_nxt[0]) == 0
    assert not bool(fs2.ack_pending[0])
    assert np.asarray(credits).sum() == 0


def test_flow_ack_advances_cwnd_and_rearms_rto():
    ft, fs = _one_flow(stream=8)
    state, _params, _root = _mini_world()
    # emit the initial window (arms the RTO + the RTT probe)
    state, fs = flows.flow_emit(ft, fs, state)[:2]
    assert int(fs.snd_nxt[0]) == 8
    assert bool(fs.rto_armed[0])
    assert int(fs.rtt_seq[0]) == 0
    cwnd0 = int(fs.cwnd[0])
    # a cumulative ack for 3 segments arrives two windows later
    atag = int(flows.ack_tag(np.int32(0)))
    d = _delivered(entries=[(0, 1, 3, atag)])
    fs2, _credits = flows.flow_recv(ft, fs, d, WINDOW)
    assert int(fs2.snd_una[0]) == 3
    assert int(fs2.cwnd[0]) == cwnd0 + 3  # slow start
    assert bool(fs2.rto_armed[0])  # data still outstanding
    # the probe (seq 0) was covered: an RTT sample landed
    assert int(fs2.srtt_ms[0]) > 0
    assert int(fs2.rtt_seq[0]) == -1
    # ack of everything disarms the timer
    d2 = _delivered(entries=[(0, 1, 8, atag)])
    fs3, _credits = flows.flow_recv(ft, fs2, d2, WINDOW)
    assert int(fs3.snd_una[0]) == 8
    assert not bool(fs3.rto_armed[0])


def test_flow_rto_fires_go_back_n():
    ft, fs = _one_flow(stream=4)
    state, _params, _root = _mini_world()
    state, fs = flows.flow_emit(ft, fs, state)[:2]
    assert int(fs.snd_nxt[0]) == 4 and int(fs.snd_max[0]) == 4
    deadline = int(fs.rto_deadline_ms[0])
    rto0 = int(fs.rto_ms[0])
    # a quiet window leaves the timer untouched...
    fs, credits = flows.flow_recv(ft, fs, _delivered(), WINDOW)
    assert np.asarray(credits).sum() == 0
    assert bool(fs.rto_armed[0])
    # ...then jump the flow clock to the deadline (the driver loop
    # would get here through `deadline // window_ms` quiet recvs — the
    # clock is the only recv effect on an idle window) and emit: fires
    fs = fs._replace(clock_ms=jnp.full_like(fs.clock_ms, deadline))
    state2, fs2 = flows.flow_emit(ft, fs, state)[:2]
    assert int(fs2.rto_fired[0]) == 1
    assert int(fs2.backoff_count[0]) == 1
    assert int(fs2.rto_ms[0]) == min(2 * rto0, RTO_MAX_MS)
    assert int(fs2.cwnd[0]) == dtcp.INITIAL_CWND  # Reno timeout reset
    # go-back-N: the whole unacked range re-emitted and counted
    assert int(fs2.snd_nxt[0]) == 4
    assert int(fs2.retransmit_count[0]) == 4
    assert int(fs2.retransmitted_bytes[0]) == 4 * 1400
    assert int(fs2.rtt_seq[0]) == -1  # Karn: probe abandoned
    # the per-host reduction agrees with the per-flow counter (the
    # tcp.retransmits_by_host twin; also what metrics.retransmits got)
    assert np.asarray(
        flows.retransmits_by_host(ft, fs2, 4)).tolist() == [4, 0, 0, 0]


def test_flow_emit_respects_cwnd_and_emit_cap():
    ft, fs = _one_flow(stream=100)
    fs = fs._replace(cwnd=jnp.array([3], jnp.int32))
    state, _params, _root = _mini_world()
    state, fs = flows.flow_emit(ft, fs, state)[:2]
    assert int(fs.snd_nxt[0]) == 3  # cwnd-limited below emit_cap
    fs = fs._replace(cwnd=jnp.array([100], jnp.int32))
    state, fs = flows.flow_emit(ft, fs, state)[:2]
    # emit_cap-limited per window
    assert int(fs.snd_nxt[0]) == 3 + flows.EMIT_CAP
    # the emit_cap knob (the `flows:` config block) overrides the lane
    # budget per call
    state, fs = flows.flow_emit(ft, fs, state, emit_cap=2)[:2]
    assert int(fs.snd_nxt[0]) == 3 + flows.EMIT_CAP + 2


def test_next_deadline_rel_ns():
    ft = flows.make_flow_tables([0, 2, -1], [1, 3, -1],
                                [100, 100, 100])
    fs = flows.make_flow_state(3)
    # nothing armed -> sentinel
    assert int(flows.next_deadline_rel_ns(ft, fs)) == flows.I32_MAX
    # two armed timers: the earliest pending deadline wins, relative
    # to the flow clock; an inactive slot's timer never counts
    fs = fs._replace(
        snd_una=jnp.asarray([0, 0, 0], jnp.int32),
        snd_nxt=jnp.asarray([2, 2, 2], jnp.int32),
        rto_armed=jnp.asarray([True, True, True]),
        rto_deadline_ms=jnp.asarray([500, 300, 1], jnp.int32),
        clock_ms=jnp.asarray([100, 100, 100], jnp.int32))
    assert int(flows.next_deadline_rel_ns(ft, fs)) == 200 * MS
    # already-due reads 0 (fires next window), never negative
    fs = fs._replace(clock_ms=jnp.asarray([600, 600, 600], jnp.int32))
    assert int(flows.next_deadline_rel_ns(ft, fs)) == 0


def test_enqueue_counts_lanes():
    ft = flows.make_flow_tables([0, 2], [1, 3], [100, 200])
    fs = flows.make_flow_state(2)
    ids = jnp.asarray([[0, 1, -1], [1, 1, 0]], jnp.int32)
    valid = jnp.asarray([[True, True, True], [True, False, True]])
    fs = flows.enqueue(ft, fs, ids, valid)
    assert np.asarray(fs.stream_len).tolist() == [2, 2]


# -- presence + threading -------------------------------------------------


def test_window_step_inactive_flows_bitwise_invisible():
    from shadow_tpu.guards import make_guards
    from shadow_tpu.telemetry import make_metrics

    state, params, root = _mini_world()
    state = plane.ingest(
        state, jnp.array([0, 1], jnp.int32), jnp.array([1, 2], jnp.int32),
        jnp.full(2, 1400, jnp.int32), jnp.arange(2, dtype=jnp.int32),
        jnp.arange(2, dtype=jnp.int32), jnp.zeros(2, bool))
    ft = flows.make_flow_tables(np.full(3, -1), np.full(3, -1),
                                np.full(3, 1400))
    fs = flows.make_flow_state(3)
    m0, g0 = make_metrics(4), make_guards(4)

    base = jax.jit(lambda st, m, g, sh: plane.window_step(
        st, params, root, sh, WINDOW, rr_enabled=False, metrics=m,
        guards=g))
    with_f = jax.jit(lambda st, m, g, fstate, sh: plane.window_step(
        st, params, root, sh, WINDOW, rr_enabled=False, metrics=m,
        guards=g, flows=(ft, fstate)))

    sa, ma, ga, sh = state, m0, g0, jnp.int32(0)
    sb, mb, gb, fsx = state, m0, g0, fs
    for _ in range(3):
        sa, da, na, ma, ga = base(sa, ma, ga, sh)
        sb, db, nb, mb, gb, fsx = with_f(sb, mb, gb, fsx, sh)
        sh = WINDOW
    for a, b in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
        assert (np.asarray(a) == np.asarray(b)).all()
    for a, b in zip(jax.tree.leaves(ma), jax.tree.leaves(mb)):
        assert (np.asarray(a) == np.asarray(b)).all()
    assert int(na) == int(nb)
    # guard contract: violation bits identically clean; only the
    # checks TALLY grows (the flow append is checked like any
    # producer's — docs/robustness.md "Flow plane")
    assert int(np.asarray(ga.violations).sum()) == 0
    assert int(np.asarray(gb.violations).sum()) == 0
    assert (np.asarray(gb.first_window)
            == np.asarray(ga.first_window)).all()
    assert int(gb.checks) > int(ga.checks)


def test_window_step_flows_refuses_pallas():
    state, params, root = _mini_world()
    ft, fs = _one_flow()
    with pytest.raises(ValueError, match="flow plane"):
        plane.window_step(state, params, root, jnp.int32(0), WINDOW,
                          rr_enabled=False, kernel="pallas",
                          flows=(ft, fs))


@pytest.mark.slow  # two eager window_step traces; CI's lossy-corpus
# job runs this file UNFILTERED so the case stays gating
def test_unpack_planes_flows_slot():
    state, params, root = _mini_world()
    ft, fs = _one_flow()
    out = plane.window_step(state, params, root, jnp.int32(0), WINDOW,
                            rr_enabled=False, flows=(ft, fs))
    (st, _d, _n), m, g, h, fr, fs2 = plane.unpack_planes(
        out, flows=fs)
    assert m is g is h is fr is None
    assert isinstance(fs2, flows.FlowState)
    assert type(st) is plane.NetPlaneState
    # legacy shape is untouched when the slot is not requested
    out2 = plane.window_step(state, params, root, jnp.int32(0), WINDOW,
                             rr_enabled=False)
    (st2, _d2, _n2), m2, g2, h2, fr2 = plane.unpack_planes(out2)
    assert m2 is None and fr2 is None


@pytest.mark.slow  # compiles the chained while_loop; CI runs this
# file unfiltered (lossy-corpus job) so the case stays gating
def test_chain_windows_flows_threads_and_refuses_workload_combo():
    state, params, root = _mini_world()
    ft, fs = _one_flow(stream=2)
    out = plane.chain_windows(
        state, params, root, jnp.int32(0), WINDOW, WINDOW,
        jnp.int32(200 * MS), jnp.int32(200 * MS),
        rr_enabled=False, flows=(ft, fs))
    fs2 = out[-1]
    assert isinstance(fs2, flows.FlowState)
    # the chain drove the flow's segments onto the wire
    assert int(fs2.snd_nxt[0]) == 2
    with pytest.raises(ValueError, match="not both"):
        plane.chain_windows(
            state, params, root, jnp.int32(0), WINDOW, WINDOW,
            jnp.int32(200 * MS), jnp.int32(200 * MS),
            rr_enabled=False, flows=(ft, fs),
            workload=(object(), object()))


# -- spec / compile -------------------------------------------------------


def _incast_raw(**over):
    raw = {
        "name": "t-incast", "family": "incast", "seed": 13,
        "hosts": 12, "windows": 64,
        "patterns": [{"kind": "incast", "first": 0, "count": 9,
                      "bytes": 8000, "rounds": 4}],
    }
    raw.update(over)
    return raw


def test_spec_lossy_requires_flows():
    from shadow_tpu.workloads.spec import ScenarioError, parse_scenario

    with pytest.raises(ScenarioError, match="transport: flows"):
        parse_scenario(_incast_raw(loss_p=0.1))
    with pytest.raises(ScenarioError, match="transport"):
        parse_scenario(_incast_raw(transport="tcp"))
    with pytest.raises(ScenarioError, match="window_ns"):
        parse_scenario(_incast_raw(transport="flows",
                                   window_ns=500_000))
    spec = parse_scenario(_incast_raw(transport="flows", loss_p=0.1))
    assert spec.transport == "flows" and spec.loss_p == 0.1


def test_spec_fingerprint_backward_stable():
    from shadow_tpu.workloads.spec import (parse_scenario,
                                           scenario_fingerprint)

    direct = parse_scenario(_incast_raw())
    explicit = parse_scenario(_incast_raw(transport="direct",
                                          loss_p=0.0))
    # default transport/loss add NO keys: pre-existing fingerprints
    # (and the golden corpus) are untouched by the new fields
    assert "transport" not in direct.as_dict()
    assert scenario_fingerprint(direct) == scenario_fingerprint(explicit)
    flowsy = parse_scenario(_incast_raw(transport="flows"))
    assert scenario_fingerprint(flowsy) != scenario_fingerprint(direct)


def test_compile_lowers_flow_tables():
    from shadow_tpu.workloads.compile import (compile_program,
                                              program_digest)
    from shadow_tpu.workloads.spec import parse_scenario

    direct = compile_program(parse_scenario(_incast_raw()))
    assert direct.flow_src is None and direct.lane_flow is None
    prog = compile_program(parse_scenario(_incast_raw(
        transport="flows")))
    # incast 8->1: 8 data flows + 8 sink->source ack-message flows
    assert prog.flow_src is not None
    F = prog.flow_src.shape[0]
    assert F == 16
    # every send lane of a participant maps to a flow with matching
    # endpoints and byte size
    for h in range(12):
        for p in range(int(prog.n_phases[h])):
            for k in range(prog.send_peer.shape[2]):
                peer = int(prog.send_peer[h, p, k])
                f = int(prog.lane_flow[h, p, k])
                if peer < 0:
                    assert f == -1
                    continue
                assert prog.flow_src[f] == h
                assert prog.flow_dst[f] == peer
                assert prog.flow_bytes[f] == prog.send_bytes[h, p, k]
    # the flow tables fold into the digest; the direct digest is
    # computed over the same first-six tables yet differs
    assert program_digest(prog) != program_digest(direct)


# -- config block + Manager -----------------------------------------------

BASE_CFG = ("general:\n  stop_time: 1s\n"
            "network:\n  graph:\n    type: 1_gbit_switch\n"
            "hosts:\n  a:\n    network_node_id: 0\n")


def test_flows_config_block():
    from shadow_tpu.core.config import ConfigError, load_config_str

    cfg = load_config_str(BASE_CFG)
    assert not cfg.flows.enabled
    assert cfg.flows.emit_cap == 8 and cfg.flows.recv_wnd == 64
    cfg = load_config_str(
        BASE_CFG + "flows:\n  enabled: true\n  emit_cap: 4\n"
                   "  recv_wnd: 32\n")
    assert cfg.flows.enabled and cfg.flows.emit_cap == 4
    # YAML 1.1 bare off/on coerce like workload / flight_recorder
    cfg = load_config_str(BASE_CFG + "flows: off\n")
    assert not cfg.flows.enabled
    cfg = load_config_str(BASE_CFG + "flows: on\n")
    assert cfg.flows.enabled
    with pytest.raises(ConfigError):
        load_config_str(BASE_CFG + "flows:\n  emit_cap: 0\n")
    with pytest.raises(ConfigError):
        load_config_str(BASE_CFG + "flows:\n  recv_wnd: 0\n")
    with pytest.raises(ConfigError, match="emit_cap"):
        load_config_str(
            BASE_CFG + "flows:\n  emit_cap: 16\n  recv_wnd: 8\n")
    with pytest.raises(ConfigError):
        load_config_str(BASE_CFG + "flows:\n  bogus: 1\n")


def test_manager_warns_on_flows(caplog):
    import logging

    from shadow_tpu.core.config import ConfigError, load_config_str
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(BASE_CFG + "flows: on\n")
    with caplog.at_level(logging.WARNING, logger="shadow_tpu.manager"):
        Manager(cfg)
    assert any("flows" in r.getMessage() for r in caplog.records)
    cfg = load_config_str(BASE_CFG + "strict: true\nflows: on\n")
    with pytest.raises(ConfigError):
        Manager(cfg)


# -- scenario integration (slow: full corpus-runner worlds) ---------------


@pytest.mark.slow
def test_lossy_incast_completes_deterministic():
    from shadow_tpu.workloads import runner
    from shadow_tpu.workloads.spec import parse_scenario

    spec = parse_scenario(_incast_raw(transport="flows", loss_p=0.05,
                                      windows=400))
    r1 = runner.run_scenario(spec, guards=True)
    assert r1["all_done"], r1
    assert r1["retransmits"] > 0
    assert r1["drops"]["loss"] > 0
    assert r1["guards"]["clean"], r1["guards"]
    assert r1["flows"]["segments_acked"] == r1["flows"][
        "segments_enqueued"]
    r2 = runner.run_scenario(spec, guards=True)
    assert r1["canonical_digest"] == r2["canonical_digest"]
    assert r1["phase_completion_ns"] == r2["phase_completion_ns"]


@pytest.mark.slow
def test_flow_knobs_plumb_from_runner():
    # the `flows:` config-block knobs reach the flow machine through
    # run_scenario (run_scenarios --config plumbs cfg.flows here): a
    # shrunken recv_wnd changes the receive-bitmap shape and the
    # record reports the effective knobs
    from shadow_tpu.workloads import runner
    from shadow_tpu.workloads.spec import parse_scenario

    spec = parse_scenario(_incast_raw(transport="flows"))
    rec = runner.run_scenario(spec, flow_emit_cap=4, flow_recv_wnd=16)
    assert rec["all_done"]
    assert rec["flows"]["emit_cap"] == 4
    assert rec["flows"]["recv_wnd"] == 16
    with pytest.raises(ValueError, match="emit_cap"):
        runner.run_scenario(spec, flow_emit_cap=32, flow_recv_wnd=16)


@pytest.mark.slow
def test_zero_loss_flows_matches_direct_completion():
    from shadow_tpu.workloads import runner
    from shadow_tpu.workloads.spec import parse_scenario

    rd = runner.run_scenario(parse_scenario(_incast_raw()))
    rf = runner.run_scenario(parse_scenario(_incast_raw(
        transport="flows")))
    assert rd["all_done"] and rf["all_done"]
    assert rf["phase_completion_ns"] == rd["phase_completion_ns"]
    assert rf["host_completion"] == rd["host_completion"]
    assert rf["retransmits"] == 0


@pytest.mark.slow
def test_flightrec_links_loss_to_retransmit():
    import io
    import json

    from shadow_tpu.workloads import runner
    from shadow_tpu.workloads.spec import parse_scenario

    spec = parse_scenario(_incast_raw(transport="flows", loss_p=0.05,
                                      windows=400))
    sink = io.StringIO()
    runner.run_scenario(spec, sample_every=1, hops_sink=sink)
    trails: dict[tuple, list] = {}
    kinds: dict[str, int] = {}
    for line in sink.getvalue().splitlines():
        h = json.loads(line)
        kinds[h["kind"]] = kinds.get(h["kind"], 0) + 1
        trails.setdefault((h["src"], h["seq"], h["dst"]),
                          []).append(h["kind"])
    assert kinds.get("rto_fired", 0) > 0
    assert kinds.get("retransmit", 0) > 0
    linked = [t for t in trails.values()
              if "drop_loss" in t and "retransmit" in t
              and "delivered" in t]
    assert linked, "no trail links a loss to its retransmission"
