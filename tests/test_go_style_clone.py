"""Go-style threading under the shim: raw clone(2) WITHOUT CLONE_SETTLS.

Go's runtime.newosproc (and other non-glibc runtimes) clones threads
with CLONE_VM but no CLONE_SETTLS — the child initially shares the
parent's %fs base, so the shim's TLS-based per-thread IPC slot would be
CLOBBERED by the child. The shim's tid-keyed fallback table
(`interpose/shim.cc:64-110`, reference `src/test/golang/` scenario) was
built exactly for this and, per VERDICT r3 item #9, had never been
driven by a real no-SETTLS clone. This test is that driver: a C program
reproducing Go's clone flags, with both parent and child making
simulated syscalls concurrently.

Environment probe (documented per the VERDICT item): this image ships
no Go toolchain (`which go` empty). The only Go binary found is
/usr/lib/google-cloud-sdk/bin/gcloud-crc32c (go1.25, STATICALLY
linked) — static binaries cannot load the LD_PRELOAD shim at all, so
running it would bypass interposition entirely; a namespace-clean
preload-injector (reference `src/lib/preload-injector/`) remains the
path to static-binary support. The raw-clone C program below exercises
the same runtime behavior a dynamic Go binary would.
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")

# Mirrors Go runtime.cloneFlags: VM | FS | FILES | SIGHAND | SYSVSEM |
# THREAD — crucially NO CLONE_SETTLS and no ctid/ptid words.
GO_CLONE_C = r"""
#define _GNU_SOURCE
#include <sched.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

#define GO_CLONE_FLAGS (CLONE_VM | CLONE_FS | CLONE_FILES | \
                        CLONE_SIGHAND | CLONE_SYSVSEM | CLONE_THREAD)

static volatile int child_progress;
static volatile int child_done;

static int worker(void *arg) {
    (void)arg;
    /* the child makes SIMULATED syscalls while sharing the parent's
       %fs base: every one must route through the tid table, not TLS */
    for (int i = 0; i < 5; i++) {
        struct timespec ts = {0, 2 * 1000 * 1000}; /* 2 simulated ms */
        if (syscall(SYS_nanosleep, &ts, 0)) { child_done = -1; return 1; }
        child_progress = i + 1;
    }
    struct timespec now;
    if (syscall(SYS_clock_gettime, CLOCK_MONOTONIC, &now)) {
        child_done = -2;
        return 1;
    }
    child_done = 1;
    return 0;
}

int main(void) {
    static char stack[256 * 1024] __attribute__((aligned(16)));
    int tid = clone(worker, stack + sizeof stack, GO_CLONE_FLAGS, 0);
    if (tid < 0) return 10;
    /* the PARENT keeps making syscalls concurrently: if the child had
       clobbered the parent's TLS IPC slot, these would interleave on
       the wrong channel and deadlock or corrupt the protocol */
    int last_seen = -1;
    for (int spins = 0; spins < 4000 && !child_done; spins++) {
        struct timespec ts = {0, 1 * 1000 * 1000};
        if (syscall(SYS_nanosleep, &ts, 0)) return 11;
        if (child_progress != last_seen) last_seen = child_progress;
    }
    if (child_done != 1) return 12;
    if (last_seen != 5 && child_progress != 5) return 13;
    printf("no-settls clone ok: child ran %d steps\n", child_progress);
    return 0;
}
"""


def test_no_settls_clone_under_sim(tmp_path):
    c = tmp_path / "goclone.c"
    c.write_text(GO_CLONE_C)
    binary = tmp_path / "goclone"
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    cfg = load_config_str(f"""
general: {{stop_time: 30s, seed: 5}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  gopher:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


def test_no_settls_clone_deterministic(tmp_path):
    """Same binary twice: simulated time interleaving of the no-SETTLS
    thread with its parent must be reproducible."""
    c = tmp_path / "goclone.c"
    c.write_text(GO_CLONE_C)
    binary = tmp_path / "goclone"
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)

    def run_once():
        cfg = load_config_str(f"""
general: {{stop_time: 30s, seed: 5}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  gopher:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
        mgr = Manager(cfg)
        stats = mgr.run()
        assert stats.process_failures == []
        return stats.events_executed

    assert run_once() == run_once()
