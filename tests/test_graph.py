import lzma

import numpy as np
import pytest

from shadow_tpu.core import simtime
from shadow_tpu.net import gml
from shadow_tpu.net.graph import (
    ONE_GBIT_SWITCH_GRAPH,
    GraphError,
    IpAssignment,
    NetworkGraph,
    build_routing,
    load_graph_text,
)


def _line_graph(loss_ab=0.1, loss_bc=0.2, extra=""):
    # a(0) -- b(1) -- c(2), self-loops everywhere
    return NetworkGraph.parse(
        f"""
graph [
  directed 0
  node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  node [ id 1 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  node [ id 2 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 1 target 1 latency "1 ms" ]
  edge [ source 2 target 2 latency "1 ms" ]
  edge [ source 0 target 1 latency "10 ms" packet_loss {loss_ab} ]
  edge [ source 1 target 2 latency "20 ms" packet_loss {loss_bc} ]
  {extra}
]
"""
    )


def test_gml_parser_basics():
    g = gml.parse('graph [ directed 1 node [ id 7 label "x" ] edge [ source 7 target 7 latency "1ms" ] ]')
    assert g.get("directed") == 1
    (node,) = g.get_all("node")
    assert node.get("id") == 7 and node.get("label") == "x"


def test_gml_comments_and_errors():
    g = gml.parse("graph [ # comment\n directed 0 ]")
    assert g.get("directed") == 0
    with pytest.raises(gml.GmlError):
        gml.parse("nothing here")
    with pytest.raises(gml.GmlError):
        gml.parse("graph [ key @bad ]")


def test_builtin_switch_graph():
    g = NetworkGraph.parse(ONE_GBIT_SWITCH_GRAPH)
    assert len(g.nodes) == 1
    assert g.nodes[0].bandwidth_up == 10**9
    lat, loss = g.compute_shortest_paths([0])
    assert lat[0, 0] == simtime.MILLISECOND
    assert loss[0, 0] == 0.0


def test_shortest_path_composition():
    g = _line_graph()
    lat, loss = g.compute_shortest_paths([0, 1, 2])
    assert lat[0, 2] == 30 * simtime.MILLISECOND
    # loss composes: 1 - (1-0.1)(1-0.2) = 0.28
    assert loss[0, 2] == pytest.approx(0.28, abs=1e-6)
    # symmetric (undirected)
    assert lat[2, 0] == lat[0, 2]
    # node->node uses the self-loop, not zero (graph/mod.rs:210-217)
    assert lat[1, 1] == simtime.MILLISECOND


def test_shortest_path_prefers_lower_latency_then_loss():
    # two a-c routes with equal latency, different loss: pick lower loss
    g = _line_graph(
        extra='edge [ source 0 target 2 latency "30 ms" packet_loss 0.5 ]'
    )
    lat, loss = g.compute_shortest_paths([0, 2])
    assert lat[0, 1] == 30 * simtime.MILLISECOND
    assert loss[0, 1] == pytest.approx(0.28, abs=1e-6)
    # and a strictly faster direct edge wins regardless of loss
    g2 = _line_graph(extra='edge [ source 0 target 2 latency "5 ms" packet_loss 0.9 ]')
    lat2, loss2 = g2.compute_shortest_paths([0, 2])
    assert lat2[0, 1] == 5 * simtime.MILLISECOND
    assert loss2[0, 1] == pytest.approx(0.9, abs=1e-6)


def test_unused_nodes_still_relay():
    # only endpoints used; middle node still relays traffic
    g = _line_graph()
    lat, _ = g.compute_shortest_paths([0, 2])
    assert lat.shape == (2, 2)
    assert lat[0, 1] == 30 * simtime.MILLISECOND


def test_missing_self_loop_is_error():
    g = NetworkGraph.parse(
        """
graph [ directed 0
  node [ id 0 ] node [ id 1 ]
  edge [ source 0 target 1 latency "10 ms" ]
]
"""
    )
    with pytest.raises(GraphError, match="self-loop"):
        g.compute_shortest_paths([0, 1])


def test_disconnected_is_error():
    g = NetworkGraph.parse(
        """
graph [ directed 0
  node [ id 0 ] node [ id 1 ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 1 target 1 latency "1 ms" ]
]
"""
    )
    with pytest.raises(GraphError, match="no path"):
        g.compute_shortest_paths([0, 1])


def test_direct_paths():
    g = _line_graph()
    lat, loss = g.get_direct_paths([0, 1])
    assert lat[0, 1] == 10 * simtime.MILLISECOND
    # 0-2 has no direct edge
    with pytest.raises(GraphError, match="exactly one edge"):
        g.get_direct_paths([0, 2])


def test_directed_graph():
    g = NetworkGraph.parse(
        """
graph [ directed 1
  node [ id 0 ] node [ id 1 ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 1 target 1 latency "1 ms" ]
  edge [ source 0 target 1 latency "10 ms" ]
  edge [ source 1 target 0 latency "99 ms" ]
]
"""
    )
    lat, _ = g.compute_shortest_paths([0, 1])
    assert lat[0, 1] == 10 * simtime.MILLISECOND
    assert lat[1, 0] == 99 * simtime.MILLISECOND


def test_edge_validation():
    with pytest.raises(GraphError, match="must not be 0"):
        NetworkGraph.parse(
            'graph [ node [ id 0 ] edge [ source 0 target 0 latency "0 ms" ] ]'
        )
    with pytest.raises(GraphError, match="doesn't exist"):
        NetworkGraph.parse(
            'graph [ node [ id 0 ] edge [ source 0 target 5 latency "1 ms" ] ]'
        )
    with pytest.raises(GraphError, match="latency"):
        NetworkGraph.parse("graph [ node [ id 0 ] edge [ source 0 target 0 ] ]")


def test_ip_assignment():
    ips = IpAssignment()
    first = ips.assign_auto(0)
    assert first == "11.0.0.1"
    # skip .0 and .255
    seen = {first}
    for _ in range(600):
        ip = ips.assign_auto(0)
        assert not ip.endswith(".0") and not ip.endswith(".255")
        assert ip not in seen
        seen.add(ip)
    ips.assign_manual("192.168.1.5", 3)
    with pytest.raises(GraphError, match="previously assigned"):
        ips.assign_manual("192.168.1.5", 4)
    assert ips.node_for("192.168.1.5") == 3
    assert ips.node_for("11.0.0.1") == 0
    assert ips.node_for("10.9.9.9") is None


def test_routing_info():
    g = _line_graph()
    ri = build_routing(g, [0, 2, 0], use_shortest_path=True)  # dup deduped
    assert ri.used_ids == [0, 2]
    p = ri.path(0, 2)
    assert p.latency_ns == 30 * simtime.MILLISECOND
    assert ri.get_smallest_latency_ns() == simtime.MILLISECOND  # self-loops
    ri.increment_packet_count(0, 2)
    ri.increment_packet_count(0, 2, 5)
    assert ri.packet_counters[0, 1] == 6


def test_compressed_graph(tmp_path):
    p = tmp_path / "g.gml.xz"
    with lzma.open(p, "wt") as fh:
        fh.write(ONE_GBIT_SWITCH_GRAPH)
    g = NetworkGraph.parse(load_graph_text(str(p)))
    assert len(g.nodes) == 1
