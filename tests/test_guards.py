"""Guard plane tests (shadow_tpu/guards/, docs/robustness.md):

- device conservation guards are a bitwise-invisible presence switch
  (the guards-on/guards-off parity matrix across rr x aqm x no_loss)
  and report ZERO violations on clean runs;
- deliberate state tamper / counter corruption is caught with per-host
  blame (seeded counter-tamper -> GuardError, populated violation
  report, emergency checkpoint with a valid MANIFEST, finalized
  telemetry);
- cross-plane reconciliation flags exactly the disagreeing (host,
  counter) pairs;
- the virtual-time progress detector trips on a deliberately stalled
  run and names the blocked host;
- the `guards:` / `strict:` config blocks parse and validate.
"""

import json
import os
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from shadow_tpu.core.config import (ConfigError,  # noqa: E402
                                    load_config_str)
from shadow_tpu.guards import (GUARD_CLOCK, GUARD_INGEST_FLOW,  # noqa: E402
                               GUARD_KEY_BUDGET, GUARD_RING_STRUCT,
                               GuardError, GuardLedger, GuardViolation,
                               HostWait, ProgressDetector, decode_bits,
                               make_guards, reconcile_fleet,
                               reconcile_per_host, summarize)
from shadow_tpu.tpu import ingest_rows, profiling  # noqa: E402
from shadow_tpu.tpu.plane import window_step  # noqa: E402

MS = 1_000_000


def _world(n=32, seed=0):
    return profiling.build_world(n, warmup_windows=2, seed=seed)


def _run_windows(world, n_windows, *, rr, aqm, no_loss, guards):
    state = world["state"]
    window = world["window"]
    params, root = world["params"], world["rng_root"]
    step = jax.jit(lambda st, sh, g: window_step(
        st, params, root, sh, window, rr_enabled=rr, router_aqm=aqm,
        no_loss=no_loss, guards=g))
    shift = jnp.int32(0)
    for _ in range(n_windows):
        out = step(state, shift, guards)
        if guards is not None:
            state, _delivered, _next, guards = out
        else:
            state, _delivered, _next = out
        shift = window
    return state, guards


def _assert_trees_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(la), np.asarray(lb))


# -- parity: guards are a bitwise-invisible presence switch ---------------

@pytest.mark.parametrize("rr,aqm,no_loss", [
    (False, False, False), (True, False, False),
    (False, True, False), (True, True, False),
    (False, False, True), (True, True, True),
])
def test_guards_parity_matrix(rr, aqm, no_loss):
    """guards=None and guards-threaded runs produce bitwise-identical
    simulation state, and the clean world records zero violations."""
    world = _world()
    s_off, _ = _run_windows(world, 5, rr=rr, aqm=aqm, no_loss=no_loss,
                            guards=None)
    s_on, g = _run_windows(world, 5, rr=rr, aqm=aqm, no_loss=no_loss,
                           guards=make_guards(32))
    _assert_trees_equal(s_off, s_on)
    summ = summarize(g)
    assert summ["clean"], summ
    assert summ["windows_checked"] == 5
    assert summ["checks_evaluated"] > 0


def test_ingest_rows_guard_parity_and_clean():
    world = _world()
    state = world["state"]
    N, CI = 32, world["ingress_cap"]
    deliv = world["delivered"]
    spawn_seq = jnp.full((N,), 10_000, jnp.int32)
    mask, dst, nbytes, seq, ctrl = profiling.respawn_batch(
        deliv, spawn_seq, jnp.int32(1), N, CI)
    plain = ingest_rows(state, dst, nbytes, seq, seq, ctrl, valid=mask)
    guarded, g = ingest_rows(state, dst, nbytes, seq, seq, ctrl,
                             valid=mask, guards=make_guards(N))
    _assert_trees_equal(plain, guarded)
    assert summarize(g)["clean"]


# -- device tamper detection ----------------------------------------------

def test_phantom_ring_slot_trips_ring_structure():
    """A phantom valid slot at the back of one ingress ring (the exact
    single-slot damage batched execution would hide) is caught at the
    next window with host blame and the window index."""
    world = _world()
    state = world["state"]
    CI = world["ingress_cap"]
    bad = state._replace(in_valid=state.in_valid.at[3, CI - 1].set(True))
    _s, _d, _n, g = window_step(
        bad, world["params"], world["rng_root"], jnp.int32(10 * MS),
        world["window"], rr_enabled=False, guards=make_guards(32))
    summ = summarize(g)
    assert not summ["clean"]
    assert summ["by_class"] == {"ring-structure": 1}
    assert summ["first_offenders"][0]["host_index"] == 3
    assert summ["first_offenders"][0]["first_window"] == 0


def test_negative_sort_key_trips_key_budget():
    """A negative priority in a live egress slot breaks the uint32
    packed-sort domain — the key-budget guard flags the host."""
    world = _world()
    state = world["state"]
    bad = state._replace(
        eg_valid=state.eg_valid.at[5, 0].set(True),
        eg_prio=state.eg_prio.at[5, 0].set(-7),
    )
    _s, _d, _n, g = window_step(
        bad, world["params"], world["rng_root"], jnp.int32(10 * MS),
        world["window"], rr_enabled=False, guards=make_guards(32))
    v = np.asarray(jax.device_get(g.violations))
    assert v[5] & GUARD_KEY_BUDGET


def test_clock_violation_sets_scalar_flag():
    world = _world()
    _s, _d, _n, g = window_step(
        world["state"], world["params"], world["rng_root"],
        jnp.int32(-5), world["window"], rr_enabled=False,
        guards=make_guards(32))
    assert int(jax.device_get(g.flags)) & GUARD_CLOCK
    assert "virtual-clock" in summarize(g)["scalar_flags"]


def test_decode_bits_names():
    assert decode_bits(0) == []
    assert decode_bits(GUARD_RING_STRUCT | GUARD_INGEST_FLOW) == [
        "ring-structure", "ingest-conservation"]


# -- reconciliation -------------------------------------------------------

def test_reconcile_per_host_agree_and_disagree():
    device = {"pkts_out": np.array([5, 3, 0], np.int64),
              "pkts_in": np.array([2, 2, 4], np.int64)}
    cpu = {"captured": np.array([5, 3, 0], np.int64),
           "released": np.array([2, 2, 4], np.int64)}
    pairs = (("pkts_out", "captured"), ("pkts_in", "released"))
    assert reconcile_per_host(1000, device, cpu, pairs,
                              ["a", "b", "c"]) == []
    cpu["released"][1] = 9  # one host's ledger disagrees
    found = reconcile_per_host(1000, device, cpu, pairs, ["a", "b", "c"])
    assert len(found) == 1
    v = found[0]
    assert (v.cls, v.check, v.host) == ("reconcile",
                                        "pkts_in-vs-released", "b")
    assert (v.expected, v.actual) == (9, 2)


def test_reconcile_per_host_caps_and_reports_truncation():
    n = 100
    device = {"pkts_out": np.arange(n, dtype=np.int64)}
    cpu = {"captured": np.arange(n, dtype=np.int64) + 1}  # all disagree
    found = reconcile_per_host(0, device, cpu,
                               (("pkts_out", "captured"),),
                               max_violations=8)
    assert len(found) == 9  # 8 + the truncation record
    assert found[-1].check == "per-host-mismatch-overflow"
    assert "92" in found[-1].detail


def test_reconcile_fleet():
    ok = reconcile_fleet(5, [("conservation", 10, 10, "d")])
    assert ok == []
    bad = reconcile_fleet(5, [("conservation", 10, 11, "leak")])
    assert len(bad) == 1 and bad[0].check == "conservation"


def test_guard_ledger_policies():
    ledger = GuardLedger(policies={"device": "warn",
                                   "reconcile": "abort"})
    v = GuardViolation(cls="device", check="x", time_ns=1)
    ledger.apply("device", [v])  # warn: records, no raise
    assert ledger.violations == [v]
    with pytest.raises(GuardError) as exc:
        ledger.apply("reconcile", [GuardViolation(
            cls="reconcile", check="y", time_ns=2)])
    assert exc.value.want_checkpoint is False
    ledger.policies["reconcile"] = "abort+checkpoint"
    with pytest.raises(GuardError) as exc:
        ledger.apply("reconcile", [GuardViolation(
            cls="reconcile", check="z", time_ns=3)])
    assert exc.value.want_checkpoint is True
    assert ledger.as_dict()["total"] == 3


# -- progress detection ---------------------------------------------------

def test_progress_detector_trips_after_budget_and_rearms():
    det = ProgressDetector(3)
    # warm-up observation establishes the clock; progress resets streak
    assert det.observe(10, events_delta=2, packets_delta=1) is None
    for t in (20, 30):
        assert det.observe(t, events_delta=0, packets_delta=0) is None
    diag = det.observe(40, events_delta=0, packets_delta=0)
    assert diag is not None
    assert diag.stalled_rounds == 3
    assert diag.first_stalled_ns == 20
    assert diag.window_start_ns == 40
    assert det.trips == 1
    # re-armed: the next stall needs a full fresh budget
    assert det.observe(50, events_delta=0, packets_delta=0) is None


def test_progress_detector_any_progress_resets():
    det = ProgressDetector(2)
    det.observe(1, events_delta=1, packets_delta=0)
    assert det.observe(2, events_delta=0, packets_delta=0) is None
    # a single executed event resets the streak
    assert det.observe(3, events_delta=1, packets_delta=0) is None
    assert det.observe(4, events_delta=0, packets_delta=0) is None
    assert det.observe(5, events_delta=0, packets_delta=0) is not None


def test_progress_detector_requires_time_advance():
    det = ProgressDetector(1)
    det.observe(7, events_delta=0, packets_delta=0)
    # same window start again: time did not advance, no stall counted
    assert det.observe(7, events_delta=0, packets_delta=0) is None
    assert det.observe(8, events_delta=0, packets_delta=0) is not None


def test_stall_diagnosis_describes_waiting_hosts():
    det = ProgressDetector(1)
    det.observe(0, events_delta=1, packets_delta=0)
    diag = det.observe(10, events_delta=0, packets_delta=0)
    diag.waiting = [HostWait("relay4", ["relay4.tgen.0"], None)]
    v = diag.to_violation()
    assert v.cls == "progress" and v.host == "relay4"
    assert "relay4.tgen.0" in v.detail
    assert "no queued events" in v.detail


# -- config ---------------------------------------------------------------

_BASE = ("general: {stop_time: 5s}\n"
         "network: {graph: {type: 1_gbit_switch}}\n"
         "hosts: {a: {network_node_id: 0}}\n")


def test_guards_config_block_parses():
    cfg = load_config_str(_BASE + """
guards:
  enabled: true
  device: warn
  reconcile: abort+checkpoint
  progress: off
  progress_rounds: 16
""")
    g = cfg.guards
    assert g.enabled and g.device == "warn"
    assert g.reconcile == "abort+checkpoint"
    # YAML 1.1 parses bare `off` as False; the policy field maps it back
    assert g.progress == "off"
    assert g.progress_rounds == 16
    assert g.active("device") and g.active("reconcile")
    assert not g.active("progress")
    # disabled master switch deactivates every class
    cfg2 = load_config_str(_BASE + "guards: {device: abort}\n")
    assert not cfg2.guards.active("device")


def test_guards_config_validation():
    with pytest.raises(ConfigError, match="guards.device"):
        load_config_str(_BASE + "guards: {device: explode}\n")
    with pytest.raises(ConfigError, match="progress_rounds"):
        load_config_str(_BASE + "guards: {progress_rounds: 0}\n")
    with pytest.raises(ConfigError, match="unknown option"):
        load_config_str(_BASE + "guards: {bogus: 1}\n")


def test_strict_config_parses():
    assert load_config_str(_BASE + "strict: true\n").strict
    assert not load_config_str(_BASE).strict
    with pytest.raises(ConfigError, match="strict"):
        load_config_str(_BASE + "strict: yes please\n")
    # general.progress stays a plain boolean (the off->policy mapping
    # must not leak onto it)
    cfg = load_config_str(
        _BASE.replace("stop_time: 5s", "stop_time: 5s, progress: off"))
    assert cfg.general.progress is False


# -- transport guard + reconciliation end-to-end --------------------------

_GUARDED_SIM = """
general: {{stop_time: 4s, seed: 7}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{use_tpu_transport: true, tpu_transport_mode: {mode},
               scheduler: serial}}
telemetry: {{enabled: true, interval: 1s, sink: {sink}, trace: off}}
guards: {{enabled: true{extra}}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
  client:
    network_node_id: 0
    processes:
    - {{path: udp-client, args: ["server", "9000", "4", "50"],
       start_time: 2s}}
"""


def _guarded_manager(tmp_path=None, mode="sync", extra="", sink="off"):
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(_GUARDED_SIM.format(mode=mode, extra=extra,
                                              sink=sink))
    return Manager(cfg, data_dir=str(tmp_path) if tmp_path else None)


@pytest.mark.parametrize("mode", ["sync", "mirrored"])
def test_guarded_transport_run_is_clean(mode, tmp_path):
    """A healthy guarded run: zero violations from the device guard,
    the harvest-boundary reconciliation, the teardown reconciliation,
    and the progress detector — and the CPU ledger equals the device
    counters exactly."""
    mgr = _guarded_manager(tmp_path, mode=mode)
    stats = mgr.run()
    assert stats.process_failures == []
    assert mgr.guard_violations == []
    report = mgr.transport.guard_report()
    assert report is not None and report["clean"], report
    ledger = mgr.transport.cpu_ledger()
    device = {k: np.asarray(jax.device_get(v), np.int64)
              for k, v in mgr.transport.telemetry_arrays().items()}
    assert np.array_equal(device["pkts_out"], ledger["captured"])
    assert np.array_equal(device["pkts_in"], ledger["released"])
    assert ledger["captured"].sum() == stats.packets_sent
    # the run-long report artifact records a clean run
    rep = json.load(open(tmp_path / "guards-report.json"))
    assert rep["clean"] and rep["total"] == 0


def test_counter_tamper_aborts_with_postmortem_bundle(
        tmp_path, monkeypatch):
    """The seeded counter-tamper proof: a device counter that reads 3
    high for one host trips reconciliation at the FIRST harvest
    boundary; under abort+checkpoint the run dies as a GuardError with
    host blame and the offending counter pair, leaves an emergency
    checkpoint with a valid MANIFEST, a populated guards-report.json,
    and a finalized telemetry sink."""
    from shadow_tpu.faults.checkpoint import load_checkpoint
    from shadow_tpu.tpu.transport import DeviceTransport

    orig = DeviceTransport.telemetry_arrays

    def tampered(self):
        out = orig(self)
        out["pkts_out"] = out["pkts_out"].at[0].add(3)
        return out

    monkeypatch.setattr(DeviceTransport, "telemetry_arrays", tampered)
    sink = str(tmp_path / "telemetry.jsonl")
    mgr = _guarded_manager(tmp_path, extra=", reconcile: abort+checkpoint",
                           sink=sink)
    with pytest.raises(GuardError) as exc:
        mgr.run()
    err = exc.value
    assert err.want_checkpoint
    assert err.violations[0].check == "pkts_out-vs-captured"
    assert err.violations[0].host == "server"
    # emergency checkpoint: present, MANIFEST checksums verify, and it
    # carries the violation ledger
    meta, _arrays = load_checkpoint(
        str(tmp_path / "checkpoints" / "emergency"))
    assert meta["reason"] == "emergency"
    assert meta["guards"]["total"] >= 1
    # populated violation report
    rep = json.load(open(tmp_path / "guards-report.json"))
    assert not rep["clean"] and rep["by_class"] == {"reconcile": 1}
    assert rep["violations"][0]["host"] == "server"
    # telemetry finalized: the sink holds the buffered heartbeats
    assert os.path.getsize(sink) > 0


def test_counter_tamper_plain_abort_skips_checkpoint(
        tmp_path, monkeypatch):
    """Plain `abort` dies with the report but opts out of the
    emergency checkpoint (abort+checkpoint is the postmortem bundle)."""
    from shadow_tpu.tpu.transport import DeviceTransport

    orig = DeviceTransport.telemetry_arrays

    def tampered(self):
        out = orig(self)
        out["pkts_in"] = out["pkts_in"].at[1].add(1)
        return out

    monkeypatch.setattr(DeviceTransport, "telemetry_arrays", tampered)
    mgr = _guarded_manager(tmp_path, extra=", reconcile: abort")
    with pytest.raises(GuardError) as exc:
        mgr.run()
    assert not exc.value.want_checkpoint
    assert not os.path.exists(tmp_path / "checkpoints" / "emergency")
    rep = json.load(open(tmp_path / "guards-report.json"))
    assert rep["total"] >= 1


def test_cli_exit_guard_is_5(tmp_path, monkeypatch):
    """EXIT_GUARD is 5 in the documented table, and the CLI maps a
    GuardError onto it (in-process main so the tamper monkeypatch
    holds)."""
    from shadow_tpu import cli
    from shadow_tpu.tpu.transport import DeviceTransport

    assert cli.EXIT_GUARD == 5

    orig = DeviceTransport.telemetry_arrays

    def tampered(self):
        out = orig(self)
        out["pkts_out"] = out["pkts_out"].at[0].add(2)
        return out

    monkeypatch.setattr(DeviceTransport, "telemetry_arrays", tampered)
    cfg = tmp_path / "sim.yaml"
    cfg.write_text(_GUARDED_SIM.format(
        mode="sync", extra=", reconcile: abort+checkpoint", sink="off")
        .replace("general: {stop_time: 4s, seed: 7}",
                 "general: {stop_time: 4s, seed: 7, data_directory: %s}"
                 % (tmp_path / "data")))
    rc = cli.main([str(cfg)])
    assert rc == 5
    assert (tmp_path / "data" / "guards-report.json").is_file()


# -- progress detection end-to-end ----------------------------------------

class _PhantomTransport:
    """A next-event source that keeps advertising pending device work
    which never materializes — the zero-progress livelock the detector
    exists to catch."""

    divergence_count = 0
    verified_windows = 0
    in_flight = 3

    def __init__(self):
        self.next_pending_abs = None

    def release(self, start_ns, end_ns, horizon_ns=None,
                runahead_ns=None, stop_ns=None):
        self.next_pending_abs = end_ns + 1_000_000  # always "1ms away"

    def finish_round(self, start_ns, end_ns):
        pass

    def finalize(self):
        pass

    def guard_report(self):
        return None


_STALL_SIM = """
general: {{stop_time: 3s, seed: 3, heartbeat_interval: null}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{scheduler: serial, host_heartbeat_interval: null}}
guards: {{enabled: true, progress: {policy}, progress_rounds: 40}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
"""


def _stalled_manager(policy):
    from shadow_tpu.core.manager import Manager

    mgr = Manager(load_config_str(_STALL_SIM.format(policy=policy)))
    # the deliberately stalled world: the server blocks on recv forever
    # while a phantom next-event source keeps the round loop spinning
    mgr.transport = _PhantomTransport()
    return mgr


def test_manager_detects_stalled_host_and_aborts():
    mgr = _stalled_manager("abort")
    with pytest.raises(GuardError) as exc:
        mgr.run()
    v = exc.value.violations[0]
    assert v.cls == "progress" and v.check == "zero-progress-livelock"
    # the diagnosis names the blocked host, its process, and the
    # phantom device population
    assert v.host == "server"
    assert "server.udp-echo-server.0" in v.detail
    assert "device in-flight: 3" in v.detail
    assert "40 consecutive rounds" in v.detail


def test_manager_stall_warn_policy_records_and_completes():
    mgr = _stalled_manager("warn")
    stats = mgr.run()
    assert stats.process_failures == []  # the server is expected running
    assert mgr.guard_violations, "warn policy must still record the stall"
    assert all(v.cls == "progress" for v in mgr.guard_violations)
    assert mgr._progress.trips >= 1


# -- strict mode ----------------------------------------------------------

_FLOW_GML = """
      graph [
        node [ id 0 bandwidth_up "1 Gbit" bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "5 ms" packet_loss 0.0 ]
      ]
"""


def _flow_cfg(extra=""):
    return ("general: {stop_time: 10s, seed: 1}\n"
            "experimental: {use_flow_engine: true}\n"
            + extra +
            "network:\n  graph:\n    type: gml\n    inline: |\n"
            + _FLOW_GML +
            "hosts:\n"
            "  server:\n    network_node_id: 0\n    processes:\n"
            "    - {path: tgen-server, args: ['8888'], start_time: 1s,\n"
            "       expected_final_state: running}\n"
            "  client0:\n    network_node_id: 0\n    processes:\n"
            "    - {path: tgen-client, args: ['server', '8888', '50000',"
            " '1'], start_time: 2s}\n")


@pytest.mark.parametrize("extra,needle", [
    ("telemetry: {enabled: true}\n", "telemetry"),
    ("faults: {watchdog: 10s}\n", "faults"),
    ("faults: {events: [{at: 1s, kind: iface_down, host: server}]}\n",
     "faults"),
    ("guards: {enabled: true}\n", "guards"),
])
def test_strict_promotes_flow_engine_combos(extra, needle, caplog):
    import logging

    from shadow_tpu.core.manager import Manager

    # default: log-and-ignore — the Manager builds, a warning names the
    # dropped feature
    with caplog.at_level(logging.WARNING, logger="shadow_tpu.manager"):
        Manager(load_config_str(_flow_cfg(extra)))
    assert any(needle in r.message and "not supported" in r.message
               for r in caplog.records)
    # strict: the same combo is a ConfigError (exit 2) at build time
    with pytest.raises(ConfigError, match="strict mode"):
        Manager(load_config_str("strict: true\n" + _flow_cfg(extra)))


def test_plane_kernel_no_op_warns_and_strict_refuses(caplog):
    """`experimental.plane_kernel: pallas` is validated by the config
    but never consulted by Manager-driven runs (the use_tpu_transport
    caveat in docs/performance.md): the Manager must say so loudly
    instead of silently no-op-ing, and `strict: true` must refuse."""
    import logging

    from shadow_tpu.core.manager import Manager

    cfg = ("general: {stop_time: 1s, seed: 1}\n"
           "experimental: {plane_kernel: pallas}\n"
           "network:\n  graph:\n    type: 1_gbit_switch\n"
           "hosts:\n  peer0:\n    network_node_id: 0\n")
    with caplog.at_level(logging.WARNING, logger="shadow_tpu.manager"):
        Manager(load_config_str(cfg))
    assert any("plane_kernel" in r.message and "not consulted" in r.message
               for r in caplog.records)
    with pytest.raises(ConfigError, match="strict mode.*plane_kernel"):
        Manager(load_config_str("strict: true\n" + cfg))
    # the default kernel stays silent — no spurious warning
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="shadow_tpu.manager"):
        Manager(load_config_str(cfg.replace("pallas", "xla")))
    assert not any("plane_kernel" in r.message for r in caplog.records)


# -- the device retransmits producer (telemetry satellite) ----------------

def test_transport_retransmits_producer_feeds_harvest():
    """`DeviceTransport.attach_tcp_source` + `tcp.retransmits_by_host`
    + `telemetry.add_retransmits` wire the device `retransmits` field
    end to end: per-connection counters reduce to per-host totals and
    ride the harvester into per-host heartbeat lines."""
    import io

    from shadow_tpu.analysis.jaxpr_audit import _StubHost, _StubRouting
    from shadow_tpu.telemetry import TelemetryHarvester
    from shadow_tpu.tpu import tcp as dtcp
    from shadow_tpu.tpu.transport import DeviceTransport

    n = 4
    dt = DeviceTransport([_StubHost(i + 1, i % 3) for i in range(n)],
                         _StubRouting(3), None, egress_cap=8,
                         ingress_cap=8, mode="sync", compact_cap=16)
    plane = dtcp.make_tcp_plane(6, reass_slots=4)
    plane = plane._replace(retransmit_count=jnp.asarray(
        [2, 0, 1, 3, 0, 5], jnp.int32))
    conn_host = jnp.asarray([0, 0, 1, 3, 3, 3], jnp.int32)
    dt.attach_tcp_source(lambda: plane, conn_host)

    arrays = dt.telemetry_arrays()
    assert np.array_equal(np.asarray(arrays["retransmits"]),
                          [2, 1, 0, 8])

    sink = io.StringIO()
    h = TelemetryHarvester(interval_ns=1_000, sink=sink)
    h.tick(1_000, device=arrays)
    h.finalize()
    lines = [json.loads(line) for line in
             sink.getvalue().strip().splitlines()]
    sim = [r for r in lines if r["type"] == "sim"][0]
    assert sim["device_totals"]["retransmits"] == 11
    host4 = [r for r in lines
             if r["type"] == "host" and r["host_id"] == 4][0]
    assert host4["device"]["retransmits"] == 8
