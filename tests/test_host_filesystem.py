"""Per-host filesystem view + the round-4 syscall-breadth batch.

Parity targets: reference per-host data dirs (`regular_file.c:277-329`,
host data dirs in `process.rs`) — managed processes start in THEIR
host's data directory so relative paths are host-local — plus the
virtualized identity/rlimit/scheduling families (deterministic results
independent of the invoking machine) and virtual-fd guards on
mmap/sendfile.
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")


def _compile(tmp_path, name, src):
    c = tmp_path / f"{name}.c"
    c.write_text(src)
    binary = tmp_path / name
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    return str(binary)


WRITER_C = r"""
#include <stdio.h>
#include <string.h>
#include <unistd.h>

int main(int argc, char **argv) {
    /* relative path: must land in THIS host's data dir */
    FILE *f = fopen("collide.txt", "w");
    if (!f) return 1;
    fprintf(f, "%s\n", argv[1]);
    fclose(f);
    char cwd[4096];
    if (!getcwd(cwd, sizeof cwd)) return 2;
    /* the cwd must name this host's directory */
    if (!strstr(cwd, argv[1])) return 3;
    return 0;
}
"""


def test_relative_paths_are_host_local(tmp_path):
    """Two hosts writing the same relative filename do NOT collide: each
    process starts in its own per-host data dir (VERDICT r3 item #4's
    'done' criterion)."""
    binary = _compile(tmp_path, "writer", WRITER_C)
    data_dir = tmp_path / "shadow.data"
    cfg = load_config_str(f"""
general: {{stop_time: 5s, seed: 3}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: ["alpha"], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
  beta:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: ["beta"], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg, data_dir=str(data_dir)).run()
    assert stats.process_failures == [], stats.process_failures
    a = (data_dir / "hosts" / "alpha" / "collide.txt").read_text().strip()
    b = (data_dir / "hosts" / "beta" / "collide.txt").read_text().strip()
    assert (a, b) == ("alpha", "beta")


SYSCALL_BATCH_C = r"""
#include <errno.h>
#include <sched.h>
#include <stdio.h>
#include <sys/mman.h>
#include <sys/mount.h>
#include <sys/resource.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <sys/wait.h>
#include <unistd.h>

int main(void) {
    /* deterministic simulated identity: 1000/1000 regardless of the
       real uid the simulator runs as (root in CI, a user elsewhere) */
    if (getuid() != 1000 || geteuid() != 1000) return 10;
    if (getgid() != 1000 || getegid() != 1000) return 11;
    if (setuid(1000) != 0) return 12;
    if (setuid(0) != -1 || errno != EPERM) return 13;
    gid_t g[8];
    if (getgroups(8, g) != 1 || g[0] != 1000) return 14;

    /* visible fd limit covers the virtual range (1024), regardless of
       the 700-fd kernel cap on the native table */
    struct rlimit rl;
    if (getrlimit(RLIMIT_NOFILE, &rl)) return 20;
    if (rl.rlim_cur != 1024 || rl.rlim_max != 1024) return 21;
    /* lowering is allowed, raising back above the hard limit is not */
    rl.rlim_cur = 512;
    if (setrlimit(RLIMIT_NOFILE, &rl)) return 22;
    rl.rlim_cur = rl.rlim_max = 4096;
    if (setrlimit(RLIMIT_NOFILE, &rl) != -1 || errno != EPERM) return 23;

    /* scheduling: fixed nice 0, SCHED_OTHER (glibc getpriority converts
       the kernel's 20-nice encoding back to the nice value) */
    errno = 0;
    int prio = getpriority(PRIO_PROCESS, 0);
    if ((prio == -1 && errno) || prio != 0) return 30;
    if (setpriority(PRIO_PROCESS, 0, 5)) return 32;      /* raise nice */
    if (getpriority(PRIO_PROCESS, 0) != 5) return 33;
    if (setpriority(PRIO_PROCESS, 0, 2) != -1 || errno != EACCES)
        return 34;                                       /* lowering: CAP */
    if (sched_getscheduler(0) != SCHED_OTHER) return 31;

    /* privileged ops are deterministically denied */
    if (chroot("/") != -1 || errno != EPERM) return 40;
    struct timeval tv = {0, 0};
    if (settimeofday(&tv, 0) != -1 || errno != EPERM) return 41;

    /* a virtual fd (socket) must never reach a native mmap */
    int s = socket(AF_INET, SOCK_STREAM, 0);
    if (s < 0) return 50;
    void *p = mmap(0, 4096, PROT_READ, MAP_SHARED, s, 0);
    if (p != MAP_FAILED || errno != ENODEV) return 51;
    /* sendfile into a virtual socket: EINVAL -> app fallback path */
    if (sendfile(s, 0, 0, 16) != -1 || errno != EINVAL) return 52;
    /* dup2 of a virtual fd past the visible limit: EBADF like Linux */
    if (dup2(s, 5000) != -1 || errno != EBADF) return 53;
    /* the lowered soft limit (512, set above) is inherited by fork */
    pid_t pid = fork();
    if (pid == 0) {
        struct rlimit crl;
        if (getrlimit(RLIMIT_NOFILE, &crl)) _exit(1);
        _exit(crl.rlim_cur == 512 ? 0 : 2);
    }
    int st;
    if (waitpid(pid, &st, 0) != pid) return 54;
    if (!WIFEXITED(st) || WEXITSTATUS(st)) return 55;

    /* mlock family: deterministic no-op success */
    static char page[4096];
    if (mlock(page, sizeof page)) return 60;
    if (munlockall()) return 61;

    /* anonymous mmap still works natively through the validated path */
    p = mmap(0, 8192, PROT_READ | PROT_WRITE,
             MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (p == MAP_FAILED) return 62;
    ((char *)p)[100] = 7;
    if (munmap(p, 8192)) return 63;
    if (munmap((char *)p + 1, 4096) != -1 || errno != EINVAL) return 64;
    return 0;
}
"""


def test_syscall_breadth_batch(tmp_path):
    """The round-4 handler batch end-to-end in one managed binary:
    identity, rlimits, scheduling, privileged-op denial, virtual-fd mmap
    and sendfile guards, mlock no-ops, mapping validation."""
    binary = _compile(tmp_path, "sysbatch", SYSCALL_BATCH_C)
    cfg = load_config_str(f"""
general: {{stop_time: 5s, seed: 3}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


def test_dispatch_table_breadth():
    """VERDICT r3 item #5's 'done' criterion: >= 120 dispatch-table
    entries (the reference's table holds ~160,
    `handler/mod.rs:357-496`)."""
    from shadow_tpu.process.syscall_handler import SyscallHandler

    assert len(SyscallHandler._HANDLERS) >= 120
