import pytest

from shadow_tpu.core.config import QDiscMode
from shadow_tpu.core.rng import Xoshiro256pp
from shadow_tpu.net.dns import Dns, DnsError
from shadow_tpu.net.interface import NetworkInterface, WILDCARD_PEER
from shadow_tpu.net.namespace import (
    EPHEMERAL_PORT_MAX,
    EPHEMERAL_PORT_MIN,
    NetworkNamespace,
)
from shadow_tpu.net.packet import Packet, PacketStatus, Protocol


class FakeSocket:
    def __init__(self):
        self.outq = []
        self.inq = []

    def pull_out_packet(self):
        return self.outq.pop(0) if self.outq else None

    def peek_next_priority(self):
        return self.outq[0].priority if self.outq else None

    def push_in_packet(self, packet):
        self.inq.append(packet)


def _pkt(src_port=1, dst_port=80, prio=0, proto=Protocol.UDP):
    return Packet(
        proto, ("11.0.0.1", src_port), ("11.0.0.2", dst_port), b"data", priority=prio
    )


def test_fifo_qdisc_orders_by_priority():
    nic = NetworkInterface("11.0.0.1", QDiscMode.FIFO)
    a, b = FakeSocket(), FakeSocket()
    a.outq = [_pkt(prio=5), _pkt(prio=6)]
    b.outq = [_pkt(prio=1), _pkt(prio=9)]
    nic.add_data_source(a)
    nic.add_data_source(b)
    order = [nic.pop().priority for _ in range(4)]
    assert order == [1, 5, 6, 9]
    assert nic.pop() is None


def test_rr_qdisc_alternates_sockets():
    nic = NetworkInterface("11.0.0.1", QDiscMode.ROUND_ROBIN)
    a, b = FakeSocket(), FakeSocket()
    a.outq = [_pkt(src_port=1), _pkt(src_port=1), _pkt(src_port=1)]
    b.outq = [_pkt(src_port=2)]
    nic.add_data_source(a)
    nic.add_data_source(b)
    srcs = [nic.pop().src[1] for _ in range(4)]
    assert srcs == [1, 2, 1, 1]


def test_receive_delivery_exact_then_wildcard():
    nic = NetworkInterface("11.0.0.2")
    listener, child = FakeSocket(), FakeSocket()
    nic.associate(listener, Protocol.TCP, 80)  # wildcard peer
    nic.associate(child, Protocol.TCP, 80, peer=("11.0.0.1", 5))
    p_known = _pkt(src_port=5, proto=Protocol.TCP)
    p_new = _pkt(src_port=7, proto=Protocol.TCP)
    nic.push(p_known)
    nic.push(p_new)
    assert child.inq == [p_known]
    assert listener.inq == [p_new]


def test_receive_no_association_drops():
    nic = NetworkInterface("11.0.0.2")
    p = _pkt()
    nic.push(p)
    assert PacketStatus.RCV_INTERFACE_DROPPED in p.statuses


def test_double_association_rejected():
    nic = NetworkInterface("11.0.0.2")
    s = FakeSocket()
    nic.associate(s, Protocol.TCP, 80)
    with pytest.raises(ValueError, match="association exists"):
        nic.associate(FakeSocket(), Protocol.TCP, 80)
    nic.disassociate(Protocol.TCP, 80)
    nic.associate(s, Protocol.TCP, 80)  # ok after disassociate


def test_namespace_interfaces_and_ports():
    ns = NetworkNamespace("11.0.0.5")
    assert ns.interface_for("127.0.0.1") is ns.localhost
    assert ns.interface_for("11.0.0.5") is ns.internet
    assert ns.interface_for("9.9.9.9") is None
    rng = Xoshiro256pp(1)
    port = ns.get_random_free_port(Protocol.TCP, rng)
    assert EPHEMERAL_PORT_MIN <= port <= EPHEMERAL_PORT_MAX
    # binding 0.0.0.0 takes the port on both interfaces
    ns.associate(FakeSocket(), Protocol.TCP, "0.0.0.0", port)
    assert not ns.is_port_free(Protocol.TCP, port)
    port2 = ns.get_random_free_port(Protocol.TCP, rng)
    assert port2 != port


def test_namespace_port_determinism():
    a = NetworkNamespace("11.0.0.5")
    b = NetworkNamespace("11.0.0.5")
    ra, rb = Xoshiro256pp(7), Xoshiro256pp(7)
    pa = [a.get_random_free_port(Protocol.UDP, ra) for _ in range(20)]
    pb = [b.get_random_free_port(Protocol.UDP, rb) for _ in range(20)]
    assert pa == pb


def test_dns():
    dns = Dns()
    dns.register("server", "11.0.0.1")
    dns.register("client1", "11.0.0.2")
    assert dns.name_to_ip("server") == "11.0.0.1"
    assert dns.name_to_ip("localhost") == "127.0.0.1"
    assert dns.ip_to_name("11.0.0.2") == "client1"
    assert dns.name_to_ip("nope") is None
    with pytest.raises(DnsError):
        dns.register("server", "11.0.0.9")
    with pytest.raises(DnsError):
        dns.register("other", "11.0.0.1")
    hosts = dns.hosts_file()
    assert "127.0.0.1 localhost" in hosts
    assert "11.0.0.1 server" in hosts
    dns.deregister("server")
    assert dns.name_to_ip("server") is None
