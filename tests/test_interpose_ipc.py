"""Native runtime plane tests: shared-memory blocks crossing a real process
boundary, futex channel rendezvous, shim event round-trips, writer-close
semantics (parity model: reference shmem/scchannel/ipc unit tests +
ChildPidWatcher close behavior).
"""

import ctypes
import os
import signal
import struct
import sys

import pytest

from shadow_tpu import interpose
from shadow_tpu.interpose import (
    EVENT_PROCESS_DEATH,
    EVENT_SYSCALL,
    EVENT_SYSCALL_COMPLETE,
    IpcChannel,
    SharedBlock,
    ShimEvent,
)


@pytest.fixture(scope="module")
def lib():
    return interpose.load()


def test_layout_contract(lib):
    """ctypes structs must match the C++ layout exactly."""
    assert ctypes.sizeof(ShimEvent) == lib.shim_event_sizeof()
    assert lib.ipc_sizeof() >= 2 * 64  # two cache-aligned channels


def test_shmem_roundtrip_same_process(lib):
    b = SharedBlock(size=4096)
    try:
        handle = b.serialize()
        assert handle.startswith("/shadow_tpu_shm_")
        ctypes.memmove(b.addr, b"hello shmem", 11)
        b2 = SharedBlock(handle=handle)
        data = ctypes.string_at(b2.addr, 11)
        assert data == b"hello shmem"
        # writes through the second mapping appear in the first
        ctypes.memmove(b2.addr, b"HELLO", 5)
        assert ctypes.string_at(b.addr, 11) == b"HELLO shmem"
        b2.free()
    finally:
        b.free()


def test_ipc_cross_process_syscall_roundtrip(lib):
    """Fork a real child ('the shim side'); exchange syscall events over the
    futex channels through shared memory — the managed_thread resume loop in
    miniature (`managed_thread.rs:185-322`)."""
    ipc = IpcChannel.create()
    handle = ipc.block.serialize()

    pid = os.fork()
    if pid == 0:
        # child: the shim side
        try:
            shim = IpcChannel.attach(handle)
            for _ in range(3):
                # "make a syscall": send nr + args, await completion
                ev = ShimEvent()
                ev.kind = EVENT_SYSCALL
                ev.sim_time_ns = 42
                ev.u.syscall.number = 39  # getpid
                shim.send_to_shadow(ev)
                reply = shim.recv_from_shadow()
                assert reply is not None
                assert reply.kind == EVENT_SYSCALL_COMPLETE
                assert reply.u.complete.retval == 1000
            death = ShimEvent()
            death.kind = EVENT_PROCESS_DEATH
            shim.send_to_shadow(death)
            os._exit(0)
        except BaseException:
            os._exit(1)

    # parent: the shadow side
    handled = 0
    while True:
        ev = ipc.recv_from_shim()
        assert ev is not None
        if ev.kind == EVENT_PROCESS_DEATH:
            break
        assert ev.kind == EVENT_SYSCALL
        assert ev.u.syscall.number == 39
        assert ev.sim_time_ns == 42
        reply = ShimEvent()
        reply.kind = EVENT_SYSCALL_COMPLETE
        reply.u.complete.retval = 1000
        reply.u.complete.restartable = 1
        ipc.send_to_shim(reply)
        handled += 1
    _, status = os.waitpid(pid, 0)
    assert os.waitstatus_to_exitcode(status) == 0
    assert handled == 3
    ipc.block.free()


def test_writer_close_unblocks_reader(lib):
    """A dying 'managed process' closes the channel; the blocked shadow-side
    recv returns closed instead of hanging (ChildPidWatcher semantics,
    `managed_thread.rs:444-447`)."""
    ipc = IpcChannel.create()
    handle = ipc.block.serialize()

    pid = os.fork()
    if pid == 0:
        shim = IpcChannel.attach(handle)
        shim.close()  # abrupt death: close both directions, send nothing
        os._exit(0)

    got = ipc.recv_from_shim()  # blocks on the futex until the close wakes it
    assert got is None  # WriterIsClosed
    os.waitpid(pid, 0)
    ipc.block.free()


def test_shmem_cleanup_ignores_live_blocks(lib):
    b = SharedBlock(size=256)
    try:
        removed = lib.shmem_cleanup()
        # our own (live-pid) block must survive
        b2 = SharedBlock(handle=b.serialize())
        b2.free()
        assert removed >= 0
    finally:
        b.free()


def test_send_on_closed_channel_fails_fast(lib):
    """Sending to a dead peer returns an error instead of blocking forever."""
    ipc = IpcChannel.create()
    ipc.close()
    ev = ShimEvent()
    ev.kind = EVENT_SYSCALL
    with pytest.raises(OSError):
        ipc.send_to_shim(ev)
    ipc.block.free()


def test_preload_chain_is_single_entry():
    """Reference preload-injector parity (`src/lib/preload-injector/`):
    LD_PRELOAD lists ONE combined library; the shim rides in as a
    DT_NEEDED dependency (its symbols never interpose), pulled by a
    constructor-only injector."""
    import subprocess

    from shadow_tpu import interpose
    from shadow_tpu.process.managed import _preload_chain

    interpose.build()  # a clean checkout has no .so yet
    for ssl in (False, True):
        chain = _preload_chain(ssl)
        assert " " not in chain, chain  # exactly one entry
        out = subprocess.run(["ldd", chain], capture_output=True,
                             text=True).stdout
        shim_line = [ln for ln in out.splitlines()
                     if "libshadow_shim.so" in ln]
        assert shim_line and "=> /" in shim_line[0], out  # RESOLVES
