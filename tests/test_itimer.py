"""ITIMER_REAL / alarm(2) in simulated time for managed binaries.

Parity: reference `handler/time.rs:31-100` (ITIMER_REAL only, SIGALRM on
expiry, remaining-time reporting) + `src/test/signal`-style alarm tests.
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")


def _compile(tmp_path, name, src):
    c = tmp_path / f"{name}.c"
    c.write_text(src)
    binary = tmp_path / name
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    return str(binary)


def _run(binary, args=(), stop="30s"):
    arglist = ", ".join(f'"{a}"' for a in args)
    cfg = load_config_str(f"""
general: {{stop_time: {stop}, seed: 3}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: [{arglist}], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


ALARM_C = r"""
#include <signal.h>
#include <time.h>
#include <unistd.h>

static volatile sig_atomic_t fired;
static void on_alarm(int sig) { (void)sig; fired = 1; }

static long long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(void) {
    struct sigaction sa = {0};
    sa.sa_handler = on_alarm;
    if (sigaction(SIGALRM, &sa, 0)) return 60;
    long long t0 = now_ns();
    alarm(2);
    /* a second alarm() must report the remaining seconds of the first */
    unsigned prev = alarm(5);
    if (prev == 0 || prev > 2) return 61;
    /* pause until SIGALRM: in simulated time this is exactly 5s away */
    while (!fired) pause();
    long long dt = now_ns() - t0;
    if (dt < 4900000000LL) return 62;  /* fired too early */
    if (dt > 20000000000LL) return 63; /* or virtual time ran away */
    return 0;
}
"""


SETITIMER_C = r"""
#include <signal.h>
#include <string.h>
#include <sys/time.h>
#include <unistd.h>

static volatile sig_atomic_t ticks;
static void on_alarm(int sig) { (void)sig; ticks++; }

int main(void) {
    struct sigaction sa = {0};
    sa.sa_handler = on_alarm;
    if (sigaction(SIGALRM, &sa, 0)) return 70;
    struct itimerval it;
    memset(&it, 0, sizeof it);
    it.it_value.tv_usec = 250000;    /* first fire at 250ms */
    it.it_interval.tv_usec = 250000; /* then every 250ms */
    if (setitimer(ITIMER_REAL, &it, 0)) return 71;
    /* getitimer must see a pending value <= 250ms */
    struct itimerval cur;
    if (getitimer(ITIMER_REAL, &cur)) return 72;
    if (cur.it_value.tv_sec != 0 || cur.it_value.tv_usec > 250000) return 73;
    if (cur.it_interval.tv_usec != 250000) return 74;
    while (ticks < 4) pause();
    /* disarm and confirm */
    memset(&it, 0, sizeof it);
    if (setitimer(ITIMER_REAL, &it, 0)) return 75;
    if (getitimer(ITIMER_REAL, &cur)) return 76;
    if (cur.it_value.tv_sec || cur.it_value.tv_usec) return 77;
    return 0;
}
"""


TIMES_C = r"""
#include <sys/times.h>
#include <unistd.h>

int main(void) {
    struct tms t;
    clock_t a = times(&t);
    if (a == (clock_t)-1) return 80;
    sleep(2); /* 2 simulated seconds */
    clock_t b = times(&t);
    long dt = (long)(b - a);
    /* 2 sim seconds at 100 ticks/s, allowing syscall-latency slack */
    if (dt < 195 || dt > 400) return 81;
    return 0;
}
"""


def test_alarm_interrupts_pause_in_sim_time(tmp_path):
    _run(_compile(tmp_path, "talarm", ALARM_C))


def test_setitimer_interval_ticks(tmp_path):
    _run(_compile(tmp_path, "titimer", SETITIMER_C))


def test_times_returns_sim_ticks(tmp_path):
    _run(_compile(tmp_path, "ttimes", TIMES_C))


def test_alarm_default_disposition_terminates(tmp_path):
    """No handler installed: SIGALRM's default action kills the process
    at the simulated expiry instant."""
    src = r"""
#include <unistd.h>
int main(void) { alarm(1); for (;;) pause(); }
"""
    binary = _compile(tmp_path, "talarmdie", src)
    cfg = load_config_str(f"""
general: {{stop_time: 30s, seed: 3}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: [], start_time: 1s,
       expected_final_state: {{signaled: 14}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


MT_SIGNAL_C = r"""
#include <errno.h>
#include <pthread.h>
#include <signal.h>
#include <time.h>
#include <unistd.h>

static volatile sig_atomic_t fired;
static volatile int worker_eintr;
static void on_alarm(int sig) { (void)sig; fired = 1; }

static void *worker(void *arg) {
    (void)arg;
    struct timespec ts = {3, 0};
    /* must NOT be interrupted: the signal goes to one thread only */
    if (nanosleep(&ts, 0) == -1 && errno == EINTR) worker_eintr = 1;
    return 0;
}

int main(void) {
    struct sigaction sa = {0};
    sa.sa_handler = on_alarm; /* no SA_RESTART */
    if (sigaction(SIGALRM, &sa, 0)) return 100;
    pthread_t t;
    if (pthread_create(&t, 0, worker, 0)) return 101;
    alarm(1);
    struct timespec ts = {10, 0};
    int rc = nanosleep(&ts, 0);
    /* main (lowest tindex) is the deterministic recipient: EINTR here */
    if (!(rc == -1 && errno == EINTR)) return 102;
    if (!fired) return 103;
    if (pthread_join(t, 0)) return 104;
    if (worker_eintr) return 105; /* exactly one thread interrupted */
    return 0;
}
"""


def test_signal_interrupts_exactly_one_thread(tmp_path):
    """A process-directed SIGALRM must EINTR a single parked thread
    (deterministically the lowest tindex), not every blocked syscall in
    the process — signal(7) one-recipient semantics."""
    c = tmp_path / "mtsig.c"
    c.write_text(MT_SIGNAL_C)
    binary = tmp_path / "mtsig"
    subprocess.run([CC, "-O1", "-pthread", "-o", str(binary), str(c)],
                   check=True)
    _run(str(binary))
