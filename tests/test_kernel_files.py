"""Kernel file-type tests: pipes, eventfd, timerfd, epoll, descriptor
table — including in-sim use through processes (parity model:
`src/test/{pipe,eventfd,timerfd,epoll,dup}`).
"""

import pytest

from shadow_tpu.core import simtime
from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager
from shadow_tpu.kernel import errors
from shadow_tpu.kernel.descriptor import DescriptorTable
from shadow_tpu.kernel.epoll import Epoll, EpollEvents
from shadow_tpu.kernel.eventfd import EventFd
from shadow_tpu.kernel.pipe import PIPE_CAPACITY, make_pipe
from shadow_tpu.kernel.status import FileState
from shadow_tpu.process.process import SimProcess

MS = simtime.MILLISECOND


def _host():
    cfg = load_config_str(
        """
general: {stop_time: 10s, seed: 2}
network: {graph: {type: 1_gbit_switch}}
hosts:
  a: {network_node_id: 0}
"""
    )
    mgr = Manager(cfg)
    return mgr, mgr.hosts[0]


# -- pipes ------------------------------------------------------------


def test_pipe_roundtrip_and_eof():
    r, w = make_pipe()
    assert w.send(b"hello") == 5
    assert r.state & FileState.READABLE
    assert r.recv(3) == b"hel"
    assert r.recv(100) == b"lo"
    with pytest.raises(errors.Blocked):
        r.recv(1)
    w.close()
    assert r.recv(1) == b""  # EOF


def test_pipe_capacity_and_epipe():
    r, w = make_pipe()
    assert w.send(b"x" * (PIPE_CAPACITY + 5)) == PIPE_CAPACITY
    with pytest.raises(errors.Blocked):
        w.send(b"y")
    assert not (w.state & FileState.WRITABLE)
    r.recv(10)
    assert w.state & FileState.WRITABLE
    r.close()
    with pytest.raises(errors.SyscallError) as e:
        w.send(b"z")
    assert e.value.errno == errors.EPIPE


# -- eventfd ----------------------------------------------------------


def test_eventfd_counter_and_semaphore():
    e = EventFd()
    with pytest.raises(errors.Blocked):
        e.read_value()
    e.write_value(3)
    e.write_value(4)
    assert e.read_value() == 7
    with pytest.raises(errors.Blocked):
        e.read_value()

    s = EventFd(2, semaphore=True)
    assert s.read_value() == 1
    assert s.read_value() == 1
    with pytest.raises(errors.Blocked):
        s.read_value()


def test_eventfd_blocked_write_waits_for_read():
    """A write that would overflow parks on a state bit that is OFF until a
    read makes room for that write's value — not on the always-on WRITABLE
    bit (which would spin the retry loop at the same sim time)."""
    from shadow_tpu.kernel.status import FileState

    e = EventFd(0)
    big = (1 << 64) - 3  # fills the counter completely
    e.write_value(big)
    with pytest.raises(errors.Blocked) as bi:
        e.write_value(5)
    mask = bi.value.state_mask
    # the armed condition must NOT be satisfied yet
    assert not (e.state & mask)
    # a read drains the counter; now the blocked write's value fits
    assert e.read_value() == big
    assert e.state & mask
    e.write_value(5)
    assert e.read_value() == 5
    # poll-visible WRITABLE semantics unchanged: write of 1 possible
    e2 = EventFd(0)
    assert e2.state & FileState.WRITABLE


# -- timerfd ----------------------------------------------------------


def test_timerfd_oneshot_and_interval():
    mgr, host = _host()
    ticks = []

    def app(api):
        tfd = api.timerfd()
        tfd.settime(100 * MS)  # one-shot at +100ms
        while True:
            try:
                n = tfd.read_expirations()
            except errors.Blocked as b:
                yield b
                continue
            ticks.append((api.now(), n))
            if len(ticks) == 1:
                tfd.settime(50 * MS, interval_ns=200 * MS)
            if len(ticks) >= 4:
                return

    host.add_application(1 * MS, lambda h: SimProcess(h, "t", app).spawn())
    mgr.run()
    times = [t for t, _ in ticks]
    assert times[0] == 101 * MS
    assert times[1] == 151 * MS
    assert times[2] == 351 * MS
    assert times[3] == 551 * MS


# -- epoll ------------------------------------------------------------


def test_epoll_level_triggered():
    r, w = make_pipe()
    ep = Epoll()
    ep.add(r, EpollEvents.IN, data="r")
    ep.add(w, EpollEvents.OUT, data="w")
    got = dict(ep.ready())
    assert "w" in got and "r" not in got  # empty pipe: only writable
    w.send(b"data")
    got = dict(ep.ready())
    assert "r" in got  # level-triggered: remains ready until drained
    got = dict(ep.ready())
    assert "r" in got
    r.recv(100)
    got = dict(ep.ready())
    assert "r" not in got
    assert ep.state & FileState.READABLE  # w still ready


def test_epoll_edge_triggered():
    r, w = make_pipe()
    ep = Epoll()
    ep.add(r, EpollEvents.IN | EpollEvents.ET, data="r")
    assert dict(ep.ready()) == {}
    w.send(b"x")
    assert "r" in dict(ep.ready())
    assert "r" not in dict(ep.ready())  # consumed edge
    # Linux ET: NEW data while already readable is a fresh event
    # (epoll(7); delivered via the READ_BUFFER_GREW signal path)
    w.send(b"y")
    assert "r" in dict(ep.ready())
    assert "r" not in dict(ep.ready())
    r.recv(100)  # drain -> off
    w.send(b"z")  # off->on transition also re-arms
    assert "r" in dict(ep.ready())


def test_epoll_oneshot_and_modify():
    r, w = make_pipe()
    ep = Epoll()
    ep.add(r, EpollEvents.IN | EpollEvents.ONESHOT, data="r")
    w.send(b"x")
    assert "r" in dict(ep.ready())
    assert "r" not in dict(ep.ready())  # disarmed
    ep.modify(r, EpollEvents.IN)  # re-arm, level-triggered
    assert "r" in dict(ep.ready())
    ep.remove(r)
    assert dict(ep.ready()) == {}
    with pytest.raises(errors.SyscallError):
        ep.remove(r)


def test_epoll_wait_blocks_process_until_ready():
    mgr, host = _host()
    log = []

    def app(api):
        r, w = api.pipe()
        ep = api.epoll()
        ep.add(r, EpollEvents.IN, data="pipe")
        tfd = api.timerfd()
        tfd.settime(200 * MS)
        ep.add(tfd, EpollEvents.IN, data="timer")
        events = yield from api.epoll_wait(ep)
        log.append((api.now(), sorted(d for d, _ in events)))

    host.add_application(1 * MS, lambda h: SimProcess(h, "e", app).spawn())
    mgr.run()
    assert log == [(201 * MS, ["timer"])]


# -- descriptor table -------------------------------------------------


def test_descriptor_table_alloc_dup_close():
    t = DescriptorTable()
    r, w = make_pipe()
    fd_r = t.register(r)
    fd_w = t.register(w)
    assert (fd_r, fd_w) == (0, 1)
    fd_r2 = t.dup(fd_r)
    assert fd_r2 == 2
    t.close(fd_r)
    assert not r.is_closed()  # dup still references it
    t.close(fd_r2)
    assert r.is_closed()  # last reference closed the file
    assert t.get(fd_w) is w
    with pytest.raises(errors.SyscallError):
        t.get(fd_r)
    fd_new = t.register(make_pipe()[0])
    assert fd_new == 0  # lowest free fd reused


def test_descriptor_register_at_closes_previous():
    t = DescriptorTable()
    r1, w1 = make_pipe()
    r2, _w2 = make_pipe()
    fd = t.register(r1)
    t.register_at(fd, r2)
    assert r1.is_closed()
    assert t.get(fd) is r2
