"""Managed native processes inside the simulation event loop: /bin/sleep
and compiled binaries run under the shim with their sleeps scheduled as
host events — emulated time, not wall time, decides when they finish.

Parity model: the reference's whole point — real binaries inside the
discrete-event simulation (`docs/design_2x.md`).
"""

import shutil
import subprocess
import time

import pytest

from shadow_tpu.core import simtime
from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager
from shadow_tpu.process.process import ProcessState

S = simtime.SECOND

SLEEP = shutil.which("sleep")


@pytest.mark.skipif(SLEEP is None, reason="no sleep binary")
def test_bin_sleep_finishes_in_simulated_time():
    """/bin/sleep 30 completes inside a 60s simulation in ~zero wall time;
    a 30s simulation ends with it still running."""
    cfg_text = """
general: {{stop_time: {stop}, seed: 1}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {sleep}, args: ["30"], start_time: 1s,
       expected_final_state: {expect}}}
"""
    wall_start = time.monotonic()
    stats = Manager(
        load_config_str(cfg_text.format(stop="60s", sleep=SLEEP,
                                        expect="{exited: 0}"))
    ).run()
    wall = time.monotonic() - wall_start
    assert stats.process_failures == [], stats.process_failures
    assert wall < 15.0  # 30 simulated seconds, not 30 real ones

    stats = Manager(
        load_config_str(cfg_text.format(stop="20s", sleep=SLEEP,
                                        expect="running"))
    ).run()
    assert stats.process_failures == [], stats.process_failures


def test_mixed_native_and_coroutine_processes(tmp_path):
    """A compiled binary and coroutine apps share one simulation; the
    binary's virtual clock tracks the same host timeline."""
    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    src = tmp_path / "ticker.c"
    src.write_text(
        r"""
#include <stdio.h>
#include <time.h>
int main(void) {
    for (int i = 0; i < 3; i++) {
        struct timespec req = {2, 0};
        nanosleep(&req, 0);
        struct timespec ts;
        clock_gettime(CLOCK_MONOTONIC, &ts);
        printf("tick %ld\n", (long)ts.tv_sec);
    }
    return 0;
}
"""
    )
    binary = tmp_path / "ticker"
    subprocess.run([cc, "-O1", "-o", str(binary), str(src)], check=True)

    cfg = load_config_str(
        f"""
general: {{stop_time: 30s, seed: 2, data_directory: {tmp_path}/data}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  native:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s, expected_final_state: {{exited: 0}}}}
  pyapp:
    network_node_id: 0
    processes:
    - {{path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
"""
    )
    mgr = Manager(cfg, data_dir=str(tmp_path / "data"))
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    out = (tmp_path / "data" / "hosts" / "native" /
           "native.ticker.0.stdout").read_bytes()
    # started at sim 1s; ticks at 3, 5, 7 virtual seconds
    assert out == b"tick 3\ntick 5\ntick 7\n"
