"""Managed native binaries on the SIMULATED network: compiled C programs
whose socket/bind/listen/accept/connect/read/write/poll syscalls are
emulated against the simulated kernel, transferring data through the
simulated internet with latency and loss applied.

Parity: this is the reference's defining capability (`README.md:18-63`) —
the syscall-handler dispatch (`syscall/handler/mod.rs:357-496`) routing
real processes onto the simulated transport. The reference's equivalent
tests are `src/test/socket/*` + `examples/docs/basic-file-transfer`.
"""

import shutil
import subprocess

import pytest
from pathlib import Path

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")

SERVER_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
    int port = atoi(argv[1]);
    long size = atol(argv[2]);
    int ls = socket(AF_INET, SOCK_STREAM, 0);
    if (ls < 0) return 10;
    int one = 1;
    setsockopt(ls, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    a.sin_addr.s_addr = INADDR_ANY;
    if (bind(ls, (struct sockaddr *)&a, sizeof a)) return 11;
    if (listen(ls, 8)) return 12;
    struct sockaddr_in peer;
    socklen_t plen = sizeof peer;
    int c = accept(ls, (struct sockaddr *)&peer, &plen);
    if (c < 0) return 13;
    if (plen < 8 || peer.sin_family != AF_INET) return 14;
    char buf[16384];
    long sent = 0;
    while (sent < size) {
        long n = size - sent;
        if (n > (long)sizeof buf) n = (long)sizeof buf;
        /* position-based pattern: byte at absolute offset i is i & 0xff,
         * stable across partial writes */
        for (long i = 0; i < n; i++) buf[i] = (char)((sent + i) & 0xff);
        long w = write(c, buf, n);
        if (w <= 0) return 15;
        sent += w;
    }
    close(c);
    close(ls);
    return 0;
}
"""

CLIENT_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
    const char *ip = argv[1];
    int port = atoi(argv[2]);
    long expect = atol(argv[3]);
    int s = socket(AF_INET, SOCK_STREAM, 0);
    if (s < 0) return 20;
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    a.sin_addr.s_addr = inet_addr(ip);
    if (connect(s, (struct sockaddr *)&a, sizeof a)) return 21;
    /* the simulated kernel must report our ephemeral source address */
    struct sockaddr_in self;
    socklen_t slen = sizeof self;
    if (getsockname(s, (struct sockaddr *)&self, &slen)) return 22;
    if (ntohs(self.sin_port) == 0) return 23;
    long got = 0;
    char buf[16384];
    for (;;) {
        long n = read(s, buf, sizeof buf);
        if (n < 0) return 24;
        if (n == 0) break;
        /* every byte is its absolute stream offset & 0xff: catches
         * truncation, reordering, and duplication exactly */
        for (long i = 0; i < n; i++)
            if ((unsigned char)buf[i] != (unsigned char)((got + i) & 0xff))
                return 26;
        got += n;
    }
    close(s);
    if (got != expect) return 25;
    return 0;
}
"""

UDP_ECHO_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
    int port = atoi(argv[1]);
    int s = socket(AF_INET, SOCK_DGRAM, 0);
    if (s < 0) return 30;
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    a.sin_addr.s_addr = INADDR_ANY;
    if (bind(s, (struct sockaddr *)&a, sizeof a)) return 31;
    char buf[2048];
    for (;;) {
        struct sockaddr_in peer;
        socklen_t plen = sizeof peer;
        long n = recvfrom(s, buf, sizeof buf, 0,
                          (struct sockaddr *)&peer, &plen);
        if (n < 0) return 32;
        if (sendto(s, buf, n, 0, (struct sockaddr *)&peer, plen) != n)
            return 33;
    }
}
"""

UDP_CLIENT_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <poll.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

static long long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(int argc, char **argv) {
    const char *ip = argv[1];
    int port = atoi(argv[2]);
    int rounds = atoi(argv[3]);
    long long min_rtt_ns = atoll(argv[4]);
    int s = socket(AF_INET, SOCK_DGRAM, 0);
    if (s < 0) return 40;
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    a.sin_addr.s_addr = inet_addr(ip);
    char msg[64], back[64];
    for (int i = 0; i < rounds; i++) {
        memset(msg, 'a' + i, sizeof msg);
        long long t0 = now_ns();
        if (sendto(s, msg, sizeof msg, 0, (struct sockaddr *)&a, sizeof a)
                != (long)sizeof msg)
            return 41;
        struct pollfd p = { .fd = s, .events = POLLIN };
        int pr = poll(&p, 1, 30000); /* generous virtual-ms timeout */
        if (pr != 1 || !(p.revents & POLLIN)) return 42;
        struct sockaddr_in from;
        socklen_t flen = sizeof from;
        long n = recvfrom(s, back, sizeof back, 0,
                          (struct sockaddr *)&from, &flen);
        if (n != (long)sizeof msg) return 43;
        if (memcmp(msg, back, sizeof msg)) return 44;
        /* the echo crossed the simulated network twice: virtual time must
         * have advanced by at least the round-trip latency */
        if (now_ns() - t0 < min_rtt_ns) return 45;
    }
    close(s);
    return 0;
}
"""


def _compile(tmp_path, name: str, src: str) -> str:
    c = tmp_path / f"{name}.c"
    c.write_text(src)
    binary = tmp_path / name
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    return str(binary)


GRAPH = """
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss {loss} ]
      ]
"""


def test_tcp_transfer_through_simulated_network(tmp_path):
    """A compiled C server sends 1 MiB to a compiled C client over the
    simulated network with 10ms latency and 2% loss; both verify the data
    at the syscall level (VERDICT round-1 item #2's 'done' criterion)."""
    server = _compile(tmp_path, "tserver", SERVER_C)
    client = _compile(tmp_path, "tclient", CLIENT_C)
    size = 1048576
    cfg = load_config_str(f"""
general: {{stop_time: 60s, seed: 11}}
network:
  graph:
    type: gml
    inline: |
{GRAPH.format(loss=0.02)}
hosts:
  server:
    network_node_id: 0
    ip_addr: 11.0.0.1
    processes:
    - {{path: {server}, args: ["8080", "{size}"], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
  client:
    network_node_id: 0
    ip_addr: 11.0.0.2
    processes:
    - {{path: {client}, args: ["11.0.0.1", "8080", "{size}"], start_time: 2s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures
    assert stats.packets_sent > size / 1500  # it actually crossed the network
    assert stats.packets_dropped > 0  # ...with loss applied


def test_udp_echo_with_poll_and_virtual_rtt(tmp_path):
    """A compiled C UDP echo pair: recvfrom/sendto with address writeback,
    poll()-based waits, and clock_gettime showing the simulated RTT (2 x
    25ms latency) rather than wall time. The threshold sits just under the
    exact RTT to stay robust if the syscall-latency model is enabled."""
    echo = _compile(tmp_path, "uecho", UDP_ECHO_C)
    cli = _compile(tmp_path, "uclient", UDP_CLIENT_C)
    cfg = load_config_str(f"""
general: {{stop_time: 30s, seed: 12}}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "25 ms" packet_loss 0.0 ]
      ]
hosts:
  echoer:
    network_node_id: 0
    ip_addr: 11.0.0.1
    processes:
    - {{path: {echo}, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
  pinger:
    network_node_id: 0
    ip_addr: 11.0.0.2
    processes:
    - {{path: {cli}, args: ["11.0.0.1", "9000", "5", "49000000"],
       start_time: 2s, expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


def test_tcp_transfer_is_deterministic(tmp_path):
    """Same config, two runs: identical packet counts and drop counts even
    with real binaries in the loop (loss draws come from per-host RNG
    streams, not wall-clock state)."""
    server = _compile(tmp_path, "dserver", SERVER_C)
    client = _compile(tmp_path, "dclient", CLIENT_C)
    size = 262144
    text = f"""
general: {{stop_time: 60s, seed: 13}}
network:
  graph:
    type: gml
    inline: |
{GRAPH.format(loss=0.05)}
hosts:
  server:
    network_node_id: 0
    ip_addr: 11.0.0.1
    processes:
    - {{path: {server}, args: ["8080", "{size}"], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
  client:
    network_node_id: 0
    ip_addr: 11.0.0.2
    processes:
    - {{path: {client}, args: ["11.0.0.1", "8080", "{size}"], start_time: 2s,
       expected_final_state: {{exited: 0}}}}
"""
    s1 = Manager(load_config_str(text)).run()
    s2 = Manager(load_config_str(text)).run()
    assert s1.process_failures == [] and s2.process_failures == []
    assert (s1.packets_sent, s1.packets_dropped) == \
        (s2.packets_sent, s2.packets_dropped)


def test_curl_resolves_simulated_hostname(tmp_path):
    """The addrinfo preload (`shim_api_addrinfo.c` parity): real curl
    fetches by SIMULATED hostname ("http://server:8000/...") resolved
    through the simulation's hosts view — no real-resolver fallback, no
    gethostby* NSS walk into fake DNS queries."""
    import shutil as _sh

    py = _sh.which("python3")
    curl = _sh.which("curl")
    if py is None or curl is None:
        pytest.skip("python3/curl not available")
    payload = bytes(range(256)) * 64  # 16 KiB
    (tmp_path / "data.bin").write_bytes(payload)
    out = tmp_path / "fetched.bin"
    cfg = load_config_str(f"""
general: {{stop_time: 30s, seed: 5}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: {py}, args: ["-m", "http.server", "8000", "--bind",
        "0.0.0.0", "--directory", "{tmp_path}"], start_time: 1s,
       expected_final_state: running}}
  client:
    network_node_id: 0
    processes:
    - {{path: {curl}, args: ["-s", "-f", "-o", "{out}",
        "http://server:8000/data.bin"], start_time: 3s,
       expected_final_state: {{exited: 0}}}}
""")
    mgr = Manager(cfg, data_dir=str(tmp_path / "data"))
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    # the client's absolute -o path lives in ITS per-host filesystem view
    # (experimental.host_path_isolation, round 5)
    vout = Path(mgr.hosts_by_name["client"].vfs_root + str(out))
    assert vout.read_bytes() == payload


BAD_OPTLEN_C = r"""
#include <errno.h>
#include <sys/socket.h>

int main(void) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return 1;
    int v = 8192;
    /* Linux: optlen < sizeof(int) for int-valued options is EINVAL,
       not a silent success that never pinned the buffer. */
    if (setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &v, 2) != -1 ||
        errno != EINVAL) return 2;
    if (setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &v, -1) != -1 ||
        errno != EINVAL) return 3;
    /* short-optlen EINVAL wins over the NULL fault; NULL with a valid
       length faults (Linux copy_from_sockptr order) */
    if (setsockopt(fd, SOL_SOCKET, SO_RCVBUF, 0, 4) != -1 ||
        errno != EFAULT) return 4;
    if (setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &v, sizeof v) != 0) return 5;
    return 0;
}
"""


def test_setsockopt_short_optlen_is_einval(tmp_path):
    """ADVICE r3 (low): SO_SNDBUF/SO_RCVBUF with optlen < 4 (or a NULL
    optval) must fail EINVAL like Linux, not return 0 without pinning."""
    binary = _compile(tmp_path, "badoptlen", BAD_OPTLEN_C)
    cfg = load_config_str(f"""
general: {{stop_time: 5s, seed: 11}}
network:
  graph:
    type: 1_gbit_switch
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures
