"""Interposition end-to-end: a REAL native binary (compiled in the test)
runs under the LD_PRELOAD + seccomp shim; its syscalls are trapped,
forwarded over shared-memory IPC, and answered with *virtual* time —
5 simulated seconds of sleeping pass in near-zero wall time.

Parity model: the reference's core claim (`README.md:18-63` — directly
executes real unmodified binaries, co-opted via syscall interposition)
and its linux-vs-shadow dual test pattern (`src/test/CMakeLists.txt`).
"""

import os
import shutil
import subprocess
import sys
import time

import pytest

from shadow_tpu.process.managed import ManagedProcess, SyscallServer

CC = shutil.which("gcc") or shutil.which("cc")

TEST_PROGRAM = r"""
#include <stdio.h>
#include <time.h>
#include <unistd.h>
#include <sys/syscall.h>

int main(void) {
    struct timespec ts;
    syscall(SYS_clock_gettime, CLOCK_MONOTONIC, &ts);
    long t0_sec = ts.tv_sec, t0_nsec = ts.tv_nsec;

    struct timespec req = {5, 0};  /* five SIMULATED seconds */
    syscall(SYS_nanosleep, &req, (void *)0);

    syscall(SYS_clock_gettime, CLOCK_MONOTONIC, &ts);
    long pid = syscall(SYS_getpid);
    printf("pid=%ld start=%ld.%09ld elapsed=%ld\n",
           pid, t0_sec, t0_nsec, ts.tv_sec - t0_sec);

    /* REALTIME clock observes the emulated epoch (2000-01-01) */
    syscall(SYS_clock_gettime, CLOCK_REALTIME, &ts);
    printf("realtime=%ld\n", ts.tv_sec);
    return 0;
}
"""


@pytest.fixture(scope="module")
def test_binary(tmp_path_factory):
    if CC is None:
        pytest.skip("no C compiler")
    d = tmp_path_factory.mktemp("managed")
    src = d / "vtime.c"
    src.write_text(TEST_PROGRAM)
    binary = d / "vtime"
    subprocess.run([CC, "-O1", "-o", str(binary), str(src)], check=True)
    return str(binary)


def test_binary_runs_unmanaged(test_binary):
    """The linux half of the dual-execution pattern: the binary itself is
    valid (elapsed ~5 REAL seconds would be too slow; use a quick check
    that it at least starts and prints a pid)."""
    proc = subprocess.run([test_binary], capture_output=True, text=True,
                          timeout=10)
    assert proc.returncode == 0
    assert "pid=" in proc.stdout


def test_virtual_time_under_interposition(test_binary):
    server = SyscallServer(virtual_pid=4242)
    wall_start = time.monotonic()
    mp = ManagedProcess([test_binary], server=server)
    code, out, err = mp.wait(timeout=30)
    wall = time.monotonic() - wall_start
    text = out.decode()
    assert code == 0, (code, text, err.decode())
    first_line = text.strip().splitlines()[0]
    parts = dict(p.split("=") for p in first_line.split())
    assert int(parts["pid"]) == 4242  # virtual pid, not the real one
    assert parts["start"].startswith("0.")  # virtual monotonic starts at 0
    assert int(parts["elapsed"]) == 5  # five virtual seconds elapsed
    assert wall < 10.0  # ...in approximately zero wall time
    # realtime clock sits at the emulated epoch (2000-01-01 => 946684800)
    realtime = int(text.strip().splitlines()[1].split("=")[1])
    assert 946684800 <= realtime <= 946684800 + 10
    # the server actually saw the syscalls
    assert server.syscall_counts.get(228, 0) >= 3  # clock_gettime
    assert server.syscall_counts.get(35, 0) == 1  # nanosleep
    assert mp.native_pid is not None and mp.native_pid != 4242


def test_interposition_is_transparent_to_output(test_binary):
    """stdout write()s pass through natively and are captured intact."""
    mp = ManagedProcess([test_binary])
    code, out, _err = mp.wait(timeout=30)
    assert code == 0
    assert out.decode().startswith("pid=1000 ")


def test_real_coreutils_under_shim():
    """An unmodified system binary (/bin/echo) survives full interposition."""
    echo = shutil.which("echo")
    if echo is None:
        pytest.skip("no echo binary")
    mp = ManagedProcess([echo, "hello", "managed", "world"])
    code, out, _err = mp.wait(timeout=30)
    assert code == 0
    assert out == b"hello managed world\n"


LIBC_TIME_PROGRAM = r"""
#include <stdio.h>
#include <time.h>
#include <sys/time.h>
int main(void) {
    /* plain libc calls — normally served by the vDSO without any syscall;
       the shim's vdso patching forces them onto the trappable path */
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    long t0 = ts.tv_sec;
    struct timespec req = {7, 0};
    nanosleep(&req, NULL);
    clock_gettime(CLOCK_MONOTONIC, &ts);
    struct timeval tv;
    gettimeofday(&tv, NULL);
    printf("elapsed=%ld realtime=%ld\n", ts.tv_sec - t0, (long)tv.tv_sec);
    return 0;
}
"""


def test_vdso_time_virtualized(tmp_path):
    """libc/vDSO-routed time is virtualized, not just raw syscalls
    (reference patch_vdso.c capability)."""
    if CC is None:
        pytest.skip("no C compiler")
    src = tmp_path / "libc_time.c"
    src.write_text(LIBC_TIME_PROGRAM)
    binary = tmp_path / "libc_time"
    subprocess.run([CC, "-O1", "-o", str(binary), str(src)], check=True)

    wall_start = time.monotonic()
    mp = ManagedProcess([str(binary)])
    code, out, _err = mp.wait(timeout=30)
    wall = time.monotonic() - wall_start
    assert code == 0
    parts = dict(p.split("=") for p in out.decode().split())
    assert int(parts["elapsed"]) == 7  # virtual seconds via plain libc calls
    assert 946684800 <= int(parts["realtime"]) <= 946684900  # emulated epoch
    assert wall < 10.0
