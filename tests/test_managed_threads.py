"""Managed multithreading + process family: pthreads (clone trampoline,
per-thread IPC channels, emulated futex), fork (child process objects with
forked descriptor tables), wait4, pipes, eventfd, timerfd, and uname — all
exercised by REAL compiled binaries on the simulated network.

Parity: reference `src/test/{threads,clone,futex,pipe,eventfd,timerfd,
wait,unistd}` + `managed_thread.rs:349-428` (AddThread handshake) +
`shim/src/clone.rs` (clone trampoline).
"""

import shutil
import subprocess

import pytest
from pathlib import Path

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")


def _compile(tmp_path, name: str, src: str, libs=("-pthread",)) -> str:
    c = tmp_path / f"{name}.c"
    c.write_text(src)
    binary = tmp_path / name
    subprocess.run([CC, "-O1", "-o", str(binary), str(c), *libs], check=True)
    return str(binary)


GRAPH = """
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "5 ms" packet_loss 0.0 ]
      ]
"""

THREADED_CLIENT_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <pthread.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

static const char *g_ip;
static int g_port;
static pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
static pthread_cond_t cv = PTHREAD_COND_INITIALIZER;
static int done_count = 0;

static void *worker(void *arg) {
    long idx = (long)arg;
    int s = socket(AF_INET, SOCK_STREAM, 0);
    if (s < 0) return (void *)10;
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons(g_port);
    a.sin_addr.s_addr = inet_addr(g_ip);
    if (connect(s, (struct sockaddr *)&a, sizeof a)) return (void *)11;
    char msg[32], back[32];
    memset(msg, 'a' + (int)idx, sizeof msg);
    if (write(s, msg, sizeof msg) != (long)sizeof msg) return (void *)12;
    long got = 0;
    while (got < (long)sizeof back) {
        long n = read(s, back + got, sizeof back - got);
        if (n <= 0) return (void *)13;
        got += n;
    }
    if (memcmp(msg, back, sizeof msg)) return (void *)14;
    close(s);
    pthread_mutex_lock(&mu);
    done_count++;
    pthread_cond_signal(&cv);
    pthread_mutex_unlock(&mu);
    return (void *)0;
}

int main(int argc, char **argv) {
    g_ip = argv[1];
    g_port = atoi(argv[2]);
    pthread_t t1, t2;
    if (pthread_create(&t1, 0, worker, (void *)1)) return 1;
    if (pthread_create(&t2, 0, worker, (void *)2)) return 2;
    /* condvar wait: emulated futex WAIT, woken by the workers' signals */
    pthread_mutex_lock(&mu);
    while (done_count < 2) pthread_cond_wait(&cv, &mu);
    pthread_mutex_unlock(&mu);
    /* join: emulated futex on the CLONE_CHILD_CLEARTID word */
    void *r1 = 0, *r2 = 0;
    if (pthread_join(t1, &r1)) return 3;
    if (pthread_join(t2, &r2)) return 4;
    if (r1 || r2) return 5;
    return 0;
}
"""

ECHO2_SERVER_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
    int port = atoi(argv[1]);
    int conns = atoi(argv[2]);
    int ls = socket(AF_INET, SOCK_STREAM, 0);
    if (ls < 0) return 20;
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    a.sin_addr.s_addr = INADDR_ANY;
    if (bind(ls, (struct sockaddr *)&a, sizeof a)) return 21;
    if (listen(ls, 8)) return 22;
    for (int i = 0; i < conns; i++) {
        int c = accept(ls, 0, 0);
        if (c < 0) return 23;
        char buf[32];
        long got = 0;
        while (got < (long)sizeof buf) {
            long n = read(c, buf + got, sizeof buf - got);
            if (n <= 0) return 24;
            got += n;
        }
        if (write(c, buf, sizeof buf) != (long)sizeof buf) return 25;
        close(c);
    }
    close(ls);
    return 0;
}
"""

FORK_PIPE_C = r"""
#include <errno.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

int main(void) {
    int p[2];
    if (pipe(p)) return 1;
    pid_t pid = fork();
    if (pid < 0) return 2;
    if (pid == 0) {
        close(p[0]);
        if (write(p[1], "from-child", 10) != 10) _exit(3);
        _exit(42);
    }
    if (pid == getpid()) return 8;
    close(p[1]);
    char buf[16];
    long got = 0, n;
    while ((n = read(p[0], buf + got, sizeof buf - got)) > 0) got += n;
    if (got != 10 || memcmp(buf, "from-child", 10)) return 4;
    int st = 0;
    pid_t w = waitpid(pid, &st, 0);
    if (w != pid) return 5;
    if (!WIFEXITED(st)) return 6;
    if (WEXITSTATUS(st) != 42) return 7;
    /* drain loop: a second wait must see ECHILD, not block forever */
    if (waitpid(-1, 0, 0) != -1 || errno != ECHILD) return 9;
    return 0;
}
"""

KERNEL_OBJECTS_C = r"""
#include <string.h>
#include <stdint.h>
#include <sys/eventfd.h>
#include <sys/timerfd.h>
#include <sys/utsname.h>
#include <time.h>
#include <unistd.h>

int main(void) {
    struct utsname u;
    if (uname(&u)) return 1;
    if (strcmp(u.nodename, "box")) return 2; /* the SIMULATED hostname */
    int efd = eventfd(5, 0);
    if (efd < 0) return 3;
    uint64_t v = 0;
    if (read(efd, &v, 8) != 8 || v != 5) return 4;
    v = 7;
    if (write(efd, &v, 8) != 8) return 5;
    v = 0;
    if (read(efd, &v, 8) != 8 || v != 7) return 6;
    close(efd);

    int tfd = timerfd_create(CLOCK_MONOTONIC, 0);
    if (tfd < 0) return 7;
    struct itimerspec its;
    memset(&its, 0, sizeof its);
    its.it_value.tv_nsec = 50 * 1000 * 1000; /* 50 ms */
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    if (timerfd_settime(tfd, 0, &its, 0)) return 8;
    if (read(tfd, &v, 8) != 8 || v != 1) return 9; /* blocks in SIM time */
    clock_gettime(CLOCK_MONOTONIC, &t1);
    long long d = (t1.tv_sec - t0.tv_sec) * 1000000000LL
                  + (t1.tv_nsec - t0.tv_nsec);
    if (d < 50 * 1000 * 1000) return 10; /* virtual clock must have moved */
    close(tfd);
    return 0;
}
"""


def test_pthreads_sockets_futex_join(tmp_path):
    """Two pthreads each run a TCP exchange over the simulated network;
    the main thread blocks on a condvar (emulated futex) and then joins
    both (emulated CLEARTID futex). VERDICT round-2 item #2's criterion."""
    client = _compile(tmp_path, "threaded-client", THREADED_CLIENT_C)
    server = _compile(tmp_path, "echo2-server", ECHO2_SERVER_C, libs=())
    cfg = load_config_str(f"""
general: {{stop_time: 30s, seed: 21}}
network:
  graph:
    type: gml
    inline: |
{GRAPH}
hosts:
  server:
    network_node_id: 0
    ip_addr: 11.0.0.1
    processes:
    - {{path: {server}, args: ["7000", "2"], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
  client:
    network_node_id: 0
    ip_addr: 11.0.0.2
    processes:
    - {{path: {client}, args: ["11.0.0.1", "7000"], start_time: 2s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


RAW_CLONE_C = r"""
/* A thread created the way Go's runtime.newosproc does it: raw
 * clone(CLONE_VM|CLONE_THREAD|...) WITHOUT CLONE_SETTLS, child jumps
 * straight into a function that uses only raw syscalls. The child shares
 * the parent's TLS, so the shim must route its syscalls by tid, not TLS
 * -- a TLS'd shim would cross the two channels and hang the simulation. */
#define _GNU_SOURCE
#include <sched.h>
#include <sys/syscall.h>
#include <unistd.h>

static char child_stack[65536] __attribute__((aligned(64)));
static int pipefd[2];

static long rawsys3(long nr, long a, long b, long c) {
    long ret;
    __asm__ volatile("syscall"
                     : "=a"(ret)
                     : "a"(nr), "D"(a), "S"(b), "d"(c)
                     : "rcx", "r11", "memory");
    return ret;
}

static void child_main(void) {
    rawsys3(SYS_write, pipefd[1], (long)"hi", 2);
    rawsys3(SYS_exit, 0, 0, 0);
    __builtin_unreachable();
}

int main(void) {
    if (pipe(pipefd)) return 1;
    long flags = CLONE_VM | CLONE_FS | CLONE_FILES | CLONE_SIGHAND
                 | CLONE_THREAD;
    long tid;
    register long r10 __asm__("r10") = 0;
    register long r8 __asm__("r8") = 0;
    register void (*fn)(void) __asm__("rbx") = child_main;
    __asm__ volatile(
        "syscall\n\t"
        "test %%rax, %%rax\n\t"
        "jnz 1f\n\t"
        "call *%%rbx\n\t" /* child: new stack, shared TLS */
        "1:"
        : "=a"(tid)
        : "a"(SYS_clone), "D"(flags),
          "S"((long)(child_stack + sizeof child_stack)), "d"(0), "r"(r10),
          "r"(r8), "r"(fn)
        : "rcx", "r11", "memory");
    if (tid <= 0) return 2;
    char buf[2];
    long got = 0;
    while (got < 2) { /* the parent's own syscalls must stay on ITS channel */
        long n = read(pipefd[0], buf + got, 2 - got);
        if (n <= 0) return 3;
        got += n;
    }
    if (buf[0] != 'h' || buf[1] != 'i') return 4;
    return 0;
}
"""


def test_raw_clone_without_settls(tmp_path):
    """Go-runtime-shaped threading: raw clone with no CLONE_SETTLS. The
    child shares the parent's TLS; shim channel routing must fall back to
    the tid table or the parent's channel gets hijacked (hang)."""
    binary = _compile(tmp_path, "raw-clone", RAW_CLONE_C, libs=())
    cfg = load_config_str(f"""
general: {{stop_time: 10s, seed: 24}}
network:
  graph:
    type: 1_gbit_switch
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s, expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


LEADER_EXIT_C = r"""
#include <pthread.h>
#include <stdio.h>
#include <unistd.h>

static void *worker(void *arg) {
    (void)arg;
    usleep(5000);
    printf("worker outlived leader\n");
    return NULL;
}

int main(void) {
    pthread_t th;
    if (pthread_create(&th, NULL, worker, NULL)) return 1;
    pthread_exit(NULL); /* leader exits; the group lives on via the worker */
}
"""


def test_leader_pthread_exit_workers_continue(tmp_path):
    """The main thread pthread_exit()s while a worker keeps running: the
    zombie leader's /proc task entry lingers until the whole group exits,
    so the thread-gone wait must treat state Z as gone (not spin out its
    wall-clock timeout), and the process must still exit cleanly."""
    import time

    binary = _compile(tmp_path, "leader-exit", LEADER_EXIT_C)
    cfg = load_config_str(f"""
general: {{stop_time: 5s, seed: 23}}
network:
  graph:
    type: 1_gbit_switch
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s, expected_final_state: {{exited: 0}}}}
""")
    t0 = time.monotonic()
    stats = Manager(cfg).run()
    wall = time.monotonic() - t0
    assert stats.process_failures == [], stats.process_failures
    # the old /proc-exists wait burned a full 2s timeout on the zombie
    # leader; the Z-state-aware wait finishes in milliseconds
    assert wall < 2.0, f"leader zombie wait leaked wall time ({wall:.2f}s)"


def test_fork_pipe_wait4(tmp_path):
    """fork() creates a managed child process sharing the parent's pipe
    through a forked descriptor table; the parent reads the child's bytes
    and reaps its exit code via emulated wait4."""
    binary = _compile(tmp_path, "fork-pipe", FORK_PIPE_C, libs=())
    cfg = load_config_str(f"""
general: {{stop_time: 10s, seed: 22}}
network:
  graph:
    type: 1_gbit_switch
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s, expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


def test_eventfd_timerfd_uname(tmp_path):
    """eventfd counter semantics, a timerfd firing on the VIRTUAL clock,
    and uname reporting the simulated hostname."""
    binary = _compile(tmp_path, "kernel-objects", KERNEL_OBJECTS_C, libs=())
    cfg = load_config_str(f"""
general: {{stop_time: 10s, seed: 23}}
network:
  graph:
    type: 1_gbit_switch
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s, expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


PY_CLIENT = (
    "import urllib.request,sys\n"
    "r = urllib.request.urlopen('http://11.0.0.1:8000/', timeout=60)\n"
    "body = r.read()\n"
    "sys.exit(0 if r.status == 200 and len(body) > 0 else 9)\n"
)


def test_python_http_server_and_client(tmp_path):
    """The reference's literal rung-1 workload
    (`examples/docs/basic-file-transfer/shadow.yaml`): a REAL python3
    http.server (threaded: one clone per request) serving a REAL python3
    urllib client over the simulated network."""
    import shutil as _sh

    py = _sh.which("python3")
    if py is None:
        pytest.skip("no python3")
    (tmp_path / "index.html").write_text("hello from the simulation\n")
    script = tmp_path / "client.py"
    script.write_text(PY_CLIENT)
    cfg = load_config_str(f"""
general: {{stop_time: 60s, seed: 24}}
network:
  graph:
    type: gml
    inline: |
{GRAPH}
hosts:
  server:
    network_node_id: 0
    ip_addr: 11.0.0.1
    processes:
    - {{path: {py}, args: ["-m", "http.server", "8000", "--bind", "0.0.0.0",
        "--directory", "{tmp_path}"], start_time: 1s,
       expected_final_state: running}}
  client:
    network_node_id: 0
    ip_addr: 11.0.0.2
    processes:
    - {{path: {py}, args: ["{script}"], start_time: 3s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


def test_curl_fetches_from_python_http_server(tmp_path):
    """The reference's rung-1 binaries verbatim
    (`examples/docs/basic-file-transfer/shadow.yaml`): real curl
    downloading a file from real `python3 -m http.server`, bytes
    verified. VERDICT round-2 item #3's 'done' criterion."""
    import shutil as _sh

    py = _sh.which("python3")
    curl = _sh.which("curl")
    if py is None or curl is None:
        pytest.skip("python3/curl not available")
    payload = bytes(range(256)) * 128  # 32 KiB, position-coded
    (tmp_path / "data.bin").write_bytes(payload)
    out = tmp_path / "fetched.bin"
    cfg = load_config_str(f"""
general: {{stop_time: 60s, seed: 25}}
network:
  graph:
    type: gml
    inline: |
{GRAPH}
hosts:
  server:
    network_node_id: 0
    ip_addr: 11.0.0.1
    processes:
    - {{path: {py}, args: ["-m", "http.server", "8000", "--bind", "0.0.0.0",
        "--directory", "{tmp_path}"], start_time: 1s,
       expected_final_state: running}}
  client:
    network_node_id: 0
    ip_addr: 11.0.0.2
    processes:
    - {{path: {curl}, args: ["-s", "-f", "-o", "{out}",
        "http://11.0.0.1:8000/data.bin"], start_time: 3s,
       expected_final_state: {{exited: 0}}}}
""")
    mgr = Manager(cfg, data_dir=str(tmp_path / "data"))
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    # the client's absolute -o path lives in ITS per-host filesystem view
    # (experimental.host_path_isolation, round 5)
    vout = Path(mgr.hosts_by_name["client"].vfs_root + str(out))
    assert vout.read_bytes() == payload
