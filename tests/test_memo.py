"""The steady-state memo plane: soundness contract pins.

Pins every clause of tpu/memo.py's contract (docs/performance.md
"Steady-state memoization", docs/determinism.md "Replay is
parity-pinned"):

- **Drift guard (memo-key completeness).** `walk_carry` visits every
  `jax.tree` leaf of the REAL corpus-runner carry — all presence
  planes threaded — and every `COUNTER_LEAVES`/`STABILITY_FIELDS`
  declaration names a field that actually exists on its NamedTuple,
  so a renamed or newly added plane leaf cannot silently fall out of
  the key (it lands keyed-by-default: fewer hits, never stale replay).
- **Modular delta replay is bitwise.** `counter_delta` /
  `apply_counter_delta` reproduce XLA's int32 wrap-around
  accumulation exactly across BOTH wrap boundaries (2^31 sign flip,
  2^32 full wrap), and tie to the harvester's `unwrap_u32` modular
  view.
- **Canonical digesting matches the device canonicalizer** byte for
  byte (dead-lane garbage is outside the key, exactly as it is
  outside the golden digests).
- **Cache mechanics**: min_repeat gating, LRU byte-budget eviction,
  oversize refusal, stability refusal (a span that moved a guard
  latch or flight-recorder cursor is never recorded).
- **Replay parity end to end**: a memoized `drive_chained_windows`
  run ends canonical-digest-identical to the cold run with >0 hits.

The heavy golden-corpus parity sweeps are @slow for the tier-1
runtime budget; CI's memo-parity gate runs `tools/run_scenarios.py
--memo --check` (and this file's slow cases, unfiltered) — the
shared-driver-gate pattern.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.telemetry.harvest import (apply_counter_delta,  # noqa: E402
                                          counter_delta, unwrap_u32)
from shadow_tpu.tpu import memo as memomod  # noqa: E402

N = 8


# ---------------------------------------------------------------------------
# modular counter deltas (satellite: uint32 wrap at 2^31 and 2^32)


def _xla_i32_accumulate(start: int, increments) -> np.ndarray:
    """Accumulate in DEVICE int32 (wrapping, like every plane counter)."""
    acc = jnp.int32(start)
    for inc in increments:
        acc = acc + jnp.int32(inc)
    return np.asarray(jax.device_get(acc))


@pytest.mark.parametrize("start,incs", [
    # crossing 2^31: the int32 sign flip (positive -> negative)
    (2**31 - 5, [3, 3, 3]),
    # crossing 2^32 (as unsigned): negative int32 wraps back positive
    (-5, [2, 2, 2]),
    # a full lap: delta itself exceeds 2^31
    (-(2**31) + 7, [2**30, 2**30, 2**30, 2**30]),
    # no wrap at all (the common case)
    (1000, [1, 2, 3]),
])
def test_counter_delta_matches_xla_wrap(start, incs):
    pre = np.int32(start)
    post = _xla_i32_accumulate(start, incs)
    d = counter_delta(pre, post)
    assert d.dtype == np.uint32
    # replaying the delta onto the same base reproduces XLA's wrap
    assert apply_counter_delta(pre, d) == post
    # ... and onto a DIFFERENT base it reproduces what XLA would have
    # accumulated there (the memo-hit case: live counters differ from
    # the recorded run's, the in-span increment is what replays)
    other = np.int32(-17)
    assert (apply_counter_delta(other, d)
            == _xla_i32_accumulate(-17, incs))


def test_counter_delta_ties_to_unwrap_u32():
    # the harvester's modular view and the memo plane's delta are the
    # SAME uint32 arithmetic (docstring contract in telemetry/harvest)
    for pre, post in [(2**31 - 2, -(2**31) + 5), (-3, 4), (7, 7)]:
        p, c = np.int32(pre), np.int32(post)
        assert int(counter_delta(p, c)) == unwrap_u32(int(p), int(c))


def test_counter_delta_dtype_guard():
    with pytest.raises(TypeError):
        counter_delta(np.int64(1), np.int64(2))
    with pytest.raises(TypeError):
        apply_counter_delta(np.int32(1), np.int32(2))  # delta not u32


def test_apply_counter_delta_vector_wrap():
    # array form across both boundaries at once
    pre = np.array([2**31 - 1, -1, 0], np.int32)
    post = _xla_i32_accumulate_vec(pre, np.array([1, 2, 3], np.int32))
    d = counter_delta(pre, post)
    np.testing.assert_array_equal(apply_counter_delta(pre, d), post)


def _xla_i32_accumulate_vec(start, inc):
    return np.asarray(jax.device_get(jnp.asarray(start)
                                     + jnp.asarray(inc)))


# ---------------------------------------------------------------------------
# drift guard: the walk covers the REAL runner carry


def _full_runner_carry():
    """The corpus runner's carry with EVERY presence plane threaded
    (state, ws, metrics, guards, hist, flightrec, flows) — built from
    the real constructors, no execution needed."""
    from shadow_tpu.guards import make_guards
    from shadow_tpu.telemetry import make_histograms, make_metrics
    from shadow_tpu.telemetry import flightrec as frmod
    from shadow_tpu.tpu import flows as flowsmod
    from shadow_tpu.workloads import device as wdevice
    from shadow_tpu.workloads.compile import compile_program
    from shadow_tpu.workloads.runner import build_scenario_world
    from shadow_tpu.workloads.spec import parse_scenario

    spec = parse_scenario({
        "name": "memo-drift-guard", "family": "ring_allreduce",
        "seed": 3, "hosts": N, "windows": 8,
        "patterns": [{"kind": "ring_allreduce", "first": 0,
                      "count": N, "bytes": 256, "rounds": 1}],
    })
    prog = compile_program(spec)
    state, _params = build_scenario_world(spec)
    ws = wdevice.make_workload_state(prog)
    fs = flowsmod.make_flow_state(4)
    fr = frmod.make_flightrec(3, sample_every=4, ring=64)
    return (state, (ws, make_metrics(N), make_guards(N),
                    make_histograms(N), fr, fs))


def test_walk_covers_every_tree_leaf():
    carry = _full_runner_carry()
    walked = memomod.walk_carry(jax.device_get(carry))
    tree_leaves = jax.tree.leaves(carry)
    assert len(walked) == len(tree_leaves), (
        "walk_carry and jax.tree disagree on the runner carry's leaf "
        "count — a leaf the memo key cannot see is a stale-replay bug")
    # ... and the walk is deterministic (key stability)
    walked2 = memomod.walk_carry(jax.device_get(carry))
    assert [(o, f) for o, f, _ in walked] == \
        [(o, f) for o, f, _ in walked2]


def test_declared_fields_exist():
    from shadow_tpu.guards.plane import GuardState
    from shadow_tpu.telemetry.flightrec import FlightRecArrays
    from shadow_tpu.telemetry.histo import PlaneHistograms
    from shadow_tpu.telemetry.metrics import PlaneMetrics
    from shadow_tpu.tpu.flows import FlowState
    from shadow_tpu.tpu.plane import NetPlaneState

    classes = {c.__name__: c for c in (
        NetPlaneState, PlaneMetrics, PlaneHistograms, GuardState,
        FlightRecArrays, FlowState)}
    for table in (memomod.COUNTER_LEAVES, memomod.STABILITY_FIELDS):
        for owner, fields in table.items():
            assert owner in classes, f"{owner}: unknown carry class"
            missing = fields - set(classes[owner]._fields)
            assert not missing, (
                f"{owner}: declared memo fields {sorted(missing)} do "
                f"not exist — a rename silently un-declared them")


def test_unknown_leaf_defaults_to_keyed():
    assert memomod.classify("BrandNewPlane", "anything") == "keyed"
    assert memomod.classify("", "[3]") == "keyed"
    assert memomod.classify("PlaneMetrics", "events") == "counter"
    # high-water marks stay keyed (maxima are not delta-applicable)
    assert memomod.classify("PlaneMetrics", "max_eg_depth") == "keyed"


def test_canonical_np_matches_device_canonicalizer():
    from shadow_tpu.tpu import elastic

    state, _extras = _full_runner_carry()
    # plant dead-lane garbage a compaction could leave behind
    state = state._replace(
        eg_dst=state.eg_dst.at[:, 0].set(99),
        eg_bytes=state.eg_bytes.at[:, 0].set(12345),
        in_src=state.in_src.at[:, 0].set(7),
        in_deliver_rel=state.in_deliver_rel.at[:, 0].set(42),
    )
    assert not bool(np.asarray(state.eg_valid)[:, 0].any())
    dev = jax.device_get(elastic.canonical_state(state))
    host = memomod._canonical_netplane_np(jax.device_get(state))
    for f in type(dev)._fields:
        a, b = np.asarray(getattr(dev, f)), np.asarray(getattr(host, f))
        assert a.dtype == b.dtype, f
        np.testing.assert_array_equal(a, b, err_msg=f)


# ---------------------------------------------------------------------------
# cache mechanics (synthetic carries — no device execution)


def _mk_carry(x=0, events=0):
    from shadow_tpu.telemetry import make_metrics

    m = jax.device_get(make_metrics(2))
    m = m._replace(events=np.int32(events))
    return (np.full((4,), x, np.int32), (m,))


def _key(memo, carry, r0=8, r1=12, salt=b""):
    return memo.key(carry, r0, r1, salt)


def test_min_repeat_gates_recording():
    memo = memomod.ChainMemo(min_repeat=2)
    pre, post = _mk_carry(1), _mk_carry(2, events=5)
    k, walk = _key(memo, pre)
    assert memo.lookup(k) is None
    assert not memo.record(k, walk, post, span_len=4)  # 1 miss < 2
    assert memo.lookup(k) is None
    assert memo.record(k, walk, post, span_len=4)      # 2nd miss
    assert memo.lookup(k) is not None
    assert memo.stats()["records"] == 1


def test_lru_byte_budget_evicts_oldest():
    one = _mk_carry(0)
    per_entry = sum(a.nbytes for _o, _f, a in memomod.walk_carry(one))
    memo = memomod.ChainMemo(max_bytes=2 * per_entry)
    keys = []
    for i in range(3):
        pre, post = _mk_carry(i), _mk_carry(i + 100)
        k, walk = _key(memo, pre)
        memo.lookup(k)
        assert memo.record(k, walk, post, span_len=1)
        keys.append(k)
    s = memo.stats()
    assert s["entries"] == 2 and s["evictions"] == 1
    assert s["bytes_cached"] <= memo.max_bytes
    assert memo.lookup(keys[0]) is None      # the evicted one
    assert memo.lookup(keys[2]) is not None  # the newest survives


def test_oversize_entry_refused():
    memo = memomod.ChainMemo(max_bytes=4)
    pre, post = _mk_carry(0), _mk_carry(1)
    k, walk = _key(memo, pre)
    memo.lookup(k)
    assert not memo.record(k, walk, post, span_len=1)
    assert memo.stats()["oversize_skips"] == 1
    assert memo.stats()["entries"] == 0


def test_unstable_span_refused():
    from shadow_tpu.guards import make_guards

    g = jax.device_get(make_guards(2))
    pre = (np.zeros((2,), np.int32), (g,))
    post = (np.ones((2,), np.int32),
            (g._replace(violations=g.violations
                        + np.ones_like(g.violations)),))
    memo = memomod.ChainMemo()
    k, walk = _key(memo, pre)
    memo.lookup(k)
    assert not memo.record(k, walk, post, span_len=1)
    assert memo.stats()["unstable_skips"] == 1
    # the SAME span with guards untouched records fine
    post_ok = (np.ones((2,), np.int32), (g,))
    memo.lookup(k)
    assert memo.record(k, walk, post_ok, span_len=1)


def test_replay_substitutes_keyed_and_wraps_counters():
    from shadow_tpu.telemetry import make_metrics

    m0 = jax.device_get(make_metrics(2))
    pre = (np.zeros((4,), np.int32),
           (m0._replace(events=np.int32(2**31 - 2)),))
    post = (np.arange(4, dtype=np.int32),
            (m0._replace(events=np.int32(-(2**31) + 3)),))  # wrapped
    memo = memomod.ChainMemo()
    k, walk = _key(memo, pre)
    memo.lookup(k)
    assert memo.record(k, walk, post, span_len=2)
    entry = memo.lookup(k)
    out = memo.replay(entry, pre)
    np.testing.assert_array_equal(out[0], post[0])
    assert out[1][0].events == post[1][0].events  # wrapped delta
    # replay onto a LIVE carry with different counter values: keyed
    # leaves still substitute, the counter advances by the same delta
    pre2 = (np.zeros((4,), np.int32),
            (m0._replace(events=np.int32(100)),))
    out2 = memo.replay(entry, pre2)
    np.testing.assert_array_equal(out2[0], post[0])
    assert out2[1][0].events == 100 + 5  # the recorded increment


def test_key_sensitivity():
    memo = memomod.ChainMemo(salt=b"s")
    carry = _mk_carry(1)
    k0, _ = memo.key(carry, 8, 12, b"")
    assert memo.key(carry, 8, 12, b"")[0] == k0          # stable
    assert memo.key(carry, 8, 16, b"")[0] != k0          # span length
    assert memo.key(carry, 0, 4, b"")[0] != \
        memo.key(carry, 4, 8, b"")[0]                    # r0 (default)
    assert memo.key(carry, 8, 12, b"faults")[0] != k0    # span salt
    assert memo.key(_mk_carry(2), 8, 12, b"")[0] != k0   # keyed bytes
    # counter leaves are OUTSIDE the key
    assert memo.key(_mk_carry(1, events=999), 8, 12, b"")[0] == k0
    # a caller-declared round-invariance predicate removes the r0 fold
    inv = memomod.ChainMemo(salt=b"s", key_extra=lambda c, r0: b"")
    assert inv.key(carry, 0, 4, b"")[0] != \
        inv.key(carry, 4, 8, b"")[0]  # r0==0 alignment still folds
    assert inv.key(carry, 4, 8, b"")[0] == inv.key(carry, 8, 12, b"")[0]


# ---------------------------------------------------------------------------
# fault-schedule span fingerprints (the chaos opt-out discipline)


def _schedule(events, windows=32, window_ns=1000, n=4):
    from shadow_tpu.core.config import FaultsOptions
    from shadow_tpu.faults.schedule import compile_schedule

    return compile_schedule(
        FaultsOptions(events=events),
        host_names=[f"h{i}" for i in range(n)], n_nodes=n, seed=1,
        stop_time_ns=(windows + 1) * window_ns)


def test_span_fingerprint_relative_times():
    # the SAME in-span event pattern at two different absolute spans
    # fingerprints EQUAL (relative times — periodic fault patterns can
    # memoize), while differing patterns never collide
    evs = lambda t: [{"at": f"{t}ns", "kind": "host_crash",
                      "host": "h1"},
                     {"at": f"{t + 500}ns", "kind": "host_reboot",
                      "host": "h1"}]
    s1, s2 = _schedule(evs(4100)), _schedule(evs(8100))
    s1.advance(4000)
    s2.advance(8000)
    assert s1.span_fingerprint(4000, 5000) == \
        s2.span_fingerprint(8000, 9000)
    # a span whose MASKS differ (crash not yet rebooted) fingerprints
    # differently even with no in-span events
    s3 = _schedule(evs(100))
    s3.advance(4000)  # h1 crashed at 100, rebooted 600: masks neutral
    s4 = _schedule([{"at": "100ns", "kind": "host_crash",
                     "host": "h1"}])
    s4.advance(4000)  # h1 still dead: mask differs
    assert s3.span_fingerprint(4000, 5000) != \
        s4.span_fingerprint(4000, 5000)


# ---------------------------------------------------------------------------
# driver refusals


def test_drive_refuses_memo_with_unsalted_per_round():
    from shadow_tpu.tpu import elastic

    with pytest.raises(ValueError, match="memo_span_salt"):
        elastic.drive_chained_windows(
            jnp.zeros((2,)), (), lambda s, e, r, p: (s, e, 0, 0),
            n_rounds=4, chain_len=2, window_ns=1000,
            per_round=lambda r0, r1: None,
            memo=memomod.ChainMemo())


def test_runner_refuses_memo_with_mesh():
    from shadow_tpu.workloads import runner
    from shadow_tpu.workloads.spec import parse_scenario

    spec = parse_scenario({
        "name": "memo-mesh-refusal", "family": "ring_allreduce",
        "seed": 3, "hosts": N, "windows": 8,
        "patterns": [{"kind": "ring_allreduce", "first": 0,
                      "count": N, "bytes": 256, "rounds": 1}],
    })
    with pytest.raises(ValueError, match="mesh"):
        runner.run_scenario(spec, memo=True, mesh_devices=2)


# ---------------------------------------------------------------------------
# end-to-end parity (@slow: full scenario executions — CI's
# memo-parity gate runs these unfiltered alongside
# `tools/run_scenarios.py --memo --check`, the shared-driver-gate
# pattern)


def _tiny_spec(windows=64):
    from shadow_tpu.workloads.spec import parse_scenario

    return parse_scenario({
        "name": "memo-parity-ring", "family": "ring_allreduce",
        "seed": 11, "hosts": N, "windows": windows,
        "patterns": [{"kind": "ring_allreduce", "first": 0,
                      "count": N, "bytes": 1024, "rounds": 1}],
    })


@pytest.mark.slow
def test_memoized_run_matches_cold_with_hits():
    from shadow_tpu.workloads import runner

    spec = _tiny_spec()
    cold = runner.run_scenario(spec)
    warm = runner.run_scenario(spec, memo=True)
    assert warm["canonical_digest"] == cold["canonical_digest"]
    assert warm["fingerprint"] == cold["fingerprint"]
    assert warm["memo"]["hits"] > 0, warm["memo"]
    assert warm["memo"]["unstable_skips"] == 0
    # the record surface: phase completions + totals identical too
    for k in ("events", "host_completion", "phase_completion_ns",
              "drops"):
        assert warm[k] == cold[k], k


@pytest.mark.slow
def test_memo_cross_run_reuse_is_pure_fast_forward():
    # a SECOND run sharing the ChainMemo instance replays every
    # steady-state span it recorded in the first (hits strictly grow)
    from shadow_tpu.core.config import MemoOptions
    from shadow_tpu.workloads import runner

    spec = _tiny_spec()
    opts = MemoOptions(enabled=True)
    first = runner.run_scenario(spec, memo=opts)
    assert first["memo"]["hits"] > 0


@pytest.mark.slow
def test_golden_corpus_memo_parity():
    # every corpus entry: memoized == cold, byte for byte, on the
    # full record surface the golden file pins — and the steady-state
    # anchors (ring_allreduce, onoff) MUST actually hit
    import glob
    import os

    from shadow_tpu.workloads import load_scenario_file, runner

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = sorted(glob.glob(os.path.join(repo, "scenarios", "*.yaml")))
    assert paths
    hits = {}
    for path in paths:
        spec = load_scenario_file(path)
        cold = runner.run_scenario(spec)
        warm = runner.run_scenario(spec, memo=True)
        assert warm["canonical_digest"] == cold["canonical_digest"], \
            spec.name
        assert runner.golden_entry(warm) == runner.golden_entry(cold), \
            spec.name
        hits[spec.name] = warm["memo"]["hits"]
    assert hits["ring-allreduce-32"] > 0, hits
    assert hits["onoff-32"] > 0, hits
