"""Memory-region bookkeeping for managed processes.

Parity: reference `src/main/host/memory_manager/mod.rs:616-709` (region
interval map maintained across brk/mmap/munmap/mprotect) seeded from
/proc/<pid>/maps (`proc_maps.rs`).
"""

import ctypes
import mmap
import os
import shutil
import subprocess

import pytest

from shadow_tpu.process.memory import (MAPPING_SYSCALLS, MemoryRegions,
                                       SYS_mmap)


def test_parse_own_maps_finds_heap_and_stack():
    regions = MemoryRegions(os.getpid())
    assert regions.heap() is not None
    assert regions.stack() is not None
    all_regions = regions.regions()
    assert len(all_regions) > 10
    assert all(r.start < r.end for r in all_regions)
    # sorted and non-overlapping, like the kernel's own table
    for a, b in zip(all_regions, all_regions[1:]):
        assert a.end <= b.start


def test_region_queries_on_live_buffer():
    regions = MemoryRegions(os.getpid())
    buf = ctypes.create_string_buffer(4096)
    addr = ctypes.addressof(buf)
    r = regions.region_at(addr)
    assert r is not None and r.read and r.write
    assert regions.is_readable(addr, 4096)
    assert regions.is_writable(addr, 4096)
    # an address far past any mapping is unmapped
    assert regions.region_at(1 << 47) is None
    assert not regions.is_readable(1 << 47, 1)
    assert "unmapped" in regions.describe(1 << 47)


def test_dirty_refresh_sees_new_mapping():
    regions = MemoryRegions(os.getpid())
    regions.regions()  # force a parse
    m = mmap.mmap(-1, 1 << 20)
    addr = ctypes.addressof(ctypes.c_char.from_buffer(m))
    # stale table may or may not cover it; after mark_dirty it must
    regions.mark_dirty()
    r = regions.region_at(addr)
    # CPython's anonymous mmap may surface as "/dev/zero (deleted)"
    assert r is not None and r.kind in ("anonymous", "file")
    assert regions.is_writable(addr, 1 << 20)
    del r
    m.close()
    regions.mark_dirty()
    assert regions.region_at(addr) is None


def test_spans_compose_across_contiguous_regions():
    regions = MemoryRegions(os.getpid())
    # read-only + read-write adjacent pair: find any two contiguous
    # readable regions and span them
    table = [r for r in regions.regions() if r.read]
    pair = next(((a, b) for a, b in zip(table, table[1:])
                 if a.end == b.start and b.read), None)
    if pair is None:
        pytest.skip("no contiguous readable pair in this process")
    a, b = pair
    assert regions.is_readable(a.end - 8, 16)  # crosses the boundary


CC = shutil.which("gcc") or shutil.which("cc")


@pytest.mark.skipif(CC is None, reason="no C compiler")
def test_managed_mmap_invalidates_region_table(tmp_path):
    """End-to-end: a managed binary's mmap/munmap passes through dispatch
    and invalidates the process's region table."""
    from shadow_tpu.core.config import load_config_str
    from shadow_tpu.core.manager import Manager

    c = tmp_path / "mapper.c"
    c.write_text(r"""
#include <sys/mman.h>
int main(void) {
    void *p = mmap(0, 1 << 20, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (p == MAP_FAILED) return 110;
    if (munmap(p, 1 << 20)) return 111;
    return 0;
}
""")
    binary = tmp_path / "mapper"
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    cfg = load_config_str(f"""
general: {{stop_time: 5s, seed: 3}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: [], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    mgr = Manager(cfg)
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    (proc,) = [cell.get("proc") for _n, _p, cell in mgr._spawned]
    assert proc.regions is not None
    # at least the test's own mmap + munmap, plus loader/libc mappings
    assert proc.regions.invalidations >= 2
    assert SYS_mmap in MAPPING_SYSCALLS
