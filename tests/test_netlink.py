"""Netlink route sockets: unit-level dump framing plus a managed C binary
running getifaddrs() against the simulated interfaces.

Parity: reference `src/main/host/descriptor/socket/netlink.rs` (RTM_GETLINK
/ RTM_GETADDR dumps) and `src/test/netlink` / `src/test/ifaddrs`.
"""

import shutil
import struct
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager
from shadow_tpu.kernel import errors
from shadow_tpu.kernel.socket.netlink import (NLM_F_ACK, NLM_F_DUMP,
                                              NLM_F_MULTI, NLM_F_REQUEST,
                                              NLMSG_DONE, NLMSG_ERROR,
                                              RTM_GETADDR, RTM_GETLINK,
                                              RTM_NEWADDR, RTM_NEWLINK,
                                              NetlinkSocket)

CONFIG = """
general:
  stop_time: 1s
  seed: 7
network:
  graph:
    type: 1_gbit_switch
hosts:
  alpha:
    network_node_id: 0
    ip_addr: 11.0.0.1
"""


def _host():
    return Manager(load_config_str(CONFIG)).hosts[0]


def _req(msg_type: int, flags: int, seq: int) -> bytes:
    # empty ifinfomsg/ifaddrmsg payloads are what glibc sends for dumps
    payload = b"\x00" * 16
    return struct.pack("<IHHII", 16 + len(payload), msg_type,
                       NLM_F_REQUEST | flags, seq, 0) + payload


def _parse_msgs(dgram: bytes):
    msgs = []
    off = 0
    while off + 16 <= len(dgram):
        ln, t, fl, seq, pid = struct.unpack_from("<IHHII", dgram, off)
        msgs.append((t, fl, seq, pid, dgram[off + 16:off + ln]))
        off += (ln + 3) & ~3
    return msgs


def _parse_rtattrs(payload: bytes, fixed: int):
    attrs = {}
    off = fixed
    while off + 4 <= len(payload):
        ln, t = struct.unpack_from("<HH", payload, off)
        if ln < 4:
            break
        attrs[t] = payload[off + 4:off + ln]
        off += (ln + 3) & ~3
    return attrs


def test_getlink_dump_lists_lo_and_eth0():
    sock = NetlinkSocket(_host())
    sock.sendto(_req(RTM_GETLINK, NLM_F_DUMP, 101), None)
    part, _src, _ln = sock.recvfrom(1 << 16)
    msgs = _parse_msgs(part)
    assert [m[0] for m in msgs] == [RTM_NEWLINK, RTM_NEWLINK]
    names = []
    for t, fl, seq, pid, payload in msgs:
        assert fl & NLM_F_MULTI
        assert seq == 101
        attrs = _parse_rtattrs(payload, 16)
        names.append(attrs[3].rstrip(b"\x00").decode())  # IFLA_IFNAME
    assert names == ["lo", "eth0"]
    done, _src, _ln = sock.recvfrom(1 << 16)
    assert _parse_msgs(done)[0][0] == NLMSG_DONE


def test_getaddr_dump_carries_simulated_ips():
    sock = NetlinkSocket(_host())
    sock.sendto(_req(RTM_GETADDR, NLM_F_DUMP, 7), None)
    part, _src, _ln = sock.recvfrom(1 << 16)
    msgs = _parse_msgs(part)
    assert [m[0] for m in msgs] == [RTM_NEWADDR, RTM_NEWADDR]
    ips = []
    for _t, _fl, _seq, _pid, payload in msgs:
        attrs = _parse_rtattrs(payload, 8)
        ips.append(".".join(str(b) for b in attrs[1]))  # IFA_ADDRESS
    assert ips == ["127.0.0.1", "11.0.0.1"]


def test_unsupported_request_gets_nlmsg_error():
    sock = NetlinkSocket(_host())
    RTM_GETROUTE = 26
    sock.sendto(_req(RTM_GETROUTE, NLM_F_DUMP | NLM_F_ACK, 9), None)
    part, _src, _ln = sock.recvfrom(1 << 16)
    t, _fl, seq, _pid, payload = _parse_msgs(part)[0]
    assert t == NLMSG_ERROR
    assert seq == 9
    (code,) = struct.unpack_from("<i", payload, 0)
    assert code == -errors.EOPNOTSUPP


def test_peek_and_trunc_semantics():
    """glibc sizes its buffer with a MSG_PEEK|MSG_TRUNC probe: the probe
    must report the full datagram length without consuming it."""
    sock = NetlinkSocket(_host())
    sock.sendto(_req(RTM_GETLINK, NLM_F_DUMP, 1), None)
    _data, _src, full = sock.recvfrom(1, peek=True)
    assert full > 16
    data, _src, ln = sock.recvfrom(1 << 16)
    assert len(data) == full == ln
    # queue still has the DONE datagram
    done, _src, _ln = sock.recvfrom(1 << 16)
    assert _parse_msgs(done)[0][0] == NLMSG_DONE
    with pytest.raises(errors.Blocked):
        sock.recvfrom(1 << 16)


def test_queue_overflow_surfaces_enobufs():
    """When the reply queue overflows (a DONE terminator may have been
    dropped), the next recv must fail with ENOBUFS rather than leave the
    reader hanging for a terminator that never comes. Like Linux, the
    pending sk_err surfaces BEFORE queued data (__skb_try_recv_datagram
    consumes sock_error() ahead of the dequeue), which is what lets a
    libnl-style dump loop restart immediately."""
    sock = NetlinkSocket(_host())
    for i in range(40):  # 2 datagrams per dump > RECV_QUEUE_MAX=64
        sock.sendto(_req(RTM_GETLINK, NLM_F_DUMP, i), None)
    # like Linux's sk_err, the pending error surfaces before queued data
    with pytest.raises(errors.SyscallError) as e:
        sock.recvfrom(1 << 16)
    assert e.value.errno == errors.ENOBUFS
    drained = 0
    with pytest.raises(errors.Blocked):
        for _ in range(200):
            sock.recvfrom(1 << 16)
            drained += 1
    assert drained == 64
    # after the error the socket is usable again
    sock.sendto(_req(RTM_GETADDR, NLM_F_DUMP, 99), None)
    part, _src, _ln = sock.recvfrom(1 << 16)
    assert _parse_msgs(part)[0][0] == RTM_NEWADDR


# ---------------------------------------------------------------------------
# end-to-end: a managed native binary calls getifaddrs()
# ---------------------------------------------------------------------------

CC = shutil.which("gcc") or shutil.which("cc")

IFADDRS_C = r"""
#include <arpa/inet.h>
#include <ifaddrs.h>
#include <netinet/in.h>
#include <string.h>
#include <sys/socket.h>

int main(int argc, char **argv) {
    const char *want = argv[1]; /* the host's simulated public IP */
    struct ifaddrs *ifa0, *ifa;
    if (getifaddrs(&ifa0)) return 50;
    int saw_lo = 0, saw_eth = 0;
    for (ifa = ifa0; ifa; ifa = ifa->ifa_next) {
        if (!ifa->ifa_addr || ifa->ifa_addr->sa_family != AF_INET)
            continue;
        char ip[INET_ADDRSTRLEN];
        struct sockaddr_in *sa = (struct sockaddr_in *)ifa->ifa_addr;
        inet_ntop(AF_INET, &sa->sin_addr, ip, sizeof ip);
        if (!strcmp(ifa->ifa_name, "lo") && !strcmp(ip, "127.0.0.1"))
            saw_lo = 1;
        if (!strcmp(ifa->ifa_name, "eth0") && !strcmp(ip, want))
            saw_eth = 1;
    }
    freeifaddrs(ifa0);
    if (!saw_lo) return 51;
    if (!saw_eth) return 52;
    return 0;
}
"""


@pytest.mark.skipif(CC is None, reason="no C compiler")
def test_managed_getifaddrs_sees_simulated_interfaces(tmp_path):
    c = tmp_path / "ifaddrs.c"
    c.write_text(IFADDRS_C)
    binary = tmp_path / "ifaddrs"
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    cfg = load_config_str(f"""
general: {{stop_time: 5s, seed: 3}}
network:
  graph:
    type: 1_gbit_switch
hosts:
  alpha:
    network_node_id: 0
    ip_addr: 11.0.0.5
    processes:
    - {{path: {binary}, args: ["11.0.0.5"], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures
