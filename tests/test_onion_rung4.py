"""Rung-4 shape at CI scale: REAL compiled onion relays doing layered
store-and-forward over a latency GML, clients pushing payloads through
3-hop circuits (tools/onion/{relay,client}.c). Reference analogue:
the minimal Tor network test (`src/test/tor/minimal/tor-minimal.yaml`)
— no tor binary exists on this image, so the SHAPE is rebuilt with
purpose-built relays (BASELINE.md rung 4)."""

import os
import shutil
import subprocess
import tempfile

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

pytestmark = pytest.mark.skipif(shutil.which("gcc") is None,
                                reason="no gcc")

GML = """\
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        node [ id 1 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
        edge [ source 0 target 0 latency "5 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "30 ms" packet_loss 0.0 ]
        edge [ source 1 target 1 latency "5 ms" packet_loss 0.0 ]
      ]
"""


def test_onion_circuits_complete():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tmp = tempfile.mkdtemp(prefix="onion-test-")
    for name in ("relay", "client"):
        subprocess.run(
            ["gcc", "-O1", "-o", f"{tmp}/{name}",
             os.path.join(here, "tools", "onion", f"{name}.c")],
            check=True)

    n_relays, n_clients = 6, 2
    rip = lambda r: f"10.4.0.{r + 1}"
    hosts = []
    for r in range(n_relays):
        hosts.append(
            f"  relay{r}:\n    network_node_id: {r % 2}\n"
            f"    ip_addr: {rip(r)}\n    processes:\n"
            f"    - {{path: {tmp}/relay, args: ['7000'], start_time: 1s,\n"
            f"       expected_final_state: running}}")
    for c in range(n_clients):
        g, m, e = c, (c + 2) % n_relays, (c + 4) % n_relays
        hosts.append(
            f"  client{c}:\n    network_node_id: {c % 2}\n"
            f"    ip_addr: 10.5.0.{c + 1}\n    processes:\n"
            f"    - {{path: {tmp}/client, args: ['{rip(g)}', '7000', "
            f"'{rip(m)}', '7000', '{rip(e)}', '7000', '16384'], "
            f"start_time: 2s,\n"
            f"       expected_final_state: {{exited: 0}}}}")
    cfg = load_config_str(
        "general: {stop_time: 20s, seed: 1}\n"
        "network:\n  graph:\n    type: gml\n    inline: |\n" + GML +
        "hosts:\n" + "\n".join(hosts))
    stats = Manager(cfg, data_dir=f"{tmp}/data").run()
    assert stats.process_failures == [], stats.process_failures
    for c in range(n_clients):
        out = open(f"{tmp}/data/hosts/client{c}/"
                   f"client{c}.client.0.stdout").read()
        assert "circuit complete: 16384 bytes through 3 hops" in out
