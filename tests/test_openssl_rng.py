"""Deterministic OpenSSL RNG preload for managed binaries.

Parity: reference `src/lib/preload-openssl/rng.c` — libcrypto's RAND
entry points are shadowed so TLS apps draw from the simulated, seeded
getrandom stream instead of RDRAND/jitter entropy.
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")

RAND_C = r"""
#include <stdio.h>

extern int RAND_bytes(unsigned char *buf, int num);
extern int RAND_status(void);

int main(void) {
    if (!RAND_status()) return 90;
    unsigned char buf[32];
    if (RAND_bytes(buf, sizeof buf) != 1) return 91;
    for (unsigned i = 0; i < sizeof buf; i++) printf("%02x", buf[i]);
    printf("\n");
    return 0;
}
"""


def _compile(tmp_path):
    from shadow_tpu import interpose

    c = tmp_path / "randbytes.c"
    c.write_text(RAND_C)
    binary = tmp_path / "randbytes"
    # link against real libcrypto when present (true interposition test);
    # otherwise against the preload itself (still exercises the
    # raw-getrandom path through the seccomp trap)
    try:
        subprocess.run([CC, "-O1", "-o", str(binary), str(c), "-lcrypto"],
                       check=True, capture_output=True)
    except subprocess.CalledProcessError:
        interpose.build()  # the fallback links the built preload library
        lib = interpose.PRELOAD_OPENSSL_LIB_PATH
        import os

        subprocess.run(
            [CC, "-O1", "-o", str(binary), str(c), lib,
             f"-Wl,-rpath,{os.path.dirname(lib)}"],
            check=True, capture_output=True)
    return str(binary)


def _run(binary, tmp_path, tag, seed):
    data = tmp_path / f"data-{tag}"
    cfg = load_config_str(f"""
general: {{stop_time: 5s, seed: {seed}}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: [], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg, data_dir=str(data)).run()
    assert stats.process_failures == [], stats.process_failures
    out = list(data.glob("hosts/alpha/*.stdout"))
    assert out, "no stdout captured"
    text = out[0].read_text().strip()
    assert len(text) == 64 and int(text, 16) >= 0  # 32 hex bytes
    return text


def test_rand_bytes_deterministic_per_seed(tmp_path):
    binary = _compile(tmp_path)
    a = _run(binary, tmp_path, "a", seed=21)
    b = _run(binary, tmp_path, "b", seed=21)
    assert a == b  # same seed, same stream — the whole point
    c = _run(binary, tmp_path, "c", seed=22)
    assert c != a  # different seed, different stream
