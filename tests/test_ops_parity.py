"""Ops-parity subsystems: manager rusage heartbeat (tornettools contract),
resource watchdogs, status printer, perf timers, and the parse/plot tools.

Parity: reference `manager.rs:675-793` (heartbeat + watchdogs),
`controller.rs:116-168` (status), `host.rs:722-730` + `handler/mod.rs:84-89`
(perf timers), `src/tools/parse-shadow.py` / `plot-shadow.py`.
"""

import json
import logging
import sys

import pytest

sys.path.insert(0, ".")

from shadow_tpu.core import resource_usage, simtime
from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.event import TaskRef
from shadow_tpu.core.manager import Manager
from tools.parse_shadow import HEARTBEAT_RE, MEMINFO_RE, RUSAGE_RE, \
    parse_stream

MS = simtime.MILLISECOND

BASE = """
general: {{stop_time: 5s, seed: 7, heartbeat_interval: {hb}}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha: {{network_node_id: 0}}
  beta: {{network_node_id: 0}}
"""


def _busy_config(extra=""):
    # a PHOLD-ish pair so rounds actually advance across 5s of sim time
    # (bare ints in time fields are seconds, so spell the unit out)
    return load_config_str(BASE.format(hb="1s") + extra)


def _add_ticker(mgr):
    """Keep the event loop busy so windows progress through sim time."""
    def tick(host):
        host.schedule_task_with_delay(TaskRef(tick, "tick"), 100 * MS)
    for host in mgr.hosts:
        host.add_application(0, lambda h: tick(h))


# ---------------------------------------------------------------------------
# resource probes
# ---------------------------------------------------------------------------


def test_meminfo_parses_to_bytes():
    info = resource_usage.meminfo()
    assert info["MemTotal"] > 1 << 20  # bytes, not KiB
    assert "MemAvailable" in info


def test_fd_usage_sane():
    usage, limit = resource_usage.fd_usage()
    assert 0 < usage < limit


def test_memory_remaining_positive():
    assert resource_usage.memory_remaining() > 0


# ---------------------------------------------------------------------------
# manager heartbeat + watchdogs + progress
# ---------------------------------------------------------------------------


def test_rusage_heartbeat_matches_tornettools_contract(caplog):
    mgr = Manager(_busy_config())
    _add_ticker(mgr)
    with caplog.at_level(logging.INFO, logger="shadow_tpu.manager"):
        mgr.run()
    rusage_lines = [r.getMessage() for r in caplog.records
                    if "getrusage" in r.getMessage()]
    assert len(rusage_lines) >= 4  # ~1 per simulated second
    m = RUSAGE_RE.search(rusage_lines[0])
    assert m, rusage_lines[0]
    meminfo_lines = [r.getMessage() for r in caplog.records
                     if "/proc/meminfo" in r.getMessage()]
    assert meminfo_lines
    m2 = MEMINFO_RE.search(meminfo_lines[0])
    assert m2
    assert json.loads(m2.group(2))["MemTotal"] > 0


def test_progress_printer_emits_status_lines(capsys):
    cfg = load_config_str(BASE.format(hb="null"))
    cfg.general.progress = True
    mgr = Manager(cfg)
    _add_ticker(mgr)
    mgr._last_progress = -10.0  # force at least one line immediately
    mgr.run()
    err = capsys.readouterr().err
    assert "simulated:" in err and "processes failed: 0" in err


def test_watchdogs_warn_once(caplog, monkeypatch):
    mgr = Manager(_busy_config())
    monkeypatch.setattr(resource_usage, "fd_usage", lambda: (95, 100))
    monkeypatch.setattr(resource_usage, "memory_remaining",
                        lambda: 100 * 1024 * 1024)
    with caplog.at_level(logging.WARNING, logger="shadow_tpu.manager"):
        mgr._check_resource_usage()
        mgr._check_resource_usage()  # second pass must not re-warn
    fd_warns = [r for r in caplog.records if "file descriptors" in
                r.getMessage()]
    mem_warns = [r for r in caplog.records if "MiB of memory" in
                 r.getMessage()]
    assert len(fd_warns) == 1 and len(mem_warns) == 1


# ---------------------------------------------------------------------------
# perf timers
# ---------------------------------------------------------------------------


def test_perf_timers_accumulate_and_surface():
    mgr = Manager(_busy_config("experimental: {use_perf_timers: true}\n"))
    _add_ticker(mgr)
    mgr.run()
    assert all(h.execution_ns > 0 for h in mgr.hosts)
    stats = mgr.host_stats()
    assert stats["alpha"]["perf"]["execution_ns"] > 0


def test_perf_timers_off_by_default():
    mgr = Manager(_busy_config())
    _add_ticker(mgr)
    mgr.run()
    assert all(h.execution_ns == 0 for h in mgr.hosts)
    assert "perf" not in mgr.host_stats().get("alpha", {})


# ---------------------------------------------------------------------------
# parse + plot tools
# ---------------------------------------------------------------------------


def test_parse_stream_extracts_all_series(caplog):
    mgr = Manager(_busy_config())
    _add_ticker(mgr)
    with caplog.at_level(logging.INFO):
        mgr.run()
    log_text = "\n".join(r.getMessage() for r in caplog.records)
    stats = parse_stream(log_text.splitlines())
    assert set(stats["nodes"]) == {"alpha", "beta"}
    alpha = stats["nodes"]["alpha"]
    assert len(alpha["time_ns"]) >= 4  # per-second tracker heartbeats
    assert "packets_out" in alpha["counters"][0]
    assert len(stats["rusage"]) >= 4
    assert stats["meminfo"] and stats["meminfo"][0]["MemTotal"] > 0


def test_heartbeat_line_is_json_parseable(caplog):
    mgr = Manager(_busy_config())
    _add_ticker(mgr)
    with caplog.at_level(logging.INFO, logger="shadow_tpu.tracker"):
        mgr.run()
    hb = [r.getMessage() for r in caplog.records
          if r.getMessage().startswith("heartbeat ")]
    assert hb
    m = HEARTBEAT_RE.search(hb[0])
    assert m
    assert "packets_in" in json.loads(m.group(3))


def test_plot_tool_writes_figures(tmp_path, caplog):
    pytest.importorskip("matplotlib")
    from tools import plot_shadow

    mgr = Manager(_busy_config())
    _add_ticker(mgr)
    with caplog.at_level(logging.INFO):
        mgr.run()
    stats = parse_stream(
        "\n".join(r.getMessage() for r in caplog.records).splitlines())
    data = tmp_path / "stats.shadow.json"
    data.write_text(json.dumps(stats))
    prefix = str(tmp_path / "plots")
    rc = plot_shadow.main(["-d", str(data), "run1", "-p", prefix,
                           "--format", "png"])
    assert rc == 0
    assert (tmp_path / "plots.bytes_out.png").exists()


def test_strip_log_for_compare_removes_wall_lines():
    from tools.strip_log_for_compare import strip

    lines = [
        "00:01 [INFO] [-] m: Process resource usage at simtime 5 "
        "reported by getrusage(): ru_maxrss=0.1 GiB\n",
        "00:01 [INFO] [-] m: System memory usage in bytes at simtime 5 ns "
        "reported by /proc/meminfo: {}\n",
        "2026-07-30 12:00:00,123 00:01 [INFO] [alpha] t: heartbeat "
        "host=alpha time_ns=5 {}\n",
        "00:01 [INFO] [alpha] x: simulated content\n",
    ]
    out = list(strip(lines))
    assert out == [
        "00:01 [INFO] [alpha] t: heartbeat host=alpha time_ns=5 {}\n",
        "00:01 [INFO] [alpha] x: simulated content\n",
    ]
