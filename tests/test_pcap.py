"""Round-trip coverage for the pcap writer (`utils/pcap.py`).

Reads back the written global header and per-record headers with a
minimal in-test pcap parser and verifies the snaplen (capture-size)
truncation path: a frame longer than the snaplen is stored truncated
with `incl_len == snaplen` and `orig_len == full frame length`, and the
record stream stays aligned afterwards (the record that FOLLOWS a
truncated one parses cleanly)."""

import io
import struct

from shadow_tpu.net.packet import Packet, Protocol, TcpHeader
from shadow_tpu.utils.pcap import LINKTYPE_ETHERNET, PCAP_MAGIC, PcapWriter

ETH_LEN = 14
IP_LEN = 20
TCP_LEN = 20
UDP_LEN = 8


def parse_pcap(data: bytes):
    """(global_header dict, [record dict]) from classic pcap bytes."""
    (magic, major, minor, thiszone, sigfigs, snaplen,
     linktype) = struct.unpack_from("<IHHiIII", data, 0)
    records = []
    off = 24
    while off < len(data):
        sec, usec, incl, orig = struct.unpack_from("<IIII", data, off)
        off += 16
        frame = data[off:off + incl]
        assert len(frame) == incl, "truncated record body"
        off += incl
        records.append({"sec": sec, "usec": usec, "incl_len": incl,
                        "orig_len": orig, "frame": frame})
    assert off == len(data), "trailing bytes after the last record"
    return {
        "magic": magic, "version": (major, minor), "thiszone": thiszone,
        "sigfigs": sigfigs, "snaplen": snaplen, "linktype": linktype,
    }, records


def _tcp_packet(payload: bytes, seq=7, ack=9, flags=0x18, window=4096):
    return Packet(Protocol.TCP, ("10.0.0.1", 80), ("10.0.0.2", 8080),
                  payload,
                  header=TcpHeader(seq=seq, ack=ack, window=window,
                                   flags=flags))


def _udp_packet(payload: bytes):
    return Packet(Protocol.UDP, ("10.0.0.3", 53), ("10.0.0.4", 5353),
                  payload)


def test_global_header_round_trip():
    buf = io.BytesIO()
    PcapWriter(buf, capture_size=1234)
    header, records = parse_pcap(buf.getvalue())
    assert header["magic"] == PCAP_MAGIC
    assert header["version"] == (2, 4)
    assert header["snaplen"] == 1234
    assert header["linktype"] == LINKTYPE_ETHERNET
    assert records == []


def test_untruncated_records_round_trip():
    buf = io.BytesIO()
    w = PcapWriter(buf, capture_size=65535)
    w.record(_tcp_packet(b"hello tcp"), 1_500_000_000)
    w.record(_udp_packet(b"hello udp!"), 2_000_001_000)
    _header, records = parse_pcap(buf.getvalue())
    assert len(records) == 2

    tcp = records[0]
    assert (tcp["sec"], tcp["usec"]) == (1, 500_000)
    assert tcp["incl_len"] == tcp["orig_len"] == \
        ETH_LEN + IP_LEN + TCP_LEN + len(b"hello tcp")
    # ethernet ethertype = IPv4, IP proto = TCP, ports + seq/ack intact
    assert tcp["frame"][12:14] == b"\x08\x00"
    assert tcp["frame"][ETH_LEN + 9] == 6
    sport, dport, seq, ack = struct.unpack_from(
        ">HHII", tcp["frame"], ETH_LEN + IP_LEN)
    assert (sport, dport, seq, ack) == (80, 8080, 7, 9)
    assert tcp["frame"].endswith(b"hello tcp")

    udp = records[1]
    assert (udp["sec"], udp["usec"]) == (2, 1)
    assert udp["incl_len"] == ETH_LEN + IP_LEN + UDP_LEN + len(b"hello udp!")
    assert udp["frame"][ETH_LEN + 9] == 17
    udp_len = struct.unpack_from(">H", udp["frame"], ETH_LEN + IP_LEN + 4)[0]
    assert udp_len == UDP_LEN + len(b"hello udp!")


def test_snaplen_truncates_and_stream_stays_aligned():
    snaplen = 60  # below eth+ip+tcp+payload, above the headers
    buf = io.BytesIO()
    w = PcapWriter(buf, capture_size=snaplen)
    big = _tcp_packet(b"x" * 400)
    w.record(big, 3_000_000_000)
    w.record(_udp_packet(b"ok"), 4_000_000_000)  # must still parse
    header, records = parse_pcap(buf.getvalue())
    assert header["snaplen"] == snaplen

    truncated = records[0]
    full_len = ETH_LEN + IP_LEN + TCP_LEN + 400
    assert truncated["incl_len"] == snaplen
    assert truncated["orig_len"] == full_len
    assert len(truncated["frame"]) == snaplen
    # the stored prefix is the real frame prefix: the IP total-length
    # field still announces the ORIGINAL datagram size
    ip_total = struct.unpack_from(">H", truncated["frame"], ETH_LEN + 2)[0]
    assert ip_total == IP_LEN + TCP_LEN + 400

    tail = records[1]
    assert tail["incl_len"] == tail["orig_len"] == \
        ETH_LEN + IP_LEN + UDP_LEN + 2
    assert tail["frame"].endswith(b"ok")


def test_frame_exactly_snaplen_not_truncated():
    payload = b"y" * 10
    full_len = ETH_LEN + IP_LEN + UDP_LEN + len(payload)
    buf = io.BytesIO()
    w = PcapWriter(buf, capture_size=full_len)
    w.record(_udp_packet(payload), 0)
    _header, records = parse_pcap(buf.getvalue())
    assert records[0]["incl_len"] == records[0]["orig_len"] == full_len


def test_oversize_window_clamped_to_u16():
    buf = io.BytesIO()
    w = PcapWriter(buf, capture_size=65535)
    w.record(_tcp_packet(b"", window=1 << 20), 0)
    _header, records = parse_pcap(buf.getvalue())
    window = struct.unpack_from(
        ">H", records[0]["frame"], ETH_LEN + IP_LEN + 14)[0]
    assert window == 0xFFFF
