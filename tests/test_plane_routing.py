"""Routing-plane sort diet (bucketed route-scatter + packed flat ingest
+ fused Pallas routing stage).

The PR-2 sort diet left one variadic sort standing: the flat [N*CE]
4-key routing sort in `_route_scatter`. The bucketed rebuild replaces it
with counting placement over a diet sort; this file pins what
tests/test_plane_sortdiet.py's base matrix does not reach:

- the metrics/faults/guards static presence switches thread through the
  bucketed path with bitwise-identical state AND identical accumulator
  contents vs the `packed_sort=False` reference (overflow attribution,
  fault dst-blocking, routed-arrivals conservation);
- the packed flat `ingest` append (bucketed counting placement) against
  its 9-array variadic reference, including overflow and guards;
- the fused Pallas routing kernel (`tpu/pallas_route.py`, interpret
  mode on CPU) directly against the XLA scatters, with overflow forced;
- the profiler's routing_rank/routing_place split and the bench
  `sections` plumbing (tools/compare_runs.py --bench).
"""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.faults.plane import FaultArrays  # noqa: E402
from shadow_tpu.guards.plane import make_guards, summarize  # noqa: E402
from shadow_tpu.telemetry import make_metrics  # noqa: E402
from shadow_tpu.tpu import ingest, make_params, make_state  # noqa: E402
from shadow_tpu.tpu.plane import window_step  # noqa: E402

MS = 1_000_000
N = 8


def busy_world(rr_mix=True, *, ingress_cap=8, seed=7):
    """The test_plane_sortdiet busy world: starved buckets, real loss,
    duplicate priorities — every tiebreak path exercised."""
    rng = np.random.default_rng(seed)
    lat = rng.integers(1 * MS, 20 * MS, size=(N, N)).astype(np.int32)
    loss = np.full((N, N), 0.3, np.float32)
    qrr = (np.arange(N) % 2 == 0) if rr_mix else np.zeros(N, bool)
    params = make_params(lat, loss, np.full((N,), 80_000, np.int64),
                         qdisc_rr=qrr, down_bw_bps=np.full((N,), 400_000))
    state = make_state(N, egress_cap=8, ingress_cap=ingress_cap,
                       params=params,
                       initial_tokens=np.asarray(params.tb_cap))
    b = 48
    state = ingest(
        state,
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(100, 1500, b), jnp.int32),
        jnp.asarray(rng.integers(0, 6, b), jnp.int32),
        jnp.arange(b, dtype=jnp.int32),
        jnp.asarray(rng.integers(0, 3, b) == 0),
        sock=jnp.asarray(rng.integers(0, 40, b), jnp.int32),
    )
    return state, params


def active_faults():
    """A genuinely-active mask set: two dead/blocked hosts (their queued
    egress purges, routing toward them drops — the dst-blocking leg),
    degraded links and bandwidth, some corruption."""
    lat_mult = np.ones((N, N), np.int32)
    lat_mult[1, :] = 3
    return FaultArrays(
        host_alive=jnp.asarray(np.arange(N) != 2),
        link_up=jnp.asarray(np.arange(N) != 5),
        lat_mult=jnp.asarray(lat_mult),
        bw_div=jnp.asarray(np.where(np.arange(N) == 3, 4, 1)
                           .astype(np.int32)),
        corrupt_p=jnp.asarray(np.where(np.arange(N) == 1, 0.5, 0.0)
                              .astype(np.float32)),
    )


def run_windows(state, params, *, windows=4, extra=None, **kw):
    """Chain windows; `extra` (metrics/faults/guards pytrees) rides
    through every step. Returns [(state, delivered, next, extra_out)]."""
    key = jax.random.key(3)
    out = []
    shift = jnp.int32(0)
    for _ in range(windows):
        res = window_step(state, params, key, shift, jnp.int32(10 * MS),
                          **kw, **(extra or {}))
        if extra and "metrics" in extra:
            state, delivered, nxt, extra["metrics"] = res
            extra_out = extra["metrics"]
        elif extra and "guards" in extra:
            state, delivered, nxt, extra["guards"] = res
            extra_out = extra["guards"]
        else:
            state, delivered, nxt = res
            extra_out = None
        out.append((state, delivered, nxt, extra_out))
        shift = jnp.int32(10 * MS)
    return out


def assert_runs_equal(a, b, ctx):
    for w, ((sa, da, na, xa), (sb, db, nb, xb)) in enumerate(zip(a, b)):
        for la, lb in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), (ctx, w)
        for k in da:
            assert np.array_equal(np.asarray(da[k]),
                                  np.asarray(db[k])), (ctx, w, k)
        assert int(na) == int(nb), (ctx, w)
        if xa is not None or xb is not None:
            for la, lb in zip(jax.tree.leaves(xa), jax.tree.leaves(xb)):
                assert np.array_equal(np.asarray(la),
                                      np.asarray(lb)), (ctx, w, "extra")


# -- threading: the presence switches flow through the bucketed path ------

@pytest.mark.slow  # faulted reference-vs-bucketed sweep (~20s both
# cells); stays GATING in CI's tier-1-overflow unfiltered step
@pytest.mark.parametrize("router_aqm", [False, True])
def test_bucketed_routing_with_active_faults_matches_reference(router_aqm):
    """Fault dst-blocking, egress purge, latency/bw degradation and
    corruption all thread through the bucketed route-scatter unchanged:
    state, delivered sets, and the n_fault_dropped attribution are
    bitwise the packed_sort=False reference's."""
    state, params = busy_world(rr_mix=False)
    kw = dict(rr_enabled=False, router_aqm=router_aqm,
              faults=active_faults())
    packed = run_windows(state, params, packed_sort=True, **kw)
    ref = run_windows(state, params, packed_sort=False, **kw)
    assert_runs_equal(packed, ref, ("faults", router_aqm))
    # the fault plane actually did something (dead test guard)
    assert int(packed[-1][0].n_fault_dropped.sum()) > 0


@pytest.mark.slow  # guarded reference-vs-bucketed sweep (~16s both
# cells); stays GATING in CI's tier-1-overflow unfiltered step
@pytest.mark.parametrize("router_aqm", [False, True])
def test_bucketed_routing_with_guards_matches_reference(router_aqm):
    """The guards' routed-arrivals conservation term (ingress occupancy
    + arrivals == drops + deliveries + exit occupancy) holds over the
    bucketed scatter, accumulates identically to the reference, and
    stays clean on a healthy world."""
    state, params = busy_world()
    kw = dict(rr_enabled=True, router_aqm=router_aqm)
    packed = run_windows(state, params, packed_sort=True,
                         extra={"guards": make_guards(N)}, **kw)
    ref = run_windows(state, params, packed_sort=False,
                      extra={"guards": make_guards(N)}, **kw)
    assert_runs_equal(packed, ref, ("guards", router_aqm))
    report = summarize(packed[-1][3])
    assert report["clean"], report


def test_bucketed_routing_with_metrics_matches_reference():
    """Overflow attribution (drop_ring_full), traffic counters, and the
    depth high-water marks come out of the bucketed path bit-identical
    to the reference — with ring overflow actually forced: fat pipes,
    tiny ingress rings, and everything routed at two hot hosts."""
    rng = np.random.default_rng(3)
    lat = np.full((N, N), 2 * MS, np.int32)
    params = make_params(lat, np.zeros((N, N), np.float32),
                         np.full((N,), 10_000_000_000, np.int64))
    state = make_state(N, egress_cap=8, ingress_cap=2, params=params,
                       initial_tokens=np.asarray(params.tb_cap))
    b = 64
    state = ingest(
        state,
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(0, 2, b), jnp.int32),  # hot dsts
        jnp.full((b,), 200, jnp.int32),
        jnp.asarray(rng.integers(0, 6, b), jnp.int32),
        jnp.arange(b, dtype=jnp.int32),
        jnp.zeros((b,), bool),
    )
    kw = dict(rr_enabled=False, no_loss=True)
    packed = run_windows(state, params, packed_sort=True,
                         extra={"metrics": make_metrics(N)}, **kw)
    ref = run_windows(state, params, packed_sort=False,
                      extra={"metrics": make_metrics(N)}, **kw)
    assert_runs_equal(packed, ref, ("metrics",))
    assert int(packed[-1][3].drop_ring_full.sum()) > 0  # overflow seen


# -- packed flat ingest ---------------------------------------------------

def test_packed_ingest_matches_variadic():
    """The bucketed counting-placement ingest == the 9-array 2-key
    variadic reference: same rings, same overflow, same guard
    accumulator — including an overflowing batch and duplicate
    (src, seq) pairs (stability must break ties by batch order)."""
    state, params = busy_world()
    rng = np.random.default_rng(11)
    for b, hi in ((40, N), (200, 3)):  # second batch overflows rows
        src = jnp.asarray(rng.integers(0, hi, b), jnp.int32)
        dst = jnp.asarray(rng.integers(0, N, b), jnp.int32)
        nbytes = jnp.asarray(rng.integers(100, 1500, b), jnp.int32)
        prio = jnp.asarray(rng.integers(0, 6, b), jnp.int32)
        seq = jnp.asarray(rng.integers(0, 8, b), jnp.int32)  # dup seqs
        ctrl = jnp.zeros((b,), bool)
        valid = jnp.asarray(rng.integers(0, 4, b) > 0)
        got, g1 = ingest(state, src, dst, nbytes, prio, seq, ctrl,
                         valid=valid, guards=make_guards(N))
        ref, g2 = ingest(state, src, dst, nbytes, prio, seq, ctrl,
                         valid=valid, packed_sort=False,
                         guards=make_guards(N))
        for la, lb in zip(jax.tree.leaves(got), jax.tree.leaves(ref)):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), b
        for la, lb in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), b
        assert summarize(g1)["clean"]
    assert int(got.n_overflow_dropped.sum()) > 0  # the b=200 batch


# -- the fused Pallas routing stage ---------------------------------------

def test_pallas_route_matches_xla_scatters_with_overflow():
    """`pallas_route.route_scatter` (interpret mode on CPU) is bitwise
    the XLA diet path — merged columns, valid mask, and per-host
    overflow — on a world whose ingress rows overflow."""
    from shadow_tpu.tpu import pallas_route
    from shadow_tpu.tpu.plane import (I32_MAX, _compact_ingress,
                                      _route_scatter)

    state, params = busy_world(rr_mix=False, ingress_cap=4)
    rng = np.random.default_rng(0)
    CE, CI = 8, 4
    sent = jnp.asarray(rng.integers(0, 2, (N, CE)) == 0)
    deliver = jnp.asarray(rng.integers(-5 * MS, 15 * MS, (N, CE)),
                          jnp.int32)
    # a hot destination so at least one bucket overflows its free slots
    eg_dst = jnp.asarray(rng.integers(0, 3, (N, CE)), jnp.int32)
    in_deliver = jnp.where(state.in_valid, state.in_deliver_rel, I32_MAX)
    compact = _compact_ingress(state, in_deliver, packed_sort=True)
    (in_deliver_c, in_src_c, in_seq_c, in_sock_c, in_bytes_c, in_valid_c,
     n_valid_in) = compact
    args = (sent, eg_dst, state.eg_seq, state.eg_bytes, state.eg_sock,
            deliver, in_deliver_c, in_src_c, in_seq_c, in_sock_c,
            in_bytes_c, in_valid_c, n_valid_in)
    got = jax.jit(pallas_route.route_scatter)(*args)
    ref = jax.jit(lambda *a: _route_scatter(*a, packed_sort=True))(*args)
    for la, lb in zip(got, ref):
        assert np.array_equal(np.asarray(la), np.asarray(lb))
    assert int(got[-1].sum()) > 0  # overflow exercised


def test_pallas_kernel_refuses_legacy_sort():
    """kernel='pallas' implements the packed/bucketed ordering only: the
    contradictory combination with the packed_sort=False parity
    reference must be refused at trace time (like rr/faults/guards),
    never silently mislabel a legacy measurement."""
    state, params = busy_world(rr_mix=False)
    with pytest.raises(ValueError, match="packed"):
        window_step(state, params, jax.random.key(0), jnp.int32(0),
                    jnp.int32(MS), rr_enabled=False, packed_sort=False,
                    kernel="pallas")


# -- profiler split + bench sections plumbing -----------------------------

@pytest.mark.parametrize("packed", [True, False])
def test_profiler_routing_split_times_both_paths(packed):
    """routing_rank + routing_place time on both sort modes, and the
    composed routing_scatter section still exists for before/after
    tables."""
    from shadow_tpu.tpu import profiling

    rep = profiling.profile_sections(
        8, reps=1, rr_enabled=False, packed_sort=packed, n_nodes=4,
        egress_cap=8, ingress_cap=8,
        sections=("routing_scatter", "routing_rank", "routing_place"))
    for name in ("routing_scatter", "routing_rank", "routing_place"):
        assert rep["sections"][name]["min_ms"] >= 0


def test_bench_sections_subset_and_compare_runs_bench_mode(tmp_path,
                                                           capsys):
    """BENCH_SECTIONS is a valid section subset, and compare_runs
    --bench prints headline + per-section deltas for two bench JSONs
    (one wrapped the way the PR driver wraps them)."""
    from shadow_tpu.tpu import profiling
    from tools import compare_runs

    assert set(profiling.BENCH_SECTIONS) <= set(profiling.DEFAULT_SECTIONS)

    before = {"value": 1_000_000.0, "hosts": 1024,
              "sections": {"routing_scatter": 20.0, "window_step": 30.0}}
    after = {"value": 2_000_000.0, "hosts": 1024,
             "sections": {"routing_scatter": 8.0, "window_step": 18.0,
                          "routing_rank": 5.0}}
    a = tmp_path / "before.json"
    b = tmp_path / "after.json"
    a.write_text(json.dumps({"parsed": before}))  # driver-wrapped form
    b.write_text(json.dumps(after))
    assert compare_runs.main(["--bench", str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "2.00x" in out and "routing_scatter" in out
    assert "2.50x" in out  # 20.0 -> 8.0 section ratio


def test_bench_backend_fingerprint_refuses_cross_container(
        tmp_path, capsys, monkeypatch):
    """The PR-7 false-regression rule: a prior BENCH_r*.json measured
    on a different backend (or predating the stamp) makes the
    prior_round guard SKIP with `skipped_mismatched_backend`, and
    compare_runs --bench prints a meaningless-comparison banner
    instead of a speedup verdict."""
    import bench
    from tools import compare_runs

    fp = {"platform": "cpu", "device_kind": "cpu"}
    prior = tmp_path / "BENCH_r91.json"

    def write_prior(backend):
        rec = {"value": 5_000_000.0, "hosts": bench.N_HOSTS}
        if backend is not None:
            rec["backend"] = backend
        prior.write_text(json.dumps(rec))

    monkeypatch.setattr("glob.glob", lambda pat: [str(prior)])

    # prior predates the stamp (no backend field): not comparable
    write_prior(None)
    guard = bench._regression_guard(1_000_000.0, fp)
    assert guard["skipped_mismatched_backend"] is True
    assert guard["regressed"] is False
    assert "SKIPPED" in capsys.readouterr().err

    # prior from another container: not comparable either
    write_prior({"platform": "axon", "device_kind": "axon-v5"})
    guard = bench._regression_guard(1_000_000.0, fp)
    assert guard["skipped_mismatched_backend"] is True
    assert guard["prior_backend"]["platform"] == "axon"
    assert "SKIPPED" in capsys.readouterr().err

    # matched fingerprint: the 20% gate applies as before
    write_prior(fp)
    guard = bench._regression_guard(1_000_000.0, fp)
    assert guard == {"vs_round": 91, "ratio": 0.2, "regressed": True}

    # compare_runs --bench: mismatched fingerprints warn loudly and
    # withhold the speedup verdict
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps({"value": 1.0, "hosts": 64, "backend": fp}))
    b.write_text(json.dumps({
        "value": 9.0, "hosts": 64,
        "backend": {"platform": "axon", "device_kind": "axon-v5"}}))
    assert compare_runs.main(["--bench", str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "MISMATCHED BACKENDS" in out and "MEANINGLESS" in out


def test_routing_rank_seq_tiebreak_vs_row_position():
    """The regression the bucketed path must not reintroduce: two
    same-src packets to the same dst with the same (clamped) deliver
    time but qdisc order opposite to seq order must land in seq order —
    the (deliver, src, seq) contract, not (deliver, src, row-position).
    Compared against the variadic reference on a world built to hit it."""
    rng = np.random.default_rng(1)
    lat = np.full((N, N), 2 * MS, np.int32)  # uniform: deliver ties
    params = make_params(lat, np.zeros((N, N), np.float32),
                         np.full((N,), 10_000_000, np.int64))
    state = make_state(N, egress_cap=8, ingress_cap=8, params=params,
                       initial_tokens=np.asarray(params.tb_cap))
    b = 32
    # priorities DESCEND while seqs ascend: the qdisc row order inverts
    # seq order, and the uniform latency + window clamp makes every
    # same-(src,dst) pair tie on deliver time
    state = ingest(
        state,
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.full((b,), 200, jnp.int32),
        jnp.asarray(np.arange(b)[::-1].copy(), jnp.int32),
        jnp.arange(b, dtype=jnp.int32),
        jnp.zeros((b,), bool),
    )
    kw = dict(rr_enabled=False, no_loss=True)
    key = jax.random.key(0)
    step = lambda ps: window_step(state, params, key, jnp.int32(0),
                                  jnp.int32(10 * MS), packed_sort=ps,
                                  **kw)
    got, ref = step(True), step(False)
    for la, lb in zip(jax.tree.leaves(got[0]), jax.tree.leaves(ref[0])):
        assert np.array_equal(np.asarray(la), np.asarray(lb))
    for k in got[1]:
        assert np.array_equal(np.asarray(got[1][k]), np.asarray(ref[1][k]))
