"""Sort-diet + Pallas-kernel parity for the device plane (PR 2).

The packed-key row sorts, the routing sort's recovered `sent` column,
the `ingest_rows` single-key merge + idle gate, and the fused Pallas
egress kernel must all be BITWISE-identical to the pre-change variadic
paths (kept compiled-in as `packed_sort=False` / `kernel="xla"`): same
`NetPlaneState` (every leaf, including compacted-slot contents), same
delivered sets, same next-event scalar — across the RR/FIFO x
router_aqm x no_loss matrix, over multiple chained windows.

Also pins the trace-time bit-budget assertion for packed sort keys.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.tpu import (ingest, ingest_rows, make_params, make_state,
                            plane)
from shadow_tpu.tpu.plane import window_step

MS = 1_000_000
N = 8


def busy_world(rr_mix=True):
    """A small world with starved token buckets (leftover egress every
    window), real loss, mixed qdiscs, duplicate priorities and colliding
    socket slots — every tiebreak path of the sorts gets exercised."""
    rng = np.random.default_rng(7)
    lat = rng.integers(1 * MS, 20 * MS, size=(N, N)).astype(np.int32)
    loss = np.full((N, N), 0.3, np.float32)
    qrr = (np.arange(N) % 2 == 0) if rr_mix else np.zeros(N, bool)
    params = make_params(lat, loss, np.full((N,), 80_000, np.int64),
                         qdisc_rr=qrr, down_bw_bps=np.full((N,), 400_000))
    state = make_state(N, egress_cap=8, ingress_cap=8, params=params,
                       initial_tokens=np.asarray(params.tb_cap))
    b = 48
    state = ingest(
        state,
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(100, 1500, b), jnp.int32),
        # duplicate priorities on purpose: stability must break the ties
        jnp.asarray(rng.integers(0, 6, b), jnp.int32),
        jnp.arange(b, dtype=jnp.int32),
        jnp.asarray(rng.integers(0, 3, b) == 0),
        # socket ids beyond RR_SOCK_SLOTS: slot collisions merge flows
        sock=jnp.asarray(rng.integers(0, 40, b), jnp.int32),
    )
    return state, params


def run_windows(state, params, *, windows=4, **kw):
    key = jax.random.key(3)
    step = jax.jit(lambda s, sh: window_step(
        s, params, key, sh, jnp.int32(10 * MS), **kw))
    shift = jnp.int32(0)
    out = []
    for _ in range(windows):
        state, delivered, nxt = step(state, shift)
        out.append((state, delivered, nxt))
        shift = jnp.int32(10 * MS)
    return out


def assert_runs_equal(a, b, ctx):
    for w, ((sa, da, na), (sb, db, nb)) in enumerate(zip(a, b)):
        for la, lb in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), (ctx, w)
        for k in da:
            assert np.array_equal(np.asarray(da[k]),
                                  np.asarray(db[k])), (ctx, w, k)
        assert int(na) == int(nb), (ctx, w)


@pytest.mark.parametrize("rr_enabled", [False, True])
@pytest.mark.parametrize("router_aqm", [False, True])
@pytest.mark.parametrize("no_loss", [False, True])
def test_packed_sort_matches_variadic(rr_enabled, router_aqm, no_loss):
    state, params = busy_world(rr_mix=rr_enabled)
    kw = dict(rr_enabled=rr_enabled, router_aqm=router_aqm,
              no_loss=no_loss)
    packed = run_windows(state, params, packed_sort=True, **kw)
    ref = run_windows(state, params, packed_sort=False, **kw)
    assert_runs_equal(packed, ref, kw)


@pytest.mark.parametrize("router_aqm", [False, True])
@pytest.mark.parametrize("no_loss", [False, True])
def test_pallas_kernel_matches_xla(router_aqm, no_loss):
    """The fused Pallas egress kernel (interpret mode on CPU) is bitwise
    the XLA path for FIFO worlds."""
    state, params = busy_world(rr_mix=False)
    kw = dict(rr_enabled=False, router_aqm=router_aqm, no_loss=no_loss)
    pal = run_windows(state, params, kernel="pallas", **kw)
    ref = run_windows(state, params, kernel="xla", **kw)
    assert_runs_equal(pal, ref, kw)


def test_pallas_rejects_rr_and_bad_kernel():
    state, params = busy_world()
    key = jax.random.key(0)
    with pytest.raises(ValueError, match="FIFO"):
        window_step(state, params, key, jnp.int32(0), jnp.int32(MS),
                    rr_enabled=True, kernel="pallas")
    with pytest.raises(ValueError, match="unknown plane kernel"):
        window_step(state, params, key, jnp.int32(0), jnp.int32(MS),
                    kernel="mosaic")


@pytest.mark.parametrize("router_aqm,no_loss",
                         [(False, False), (True, True)])
def test_pallas_fused_kernel_matches_xla(router_aqm, no_loss):
    """The single rank→place→egress pipeline (tpu/pallas_pipeline.py,
    interpret mode on CPU) is bitwise the XLA path for FIFO worlds —
    two corners covering both compile switches (the full 2×2 runs on
    the two-dispatch kernel above; the fused pipeline shares every
    stage downstream of the fused span)."""
    state, params = busy_world(rr_mix=False)
    kw = dict(rr_enabled=False, router_aqm=router_aqm, no_loss=no_loss)
    fused = run_windows(state, params, kernel="pallas_fused", **kw)
    ref = run_windows(state, params, kernel="xla", **kw)
    assert_runs_equal(fused, ref, kw)


def test_pallas_fused_overflow_parity():
    """A deliberately tiny ingress ring: the fused placement's
    take/overflow arithmetic (route_place kernel B) must be bitwise the
    XLA counting placement exactly where buckets overflow their free
    slots — merged columns, valid mask, AND the per-host overflow
    counter the capacity policy reads."""
    rng = np.random.default_rng(11)
    lat = rng.integers(1 * MS, 5 * MS, size=(N, N)).astype(np.int32)
    params = make_params(lat, np.zeros((N, N), np.float32),
                         np.full((N,), 10_000_000, np.int64))
    state = make_state(N, egress_cap=8, ingress_cap=4, params=params,
                       initial_tokens=np.asarray(params.tb_cap))
    b = 40
    # a hot destination so at least one bucket overflows its 4 slots
    state = ingest(
        state,
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(0, 3, b), jnp.int32),
        jnp.asarray(rng.integers(100, 1500, b), jnp.int32),
        jnp.asarray(rng.integers(0, 6, b), jnp.int32),
        jnp.arange(b, dtype=jnp.int32),
        jnp.asarray(rng.integers(0, 3, b) == 0),
    )
    kw = dict(rr_enabled=False)
    fused = run_windows(state, params, windows=3, kernel="pallas_fused",
                        **kw)
    ref = run_windows(state, params, windows=3, kernel="xla", **kw)
    assert_runs_equal(fused, ref, kw)
    drops = int(np.asarray(ref[-1][0].n_overflow_dropped).sum())
    assert drops > 0, "ingress never overflowed — dead test"


def test_pallas_fused_rejects_non_power_of_two_ingress():
    rng = np.random.default_rng(0)
    lat = np.full((4, 4), 5 * MS, np.int32)
    params = make_params(lat, np.zeros((4, 4), np.float32),
                         np.full((4,), 1_000_000_000, np.int64))
    state = make_state(4, egress_cap=8, ingress_cap=6, params=params)
    with pytest.raises(ValueError, match="power-of-two"):
        window_step(state, params, jax.random.key(0), jnp.int32(0),
                    jnp.int32(MS), rr_enabled=False,
                    kernel="pallas_fused")


def test_pallas_rejects_non_power_of_two_cap():
    rng = np.random.default_rng(0)
    lat = np.full((4, 4), 5 * MS, np.int32)
    params = make_params(lat, np.zeros((4, 4), np.float32),
                         np.full((4,), 1_000_000_000, np.int64))
    state = make_state(4, egress_cap=6, ingress_cap=8, params=params)
    with pytest.raises(ValueError, match="power-of-two"):
        window_step(state, params, jax.random.key(0), jnp.int32(0),
                    jnp.int32(MS), rr_enabled=False, kernel="pallas")


def test_ingest_rows_packed_and_gate_match_reference():
    """The single-key merge and the idle gate are bitwise the 10-array
    variadic merge — with new entries, and with an all-invalid batch
    (the gate's skip branch must equal the reference's identity merge,
    garbage columns included)."""
    state, params = busy_world()
    rng = np.random.default_rng(5)
    K = 4
    dst = jnp.asarray(rng.integers(0, N, (N, K)), jnp.int32)
    nbytes = jnp.asarray(rng.integers(100, 900, (N, K)), jnp.int32)
    prio = jnp.asarray(rng.integers(0, 30, (N, K)), jnp.int32)
    seq = jnp.asarray(rng.integers(100, 200, (N, K)), jnp.int32)
    ctrl = jnp.zeros((N, K), bool)
    for valid in (jnp.asarray(rng.integers(0, 2, (N, K)) == 0),
                  jnp.ones((N, K), bool),
                  jnp.zeros((N, K), bool)):
        got = ingest_rows(state, dst, nbytes, prio, seq, ctrl, valid)
        ref = ingest_rows(state, dst, nbytes, prio, seq, ctrl, valid,
                          packed_sort=False, gate_idle=False)
        for la, lb in zip(jax.tree.leaves(got), jax.tree.leaves(ref)):
            assert np.array_equal(np.asarray(la), np.asarray(lb))


def test_ingest_rows_overflow_counts_match():
    """Overflow accounting survives the diet: overfill a row past the
    egress capacity through ingest_rows and compare both paths."""
    state, params = busy_world()
    K = 12  # 48 seeded packets over 8 hosts + 12 more can overflow CE=8
    dst = jnp.zeros((N, K), jnp.int32)
    nbytes = jnp.full((N, K), 500, jnp.int32)
    prio = jnp.arange(N * K, dtype=jnp.int32).reshape(N, K)
    valid = jnp.ones((N, K), bool)
    got = ingest_rows(state, dst, nbytes, prio, prio,
                      jnp.zeros((N, K), bool), valid)
    ref = ingest_rows(state, dst, nbytes, prio, prio,
                      jnp.zeros((N, K), bool), valid,
                      packed_sort=False, gate_idle=False)
    assert int(got.n_overflow_dropped.sum()) > 0
    assert np.array_equal(np.asarray(got.n_overflow_dropped),
                          np.asarray(ref.n_overflow_dropped))


def test_pack_key_bit_budget_asserts_at_trace_time():
    """The packed-key helpers refuse budgets past 32 bits while TRACING
    (static capacities), not at runtime."""
    plane._assert_bit_budget((1, "validity"), (31, "key"))  # exactly fits
    with pytest.raises(ValueError, match="bit-budget overflow"):
        plane._assert_bit_budget((1, "validity"), (32, "key"))

    # _pack_rank_key's rank width is derived from the static column
    # count: an impossible capacity must die inside jit TRACING
    def over_budget():
        valid = jnp.ones((4,), bool)
        rank = jnp.zeros((4,), jnp.int32)
        return plane._pack_rank_key(valid, rank, width=2**32)

    with pytest.raises(ValueError, match="bit-budget overflow"):
        jax.jit(over_budget)()


def test_pack_time_key_orders_full_int32_range():
    """_pack_time_key must order legitimately-negative rebased times
    before positive ones and keep invalid slots last."""
    valid = jnp.array([True, True, True, False])
    t = jnp.array([-5, 3, -(2**30), 0], jnp.int32)
    key = plane._pack_time_key(valid, t)
    order = np.argsort(np.asarray(key), kind="stable")
    assert order.tolist() == [2, 0, 1, 3]
