"""Process groups and sessions for managed binaries.

Parity: reference `src/main/host/process.rs` (process groups/sessions)
and `kill(2)` group forms (0, -pgid, -1).
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")


def _run(tmp_path, name, src, stop="30s"):
    c = tmp_path / f"{name}.c"
    c.write_text(src)
    binary = tmp_path / name
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    cfg = load_config_str(f"""
general: {{stop_time: {stop}, seed: 3}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: [], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


SESSIONS_C = r"""
#include <errno.h>
#include <sys/wait.h>
#include <unistd.h>

int main(void) {
    pid_t me = getpid();
    /* top-level processes live in init's group+session (pgid=sid=1) */
    if (getpgrp() != 1) return 180;
    if (getsid(0) != 1) return 181;
    /* a non-leader daemonizes: new session + group, both led by us */
    if (setsid() != me) return 182;
    if (getpgrp() != me || getsid(0) != me) return 183;
    /* now we ARE a session (and group) leader: both ops must fail */
    if (setsid() != -1 || errno != EPERM) return 184;
    if (setpgid(0, 0) != -1 || errno != EPERM) return 185;
    pid_t child = fork();
    if (child < 0) return 186;
    if (child == 0) {
        /* fork inherits the parent's (new) group and session */
        if (getpgrp() != getppid()) _exit(90);
        if (getsid(0) != getppid()) _exit(91);
        /* a non-leader child may itself daemonize */
        if (setsid() != getpid()) _exit(92);
        if (getpgrp() != getpid() || getsid(0) != getpid()) _exit(93);
        /* ...after which it is a group leader: setsid again fails */
        if (setsid() != -1 || errno != EPERM) _exit(94);
        _exit(0);
    }
    int status;
    if (waitpid(child, &status, 0) != child) return 187;
    if (!WIFEXITED(status) || WEXITSTATUS(status) != 0)
        return 100 + WEXITSTATUS(status);
    return 0;
}
"""


GROUP_KILL_C = r"""
#include <errno.h>
#include <signal.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

static volatile sig_atomic_t poked;
static void on_usr1(int sig) { (void)sig; poked = 1; }

int main(void) {
    struct sigaction sa = {0};
    sa.sa_handler = on_usr1;
    if (sigaction(SIGUSR1, &sa, 0)) return 190;
    pid_t child = fork();
    if (child < 0) return 191;
    if (child == 0) {
        /* same group as the parent; wait for the group signal */
        struct timespec ts = {5, 0};
        while (!poked && nanosleep(&ts, &ts) == -1 && errno == EINTR) {}
        _exit(poked ? 0 : 95);
    }
    struct timespec settle = {0, 200000000};
    nanosleep(&settle, 0);
    /* kill(0): every process in the caller's group, both of us */
    if (kill(0, SIGUSR1)) return 192;
    int status;
    if (waitpid(child, &status, 0) != child) return 193;
    if (!WIFEXITED(status) || WEXITSTATUS(status) != 0)
        return 100 + (WIFEXITED(status) ? WEXITSTATUS(status) : 99);
    if (!poked) return 194; /* the caller is part of its own group */
    return 0;
}
"""


def test_sessions_and_group_inheritance(tmp_path):
    _run(tmp_path, "tsess", SESSIONS_C)


def test_kill_zero_signals_whole_group(tmp_path):
    _run(tmp_path, "tgkill0", GROUP_KILL_C)
