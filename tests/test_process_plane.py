"""Process-plane tests: coroutine processes, blocking syscalls via
conditions, config-driven spawning, expected_final_state checking — capped
by the BASELINE rung-1 analogue (3-host basic file transfer:
`examples/docs/basic-file-transfer/shadow.yaml`).
"""

from shadow_tpu.core import simtime
from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager
from shadow_tpu.process.process import ProcessState

MS = simtime.MILLISECOND
S = simtime.SECOND

BASIC_TRANSFER = """
general:
  stop_time: 60s
  seed: 1
network:
  graph:
    type: 1_gbit_switch
hosts:
  server:
    network_node_id: 0
    processes:
    - path: http-server
      args: ["80", "1048576"]
      start_time: 3s
      expected_final_state: running
  client1:
    network_node_id: 0
    processes:
    - path: http-client
      args: ["server", "80"]
      start_time: 5s
  client2:
    network_node_id: 0
    processes:
    - path: http-client
      args: ["server", "80"]
      start_time: 5s
"""


def test_basic_file_transfer():
    """BASELINE rung 1: two clients fetch 1 MiB from an http server."""
    mgr = Manager(load_config_str(BASIC_TRANSFER))
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    # both clients exited 0; server still running
    procs = {p.name: p for h in mgr.hosts for p in h.processes}
    assert procs["client1.http-client.0"].state == ProcessState.EXITED
    assert procs["client1.http-client.0"].exit_status == 0
    assert procs["client2.http-client.0"].exit_status == 0
    # the server was RUNNING at the final-state check (no failure recorded)
    # and was then torn down by shutdown
    assert procs["server.http-server.0"].state == ProcessState.KILLED


def test_basic_file_transfer_deterministic():
    runs = []
    for _ in range(2):
        mgr = Manager(load_config_str(BASIC_TRANSFER))
        stats = mgr.run()
        runs.append((stats.rounds, stats.packets_sent, stats.packets_dropped))
    assert runs[0] == runs[1]


def test_udp_echo_apps():
    cfg = load_config_str(
        """
general: {stop_time: 10s, seed: 3}
network: {graph: {type: 1_gbit_switch}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}
  client:
    network_node_id: 0
    processes:
    - {path: udp-client, args: ["server", "9000", "5", "50"], start_time: 2s}
"""
    )
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


def test_tgen_fixed_size_transfer():
    cfg = load_config_str(
        """
general: {stop_time: 60s, seed: 4}
network: {graph: {type: 1_gbit_switch}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {path: tgen-server, args: ["8888"], start_time: 1s,
       expected_final_state: running}
  client:
    network_node_id: 0
    processes:
    - {path: tgen-client, args: ["server", "8888", "2097152", "2"],
       start_time: 2s}
"""
    )
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


def test_shutdown_signal_and_expected_signaled():
    cfg = load_config_str(
        """
general: {stop_time: 10s, seed: 5}
network: {graph: {type: 1_gbit_switch}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {path: udp-echo-server, args: ["9000"], start_time: 1s,
       shutdown_time: 5s, shutdown_signal: 15,
       expected_final_state: {signaled: 15}}
"""
    )
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


def test_expected_state_mismatch_reported():
    cfg = load_config_str(
        """
general: {stop_time: 10s, seed: 6}
network: {graph: {type: 1_gbit_switch}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: {exited: 0}}
"""
    )
    stats = Manager(cfg).run()
    # echo server never exits on its own -> mismatch must be reported
    assert len(stats.process_failures) == 1


def test_sleep_advances_emulated_time():
    cfg = load_config_str(
        """
general: {stop_time: 5s, seed: 7}
network: {graph: {type: 1_gbit_switch}}
hosts:
  a: {network_node_id: 0}
"""
    )
    mgr = Manager(cfg)
    host = mgr.hosts[0]
    times = []

    def napper(api):
        times.append(api.now())
        yield from api.sleep(500 * MS)
        times.append(api.now())
        yield from api.sleep(1 * S)
        times.append(api.now())

    from shadow_tpu.process.process import SimProcess

    def start(h):
        SimProcess(h, "napper", napper).spawn()

    host.add_application(100 * MS, start)
    mgr.run()
    assert times == [100 * MS, 600 * MS, 1600 * MS]


def test_app_crash_is_contained():
    """An app raising an arbitrary exception is an abnormal exit of that
    process, not a simulator crash."""
    cfg = load_config_str(
        """
general: {stop_time: 5s, seed: 8}
network: {graph: {type: 1_gbit_switch}}
hosts:
  a: {network_node_id: 0}
  b:
    network_node_id: 0
    processes:
    - {path: udp-client, args: ["a", "9", "1", "10"], start_time: 1s,
       expected_final_state: {exited: 0}}
"""
    )
    mgr = Manager(cfg)
    host = mgr.hosts_by_name["a"]

    def crasher(api):
        yield from api.sleep(100 * MS)
        raise ValueError("app bug")

    from shadow_tpu.process.process import SimProcess

    def start(h):
        SimProcess(h, "crasher", crasher).spawn()

    host.add_application(50 * MS, start)
    stats = mgr.run()  # must not raise
    crashed = [p for p in host.processes if p.name == "crasher"][0]
    assert crashed.state == ProcessState.EXITED
    assert crashed.exit_status == 1


def test_shutdown_at_start_time_not_dropped():
    cfg = load_config_str(
        """
general: {stop_time: 10s, seed: 9}
network: {graph: {type: 1_gbit_switch}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {path: udp-echo-server, args: ["9000"], start_time: 2s,
       shutdown_time: 2s, shutdown_signal: 9,
       expected_final_state: {signaled: 9}}
"""
    )
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


def test_digit_leading_hostname_resolves():
    cfg = load_config_str(
        """
general: {stop_time: 10s, seed: 10}
network: {graph: {type: 1_gbit_switch}}
hosts:
  3server:
    network_node_id: 0
    processes:
    - {path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}
  client:
    network_node_id: 0
    processes:
    - {path: udp-client, args: ["3server", "9000", "3", "10"], start_time: 2s}
"""
    )
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures
