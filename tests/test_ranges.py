"""The SL506 integer range analysis (analysis/ranges.py):

- the acceptance gate: the whole registered surface (window_step
  family, ingest_rows, flow_step, chain_windows) is wrap-free under
  the checked-in input domains — zero active findings, every residual
  suppression justified;
- transfer-function semantics: add/sub/mul wrap detection, exact
  trunc-division, cumsum/reduce_sum shape factors, the modular
  exemption, select/clamp joins, the floor_divide/searchsorted
  library-call models;
- the while-loop predicate refinement: the `chain_windows` hand-proof
  (`off + next_ev` stays inside int32 BECAUSE the loop only continues
  while `next_ev < hs - off`) closes mechanically — and stops closing
  when the guard is removed;
- the overflow fixture fails naming the op and its computed interval;
- report shape: per-entry interval tables, seeds, assumptions.
"""

import importlib.util
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.analysis import ranges  # noqa: E402
from shadow_tpu.analysis.ranges import RangeSpec  # noqa: E402

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")

I32 = 2**31 - 1


def _load_fixture(name: str):
    spec = importlib.util.spec_from_file_location(
        name.removesuffix(".py"), os.path.join(FIXTURES, name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _analyze(fn, args, domains=None, modular=None, arg_names=None):
    spec = RangeSpec(
        key="tests:inline",
        arg_names=arg_names or [f"a{i}" for i in range(len(args))],
        domains=domains or {}, modular=modular or {})
    trace, shape = jax.make_jaxpr(fn, return_shape=True)(*args)
    return ranges.analyze_entry(spec, trace=trace, args=args,
                                out_shape=shape)


# -- acceptance: the real tree ---------------------------------------------

@pytest.mark.slow  # traces + analyzes the full registered surface;
# the CI proof gate runs the identical analysis via shadowlint
# --only SL506, and CI's proof-suite step runs this file UNFILTERED
@pytest.mark.parametrize(
    "spec", ranges.range_specs(), ids=lambda s: s.key.split(":")[1])
def test_registered_surface_is_wrap_free(spec):
    findings, report = ranges.analyze_entry(spec)
    active = [f for f in findings if not f.suppressed]
    assert active == [], "\n".join(f.message for f in active)
    for f in findings:
        assert f.justification, f.message  # residuals all justified


@pytest.mark.slow  # a second full-surface sweep; CI proof-suite step
# runs it unfiltered
def test_report_shape_and_summary():
    findings, report = ranges.check_all_ranges()
    assert report["summary"]["active_findings"] == 0
    assert report["summary"]["entries"] == len(ranges.range_specs())
    by_key = {s["entry"]: s for s in report["entries"]}
    lean = by_key["shadow_tpu.tpu.plane:window_step[lean]"]
    # the per-entry interval table names output leaves with intervals
    assert any(v is not None for v in lean["outputs"].values())
    assert lean["seeds"] and lean["assumptions"]
    # the suppression inventory is explicit in the artifact
    aqm = by_key["shadow_tpu.tpu.plane:window_step[rr,aqm,loss]"]
    assert aqm["suppressed"] and not aqm["findings"]


# -- transfer semantics ----------------------------------------------------

def test_add_wrap_detected_and_bounded_add_clean():
    def fn(a, b):
        return a + b

    args = (jnp.int32(0), jnp.int32(0))
    findings, _ = _analyze(fn, args,
                           domains={"a0": (0, I32, "x"),
                                    "a1": (0, 8, "x")})
    assert len(findings) == 1 and "add" in findings[0].message
    assert f"[0, {I32 + 8}]" in findings[0].message
    findings, _ = _analyze(fn, args,
                           domains={"a0": (0, I32 // 2, "x"),
                                    "a1": (0, I32 // 2, "x")})
    assert findings == []


def test_modular_exemption_propagates():
    def fn(counter, k):
        return counter + k, k + 1

    args = (jnp.zeros((4,), jnp.int32), jnp.int32(0))
    findings, _ = _analyze(
        fn, args, domains={"a1": (0, I32, "x")},
        modular={"a0": "declared counter"})
    # counter + k exempt (modular operand); k + 1 still checked
    assert len(findings) == 1 and "add" in findings[0].message


def test_trunc_division_is_exact():
    def fn(a, b):
        return a // jnp.int32(125000), jax.lax.div(a, b)

    args = (jnp.int32(0), jnp.int32(1))
    findings, report = _analyze(
        fn, args, domains={"a0": (0, 251_499, "x"),
                           "a1": (1, 1, "y")})
    assert findings == []
    # jnp floor-divide is modeled (the q-1 correction arm must not
    # join): [0, 251499] // 125000 == [0, 2]
    assert report["outputs"]["[0]"] == [0, 2]
    assert report["outputs"]["[1]"] == [0, 251_499]


def test_cumsum_and_reduce_sum_scale_by_shape():
    def fn(x):
        return jnp.cumsum(x, axis=1), x.sum(axis=1, dtype=jnp.int32)

    args = (jnp.zeros((4, 8), jnp.int32),)
    findings, report = _analyze(fn, args,
                                domains={"a0": (0, 100, "x")})
    assert findings == []
    assert report["outputs"]["[0]"] == [0, 800]
    assert report["outputs"]["[1]"] == [0, 800]
    findings, _ = _analyze(fn, args,
                           domains={"a0": (0, I32 // 4, "x")})
    assert any("cumsum" in f.message for f in findings)


def test_clamp_is_monotone_per_argument():
    """Review-found soundness bug: clamp bounds must use each
    operand's MATCHING bound (a computed upper bound below x must not
    produce an interval excluding reachable values)."""
    def fn(x, hi):
        return jnp.clip(x, 0, hi), jnp.clip(jnp.int32(0), x, 1000)

    args = (jnp.int32(0), jnp.int32(0))
    _, report = _analyze(fn, args,
                         domains={"a0": (100, 200, "x"),
                                  "a1": (0, 50, "computed hi")})
    # clamp(x in [100,200], 0, hi in [0,50]) reaches every value in
    # [0, 50] (hi=0 -> 0), not just 50
    assert report["outputs"]["[0]"] == [0, 50]
    # clamp(0, lo in [100,200], 1000) = lo itself: [100, 200]
    assert report["outputs"]["[1]"] == [100, 200]


def test_clip_launders_the_modular_exemption():
    """A clip/clamp pins its output into the bound operands' range for
    ANY input — including a wrapped modular counter — so arithmetic on
    the clipped value is ordinary checked arithmetic (the flow plane's
    `clip(deadline - clock, 0, budget)` wake path must be genuinely
    proven, not modular-exempt). Covers BOTH spellings: jnp.clip (a
    pjit of max-then-min) and the raw lax.clamp primitive."""
    def fn(counter):
        clipped = jnp.clip(counter, 0, jnp.int32(1073))
        clamped = jax.lax.clamp(jnp.int32(0), counter,
                                jnp.int32(1073))
        return clipped * 1_000_000, clipped + jnp.int32(I32), clamped

    args = (jnp.int32(0),)
    findings, report = _analyze(fn, args,
                                modular={"a0": "wrapped counter"})
    # the in-budget product is proven, NOT exempted...
    assert report["outputs"]["[0]"] == [0, 1_073_000_000]
    assert report["outputs"]["[2]"] == [0, 1073]
    # ...and an over-budget add on the clipped value still FAILS
    assert any("add" in f.message for f in findings)


def test_where_select_and_sentinel_join():
    def fn(valid, x):
        return jnp.where(valid, x, jnp.int32(I32))

    args = (jnp.zeros((4,), bool), jnp.zeros((4,), jnp.int32))
    findings, report = _analyze(fn, args,
                                domains={"a1": (-5, 100, "x")})
    assert findings == []
    assert report["outputs"][""] == [-5, I32]


def test_searchsorted_modeled_as_insertion_range():
    def fn(sorted_arr, q):
        return jnp.searchsorted(sorted_arr, q)

    args = (jnp.zeros((32,), jnp.int32), jnp.zeros((5,), jnp.int32))
    findings, report = _analyze(
        fn, args, domains={"a0": (-I32, I32, "x"),
                           "a1": (-I32, I32, "x")})
    assert findings == []
    assert report["outputs"][""] == [0, 32]


def test_scan_exact_unroll_bounds_loop_counters():
    """A bounded scan's carry counter stays exact (no widening): the
    codel micro-step / searchsorted shape."""
    def fn(x):
        def body(c, xi):
            return c + 1, c

        return jax.lax.scan(body, jnp.int32(0), x)

    args = (jnp.zeros((16,), jnp.int32),)
    findings, report = _analyze(fn, args)
    assert findings == []
    assert report["outputs"]["[0]"] == [16, 16]
    assert report["outputs"]["[1]"] == [0, 15]


def test_allow_suppresses_with_justification():
    def fn(a):
        return a + a

    args = (jnp.int32(0),)
    spec = RangeSpec(
        key="tests:allowed", arg_names=["a"],
        domains={"a": (0, I32, "x")},
        allow={"`add` admits wraparound": "known-masked lanes"})
    trace, _ = jax.make_jaxpr(fn, return_shape=True)(*args)
    findings, report = ranges.analyze_entry(spec, trace=trace,
                                            args=args)
    assert len(findings) == 1 and findings[0].suppressed
    assert findings[0].justification == "known-masked lanes"
    assert report["findings"] == [] and report["suppressed"]


# -- the while-loop predicate refinement -----------------------------------

def _chain_shaped(guarded: bool):
    """The chain_windows arithmetic shape: off += next_ev while
    next_ev < hs - off (guarded) or unconditionally (broken)."""
    def fn(hs, step):
        def cond(c):
            off, n = c
            pred = n < 64
            if guarded:
                pred = pred & (step < hs - off)
            return pred

        def body(c):
            off, n = c
            return off + step, n + 1

        return jax.lax.while_loop(cond, body,
                                  (jnp.int32(0), jnp.int32(0)))

    return fn, (jnp.int32(0), jnp.int32(0))


def test_while_refinement_proves_the_chain_theorem():
    """`off + next_ev` fits int32 BECAUSE the predicate keeps both
    below I32_MAX//2 — the plane.py:650 hand-proof, mechanized."""
    fn, args = _chain_shaped(guarded=True)
    findings, _ = _analyze(
        fn, args, domains={"a0": (0, I32 // 2, "horizon clamp"),
                           "a1": (0, I32, "unclamped step")})
    assert findings == []


def test_while_without_the_guard_admits_the_wrap():
    """Drop the predicate and the same arithmetic must FAIL — the
    refinement is load-bearing, not decorative."""
    fn, args = _chain_shaped(guarded=False)
    findings, _ = _analyze(
        fn, args, domains={"a0": (0, I32 // 2, "horizon clamp"),
                           "a1": (0, I32, "unclamped step")})
    assert any("add" in f.message for f in findings)


# -- the fixture -----------------------------------------------------------

def test_overflow_fixture_fails_naming_op_and_interval():
    fixture = _load_fixture("fixture_int_overflow.py")
    fn, args = fixture.build()
    trace, _ = jax.make_jaxpr(fn, return_shape=True)(*args)
    findings, _ = ranges.analyze_entry(fixture.spec(), trace=trace,
                                       args=args)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "SL506" and not f.suppressed
    assert "`add`" in f.message
    assert f"[0, {I32 // 4 + I32}]" in f.message  # computed interval


# -- registry hygiene ------------------------------------------------------

def test_every_domain_and_modular_entry_carries_a_why():
    for spec in ranges.range_specs():
        for pat, (lo, hi, why) in spec.domains.items():
            assert lo <= hi and why, (spec.key, pat)
        for pat, why in spec.modular.items():
            assert why, (spec.key, pat)
        for pat, why in spec.allow.items():
            assert why, (spec.key, pat)


# -- the registry's domains are ENFORCED, not just assumed -----------------

def test_window_budget_enforced_at_scenario_parse():
    """window_ns <= I32_MAX//4 (the registry's _WHY_WINDOW) is a
    ScenarioError at parse, not a comment."""
    from shadow_tpu.workloads.spec import ScenarioError, parse_scenario

    base = {"name": "t", "hosts": 4, "windows": 2,
            "patterns": [{"kind": "onoff", "burst": 1, "rounds": 1}]}
    with pytest.raises(ScenarioError, match="window_ns"):
        parse_scenario({**base, "window_ns": I32 // 4 + 1})
    parse_scenario({**base, "window_ns": I32 // 4})  # the boundary


def test_window_budget_enforced_on_config_runahead():
    """The Manager path's window floor obeys the same budget: a
    runahead beyond I32_MAX//4 ns is a ConfigError."""
    from shadow_tpu.core.config import ConfigError, parse_config_dict

    def cfg(runahead):
        return {
            "general": {"stop_time": "1s"},
            "experimental": {"runahead": runahead},
            "hosts": {"h1": {"network_node_id": 0}},
        }

    parse_config_dict(cfg("100ms"))
    with pytest.raises(ConfigError, match="runahead.*budget"):
        parse_config_dict(cfg("3s"))
    with pytest.raises(ConfigError, match="runahead"):
        parse_config_dict(cfg(0))


def test_latency_budget_enforced_in_make_params():
    """Path latencies beyond I32_MAX//2 ns (or negative) are refused
    at params construction — the deliver-arithmetic budget the
    `state.in_deliver_rel` domain cites."""
    import numpy as np

    from shadow_tpu.tpu import plane

    good = dict(loss=np.zeros((2, 2)),
                up_bw_bps=np.full(2, 1_000_000_000))
    plane.make_params(
        latency_ns=np.full((2, 2), I32 // 2), **good)  # boundary
    with pytest.raises(ValueError, match="latency_ns.*budget"):
        plane.make_params(
            latency_ns=np.full((2, 2), I32 // 2 + 1), **good)
    with pytest.raises(ValueError, match="latency_ns"):
        plane.make_params(latency_ns=np.full((2, 2), -1), **good)


def test_byte_budget_matches_the_registry():
    """The spec's per-message byte cap IS the registry's BYTES_BUDGET
    — the two constants must never drift apart."""
    from shadow_tpu.workloads import spec as wspec

    assert wspec._MAX_BYTES == ranges.BYTES_BUDGET
    base = {"name": "t", "hosts": 4, "windows": 2,
            "patterns": [{"kind": "onoff", "burst": 1, "rounds": 1,
                          "bytes": ranges.BYTES_BUDGET + 1}]}
    with pytest.raises(wspec.ScenarioError, match="bytes"):
        wspec.parse_scenario(base)


def test_flows_window_floor_still_enforced():
    """The flow plane's ms-clock floor (window_ns >= 1ms) — part of
    the same enforced-domain inventory."""
    from shadow_tpu.workloads.spec import ScenarioError, parse_scenario

    with pytest.raises(ScenarioError, match="1ms"):
        parse_scenario({
            "name": "t", "hosts": 4, "windows": 2,
            "window_ns": 500_000, "transport": "flows",
            "patterns": [{"kind": "onoff", "burst": 1, "rounds": 1}]})


def test_unseeded_leaves_default_to_full_range():
    """Conservatism check: a leaf the registry forgot defaults to the
    full dtype range and forces the assumption to be written down."""
    def fn(a):
        return a + 1

    findings, report = _analyze(fn, (jnp.int32(0),))
    assert any("unseeded" in n for n in report["seeds"])
    assert findings and "add" in findings[0].message
