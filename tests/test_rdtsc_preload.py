"""rdtsc trap-and-emulate + preload-libc direct-call wrappers.

Parity: reference `src/lib/shim/shim_rdtsc.c` + `src/lib/tsc` (cycle
counters observe simulated time at a nominal rate) and
`src/lib/preload-libc` (libc overrides that skip the seccomp trap).
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")

RDTSC_C = r"""
#include <stdio.h>
#include <time.h>
#include <x86intrin.h>

int main(void) {
    unsigned long long t0 = __rdtsc();
    struct timespec req = {2, 0};  /* 2 simulated seconds */
    nanosleep(&req, 0);
    unsigned int aux;
    unsigned long long t1 = __rdtscp(&aux);
    long long delta = (long long)(t1 - t0);
    /* nominal 1 GHz emulated TSC: the sleep must read as ~2e9 cycles.
     * A leaked REAL tsc would differ wildly (GHz-scale counter with
     * nanosecond-scale wall sleep => ~1e6, or absolute values ~1e14). */
    if (delta < 1900000000LL || delta > 2200000000LL) {
        printf("delta %lld t0 %llu\n", delta, t0);
        return 1;
    }
    /* absolute value is simulated ns: process starts ~1s in, so t0 must
     * be small (minutes of virtual time), never a real TSC reading */
    if (t0 > 600000000000ULL) { printf("t0 %llu\n", t0); return 2; }
    if (aux != 0) return 3;
    return 0;
}
"""

# exercises the preload wrappers end-to-end: if the direct-call path broke
# (bad symbol, wrong arg marshalling), this socket pair fails
PRELOAD_PAIR_C = r"""
#include <arpa/inet.h>
#include <netinet/in.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

int main(void) {
    int s = socket(AF_INET, SOCK_DGRAM, 0);
    if (s < 0) return 1;
    struct sockaddr_in me;
    memset(&me, 0, sizeof me);
    me.sin_family = AF_INET;
    me.sin_port = htons(5500);
    me.sin_addr.s_addr = INADDR_ANY;
    if (bind(s, (struct sockaddr *)&me, sizeof me)) return 2;
    /* send to ourselves through the simulated loopback */
    struct sockaddr_in dst = me;
    dst.sin_addr.s_addr = inet_addr("127.0.0.1");
    const char msg[] = "preload";
    if (sendto(s, msg, sizeof msg, 0, (struct sockaddr *)&dst, sizeof dst)
            != (long)sizeof msg)
        return 3;
    char back[32];
    struct sockaddr_in from;
    socklen_t flen = sizeof from;
    long n = recvfrom(s, back, sizeof back, 0, (struct sockaddr *)&from,
                      &flen);
    if (n != (long)sizeof msg || memcmp(back, msg, sizeof msg)) return 4;
    if (ntohs(from.sin_port) != 5500) return 5;
    close(s);
    return 0;
}
"""


def _run_one(tmp_path, name, src, expect="{exited: 0}"):
    c = tmp_path / f"{name}.c"
    c.write_text(src)
    binary = tmp_path / name
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    cfg = load_config_str(f"""
general: {{stop_time: 20s, seed: 31}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s, expected_final_state: {expect}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


def test_rdtsc_observes_simulated_time(tmp_path):
    _run_one(tmp_path, "rdtscer", RDTSC_C)


def test_preload_wrappers_drive_simulated_udp(tmp_path):
    from shadow_tpu.process.managed import PRELOAD_LIBC_PATH
    import os

    assert os.path.exists(PRELOAD_LIBC_PATH), "preload-libc lib not built"
    _run_one(tmp_path, "ppair", PRELOAD_PAIR_C)
