from shadow_tpu.core import simtime
from shadow_tpu.net.packet import CONFIG_MTU, Packet, PacketStatus, Protocol
from shadow_tpu.net.relay import Relay, TokenBucket, create_token_bucket

MS = simtime.MILLISECOND


class FakeDevice:
    def __init__(self, address):
        self.address = address
        self.outq = []
        self.received = []

    def get_address(self):
        return self.address

    def pop(self):
        return self.outq.pop(0) if self.outq else None

    def push(self, packet):
        self.received.append(packet)


class FakeHost:
    def __init__(self):
        self.devices = {}
        self.tasks = []  # (fire_time, callback)
        self.time = 0
        self.bootstrapping = False

    def get_packet_device(self, ip):
        return self.devices[ip]

    def schedule_relay_task(self, cb, delay_ns):
        self.tasks.append((self.time + delay_ns, cb))

    def now(self):
        return self.time

    def is_bootstrapping(self):
        return self.bootstrapping

    def run_due(self):
        due = [t for t in self.tasks if t[0] <= self.time]
        self.tasks = [t for t in self.tasks if t[0] > self.time]
        for _, cb in sorted(due, key=lambda x: x[0]):
            cb()


def _pkt(dst, n=1000):
    return Packet(Protocol.UDP, ("10.0.0.1", 1), (dst, 2), b"x" * n)


def test_token_bucket_refill_and_wait():
    tb = TokenBucket(capacity=100, refill_increment=10, refill_interval=MS)
    ok, bal = tb.conforming_remove(100, now=0)
    assert ok and bal == 0
    ok, wait = tb.conforming_remove(25, now=0)
    assert not ok and wait == 3 * MS  # 3 refills of 10 needed for 25
    tb2 = TokenBucket(100, 10, MS)
    tb2.conforming_remove(100, 0)
    ok, bal = tb2.conforming_remove(30, now=5 * MS)  # 5 refills passed
    assert ok and bal == 20


def test_token_bucket_capacity_clamp():
    tb = TokenBucket(100, 10, MS)
    ok, bal = tb.conforming_remove(0, now=1000 * MS)
    assert ok and bal == 100  # refills never exceed capacity


def test_create_token_bucket_burst_allowance():
    tb = create_token_bucket(1_000_000)  # 1 MB/s
    assert tb.refill_increment == 1000
    assert tb.capacity == 1000 + CONFIG_MTU


def test_relay_unlimited_forwards_all():
    host = FakeHost()
    src = FakeDevice("10.0.0.1")
    dst = FakeDevice("10.0.0.9")
    host.devices = {"10.0.0.1": src, "10.0.0.9": dst}
    relay = Relay(host, "10.0.0.1", bytes_per_second=None)
    src.outq = [_pkt("10.0.0.9") for _ in range(5)]
    relay.notify()
    host.run_due()
    assert len(dst.received) == 5
    assert all(PacketStatus.RELAY_FORWARDED in p.statuses for p in dst.received)


def test_relay_rate_limit_blocks_and_resumes():
    host = FakeHost()
    src = FakeDevice("10.0.0.1")
    dst = FakeDevice("10.0.0.9")
    host.devices = {"10.0.0.1": src, "10.0.0.9": dst}
    # 1 MB/s -> 1000 bytes/ms refill, capacity 1000+1500=2500.
    relay = Relay(host, "10.0.0.1", bytes_per_second=1_000_000)
    pkts = [_pkt("10.0.0.9") for _ in range(5)]  # 1042 total bytes each
    src.outq = list(pkts)
    relay.notify()
    host.run_due()
    # capacity 2500 admits two packets (2084), third blocks
    assert len(dst.received) == 2
    assert host.tasks, "relay must have rescheduled itself"
    assert PacketStatus.RELAY_CACHED in pkts[2].statuses
    # advance until all delivered
    for _ in range(20):
        if not host.tasks:
            break
        host.time = max(t for t, _ in host.tasks)
        host.run_due()
    assert len(dst.received) == 5
    assert [p for p in dst.received] == pkts


def test_relay_local_delivery_exempt_from_rate_limit():
    host = FakeHost()
    lo = FakeDevice("127.0.0.1")
    host.devices = {"127.0.0.1": lo}
    relay = Relay(host, "127.0.0.1", bytes_per_second=1)  # absurdly low limit
    lo.outq = [_pkt("127.0.0.1") for _ in range(10)]
    relay.notify()
    host.run_due()
    assert len(lo.received) == 10  # local: no limit applies


def test_relay_bootstrap_bypasses_rate_limit():
    host = FakeHost()
    host.bootstrapping = True
    src = FakeDevice("10.0.0.1")
    dst = FakeDevice("10.0.0.9")
    host.devices = {"10.0.0.1": src, "10.0.0.9": dst}
    relay = Relay(host, "10.0.0.1", bytes_per_second=1)
    src.outq = [_pkt("10.0.0.9") for _ in range(10)]
    relay.notify()
    host.run_due()
    assert len(dst.received) == 10


def test_relay_notify_while_pending_is_noop():
    host = FakeHost()
    src = FakeDevice("10.0.0.1")
    host.devices = {"10.0.0.1": src}
    relay = Relay(host, "10.0.0.1", None)
    relay.notify()
    relay.notify()
    relay.notify()
    assert len(host.tasks) == 1  # only one forward task scheduled
