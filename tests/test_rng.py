from shadow_tpu.core import rng


def test_xoshiro_deterministic():
    a = rng.Xoshiro256pp(42)
    b = rng.Xoshiro256pp(42)
    seq_a = [a.next_u64() for _ in range(100)]
    seq_b = [b.next_u64() for _ in range(100)]
    assert seq_a == seq_b
    c = rng.Xoshiro256pp(43)
    assert [c.next_u64() for _ in range(100)] != seq_a


def test_xoshiro_known_vector():
    # Ground-truth vectors generated from an independent C implementation of
    # the canonical (Vigna) xoshiro256++ seeded via splitmix64.
    expected = {
        0: [
            5987356902031041503,
            7051070477665621255,
            6633766593972829180,
            211316841551650330,
            9136120204379184874,
        ],
        42: [
            15021278609987233951,
            5881210131331364753,
            18149643915985481100,
            12933668939759105464,
            14637574242682825331,
        ],
        0xDEADBEEF: [
            887788264254705374,
            3131310381243359458,
            13700943409776775970,
            6855428166950120087,
            16142291723720382552,
        ],
    }
    for seed, vals in expected.items():
        r = rng.Xoshiro256pp(seed)
        assert [r.next_u64() for _ in range(5)] == vals


def test_draw_helpers():
    r = rng.Xoshiro256pp(7)
    for _ in range(1000):
        x = r.random()
        assert 0.0 <= x < 1.0
    for _ in range(1000):
        v = r.randrange(10, 20)
        assert 10 <= v < 20
    # bernoulli extremes
    assert not any(r.bernoulli(0.0) for _ in range(100))
    assert all(r.bernoulli(1.0) for _ in range(100))


def test_shuffle_deterministic():
    r1, r2 = rng.Xoshiro256pp(5), rng.Xoshiro256pp(5)
    xs, ys = list(range(50)), list(range(50))
    r1.shuffle(xs)
    r2.shuffle(ys)
    assert xs == ys
    assert sorted(xs) == list(range(50))


def test_host_seed_independent_of_order():
    # Host seeds depend on the draw position (config order) and name only.
    g1 = rng.Xoshiro256pp(1)
    s_a = rng.host_seed_for(g1, "alice")
    s_b = rng.host_seed_for(g1, "bob")
    g2 = rng.Xoshiro256pp(1)
    assert rng.host_seed_for(g2, "alice") == s_a
    assert rng.host_seed_for(g2, "bob") == s_b
    assert s_a != s_b


def test_hostname_hash_stable():
    assert rng.hostname_hash("server0") == rng.hostname_hash("server0")
    assert rng.hostname_hash("server0") != rng.hostname_hash("server1")
