"""Full-run checkpoint/resume (`faults/runstate.py`) and the shared
atomic npz format (`faults/checkpoint.write_npz_checkpoint`).

Pins the PR-19 crash-survivability contract (docs/robustness.md
"Resumable runs"):

- the single-file format round-trips and REFUSES truncation, array
  bit flips, schema drift, and missing/extra arrays — each with a
  `CheckpointError` naming the offending field;
- `flatten_carry`/`restore_carry` round-trip a full driver carry with
  disabled presence planes recorded as explicit ``none_paths`` and
  presence drift refused by path;
- a `drive_chained_windows` / `drive_ensemble` run resumed from a
  mid-run checkpoint ends bitwise-identical to the uninterrupted
  twin (the chain-length-invisibility theorem at work);
- `ChainMemo.save/load` persists the cache across driver invocations
  with hits > 0 on the second (ROADMAP-3 "cross-run cache
  persistence"), and `absorb(restore=True)` reproduces the spilled
  instance exactly (the memoized kill/resume parity surface).

The heavy end-to-end cases are @slow; CI's kill/resume gate runs this
file unfiltered alongside the `tools/run_scenarios.py --kill-at /
--resume` corpus proof (the shared-driver-gate pattern).
"""

from __future__ import annotations

import json
import os
import zipfile

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.faults.checkpoint import (CheckpointError,  # noqa: E402
                                          NPZ_META_KEY,
                                          load_npz_checkpoint,
                                          write_npz_checkpoint)
from shadow_tpu.faults.runstate import (RUNSTATE_SCHEMA,  # noqa: E402
                                        RunCheckpointer, flatten_carry,
                                        latest_checkpoint, load_runstate,
                                        restore_carry, resume_carry)
from shadow_tpu.tpu import elastic, ingest_rows, profiling  # noqa: E402
from shadow_tpu.tpu import memo as memomod  # noqa: E402
from shadow_tpu.tpu.plane import unpack_planes, window_step  # noqa: E402
from shadow_tpu.workloads.phold import respawn_batch  # noqa: E402
from shadow_tpu.workloads.runner import digest_pytrees  # noqa: E402

N = 16
SPAWN_BASE = 10_000


# ---------------------------------------------------------------------------
# the shared single-file npz format


def _write_sample(path):
    arrays = {"x": np.arange(6, dtype=np.int32).reshape(2, 3),
              "y": np.linspace(0.0, 1.0, 4)}
    write_npz_checkpoint(path, schema="fmt-test-v1",
                         meta={"knob": 7}, arrays=arrays)
    return arrays


def test_npz_roundtrip(tmp_path):
    path = str(tmp_path / "a.npz")
    arrays = _write_sample(path)
    meta, got = load_npz_checkpoint(path, schema="fmt-test-v1")
    assert meta["knob"] == 7
    assert set(got) == set(arrays)
    for k in arrays:
        np.testing.assert_array_equal(got[k], arrays[k])
        assert got[k].dtype == arrays[k].dtype


def test_npz_no_tmp_left_behind(tmp_path):
    path = str(tmp_path / "a.npz")
    _write_sample(path)
    assert os.listdir(tmp_path) == ["a.npz"]


def test_npz_meta_key_collision_refused(tmp_path):
    with pytest.raises(CheckpointError, match="collides"):
        write_npz_checkpoint(str(tmp_path / "a.npz"), schema="s",
                             meta={}, arrays={NPZ_META_KEY: np.zeros(1)})


def test_npz_schema_drift_refused(tmp_path):
    path = str(tmp_path / "a.npz")
    _write_sample(path)
    with pytest.raises(CheckpointError, match="schema 'fmt-test-v1'"):
        load_npz_checkpoint(path, schema="fmt-test-v2")


def test_npz_truncation_refused(tmp_path):
    path = str(tmp_path / "a.npz")
    _write_sample(path)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 2])
    with pytest.raises(CheckpointError, match="truncated or corrupt"):
        load_npz_checkpoint(path, schema="fmt-test-v1")


def _rewrite(src, dst, mutate):
    """Re-pack an npz with ``mutate(arrays)`` applied — the zip stays
    well-formed, so only the per-array checksums can catch it."""
    with np.load(src) as z:
        arrays = {k: z[k] for k in z.files}
    mutate(arrays)
    np.savez(dst, **arrays)


def test_npz_array_bitflip_refused(tmp_path):
    path = str(tmp_path / "a.npz")
    _write_sample(path)

    def flip(arrays):
        x = arrays["x"].copy()
        x.flat[0] ^= 1
        arrays["x"] = x

    _rewrite(path, path, flip)
    with pytest.raises(CheckpointError,
                       match="checksum mismatch on array 'x'"):
        load_npz_checkpoint(path, schema="fmt-test-v1")


def test_npz_missing_array_refused(tmp_path):
    path = str(tmp_path / "a.npz")
    _write_sample(path)
    _rewrite(path, path, lambda arrays: arrays.pop("y"))
    with pytest.raises(CheckpointError, match="'y'"):
        load_npz_checkpoint(path, schema="fmt-test-v1")


def test_npz_extra_uncovered_array_refused(tmp_path):
    path = str(tmp_path / "a.npz")
    _write_sample(path)
    _rewrite(path, path,
             lambda arrays: arrays.__setitem__("smuggled", np.zeros(2)))
    with pytest.raises(CheckpointError, match="'smuggled'"):
        load_npz_checkpoint(path, schema="fmt-test-v1")


def test_npz_meta_corruption_refused(tmp_path):
    # a damaged meta blob (valid zip, broken JSON) is refused by name
    # — the corruption-detection contract; checksums are not a
    # cryptographic tamper seal (module docstring)
    path = str(tmp_path / "a.npz")
    _write_sample(path)

    def smash(arrays):
        blob = bytearray(bytes(arrays[NPZ_META_KEY]))
        blob[0] = ord("X")  # no longer parses as JSON
        arrays[NPZ_META_KEY] = np.frombuffer(bytes(blob), np.uint8)

    _rewrite(path, path, smash)
    with pytest.raises(CheckpointError, match="meta"):
        load_npz_checkpoint(path, schema="fmt-test-v1")


def test_npz_is_a_plain_zip(tmp_path):
    # operators can inspect checkpoints with stock tooling
    path = str(tmp_path / "a.npz")
    _write_sample(path)
    with zipfile.ZipFile(path) as z:
        names = {n.removesuffix(".npy") for n in z.namelist()}
    assert {"x", "y", NPZ_META_KEY} <= names


# ---------------------------------------------------------------------------
# carry flatten/restore


def _toy_carry(hist=True):
    from shadow_tpu.telemetry import make_metrics

    # device-realistic dtypes (int32/float32): restore_carry
    # re-uploads with jnp.asarray, which honors the session's default
    # 32-bit precision — exactly what real driver carries hold
    metrics = jax.device_get(make_metrics(4))
    h = np.arange(8, dtype=np.int32) if hist else None
    return (np.arange(12, dtype=np.int32).reshape(3, 4),
            (metrics, h, {"b": np.float32(2.5), "a": np.int32(3)}))


def test_flatten_restore_roundtrip():
    carry = _toy_carry()
    arrays, none_paths = flatten_carry(carry)
    assert none_paths == []
    # structural paths: namedtuple fields + tuple indices + dict keys
    assert "carry.0" in arrays
    assert "carry.1.1" in arrays and "carry.1.2.a" in arrays
    assert any(p.startswith("carry.1.0.") for p in arrays)
    back = jax.device_get(
        restore_carry(carry, arrays, none_paths=none_paths))
    la, lb = jax.tree.leaves(carry), jax.tree.leaves(back)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        assert np.asarray(x).dtype == np.asarray(y).dtype


def test_restore_takes_shapes_from_file():
    # elastic growth: the checkpoint's GROWN shapes win over the
    # cold-template shapes
    grown = (np.zeros((4, 8), np.int32), (np.ones((5,), np.int32),))
    arrays, nones = flatten_carry(grown)
    template = (np.zeros((4, 2), np.int32), (np.ones((5,), np.int32),))
    back = restore_carry(template, arrays, none_paths=nones)
    assert back[0].shape == (4, 8)


def test_restore_none_roundtrip_and_presence_refusals():
    carry_off = _toy_carry(hist=False)
    arrays_off, nones_off = flatten_carry(carry_off)
    assert nones_off == ["carry.1.1"]
    back = restore_carry(carry_off, arrays_off, none_paths=nones_off)
    assert back[1][1] is None

    carry_on = _toy_carry(hist=True)
    arrays_on, nones_on = flatten_carry(carry_on)
    # checkpoint recorded the plane LIVE, this run disabled it
    with pytest.raises(CheckpointError,
                       match=r"presence mismatch at 'carry\.1\.1'"):
        restore_carry(carry_off, arrays_on, none_paths=nones_on)
    # checkpoint recorded the plane DISABLED, this run enabled it
    with pytest.raises(CheckpointError,
                       match=r"presence mismatch at 'carry\.1\.1'"):
        restore_carry(carry_on, arrays_off, none_paths=nones_off)


def test_restore_missing_leaf_refused():
    carry = _toy_carry()
    arrays, nones = flatten_carry(carry)
    del arrays["carry.1.1"]
    with pytest.raises(CheckpointError,
                       match=r"missing carry leaf 'carry\.1\.1'"):
        restore_carry(carry, arrays, none_paths=nones)


# ---------------------------------------------------------------------------
# RunCheckpointer mechanics


def test_checkpointer_validation(tmp_path):
    with pytest.raises(ValueError, match="every"):
        RunCheckpointer(str(tmp_path), every=0)
    with pytest.raises(ValueError, match="keep"):
        RunCheckpointer(str(tmp_path), every=4, keep=0)


def test_checkpointer_cadence(tmp_path):
    ck = RunCheckpointer(str(tmp_path), every=4)
    assert ck.cut_rounds(12) == (4, 8)
    assert ck.due(4, 12) and ck.due(8, 12)
    assert not ck.due(3, 12)
    assert not ck.due(12, 12)  # final boundary: run already finishing


def test_checkpointer_save_prune_latest(tmp_path):
    ck = RunCheckpointer(str(tmp_path), every=2, label="toy", keep=2)
    carry = _toy_carry()
    for r1 in (2, 4, 6):
        info = ck.save(r1, carry, host=True)
        assert os.path.isfile(info["path"])
    names = sorted(os.listdir(tmp_path))
    assert names == ["toy-r00000004.runstate.npz",
                     "toy-r00000006.runstate.npz"]  # keep=2 pruned r2
    assert ck.saved == 3
    latest = latest_checkpoint(str(tmp_path), label="toy")
    assert latest.endswith("toy-r00000006.runstate.npz")
    meta, arrays = load_runstate(latest)
    assert meta["round"] == 6
    res = resume_carry(latest, carry)
    assert res["round"] == 6
    got = jax.device_get(res["carry"])
    for x, y in zip(jax.tree.leaves(carry), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_runstate_refuses_other_kind(tmp_path):
    path = str(tmp_path / "x.runstate.npz")
    write_npz_checkpoint(path, schema=RUNSTATE_SCHEMA,
                         meta={"kind": "other"}, arrays={})
    with pytest.raises(CheckpointError, match="kind"):
        load_runstate(path)


def test_resume_refuses_schedule_fingerprint_mismatch(tmp_path):
    class FakeSched:
        def __init__(self, fp):
            self._fp = fp
            self.advanced = []

        def fingerprint(self):
            return self._fp

        def advance(self, now_ns):
            self.advanced.append(now_ns)

    carry = _toy_carry()
    ck = RunCheckpointer(str(tmp_path), every=2, window_ns=100,
                         schedule=FakeSched("aaaa"))
    info = ck.save(2, carry, host=True)
    with pytest.raises(CheckpointError, match="fingerprint mismatch"):
        resume_carry(info["path"], carry, schedule=FakeSched("bbbb"))
    sched = FakeSched("aaaa")
    resume_carry(info["path"], carry, schedule=sched)
    assert sched.advanced == [200]  # one advance to round * window_ns


# ---------------------------------------------------------------------------
# ChainMemo persistence (ROADMAP-3 "cross-run cache persistence")


def _mk_carry(x=0, events=0):
    from shadow_tpu.telemetry import make_metrics

    m = jax.device_get(make_metrics(2))
    m = m._replace(events=np.int32(events))
    return (np.full((4,), x, np.int32), (m,))


def _record_one(memo, x=1, r0=8):
    pre, post = _mk_carry(x), _mk_carry(x + 1, events=5)
    k, walk = memo.key(pre, r0, r0 + 4)
    memo.lookup(k)
    assert memo.record(k, walk, post, span_len=4)
    return k, pre, post


def test_memo_save_load_hits_on_second_invocation(tmp_path):
    path = str(tmp_path / "cache.memo.npz")
    first = memomod.ChainMemo(salt=b"world-v1")
    k, pre, post = _record_one(first)
    first.save(path)

    second = memomod.ChainMemo(salt=b"world-v1")
    assert second.load(path) == 1
    assert second.loaded_entries == 1
    entry = second.lookup(k)
    assert entry is not None and entry.persisted
    assert second.persisted_hits == 1  # ROADMAP-3: hits > 0, run 2
    replayed = second.replay(entry, jax.device_get(pre))
    np.testing.assert_array_equal(replayed[0], post[0])
    assert int(replayed[1][0].events) == int(post[1][0].events)


def test_memo_load_salt_mismatch_refused(tmp_path):
    path = str(tmp_path / "cache.memo.npz")
    first = memomod.ChainMemo(salt=b"world-v1")
    _record_one(first)
    first.save(path)
    other = memomod.ChainMemo(salt=b"world-v2")
    with pytest.raises(CheckpointError, match="salt_sha256"):
        other.load(path)


def test_memo_absorb_missing_leaf_refused():
    memo = memomod.ChainMemo(salt=b"w")
    _record_one(memo)
    meta, arrays = memo.spill()
    victim = next(iter(arrays))
    del arrays[victim]
    fresh = memomod.ChainMemo(salt=b"w")
    with pytest.raises(CheckpointError, match=victim):
        fresh.absorb(meta, arrays)


def test_memo_restore_reproduces_instance_exactly():
    # the memoized kill/resume byte-parity surface: spill +
    # absorb(restore=True) reproduces stats() and report() verbatim,
    # including per-entry hit counts and the pre-record miss census
    memo = memomod.ChainMemo(salt=b"w", min_repeat=2)
    pre, post = _mk_carry(1), _mk_carry(2, events=3)
    k, walk = memo.key(pre, 8, 12)
    memo.lookup(k)                       # miss 1 (below min_repeat)
    assert not memo.record(k, walk, post, span_len=4)
    memo.lookup(k)                       # miss 2
    assert memo.record(k, walk, post, span_len=4)
    assert memo.lookup(k) is not None    # a hit on the entry

    meta, arrays = memo.spill()
    twin = memomod.ChainMemo(salt=b"w", min_repeat=2)
    twin.absorb(meta, arrays, restore=True)
    assert twin.stats() == memo.stats()
    assert twin.report() == memo.report()
    assert twin._seen == memo._seen
    # and the restored entry still replays
    entry = twin.lookup(k)
    replayed = twin.replay(entry, jax.device_get(pre))
    np.testing.assert_array_equal(replayed[0], post[0])


def test_memo_spill_rides_runstate_checkpoint(tmp_path):
    memo = memomod.ChainMemo(salt=b"w")
    k, pre, post = _record_one(memo)
    ck = RunCheckpointer(str(tmp_path), every=2, memo=memo)
    info = ck.save(2, _toy_carry(), host=True)
    fresh = memomod.ChainMemo(salt=b"w")
    res = resume_carry(info["path"], _toy_carry(), memo=fresh)
    assert res["memo_loaded"] == 1
    assert fresh.stats() == memo.stats()


# ---------------------------------------------------------------------------
# driver-level resume parity (@slow — CI runs this file unfiltered)


ROUNDS, CHAIN_LEN, EVERY = 12, 4, 4


def _world():
    return profiling.build_world(N, n_nodes=8, egress_cap=8,
                                 ingress_cap=16, seed=3,
                                 warmup_windows=1)


def _make_chain_fn(params, window):
    def chain_fn(state, extras, rids, _pr):
        key, spawn_seq, total = extras

        def round_fn(carry, round_idx):
            state, spawn_seq = carry
            shift = jnp.where(round_idx == 0, jnp.int32(0), window)
            out = window_step(state, params, key, shift, window,
                              rr_enabled=False)
            (state, delivered, _nx), _m, _g, _h, _fr = \
                unpack_planes(out)
            mask, new_dst, nbytes, seq_vals, ctrl = respawn_batch(
                delivered, spawn_seq, round_idx, N,
                state.in_src.shape[1])
            out = ingest_rows(state, new_dst, nbytes, seq_vals,
                              seq_vals, ctrl, valid=mask)
            (state,), _m, _g, _h, _fr = unpack_planes(out, n_lead=1)
            spawn_seq = spawn_seq + mask.sum(axis=1, dtype=jnp.int32)
            return (state, spawn_seq), mask.sum(dtype=jnp.int32)

        (state, spawn_seq), nd = jax.lax.scan(
            round_fn, (state, spawn_seq), rids)
        zeros = jnp.zeros((N,), jnp.int32)
        return state, (key, spawn_seq, total + nd.sum()), zeros, zeros

    return chain_fn


def _fresh_extras(key):
    return (key, jnp.full((N,), SPAWN_BASE, jnp.int32),
            jnp.zeros((), jnp.int32))


def _digest(state, extras):
    return digest_pytrees(elastic.canonical_state(state),
                          extras[1], extras[2])


@pytest.mark.slow
def test_driver_checkpoint_resume_parity(tmp_path):
    """The tentpole theorem at driver level: run-to-r8, resume-from-r8
    ends bitwise-identical to the uninterrupted run — and the
    checkpointing run itself matches too (cuts are invisible)."""
    world = _world()
    chain_fn = _make_chain_fn(world["params"], world["window"])
    key = world["rng_root"]

    plain_state, plain_extras = elastic.drive_chained_windows(
        world["state"], _fresh_extras(key), chain_fn,
        n_rounds=ROUNDS, chain_len=CHAIN_LEN)
    want = _digest(plain_state, plain_extras)

    ck = RunCheckpointer(str(tmp_path), every=EVERY, label="drv")
    ck_state, ck_extras = elastic.drive_chained_windows(
        world["state"], _fresh_extras(key), chain_fn,
        n_rounds=ROUNDS, chain_len=CHAIN_LEN, checkpointer=ck)
    assert _digest(ck_state, ck_extras) == want
    assert ck.saved == 2  # r4 and r8; r12 skipped (final)

    # "crash" after r8: rebuild a cold template, restore, continue
    res = resume_carry(latest_checkpoint(str(tmp_path), label="drv"),
                       (world["state"], _fresh_extras(key)))
    assert res["round"] == 8
    r_state, r_extras = res["carry"]
    r_state, r_extras = elastic.drive_chained_windows(
        r_state, r_extras, chain_fn, n_rounds=ROUNDS,
        chain_len=CHAIN_LEN, start_round=res["round"])
    assert _digest(r_state, r_extras) == want


@pytest.mark.slow
def test_ensemble_checkpoint_resume_parity(tmp_path):
    """2-world ensemble: the per-world batched carries spill to ONE
    file and a resumed ensemble matches the uninterrupted one
    bitwise, world by world."""
    W = 2
    world = _world()
    chain_fn = _make_chain_fn(world["params"], world["window"])
    keys = elastic.world_keys(world["rng_root"],
                              jnp.arange(W, dtype=jnp.int32))
    stacked = jax.tree.map(lambda x: jnp.stack([x] * W), world["state"])

    def fresh_extras():
        return (keys, jnp.full((W, N), SPAWN_BASE, jnp.int32),
                jnp.zeros((W,), jnp.int32))

    plain_states, plain_extras = elastic.drive_ensemble(
        stacked, fresh_extras(), chain_fn, n_rounds=ROUNDS,
        chain_len=CHAIN_LEN)
    want = digest_pytrees(plain_states, plain_extras[1],
                          plain_extras[2])

    ck = RunCheckpointer(str(tmp_path), every=EVERY, label="ens")
    ck_states, ck_extras = elastic.drive_ensemble(
        stacked, fresh_extras(), chain_fn, n_rounds=ROUNDS,
        chain_len=CHAIN_LEN, checkpointer=ck)
    assert digest_pytrees(ck_states, ck_extras[1],
                          ck_extras[2]) == want
    assert ck.saved == 2

    res = resume_carry(latest_checkpoint(str(tmp_path), label="ens"),
                       (stacked, fresh_extras()))
    r_states, r_extras = res["carry"]
    r_states, r_extras = elastic.drive_ensemble(
        r_states, r_extras, chain_fn, n_rounds=ROUNDS,
        chain_len=CHAIN_LEN, start_round=res["round"])
    assert digest_pytrees(r_states, r_extras[1], r_extras[2]) == want


def _tiny_spec(windows=48, lossy=False):
    from shadow_tpu.workloads.spec import parse_scenario

    d = {
        "name": "runstate-ring", "family": "ring_allreduce",
        "seed": 11, "hosts": N, "windows": windows,
        "patterns": [{"kind": "ring_allreduce", "first": 0,
                      "count": N, "bytes": 1024, "rounds": 1}],
    }
    if lossy:
        d["transport"] = "flows"
        d["loss_p"] = 0.05
    return parse_scenario(d)


@pytest.mark.slow
def test_run_scenario_resume_record_identical(tmp_path):
    """`run_scenario(resume=True)` reproduces the EXACT record dict of
    the uninterrupted run (the byte-parity CI gate's in-process twin),
    and stamps provenance on the side channel only."""
    from shadow_tpu.workloads import runner

    spec = _tiny_spec()
    plain = runner.run_scenario(spec)

    ckdir = str(tmp_path / "ck")
    prov: dict = {}
    ck_rec = runner.run_scenario(spec, checkpoint_dir=ckdir,
                                 checkpoint_every=16, provenance=prov)
    assert ck_rec == plain  # checkpoint cuts are bitwise-invisible
    assert prov["checkpoints_written"] == 2
    assert prov["resumed_from"] is None

    prov2: dict = {}
    res_rec = runner.run_scenario(spec, checkpoint_dir=ckdir,
                                  checkpoint_every=16, resume=True,
                                  provenance=prov2)
    assert res_rec == plain
    assert prov2["resumed_from"] == "runstate-ring-r00000032"
    assert prov2["start_round"] == 32
    assert json.dumps(res_rec, sort_keys=True) == \
        json.dumps(plain, sort_keys=True)


@pytest.mark.slow
def test_run_scenario_resume_parity_lossy(tmp_path):
    """Resume parity under the flows transport with the loss plane
    live (the CI corpus gate's in-process twin)."""
    from shadow_tpu.workloads import runner

    spec = _tiny_spec(lossy=True)
    plain = runner.run_scenario(spec)

    ckdir = str(tmp_path / "ck")
    runner.run_scenario(spec, checkpoint_dir=ckdir, checkpoint_every=16)
    res = runner.run_scenario(spec, checkpoint_dir=ckdir,
                              checkpoint_every=16, resume=True)
    assert res == plain


@pytest.mark.slow
def test_run_scenario_resume_parity_memoized(tmp_path):
    """Resume parity with the memo plane live — the memo census comes
    back verbatim (hits and all), so even the report matches."""
    from shadow_tpu.workloads import runner

    spec = _tiny_spec()
    plain = runner.run_scenario(spec, memo=True)
    # the memo plane is really live: spans were looked up and recorded
    # (in-run hits need longer periodic runs; the census-restoration
    # exactness is pinned by test_memo_restore_reproduces_instance_exactly)
    assert plain["memo"]["lookups"] > 0
    assert plain["memo"]["records"] > 0

    ckdir = str(tmp_path / "ck")
    runner.run_scenario(spec, memo=True, checkpoint_dir=ckdir,
                        checkpoint_every=16)
    res = runner.run_scenario(spec, memo=True, checkpoint_dir=ckdir,
                              checkpoint_every=16, resume=True)
    assert res == plain


@pytest.mark.slow
def test_run_scenario_memo_cache_second_invocation(tmp_path):
    """`--memo-cache` end to end: run 2 serves every span from the
    persisted cache (persisted hits > 0, zero misses) with an
    identical canonical digest."""
    from shadow_tpu.workloads import runner

    spec = _tiny_spec()
    cache = str(tmp_path / "ring.memo.npz")
    first = runner.run_scenario(spec, memo=True, memo_cache=cache)
    assert os.path.isfile(cache)
    assert first["memo"]["persisted_hits"] == 0

    second = runner.run_scenario(spec, memo=True, memo_cache=cache)
    assert second["canonical_digest"] == first["canonical_digest"]
    assert second["memo"]["loaded_entries"] > 0
    assert second["memo"]["persisted_hits"] > 0
    assert second["memo"]["misses"] == 0


@pytest.mark.slow
def test_run_scenario_resume_refuses_drifted_scenario(tmp_path):
    from shadow_tpu.workloads import runner

    ckdir = str(tmp_path / "ck")
    runner.run_scenario(_tiny_spec(), checkpoint_dir=ckdir,
                        checkpoint_every=16)
    drifted = _tiny_spec(lossy=True)  # same name, different physics
    with pytest.raises(CheckpointError, match="fingerprint"):
        runner.run_scenario(drifted, checkpoint_dir=ckdir,
                            checkpoint_every=16, resume=True)
