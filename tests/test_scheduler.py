"""Scheduler semantics: persistent pools, stealing, error transport.

Parity: reference `src/lib/scheduler/` unit tests
(`thread_per_core.rs:214-328`) — run/run_with_hosts over a persistent pool,
plus the determinism contract that scheduling strategy never changes
results (covered end-to-end by tools/compare_runs.py --matrix).
"""

from __future__ import annotations

import pytest

from shadow_tpu.core.scheduler import (
    SerialScheduler,
    ThreadPerCoreScheduler,
    ThreadPerHostScheduler,
    make_scheduler,
)
from shadow_tpu.core.worker import WorkerShared


class FakeHost:
    """Minimal host: execute() records the call; next_event_time fixed."""

    def __init__(self, next_time=None, fail=False):
        self._next = next_time
        self._fail = fail
        self.executed = 0

    def execute(self, until):
        if self._fail:
            raise RuntimeError("host exploded")
        self.executed += 1

    def next_event_time(self):
        return self._next


def make_shared():
    return WorkerShared(
        dns=None, routing=None, ip_to_host={}, ip_to_node_id={},
        runahead=None, sim_end_time=10**9,
    )


@pytest.mark.parametrize("kind", ["serial", "thread-per-core"])
def test_all_hosts_execute_and_min_next(kind):
    shared = make_shared()
    hosts = [FakeHost(next_time=100 + i) for i in range(7)]
    sched = make_scheduler(kind, shared, 3)
    try:
        for round_no in range(3):
            got = sched.run_round(hosts, 10**9)
            assert got == 100
        assert all(h.executed == 3 for h in hosts)
    finally:
        sched.join()


def test_thread_per_host_pins_hosts():
    shared = make_shared()
    hosts = [FakeHost(next_time=50), FakeHost(next_time=40), FakeHost()]
    sched = make_scheduler("thread-per-host", shared, 2, hosts=hosts)
    assert isinstance(sched, ThreadPerHostScheduler)
    try:
        assert sched.run_round(hosts, 10**9) == 40
        assert all(h.executed == 1 for h in hosts)
        # an active SUBSET runs only those hosts (the Manager's
        # active-host heap hands the scheduler just the hosts with an
        # event this round); pinned threads for the rest stay parked
        assert sched.run_round(hosts[:2], 10**9) == 40
        assert [h.executed for h in hosts] == [2, 2, 1]
        # a host the scheduler was never constructed with is an error
        with pytest.raises(ValueError):
            sched.run_round([FakeHost()], 10**9)
    finally:
        sched.join()


def test_worker_exception_propagates_and_pool_survives():
    """A failing host must raise on the driving thread, and the pool must
    stay usable for the next round (a dead worker thread would deadlock)."""
    shared = make_shared()
    good = [FakeHost(next_time=10) for _ in range(3)]
    bad = FakeHost(fail=True)
    sched = ThreadPerCoreScheduler(shared, 2, pin_cpus=False)
    try:
        with pytest.raises(RuntimeError, match="host exploded"):
            sched.run_round(good + [bad], 10**9)
        # pool survives: next round (without the bad host) runs normally
        assert sched.run_round(good, 10**9) == 10
    finally:
        sched.join()


def test_serial_when_parallelism_one():
    shared = make_shared()
    assert isinstance(make_scheduler("thread-per-core", shared, 1), SerialScheduler)
    assert isinstance(make_scheduler("serial", shared, 8), SerialScheduler)


def test_managed_threads_follow_worker_pin(tmp_path):
    """Managed native threads are migrated to their worker's CPU
    (`managed_thread.rs:533-544` + affinity.c); requires a parallel,
    pinned scheduler (on a 1-core box both workers share cpu 0)."""
    import os
    import shutil

    if not hasattr(os, "sched_setaffinity"):
        import pytest

        pytest.skip("no sched_setaffinity")
    if shutil.which("sleep") is None:
        import pytest

        pytest.skip("no sleep binary")
    from shadow_tpu.core.config import load_config_str
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str("""
general: {stop_time: 3s, seed: 3, parallelism: 2}
network:
  graph: {type: 1_gbit_switch}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {path: /bin/sleep, args: ["1"], start_time: 1s,
       expected_final_state: {exited: 0}}
  beta:
    network_node_id: 0
    processes:
    - {path: /bin/sleep, args: ["1"], start_time: 1s,
       expected_final_state: {exited: 0}}
""")
    mgr = Manager(cfg)
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    pins = [c.get("proc").threads[0].pinned_cpu
            for _n, _p, c in mgr._spawned]
    assert all(p is not None for p in pins), pins
