"""shadowlint coverage: every rule fires on its seeded fixture, the
suppression syntax works, scoping is honored, the jaxpr rules trigger on
synthetic kernels, and the real tree is clean (the acceptance gate)."""

import os
import sys

import pytest

pytest_plugins = ["pytester"]

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from shadow_tpu.analysis import (  # noqa: E402
    RULES, audit_all, audit_jaxpr, lint_source, parse_suppressions,
    rule_applies, sweep_window_step,
)

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")


def _lint_fixture(name: str, relpath: str):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as fh:
        source = fh.read()
    return source, lint_source(source, relpath)


def _line_of(source: str, needle: str) -> int:
    for i, text in enumerate(source.splitlines(), start=1):
        if needle in text:
            return i
    raise AssertionError(f"{needle!r} not in fixture")


# -- pass 1 rules ---------------------------------------------------------

def test_sl101_wallclock_fires_and_suppresses():
    src, findings = _lint_fixture(
        "fixture_wallclock.py", "shadow_tpu/core/fixture_wallclock.py")
    f101 = [f for f in findings if f.rule == "SL101"]
    active = {f.line for f in f101 if not f.suppressed}
    assert active == {
        _line_of(src, "a = time.time()"),
        _line_of(src, "b = _walltime.monotonic()"),
        _line_of(src, "c = _perf_ns()"),
        _line_of(src, "d = datetime.now()"),
        # the malformed (justification-free) disable must NOT suppress
        _line_of(src, "return time.perf_counter()"),
    }
    sup = {f.line: f.justification for f in f101 if f.suppressed}
    assert sup == {
        _line_of(src, "return time.monotonic()"): "test justification",
        _line_of(src, "return time.monotonic_ns()"):
            "justified on the preceding line",
    }
    malformed = parse_suppressions(src).malformed
    assert [ln for ln, _ in malformed] == [
        _line_of(src, "time.perf_counter()")]


def test_sl102_randomness_fires_not_on_seeded_generators():
    src, findings = _lint_fixture(
        "fixture_randomness.py", "shadow_tpu/net/fixture_randomness.py")
    lines = {f.line for f in findings if f.rule == "SL102"}
    assert lines == {
        _line_of(src, "random.random()"),
        _line_of(src, "_rnd.randint"),
        _line_of(src, "random.seed(42)"),
        _line_of(src, "np.random.rand(3)"),
        _line_of(src, "np.random.shuffle"),
    }


def test_sl102_exempts_core_rng():
    source = "import random\nx = random.random()\n"
    assert lint_source(source, "shadow_tpu/core/rng.py") == []
    assert len(lint_source(source, "shadow_tpu/core/other.py")) == 1


def test_sl103_unordered_iteration():
    src, findings = _lint_fixture(
        "fixture_unordered.py", "shadow_tpu/core/fixture_unordered.py")
    lines = {f.line for f in findings if f.rule == "SL103"}
    assert lines == {
        _line_of(src, "for h in pending:"),
        _line_of(src, "for h in set(hosts):"),
        _line_of(src, "for h in list({1, 2, 3}):"),
        _line_of(src, "frozenset(hosts)]"),
        _line_of(src, "for h in other:"),
    }
    assert not [f for f in findings if f.rule != "SL103"]


def test_sl103_scoped_to_scheduling_dirs():
    source = "for x in set(range(3)):\n    pass\n"
    assert lint_source(source, "shadow_tpu/core/scheduler.py")
    assert not lint_source(source, "shadow_tpu/tpu/plane.py")
    assert not lint_source(source, "tools/bench_ladder.py")


def test_sl104_mutable_defaults():
    src, findings = _lint_fixture(
        "fixture_mutable_default.py",
        "shadow_tpu/utils/fixture_mutable_default.py")
    by_line = sorted(f.line for f in findings if f.rule == "SL104")
    two = _line_of(src, "seen=set(), extra=dict()")
    assert by_line == sorted([
        _line_of(src, "xs=[]"),
        _line_of(src, "opts={}"),
        two, two,
        _line_of(src, "collections.deque()"),
    ])


def test_sl102_not_fooled_by_shadowing_names():
    # a parameter/local named `random` or `time` is not the stdlib
    # module; only imported names resolve to module paths
    source = ("def f(random):\n"
              "    return random.random()\n"
              "def g():\n"
              "    time = object()\n"
              "    return time.monotonic()\n")
    assert lint_source(source, "shadow_tpu/core/other.py") == []


def test_sl103_covers_tcp_and_apps():
    source = "for x in set(range(3)):\n    pass\n"
    assert lint_source(source, "shadow_tpu/tcp/connection.py")
    assert lint_source(source, "shadow_tpu/apps/iperf.py")


def test_sl105_traced_branches():
    src, findings = _lint_fixture(
        "fixture_traced_branch.py",
        "shadow_tpu/tpu/fixture_traced_branch.py")
    lines = {f.line for f in findings if f.rule == "SL105"}
    assert lines == {
        _line_of(src, "jnp.any(mask):"),
        _line_of(src, "x.sum() > 0:"),
        _line_of(src, "jnp.all(mask) else"),
        _line_of(src, "assert jnp.max(x)"),
    }
    # tpu/-only scoping
    assert not lint_source(
        "import jax.numpy as jnp\nif jnp.any(x):\n    pass\n",
        "shadow_tpu/core/scheduler.py")


def test_sl105_device_get_exempts_only_its_subexpression():
    prologue = "import jax\nimport jax.numpy as jnp\n"
    # the whole test routed through the sync: intentional, no finding
    assert not lint_source(
        prologue + "if jax.device_get(jnp.any(x)):\n    pass\n",
        "shadow_tpu/tpu/plane.py")
    # a traced read ALONGSIDE a sync is still a hazard
    findings = lint_source(
        prologue + "if jnp.any(x) and jax.device_get(y):\n    pass\n",
        "shadow_tpu/tpu/plane.py")
    assert [f.rule for f in findings] == ["SL105"]


def test_sl301_sync_in_kernel_bodies():
    src, findings = _lint_fixture(
        "fixture_kernel_sync.py",
        "shadow_tpu/tpu/fixture_kernel_sync.py")
    lines = {f.line for f in findings if f.rule == "SL301"}
    assert lines == {
        _line_of(src, "# violation: sync inside a jit-decorated body"),
        _line_of(src, "# violation: fn is passed to donating_jit below"),
        _line_of(src, "# violation: while_loop body"),
        _line_of(src, "# violation: lambda under jit"),
    }


def test_sl301_scoped_to_tpu_and_allows_barrier_syncs():
    kernel = ("import jax\n"
              "@jax.jit\n"
              "def k(x):\n"
              "    return jax.device_get(x)\n")
    # tpu/-only scoping: the same kernel in core/ is out of scope
    assert not [f for f in lint_source(kernel, "shadow_tpu/core/x.py")
                if f.rule == "SL301"]
    assert [f.rule for f in lint_source(kernel, "shadow_tpu/tpu/x.py")
            if f.rule == "SL301"] == ["SL301"]
    # a sync in a plain (non-kernel) function is the sanctioned pattern
    barrier = ("import jax\n"
               "def release(state):\n"
               "    return jax.device_get(state)\n")
    assert not [f for f in lint_source(barrier, "shadow_tpu/tpu/x.py")
                if f.rule == "SL301"]


def test_sl301_builtin_map_is_not_a_lax_body():
    # Python's map()/local helpers named cond must not mark their
    # callees as kernels — only resolved jax.lax.* control flow does
    src = ("import jax\n"
           "def _drain(x):\n"
           "    return jax.device_get(x)\n"
           "def flush(chunks):\n"
           "    return list(map(_drain, chunks))\n"
           "def cond(fn, x):\n"
           "    return fn(x)\n"
           "def use(x):\n"
           "    return cond(_drain, x)\n")
    assert not [f for f in lint_source(src, "shadow_tpu/tpu/x.py")
                if f.rule == "SL301"]
    # ...while an aliased lax import still counts
    src2 = ("import jax\nfrom jax import lax\n"
            "def body(c):\n"
            "    return jax.device_get(c)\n"
            "def drive(x):\n"
            "    return lax.while_loop(lambda c: True, body, x)\n")
    assert [f.rule for f in lint_source(src2, "shadow_tpu/tpu/x.py")
            if f.rule == "SL301"] == ["SL301"]


def test_sl301_suppression_works():
    src = ("import jax\n"
           "@jax.jit\n"
           "def k(x):\n"
           "    # shadowlint: disable=SL301 -- test-only sync\n"
           "    return jax.device_get(x)\n")
    findings = [f for f in lint_source(src, "shadow_tpu/tpu/x.py")
                if f.rule == "SL301"]
    assert len(findings) == 1 and findings[0].suppressed


def test_sl402_assert_in_kernel_bodies():
    src, findings = _lint_fixture(
        "fixture_kernel_assert.py",
        "shadow_tpu/tpu/fixture_kernel_assert.py")
    lines = {f.line for f in findings if f.rule == "SL402"}
    assert lines == {
        _line_of(src, "# violation: assert in a jit-decorated body"),
        _line_of(src, "# violation: fn passed to donating_jit"),
        _line_of(src, "# violation: while_loop body"),
    }


def test_sl402_scoped_to_tpu_and_allows_host_asserts():
    kernel = ("import jax\n"
              "@jax.jit\n"
              "def k(x):\n"
              "    assert x is not None\n"
              "    return x\n")
    # tpu/-only scoping: the same kernel elsewhere is out of scope
    assert not [f for f in lint_source(kernel, "shadow_tpu/core/x.py")
                if f.rule == "SL402"]
    assert [f.rule for f in lint_source(kernel, "shadow_tpu/tpu/x.py")
            if f.rule == "SL402"] == ["SL402"]
    # a host-side assert in a plain function is untouched
    host = ("def barrier(batch):\n"
            "    assert batch\n"
            "    return batch\n")
    assert not [f for f in lint_source(host, "shadow_tpu/tpu/x.py")
                if f.rule == "SL402"]


def test_sl402_suppression_works():
    src = ("import jax\n"
           "@jax.jit\n"
           "def k(x):\n"
           "    # shadowlint: disable=SL402 -- trace-time shape pin\n"
           "    assert x is not None\n"
           "    return x\n")
    findings = [f for f in lint_source(src, "shadow_tpu/tpu/x.py")
                if f.rule == "SL402"]
    assert len(findings) == 1 and findings[0].suppressed


def test_sl402_tree_is_clean():
    """No active assert-in-kernel finding anywhere in shadow_tpu/tpu/:
    runtime invariants go through the guard plane (shadow_tpu/guards/),
    trace-time checks through explicit raises."""
    root = os.path.join(os.path.dirname(__file__), "..",
                        "shadow_tpu", "tpu")
    for name in sorted(os.listdir(root)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(root, name), encoding="utf-8") as fh:
            findings = lint_source(fh.read(), f"shadow_tpu/tpu/{name}")
        active = [f for f in findings
                  if f.rule == "SL402" and not f.suppressed]
        assert not active, [str(f) for f in active]


def test_sl403_variadic_sorts_fire():
    src, findings = _lint_fixture(
        "fixture_variadic_sort.py",
        "shadow_tpu/tpu/fixture_variadic_sort.py")
    f403 = [f for f in findings if f.rule == "SL403"]
    active = {f.line for f in f403 if not f.suppressed}
    assert active == {
        _line_of(src, "return jax.lax.sort((a, b, c, d, e, f), "
                      "dimension=0, is_stable=True,"),
        _line_of(src, "return _row_sort(a, b, c, d, e, f, keys=1)"),
    }
    # the suppressed legacy-reference call carries its justification
    sup = [f for f in f403 if f.suppressed]
    assert len(sup) == 1
    assert sup[0].justification == "legacy parity reference (fixture)"


def test_sl403_skips_uncountable_and_budget_sorts():
    """Starred operand tuples, non-tuple operand forwarding, computed
    key counts, and sorts at the 3-payload budget all stay clean."""
    src, findings = _lint_fixture(
        "fixture_variadic_sort.py",
        "shadow_tpu/tpu/fixture_variadic_sort.py")
    flagged = {f.line for f in findings if f.rule == "SL403"}
    for needle in ("return jax.lax.sort((a, b, c, d), dimension=0,",
                   "one = jax.lax.sort((packed, *extras, col)",
                   "two = jax.lax.sort(arrays",
                   "three = _row_sort(packed, col, keys=k)",
                   # the wrapper's own forwarding call (Name, not tuple)
                   "return jax.lax.sort(arrays, dimension=1"):
        assert _line_of(src, needle) not in flagged, needle


def test_sl403_scoped_to_tpu():
    src = ("import jax\n"
           "def f(a, b, c, d, e):\n"
           "    return jax.lax.sort((a, b, c, d, e), num_keys=1)\n")
    assert [f.rule for f in lint_source(src, "shadow_tpu/tpu/x.py")] \
        == ["SL403"]
    assert not lint_source(src, "shadow_tpu/core/x.py")
    assert not lint_source(src, "tools/x.py")


def test_sl403_tree_is_clean():
    """No active variadic-sort finding anywhere in shadow_tpu/tpu/: the
    hot paths are on the packed-key/bucketed diet, and the compiled-in
    packed_sort=False parity references carry justified suppressions."""
    root = os.path.join(os.path.dirname(__file__), "..",
                        "shadow_tpu", "tpu")
    suppressed = []
    for name in sorted(os.listdir(root)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(root, name), encoding="utf-8") as fh:
            findings = lint_source(fh.read(), f"shadow_tpu/tpu/{name}")
        active = [f for f in findings
                  if f.rule == "SL403" and not f.suppressed]
        assert not active, [str(f) for f in active]
        suppressed += [f for f in findings
                       if f.rule == "SL403" and f.suppressed]
    # the legacy reference paths exist and are justified, not silently
    # diet-ed away (they ARE the parity baseline)
    assert len(suppressed) >= 6
    assert all(f.justification for f in suppressed)


def test_sl405_telemetry_reads_fire():
    src, findings = _lint_fixture(
        "fixture_telemetry_read.py",
        "shadow_tpu/core/fixture_telemetry_read.py")
    f405 = [f for f in findings if f.rule == "SL405"]
    active = {f.line for f in f405 if not f.suppressed}
    assert active == {
        _line_of(src, "float(metrics.pkts_out.sum())"),
        _line_of(src, "metrics.drop_loss.sum().item()"),
        _line_of(src, "float(state.n_out[0])"),
        _line_of(src, "hist.hist_delivery_ns.sum().item()"),
        _line_of(src, "float(metrics.windows)"),
    }
    sup = [f for f in f405 if f.suppressed]
    assert len(sup) == 1
    assert sup[0].justification == \
        "teardown diagnostic, run already over"


def test_sl405_skips_host_side_and_untyped_reads():
    src, findings = _lint_fixture(
        "fixture_telemetry_read.py",
        "shadow_tpu/core/fixture_telemetry_read.py")
    flagged = {f.line for f in findings if f.rule == "SL405"}
    for needle in ('float(np.asarray(totals["pkts_out"]).sum())',
                   "float(weights[0])",
                   "weights.sum().item()"):
        assert _line_of(src, needle) not in flagged, needle


def test_sl405_scope_exempts_harvest_boundary_and_tools():
    src = "def f(metrics):\n    return float(metrics.pkts_out.sum())\n"
    assert [f.rule for f in lint_source(src, "shadow_tpu/core/x.py")] \
        == ["SL405"]
    assert [f.rule for f in lint_source(src, "shadow_tpu/tpu/x.py")] \
        == ["SL405"]
    # the harvest boundary itself is the sanctioned reader
    assert not lint_source(src, "shadow_tpu/telemetry/harvest.py")
    assert not lint_source(src, "shadow_tpu/telemetry/flightrec.py")
    # tools/ drivers pull at sync points they own
    assert not lint_source(src, "tools/chaos_smoke.py")


def test_sl405_field_set_matches_live_pytrees():
    """The lexical field net must cover every leaf of the live
    telemetry pytrees — a new counter field cannot silently escape
    the rule."""
    from shadow_tpu.analysis.astlint import _TELEMETRY_FIELD_ATTRS
    from shadow_tpu.telemetry.flightrec import FlightRecArrays
    from shadow_tpu.telemetry.histo import PlaneHistograms
    from shadow_tpu.telemetry.metrics import PlaneMetrics
    from shadow_tpu.tpu.transport import TransportHist

    want = (set(PlaneMetrics._fields) | set(PlaneHistograms._fields)
            | set(TransportHist._fields)
            | {f for f in FlightRecArrays._fields
               if f.startswith("ev_")}
            | {"n_out", "n_released"})
    missing = want - _TELEMETRY_FIELD_ATTRS
    assert not missing, f"SL405 field set is missing {missing}"


def test_sl405_tree_is_clean():
    """No active sync-telemetry-read anywhere in shadow_tpu/ outside
    the harvest boundary: every observability read rides the
    asynchronous drain."""
    root = os.path.join(os.path.dirname(__file__), "..", "shadow_tpu")
    for dirpath, _dirs, files in os.walk(root):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, os.path.join(root, "..")) \
                .replace(os.sep, "/")
            with open(full, encoding="utf-8") as fh:
                findings = lint_source(fh.read(), rel)
            active = [f for f in findings
                      if f.rule == "SL405" and not f.suppressed]
            assert not active, [str(f) for f in active]


def test_clean_fixture_and_sl101_scope():
    _, findings = _lint_fixture(
        "fixture_clean.py", "shadow_tpu/core/fixture_clean.py")
    assert findings == []
    # wall-clock reads are fine in tools/ benchmarks
    source = "import time\nt = time.monotonic()\n"
    assert not lint_source(source, "tools/bench_ladder.py")
    assert lint_source(source, "shadow_tpu/core/manager.py")


def test_rule_registry_complete():
    assert set(RULES) == {f"SL10{i}" for i in range(1, 6)} | {
        f"SL20{i}" for i in range(1, 6)} | {
        f"SL50{i}" for i in range(1, 7)} | {
        f"SL60{i}" for i in range(1, 4)} | {
        f"SL70{i}" for i in range(1, 4)} | {"SL301", "SL401", "SL402",
                                            "SL403", "SL405"}
    for rid in ("SL101", "SL102", "SL103", "SL104", "SL105", "SL301",
                "SL401", "SL402", "SL403", "SL405", "SL503"):
        assert rule_applies(rid, "shadow_tpu/core/x.py") \
            or rid in ("SL105", "SL301", "SL402", "SL403")


# -- SL401: swallowed broad exceptions ------------------------------------

def test_sl401_swallowed_errors():
    src, findings = _lint_fixture(
        "fixture_swallowed.py", "shadow_tpu/process/fixture_swallowed.py")
    f401 = [f for f in findings if f.rule == "SL401"]
    active = {f.line for f in f401 if not f.suppressed}
    assert active == {
        _line_of(src, "except Exception:  # BAD"),
        _line_of(src, "except (ValueError, BaseException):  # BAD"),
        _line_of(src, "except:  # noqa: E722  BAD"),
    }


def test_sl401_scoped_to_shadow_tpu():
    src = "try:\n    pass\nexcept Exception:\n    pass\n"
    assert [f for f in lint_source(src, "shadow_tpu/core/x.py")
            if f.rule == "SL401"]
    assert not [f for f in lint_source(src, "tools/x.py")
                if f.rule == "SL401"]


def test_sl401_narrow_types_and_logged_handlers_pass():
    src, findings = _lint_fixture(
        "fixture_swallowed.py", "shadow_tpu/process/fixture_swallowed.py")
    ok_lines = {
        _line_of(src, "except:  # noqa: E722  OK"),
        _line_of(src, "except Exception:  # OK"),
        _line_of(src, "except Exception as e:  # OK"),
        _line_of(src, "except OSError:  # OK"),
    }
    assert not ok_lines & {f.line for f in findings if f.rule == "SL401"}


def test_sl401_suppression_works():
    src = (
        "try:\n"
        "    risky()\n"
        "# shadowlint: disable=SL401 -- cleanup-only teardown guard\n"
        "except Exception:\n"
        "    pass\n"
    )
    f401 = [f for f in lint_source(src, "shadow_tpu/core/x.py")
            if f.rule == "SL401"]
    assert len(f401) == 1 and f401[0].suppressed
    assert f401[0].justification == "cleanup-only teardown guard"


def test_sl401_tree_is_clean():
    """Every in-tree broad handler either logs, re-raises, or carries a
    justified suppression — the satellite's fix-or-suppress contract."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.join(repo, "shadow_tpu")
    bad = []
    for dirpath, _dirs, files in os.walk(root):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, repo).replace(os.sep, "/")
            with open(path, encoding="utf-8") as fh:
                for f in lint_source(fh.read(), rel):
                    if f.rule == "SL401" and not f.suppressed:
                        bad.append(str(f))
    assert not bad, "\n".join(bad)


# -- SL503: buffer-donation safety ----------------------------------------

def test_sl503_donation_fixture():
    src, findings = _lint_fixture(
        "fixture_donation.py", "shadow_tpu/tpu/fixture_donation.py")
    f503 = [f for f in findings if f.rule == "SL503"]
    active = {f.line for f in f503 if not f.suppressed}
    assert active == {
        _line_of(src, "total = state.n_sent.sum()  # violation"),
        _line_of(src, "print(state)  # violation"),
        _line_of(src, "return rows.sum()  # violation"),
        _line_of(src, "jax.jit(fn, donate_argnums=(0,))  # violation"),
    }
    sup = [f for f in f503 if f.suppressed]
    assert len(sup) == 1
    assert sup[0].justification == "cpu-only diagnostic path (fixture)"
    # the sanctioned consume-and-rebind shape and the clean lookalike
    for needle in ("state = step(state, deltas)  # consume-and-rebind",
                   "return jax.jit(fn)"):
        assert _line_of(src, needle) not in {f.line for f in f503}


def test_sl503_scope_covers_drivers_and_bench():
    src = ("import jax\n"
           "def f(fn):\n"
           "    return jax.jit(fn, donate_argnums=(0,))\n")
    for rel in ("shadow_tpu/tpu/x.py", "tools/chaos_smoke.py",
                "bench.py"):
        assert [f.rule for f in lint_source(src, rel)] == ["SL503"], rel
    # out of scope: tests and arbitrary paths
    assert not lint_source(src, "tests/test_x.py")


def test_sl503_wrapper_own_forwarding_is_exempt():
    src = ("import functools\n"
           "import jax\n"
           "def donating_jit(fun=None, donate_argnums=(0,), **kw):\n"
           "    if fun is None:\n"
           "        return functools.partial(donating_jit,\n"
           "                                 donate_argnums=donate_argnums)\n"
           "    return jax.jit(fun, donate_argnums=donate_argnums, **kw)\n")
    assert not [f for f in lint_source(src, "shadow_tpu/tpu/__init__.py")
                if f.rule == "SL503"]


def test_sl503_tree_is_clean():
    """No active donation hazard anywhere shadowlint gates (the
    package, tools/, bench.py)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = []
    targets = []
    for root in ("shadow_tpu", "tools"):
        for dirpath, _dirs, files in os.walk(os.path.join(repo, root)):
            targets += [os.path.join(dirpath, n) for n in sorted(files)
                        if n.endswith(".py")]
    targets.append(os.path.join(repo, "bench.py"))
    for path in targets:
        rel = os.path.relpath(path, repo).replace(os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            for f in lint_source(fh.read(), rel):
                if f.rule == "SL503" and not f.suppressed:
                    bad.append(str(f))
    assert not bad, "\n".join(bad)


# -- registry consistency (every rule has a firing fixture) ----------------

def _fires_ast(fixture: str, relpath: str, rule: str):
    def check():
        with open(os.path.join(FIXTURES, fixture),
                  encoding="utf-8") as fh:
            findings = lint_source(fh.read(), relpath)
        assert any(f.rule == rule for f in findings), \
            f"{fixture} does not trigger {rule}"
    return check


def _fires_jaxpr(fixture: str, rule: str):
    def check():
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            fixture.removesuffix(".py"),
            os.path.join(FIXTURES, fixture))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        findings = audit_jaxpr(mod.trace(), f"fixture:{fixture}")
        assert any(f.rule == rule for f in findings), \
            f"{fixture} does not trigger {rule}"
    return check


def _fires_taint():
    def check():
        import importlib.util

        from shadow_tpu.analysis import proofs

        spec = importlib.util.spec_from_file_location(
            "fixture_taint_leak",
            os.path.join(FIXTURES, "fixture_taint_leak.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert proofs.check_invisibility(mod.spec())
    return check


def _fires_budget():
    def check():
        import importlib.util
        import json
        import tempfile

        from shadow_tpu.analysis import proofs

        spec = importlib.util.spec_from_file_location(
            "fixture_op_budget",
            os.path.join(FIXTURES, "fixture_op_budget.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        entry = mod.entry()
        with tempfile.NamedTemporaryFile(
                "w", suffix=".json", delete=False) as fh:
            json.dump({"version": 1, "budgets": {
                f"{entry.module}:{entry.name}": mod.BUDGET}}, fh)
        try:
            findings, _ = proofs.check_op_budgets(fh.name, [entry])
        finally:
            os.unlink(fh.name)
        assert findings
    return check


def _fires_shard():
    def check():
        import importlib.util

        import jax

        from shadow_tpu.analysis import proofs
        from shadow_tpu.analysis.dataflow import shard_census

        spec = importlib.util.spec_from_file_location(
            "fixture_shard_classify",
            os.path.join(FIXTURES, "fixture_shard_classify.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        fn, args = mod.build()
        census = shard_census(jax.make_jaxpr(fn)(*args))
        assert census["cross_host"]
        # the GATING half: the same cross-host census planted on a
        # row-local-pinned entry must fail the fence with SL504
        pinned = sorted(proofs.ROW_LOCAL_PINNED)[0]
        findings = proofs.check_row_local_fence(
            {"sections": {key: (census if key == pinned
                                else {"cross_host": [],
                                      "host_local": {}, "opaque": []})
                          for key in proofs.ROW_LOCAL_PINNED}})
        assert findings and all(f.rule == "SL504" for f in findings)
        assert pinned in findings[0].path
    return check


def _fires_condeq():
    def check():
        import importlib.util

        from shadow_tpu.analysis import condeq

        spec = importlib.util.spec_from_file_location(
            "fixture_condeq_gate",
            os.path.join(FIXTURES, "fixture_condeq_gate.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        proof = condeq.check_gate(mod.obligation())
        assert not proof.ok
        assert proof.findings \
            and proof.findings[0].rule == "SL505"
    return check


def _fires_range():
    def check():
        import importlib.util

        import jax

        from shadow_tpu.analysis import ranges

        spec = importlib.util.spec_from_file_location(
            "fixture_int_overflow",
            os.path.join(FIXTURES, "fixture_int_overflow.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        fn, args = mod.build()
        trace = jax.make_jaxpr(fn)(*args)
        findings, _report = ranges.analyze_entry(
            mod.spec(), trace=trace, args=args)
        assert findings and findings[0].rule == "SL506" \
            and not findings[0].suppressed
    return check


def _fires_cost(rule: str, **overrides):
    """SL601/SL602 through the real checker: the fusion-break fixture
    kernel against a tampered budget (flops drift for SL601, a zeroed
    boundary count for SL602)."""
    def check():
        import importlib.util
        import json
        import tempfile

        from shadow_tpu.analysis import costmodel

        spec = importlib.util.spec_from_file_location(
            "fixture_fusion_break",
            os.path.join(FIXTURES, "fixture_fusion_break.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        doc = mod.budget(**overrides)
        with tempfile.NamedTemporaryFile(
                "w", suffix=".json", delete=False) as fh:
            json.dump(doc, fh)
        try:
            findings, _ = costmodel.check_cost_budgets(
                fh.name, entries=[mod.entry()])
        finally:
            os.unlink(fh.name)
        assert any(f.rule == rule for f in findings), \
            f"fixture_fusion_break does not trigger {rule}"
    return check


def _fires_host_sync():
    def check():
        from shadow_tpu.analysis import costmodel

        with open(os.path.join(FIXTURES, "fixture_host_sync.py"),
                  encoding="utf-8") as fh:
            findings = costmodel.check_host_sync_source(
                fh.read(), "bench.py")
        assert any(f.rule == "SL603" and not f.suppressed
                   for f in findings)
    return check


def _load_fixture(fixture: str):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        fixture.removesuffix(".py"), os.path.join(FIXTURES, fixture))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fires_world():
    def check():
        import jax

        from shadow_tpu.analysis import batchdim

        mod = _load_fixture("fixture_cross_world.py")
        fn, args = mod.build()
        findings, row = batchdim.world_axis_findings(
            jax.make_jaxpr(fn)(*args), "fixture:cross_world",
            args[0].shape[0])
        assert findings and all(f.rule == "SL701" for f in findings)
        assert not row["proved"]
    return check


def _fires_rng():
    def check():
        from shadow_tpu.analysis import batchdim

        mod = _load_fixture("fixture_rng_overlap.py")
        findings, row = batchdim.prove_fold_chain(mod.obligation())
        assert findings and findings[0].rule == "SL702"
        assert not row["ok"]
        # the prover names the demoting primitive, not just "unproved"
        assert "mul" in findings[0].message
    return check


def _fires_refusal():
    def check():
        from shadow_tpu.analysis import batchdim

        mod = _load_fixture("fixture_vmap_refusal.py")
        findings, _rows, _refs = batchdim.check_vmap_census(
            mod.entries(), refusals=mod.refusals())
        msgs = " | ".join(f.message for f in findings)
        assert all(f.rule == "SL703" for f in findings)
        assert "stale vmap refusal" in msgs
        assert "without a written rationale" in msgs
        assert "not world-count-stable" in msgs
    return check


#: rule id -> a check that its fixture actually TRIGGERS it. Keys must
#: exactly cover the registry: a new rule cannot land without a failing
#: fixture (test_every_rule_has_a_fixture).
RULE_TRIGGERS = {
    "SL101": _fires_ast("fixture_wallclock.py",
                        "shadow_tpu/core/f.py", "SL101"),
    "SL102": _fires_ast("fixture_randomness.py",
                        "shadow_tpu/net/f.py", "SL102"),
    "SL103": _fires_ast("fixture_unordered.py",
                        "shadow_tpu/core/f.py", "SL103"),
    "SL104": _fires_ast("fixture_mutable_default.py",
                        "shadow_tpu/utils/f.py", "SL104"),
    "SL105": _fires_ast("fixture_traced_branch.py",
                        "shadow_tpu/tpu/f.py", "SL105"),
    "SL201": _fires_jaxpr("fixture_x64_leak.py", "SL201"),
    "SL202": _fires_jaxpr("fixture_convert_churn.py", "SL202"),
    "SL203": _fires_jaxpr("fixture_host_callback.py", "SL203"),
    "SL204": _fires_jaxpr("fixture_loop_transfer.py", "SL204"),
    "SL205": _fires_jaxpr("fixture_baked_constant.py", "SL205"),
    "SL301": _fires_ast("fixture_kernel_sync.py",
                        "shadow_tpu/tpu/f.py", "SL301"),
    "SL401": _fires_ast("fixture_swallowed.py",
                        "shadow_tpu/process/f.py", "SL401"),
    "SL402": _fires_ast("fixture_kernel_assert.py",
                        "shadow_tpu/tpu/f.py", "SL402"),
    "SL403": _fires_ast("fixture_variadic_sort.py",
                        "shadow_tpu/tpu/f.py", "SL403"),
    "SL405": _fires_ast("fixture_telemetry_read.py",
                        "shadow_tpu/core/f.py", "SL405"),
    "SL501": _fires_taint(),
    "SL502": _fires_budget(),
    "SL503": _fires_ast("fixture_donation.py",
                        "shadow_tpu/tpu/f.py", "SL503"),
    "SL504": _fires_shard(),
    "SL505": _fires_condeq(),
    "SL506": _fires_range(),
    "SL601": _fires_cost("SL601", flops=10**9),
    "SL602": _fires_cost("SL602", big_boundaries=0),
    "SL603": _fires_host_sync(),
    "SL701": _fires_world(),
    "SL702": _fires_rng(),
    "SL703": _fires_refusal(),
}


def test_every_rule_has_a_fixture():
    """Registry consistency (a): every rule in analysis/rules.py names
    a fixture under tests/lint_fixtures/ that exists, and the trigger
    map covers the registry exactly — a new rule without a failing
    fixture (or a fixture without its rule) breaks this test."""
    assert set(RULE_TRIGGERS) == set(RULES), (
        set(RULE_TRIGGERS) ^ set(RULES))
    for rid, info in sorted(RULES.items()):
        assert info.fixture, f"{rid} names no fixture"
        assert os.path.exists(os.path.join(FIXTURES, info.fixture)), \
            f"{rid} fixture missing: {info.fixture}"
        assert info.scope, f"{rid} has no scope line for --list-rules"


@pytest.mark.parametrize("rid", sorted(RULES))
def test_rule_fixture_triggers(rid):
    """Registry consistency (a, continued): the named fixture actually
    FIRES its rule through the real checker."""
    RULE_TRIGGERS[rid]()


def test_tree_clean_or_justified_per_rule():
    """Registry consistency (b): for every rule, the real tree reports
    zero active findings and every suppression carries a justification
    — the fix-or-suppress inventory. This sweep covers pass 1 (AST,
    cheap); the traced passes have their own dedicated gates over the
    same registries (`test_repo_jaxpr_audit_clean`,
    `test_dataflow.py::test_invisibility_theorem_holds` per spec,
    `test_dataflow.py::test_checked_in_budgets_match`), kept separate
    so the expensive traces run once, not per-sweep."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import shadowlint

    findings, malformed = shadowlint.run_ast_pass(
        [os.path.join(shadowlint._REPO, p)
         for p in shadowlint.DEFAULT_PATHS])
    assert malformed == []
    by_rule: dict[str, list] = {}
    for f in findings:
        if not f.suppressed:
            by_rule.setdefault(f.rule, []).append(str(f))
        else:
            assert f.justification, str(f)
    assert not by_rule, "\n".join(
        f"{rid}:\n  " + "\n  ".join(v) for rid, v in by_rule.items())


# -- pass 2 rules (synthetic kernels) -------------------------------------

def test_sl201_x64_leak():
    from jax.experimental import enable_x64

    with enable_x64():
        closed = jax.make_jaxpr(lambda x: x * np.float64(2.0))(
            np.float64(1.0))
    findings = audit_jaxpr(closed, "synthetic:x64")
    assert any(f.rule == "SL201" for f in findings)


def test_sl201_clean_on_x32():
    closed = jax.make_jaxpr(lambda x: x * 2)(np.float32(1.0))
    assert not audit_jaxpr(closed, "synthetic:x32")


def test_sl202_convert_churn():
    def churn(x):
        return x.astype(jnp.float32).astype(jnp.int32)

    closed = jax.make_jaxpr(churn)(np.zeros((4,), np.int32))
    findings = audit_jaxpr(closed, "synthetic:churn")
    assert any(f.rule == "SL202" for f in findings)

    def single(x):  # one purposeful convert is not churn
        return x.astype(jnp.float32)

    closed = jax.make_jaxpr(single)(np.zeros((4,), np.int32))
    assert not audit_jaxpr(closed, "synthetic:single")


def test_sl203_host_callback():
    def cb(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct((), np.int32), x)

    closed = jax.make_jaxpr(cb)(np.int32(1))
    findings = audit_jaxpr(closed, "synthetic:callback")
    assert any(f.rule == "SL203" for f in findings)


def test_sl204_callback_in_loop_body():
    def loop(x):
        def body(c, _):
            jax.debug.print("tick {}", c)
            return c + 1, c

        return jax.lax.scan(body, x, None, length=3)

    closed = jax.make_jaxpr(loop)(np.int32(0))
    findings = audit_jaxpr(closed, "synthetic:loop")
    assert any(f.rule == "SL204" for f in findings)


def test_sl205_baked_constant():
    big = np.ones((300, 300), np.float32)  # 360 KB > 256 KiB limit

    closed = jax.make_jaxpr(lambda x: x + big)(np.float32(1.0))
    findings = audit_jaxpr(closed, "synthetic:const")
    assert any(f.rule == "SL205" for f in findings)

    small = np.ones((8, 8), np.float32)
    closed = jax.make_jaxpr(lambda x: x + small)(np.float32(1.0))
    assert not audit_jaxpr(closed, "synthetic:small-const")


# -- conftest global-RNG guard --------------------------------------------

@pytest.mark.allow_global_rng  # the inner pytester tests mutate in-process
def test_conftest_rng_guard_fires(pytester):
    """The real conftest guard fails tests that touch the hidden global
    RNG streams and honors the allow_global_rng opt-out."""
    with open(os.path.join(os.path.dirname(__file__), "conftest.py"),
              encoding="utf-8") as fh:
        pytester.makeconftest(fh.read())
    pytester.makepyfile("""
        import random

        import numpy as np
        import pytest

        def test_mutates_py_random():
            random.random()

        def test_mutates_np_random():
            np.random.rand(2)

        @pytest.mark.allow_global_rng
        def test_opt_out():
            random.seed(1)

        def test_clean():
            rng = np.random.default_rng(3)
            assert 0 <= rng.random() < 1
    """)
    result = pytester.runpytest("-p", "no:cacheprovider")
    # the guard trips in teardown, so offenders surface as errors
    result.assert_outcomes(passed=4, errors=2)
    result.stdout.fnmatch_lines(["*core/rng.py*"])


# -- acceptance gates -----------------------------------------------------

def test_repo_ast_pass_clean():
    """Pass 1 over the real tree: no unsuppressed findings, every
    suppression carries a justification."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import shadowlint

    findings, malformed = shadowlint.run_ast_pass(
        [os.path.join(shadowlint._REPO, p)
         for p in shadowlint.DEFAULT_PATHS])
    active = [f for f in findings if not f.suppressed]
    assert active == [], "\n".join(str(f) for f in active)
    assert malformed == []
    assert all(f.justification for f in findings if f.suppressed)


def test_repo_jaxpr_audit_clean():
    """Pass 2 over all five tpu/ kernel modules: no active findings."""
    findings = audit_all()
    active = [f for f in findings if not f.suppressed]
    assert active == [], "\n".join(str(f) for f in active)


def test_recompile_sweep_zero_misses():
    """The bench-ladder shape sweep: one compile per static shape, zero
    cache misses on varying window scalars and on the repeat sweep."""
    report = sweep_window_step(rounds=3, repeats=2)
    assert report["unexpected_misses"] == 0, report
    assert report["total_compiles"] == len(report["shapes"])
    assert all(s["compiles"] == 1 for s in report["shapes"]), report
