"""Async buffered logging (`shadow_logger.rs:17-60` analogue): records
flush from a background thread, nothing is lost at close, and the
deterministic content contract (sim-time/host tags, no wall clock) is
identical to synchronous emission."""

import io
import logging

from shadow_tpu.core import shadowlog


def _emit_many(n):
    log = logging.getLogger("shadow_tpu.test")
    for i in range(n):
        log.info("record %d", i)


def _capture(buffered, n=500):
    stream = io.StringIO()
    root = logging.getLogger("shadow_tpu")
    old_handlers = root.handlers[:]
    root.handlers = []
    handler = shadowlog.init_logging(logging.INFO, deterministic=True,
                                     stream=stream, buffered=buffered)
    try:
        _emit_many(n)
    finally:
        handler.close()
        root.handlers = old_handlers
    return stream.getvalue()


def test_async_drains_everything_and_matches_sync():
    sync = _capture(buffered=False)
    async_ = _capture(buffered=True)
    assert sync == async_
    assert len(sync.splitlines()) == 500
    # deterministic format: sim-time tag, no wall-clock timestamp
    first = sync.splitlines()[0]
    assert first.startswith("00:00:00.000000000 [INFO] [-]")


def test_async_flush_midstream():
    stream = io.StringIO()
    root = logging.getLogger("shadow_tpu")
    old_handlers = root.handlers[:]
    root.handlers = []
    handler = shadowlog.init_logging(logging.INFO, deterministic=True,
                                     stream=stream, buffered=True)
    try:
        _emit_many(100)
        handler.flush()
        assert len(stream.getvalue().splitlines()) == 100
        _emit_many(50)
    finally:
        handler.close()
        root.handlers = old_handlers
    assert len(stream.getvalue().splitlines()) == 150
