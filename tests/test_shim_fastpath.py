"""In-shim time fast path: clock reads answered inside the managed process
from the shared clock block, zero IPC round trips, with the modeled
per-syscall latency advancing virtual time up to the runahead bound.

Parity: reference `src/lib/shim/shim_sys.c:25-80,200-226` (hot-path time
syscalls + unblocked-syscall latency accumulation + shadow_yield at the
runahead barrier).
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")

SPINNER_C = r"""
#include <stdio.h>
#include <sys/time.h>
#include <time.h>

int main(void) {
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    /* spin: 200k clock reads with zero real sleeping */
    struct timeval tv;
    for (int i = 0; i < 100000; i++) gettimeofday(&tv, 0);
    for (int i = 0; i < 100000; i++) clock_gettime(CLOCK_MONOTONIC, &t1);
    long long advanced = (t1.tv_sec - t0.tv_sec) * 1000000000LL
                         + (t1.tv_nsec - t0.tv_nsec);
    /* 200k reads at 1us modeled latency each ~= 200ms of virtual time;
     * require at least 100ms to prove latency accumulation happened */
    if (advanced < 100000000LL) { printf("only %lld ns\n", advanced); return 1; }
    /* REALTIME must sit at the emulated epoch (year 2000), not real time */
    struct timespec rt;
    clock_gettime(CLOCK_REALTIME, &rt);
    if (rt.tv_sec < 946684800 || rt.tv_sec > 946684800 + 86400) return 2;
    printf("advanced %lld\n", advanced);
    return 0;
}
"""


def test_time_spinner_uses_fast_path(tmp_path):
    src = tmp_path / "spinner.c"
    src.write_text(SPINNER_C)
    binary = tmp_path / "spinner"
    subprocess.run([CC, "-O1", "-o", str(binary), str(src)], check=True)

    cfg = load_config_str(f"""
general: {{stop_time: 30s, seed: 21, model_unblocked_syscall_latency: true}}
network: {{graph: {{type: 1_gbit_switch}}}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s, expected_final_state: {{exited: 0}}}}
""")
    mgr = Manager(cfg)
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    (proc,) = mgr.hosts_by_name["box"].processes

    # the 200k clock reads must have been answered in-shim: the simulator
    # side may see only the pre-publish stragglers and runahead-barrier
    # yields, not the spin volume
    from shadow_tpu.process.syscall_handler import (
        SYS_clock_gettime, SYS_gettimeofday, SYS_time,
    )
    ipc_time_calls = sum(
        proc.handler.syscall_counts.get(nr, 0)
        + proc.server.syscall_counts.get(nr, 0)
        for nr in (SYS_clock_gettime, SYS_gettimeofday, SYS_time)
    )
    assert ipc_time_calls < 2000, (
        f"{ipc_time_calls} time syscalls crossed the IPC boundary — the "
        "in-shim fast path is not engaging"
    )
