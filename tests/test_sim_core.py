"""End-to-end core-loop tests: a PHOLD-style workload over the full path
(socket -> NIC -> relay -> router -> worker.send_packet -> dst router ->
CoDel -> relay -> NIC -> socket), with determinism checks across runs and
across schedulers (parity with the reference's determinism CI,
`src/test/determinism/CMakeLists.txt`, and PHOLD configs,
`src/test/phold/`)."""

from shadow_tpu.core import simtime
from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.event import TaskRef
from shadow_tpu.core.manager import Manager
from shadow_tpu.net.packet import Packet, Protocol

MS = simtime.MILLISECOND

PHOLD_CONFIG = """
general:
  stop_time: 2s
  seed: 42
network:
  graph:
    type: 1_gbit_switch
hosts:
{hosts}
"""


def _phold_config(n_hosts, scheduler="serial", parallelism=1, seed=42):
    hosts = "\n".join(
        f"  peer{i}:\n    network_node_id: 0" for i in range(n_hosts)
    )
    text = PHOLD_CONFIG.format(hosts=hosts).replace("seed: 42", f"seed: {seed}")
    return load_config_str(
        text,
        overrides={
            "general": {"parallelism": parallelism},
            "experimental": {"scheduler": scheduler},
        },
    )


class PholdApp:
    """Each host bounces messages to random peers after random delays."""

    PORT = 9000

    def __init__(self, host, peer_ips):
        self.host = host
        self.peer_ips = peer_ips
        self.outq = []
        self.trace = []  # (recv_time, src_ip) — the determinism witness
        host.netns.associate(self, Protocol.UDP, "0.0.0.0", self.PORT)

    # InterfaceSocket protocol
    def pull_out_packet(self):
        return self.outq.pop(0) if self.outq else None

    def peek_next_priority(self):
        return self.outq[0].priority if self.outq else None

    def push_in_packet(self, packet):
        self.trace.append((self.host.now(), packet.src[0]))
        delay = self.host.rng.randrange(1, 10) * MS
        self.host.schedule_task_with_delay(
            TaskRef(lambda h: self.send_one(), "phold-send"), delay
        )

    def send_one(self):
        dst = self.peer_ips[self.host.rng.randrange(0, len(self.peer_ips))]
        pkt = Packet(
            Protocol.UDP,
            (self.host.ip, self.PORT),
            (dst, self.PORT),
            b"phold-payload",
            priority=self.host.get_next_packet_priority(),
        )
        self.outq.append(pkt)
        self.host.notify_socket_has_packets(self.host.ip, self)

    def start(self, host):
        self.send_one()


def _run_phold(n_hosts=8, scheduler="serial", parallelism=1, seed=42):
    cfg = _phold_config(n_hosts, scheduler, parallelism, seed)
    mgr = Manager(cfg)
    peer_ips = [h.ip for h in mgr.hosts]
    apps = {}
    for host in mgr.hosts:
        app = PholdApp(host, peer_ips)
        apps[host.name] = app
        host.add_application(1 * MS, app.start)
    stats = mgr.run()
    return {name: app.trace for name, app in apps.items()}, stats


def test_phold_runs_and_delivers():
    traces, stats = _run_phold()
    total = sum(len(t) for t in traces.values())
    assert total > 100, f"expected sustained message flow, got {total}"
    assert stats.rounds > 10
    assert stats.packets_sent > 0
    # latencies are 1ms and delays are 1-9ms: receive times sane
    for trace in traces.values():
        for t, _src in trace:
            assert 0 < t <= 2 * simtime.SECOND
        assert [t for t, _ in trace] == sorted(t for t, _ in trace)


def test_phold_deterministic_across_runs():
    t1, _ = _run_phold()
    t2, _ = _run_phold()
    assert t1 == t2


def test_phold_deterministic_across_schedulers_and_parallelism():
    serial, _ = _run_phold(scheduler="serial", parallelism=1)
    threaded2, _ = _run_phold(scheduler="thread-per-core", parallelism=2)
    threaded4, _ = _run_phold(scheduler="thread-per-core", parallelism=4)
    assert serial == threaded2
    assert serial == threaded4


def test_phold_seed_changes_behavior():
    t1, _ = _run_phold(seed=42)
    t2, _ = _run_phold(seed=43)
    assert t1 != t2


def test_stats_and_runahead():
    cfg = _phold_config(4)
    mgr = Manager(cfg)
    # builtin switch graph: min latency 1ms drives the static runahead
    assert mgr.runahead.get() == 1 * MS
    peer_ips = [h.ip for h in mgr.hosts]
    for host in mgr.hosts:
        app = PholdApp(host, peer_ips)
        host.add_application(1 * MS, app.start)
    stats = mgr.run()
    assert stats.wall_seconds > 0
    assert stats.sim_time_ns == 2 * simtime.SECOND
    d = stats.as_dict()
    assert d["rounds"] == stats.rounds
