from shadow_tpu.core import simtime, units


def test_constants():
    assert simtime.SECOND == 1_000_000_000
    assert simtime.MILLISECOND * 1000 == simtime.SECOND
    assert simtime.MINUTE == 60 * simtime.SECOND


def test_emulated_epoch_is_y2k():
    # 2000-01-01 UTC = 946684800 unix seconds
    assert simtime.EMUTIME_SIMULATION_START_UNIX_NS == 946684800 * simtime.SECOND
    assert simtime.emulated_from_sim(5 * simtime.SECOND) == (946684800 + 5) * simtime.SECOND
    assert simtime.sim_from_emulated(simtime.emulated_from_sim(123)) == 123


def test_fmt():
    assert simtime.fmt(3 * simtime.SECOND + 42) == "00:00:03.000000042"


def test_parse_durations():
    assert units.parse_duration_ns("10 ms") == 10 * simtime.MILLISECOND
    assert units.parse_duration_ns("2s") == 2 * simtime.SECOND
    assert units.parse_duration_ns("1 minute") == simtime.MINUTE
    assert units.parse_duration_ns("500 us") == 500 * simtime.MICROSECOND
    assert units.parse_duration_ns(30) == 30 * simtime.SECOND  # bare = seconds
    assert units.parse_duration_ns("1.5 ms") == 1_500_000
    assert units.parse_duration_ns("10 m") == 10 * simtime.MINUTE


def test_parse_bytes():
    assert units.parse_bytes("16 MiB") == 16 * 2**20
    assert units.parse_bytes("1 KB") == 1000
    assert units.parse_bytes("10") == 10
    assert units.parse_bytes("2 kib") == 2048
    assert units.parse_bytes("16 kibibytes") == 16 * 1024


def test_parse_rates():
    assert units.parse_bits_per_sec("1 Gbit") == 10**9
    assert units.parse_bits_per_sec("100 Mbit") == 10**8
    assert units.parse_bits_per_sec("10 Mbps") == 10**7
    assert units.parse_bits_per_sec("1 megabit") == 10**6


def test_parse_errors():
    import pytest

    with pytest.raises(units.UnitParseError):
        units.parse_duration_ns("10 parsecs")
    with pytest.raises(units.UnitParseError):
        units.parse_bytes("x")
