"""strace logging (deterministic mode): per-process .strace files whose
bytes are identical across runs — the reference's determinism CI diffs
exactly this artifact (`syscall-logger/src/lib.rs`, determinism CMake
harness). VERDICT round-2 item #9.
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")

APP_C = r"""
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

int main(void) {
    int sv[2];
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv)) return 1;
    pid_t pid = fork();
    if (pid == 0) {
        if (write(sv[1], "abc", 3) != 3) _exit(9);
        _exit(0);
    }
    char buf[8];
    if (read(sv[0], buf, sizeof buf) != 3) return 3;
    int st;
    waitpid(pid, &st, 0);
    usleep(2000);
    close(sv[0]);
    close(sv[1]);
    return 0;
}
"""


def _compile(tmp_path):
    c = tmp_path / "app.c"
    c.write_text(APP_C)
    binary = tmp_path / "app"
    subprocess.run([CC, "-O1", "-o", str(binary), str(c)], check=True)
    return str(binary)


def _run(tmp_path, binary, data_name, mode):
    data = tmp_path / data_name
    cfg = load_config_str(f"""
general: {{stop_time: 5s, seed: 4, data_directory: {data}}}
experimental: {{strace_logging_mode: {mode}}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s, expected_final_state: {{exited: 0}}}}
""")
    mgr = Manager(cfg)
    mgr.data_dir = str(data)
    stats = mgr.run()
    assert stats.process_failures == []
    strace = data / "hosts" / "box" / "box.app.0.strace"
    assert strace.exists(), "no .strace written"
    child = data / "hosts" / "box" / "box.app.0.fork0.strace"
    assert child.exists(), "forked child has no .strace"
    return strace.read_bytes() + b"--fork--\n" + child.read_bytes()


def test_deterministic_strace_is_byte_identical(tmp_path):
    binary = _compile(tmp_path)
    a = _run(tmp_path, binary, "d1", "deterministic")
    b = _run(tmp_path, binary, "d2", "deterministic")
    assert a == b, "deterministic strace differs across identical runs"
    text = a.decode()
    # the emulated syscalls show up with simulated timestamps + stable
    # thread ordinals; pointer args are masked; fork + the child's own
    # trace (after --fork--) are present
    for needle in ("socketpair(", "clone(", "write(", "read(", "close(",
                   "wait4(", "exit_group(", "[t0]", "<ptr>", "--fork--"):
        assert needle in text, f"{needle!r} missing from:\n{text[:800]}"
    assert text.splitlines()[0].startswith("00:00:01.")


def test_strace_identical_across_scheduler_matrix(tmp_path):
    """The full syscall trace must be byte-identical across schedulers and
    parallelism (the reference determinism CI's strongest check: event
    ORDER, not just end-state counters, is schedule-independent)."""
    import os
    import sys

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    binary = _compile(tmp_path)
    cfg = tmp_path / "strace-matrix.yaml"
    cfg.write_text(f"""
general: {{stop_time: 5s, seed: 4}}
experimental: {{strace_logging_mode: deterministic}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  box1:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s}}
  box2:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 2s}}
""")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "compare_runs.py"),
         str(cfg), "--matrix"],
        env=env, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "DETERMINISTIC" in proc.stdout


def test_off_mode_writes_nothing(tmp_path):
    binary = _compile(tmp_path)
    data = tmp_path / "off"
    cfg = load_config_str(f"""
general: {{stop_time: 5s, seed: 4, data_directory: {data}}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, start_time: 1s}}
""")
    mgr = Manager(cfg)
    mgr.data_dir = str(data)
    mgr.run()
    assert not list((data / "hosts" / "box").glob("*.strace"))
