"""Newer syscall-table entries: rt_sigprocmask-aware signal routing,
recvmmsg/sendmmsg, statx on virtual descriptors.

Parity: reference `handler/signal.rs` (mask tracking), `handler/mod.rs`
recvmmsg/sendmmsg rows, `handler/file.rs` statx.
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")


def _compile(tmp_path, name, src, extra=()):
    c = tmp_path / f"{name}.c"
    c.write_text(src)
    binary = tmp_path / name
    subprocess.run([CC, "-O1", *extra, "-o", str(binary), str(c)],
                   check=True)
    return str(binary)


def _run(binary, stop="30s"):
    cfg = load_config_str(f"""
general: {{stop_time: {stop}, seed: 3}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  alpha:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: [], start_time: 1s,
       expected_final_state: {{exited: 0}}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures


MASKED_MAIN_C = r"""
#include <errno.h>
#include <pthread.h>
#include <signal.h>
#include <time.h>
#include <unistd.h>

static volatile sig_atomic_t fired;
static volatile int worker_eintr;
static void on_alarm(int sig) { (void)sig; fired = 1; }

static void *worker(void *arg) {
    (void)arg;
    /* the mask is inherited from main at create: unblock SIGALRM here
     * so this thread is the only eligible recipient */
    sigset_t set;
    sigemptyset(&set);
    sigaddset(&set, SIGALRM);
    if (pthread_sigmask(SIG_UNBLOCK, &set, 0)) return (void *)1;
    struct timespec ts = {30, 0};
    if (nanosleep(&ts, 0) == -1 && errno == EINTR) worker_eintr = 1;
    return 0;
}

int main(void) {
    struct sigaction sa = {0};
    sa.sa_handler = on_alarm;
    if (sigaction(SIGALRM, &sa, 0)) return 120;
    /* main blocks SIGALRM: delivery must skip main's parked syscall */
    sigset_t set;
    sigemptyset(&set);
    sigaddset(&set, SIGALRM);
    if (pthread_sigmask(SIG_BLOCK, &set, 0)) return 121;
    pthread_t t;
    if (pthread_create(&t, 0, worker, 0)) return 122;
    alarm(1);
    if (pthread_join(t, 0)) return 123;  /* unblocked by worker's EINTR */
    if (!worker_eintr) return 124;
    return 0;
}
"""


def test_blocked_main_routes_signal_to_worker(tmp_path):
    """rt_sigprocmask is observed: a thread with the signal blocked is
    never chosen as the EINTR recipient; the unblocked worker is."""
    _run(_compile(tmp_path, "maskroute", MASKED_MAIN_C, ("-pthread",)))


MMSG_C = r"""
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(void) {
    int rx = socket(AF_INET, SOCK_DGRAM, 0);
    int tx = socket(AF_INET, SOCK_DGRAM, 0);
    if (rx < 0 || tx < 0) return 130;
    struct sockaddr_in a;
    memset(&a, 0, sizeof a);
    a.sin_family = AF_INET;
    a.sin_port = htons(7100);
    a.sin_addr.s_addr = inet_addr("127.0.0.1");
    if (bind(rx, (struct sockaddr *)&a, sizeof a)) return 131;

    /* sendmmsg: 3 datagrams in one call */
    char p0[] = "alpha", p1[] = "beta", p2[] = "gamma";
    struct iovec iov[3] = {{p0, 5}, {p1, 4}, {p2, 5}};
    struct mmsghdr out[3];
    memset(out, 0, sizeof out);
    for (int i = 0; i < 3; i++) {
        out[i].msg_hdr.msg_name = &a;
        out[i].msg_hdr.msg_namelen = sizeof a;
        out[i].msg_hdr.msg_iov = &iov[i];
        out[i].msg_hdr.msg_iovlen = 1;
    }
    if (sendmmsg(tx, out, 3, 0) != 3) return 132;
    for (int i = 0; i < 3; i++)
        if (out[i].msg_len != iov[i].iov_len) return 133;

    /* recvmmsg: take all 3 in one call */
    char b0[16], b1[16], b2[16];
    struct iovec riov[3] = {{b0, 16}, {b1, 16}, {b2, 16}};
    struct mmsghdr in[3];
    memset(in, 0, sizeof in);
    for (int i = 0; i < 3; i++) {
        in[i].msg_hdr.msg_iov = &riov[i];
        in[i].msg_hdr.msg_iovlen = 1;
    }
    int got = recvmmsg(rx, in, 3, 0, 0);
    if (got != 3) return 134;
    if (in[0].msg_len != 5 || memcmp(b0, "alpha", 5)) return 135;
    if (in[1].msg_len != 4 || memcmp(b1, "beta", 4)) return 136;
    if (in[2].msg_len != 5 || memcmp(b2, "gamma", 5)) return 137;
    close(rx);
    close(tx);
    return 0;
}
"""


def test_sendmmsg_recvmmsg_roundtrip(tmp_path):
    _run(_compile(tmp_path, "mmsg", MMSG_C))


STATX_C = r"""
#define _GNU_SOURCE
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/socket.h>
#include <unistd.h>

int main(void) {
    int s = socket(AF_INET, SOCK_STREAM, 0);
    if (s < 0) return 140;
    struct statx stx;
    if (statx(s, "", AT_EMPTY_PATH, STATX_BASIC_STATS, &stx)) return 141;
    if (!S_ISSOCK(stx.stx_mode)) return 142;
    int p[2];
    if (pipe(p)) return 143;
    if (statx(p[0], "", AT_EMPTY_PATH, STATX_BASIC_STATS, &stx)) return 144;
    if (!S_ISFIFO(stx.stx_mode)) return 145;
    close(s); close(p[0]); close(p[1]);
    return 0;
}
"""


def test_statx_on_virtual_descriptors(tmp_path):
    _run(_compile(tmp_path, "tstatx", STATX_C))


PENDING_C = r"""
#include <errno.h>
#include <signal.h>
#include <time.h>
#include <unistd.h>

static volatile sig_atomic_t fired;
static void on_alarm(int sig) { (void)sig; fired = 1; }

static long long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(void) {
    struct sigaction sa = {0};
    sa.sa_handler = on_alarm;
    if (sigaction(SIGALRM, &sa, 0)) return 150;
    sigset_t set;
    sigemptyset(&set);
    sigaddset(&set, SIGALRM);
    if (sigprocmask(SIG_BLOCK, &set, 0)) return 151;
    alarm(1);
    /* the alarm expires at +1s but must stay pending while blocked */
    struct timespec ts = {3, 0};
    while (nanosleep(&ts, &ts) == -1 && errno == EINTR) {}
    if (fired) return 152;  /* ran while blocked: mask violated */
    long long t0 = now_ns();
    if (sigprocmask(SIG_UNBLOCK, &set, 0)) return 153;
    /* pending signal delivers on unblock; allow a short virtual wait */
    while (!fired && now_ns() - t0 < 2000000000LL) {
        struct timespec tick = {0, 50000000};
        nanosleep(&tick, 0);
    }
    if (!fired) return 154;
    return 0;
}
"""


def test_blocked_signal_stays_pending_until_unblock(tmp_path):
    """A process-directed signal with every thread's (virtual) mask
    blocking it must not fire; it delivers when the mask opens."""
    _run(_compile(tmp_path, "tpending", PENDING_C))


SIGSUSPEND_C = r"""
#include <errno.h>
#include <signal.h>
#include <time.h>
#include <unistd.h>

static volatile sig_atomic_t fired;
static void on_alarm(int sig) { (void)sig; fired = 1; }

static long long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(void) {
    struct sigaction sa = {0};
    sa.sa_handler = on_alarm;
    if (sigaction(SIGALRM, &sa, 0)) return 160;
    sigset_t blockset, suspendset, cur;
    sigemptyset(&blockset);
    sigaddset(&blockset, SIGALRM);
    if (sigprocmask(SIG_BLOCK, &blockset, 0)) return 161;
    long long t0 = now_ns();
    alarm(2);
    sigemptyset(&suspendset);
    /* canonical pattern: atomically open the mask and wait */
    int rc = sigsuspend(&suspendset);
    if (!(rc == -1 && errno == EINTR)) return 162;
    if (!fired) return 163;
    if (now_ns() - t0 < 1900000000LL) return 164; /* woke too early */
    /* the pre-suspend mask (SIGALRM blocked) must be restored */
    if (sigprocmask(SIG_BLOCK, 0, &cur)) return 165;
    if (!sigismember(&cur, SIGALRM)) return 166;
    return 0;
}
"""


def test_sigsuspend_canonical_pattern(tmp_path):
    """block SIGALRM; alarm(); sigsuspend(empty) — must wake with EINTR
    at the simulated expiry and restore the old mask afterwards."""
    _run(_compile(tmp_path, "tsuspend", SIGSUSPEND_C))


SIGWAIT_C = r"""
#include <signal.h>
#include <time.h>
#include <unistd.h>

static volatile sig_atomic_t handler_ran;
static void on_alarm(int sig) { (void)sig; handler_ran = 1; }

int main(void) {
    /* a handler is installed, but sigwait must CONSUME the signal
     * without running it */
    struct sigaction sa = {0};
    sa.sa_handler = on_alarm;
    if (sigaction(SIGALRM, &sa, 0)) return 170;
    sigset_t set;
    sigemptyset(&set);
    sigaddset(&set, SIGALRM);
    if (sigprocmask(SIG_BLOCK, &set, 0)) return 171;
    alarm(1);
    int got = 0;
    if (sigwait(&set, &got)) return 172;
    if (got != SIGALRM) return 173;
    if (handler_ran) return 174;
    return 0;
}
"""


def test_sigwait_consumes_without_handler(tmp_path):
    _run(_compile(tmp_path, "tsigwait", SIGWAIT_C))
