"""Pure TCP state-machine tests with fake dependencies — no simulator.

Parity model: reference `src/lib/tcp/src/tests/` (state machine driven by a
fake clock + timers) plus congestion/retransmission scenarios the legacy
stack covers (`src/test/tcp/` loss configs).
"""

import heapq

import pytest

from shadow_tpu.tcp import (
    RenoCongestion,
    TcpConfig,
    TcpConnection,
    TcpFlags,
    TcpState,
)
from shadow_tpu.tcp import seq as seqmod

MS = 1_000_000


class FakeDeps:
    def __init__(self, world, seed):
        self.world = world
        self._rng = seed

    def now(self):
        return self.world.time

    def set_timer(self, delay_ns, callback):
        heapq.heappush(
            self.world.timers, (self.world.time + delay_ns, next(self.world.counter), callback)
        )

    def random_u32(self):
        self._rng = (self._rng * 6364136223846793005 + 1442695040888963407) % (1 << 64)
        return self._rng >> 32

    def notify(self):
        pass


class World:
    """Two connections joined by a latency wire with programmable loss."""

    def __init__(self, latency_ns=1 * MS, seed=1234):
        import itertools

        self.time = 0
        self.timers = []
        self.counter = itertools.count()
        self.latency = latency_ns
        self.in_flight = []  # heap: (deliver_time, n, dst_conn, segment)
        self.drop_next = 0  # drop the next N data segments a->b
        self.dropped = []
        self.a = TcpConnection(FakeDeps(self, seed))
        self.b = TcpConnection(FakeDeps(self, seed + 1))
        self.sent_log = []  # (time, who, flags, seq, ack, len)

    def _pump_one(self, who, conn, peer):
        seg = conn.next_segment()
        if seg is None:
            return False
        self.sent_log.append(
            (self.time, who, seg.flags, seg.seq, seg.ack, len(seg.payload))
        )
        if who == "a" and seg.payload and self.drop_next > 0:
            self.drop_next -= 1
            self.dropped.append((self.time, seg.seq, len(seg.payload)))
            return True
        heapq.heappush(
            self.in_flight,
            (self.time + self.latency, next(self.counter), peer, seg),
        )
        return True

    def run(self, until_ns, max_iters=100_000):
        """Event loop: pump outgoing, deliver, fire timers, advance time."""
        for _ in range(max_iters):
            progressed = False
            while self._pump_one("a", self.a, self.b):
                progressed = True
            while self._pump_one("b", self.b, self.a):
                progressed = True
            if progressed:
                continue
            # nothing to send: advance to the next delivery or timer
            next_times = []
            if self.in_flight:
                next_times.append(self.in_flight[0][0])
            if self.timers:
                next_times.append(self.timers[0][0])
            if not next_times or min(next_times) > until_ns:
                self.time = until_ns
                return
            self.time = min(next_times)
            while self.in_flight and self.in_flight[0][0] <= self.time:
                _, _, dst, seg = heapq.heappop(self.in_flight)
                dst.on_segment(seg)
            while self.timers and self.timers[0][0] <= self.time:
                _, _, cb = heapq.heappop(self.timers)
                cb()
        raise AssertionError("run() did not converge")


def connect(world):
    world.b_listenerize = None
    world.a.open_active()
    # capture a's SYN manually: world pump handles it; b must be in passive mode
    # drive the handshake: b consumes SYN via open_passive
    syn = world.a.next_segment()
    assert syn.flags == TcpFlags.SYN
    world.sent_log.append((world.time, "a", syn.flags, syn.seq, syn.ack, 0))
    world.time += world.latency
    world.b.open_passive(syn)
    world.run(world.time + 10 * MS)
    assert world.a.state == TcpState.ESTABLISHED
    assert world.b.state == TcpState.ESTABLISHED


def test_three_way_handshake():
    w = World()
    connect(w)
    # SYN|ACK and final ACK crossed the wire
    flags = [f for _, _, f, _, _, _ in w.sent_log]
    assert TcpFlags.SYN | TcpFlags.ACK in flags
    assert w.a.syn_acked and w.b.syn_acked


def test_small_transfer_both_directions():
    w = World()
    connect(w)
    w.a.write(b"hello from a")
    w.b.write(b"hello from b")
    w.run(w.time + 50 * MS)
    assert w.b.read(100) == b"hello from a"
    assert w.a.read(100) == b"hello from b"


def test_bulk_transfer():
    w = World()
    connect(w)
    payload = bytes(range(256)) * 1000  # 256 KB > send buffer
    sent = 0
    received = bytearray()

    for _ in range(400):
        if sent < len(payload):
            sent += w.a.write(payload[sent : sent + 32768])
        w.run(w.time + 20 * MS)
        received.extend(w.b.read(1 << 20))
        if sent == len(payload) and len(received) == len(payload):
            break
    assert bytes(received) == payload
    # MSS-sized segments dominated
    data_segs = [s for s in w.sent_log if s[5] > 0]
    assert max(s[5] for s in data_segs) == 1460


def test_loss_recovery_by_retransmit():
    w = World()
    connect(w)
    w.drop_next = 1  # first data segment a->b vanishes
    w.a.write(b"x" * 5000)  # several segments; dupacks will trigger fast rtx
    w.run(w.time + 3000 * MS)
    got = w.b.read(1 << 20)
    assert got == b"x" * 5000
    assert w.a.retransmit_count >= 1


def test_sack_reduces_retransmitted_bytes():
    """RFC 2018 SACK vs go-back-N on the same lossy transfer: the SACK
    sender must complete with MEASURABLY fewer retransmitted bytes (the
    scoreboard skips peer-held ranges on the post-RTO resend) — the
    criterion from tcp_retransmit_tally.cc parity."""
    from shadow_tpu.tcp import TcpConfig

    def run_transfer(sack: bool) -> tuple[int, int]:
        w = World(latency_ns=5 * MS, seed=99)
        w.a = TcpConnection(FakeDeps(w, 99), TcpConfig(sack=sack))
        w.b = TcpConnection(FakeDeps(w, 100), TcpConfig(sack=sack))
        connect(w)
        payload = b"z" * 120_000
        sent = 0
        received = bytearray()
        dropped_once = False
        for _ in range(600):
            if sent < len(payload):
                sent += w.a.write(payload[sent:sent + 16384])
            if sent > 80_000 and not dropped_once:
                dropped_once = True
                # lose a burst AND its fast retransmission: recovery must
                # go through the RTO, where go-back-N resends the whole
                # in-flight tail and SACK resends only the holes
                w.drop_next = 5
            w.run(w.time + 20 * MS)
            received.extend(w.b.read(1 << 20))
            if sent == len(payload) and len(received) == len(payload):
                break
        assert bytes(received) == payload
        return w.a.retransmitted_bytes, w.a.retransmit_count

    sack_bytes, sack_count = run_transfer(True)
    gbn_bytes, gbn_count = run_transfer(False)
    assert sack_bytes < gbn_bytes, (sack_bytes, gbn_bytes)
    # the go-back-N resend re-sends the whole in-flight tail; SACK only
    # the actual holes — expect a large margin, not a rounding error
    assert sack_bytes <= gbn_bytes // 2, (sack_bytes, gbn_bytes)


def test_sack_negotiation_off_means_no_blocks():
    from shadow_tpu.tcp import TcpConfig

    w = World()
    w.a = TcpConnection(FakeDeps(w, 1), TcpConfig(sack=False))
    connect(w)
    w.a.write(b"q" * 8000)
    w.drop_next = 1
    w.run(w.time + 2000 * MS)
    assert w.b.read(1 << 20) == b"q" * 8000
    assert not w.a._sack_ok and not w.b._sack_ok


def test_fast_retransmit_uses_dupacks_not_timeout():
    w = World()
    connect(w)
    w.a.write(b"y" * (1460 * 8))  # 8 segments
    # drop the first, deliver the rest -> 3+ dupacks -> fast retransmit
    w.drop_next = 1
    t0 = w.time
    w.run(w.time + 2000 * MS)
    assert w.b.read(1 << 20) == b"y" * (1460 * 8)
    assert w.a.retransmit_count >= 1
    # recovery must beat the 1s initial RTO by a wide margin (dupack path)
    first_complete = t0 + 900 * MS
    assert w.time >= t0  # sanity
    # find when the retransmitted bytes arrived: b had everything before RTO
    assert w.a.cong.ssthresh < (1 << 30), "ssthresh halved by fast retransmit"


def test_orderly_close_fin_sequence():
    w = World()
    connect(w)
    w.a.write(b"last words")
    w.run(w.time + 20 * MS)
    w.a.close()
    w.run(w.time + 20 * MS)
    assert w.b.read(100) == b"last words"
    assert w.b.at_eof()
    assert w.b.state == TcpState.CLOSE_WAIT
    assert w.a.state == TcpState.FIN_WAIT_2
    w.b.close()
    w.run(w.time + 20 * MS)
    assert w.b.state == TcpState.CLOSED
    assert w.a.state == TcpState.TIME_WAIT
    w.run(w.time + 61_000 * MS)  # TIME_WAIT expiry
    assert w.a.state == TcpState.CLOSED


def test_simultaneous_close():
    w = World()
    connect(w)
    w.a.close()
    w.b.close()
    w.run(w.time + 200 * MS)
    assert w.a.state in (TcpState.TIME_WAIT, TcpState.CLOSED)
    assert w.b.state in (TcpState.TIME_WAIT, TcpState.CLOSED)


def test_rst_aborts():
    w = World()
    connect(w)
    w.a.abort()
    w.run(w.time + 20 * MS)
    assert w.a.state == TcpState.CLOSED
    assert w.b.state == TcpState.CLOSED
    assert w.b.error == 104  # ECONNRESET


def test_window_scaling_negotiated():
    w = World()
    connect(w)
    assert w.a._wscale_ok and w.b._wscale_ok
    assert w.a.my_wscale >= 1  # 174760 needs at least shift 2
    assert w.b.peer_wscale == w.a.my_wscale


def test_no_window_scaling_when_disabled():
    w = World()
    w.a = TcpConnection(FakeDeps(w, 1), TcpConfig(window_scaling=False))
    connect(w)
    assert not w.b._wscale_ok
    assert w.b.my_wscale == 0


def test_receiver_window_backpressure_and_reopen():
    w = World()
    small = TcpConfig(recv_buffer=4096)
    w.b = TcpConnection(FakeDeps(w, 99), small)
    connect(w)
    w.a.write(b"z" * 20000)
    w.run(w.time + 500 * MS)
    # b's buffer capped what a could put in flight
    assert w.b.readable_bytes() <= 4096
    total = bytearray()
    for _ in range(50):
        total.extend(w.b.read(1024))
        w.run(w.time + 100 * MS)
        if len(total) == 20000:
            break
    assert bytes(total) == b"z" * 20000


def test_seq_wraparound():
    # force iss near the 2^32 boundary via a custom deps
    class WrapDeps(FakeDeps):
        def random_u32(self):
            return (1 << 32) - 3

    w = World()
    w.a = TcpConnection(WrapDeps(w, 1))
    w.b = TcpConnection(WrapDeps(w, 2))
    connect(w)
    w.a.write(b"wrap" * 1000)
    w.run(w.time + 100 * MS)
    assert w.b.read(1 << 20) == b"wrap" * 1000


def test_seq_helpers():
    assert seqmod.lt(0xFFFFFFF0, 5)
    assert seqmod.gt(5, 0xFFFFFFF0)
    assert seqmod.add(0xFFFFFFFF, 1) == 0
    assert seqmod.sub(2, 0xFFFFFFFF) == 3


def test_reno_phases():
    c = RenoCongestion()
    assert c.cwnd == 10
    c.on_new_ack(5)
    assert c.cwnd == 15  # slow start
    c.ssthresh = 20
    c.on_new_ack(10)  # 15+10=25 >= 20 -> cwnd=20, carry 5 into avoidance
    assert c.cwnd == 20
    assert c.phase == 1
    # avoidance: +1 per cwnd acks
    c.on_new_ack(20)
    assert c.cwnd == 21
    # dup acks -> fast recovery on the 3rd
    assert not c.on_duplicate_ack()
    assert not c.on_duplicate_ack()
    assert c.on_duplicate_ack()
    assert c.in_fast_recovery
    assert c.ssthresh == 21 // 2 + 1
    assert c.cwnd == c.ssthresh + 3
    c.on_duplicate_ack()  # inflation
    assert c.cwnd == c.ssthresh + 4
    c.on_new_ack(1)  # deflate
    assert c.cwnd == c.ssthresh
    assert not c.in_fast_recovery
    c.on_timeout()
    assert c.cwnd == 10 and c.phase == 0


def test_write_after_close_raises():
    w = World()
    connect(w)
    w.a.close()
    with pytest.raises(Exception):
        w.a.write(b"too late")


def test_connection_refused_by_rst():
    w = World()
    w.a.open_active()
    syn = w.a.next_segment()
    # peer answers RST|ACK (no listener)
    from shadow_tpu.tcp.connection import Segment

    rst = Segment(
        flags=TcpFlags.RST | TcpFlags.ACK,
        seq=0,
        ack=seqmod.add(syn.seq, 1),
        window=0,
    )
    w.a.on_segment(rst)
    assert w.a.state == TcpState.CLOSED
    assert w.a.error == 111  # ECONNREFUSED


def test_syn_timeout_gives_up():
    """SYN black hole: connection dies with ETIMEDOUT after SYN_RETRIES."""
    w = World()
    w.a.open_active()
    w.a.next_segment()  # SYN leaves, vanishes
    # RTO backoff: 1+2+4+8+16+32+64s ~ 127s; drain segments as they rebuild
    for _ in range(20):
        w.run(w.time + 30_000 * MS)
        while w.a.next_segment() is not None:
            pass
        if w.a.state == TcpState.CLOSED:
            break
    assert w.a.state == TcpState.CLOSED
    assert w.a.error == 110  # ETIMEDOUT


def test_reads_after_reset_see_error_then_eof():
    w = World()
    connect(w)
    w.b.abort()
    w.run(w.time + 20 * MS)
    assert w.a.error == 104
    with pytest.raises(Exception):
        w.a.read(100)
    assert w.a.read(100) == b""  # post-reset reads are EOF
    assert w.a.at_eof()


def test_ack_beyond_snd_nxt_ignored():
    from shadow_tpu.tcp.connection import Segment

    w = World()
    connect(w)
    w.a.write(b"abc")
    w.run(w.time + 20 * MS)
    bogus = Segment(
        flags=TcpFlags.ACK,
        seq=w.b.iss + 1,
        ack=seqmod.add(w.a.iss, 1 + 5000),  # acks bytes never sent
        window=65535,
    )
    una_before = w.a.snd_una
    w.a.on_segment(bogus)
    assert w.a.snd_una == una_before  # ignored, not applied


def test_zero_window_then_write_arms_persist():
    """Data written while the peer window is already closed must still move
    once the window reopens, even if the update ack was lost."""
    from shadow_tpu.tcp.connection import Segment

    w = World()
    connect(w)
    # peer slams the window shut with everything acked
    w.a.on_segment(
        Segment(flags=TcpFlags.ACK, seq=w.b.iss + 1,
                ack=seqmod.add(w.a.iss, 1), window=0)
    )
    assert w.a.snd_wnd == 0
    w.a.write(b"stuck?" * 100)
    # no window update ever arrives; persist probes must elicit acks (which
    # b sends with its real, open window) and unstick the transfer
    w.run(w.time + 10_000 * MS)
    assert w.b.read(1 << 20) == b"stuck?" * 100


def test_lost_handshake_ack_survives_synack_retransmit():
    """RFC 793 p.69 / RFC 5961: a retransmitted SYN|ACK arriving after
    we reached ESTABLISHED (our handshake-completing ACK was lost) is an
    old duplicate SYN below the window — the answer is an ACK that
    completes the peer's handshake, never an RST. Round-4 behavior reset
    the connection, killing any flow whose final handshake ACK hit loss
    (surfaced by the flow engine's lossy wire; both twins fixed
    together — device side in tpu/tcp.py _ev_segment)."""
    w = World()
    w.a.open_active()
    syn = w.a.next_segment()
    w.time += w.latency
    w.b.open_passive(syn)
    synack = w.b.next_segment()
    assert synack.flags == TcpFlags.SYN | TcpFlags.ACK
    w.time += w.latency
    w.a.on_segment(synack)
    assert w.a.state == TcpState.ESTABLISHED
    ack = w.a.next_segment()  # the handshake-completing ACK: LOST
    assert ack is not None and ack.flags & TcpFlags.ACK

    # b times out and retransmits the identical SYN|ACK
    w.time += 1_000 * MS
    w.a.on_segment(synack)
    assert w.a.state == TcpState.ESTABLISHED  # not reset
    challenge = w.a.next_segment()
    assert challenge is not None
    assert challenge.flags & TcpFlags.ACK
    assert not challenge.flags & TcpFlags.RST
    w.time += w.latency
    w.b.on_segment(challenge)
    assert w.b.state == TcpState.ESTABLISHED
