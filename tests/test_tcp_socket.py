"""In-simulation TCP socket tests: connection setup, transfer, and close over
the full network path, across loopback / lossless / lossy links.

Parity model: reference `src/test/tcp/` scenario matrix
(tcp-blocking-loopback / -lossless / -lossy yaml configs).
"""

import pytest

from shadow_tpu.core import simtime
from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.event import TaskRef
from shadow_tpu.core.manager import Manager
from shadow_tpu.kernel import errors
from shadow_tpu.kernel.socket.tcp import TcpSocket
from shadow_tpu.kernel.status import FileState, ListenerFilter
from shadow_tpu.tcp.connection import TcpState

MS = simtime.MILLISECOND

SWITCH_CONFIG = """
general:
  stop_time: {stop}
  seed: {seed}
network:
  graph:
    type: 1_gbit_switch
hosts:
  server:
    network_node_id: 0
  client:
    network_node_id: 0
"""

LOSSY_GML = """graph [
  node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss {loss} ]
]"""

LOSSY_CONFIG = """
general:
  stop_time: {stop}
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
{graph}
hosts:
  server:
    network_node_id: 0
  client:
    network_node_id: 0
"""


def lossy_config(loss, stop="30s", seed=7):
    graph = LOSSY_GML.format(loss=loss)
    indented = "\n".join("      " + line for line in graph.splitlines())
    return load_config_str(
        LOSSY_CONFIG.format(stop=stop, seed=seed, graph=indented)
    )


class Server:
    """Accepts one connection, drains it, records bytes; echoes if asked."""

    PORT = 8080

    def __init__(self, host, echo=False):
        self.host = host
        self.echo = echo
        self.received = bytearray()
        self.eof_time = None
        self.accepted = None

    def start(self, host):
        self.listener = TcpSocket(host)
        self.listener.nonblocking = True
        self.listener.bind(("0.0.0.0", self.PORT))
        self.listener.listen()
        self.listener.add_listener(
            FileState.READABLE, ListenerFilter.OFF_TO_ON, self._on_acceptable
        )

    def _on_acceptable(self, state, changed, cq):
        while True:
            try:
                child = self.listener.accept()
            except errors.SyscallError:
                return
            child.nonblocking = True
            self.accepted = child
            child.add_listener(
                FileState.READABLE, ListenerFilter.OFF_TO_ON,
                lambda s, c, q: self._drain(),
            )
            self._drain()

    def _drain(self):
        while True:
            try:
                data = self.accepted.recv(65536)
            except errors.SyscallError:
                return
            if not data:
                if self.eof_time is None:
                    self.eof_time = self.host.now()
                    self.accepted.close()
                return
            self.received.extend(data)
            if self.echo:
                self.accepted.send(data)


class Client:
    """Connects and streams a payload, then closes."""

    def __init__(self, host, server_ip, payload, port=Server.PORT, expect_echo=False):
        self.host = host
        self.server_ip = server_ip
        self.payload = payload
        self.port = port
        self.expect_echo = expect_echo
        self.sent = 0
        self.connected_time = None
        self.received = bytearray()

    def start(self, host):
        self.sock = TcpSocket(host)
        self.sock.nonblocking = True
        self.sock.add_listener(
            FileState.WRITABLE, ListenerFilter.OFF_TO_ON,
            lambda s, c, q: self._on_writable(),
        )
        self.sock.add_listener(
            FileState.READABLE, ListenerFilter.OFF_TO_ON,
            lambda s, c, q: self._on_readable(),
        )
        with pytest.raises(errors.SyscallError) as e:
            self.sock.connect((self.server_ip, self.port))
        assert e.value.errno == errors.EINPROGRESS

    def _on_writable(self):
        if self.connected_time is None:
            self.connected_time = self.host.now()
        while self.sent < len(self.payload):
            try:
                n = self.sock.send(self.payload[self.sent : self.sent + 65536])
            except errors.SyscallError:
                return
            self.sent += n
        if self.sent == len(self.payload) and not self.sock._app_closed:
            if not self.expect_echo:
                self.sock.close()

    def _on_readable(self):
        while True:
            try:
                data = self.sock.recv(65536)
            except errors.SyscallError:
                return
            if not data:
                return
            self.received.extend(data)
            if (
                self.expect_echo
                and len(self.received) == len(self.payload)
                and not self.sock._app_closed
            ):
                self.sock.close()


def run_transfer(config, payload, echo=False):
    mgr = Manager(config)
    server_host = mgr.hosts_by_name["server"]
    client_host = mgr.hosts_by_name["client"]
    server = Server(server_host, echo=echo)
    client = Client(client_host, server_host.ip, payload, expect_echo=echo)
    server_host.add_application(10 * MS, server.start)
    client_host.add_application(20 * MS, client.start)
    stats = mgr.run()
    return server, client, stats


def test_tcp_transfer_lossless():
    cfg = load_config_str(SWITCH_CONFIG.format(stop="10s", seed=7))
    payload = bytes(i % 251 for i in range(200_000))
    server, client, stats = run_transfer(cfg, payload)
    assert bytes(server.received) == payload
    assert server.eof_time is not None
    assert client.connected_time is not None
    # handshake takes ~1 RTT (2ms) after client start at 20ms
    assert client.connected_time < 30 * MS


def test_tcp_transfer_is_deterministic():
    payload = bytes(i % 17 for i in range(50_000))
    runs = []
    for _ in range(2):
        cfg = load_config_str(SWITCH_CONFIG.format(stop="5s", seed=11))
        server, client, stats = run_transfer(cfg, payload)
        runs.append((server.eof_time, client.connected_time, stats.packets_sent))
    assert runs[0] == runs[1]


def test_tcp_transfer_lossy_link():
    """10% loss both ways; Reno + RTO must still complete the stream."""
    payload = bytes(i % 23 for i in range(30_000))
    server, client, stats = run_transfer(lossy_config(0.10), payload)
    assert bytes(server.received) == payload
    assert server.accepted.conn.retransmit_count + client.sock.conn.retransmit_count > 0


def test_tcp_echo_roundtrip():
    cfg = load_config_str(SWITCH_CONFIG.format(stop="10s", seed=3))
    payload = b"ping" * 2500
    server, client, stats = run_transfer(cfg, payload, echo=True)
    assert bytes(server.received) == payload
    assert bytes(client.received) == payload


def test_tcp_loopback_same_host():
    cfg = load_config_str(SWITCH_CONFIG.format(stop="5s", seed=9))
    mgr = Manager(cfg)
    host = mgr.hosts[0]
    server = Server(host)
    payload = b"local" * 4000
    client = Client(host, "127.0.0.1", payload)
    host.add_application(10 * MS, server.start)
    host.add_application(20 * MS, client.start)
    mgr.run()
    assert bytes(server.received) == payload


def test_connection_states_settle_to_closed():
    cfg = load_config_str(SWITCH_CONFIG.format(stop="100s", seed=5))
    payload = b"q" * 1000
    server, client, stats = run_transfer(cfg, payload)
    # TIME_WAIT is 60s; by stop_time everything is torn down
    assert client.sock.conn.state == TcpState.CLOSED
    assert server.accepted.conn.state == TcpState.CLOSED


def test_backlog_limits_pending_connections():
    cfg = load_config_str(SWITCH_CONFIG.format(stop="5s", seed=13))
    mgr = Manager(cfg)
    server_host = mgr.hosts_by_name["server"]
    client_host = mgr.hosts_by_name["client"]

    accepted = []

    def server_start(h):
        lst = TcpSocket(h)
        lst.nonblocking = True
        lst.bind(("0.0.0.0", 9090))
        lst.listen(1)

        def on_read(s, c, q):
            while True:
                try:
                    accepted.append(lst.accept())
                except errors.SyscallError:
                    return

        lst.add_listener(FileState.READABLE, ListenerFilter.OFF_TO_ON, on_read)

    conns = []

    def client_start(h):
        for _ in range(3):
            s = TcpSocket(h)
            s.nonblocking = True
            try:
                s.connect((server_host.ip, 9090))
            except errors.SyscallError as e:
                assert e.errno == errors.EINPROGRESS
            conns.append(s)

    server_host.add_application(10 * MS, server_start)
    client_host.add_application(20 * MS, client_start)
    mgr.run()
    # with an attentive accept loop all three eventually get in; the backlog
    # throttles simultaneous pending handshakes, not the total
    assert len(accepted) >= 1
    established = [c for c in conns if c.is_connected()]
    assert len(established) >= 1


def test_connect_ephemeral_ports_deterministic():
    results = []
    for _ in range(2):
        cfg = load_config_str(SWITCH_CONFIG.format(stop="2s", seed=21))
        mgr = Manager(cfg)
        host = mgr.hosts_by_name["client"]
        ports = []

        def start(h):
            for _ in range(3):
                s = TcpSocket(h)
                s.nonblocking = True
                try:
                    s.connect((mgr.hosts_by_name["server"].ip, 1))
                except errors.SyscallError:
                    pass
                ports.append(s.bound_addr[1])

        host.add_application(1 * MS, start)
        mgr.run()
        results.append(ports)
    assert results[0] == results[1]
    assert len(set(results[0])) == 3


def test_recv_peek_does_not_consume():
    """MSG_PEEK semantics: TcpConnection.peek returns in-order bytes
    without consuming them or touching window state (recv(2) MSG_PEEK)."""
    from tests.test_tcp_connection import World, connect

    w = World()
    connect(w)
    w.a.write(b"peekaboo")
    w.run(w.time + 50 * MS)
    assert w.b.peek(4) == b"peek"
    assert w.b.peek(100) == b"peekaboo"  # still all there
    assert w.b.readable_bytes() == 8
    assert w.b.read(100) == b"peekaboo"  # consuming read
    assert w.b.peek(100) == b""


def test_recv_buffer_autotunes_toward_rmem_max():
    """`tcp.c:587-614`: an app draining data quickly grows its receive
    buffer (2x bytes-copied-per-RTT), advertising bigger windows."""
    cfg = load_config_str(SWITCH_CONFIG.format(stop="20s", seed=9))
    payload = bytes(i % 251 for i in range(2_000_000))
    server, client, stats = run_transfer(cfg, payload)
    assert bytes(server.received) == payload
    conn = server.accepted.conn
    assert conn.config.recv_buffer > 174760  # grew past the default
    assert conn.config.recv_buffer <= TcpSocket.RMEM_MAX
    # wscale was negotiated to cover autotune headroom, not just the
    # initial buffer
    assert conn.my_wscale >= 7  # covers 6 MiB


def test_send_buffer_autotunes_with_cwnd():
    cfg = load_config_str(SWITCH_CONFIG.format(stop="20s", seed=9))
    payload = bytes(i % 17 for i in range(2_000_000))
    _server, client, _stats = run_transfer(cfg, payload)
    conn = client.sock.conn
    assert conn.config.send_buffer > 131072
    assert conn.config.send_buffer <= TcpSocket.WMEM_MAX


def test_autotune_disabled_keeps_buffers_static():
    cfg = load_config_str(SWITCH_CONFIG.format(stop="20s", seed=9) + """
experimental:
  socket_recv_autotune: false
  socket_send_autotune: false
""")
    payload = bytes(i % 251 for i in range(1_000_000))
    server, client, stats = run_transfer(cfg, payload)
    assert bytes(server.received) == payload
    assert server.accepted.conn.config.recv_buffer == 174760
    assert client.sock.conn.config.send_buffer == 131072


def test_setsockopt_pins_buffer_and_disables_autotune():
    mgr = Manager(load_config_str(SWITCH_CONFIG.format(stop="1s", seed=9)))
    host = mgr.hosts[0]
    s = TcpSocket(host)
    s.set_buffer_size("recv", 65536)
    assert s.autotune_recv is False
    assert s._config.recv_buffer == 131072  # Linux doubles the request
    s.set_buffer_size("send", 32768)
    assert s.autotune_send is False
    assert s._config.send_buffer == 65536
