"""Telemetry correctness: metrics parity + harvester + exporters.

The load-bearing guarantee is the parity matrix (the
tests/test_plane_sortdiet.py pattern): `window_step` with a PlaneMetrics
pytree threaded must produce BITWISE-identical simulation state,
delivered sets, and next-event scalars to the metrics-off path across
the qdisc matrix (RR/FIFO x router_aqm x no_loss) over chained windows.
On top of that: metric values reconcile against the state's own
counters, the harvester's async snapshot/unwrap/JSONL cycle is
deterministic, the exporters produce loadable artifacts, and the
tracker heartbeats are seed-diffable (sorted keys, idle zero lines)."""

import io
import json
import logging
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shadow_tpu.telemetry import (TelemetryHarvester,  # noqa: E402
                                  add_retransmits, make_metrics, unwrap_u32)
from shadow_tpu.telemetry import export  # noqa: E402
from shadow_tpu.tpu import (ingest, ingest_rows, make_params,  # noqa: E402
                            make_state)
from shadow_tpu.tpu.plane import window_step  # noqa: E402

MS = 1_000_000
N = 8


def busy_world(rr_mix=True):
    """The sortdiet busy world: starved buckets, real loss, mixed
    qdiscs — every counter path of the metrics section gets exercised."""
    rng = np.random.default_rng(7)
    lat = rng.integers(1 * MS, 20 * MS, size=(N, N)).astype(np.int32)
    loss = np.full((N, N), 0.3, np.float32)
    qrr = (np.arange(N) % 2 == 0) if rr_mix else np.zeros(N, bool)
    params = make_params(lat, loss, np.full((N,), 80_000, np.int64),
                         qdisc_rr=qrr, down_bw_bps=np.full((N,), 400_000))
    state = make_state(N, egress_cap=8, ingress_cap=8, params=params,
                       initial_tokens=np.asarray(params.tb_cap))
    b = 48
    state = ingest(
        state,
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.asarray(rng.integers(100, 1500, b), jnp.int32),
        jnp.asarray(rng.integers(0, 6, b), jnp.int32),
        jnp.arange(b, dtype=jnp.int32),
        jnp.asarray(rng.integers(0, 3, b) == 0),
        sock=jnp.asarray(rng.integers(0, 40, b), jnp.int32),
    )
    return state, params


def run_windows(state, params, *, windows=4, metrics=None, **kw):
    key = jax.random.key(3)
    if metrics is not None:
        step = jax.jit(lambda s, m, sh: window_step(
            s, params, key, sh, jnp.int32(10 * MS), metrics=m, **kw))
    else:
        step = jax.jit(lambda s, sh: window_step(
            s, params, key, sh, jnp.int32(10 * MS), **kw))
    shift = jnp.int32(0)
    out = []
    for _ in range(windows):
        if metrics is not None:
            state, delivered, nxt, metrics = step(state, metrics, shift)
        else:
            state, delivered, nxt = step(state, shift)
        out.append((state, delivered, nxt))
        shift = jnp.int32(10 * MS)
    return out, metrics


# -- parity: metrics are bitwise-invisible to the simulation --------------

@pytest.mark.parametrize("rr_enabled", [False, True])
@pytest.mark.parametrize("router_aqm", [False, True])
@pytest.mark.parametrize("no_loss", [False, True])
def test_metrics_bitwise_invisible(rr_enabled, router_aqm, no_loss):
    state, params = busy_world(rr_mix=rr_enabled)
    kw = dict(rr_enabled=rr_enabled, router_aqm=router_aqm,
              no_loss=no_loss)
    with_m, metrics = run_windows(state, params,
                                  metrics=make_metrics(N), **kw)
    without, _ = run_windows(state, params, **kw)
    for w, ((sa, da, na), (sb, db, nb)) in enumerate(zip(with_m, without)):
        for la, lb in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
            assert np.array_equal(np.asarray(la), np.asarray(lb)), (kw, w)
        for k in da:
            assert np.array_equal(np.asarray(da[k]),
                                  np.asarray(db[k])), (kw, w, k)
        assert int(na) == int(nb), (kw, w)
    assert int(metrics.windows) == len(with_m)


# -- metric values reconcile against the state's own counters -------------

@pytest.mark.parametrize("router_aqm", [False, True])
def test_metrics_reconcile_with_state_counters(router_aqm):
    state, params = busy_world()
    runs, m = run_windows(state, params, metrics=make_metrics(N),
                          rr_enabled=True, router_aqm=router_aqm,
                          no_loss=False)
    final = runs[-1][0]
    assert np.array_equal(np.asarray(m.pkts_out), np.asarray(final.n_sent))
    assert np.array_equal(np.asarray(m.drop_loss),
                          np.asarray(final.n_loss_dropped))
    assert np.array_equal(np.asarray(m.pkts_in),
                          np.asarray(final.n_delivered))
    if router_aqm:
        assert np.array_equal(np.asarray(m.drop_qdisc),
                              np.asarray(final.router.dropped))
    else:
        assert int(m.drop_qdisc.sum()) == 0
    assert int(m.events) == int(final.n_sent.sum()) \
        + int(final.n_delivered.sum())
    # traffic flowed, so the gauges moved
    assert int(m.bytes_out.sum()) > 0
    assert int(m.max_eg_depth.max()) > 0
    assert int(m.sort_slots) > 0


def test_ingest_and_ingest_rows_thread_ring_drops():
    state, params = busy_world()
    K = 12  # 48 seeded packets over 8 hosts + 12 more overflows CE=8
    dst = jnp.zeros((N, K), jnp.int32)
    nbytes = jnp.full((N, K), 500, jnp.int32)
    prio = jnp.arange(N * K, dtype=jnp.int32).reshape(N, K)
    ctrl = jnp.zeros((N, K), bool)
    for valid in (jnp.ones((N, K), bool), jnp.zeros((N, K), bool)):
        got, m = ingest_rows(state, dst, nbytes, prio, prio, ctrl, valid,
                             metrics=make_metrics(N))
        ref = ingest_rows(state, dst, nbytes, prio, prio, ctrl, valid)
        for la, lb in zip(jax.tree.leaves(got), jax.tree.leaves(ref)):
            assert np.array_equal(np.asarray(la), np.asarray(lb))
        assert np.array_equal(
            np.asarray(m.drop_ring_full),
            np.asarray(got.n_overflow_dropped)
            - np.asarray(state.n_overflow_dropped))
    # the flat ingest twin
    b = 80
    rng = np.random.default_rng(1)
    got2, m2 = ingest(
        state,
        jnp.zeros((b,), jnp.int32),  # all to host 0: guaranteed overflow
        jnp.asarray(rng.integers(0, N, b), jnp.int32),
        jnp.full((b,), 200, jnp.int32),
        jnp.arange(b, dtype=jnp.int32), jnp.arange(b, dtype=jnp.int32),
        jnp.zeros((b,), bool), metrics=make_metrics(N))
    assert int(m2.drop_ring_full.sum()) > 0
    assert np.array_equal(
        np.asarray(m2.drop_ring_full),
        np.asarray(got2.n_overflow_dropped)
        - np.asarray(state.n_overflow_dropped))


def test_add_retransmits_is_pure_add():
    m = make_metrics(4)
    m = add_retransmits(m, jnp.asarray([1, 0, 2, 0], jnp.int32))
    m = add_retransmits(m, jnp.asarray([0, 3, 0, 0], jnp.int32))
    assert np.asarray(m.retransmits).tolist() == [1, 3, 2, 0]


def test_retransmits_by_host_reduces_connections():
    from shadow_tpu.tpu import tcp as dtcp

    plane = dtcp.make_tcp_plane(5)
    plane = plane._replace(
        retransmit_count=jnp.asarray([2, 0, 1, 4, 0], jnp.int32))
    conn_host = jnp.asarray([0, 1, 0, 2, 1], jnp.int32)
    per_host = dtcp.retransmits_by_host(plane, conn_host, 4)
    assert np.asarray(per_host).tolist() == [3, 0, 4, 0]
    m = add_retransmits(make_metrics(4), per_host)
    assert np.asarray(m.retransmits).tolist() == [3, 0, 4, 0]


# -- harvester ------------------------------------------------------------

def test_unwrap_u32_handles_wraparound():
    assert int(unwrap_u32(np.int32(2**31 - 1),
                          np.int32(-(2**31) + 5))) == 6
    assert unwrap_u32(np.asarray([0, 100], np.int32),
                      np.asarray([7, 90], np.int32)).tolist() \
        == [7, (1 << 32) - 10]


def _fake_metrics(n, scale):
    """Numpy stand-ins (no copy_to_host_async; the harvester must fall
    back to holding the reference)."""
    m = make_metrics(n)._asdict()
    m["pkts_out"] = np.arange(n, dtype=np.int32) * scale
    m["bytes_out"] = np.full(n, 1000 * scale, np.int32)
    m["windows"] = np.int32(scale)
    m["events"] = np.int32(10 * scale)
    m["sort_slots"] = np.int32(4 * scale)
    return m


def test_harvester_jsonl_is_deterministic_and_unwrapped(tmp_path):
    def run():
        sink = io.StringIO()
        h = TelemetryHarvester(interval_ns=MS, sink=sink,
                               host_names=["a", "b", "c"],
                               slot_capacity=100)
        h.tick(1 * MS, device=_fake_metrics(3, 1),
               cpu={1: {"packets_out": 5}})
        h.tick(2 * MS, device=_fake_metrics(3, 2),
               cpu={1: {"packets_out": 9}})
        h.finalize()
        return sink.getvalue(), h

    text1, h1 = run()
    text2, _h2 = run()
    assert text1 == text2  # deterministic byte-for-byte
    assert h1.harvests == 2
    lines = [json.loads(ln) for ln in text1.splitlines()]
    sims = [r for r in lines if r["type"] == "sim"]
    hosts = [r for r in lines if r["type"] == "host"]
    assert len(sims) == 2 and len(hosts) == 2 * 3
    # cumulative totals (not raw re-reads): scale 1 then scale 2
    assert sims[0]["device_totals"]["pkts_out"] == 0 + 1 + 2
    assert sims[1]["device_totals"]["pkts_out"] == 2 * (0 + 1 + 2)
    # high-water marks aggregate with max, not a fleet sum
    assert sims[0]["device_totals"]["max_eg_depth"] == 0
    h3 = TelemetryHarvester(interval_ns=1, sink=None, per_host=False)
    h3.tick(1, device={"max_eg_depth": np.asarray([3, 7, 2], np.int32)})
    h3.finalize()
    assert h3.heartbeats[0]["device_totals"]["max_eg_depth"] == 7
    assert sims[1]["sort_occupancy"] == pytest.approx(8 / (2 * 100))
    a_lines = [r for r in hosts if r["host"] == "a"]
    assert a_lines[0]["cpu"]["packets_out"] == 5
    assert a_lines[1]["cpu"]["packets_out"] == 9


def test_harvester_lags_by_one_tick_and_cadence(tmp_path):
    h = TelemetryHarvester(interval_ns=10, sink=None)
    assert not h.due(5) and h.due(10)
    h.tick(10, device={"pkts_out": np.zeros(2, np.int32)})
    assert h.harvests == 0  # snapshot pending, not yet materialized
    assert not h.due(15) and h.due(20)
    h.tick(20, device={"pkts_out": np.ones(2, np.int32)})
    assert h.harvests == 1  # the 10ns snapshot drained on the next tick
    h.finalize()
    assert h.harvests == 2
    assert [r["time_ns"] for r in h.heartbeats
            if r["type"] == "sim"] == [10, 20]


def test_harvester_counter_wrap_across_ticks():
    h = TelemetryHarvester(interval_ns=1, sink=None, per_host=False)
    near = np.asarray([2**31 - 2], np.int32)
    wrapped = np.asarray([-(2**31) + 10], np.int32)  # +12 mod 2^32
    h.tick(1, device={"pkts_out": near})
    h.tick(2, device={"pkts_out": wrapped})
    h.finalize()
    sims = [r for r in h.heartbeats if r["type"] == "sim"]
    assert sims[0]["device_totals"]["pkts_out"] == 2**31 - 2
    assert sims[1]["device_totals"]["pkts_out"] == 2**31 + 10


def test_harvester_unwrap_across_int32_and_uint32_boundaries():
    """Drive one counter across BOTH wrap boundaries — 2^31 (the int32
    sign flip) and 2^32 (the full modular wrap back past zero) — over
    several harvest intervals and pin the reconstructed totals. The
    device counters are int32 two's-complement views of a modular-2^32
    stream; the unwrap must be exact as long as any single interval
    moves < 2^32."""
    # true totals, strictly increasing, crossing 2^31 then 2^32
    truth = [0, 2**31 - 10, 2**31 + 10, 2**32 - 7, 2**32 + 9,
             2**32 + 2**31 + 1]
    raw = [np.asarray([t], np.uint64).astype(np.uint32).astype(np.int32)
           for t in truth]
    # the raw int32 views really do go negative / wrap to small again
    assert int(raw[2][0]) < 0 and 0 < int(raw[4][0]) < 100
    h = TelemetryHarvester(interval_ns=1, sink=None, per_host=False)
    for i, arr in enumerate(raw, start=1):
        h.tick(i, device={"pkts_out": arr})
    h.finalize()
    totals = [r["device_totals"]["pkts_out"] for r in h.heartbeats
              if r["type"] == "sim"]
    assert totals == truth
    # the scalar helper agrees at both boundaries
    assert int(unwrap_u32(raw[1], raw[2])) == truth[2] - truth[1]
    assert int(unwrap_u32(raw[3], raw[4])) == truth[4] - truth[3]


def test_harvester_rejects_bad_interval():
    with pytest.raises(ValueError):
        TelemetryHarvester(interval_ns=0)


# -- exporters ------------------------------------------------------------

def _sample_heartbeats():
    h = TelemetryHarvester(interval_ns=MS, sink=None,
                           host_names=["a", "b", "c"], slot_capacity=100)
    h.tick(1 * MS, device=_fake_metrics(3, 1),
           cpu={1: {"packets_out": 5, "bytes_out": 700}})
    h.tick(2 * MS, device=_fake_metrics(3, 3))
    h.finalize()
    return h.heartbeats


def test_perfetto_trace_loads_and_uses_virtual_time(tmp_path):
    path = str(tmp_path / "trace.json")
    info = export.write_perfetto_trace(_sample_heartbeats(), path)
    with open(path) as fh:
        trace = json.load(fh)
    events = trace["traceEvents"]
    assert info["events"] == len(events) > 0
    assert info["hosts_dropped_by_cap"] == 0
    phases = {e["ph"] for e in events}
    assert {"M", "X", "C"} <= phases
    slices = [e for e in events if e["ph"] == "X"]
    # harvest slices tile the virtual axis: 0-1ms and 1-2ms in trace us
    assert [(s["ts"], s["dur"]) for s in slices] == [
        (0.0, 1000.0), (1000.0, 1000.0)]
    names = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert {"a", "b", "c"} <= names


def test_perfetto_trace_host_cap_is_loud(tmp_path):
    path = str(tmp_path / "trace.json")
    info = export.write_perfetto_trace(_sample_heartbeats(), path,
                                       max_hosts=2)
    assert info["hosts_plotted"] == 2 and info["hosts_dropped_by_cap"] == 1
    with open(path) as fh:
        assert json.load(fh)["otherData"]["hosts_dropped_by_cap"] == 1


def test_summarize_aggregates_max_fields_with_max():
    h = TelemetryHarvester(interval_ns=1, sink=None)
    h.tick(1, device={
        "pkts_out": np.asarray([2, 3], np.int32),
        "max_eg_depth": np.asarray([4, 9], np.int32),
    })
    h.finalize()
    summary = export.summarize(h.heartbeats)
    assert summary["totals"]["pkts_out"] == 5  # counters sum
    assert summary["totals"]["max_eg_depth"] == 9  # marks take the max


def test_to_plot_stats_matches_plot_shadow_schema():
    stats = export.to_plot_stats(_sample_heartbeats())
    assert set(stats) == {"nodes", "rusage", "meminfo"}
    node = stats["nodes"]["b"]
    assert len(node["time_ns"]) == len(node["counters"]) == 2
    assert "packets_dropped" in node["counters"][0]
    # cumulative bytes_out: the plot's delta/throughput math needs it
    assert node["counters"][1]["bytes_out"] >= \
        node["counters"][0]["bytes_out"]


def test_read_heartbeats_accepts_log_prefixed_lines():
    raw = json.dumps({"type": "sim", "time_ns": 5})
    lines = [
        "00:00:01.0 [INFO] [-] shadow_tpu.telemetry: telemetry "
        "time_ns=5 " + raw,
        raw,
        "not json at all",
        '{"type": "other"}',
    ]
    assert export.read_heartbeats(lines) == [
        {"type": "sim", "time_ns": 5}] * 2


def test_telemetry_report_cli(tmp_path, capsys):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import telemetry_report

    jsonl = tmp_path / "hb.jsonl"
    with open(jsonl, "w") as fh:
        for rec in _sample_heartbeats():
            fh.write(json.dumps(rec, sort_keys=True) + "\n")
    trace = tmp_path / "trace.json"
    stats_dir = tmp_path / "stats"
    rc = telemetry_report.main([str(jsonl), "--json",
                                "--trace", str(trace),
                                "--stats-dir", str(stats_dir)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["hosts"] == 3 and out["harvests"] == 2
    assert json.load(open(trace))["traceEvents"]
    assert json.load(open(stats_dir / "stats.shadow.json"))["nodes"]
    # empty input is an error, not a silent empty report
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert telemetry_report.main([str(empty)]) == 1


# -- config + manager integration ----------------------------------------

def test_telemetry_config_block_parses():
    from shadow_tpu.core.config import ConfigError, load_config_str

    base = ("general:\n  stop_time: 1s\n"
            "network:\n  graph:\n    type: 1_gbit_switch\n"
            "hosts:\n  a:\n    network_node_id: 0\n")
    cfg = load_config_str(base)
    assert not cfg.telemetry.enabled
    assert cfg.telemetry.interval == 1_000_000_000
    cfg = load_config_str(
        base + "telemetry:\n  enabled: true\n  interval: 250ms\n"
               "  per_host: false\n  sink: /tmp/x.jsonl\n")
    assert cfg.telemetry.enabled and not cfg.telemetry.per_host
    assert cfg.telemetry.interval == 250 * MS
    assert cfg.telemetry.sink == "/tmp/x.jsonl"
    with pytest.raises(ConfigError):
        load_config_str(base + "telemetry:\n  nonsense: 1\n")
    # interval validation is unconditional: --telemetry can flip
    # `enabled` on after parsing, so a bad interval must die here
    with pytest.raises(ConfigError):
        load_config_str(base + "telemetry:\n  interval: 0\n")
    # YAML 1.1 parses bare `off`/`on` as booleans; the documented
    # spellings must land as the sentinels the manager checks for
    cfg = load_config_str(base + "telemetry:\n  trace: off\n"
                                 "  sink: off\n")
    assert cfg.telemetry.trace == "off"
    assert cfg.telemetry.sink == "off"
    cfg = load_config_str(base + "telemetry:\n  trace: on\n  sink: on\n")
    assert cfg.telemetry.trace is None  # on = enabled at default path
    assert cfg.telemetry.sink is None
    with pytest.raises(ConfigError):
        load_config_str(base + "telemetry:\n  trace: 3\n")


def test_trace_off_disables_trace_export(tmp_path):
    from shadow_tpu.core.config import load_config_str
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(
        "general:\n  stop_time: 1s\n"
        "network:\n  graph:\n    type: 1_gbit_switch\n"
        "telemetry:\n  enabled: true\n  trace: off\n"
        "hosts:\n  a:\n    network_node_id: 0\n")
    data_dir = str(tmp_path / "run")
    os.makedirs(data_dir)
    mgr = Manager(cfg, data_dir=data_dir)
    mgr.run()
    assert not os.path.exists(os.path.join(data_dir, "trace.json"))
    assert os.path.exists(os.path.join(data_dir, "telemetry.jsonl"))
    # with the trace off nothing consumes retained heartbeats: none kept
    assert mgr.harvester.heartbeats == []
    assert mgr.harvester.emitted > 0


def test_sink_off_means_log_only(tmp_path):
    from shadow_tpu.core.config import load_config_str
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(
        "general:\n  stop_time: 1s\n"
        "network:\n  graph:\n    type: 1_gbit_switch\n"
        "telemetry:\n  enabled: true\n  sink: off\n  trace: off\n"
        "hosts:\n  a:\n    network_node_id: 0\n")
    data_dir = str(tmp_path / "run")
    os.makedirs(data_dir)
    mgr = Manager(cfg, data_dir=data_dir)
    assert mgr._telemetry_sink_path() is None
    mgr.run()
    assert not os.path.exists(os.path.join(data_dir, "telemetry.jsonl"))
    assert mgr.harvester.emitted > 0  # summary still goes to the log


def test_flow_engine_warns_on_telemetry(caplog):
    from shadow_tpu.core.config import load_config_str
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(
        "general:\n  stop_time: 1s\n"
        "network:\n  graph:\n    type: 1_gbit_switch\n"
        "experimental:\n  use_flow_engine: true\n"
        "telemetry:\n  enabled: true\n"
        "hosts:\n  a:\n    network_node_id: 0\n")
    with caplog.at_level(logging.WARNING, logger="shadow_tpu.manager"):
        mgr = Manager(cfg)
    assert any("use_flow_engine" in r.getMessage()
               for r in caplog.records)
    assert mgr.harvester is None  # attribute exists for the CLI


def test_manager_run_emits_heartbeats_and_trace(tmp_path):
    from shadow_tpu.core.config import load_config_str
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str(
        "general:\n  stop_time: 3s\n  heartbeat_interval: 1s\n"
        "network:\n  graph:\n    type: 1_gbit_switch\n"
        "telemetry:\n  enabled: true\n  interval: 1s\n"
        "hosts:\n  alpha:\n    network_node_id: 0\n"
        "  beta:\n    network_node_id: 0\n")
    data_dir = str(tmp_path / "run")
    os.makedirs(data_dir)
    mgr = Manager(cfg, data_dir=data_dir)
    mgr.run()
    sink = os.path.join(data_dir, "telemetry.jsonl")
    with open(sink) as fh:
        beats = export.read_heartbeats(fh)
    sims = [r for r in beats if r["type"] == "sim"]
    hosts = [r for r in beats if r["type"] == "host"]
    assert len(sims) >= 3  # >= 1 line per 1s harvest interval over 3s
    assert {r["host"] for r in hosts} == {"alpha", "beta"}
    assert all("cpu" in r for r in hosts)
    trace = json.load(open(os.path.join(data_dir, "trace.json")))
    assert trace["traceEvents"]


# -- device transport counters -------------------------------------------

class _StubHost:
    def __init__(self, hid):
        self.host_id = hid
        self.node_id = 0
        self.delivered = []

    def push_packet_event(self, packet, t, src_id, seq):
        self.delivered.append((packet, t, src_id, seq))


class _StubRouting:
    latency_ns = np.asarray([[1_000_000]], np.int64)

    def node_index(self, node_id):
        return 0


def test_device_transport_counts_out_and_released():
    from shadow_tpu.tpu.transport import DeviceTransport

    hosts = [_StubHost(1), _StubHost(2)]
    tr = DeviceTransport(hosts, _StubRouting(), {}, mode="sync",
                         egress_cap=8, ingress_cap=8)
    tr.release(0, 1000)
    tr.capture(hosts[0], hosts[1], "pkt-a", now_ns=0, seq=1,
               round_end_ns=1000, deliver_ns=1_000_000)
    tr.finish_round(0, 1000)
    tr.release(1000, 2_000_001)
    assert len(hosts[1].delivered) == 1
    arrs = {k: np.asarray(v) for k, v in tr.telemetry_arrays().items()}
    assert set(arrs) == {"pkts_out", "pkts_in", "drop_ring_full"}
    assert arrs["pkts_out"].tolist() == [1, 0]
    assert arrs["pkts_in"].tolist() == [0, 1]
    assert arrs["drop_ring_full"].tolist() == [0, 0]


# -- tracker heartbeats (satellite) ---------------------------------------

class _TrackerHost:
    name = "idle-host"

    def now(self):
        return 42

    def schedule_task_with_delay(self, task, delay):
        pass


def test_tracker_heartbeat_sorted_keys_and_idle_zero_lines(caplog):
    from shadow_tpu.host.tracker import Tracker

    host = _TrackerHost()
    tracker = Tracker(host, heartbeat_interval_ns=1_000_000_000)
    tracker.counters.by_protocol = {"UDP": 3, "TCP": 1}
    with caplog.at_level(logging.INFO, logger="shadow_tpu.tracker"):
        tracker._heartbeat(host)
    line = caplog.records[-1].getMessage()
    payload = json.loads(line[line.index("{"):])
    # serialized key order is sorted — stable across seeds
    assert list(payload) == sorted(payload)
    assert list(payload["by_protocol"]) == ["TCP", "UDP"]
    assert "time_ns=42" in line

    # an idle host still emits a full zero-counter line
    caplog.clear()
    idle = Tracker(_TrackerHost(), heartbeat_interval_ns=1_000_000_000)
    with caplog.at_level(logging.INFO, logger="shadow_tpu.tracker"):
        idle._heartbeat(idle.host)
    line = caplog.records[-1].getMessage()
    payload = json.loads(line[line.index("{"):])
    assert payload == {"by_protocol": {}, "bytes_in": 0, "bytes_out": 0,
                       "packets_dropped": 0, "packets_dropped_fault": 0,
                       "packets_in": 0, "packets_out": 0,
                       "retransmitted": 0}
