"""Device CoDel must match the CPU plane's CoDelQueue drop-for-drop.

Parity: the VERDICT/SURVEY contract for the TPU router model — replay the
same (push, pop) trace through `shadow_tpu.net.router.CoDelQueue` (the
reference-matching implementation, `codel_queue.rs:23-33`) and through the
batched `shadow_tpu.tpu.codel.codel_drain` kernel, and require identical
per-packet outcomes, delivery times, and drop counters.
"""

from __future__ import annotations

import zlib

import numpy as np
import pytest

from shadow_tpu.core import simtime
from shadow_tpu.net.router import CoDelQueue

MS = simtime.MILLISECOND


class FakePacket:
    def __init__(self, size: int):
        self._size = size

    def total_size(self) -> int:
        return self._size

    def add_status(self, status) -> None:
        pass


def cpu_replay(pushes, pops):
    """pushes: [(time, size)] ascending; pops: [time] ascending.
    Returns (status list per entry, deliver time per entry, dropped_count).
    Status: 0 queued, 1 delivered, 2 dropped."""
    q = CoDelQueue()
    packets = [FakePacket(size) for _, size in pushes]
    status = [0] * len(pushes)
    deliver_t = [None] * len(pushes)
    idx = {id(p): i for i, p in enumerate(packets)}

    events = [(t, 0, i) for i, (t, _) in enumerate(pushes)] + [
        (t, 1, j) for j, t in enumerate(pops)
    ]
    # pushes sort before pops at equal time (device convention: a pop at t
    # sees entries with arrival <= t)
    events.sort(key=lambda e: (e[0], e[1]))

    in_queue = set()
    for t, kind, i in events:
        if kind == 0:
            q.push(packets[i], t)
            in_queue.add(i)
        else:
            before = q.dropped_count
            out = q.pop(t)
            if out is not None:
                k = idx[id(out)]
                status[k] = 1
                deliver_t[k] = t
                in_queue.discard(k)
    # anything consumed but not delivered was dropped
    consumed_drops = q.dropped_count
    # walk the queue's internals to find what's still queued
    still = {id(p) for p, _ in q._elements}
    for i, p in enumerate(packets):
        if status[i] == 0 and id(p) not in still:
            status[i] = 2
    return status, deliver_t, consumed_drops


def device_replay(traces, K, P):
    """traces: list of (pushes, pops) per host. Returns device outputs."""
    import jax

    from shadow_tpu.tpu.codel import (
        I32_MAX,
        codel_drain,
        make_codel_state,
    )

    n = len(traces)
    arrival = np.full((n, K), I32_MAX, np.int32)
    size = np.zeros((n, K), np.int32)
    pops = np.full((n, P), I32_MAX, np.int32)
    for h, (pu, po) in enumerate(traces):
        for i, (t, s) in enumerate(pu):
            arrival[h, i] = t
            size[h, i] = s
        for j, t in enumerate(po):
            pops[h, j] = t
    state = make_codel_state(n)
    state, status, deliver_t = jax.jit(codel_drain)(arrival, size, pops, state)
    return (
        np.asarray(status), np.asarray(deliver_t), np.asarray(state.dropped)
    )


def make_trace(rng, regime: str):
    """Generate one host's (pushes, pops) trace."""
    pushes = []
    pops = []
    t = 0
    if regime == "light":
        # drain keeps up: standing delay stays below TARGET
        for _ in range(rng.integers(5, 20)):
            t += int(rng.integers(1 * MS, 5 * MS))
            pushes.append((t, int(rng.integers(100, 1500))))
            pops.append(t + int(rng.integers(0, 2 * MS)))
    elif regime == "burst":
        # burst of arrivals, slow drain: standing delay >> TARGET for longer
        # than INTERVAL -> store->drop transition and control-law drops
        nb = int(rng.integers(30, 60))
        for _ in range(nb):
            t += int(rng.integers(0, MS // 2))
            pushes.append((t, int(rng.integers(800, 1500))))
        pop_t = t
        for _ in range(nb):
            pop_t += int(rng.integers(20 * MS, 40 * MS))
            pops.append(pop_t)
    elif regime == "mixed":
        # alternating congestion and recovery
        for _ in range(4):
            nb = int(rng.integers(8, 16))
            for _ in range(nb):
                t += int(rng.integers(0, MS))
                pushes.append((t, int(rng.integers(200, 1500))))
            pop_t = t + int(rng.integers(5 * MS, 150 * MS))
            for _ in range(nb):
                pop_t += int(rng.integers(1 * MS, 30 * MS))
                pops.append(pop_t)
            t = max(t, pop_t)
    pops.sort()
    return pushes, pops


@pytest.mark.parametrize("regime", ["light", "burst", "mixed"])
def test_device_codel_matches_cpu(regime):
    rng = np.random.default_rng(zlib.crc32(regime.encode()))
    traces = [make_trace(rng, regime) for _ in range(8)]
    K = max(len(pu) for pu, _ in traces)
    P = max(len(po) for _, po in traces)

    dev_status, dev_deliver, dev_dropped = device_replay(traces, K, P)

    for h, (pushes, pops) in enumerate(traces):
        status, deliver_t, dropped = cpu_replay(pushes, pops)
        got_status = dev_status[h, : len(pushes)].tolist()
        assert got_status == status, (
            f"host {h} ({regime}): status mismatch\n"
            f"cpu: {status}\ndev: {got_status}"
        )
        for i, dt in enumerate(deliver_t):
            if dt is not None:
                assert int(dev_deliver[h, i]) == dt, (
                    f"host {h} entry {i}: deliver time "
                    f"{int(dev_deliver[h, i])} != {dt}"
                )
        assert int(dev_dropped[h]) == dropped, (
            f"host {h} ({regime}): dropped {int(dev_dropped[h])} != {dropped}"
        )


def test_device_codel_drop_mode_engages():
    """Sanity: the burst regime actually exercises drops (otherwise the
    parity test proves nothing about the control law)."""
    rng = np.random.default_rng(7)
    traces = [make_trace(rng, "burst") for _ in range(4)]
    K = max(len(pu) for pu, _ in traces)
    P = max(len(po) for _, po in traces)
    _, _, dropped = device_replay(traces, K, P)
    assert int(dropped.sum()) > 0, "burst trace produced zero CoDel drops"


def test_codel_state_rebase():
    from shadow_tpu.tpu.codel import make_codel_state, rebase_codel_state

    st = make_codel_state(2)
    st = st._replace(
        has_drop_next=np.array([True, False]),
        drop_next=np.array([500, 500], np.int32),
        has_interval_end=np.array([False, True]),
        interval_end=np.array([900, 900], np.int32),
    )
    out = rebase_codel_state(st, 100)
    assert out.drop_next.tolist() == [400, 500]
    assert out.interval_end.tolist() == [900, 800]
