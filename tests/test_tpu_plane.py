"""TPU network-plane tests (run on CPU with 8 virtual devices, see
conftest.py). Semantics under test mirror the CPU plane's contracts:
latency lookup, deliver-time clamp to the round barrier, Bernoulli loss
from per-host counter RNG, token-bucket shaping, capacity overflow, and
determinism under resharding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shadow_tpu.tpu import (
    ingest,
    make_mesh,
    make_params,
    make_state,
    shard_state,
    window_step,
)
from shadow_tpu.tpu.plane import I32_MAX

MS = 1_000_000


def simple_world(n=4, latency_ms=10, loss=0.0, bw_bps=8_000_000_000):
    lat = np.full((n, n), latency_ms * MS, np.int32)
    lo = np.full((n, n), loss, np.float32)
    bw = np.full((n,), bw_bps, np.int64)
    params = make_params(lat, lo, bw)
    state = make_state(n, initial_tokens=np.asarray(params.tb_cap))
    return state, params


def send_one(state, src, dst, nbytes=1000, prio=0, seq=1, ctrl=False):
    return ingest(
        state,
        jnp.array([src], jnp.int32),
        jnp.array([dst], jnp.int32),
        jnp.array([nbytes], jnp.int32),
        jnp.array([prio], jnp.int32),
        jnp.array([seq], jnp.int32),
        jnp.array([ctrl], bool),
    )


def test_ingest_places_packet():
    state, params = simple_world()
    state = send_one(state, 0, 2, seq=7)
    assert int(state.eg_valid.sum()) == 1
    assert int(state.eg_dst[0, 0]) == 2
    assert int(state.eg_seq[0, 0]) == 7


def test_ingest_rows_matches_flat_ingest():
    """The row-shaped twin must land packets in exactly the state the flat
    ingest produces (slot-for-slot), including appends after existing
    entries and per-row overflow counting."""
    from shadow_tpu.tpu import ingest_rows

    state_a, params = simple_world()
    state_b, _ = simple_world()
    # pre-existing entry on host 1 in both
    state_a = send_one(state_a, 1, 0, seq=99)
    state_b = send_one(state_b, 1, 0, seq=99)

    # flat batch: host 0 sends 2, host 1 sends 1 (in (src, seq) order)
    state_a = ingest(
        state_a,
        jnp.array([0, 0, 1], jnp.int32), jnp.array([2, 3, 2], jnp.int32),
        jnp.array([100, 200, 300], jnp.int32),
        jnp.array([5, 6, 7], jnp.int32), jnp.array([5, 6, 7], jnp.int32),
        jnp.array([False, True, False]),
    )
    # same packets as [N, K] rows
    N, K = state_b.eg_dst.shape[0], 2
    dst = jnp.full((N, K), -1, jnp.int32)
    dst = dst.at[0, 0].set(2).at[0, 1].set(3).at[1, 0].set(2)
    nbytes = jnp.zeros((N, K), jnp.int32)
    nbytes = nbytes.at[0, 0].set(100).at[0, 1].set(200).at[1, 0].set(300)
    pr = jnp.zeros((N, K), jnp.int32)
    pr = pr.at[0, 0].set(5).at[0, 1].set(6).at[1, 0].set(7)
    ctrl = jnp.zeros((N, K), bool).at[0, 1].set(True)
    valid = jnp.zeros((N, K), bool)
    valid = valid.at[0, 0].set(True).at[0, 1].set(True).at[1, 0].set(True)
    state_b = ingest_rows(state_b, dst, nbytes, pr, pr, ctrl, valid)

    for a, b in zip(jax.tree.leaves(state_a), jax.tree.leaves(state_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_ingest_rows_overflow_counted():
    from shadow_tpu.tpu import ingest_rows

    state, params = simple_world()
    CE = state.eg_dst.shape[1]
    K = CE + 3
    N = state.eg_dst.shape[0]
    shape = (N, K)
    valid = jnp.zeros(shape, bool).at[2, :].set(True)  # host 2 floods
    state = ingest_rows(
        state, jnp.zeros(shape, jnp.int32), jnp.full(shape, 10, jnp.int32),
        jnp.zeros(shape, jnp.int32), jnp.zeros(shape, jnp.int32),
        jnp.zeros(shape, bool), valid,
    )
    assert int(state.n_overflow_dropped[2]) == 3
    assert int(state.eg_valid[2].sum()) == CE


def test_packet_travels_with_latency():
    state, params = simple_world(latency_ms=10)
    key = jax.random.key(0)
    state = send_one(state, 0, 2)
    # round 1 (1ms window): packet leaves host 0, lands in host 2's ingress
    state, delivered, next_ev = window_step(
        state, params, key, jnp.int32(0), jnp.int32(1 * MS)
    )
    assert int(delivered["mask"].sum()) == 0
    assert int(state.in_valid[2].sum()) == 1
    assert int(next_ev) == 10 * MS  # latency 10ms > window
    # advance to the delivery window
    state, delivered, _ = window_step(
        state, params, key, jnp.int32(10 * MS), jnp.int32(1 * MS)
    )
    assert int(delivered["mask"][2].sum()) == 1
    mask2 = np.asarray(delivered["mask"][2])
    (src2,) = [int(s) for s, m in zip(np.asarray(delivered["src"][2]), mask2) if m]
    assert src2 == 0
    assert int(state.n_delivered.sum()) == 1


def test_deliver_time_clamped_to_round_end():
    """Sub-window latency still lands no earlier than the barrier
    (`worker.rs:396-399`)."""
    state, params = simple_world(latency_ms=1)
    key = jax.random.key(0)
    state = send_one(state, 0, 1)
    state, delivered, next_ev = window_step(
        state, params, key, jnp.int32(0), jnp.int32(5 * MS)
    )
    # latency 1ms < 5ms window: deliverable exactly at the next barrier
    assert int(next_ev) == 5 * MS
    assert int(delivered["mask"].sum()) == 0


def test_full_loss_drops_data_but_not_control():
    state, params = simple_world(loss=1.0)
    key = jax.random.key(0)
    state = send_one(state, 0, 1, seq=1, ctrl=False)
    state = send_one(state, 0, 1, seq=2, ctrl=True)
    state, _, _ = window_step(state, params, key, jnp.int32(0), jnp.int32(MS))
    assert int(state.n_loss_dropped.sum()) == 1  # data packet died
    assert int(state.n_sent.sum()) == 1  # control went through
    assert int(state.in_valid[1].sum()) == 1


def test_loss_depends_only_on_counter_not_batching():
    """Same logical packets, sent in one batch vs two rounds, see identical
    Bernoulli draws (counter-based keys)."""

    def run(batched):
        state, params = simple_world(loss=0.5, latency_ms=2)
        key = jax.random.key(42)
        if batched:
            state = ingest(
                state,
                jnp.zeros(8, jnp.int32),
                jnp.ones(8, jnp.int32),
                jnp.full((8,), 1000, jnp.int32),
                jnp.arange(8, dtype=jnp.int32),
                jnp.arange(8, dtype=jnp.int32),
                jnp.zeros(8, bool),
            )
            state, _, _ = window_step(state, params, key, jnp.int32(0), jnp.int32(MS))
        else:
            for i in range(4):
                state = send_one(state, 0, 1, prio=i, seq=i)
            state, _, _ = window_step(state, params, key, jnp.int32(0), jnp.int32(MS))
            for i in range(4, 8):
                state = send_one(state, 0, 1, prio=i, seq=i)
            state, _, _ = window_step(state, params, key, jnp.int32(MS), jnp.int32(MS))
        return int(state.n_loss_dropped.sum()), int(state.n_sent.sum())

    assert run(True) == run(False)


def test_token_bucket_paces_egress():
    # 8 Mbit/s = 1000 B/ms; 1 MTU burst allowance
    state, params = simple_world(bw_bps=8_000_000)
    key = jax.random.key(0)
    # 10 x 1000B packets queued at once
    state = ingest(
        state,
        jnp.zeros(10, jnp.int32),
        jnp.ones(10, jnp.int32),
        jnp.full((10,), 1000, jnp.int32),
        jnp.arange(10, dtype=jnp.int32),
        jnp.arange(10, dtype=jnp.int32),
        jnp.zeros(10, bool),
    )
    state, _, _ = window_step(state, params, key, jnp.int32(0), jnp.int32(MS))
    first = int(state.n_sent.sum())
    assert first < 10  # initial bucket (rate+MTU = 2500B) can't carry all 10
    # each following 1ms window refills 1000B -> ~1 packet per window
    for i in range(12):
        state, _, _ = window_step(state, params, key, jnp.int32(MS), jnp.int32(MS))
    assert int(state.n_sent.sum()) == 10
    assert int(state.eg_valid.sum()) == 0


def test_chain_windows_matches_manual_loop():
    """The device-resident window chain must land in the bitwise-identical
    state a Python loop applying the controller policy produces, stop at
    the first delivering window, and report that window's offset."""
    from shadow_tpu.tpu.plane import chain_windows

    def build():
        state, params = simple_world(n=4)
        # two packets with different latencies: several delivery-free
        # windows pass before the first arrival (simple_world latency
        # between distinct hosts; send at t=0)
        state = send_one(state, 0, 1, seq=1)
        state = send_one(state, 2, 3, seq=2)
        return state, params

    key = jax.random.key(0)
    W = MS  # 1 ms windows; simple_world latency is 10 ms
    runahead = MS
    horizon = 200 * MS
    stop = 400 * MS

    # manual controller loop: first window [0, W), then jump to next event
    state_m, params = build()
    off_m = 0
    shift = 0
    window = W
    n_windows = 0
    while True:
        state_m, delivered_m, next_ev = window_step(
            state_m, params, key, jnp.int32(shift), jnp.int32(window))
        n_windows += 1
        nxt = int(next_ev)
        if bool(delivered_m["mask"].any()) or off_m + nxt >= min(horizon, stop):
            break
        off_m += nxt
        shift = nxt
        window = min(runahead, stop - off_m)

    state_c, _ = build()
    state_c, delivered_c, off_c, next_c, n_c = chain_windows(
        state_c, params, key, 0, W, runahead, horizon, stop)

    assert int(off_c) == off_m
    assert int(n_c) == n_windows
    assert bool(delivered_c["mask"].any())  # stopped BECAUSE it delivered
    for a, b in zip(jax.tree.leaves(state_m), jax.tree.leaves(state_c)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in delivered_m:
        np.testing.assert_array_equal(np.asarray(delivered_m[k]),
                                      np.asarray(delivered_c[k]))
    # both packets still in flight? no — seq 1 delivered; seq 2 from a
    # different pair keeps the chain honest about per-window next events
    assert int(delivered_c["mask"].sum()) >= 1


def test_chain_windows_respects_horizon():
    """A CPU-side event before the next device event must stop the chain
    even with no deliveries produced."""
    from shadow_tpu.tpu.plane import chain_windows

    state, params = simple_world(n=2)
    state = send_one(state, 0, 1, seq=5)
    key = jax.random.key(0)
    # horizon right after the first window: chain must stop at 1 window
    state, delivered, off, next_rel, n = chain_windows(
        state, params, key, 0, MS, MS, 2 * MS, 400 * MS)
    assert int(n) == 1
    assert int(off) == 0
    assert not bool(delivered["mask"].any())
    assert int(next_rel) < I32_MAX  # the packet is still coming


def test_priority_orders_egress_under_contention():
    state, params = simple_world(bw_bps=8_000_000)  # 1000B/ms
    key = jax.random.key(0)
    # queue three packets, highest priority value last
    for i, prio in enumerate([30, 10, 20]):
        state = send_one(state, 0, 1, nbytes=1400, prio=prio, seq=i)
    sent_seqs = []
    for r in range(6):
        state, _, _ = window_step(
            state, params, key, jnp.int32(0 if r == 0 else MS), jnp.int32(MS)
        )
        # whichever new packets appeared in dst ingress, in insertion order
        for slot in range(state.in_src.shape[1]):
            if bool(state.in_valid[1, slot]) and int(state.in_seq[1, slot]) not in sent_seqs:
                sent_seqs.append(int(state.in_seq[1, slot]))
    assert sent_seqs == [1, 2, 0]  # prio 10 then 20 then 30


def test_ingress_overflow_counted():
    state, params = simple_world(n=2)
    state = make_state(2, ingress_cap=4, initial_tokens=np.asarray(params.tb_cap))
    key = jax.random.key(0)
    state = ingest(
        state,
        jnp.zeros(8, jnp.int32),
        jnp.ones(8, jnp.int32),
        jnp.full((8,), 100, jnp.int32),
        jnp.arange(8, dtype=jnp.int32),
        jnp.arange(8, dtype=jnp.int32),
        jnp.zeros(8, bool),
    )
    state, _, _ = window_step(state, params, key, jnp.int32(0), jnp.int32(MS))
    assert int(state.in_valid[1].sum()) == 4
    assert int(state.n_overflow_dropped[1]) == 4


def test_delivery_order_is_deterministic_by_src_seq():
    state, params = simple_world(n=4, latency_ms=1)
    key = jax.random.key(0)
    # three hosts send to host 3 in the same round
    for src, seq in ((2, 5), (0, 9), (1, 1)):
        state = send_one(state, src, 3, seq=seq)
    state, _, _ = window_step(state, params, key, jnp.int32(0), jnp.int32(MS))
    state, delivered, _ = window_step(state, params, key, jnp.int32(MS), jnp.int32(MS))
    mask = np.asarray(delivered["mask"][3])
    srcs = [int(s) for s, m in zip(np.asarray(delivered["src"][3]), mask) if m]
    # same deliver time -> ordered by (src, seq): hosts 0, 1, 2
    assert srcs == [0, 1, 2]


def test_jit_and_multiple_rounds():
    state, params = simple_world(n=8, latency_ms=3)
    key = jax.random.key(7)
    step = jax.jit(window_step)
    state = ingest(
        state,
        jnp.arange(8, dtype=jnp.int32),
        jnp.flip(jnp.arange(8, dtype=jnp.int32)),
        jnp.full((8,), 500, jnp.int32),
        jnp.zeros(8, jnp.int32),
        jnp.arange(8, dtype=jnp.int32),
        jnp.zeros(8, bool),
    )
    total = 0
    shift = jnp.int32(0)
    for _ in range(5):
        state, delivered, next_ev = step(state, params, key, shift, jnp.int32(MS))
        total += int(jnp.sum(delivered["mask"]))
        shift = jnp.int32(MS)
    assert total == 8  # everyone's packet arrived (incl. self-sends 3->4 etc.)


def test_sharded_step_matches_single_device():
    """The same workload produces identical results under an 8-way host
    sharding — determinism is independent of the mesh."""
    def run(shard):
        state, params = simple_world(n=16, latency_ms=2, loss=0.3)
        key = jax.random.key(3)
        if shard:
            mesh = make_mesh(8)
            state, params = shard_state(state, params, mesh)
        state = ingest(
            state,
            jnp.repeat(jnp.arange(16, dtype=jnp.int32), 2),
            jnp.tile(jnp.array([3, 11], jnp.int32), 16),
            jnp.full((32,), 800, jnp.int32),
            jnp.arange(32, dtype=jnp.int32),
            jnp.arange(32, dtype=jnp.int32),
            jnp.zeros(32, bool),
        )
        step = jax.jit(window_step)
        outs = []
        shift = jnp.int32(0)
        for _ in range(4):
            state, delivered, next_ev = step(state, params, key, shift, jnp.int32(MS))
            outs.append(
                (
                    np.asarray(delivered["mask"]).copy(),
                    np.asarray(delivered["src"]).copy(),
                    int(next_ev),
                )
            )
            shift = jnp.int32(MS)
        return outs, np.asarray(state.n_sent), np.asarray(state.n_loss_dropped)

    single, sent1, lost1 = run(False)
    sharded, sent2, lost2 = run(True)
    np.testing.assert_array_equal(sent1, sent2)
    np.testing.assert_array_equal(lost1, lost2)
    for (m1, s1, n1), (m2, s2, n2) in zip(single, sharded):
        np.testing.assert_array_equal(m1, m2)
        np.testing.assert_array_equal(s1 * m1, s2 * m2)
        assert n1 == n2


def test_window_step_round_trip_preserves_all_fields():
    """Every NetPlaneState field survives a step (regression for the r02
    eg_sock drop) and per-slot columns stay mutually aligned."""
    state, params = simple_world(bw_bps=8_000_000)  # 1000B/ms: leftovers stay
    key = jax.random.key(0)
    seqs = [3, 1, 2]
    socks = [7, 5, 6]
    for i in range(3):
        state = ingest(
            state,
            jnp.array([0], jnp.int32), jnp.array([1], jnp.int32),
            jnp.array([1400], jnp.int32), jnp.array([seqs[i]], jnp.int32),
            jnp.array([seqs[i]], jnp.int32), jnp.array([False]),
            sock=jnp.array([socks[i]], jnp.int32),
        )
    out_state, _, _ = window_step(state, params, key, jnp.int32(0), jnp.int32(MS))
    assert set(out_state._fields) == set(state._fields)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(out_state)):
        assert a.shape == b.shape
    # two leftovers remain; sock column must track seq through both sorts
    left = {(int(q), int(s)) for q, s, v in zip(
        np.asarray(out_state.eg_seq[0]), np.asarray(out_state.eg_sock[0]),
        np.asarray(out_state.eg_valid[0])) if v}
    assert left == {(2, 6), (3, 7)}


def test_rr_qdisc_interleaves_sockets_within_window():
    n = 2
    lat = np.full((n, n), MS, np.int32)
    params = make_params(lat, np.zeros((n, n), np.float32),
                         np.full(n, 8_000_000, np.int64),
                         qdisc_rr=np.array([True, True]))
    # bucket = rate + MTU = 2500B: exactly 3 x 800B go out round one
    state = make_state(n, initial_tokens=np.asarray(params.tb_cap))
    # sock 11 queues seqs 0..2, sock 22 queues seqs 3..4
    state = ingest(
        state,
        jnp.zeros(5, jnp.int32), jnp.ones(5, jnp.int32),
        jnp.full(5, 800, jnp.int32), jnp.zeros(5, jnp.int32),
        jnp.arange(5, dtype=jnp.int32),
        jnp.zeros(5, bool),
        sock=jnp.array([11, 11, 11, 22, 22], jnp.int32),
    )
    key = jax.random.key(0)
    state, _, _ = window_step(state, params, key, jnp.int32(0), jnp.int32(MS))
    sent = sorted(int(s) for s, v in zip(
        np.asarray(state.in_seq[1]), np.asarray(state.in_valid[1])) if v)
    # RR: sock11-seq0, sock22-seq3, sock11-seq1 — NOT seqs 0,1,2
    assert sent == [0, 1, 3]


def test_rr_qdisc_fair_across_windows():
    """A persistent virtual-finish counter keeps RR fair when the bucket
    only passes one packet per window (ring-of-sockets semantics,
    `network_interface.c:205-303`)."""
    n = 2
    lat = np.full((n, n), MS, np.int32)
    params = make_params(lat, np.zeros((n, n), np.float32),
                         np.full(n, 8_000_000, np.int64),
                         qdisc_rr=np.array([True, True]))
    state = make_state(n)  # empty bucket: refill 1000B per 1ms window
    state = ingest(
        state,
        jnp.zeros(6, jnp.int32), jnp.ones(6, jnp.int32),
        jnp.full(6, 900, jnp.int32), jnp.zeros(6, jnp.int32),
        jnp.arange(6, dtype=jnp.int32),
        jnp.zeros(6, bool),
        sock=jnp.array([11, 11, 11, 22, 22, 22], jnp.int32),
    )
    key = jax.random.key(0)
    order = []
    shift = jnp.int32(0)
    seen = set()
    for _ in range(8):
        state, _, _ = window_step(state, params, key, shift, jnp.int32(MS))
        shift = jnp.int32(MS)
        for s, v in zip(np.asarray(state.in_seq[1]), np.asarray(state.in_valid[1])):
            if v and int(s) not in seen:
                seen.add(int(s))
                order.append(int(s))
    # one packet per window, alternating sockets: 0,3,1,4,2,5
    assert order == [0, 3, 1, 4, 2, 5]


def test_fifo_ignores_sock_ids():
    """Default FIFO mode orders by priority even when sock ids differ."""
    state, params = simple_world(bw_bps=8_000_000)
    key = jax.random.key(0)
    state = ingest(
        state,
        jnp.zeros(3, jnp.int32), jnp.ones(3, jnp.int32),
        jnp.full(3, 1400, jnp.int32), jnp.array([30, 10, 20], jnp.int32),
        jnp.arange(3, dtype=jnp.int32),
        jnp.zeros(3, bool),
        sock=jnp.array([1, 2, 3], jnp.int32),
    )
    seen = []
    shift = jnp.int32(0)
    for _ in range(6):
        state, _, _ = window_step(state, params, key, shift, jnp.int32(MS))
        shift = jnp.int32(MS)
        for s, v in zip(np.asarray(state.in_seq[1]), np.asarray(state.in_valid[1])):
            if v and int(s) not in seen:
                seen.append(int(s))
    assert seen == [1, 2, 0]


def test_rr_survives_idle_window():
    """An empty-egress window must not corrupt the RR virtual-time floor
    (regression: min over an empty active set saturated rr_sent to
    I32_MAX and the next window's keys wrapped int32)."""
    n = 2
    lat = np.full((n, n), MS, np.int32)
    params = make_params(lat, np.zeros((n, n), np.float32),
                         np.full(n, 8_000_000, np.int64),
                         qdisc_rr=np.array([True, True]))
    state = make_state(n)
    key = jax.random.key(0)
    # idle window first: nothing queued anywhere
    state, _, _ = window_step(state, params, key, jnp.int32(0), jnp.int32(MS))
    assert int(np.asarray(state.rr_sent).max()) < 2**20
    state = ingest(
        state,
        jnp.zeros(6, jnp.int32), jnp.ones(6, jnp.int32),
        jnp.full(6, 900, jnp.int32), jnp.zeros(6, jnp.int32),
        jnp.arange(6, dtype=jnp.int32),
        jnp.zeros(6, bool),
        sock=jnp.array([11, 11, 11, 22, 22, 22], jnp.int32),
    )
    order = []
    seen = set()
    for _ in range(8):
        state, _, _ = window_step(state, params, key, jnp.int32(MS), jnp.int32(MS))
        for s, v in zip(np.asarray(state.in_seq[1]), np.asarray(state.in_valid[1])):
            if v and int(s) not in seen:
                seen.add(int(s))
                order.append(int(s))
    assert order == [0, 3, 1, 4, 2, 5]
    # counters stay rebased near zero even after many windows
    assert int(np.asarray(state.rr_sent).max()) <= 64


def test_compact_delivered_matches_mask():
    """plane.compact_delivered front-packs exactly the delivered slots:
    (dst, src, seq, sock, deliver) recovered from the compact columns must
    equal the set read straight off the [N, CI] mask — the small-transfer
    contract consumers (flow-engine result extraction) rely on."""
    from shadow_tpu.tpu.plane import compact_delivered, ingest, window_step

    n = 8
    lat = np.full((n, n), 2 * MS, np.int64)
    np.fill_diagonal(lat, MS)
    params = make_params(lat, np.zeros((n, n), np.float32), np.full(n, 1e9))
    state = make_state(n, initial_tokens=np.asarray(params.tb_cap))
    key = jax.random.PRNGKey(0)
    b = 12
    state = ingest(
        state,
        jnp.arange(b, dtype=jnp.int32) % n,
        (jnp.arange(b, dtype=jnp.int32) + 3) % n,
        jnp.full(b, 500, jnp.int32), jnp.zeros(b, jnp.int32),
        jnp.arange(b, dtype=jnp.int32), jnp.zeros(b, bool),
        sock=jnp.arange(b, dtype=jnp.int32) + 100,
    )
    # window 1 sends (NO_CLAMP = deliveries clamp to this window's end);
    # window 2 releases them
    state, delivered, _ = window_step(
        state, params, key, jnp.int32(0), jnp.int32(5 * MS))
    state, delivered, _ = window_step(
        state, params, key, jnp.int32(5 * MS), jnp.int32(5 * MS))
    cnt, dst, src, seq, sock, d_t = jax.device_get(
        compact_delivered(delivered, 16))
    mask = np.asarray(delivered["mask"])
    want = set()
    rows, cols = np.nonzero(mask)
    for r, c in zip(rows, cols):
        want.add((int(r), int(np.asarray(delivered["src"])[r, c]),
                  int(np.asarray(delivered["seq"])[r, c]),
                  int(np.asarray(delivered["sock"])[r, c]),
                  int(np.asarray(delivered["deliver_rel"])[r, c])))
    got = {(int(dst[i]), int(src[i]), int(seq[i]), int(sock[i]),
            int(d_t[i])) for i in range(int(cnt))}
    assert int(cnt) == mask.sum() == len(want) > 0
    assert got == want
    # dead tail slots are marked with dst == -1
    assert all(int(d) == -1 for d in dst[int(cnt):])
