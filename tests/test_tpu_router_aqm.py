"""Drop-for-drop parity of the device-integrated router (CoDel AQM +
down-bandwidth relay, `tpu.codel.router_drain` fused into
`plane.window_step(router_aqm=True)`) against the CPU plane's actual
`net.router.Router` + `net.relay.Relay` pipeline driven by a miniature
event loop — VERDICT round-2 item #5's criterion.

The CPU side is the real code (`net/router.py`, `net/relay.py`), not a
re-implementation: arrivals call route_incoming_packet + notify, the relay
self-schedules through a task heap, and the sink records forward times.
"""

import heapq

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from shadow_tpu.net.packet import Packet, Protocol
from shadow_tpu.net.relay import Relay
from shadow_tpu.net.router import Router
from shadow_tpu.tpu import codel, plane


class _Sink:
    def __init__(self, address):
        self.address = address
        self.records = []  # (time, src_key, seq)
        self._clock = None

    def get_address(self):
        return self.address

    def push(self, packet):
        self.records.append((self._clock(), packet.src[1], packet.dst[1]))

    def pop(self):
        return None


class _MiniHost:
    """Just enough host for Router + Relay: a task heap and a routing table."""

    def __init__(self, down_bw_bps):
        self.now_ns = 0
        self._heap = []
        self._order = 0
        self.sink = _Sink("10.0.0.1")
        self.sink._clock = lambda: self.now_ns
        self.router = Router("0.0.0.0", lambda p: None, lambda: self.now_ns)
        self.relay = Relay(self, "0.0.0.0", down_bw_bps // 8)

    def get_packet_device(self, addr):
        return self.router if addr == "0.0.0.0" else self.sink

    def schedule_relay_task(self, callback, delay_ns):
        heapq.heappush(self._heap, (self.now_ns + delay_ns, self._order,
                                    callback))
        self._order += 1

    def is_bootstrapping(self):
        return False

    def now(self):
        return self.now_ns

    def schedule_arrival(self, t, packet):
        def arrive(packet=packet):
            self.router.route_incoming_packet(packet)
            self.relay.notify()

        heapq.heappush(self._heap, (t, self._order, arrive))
        self._order += 1

    def run(self):
        while self._heap:
            t, _, cb = heapq.heappop(self._heap)
            assert t >= self.now_ns
            self.now_ns = t
            cb()


def _cpu_reference(arrivals, down_bw_bps):
    """arrivals: list of (t_ns, src_key, seq, payload_bytes) sorted by t."""
    host = _MiniHost(down_bw_bps)
    for t, src_key, seq, payload in arrivals:
        pkt = Packet(Protocol.UDP, ("10.0.0.2", src_key), ("10.0.0.1", seq),
                     payload=b"x" * payload)
        host.schedule_arrival(t, pkt)
    host.run()
    return host.sink.records, host.router._inbound.dropped_count


def _device_run(arrivals, down_bw_bps, window_ns, n_windows,
                ingress_cap=128):
    """Same arrivals through window_step(router_aqm=True), 2 hosts: all
    packets 0 -> 1. Packet sizes on device = the CPU total_size (payload +
    UDP/IP/eth header), arrival = send_rel with zero latency + clamp 0."""
    from shadow_tpu.net.packet import CONFIG_HEADER_SIZE_UDPIPETH

    n = 2
    params = plane.make_params(
        np.zeros((n, n), np.int32), np.zeros((n, n), np.float32),
        np.full(n, 8e12), down_bw_bps=np.full(n, down_bw_bps),
    )
    dn_cap = np.asarray(params.dn_cap)
    state = plane.make_state(
        n, egress_cap=len(arrivals) + 1, ingress_cap=ingress_cap,
        initial_tokens=np.full(n, 2**30, np.int32),
        initial_dn_tokens=dn_cap,
    )
    step = jax.jit(lambda *a: plane.window_step(
        *a, rr_enabled=False, router_aqm=True))

    # arrivals are ingested in the window their (absolute) time falls in,
    # with window-relative send times — the int32 device discipline
    by_window: dict[int, list] = {}
    for t, src_key, seq, payload in arrivals:
        by_window.setdefault(t // window_ns, []).append(
            (t, seq, payload + CONFIG_HEADER_SIZE_UDPIPETH))

    delivered = []
    key = jax.random.PRNGKey(0)
    for w in range(n_windows):
        start = w * window_ns
        # ingest against the state's CURRENT base (the previous window's
        # start): window_step's rebase-by-shift moves these into window w,
        # exactly like DeviceTransport.finish_round -> release
        prev_start = max(0, (w - 1)) * window_ns if w > 0 else 0
        batch = by_window.get(w, [])
        if batch:
            b = len(batch)
            state = plane.ingest(
                state,
                jnp.zeros(b, jnp.int32),  # src host 0
                jnp.ones(b, jnp.int32),  # dst host 1
                jnp.asarray([x[2] for x in batch], jnp.int32),
                jnp.asarray([x[1] for x in batch], jnp.int32),  # prio
                jnp.asarray([x[1] for x in batch], jnp.int32),  # seq
                jnp.zeros(b, bool),
                send_rel=jnp.asarray([x[0] - prev_start for x in batch],
                                     jnp.int32),
                clamp_rel=jnp.zeros(b, jnp.int32),  # no barrier clamp
            )
        shift = jnp.int32(0 if w == 0 else window_ns)
        state, out, _next = step(state, params, key, shift,
                                 jnp.int32(window_ns))
        mask, src, seq, t = jax.device_get(
            (out["mask"], out["src"], out["seq"], out["deliver_rel"]))
        start = w * window_ns
        for i, j in zip(*np.nonzero(mask)):
            delivered.append((start + int(t[i, j]), int(seq[i, j])))
    drops = int(np.asarray(jax.device_get(state.router.dropped))[1])
    return delivered, drops, state


def _compare(arrivals, down_bw_bps, window_ns, n_windows):
    cpu_recs, cpu_drops = _cpu_reference(arrivals, down_bw_bps)
    dev_recs, dev_drops, state = _device_run(arrivals, down_bw_bps,
                                             window_ns, n_windows)
    cpu = sorted((t, seq) for t, _src, seq in cpu_recs)
    dev = sorted(dev_recs)
    assert dev == cpu, (
        f"delivery mismatch: cpu={len(cpu)} dev={len(dev)}\n"
        f"cpu-only={set(cpu) - set(dev)}\ndev-only={set(dev) - set(cpu)}")
    assert dev_drops == cpu_drops
    return state


def test_unconstrained_passthrough():
    """Plenty of bandwidth, spread arrivals: every packet forwards at its
    arrival instant, zero drops."""
    arrivals = [(i * 2_000_000, 7, i, 600) for i in range(20)]
    state = _compare(arrivals, down_bw_bps=100_000_000, window_ns=10_000_000,
                     n_windows=6)
    assert int(np.asarray(state.router.dropped).sum()) == 0


def test_down_bw_queueing_and_codel_drops():
    """A 1 Mbit/s downlink hit with a burst: the relay paces deliveries to
    refill boundaries, standing delay exceeds TARGET, CoDel enters drop
    mode. Multi-window: the burst drains across many windows."""
    # 80 x 628B = ~50 KB burst at t=0..., far above 125 B/ms
    arrivals = [(i * 100_000, 3, i, 600) for i in range(80)]
    state = _compare(arrivals, down_bw_bps=1_000_000, window_ns=20_000_000,
                     n_windows=40)
    assert int(np.asarray(state.router.dropped)[1]) > 0  # CoDel really bit


def test_cached_packet_across_window_boundary():
    """Token exhaustion right before a window ends leaves the packet cached
    in the relay; it must forward at the correct resume time in a LATER
    window, ahead of queued arrivals."""
    arrivals = [(0, 1, 0, 1400), (100_000, 1, 1, 1400), (200_000, 1, 2, 1400),
                (9_900_000, 1, 3, 1400), (25_000_000, 1, 4, 200)]
    _compare(arrivals, down_bw_bps=2_000_000, window_ns=10_000_000,
             n_windows=8)


def test_idle_gaps_reset_standing_delay():
    """Bursts separated by idle gaps: the queue empties between bursts, so
    CoDel's interval tracking restarts (no spurious drops)."""
    arrivals = []
    seq = 0
    for burst in range(4):
        t0 = burst * 150_000_000
        for i in range(10):
            arrivals.append((t0 + i * 50_000, 9, seq, 400))
            seq += 1
    _compare(arrivals, down_bw_bps=5_000_000, window_ns=25_000_000,
             n_windows=30)


def test_long_inbound_idle_then_burst():
    """>2.1 s of inbound-idle sim time then a burst: dn_last_refill must be
    re-anchored during rebasing or it wraps int32 and corrupts the bucket
    (code-review repro: second packet resumed ~1.8 s late)."""
    arrivals = [(0, 1, 0, 1400), (5_000_000, 1, 1, 1400),
                (2_500_000_000, 1, 2, 1400), (2_501_000_000, 1, 3, 1400),
                (2_502_000_000, 1, 4, 1400)]
    _compare(arrivals, down_bw_bps=1_000_000, window_ns=100_000_000,
             n_windows=30)


def test_resume_time_int32_overflow():
    """A slow link blocked late inside a huge window: now + wait exceeds
    int32. The saturated resume must self-correct across windows (fire
    early, fail the conformance re-check, re-block with the remaining
    wait) instead of deadlocking the host's ingress."""
    arrivals = [(0, 1, 0, 1400), (890_000_000, 1, 1, 1400),
                (900_000_000, 1, 2, 1400)]
    _compare(arrivals, down_bw_bps=8_000, window_ns=1_000_000_000,
             n_windows=6)


def test_multi_host_independent_state():
    """Two destination hosts with different rates evolve independent
    router state (vmapped scalars must not bleed across rows)."""
    n = 3
    params = plane.make_params(
        np.zeros((n, n), np.int32), np.zeros((n, n), np.float32),
        np.full(n, 8e12),
        down_bw_bps=np.asarray([8e12, 1_000_000, 100_000_000]),
    )
    state = plane.make_state(
        n, egress_cap=64, ingress_cap=64,
        initial_tokens=np.full(n, 2**30, np.int32),
        initial_dn_tokens=np.asarray(params.dn_cap),
    )
    b = 40
    # 20 packets to each of hosts 1 and 2, same schedule
    src = np.zeros(b, np.int32)
    dst = np.asarray([1, 2] * 20, np.int32)
    t = np.repeat(np.arange(20) * 100_000, 2).astype(np.int32)
    state = plane.ingest(
        state, jnp.asarray(src), jnp.asarray(dst),
        jnp.full(b, 628, jnp.int32), jnp.arange(b, dtype=jnp.int32),
        jnp.arange(b, dtype=jnp.int32), jnp.zeros(b, bool),
        send_rel=jnp.asarray(t), clamp_rel=jnp.zeros(b, jnp.int32),
    )
    step = jax.jit(lambda *a: plane.window_step(
        *a, rr_enabled=False, router_aqm=True))
    key = jax.random.PRNGKey(0)
    window = 50_000_000
    n_h2 = 0
    for w in range(10):
        shift = jnp.int32(0 if w == 0 else window)
        state, out, _ = step(state, params, key, shift, jnp.int32(window))
        mask, t_out = jax.device_get((out["mask"], out["deliver_rel"]))
        n_h2 += int(mask[2].sum())
    drops = np.asarray(jax.device_get(state.router.dropped))
    # the fast host delivered everything instantly, the slow host paced
    # (and possibly dropped); host 0 untouched
    assert n_h2 == 20
    assert drops[0] == 0 and drops[2] == 0
    delivered_h1 = int(np.asarray(jax.device_get(state.n_delivered))[1])
    assert delivered_h1 + int(drops[1]) == 20
