"""TCP-on-TPU bitwise parity: record full event traces from the CPU
TcpConnection (pairs joined by a lossy latency wire) and replay them
through the vectorized device kernel — every next_segment() output, every
write/read return, and the final scalar state must match exactly.
VERDICT round-2 item #4's criterion, at >= 1k concurrent connections.
"""

import heapq
import itertools

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from shadow_tpu.tcp import TcpConnection, TcpError, TcpFlags
from shadow_tpu.tpu import tcp as dtcp

MS = 1_000_000


def u32_bits(x):
    return int(np.int32(np.uint32(x)))


class Recorder:
    def __init__(self):
        self.events = []  # (now_ns, kind, fields[N_FIELDS], expected)

    def add(self, now, kind, fields=(), expected=None):
        f = list(fields) + [0] * (dtcp.N_FIELDS - len(fields))
        self.events.append((now, kind, f, expected))


class RecDeps:
    """FakeDeps + event recording; timer callbacks are classified by
    introspecting the closure (kind from co_names, generation from the
    captured int) so replays can feed the device the same (kind, gen)."""

    def __init__(self, world, rec, seed):
        self.world = world
        self.rec = rec
        self._rng = seed

    def now(self):
        return self.world.time

    def set_timer(self, delay_ns, callback):
        names = callback.__code__.co_names
        if "_on_rto_fire" in names:
            kind = dtcp.EV_TIMER_RTO
        elif "_on_persist_fire" in names:
            kind = dtcp.EV_TIMER_PERSIST
        else:
            kind = dtcp.EV_TIMER_TW
        gen = next(c.cell_contents for c in (callback.__closure__ or ())
                   if isinstance(c.cell_contents, int))
        heapq.heappush(
            self.world.timers,
            (self.world.time + delay_ns, next(self.world.counter),
             self.rec, kind, gen, callback),
        )

    def random_u32(self):
        self._rng = (self._rng * 6364136223846793005
                     + 1442695040888963407) % (1 << 64)
        return self._rng >> 32

    def notify(self):
        pass


def seg_fields(seg):
    blocks = list(seg.sack[:3])
    flat = []
    for ws, we in blocks:
        flat += [u32_bits(ws), u32_bits(we)]
    flat += [0] * (6 - len(flat))
    return [int(seg.flags), u32_bits(seg.seq), u32_bits(seg.ack),
            seg.window, len(seg.payload),
            -1 if seg.window_scale is None else seg.window_scale,
            u32_bits(seg.timestamp), u32_bits(seg.timestamp_echo),
            1 if seg.sack_permitted else 0, len(blocks), *flat]


class RecordedConn:
    """A TcpConnection plus its event trace."""

    def __init__(self, world, seed, config=None):
        self.rec = Recorder()
        self.deps = RecDeps(world, self.rec, seed)
        self.conn = TcpConnection(self.deps, config)
        self.world = world

    def open_active(self):
        # record the ISS the CPU machine draws
        iss_preview = RecDeps(self.world, None, self.deps._rng).random_u32()
        self.conn.open_active()
        assert self.conn.iss == iss_preview & 0xFFFFFFFF
        self.rec.add(self.world.time, dtcp.EV_OPEN_ACTIVE,
                     [u32_bits(self.conn.iss)])

    def open_passive(self, syn):
        self.conn.open_passive(syn)
        self.rec.add(
            self.world.time, dtcp.EV_OPEN_PASSIVE,
            [u32_bits(self.conn.iss), u32_bits(syn.seq), syn.window,
             -1 if syn.window_scale is None else syn.window_scale,
             u32_bits(syn.timestamp), u32_bits(syn.timestamp_echo),
             1 if syn.sack_permitted else 0])

    def write(self, n):
        try:
            ret = self.conn.write(b"x" * n)
        except TcpError as e:
            ret = -e.errno
        self.rec.add(self.world.time, dtcp.EV_WRITE, [n], ret)
        return ret

    def read(self, n):
        try:
            ret = len(self.conn.read(n))
        except TcpError as e:
            ret = -e.errno
        self.rec.add(self.world.time, dtcp.EV_READ, [n], ret)
        return ret

    def close(self):
        self.conn.close()
        self.rec.add(self.world.time, dtcp.EV_CLOSE)

    def abort(self):
        self.conn.abort()
        self.rec.add(self.world.time, dtcp.EV_ABORT)

    def on_segment(self, seg):
        self.rec.add(self.world.time, dtcp.EV_SEG, seg_fields(seg))
        self.conn.on_segment(seg)

    def pull(self):
        seg = self.conn.next_segment()
        expected = None
        if seg is not None:
            sf = seg_fields(seg)
            # device out layout: 8 base fields, retx flag, then the SACK
            # tail (sack_permitted, nsack, 3 blocks)
            expected = sf[:8] + [
                1 if self.conn.last_segment_retransmit else 0] + sf[8:]
        self.rec.add(self.world.time, dtcp.EV_PULL, [], expected)
        return seg


class Wire:
    """Two recorded connections joined by a latency wire with scripted
    data-segment drops (a->b)."""

    def __init__(self, latency_ns=1 * MS, seed=1234, drop_at=(),
                 config=None):
        self.time = 0
        self.timers = []
        self.counter = itertools.count()
        self.latency = latency_ns
        self.in_flight = []
        self.a = RecordedConn(self, seed, config)
        self.b = RecordedConn(self, seed + 77, config)
        self.drop_at = set(drop_at)  # indices of a->b data segments to drop
        self._a_data_segs = 0

    def _pump(self, rc, peer):
        seg = rc.pull()
        if seg is None:
            return False
        if rc is self.a and seg.payload:
            idx = self._a_data_segs
            self._a_data_segs += 1
            if idx in self.drop_at:
                return True
        heapq.heappush(self.in_flight,
                       (self.time + self.latency, next(self.counter),
                        peer, seg))
        return True

    def run(self, until_ns, app=None, max_iters=200_000):
        for _ in range(max_iters):
            if app is not None:
                app(self)
            progressed = False
            while self._pump(self.a, self.b):
                progressed = True
            while self._pump(self.b, self.a):
                progressed = True
            if progressed:
                continue
            nxt = []
            if self.in_flight:
                nxt.append(self.in_flight[0][0])
            if self.timers:
                nxt.append(self.timers[0][0])
            if not nxt or min(nxt) > until_ns:
                self.time = until_ns
                return
            self.time = min(nxt)
            while self.in_flight and self.in_flight[0][0] <= self.time:
                _, _, dst, seg = heapq.heappop(self.in_flight)
                dst.on_segment(seg)
            while self.timers and self.timers[0][0] <= self.time:
                _, _, rec, kind, gen, cb = heapq.heappop(self.timers)
                rec.add(self.time, kind, [gen])
                cb()
        raise AssertionError("wire did not converge")


def transfer_scenario(latency_ns, seed, size, chunk, drop_at=(),
                      abort_at_ns=None, b_writes=0, config=None):
    """One end-to-end life: handshake, a->b transfer (+ optional b->a),
    loss, orderly close (or abort). Returns the two RecordedConns."""
    w = Wire(latency_ns=latency_ns, seed=seed, drop_at=drop_at,
             config=config)
    w.a.open_active()
    syn = w.a.pull()
    assert syn is not None and syn.flags & TcpFlags.SYN
    w.time += w.latency  # the SYN travels the wire by hand
    w.b.open_passive(syn)

    progress = {"written": 0, "b_written": 0, "a_closed": False,
                "b_closed": False, "aborted": False}

    def app(wire):
        t = wire.time
        if abort_at_ns is not None and t >= abort_at_ns \
                and not progress["aborted"]:
            progress["aborted"] = True
            wire.a.abort()
            return
        if progress["aborted"]:
            # peer drains and closes after the reset surfaces
            if wire.b.conn.readable_bytes():
                wire.b.read(1 << 20)
            return
        a, b = wire.a, wire.b
        if a.conn.is_established() and progress["written"] < size:
            n = a.write(min(chunk, size - progress["written"]))
            if n > 0:
                progress["written"] += n
        if b.conn.is_established() and progress["b_written"] < b_writes:
            n = b.write(min(chunk, b_writes - progress["b_written"]))
            if n > 0:
                progress["b_written"] += n
        if b.conn.readable_bytes():
            b.read(1 << 20)
        if a.conn.readable_bytes():
            a.read(1 << 20)
        if (progress["written"] >= size and not progress["a_closed"]
                and a.conn.is_established()):
            progress["a_closed"] = True
            a.close()
        if (b.conn.at_eof() and not progress["b_closed"]
                and progress["b_written"] >= b_writes
                and b.conn.state != 0):
            progress["b_closed"] = True
            b.close()

    w.run(90_000 * MS, app=app)
    return w.a, w.b


def replay_and_compare(recorded, sack=True):
    """Replay every connection's trace on device; assert all PULL outputs,
    write/read returns, and final states match the CPU machines."""
    C = len(recorded)
    T = max(len(rc.rec.events) for rc in recorded)
    kinds = np.zeros((C, T), np.int32)
    fields = np.zeros((C, T, dtcp.N_FIELDS), np.int32)
    now_ms = np.zeros((C, T), np.int32)
    for i, rc in enumerate(recorded):
        for j, (t, kind, f, _exp) in enumerate(rc.rec.events):
            kinds[i, j] = kind
            fields[i, j] = f
            now_ms[i, j] = t // MS

    plane = dtcp.make_tcp_plane(C, sack=sack)
    replay = jax.jit(dtcp.tcp_replay)
    plane, outs, rets = replay(plane, jnp.asarray(kinds),
                               jnp.asarray(fields), jnp.asarray(now_ms))
    outs = np.asarray(jax.device_get(outs))  # [T, C, 18]
    rets = np.asarray(jax.device_get(rets))  # [T, C]

    mismatches = []
    for i, rc in enumerate(recorded):
        for j, (t, kind, f, exp) in enumerate(rc.rec.events):
            if kind == dtcp.EV_PULL:
                got = outs[j, i]
                if exp is None:
                    if got[0] != 0:
                        mismatches.append((i, j, "pull none", got.tolist()))
                else:
                    want = [1] + exp
                    if got.tolist() != want:
                        mismatches.append((i, j, want, got.tolist()))
            elif kind in (dtcp.EV_WRITE, dtcp.EV_READ):
                if int(rets[j, i]) != exp:
                    mismatches.append((i, j, ("ret", exp), int(rets[j, i])))
            if len(mismatches) > 5:
                break
        if len(mismatches) > 5:
            break
    assert not mismatches, mismatches[:5]

    # final-state comparison
    dev = jax.device_get(plane)
    bad = []
    for i, rc in enumerate(recorded):
        c = rc.conn
        want = {
            "state": int(c.state), "error": c.error or 0,
            "snd_una": c.snd_una, "snd_nxt": c.snd_nxt,
            "snd_wnd": c.snd_wnd, "stream_len": c.stream_len,
            "snd_max": c.snd_max, "rcv_nxt": c.rcv_nxt,
            "ordered_bytes": c._ordered_bytes,
            "reass_bytes": c._reassembly.byte_count(),
            "fin_requested": c.fin_requested, "fin_sent": c.fin_sent,
            "fin_acked": c.fin_acked, "fin_received": c.fin_received,
            "cwnd": c.cong.cwnd, "ssthresh": c.cong.ssthresh,
            "phase": c.cong.phase, "dup_acks": c.cong.dup_acks,
            "avoid_acked": c.cong._avoid_acked,
            "srtt_ms": c.rtt.srtt_ms, "rttvar_ms": c.rtt.rttvar_ms,
            "rto_ms": c.rtt.rto_ms, "backoff_count": c.rtt.backoff_count,
            "retransmit_count": c.retransmit_count,
            "retransmitted_bytes": c.retransmitted_bytes,
            "sack_ok": c._sack_ok,
            "sacked": sorted((a, b) for a, b in zip(c._sacked.s,
                                                    c._sacked.e) if b > a),
            "rto_gen": c._rto_gen, "persist_gen": c._persist_gen,
            "rto_armed": c._rto_armed, "persist_armed": c._persist_armed,
            "iss": u32_bits(c.iss), "irs": u32_bits(c.irs),
        }
        got = {
            "state": int(dev.state[i]), "error": int(dev.error[i]),
            "snd_una": int(dev.snd_una[i]), "snd_nxt": int(dev.snd_nxt[i]),
            "snd_wnd": int(dev.snd_wnd[i]),
            "stream_len": int(dev.stream_len[i]),
            "snd_max": int(dev.snd_max[i]), "rcv_nxt": int(dev.rcv_nxt[i]),
            "ordered_bytes": int(dev.ordered_bytes[i]),
            "reass_bytes": int(dev.reass_bytes[i]),
            "fin_requested": bool(dev.fin_requested[i]),
            "fin_sent": bool(dev.fin_sent[i]),
            "fin_acked": bool(dev.fin_acked[i]),
            "fin_received": bool(dev.fin_received[i]),
            "cwnd": int(dev.cwnd[i]), "ssthresh": int(dev.ssthresh[i]),
            "phase": int(dev.phase[i]), "dup_acks": int(dev.dup_acks[i]),
            "avoid_acked": int(dev.avoid_acked[i]),
            "srtt_ms": int(dev.srtt_ms[i]),
            "rttvar_ms": int(dev.rttvar_ms[i]),
            "rto_ms": int(dev.rto_ms[i]),
            "backoff_count": int(dev.backoff_count[i]),
            "retransmit_count": int(dev.retransmit_count[i]),
            "retransmitted_bytes": int(dev.retransmitted_bytes[i]),
            "sack_ok": bool(dev.sack_ok[i]),
            "sacked": sorted(
                (int(a), int(b)) for a, b in zip(dev.sacked_s[i],
                                                 dev.sacked_e[i]) if b > a),
            "rto_gen": int(dev.rto_gen[i]),
            "persist_gen": int(dev.persist_gen[i]),
            "rto_armed": bool(dev.rto_armed[i]),
            "persist_armed": bool(dev.persist_armed[i]),
            "iss": int(np.int32(np.uint32(dev.iss[i]))),
            "irs": int(np.int32(np.uint32(dev.irs[i]))),
        }
        diff = {k: (want[k], got[k]) for k in want if want[k] != got[k]}
        if diff:
            bad.append((i, diff))
        if len(bad) > 3:
            break
    assert not bad, bad[:3]


@pytest.mark.slow  # full transfer sim (~13s); stays GATING in CI's
# tier-1-overflow unfiltered step
def test_clean_transfer_pair():
    a, b = transfer_scenario(1 * MS, 1, size=200_000, chunk=8192)
    assert a.conn.state in (0, 8)  # CLOSED or TIME_WAIT
    replay_and_compare([a, b])


def test_lossy_transfer_pair():
    a, b = transfer_scenario(2 * MS, 3, size=300_000, chunk=16384,
                             drop_at=(5, 6, 40, 41, 42, 90))
    assert a.conn.retransmit_count > 0
    replay_and_compare([a, b])


def test_abort_pair():
    a, b = transfer_scenario(1 * MS, 9, size=50_000, chunk=4096,
                             abort_at_ns=30 * MS)
    replay_and_compare([a, b])


@pytest.mark.slow  # bidirectional transfer sim (~12s); stays GATING in
# CI's tier-1-overflow unfiltered step
def test_bidirectional_pair():
    a, b = transfer_scenario(3 * MS, 21, size=60_000, chunk=8192,
                             b_writes=40_000)
    replay_and_compare([a, b])


def test_rto_deadline_array_matches_timer_schedule():
    """The device's per-connection RTO deadline array must equal the ms
    time the CPU timer actually fires at (valid generations only)."""
    a, b = transfer_scenario(2 * MS, 5, size=40_000, chunk=8192,
                             drop_at=(1, 2, 3, 4, 5, 6, 7, 8))
    # replay a's trace step by step; whenever a gen-valid RTO fire event
    # arrives, the deadline recorded on device must equal its time
    rc = a
    C = 1
    plane = dtcp.make_tcp_plane(C)
    step = jax.jit(dtcp.tcp_event_step)
    checked = 0
    for (t, kind, f, _e) in rc.rec.events:
        if kind == dtcp.EV_TIMER_RTO:
            gen_ok = int(plane.rto_gen[0]) == f[0]
            if gen_ok and bool(plane.rto_armed[0]):
                assert int(plane.rto_deadline_ms[0]) == t // MS
                checked += 1
        plane, _o, _r = step(
            plane, jnp.asarray([kind], jnp.int32),
            jnp.asarray([f], jnp.int32),
            jnp.asarray([t // MS], jnp.int32))
    assert checked > 0  # the scenario really exercised RTO fires


def test_tracker_retransmitted_counter_on_lossy_link():
    """End-to-end through the Manager: a tgen transfer over a lossy link
    must surface SACK-era retransmissions in the tracker's `retransmitted`
    counter (stamped via SND_TCP_RETRANSMITTED at the socket wrapper) —
    the VERDICT #10 validation criterion."""
    from shadow_tpu.core.config import load_config_str
    from shadow_tpu.core.manager import Manager

    cfg = load_config_str("""
general: {stop_time: 60s, seed: 31}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "50 Mbit" host_bandwidth_down "50 Mbit" ]
        edge [ source 0 target 0 latency "20 ms" packet_loss 0.02 ]
      ]
hosts:
  server:
    network_node_id: 0
    processes:
    - {path: tgen-server, args: ['8888'], start_time: 1s,
       expected_final_state: running}
  client:
    network_node_id: 0
    processes:
    - {path: tgen-client, args: ['server', '8888', '524288', '1'],
       start_time: 2s}
""")
    mgr = Manager(cfg)
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    retrans = sum(t.counters.retransmitted for t in mgr.trackers.values())
    assert retrans > 0, "2% loss on 360 packets must retransmit something"
    # SACK must have actually negotiated over the REAL packet path (the
    # header carries sack_permitted + blocks), not just in unit harnesses
    sack_conns = [
        sock for host in mgr.hosts
        for iface in (host.netns.localhost, host.netns.internet)
        for sock in iface._associations.values()
        if getattr(getattr(sock, "conn", None), "_sack_ok", False)
    ]
    assert sack_conns, "no socket negotiated SACK through the packet layer"


@pytest.mark.slow
def test_thousand_connections_bitwise():
    """>= 1k concurrent connections (512 pairs), randomized scenarios:
    sizes, chunks, latencies, loss bursts, aborts, bidirectional traffic —
    one device replay kernel, bitwise outputs + state."""
    rng = np.random.default_rng(42)
    recorded = []
    for p in range(512):
        size = int(rng.integers(2_000, 120_000))
        chunk = int(rng.choice([1460, 4096, 8192, 16384]))
        latency = int(rng.integers(1, 8)) * MS
        drops = ()
        if p % 3 == 0:
            start = int(rng.integers(0, 30))
            drops = tuple(range(start, start + int(rng.integers(1, 4))))
        abort_at = 25 * MS if p % 17 == 0 else None
        b_writes = int(rng.integers(0, 30_000)) if p % 5 == 0 else 0
        a, b = transfer_scenario(latency, 1000 + p, size=size, chunk=chunk,
                                 drop_at=drops, abort_at_ns=abort_at,
                                 b_writes=b_writes)
        recorded.extend([a, b])
    assert len(recorded) == 1024
    replay_and_compare(recorded)


@pytest.mark.slow  # SACK-on/off twin transfers (~15s); stays GATING in
# CI's tier-1-overflow unfiltered step
def test_sack_disabled_parity():
    """With TcpConfig(sack=False) the device must mirror the CPU machine
    bitwise too: no sack_permitted on SYNs, no SACK blocks, go-back-N
    recovery — the config gate is per-connection state (`sack_on`), not a
    baked-in constant."""
    from shadow_tpu.tcp.connection import TcpConfig

    a, b = transfer_scenario(2 * MS, 91, size=40_000, chunk=8192,
                             drop_at=(1, 3, 4), config=TcpConfig(sack=False))
    assert not a.conn._sack_ok and not b.conn._sack_ok
    replay_and_compare([a, b], sack=False)


def test_reass_insert_bridging_segment_no_double_count():
    """A segment bridging two disjoint reassembly ranges must merge them
    into ONE slot with exact coverage bytes — the pre-fix extend-on-touch
    grew the first slot across the second and left the second's bytes
    double-counted in reass_bytes until the next drain (advisor r5
    finding)."""
    plane0 = dtcp.make_tcp_plane(1, reass_slots=4)
    s = jax.tree.map(lambda x: x[0], plane0)
    s = dtcp._reass_insert(s, jnp.int32(100), jnp.int32(10))  # [100,110)
    s = dtcp._reass_insert(s, jnp.int32(120), jnp.int32(10))  # [120,130)
    assert int(s.reass_bytes) == 20
    s = dtcp._reass_insert(s, jnp.int32(108), jnp.int32(14))  # [108,122)
    assert int(s.reass_bytes) == 30  # [100,130) exactly once, not 32
    live = np.asarray(s.reass_len) > 0
    assert int(live.sum()) == 1
    slot = int(np.argmax(live))
    assert int(s.reass_off[slot]) == 100
    assert int(s.reass_len[slot]) == 30


def test_reass_insert_bridge_covering_second_range_entirely():
    """Bridging segment that fully covers the later range: the covered
    slot must be cleared (freed), not left to linger until drain."""
    plane0 = dtcp.make_tcp_plane(1, reass_slots=4)
    s = jax.tree.map(lambda x: x[0], plane0)
    s = dtcp._reass_insert(s, jnp.int32(100), jnp.int32(10))  # [100,110)
    s = dtcp._reass_insert(s, jnp.int32(120), jnp.int32(10))  # [120,130)
    s = dtcp._reass_insert(s, jnp.int32(105), jnp.int32(30))  # [105,135)
    assert int(s.reass_bytes) == 35  # [100,135)
    assert int((np.asarray(s.reass_len) > 0).sum()) == 1
