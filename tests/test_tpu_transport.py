"""Device-transport integration: live simulations with inter-host packet
motion on the device plane, bitwise-matching the CPU transport.

Parity model: this replaces `Worker::send_packet`'s cross-host push
(`worker.rs:326-410,629-639`) with one device round trip per scheduling
round; the round-1 verdict's top item ("wire the TPU plane into the
simulation loop; done = identical event order to the CPU plane").
"""

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager
from shadow_tpu.net import packet as packet_mod

BASIC = """
general: {{stop_time: 60s, seed: 1}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{use_tpu_transport: {device}}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: http-server, args: ["80", "1048576"], start_time: 3s,
       expected_final_state: running}}
  client1:
    network_node_id: 0
    processes:
    - {{path: http-client, args: ["server", "80"], start_time: 5s}}
  client2:
    network_node_id: 0
    processes:
    - {{path: http-client, args: ["server", "80"], start_time: 5s}}
"""

PHOLD = """
general: {{stop_time: 20s, seed: 42}}
network: {{graph: {{type: 1_gbit_switch}}}}
experimental: {{use_tpu_transport: {device}}}
hosts:
  peer0:
    network_node_id: 0
    processes:
    - {{path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
    - {{path: udp-client, args: ["peer1", "9000", "200", "10"], start_time: 2s}}
  peer1:
    network_node_id: 0
    processes:
    - {{path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
    - {{path: udp-client, args: ["peer2", "9000", "200", "10"], start_time: 2s}}
  peer2:
    network_node_id: 0
    processes:
    - {{path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
    - {{path: udp-client, args: ["peer0", "9000", "200", "10"], start_time: 2s}}
"""

LOSSY = """
general: {{stop_time: 60s, seed: 7}}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "20 ms" packet_loss 0.05 ]
      ]
experimental: {{use_tpu_transport: {device}}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: http-server, args: ["80", "262144"], start_time: 1s,
       expected_final_state: running}}
  client:
    network_node_id: 0
    processes:
    - {{path: http-client, args: ["server", "80"], start_time: 2s}}
"""


def _run_traced(cfg_text: str, mode: str | None = None):
    """Run a config collecting the full packet-status event stream — a
    complete witness of packet event order and timing."""
    trace = []

    def hook(packet, status):
        from shadow_tpu.core import worker as worker_mod

        host = worker_mod.current_host()
        trace.append((
            host.name if host else None,
            host.now() if host else -1,
            int(status), packet.src, packet.dst, packet.payload_size(),
        ))

    if mode is not None:
        # the experimental block is inline in these configs: splice the
        # mode into it
        assert "use_tpu_transport: true" in cfg_text
        cfg_text = cfg_text.replace(
            "use_tpu_transport: true",
            f"use_tpu_transport: true, tpu_transport_mode: {mode}")
    cfg = load_config_str(cfg_text)
    mgr = Manager(cfg)
    old = packet_mod.status_trace_hook
    packet_mod.status_trace_hook = hook
    try:
        stats = mgr.run()
    finally:
        packet_mod.status_trace_hook = old
    assert stats.process_failures == [], stats.process_failures
    return stats, trace, mgr


@pytest.mark.parametrize("mode", ["sync", "mirrored"])
@pytest.mark.parametrize("cfg", [BASIC, PHOLD, LOSSY],
                         ids=["basic-file-transfer", "phold", "lossy"])
def test_device_transport_matches_cpu_bitwise(cfg, mode):
    s_cpu, t_cpu, _ = _run_traced(cfg.format(device="false"))
    s_dev, t_dev, mgr = _run_traced(cfg.format(device="true"), mode=mode)
    assert s_cpu.packets_sent == s_dev.packets_sent
    assert s_cpu.packets_dropped == s_dev.packets_dropped
    assert len(t_cpu) == len(t_dev)
    # bitwise-identical packet event stream: every status transition on
    # every host at the same simulated time in the same order
    for i, (a, b) in enumerate(zip(t_cpu, t_dev)):
        assert a == b, f"trace diverges at index {i}: cpu={a} device={b}"
    if mode == "mirrored":
        # the async device pipeline verified every window against the CPU
        # ledger and found no divergence
        t = mgr.transport
        assert t.divergence_count == 0
        assert t.verified_windows > 0
        assert t.verified_packets > 0
        assert t.in_flight == 0  # every tag came back and was freed


def test_device_transport_deterministic_across_runs():
    s1, t1, _ = _run_traced(PHOLD.format(device="true"))
    s2, t2, _ = _run_traced(PHOLD.format(device="true"))
    assert t1 == t2
    assert (s1.rounds, s1.packets_sent) == (s2.rounds, s2.packets_sent)


def test_mirrored_detects_divergence():
    """The on-device verification is live: corrupt one expected deliver
    time before upload and the device divergence counter must move."""
    cfg = load_config_str(
        PHOLD.format(device="true").replace(
            "use_tpu_transport: true",
            "use_tpu_transport: true, tpu_transport_mode: mirrored"))
    mgr = Manager(cfg)
    t = mgr.transport
    orig = t._pop_expected
    poisoned = {"done": False}

    def poison(end_ns):
        expected = orig(end_ns)
        if not poisoned["done"] and expected:
            deliver, tag, dst = expected[0]
            expected[0] = (deliver + 1, tag, dst)  # ledger off by 1 ns
            poisoned["done"] = True
        return expected

    t._pop_expected = poison
    stats = mgr.run()
    assert poisoned["done"], "no window with expected deliveries seen"
    assert t.divergence_count >= 1
    # divergence is a correctness gate: the RUN must fail (nonzero CLI
    # exit comes from process_failures), not just tick a counter
    assert any(name == "device-transport" and "diverged" in why
               for name, why in stats.process_failures), \
        stats.process_failures


def test_mirrored_survives_sparse_window_gaps():
    """Windows driven by far-apart events (seconds of idle sim time
    between rounds) must not overflow the int32 device shift: records
    pending flush used to pin in_flight > 0, blocking the base teleport,
    and the next record's shift wrapped (review r4 finding). The late
    second client makes the controller jump ~50 simulated seconds after
    the first exchange completes."""
    cfg = load_config_str("""
general: {stop_time: 60s, seed: 9}
network: {graph: {type: 1_gbit_switch}}
experimental: {use_tpu_transport: true, tpu_transport_mode: mirrored}
hosts:
  server:
    network_node_id: 0
    processes:
    - {path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}
  early:
    network_node_id: 0
    processes:
    - {path: udp-client, args: ["server", "9000", "100", "3"], start_time: 2s}
  late:
    network_node_id: 0
    processes:
    - {path: udp-client, args: ["server", "9000", "100", "3"], start_time: 55s}
""")
    mgr = Manager(cfg)
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    assert mgr.transport.divergence_count == 0
    assert mgr.transport.verified_windows > 0


DYNAMIC_RUNAHEAD = """
general: {{stop_time: 60s, seed: 13}}
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [ id 0 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        node [ id 1 host_bandwidth_up "100 Mbit" host_bandwidth_down "100 Mbit" ]
        edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
        edge [ source 0 target 1 latency "80 ms" packet_loss 0.01 ]
        edge [ source 1 target 1 latency "5 ms" packet_loss 0.0 ]
      ]
experimental: {{use_tpu_transport: {device}, use_dynamic_runahead: true}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: http-server, args: ["80", "262144"], start_time: 1s,
       expected_final_state: running}}
  farclient:
    network_node_id: 0
    processes:
    - {{path: http-client, args: ["server", "80"], start_time: 2s}}
  nearserver:
    network_node_id: 1
    processes:
    - {{path: udp-echo-server, args: ["9000"], start_time: 1s,
       expected_final_state: running}}
  nearclient:
    network_node_id: 1
    processes:
    - {{path: udp-client, args: ["nearserver", "9000", "100", "8"],
       start_time: 30s}}
"""


@pytest.mark.parametrize("mode", ["sync", "mirrored"])
def test_dynamic_runahead_transport_parity(mode):
    """VERDICT r3 weak #7: with use_dynamic_runahead, the runahead (and
    therefore every window boundary) SHRINKS mid-run — the first half
    uses only 50-80 ms paths, then at t=30s a 5 ms intra-node path comes
    into use and windows tighten 10x. The device transport (which chains
    windows under the constant-runahead-while-idle assumption in sync
    mode, and replays recorded boundaries in mirrored mode) must stay
    bitwise-identical to CPU transport across the shift."""
    s_cpu, t_cpu, mgr_cpu = _run_traced(DYNAMIC_RUNAHEAD.format(device="false"))
    s_dev, t_dev, mgr_dev = _run_traced(DYNAMIC_RUNAHEAD.format(device="true"),
                                        mode=mode)
    # the scenario actually exercised a runahead change
    assert mgr_cpu.runahead.get() < 50_000_000
    assert s_cpu.packets_sent == s_dev.packets_sent
    assert s_cpu.packets_dropped == s_dev.packets_dropped
    assert len(t_cpu) == len(t_dev)
    for i, (a, b) in enumerate(zip(t_cpu, t_dev)):
        assert a == b, f"trace diverges at index {i}: cpu={a} device={b}"
    if mode == "mirrored":
        assert mgr_dev.transport.divergence_count == 0
