"""shadowscope run-ledger contract pins.

Pins every clause of telemetry/tracer.py's contract
(docs/observability.md "Run ledger"):

- **Schema drift guard.** `RUNLEDGER_SCHEMA` and the span-record field
  set (`SPAN_FIELDS`) are pinned verbatim: any field change must bump
  the version or fail here, and `read_ledger` refuses a ledger stamped
  with a different schema rather than mis-attributing fields.
- **Presence invisibility.** A traced `run_scenario` returns a record
  byte-identical to the untraced run — golden tuple AND full record
  surface — on a lossy corpus entry and on a faulted run (the SL501
  discipline, enforced by parity rather than a jaxpr taint proof:
  the tracer has no device surface). CI's trace-parity gate runs the
  FULL corpus with `--trace --check` against the unchanged golden
  file; the @slow cases here are its unfiltered pytest half.
- **One artifact, two spellings.** The ledger's folded memo record is
  the SAME dict the scenario record (and so `--memo-report`)
  publishes; `memo_view` is a filtered view, not a second measurement.
- **Chrome trace well-formedness.** The exported trace is valid JSON,
  every driver child slice nests inside its parent span slice, and
  both clock tracks are named in `otherData.clocks`.
- **Ensemble percentile-of-percentiles.** `histo.ensemble_percentiles`
  matches a hand-computed 2-world case (median averages the pair) and
  emits min/median/max error bars for a 4-world run.
"""

from __future__ import annotations

import json

import pytest

jax = pytest.importorskip("jax")

from shadow_tpu.telemetry import histo, tracer  # noqa: E402

N = 8


# ---------------------------------------------------------------------------
# schema drift guard


def test_schema_version_pinned():
    assert tracer.RUNLEDGER_SCHEMA == "runledger-v1"
    assert tracer.SPAN_FIELDS == (
        "kind", "seq", "r0", "r1", "windows", "mode", "wall_t0_ms",
        "wall_ms", "dispatch_ms", "memo_ms", "hook_ms")
    assert tracer.WALL_FIELDS == frozenset(
        {"wall_t0_ms", "wall_ms", "dispatch_ms", "memo_ms", "hook_ms"})
    assert tracer.SPAN_MODES == ("execute", "replay", "ffwd", "ensemble")


def test_span_record_fields_match_pin():
    t = tracer.RunTracer("pin", backend={"platform": "cpu",
                                         "device_kind": "cpu"})
    rec = t.span(0, 4, mode="execute", t0=t.clock())
    assert tuple(rec.keys()) == tracer.SPAN_FIELDS
    # optional fields ride AFTER the pinned prefix
    rec2 = t.span(4, 8, mode="execute", t0=t.clock(),
                  growth=[{"kind": "capacity-growth"}], span_salt="ab")
    assert tuple(rec2.keys())[:len(tracer.SPAN_FIELDS)] == \
        tracer.SPAN_FIELDS


def test_read_ledger_refuses_schema_drift(tmp_path):
    t = tracer.RunTracer("rt", backend={"platform": "cpu",
                                        "device_kind": "cpu"})
    t.span(0, 2, mode="execute", t0=t.clock())
    t.close()
    path = tmp_path / "run.ledger.jsonl"
    t.write(str(path))
    records = tracer.load_ledger(str(path))
    assert [r["kind"] for r in records] == ["meta", "span", "end"]

    lines = path.read_text().splitlines()
    head = json.loads(lines[0])
    head["schema"] = "runledger-v999"
    with pytest.raises(ValueError, match="schema mismatch"):
        tracer.read_ledger([json.dumps(head)] + lines[1:])
    with pytest.raises(ValueError, match="meta"):
        tracer.read_ledger(lines[1:])  # headless ledger refuses too


def test_phase_totals_attribution():
    t = tracer.RunTracer("pt", backend={"platform": "cpu",
                                        "device_kind": "cpu"})
    t0 = t.clock()
    t.span(0, 4, mode="execute", t0=t0, dispatch_ms=2.0, memo_ms=0.5,
           hook_ms=0.25)
    t.span(4, 8, mode="replay", t0=t0, hook_ms=0.25)
    t.span(8, 16, mode="ffwd", t0=t0)
    t.close()
    ph = tracer.phase_totals(t.records)
    assert ph["spans"] == 3
    assert ph["windows"] == 16
    assert ph["dispatch_ms"] == 2.0
    assert ph["memo_ms"] == 0.5
    assert ph["hook_ms"] == 0.5
    assert ph["execute_spans"] == 1
    assert ph["replay_spans"] == 1
    assert ph["ffwd_spans"] == 1
    assert ph["ensemble_spans"] == 0
    assert "run_wall_ms" in ph


# ---------------------------------------------------------------------------
# chrome-trace export: valid JSON, nested driver slices, named clocks


def _synthetic_ledger():
    return [
        {"kind": "meta", "schema": tracer.RUNLEDGER_SCHEMA,
         "label": "synthetic",
         "backend": {"platform": "cpu", "device_kind": "cpu"}},
        {"kind": "span", "seq": 0, "r0": 0, "r1": 8, "windows": 8,
         "mode": "execute", "wall_t0_ms": 0.0, "wall_ms": 10.0,
         "dispatch_ms": 6.0, "memo_ms": 1.0, "hook_ms": 2.0,
         "growth": [{"kind": "capacity-growth", "ring": "egress"}]},
        {"kind": "harvest", "wall_t0_ms": 10.5, "r": 8},
        {"kind": "span", "seq": 1, "r0": 8, "r1": 16, "windows": 8,
         "mode": "replay", "wall_t0_ms": 11.0, "wall_ms": 1.0,
         "dispatch_ms": 0.0, "memo_ms": 0.0, "hook_ms": 0.5},
        {"kind": "end", "wall_ms": 12.5, "spans": 2, "windows": 16},
    ]


def test_chrome_trace_valid_and_nested(tmp_path):
    out = tmp_path / "trace.json"
    info = tracer.write_chrome_trace(_synthetic_ledger(), str(out))
    trace = json.loads(out.read_text())  # valid JSON or this raises
    assert info["events"] == len(trace["traceEvents"])
    clocks = trace["otherData"]["clocks"]
    assert "driver (wall time)" in clocks
    assert "simulation (virtual time)" in clocks

    slices = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    parents = [e for e in slices if e["name"].startswith(
        ("execute", "replay", "ffwd", "ensemble"))]
    children = [e for e in slices if e not in parents]
    assert len(parents) == 2
    assert children, "wall split must render as child slices"
    eps = 1e-6
    for child in children:
        assert any(
            p["ts"] - eps <= child["ts"] and
            child["ts"] + child["dur"] <= p["ts"] + p["dur"] + eps
            for p in parents), (child, parents)
    instants = [e for e in trace["traceEvents"] if e.get("ph") == "i"]
    assert any(e["name"] == "harvest" for e in instants)
    # every driver row stays off the simulation pids
    for e in slices + instants:
        assert e["pid"] == tracer.DRIVER_PID


def test_chrome_trace_merges_sim_rows(tmp_path):
    heartbeats = [
        {"type": "sim", "time_ns": 1_000, "windows": 1, "events": 3},
        {"type": "host", "time_ns": 1_000, "host_id": 0,
         "host": "h0", "counters": {"bytes_out": 64, "bytes_in": 0}},
    ]
    out = tmp_path / "merged.json"
    tracer.write_chrome_trace(_synthetic_ledger(), str(out),
                              heartbeats=heartbeats)
    trace = json.loads(out.read_text())
    pids = {e["pid"] for e in trace["traceEvents"]}
    assert tracer.DRIVER_PID in pids
    assert len(pids) > 1, "simulation rows must merge beside the driver"


# ---------------------------------------------------------------------------
# ensemble percentile of percentiles


def test_ensemble_percentiles_hand_computed_two_worlds():
    # bucket upper edge is 2^(i+1):
    # world A: all mass in bucket 3 -> every percentile = 16
    # world B: all mass in bucket 5 -> every percentile = 64
    a = [0] * histo.HIST_BUCKETS
    a[3] = 10
    b = [0] * histo.HIST_BUCKETS
    b[5] = 10
    assert histo.percentiles(a)["p50"] == 16
    assert histo.percentiles(b)["p50"] == 64
    pp = histo.ensemble_percentiles([a, b])
    for q in ("p50", "p90", "p99", "p999"):
        # median of a 2-world ensemble averages the pair: (16+64)/2
        assert pp[q] == {"min": 16, "median": 40.0, "max": 64,
                         "worlds": 2}, q


def test_ensemble_percentiles_four_world_error_bars():
    worlds = []
    for shift in range(4):
        counts = [0] * histo.HIST_BUCKETS
        counts[4 + shift] = 100
        worlds.append(counts)
    pp = histo.ensemble_percentiles(worlds)
    bars = pp["p50"]
    assert bars["worlds"] == 4
    assert bars["min"] == 32 and bars["max"] == 256
    assert bars["min"] <= bars["median"] <= bars["max"]


def test_ensemble_percentiles_refuses_empty():
    with pytest.raises(ValueError):
        histo.ensemble_percentiles([])


def test_telemetry_report_ensemble_cli(tmp_path, capsys):
    import tools.telemetry_report as tr

    paths = []
    for w in range(4):
        counts = [0] * histo.HIST_BUCKETS
        counts[4 + w] = 100
        path = tmp_path / f"w{w}.jsonl"
        path.write_text(json.dumps(
            {"type": "sim", "time_ns": 1_000,
             "hist": {histo.HIST_PREFIX + "delivery_ns": counts}}) + "\n")
        paths.append(str(path))
    assert tr.main([*paths, "--ensemble", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["worlds"] == 4
    bars = rep["percentile_of_percentiles"]["delivery_ns"]["p50"]
    assert set(bars) == {"min", "median", "max", "worlds"}


# ---------------------------------------------------------------------------
# presence invisibility + memo agreement (@slow: full scenario
# executions — CI's trace-parity gate runs these unfiltered alongside
# `tools/run_scenarios.py --trace --check`, the shared-driver-gate
# pattern)


def _load(name):
    import os

    from shadow_tpu.workloads import load_scenario_file

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return load_scenario_file(os.path.join(repo, "scenarios", name))


@pytest.mark.slow
@pytest.mark.parametrize("yaml_name", ["rpc_fanout_lossy.yaml",
                                       "incast.yaml"])
def test_traced_golden_scenario_record_identical(yaml_name):
    from shadow_tpu.workloads import runner

    spec = _load(yaml_name)
    plain = runner.run_scenario(spec)
    t = tracer.RunTracer(spec.name)
    traced = runner.run_scenario(spec, tracer=t)
    # the FULL record surface, not just the golden tuple: the ledger
    # is a separate artifact and the record carries zero wall time
    assert traced == plain, spec.name
    assert runner.golden_entry(traced) == runner.golden_entry(plain)
    spans = [r for r in t.records if r.get("kind") == "span"]
    assert spans and all(r["mode"] == "execute" for r in spans)
    assert sum(r["windows"] for r in spans) == spec.windows


@pytest.mark.slow
def test_traced_faulted_run_record_identical_with_span_salts():
    from shadow_tpu.workloads import runner

    spec = _load("rpc_fanout.yaml")
    plain = runner.run_scenario(spec, use_default_faults=True)
    t = tracer.RunTracer(spec.name)
    traced = runner.run_scenario(spec, use_default_faults=True,
                                 tracer=t)
    assert traced == plain
    spans = [r for r in t.records if r.get("kind") == "span"]
    assert spans
    # faulted spans stamp the fault-span fingerprint on the ledger
    assert all("span_salt" in r for r in spans), spans


@pytest.mark.slow
def test_memo_report_and_ledger_memo_record_agree():
    from shadow_tpu.workloads import runner

    spec = _load("ring_allreduce.yaml")
    t = tracer.RunTracer(spec.name)
    rec = runner.run_scenario(spec, memo=True, tracer=t)
    view = tracer.memo_view(t.records)
    assert view is not None
    # one artifact, two spellings: the record's memo report (what
    # --memo-report publishes per scenario) IS the ledger's
    assert view == rec["memo"]
    assert view["hits"] + view["misses"] > 0
    # replay/ffwd spans land on the ledger when the cache hits
    modes = {r["mode"] for r in t.records if r.get("kind") == "span"}
    if view["hits"]:
        assert modes & {"replay", "ffwd"}, modes
