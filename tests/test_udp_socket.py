"""UDP socket tests: unit-level buffer/bind semantics plus an end-to-end
two-host echo through the full network path (socket -> NIC -> relay ->
router -> worker -> dst).

Parity model: reference `src/test/udp/` + `descriptor/socket/inet/udp.rs`
unit behavior (EMSGSIZE on oversize datagrams, implicit bind, peer
filtering, recv-buffer drops).
"""

import pytest

from shadow_tpu.core import simtime
from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.event import TaskRef
from shadow_tpu.core.manager import Manager
from shadow_tpu.kernel import errors
from shadow_tpu.kernel.socket.udp import CONFIG_DATAGRAM_MAX_SIZE, UdpSocket
from shadow_tpu.kernel.status import FileState, ListenerFilter

MS = simtime.MILLISECOND

CONFIG = """
general:
  stop_time: 1s
  seed: 7
network:
  graph:
    type: 1_gbit_switch
hosts:
  server:
    network_node_id: 0
  client:
    network_node_id: 0
"""


def _manager():
    return Manager(load_config_str(CONFIG))


# ---------------------------------------------------------------------------
# unit-level (single host, no traffic)
# ---------------------------------------------------------------------------


def test_bind_explicit_and_ephemeral():
    mgr = _manager()
    host = mgr.hosts[0]
    s1 = UdpSocket(host)
    addr = s1.bind((host.ip, 5000))
    assert addr == (host.ip, 5000)
    s2 = UdpSocket(host)
    with pytest.raises(errors.SyscallError) as e:
        s2.bind((host.ip, 5000))
    assert e.value.errno == errors.EADDRINUSE
    eph = s2.bind((host.ip, 0))
    assert 10000 <= eph[1] <= 65535


def test_oversize_datagram_rejected():
    mgr = _manager()
    s = UdpSocket(mgr.hosts[0])
    with pytest.raises(errors.SyscallError) as e:
        s.sendto(b"x" * (CONFIG_DATAGRAM_MAX_SIZE + 1), ("11.0.0.1", 1))
    assert e.value.errno == errors.EMSGSIZE


def test_sendto_without_destination():
    mgr = _manager()
    s = UdpSocket(mgr.hosts[0])
    with pytest.raises(errors.SyscallError) as e:
        s.send(b"hi")
    assert e.value.errno == errors.EDESTADDRREQ


def test_recv_empty_blocks_or_eagain():
    mgr = _manager()
    s = UdpSocket(mgr.hosts[0])
    with pytest.raises(errors.Blocked):
        s.recv()
    s.nonblocking = True
    with pytest.raises(errors.SyscallError) as e:
        s.recv()
    assert e.value.errno == errors.EWOULDBLOCK


def test_implicit_bind_loopback_vs_public():
    mgr = _manager()
    host = mgr.hosts[0]
    s1 = UdpSocket(host)
    s1.sendto(b"x", ("127.0.0.1", 9))
    assert s1.bound_addr[0] == "127.0.0.1"
    s2 = UdpSocket(host)
    s2.sendto(b"x", ("11.9.9.9", 9))
    assert s2.bound_addr[0] == host.ip


def test_close_releases_port():
    mgr = _manager()
    host = mgr.hosts[0]
    s = UdpSocket(host)
    s.bind((host.ip, 6000))
    s.close()
    assert s.is_closed()
    s2 = UdpSocket(host)
    s2.bind((host.ip, 6000))  # no EADDRINUSE after close


# ---------------------------------------------------------------------------
# end-to-end: two hosts, echo through the simulated internet
# ---------------------------------------------------------------------------


class EchoServer:
    PORT = 5353

    def __init__(self, host):
        self.host = host
        self.sock = None

    def start(self, host):
        self.sock = UdpSocket(host)
        self.sock.bind(("0.0.0.0", self.PORT))
        self.sock.add_listener(
            FileState.READABLE, ListenerFilter.OFF_TO_ON, self._on_readable
        )

    def _on_readable(self, state, changed, cq):
        while True:
            self.sock.nonblocking = True
            try:
                data, src = self.sock.recvfrom()
            except errors.SyscallError:
                return
            self.sock.sendto(data.upper(), src)


class EchoClient:
    def __init__(self, host, server_ip):
        self.host = host
        self.server_ip = server_ip
        self.replies = []  # (time_ns, payload)

    def start(self, host):
        self.sock = UdpSocket(host)
        self.sock.add_listener(
            FileState.READABLE, ListenerFilter.OFF_TO_ON, self._on_readable
        )
        self.sock.connect((self.server_ip, EchoServer.PORT))
        self.sock.send(b"hello shadow")
        host.schedule_task_with_delay(
            TaskRef(lambda h: self.sock.send(b"second"), "send2"), 100 * MS
        )

    def _on_readable(self, state, changed, cq):
        self.sock.nonblocking = True
        while True:
            try:
                data, _src = self.sock.recvfrom()
            except errors.SyscallError:
                return
            self.replies.append((self.host.now(), data))


def _run_echo(seed=7):
    cfg = load_config_str(CONFIG.replace("seed: 7", f"seed: {seed}"))
    mgr = Manager(cfg)
    server = EchoServer(mgr.hosts_by_name["server"])
    client = EchoClient(mgr.hosts_by_name["client"], mgr.hosts_by_name["server"].ip)
    mgr.hosts_by_name["server"].add_application(1 * MS, server.start)
    mgr.hosts_by_name["client"].add_application(2 * MS, client.start)
    stats = mgr.run()
    return client, stats


def test_udp_echo_end_to_end():
    client, stats = _run_echo()
    assert [p for _, p in client.replies] == [b"HELLO SHADOW", b"SECOND"]
    # 1 Gbit switch graph: 1ms each way; first reply no earlier than 2ms+2ms RTT
    t0 = client.replies[0][0]
    assert 2 * MS + 2 * MS <= t0 <= 2 * MS + 2 * MS + 5 * MS
    assert stats.packets_sent >= 4  # two requests + two replies


def test_udp_echo_deterministic():
    c1, _ = _run_echo()
    c2, _ = _run_echo()
    assert c1.replies == c2.replies


def test_udp_loopback_same_host():
    """Loopback traffic never crosses the worker; relay_loopback delivers."""
    mgr = _manager()
    host = mgr.hosts[0]
    got = []

    def start(h):
        srv = UdpSocket(h)
        srv.bind(("127.0.0.1", 7000))
        srv.add_listener(
            FileState.READABLE,
            ListenerFilter.OFF_TO_ON,
            lambda s, c, q: got.append((h.now(), srv.recv())),
        )
        cli = UdpSocket(h)
        cli.sendto(b"ping-local", ("127.0.0.1", 7000))

    host.add_application(1 * MS, start)
    mgr.run()
    assert [d for _, d in got] == [b"ping-local"]


def test_configured_buffer_sizes_apply():
    cfg = load_config_str(
        CONFIG, overrides={"experimental": {"socket_recv_buffer": 100,
                                           "socket_send_buffer": 200}}
    )
    mgr = Manager(cfg)
    s = UdpSocket(mgr.hosts[0])
    assert s._recv_buffer.soft_limit == 100
    assert s._send_buffer.soft_limit == 200


def test_closed_socket_raises_ebadf():
    mgr = _manager()
    host = mgr.hosts[0]
    s = UdpSocket(host)
    s.bind((host.ip, 6100))
    s.sendto(b"queued", ("11.9.9.9", 9))
    s.close()
    assert s.pull_out_packet() is None  # buffered datagrams died with close
    for fn in (lambda: s.recv(), lambda: s.bind((host.ip, 6200)),
               lambda: s.connect(("11.9.9.9", 9)), lambda: s.send(b"x")):
        with pytest.raises(errors.SyscallError) as e:
            fn()
        assert e.value.errno == errors.EBADF


def test_recvfrom_peek_leaves_datagram_queued():
    """MSG_PEEK at the socket layer: a peeked datagram must stay queued
    and be returned again by the consuming read (recvfrom(2) semantics
    the syscall handler relies on for MSG_PEEK support)."""
    mgr = _manager()
    host = mgr.hosts[0]
    s = UdpSocket(host)
    s.bind((host.ip, 7700))
    s._recv_buffer.push(b"hello", (("11.0.0.9", 1234), (host.ip, 7700), 0),
                        5)
    s._refresh_readable_writable(None)
    data, src = s.recvfrom(peek=True)
    assert data == b"hello" and src == ("11.0.0.9", 1234)
    assert len(s._recv_buffer) == 1  # still there
    data2, _ = s.recvfrom()
    assert data2 == b"hello"
    assert len(s._recv_buffer) == 0
