"""AF_UNIX sockets + virtual signal delivery for managed processes.

Parity targets: reference `descriptor/socket/unix.rs` (stream/dgram unix
families, socketpair, path namespace) and `process.rs:1309` signal
virtualization with SA_RESTART semantics (`shim/src/syscall.rs:20-120`) —
VERDICT round-2 item #8's criteria: a socketpair C program and a
SIGTERM-handling server run managed; `expected_final_state: signaled`
works without native-kill races.
"""

import shutil
import subprocess

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

CC = shutil.which("gcc") or shutil.which("cc")

pytestmark = pytest.mark.skipif(CC is None, reason="no C compiler")


def _compile(tmp_path, name, src, libs=()):
    c = tmp_path / f"{name}.c"
    c.write_text(src)
    binary = tmp_path / name
    subprocess.run([CC, "-O1", "-o", str(binary), str(c), *libs], check=True)
    return str(binary)


def _run_one(tmp_path, binary, args=(), stop="20s",
             final_state="{exited: 0}"):
    arg_list = ", ".join(f"'{a}'" for a in args)
    cfg = load_config_str(f"""
general: {{stop_time: {stop}, seed: 11}}
network:
  graph:
    type: 1_gbit_switch
hosts:
  box:
    network_node_id: 0
    processes:
    - {{path: {binary}, args: [{arg_list}], start_time: 1s,
       expected_final_state: {final_state}}}
""")
    stats = Manager(cfg).run()
    assert stats.process_failures == [], stats.process_failures
    return stats


SOCKETPAIR_C = r"""
#include <pthread.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

static int sv[2];

static void *peer(void *arg) {
    (void)arg;
    char buf[64];
    long got = read(sv[1], buf, sizeof buf); /* blocks until main writes */
    if (got <= 0) pthread_exit((void *)1);
    /* echo back upper-cased-ish */
    buf[0] = 'P';
    if (write(sv[1], buf, got) != got) pthread_exit((void *)2);
    return 0;
}

int main(void) {
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv)) return 1;
    pthread_t th;
    if (pthread_create(&th, 0, peer, 0)) return 2;
    usleep(2000); /* let the peer block in read (simulated sleep) */
    const char *msg = "ping over socketpair";
    if (write(sv[0], msg, strlen(msg)) != (long)strlen(msg)) return 3;
    char back[64];
    long got = read(sv[0], back, sizeof back);
    if (got != (long)strlen(msg) || back[0] != 'P') return 4;
    void *rv;
    pthread_join(th, &rv);
    if (rv) return 5;
    if (shutdown(sv[0], SHUT_WR)) return 6;
    close(sv[0]); close(sv[1]);
    return 0;
}
"""


def test_socketpair_stream(tmp_path):
    binary = _compile(tmp_path, "sp-stream", SOCKETPAIR_C, ("-pthread",))
    _run_one(tmp_path, binary)


UNIX_SERVER_CLIENT_C = r"""
/* fork: child = unix stream server on an abstract name, parent = client */
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <unistd.h>

static void fill_addr(struct sockaddr_un *a, socklen_t *len) {
    memset(a, 0, sizeof *a);
    a->sun_family = AF_UNIX;
    a->sun_path[0] = '\0';
    memcpy(a->sun_path + 1, "shadow-test", 11);
    *len = sizeof(sa_family_t) + 1 + 11;
}

int main(void) {
    pid_t pid = fork();
    struct sockaddr_un addr;
    socklen_t alen;
    fill_addr(&addr, &alen);
    if (pid == 0) { /* server */
        int ls = socket(AF_UNIX, SOCK_STREAM, 0);
        if (ls < 0) _exit(10);
        if (bind(ls, (struct sockaddr *)&addr, alen)) _exit(11);
        if (listen(ls, 4)) _exit(12);
        int c = accept(ls, 0, 0);
        if (c < 0) _exit(13);
        char buf[128];
        long got = read(c, buf, sizeof buf);
        if (got <= 0) _exit(14);
        if (write(c, buf, got) != got) _exit(15);
        close(c); close(ls);
        _exit(0);
    }
    usleep(10000); /* server binds first (simulated) */
    int s = socket(AF_UNIX, SOCK_STREAM, 0);
    if (s < 0) return 1;
    if (connect(s, (struct sockaddr *)&addr, alen)) return 2;
    const char *msg = "hello unix";
    if (write(s, msg, strlen(msg)) != (long)strlen(msg)) return 3;
    char back[128];
    long got = read(s, back, sizeof back);
    if (got != (long)strlen(msg) || memcmp(back, msg, got)) return 4;
    close(s);
    int st;
    waitpid(pid, &st, 0);
    return (WIFEXITED(st) && WEXITSTATUS(st) == 0) ? 0 : 5;
}
"""


def test_unix_stream_server_client(tmp_path):
    binary = _compile(tmp_path, "unix-sc", UNIX_SERVER_CLIENT_C)
    _run_one(tmp_path, binary)


UNIX_DGRAM_C = r"""
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

int main(void) {
    struct sockaddr_un a;
    memset(&a, 0, sizeof a);
    a.sun_family = AF_UNIX;
    a.sun_path[0] = '\0';
    memcpy(a.sun_path + 1, "dg", 2);
    socklen_t alen = sizeof(sa_family_t) + 3;
    int r = socket(AF_UNIX, SOCK_DGRAM, 0);
    int w = socket(AF_UNIX, SOCK_DGRAM, 0);
    if (r < 0 || w < 0) return 1;
    if (bind(r, (struct sockaddr *)&a, alen)) return 2;
    if (sendto(w, "d1", 2, 0, (struct sockaddr *)&a, alen) != 2) return 3;
    if (sendto(w, "d2", 2, 0, (struct sockaddr *)&a, alen) != 2) return 4;
    char buf[16];
    if (recv(r, buf, sizeof buf, 0) != 2 || memcmp(buf, "d1", 2)) return 5;
    if (recv(r, buf, sizeof buf, 0) != 2 || memcmp(buf, "d2", 2)) return 6;
    close(r); close(w);
    return 0;
}
"""


def test_unix_dgram(tmp_path):
    binary = _compile(tmp_path, "unix-dg", UNIX_DGRAM_C)
    _run_one(tmp_path, binary)


SIGTERM_SERVER_C = r"""
/* fork: child blocks reading a socketpair with a SIGTERM handler; parent
 * kills it with SIGTERM; the handler runs, the read returns EINTR (no
 * SA_RESTART), the child exits 0 iff the handler really fired. */
#include <errno.h>
#include <signal.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

static volatile sig_atomic_t got_term;
static void on_term(int sig) { (void)sig; got_term = 1; }

int main(void) {
    int sv[2];
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv)) return 1;
    pid_t pid = fork();
    if (pid == 0) {
        struct sigaction sa;
        memset(&sa, 0, sizeof sa);
        sa.sa_handler = on_term; /* no SA_RESTART: read must EINTR */
        if (sigaction(SIGTERM, &sa, 0)) _exit(20);
        char buf[8];
        long got = read(sv[0], buf, sizeof buf);
        if (got == -1 && errno == EINTR && got_term) _exit(0);
        _exit(21);
    }
    usleep(20000); /* child parks in read (simulated time) */
    if (kill(pid, SIGTERM)) return 2;
    int st;
    waitpid(pid, &st, 0);
    return (WIFEXITED(st) && WEXITSTATUS(st) == 0) ? 0 : 3;
}
"""


def test_sigterm_handler_interrupts_read(tmp_path):
    binary = _compile(tmp_path, "sigterm-eintr", SIGTERM_SERVER_C)
    _run_one(tmp_path, binary)


SA_RESTART_C = r"""
/* SA_RESTART: the interrupted read RESTARTS after the handler and then
 * completes with the data the parent writes. */
#include <errno.h>
#include <signal.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

static volatile sig_atomic_t fired;
static void on_usr1(int sig) { (void)sig; fired = 1; }

int main(void) {
    int sv[2];
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv)) return 1;
    pid_t pid = fork();
    if (pid == 0) {
        struct sigaction sa;
        memset(&sa, 0, sizeof sa);
        sa.sa_handler = on_usr1;
        sa.sa_flags = SA_RESTART;
        if (sigaction(SIGUSR1, &sa, 0)) _exit(20);
        char buf[8];
        long got = read(sv[0], buf, sizeof buf); /* restarts across USR1 */
        if (got == 4 && fired && !memcmp(buf, "data", 4)) _exit(0);
        _exit(got == -1 && errno == EINTR ? 21 : 22);
    }
    usleep(20000);
    if (kill(pid, SIGUSR1)) return 2;
    usleep(20000); /* child's read restarted and re-parked */
    if (write(sv[1], "data", 4) != 4) return 3;
    int st;
    waitpid(pid, &st, 0);
    return (WIFEXITED(st) && WEXITSTATUS(st) == 0) ? 0 : 4;
}
"""


def test_sa_restart_restarts_read(tmp_path):
    binary = _compile(tmp_path, "sa-restart", SA_RESTART_C)
    _run_one(tmp_path, binary)


DEFAULT_TERM_C = r"""
/* no handler: SIGTERM's default disposition terminates the child AT
 * SIMULATED TIME (the process plane reports it signaled, not the native
 * death watcher racing a wall-clock kill). */
#include <sys/socket.h>
#include <sys/wait.h>
#include <unistd.h>

int main(void) {
    pid_t pid = fork();
    if (pid == 0) {
        for (;;) usleep(50000);
    }
    usleep(30000);
    if (kill(pid, SIGTERM)) return 1;
    int st;
    waitpid(pid, &st, 0);
    return (WIFSIGNALED(st) && WTERMSIG(st) == SIGTERM) ? 0 : 2;
}
"""


def test_default_sigterm_terminates_deterministically(tmp_path):
    import signal as _signal  # noqa: F401  (documentation of intent)

    binary = _compile(tmp_path, "default-term", DEFAULT_TERM_C)
    _run_one(tmp_path, binary)


SELF_SIGNALED_C = r"""
#include <signal.h>
#include <unistd.h>

int main(void) {
    usleep(5000);
    kill(getpid(), SIGTERM); /* default disposition: we die signaled */
    for (;;) usleep(50000);  /* the stop happens at sim time */
}
"""


def test_expected_final_state_signaled(tmp_path):
    """expected_final_state: {signaled: 15} via the VIRTUAL kill path —
    deterministic at sim time, no native-kill race."""
    binary = _compile(tmp_path, "self-term", SELF_SIGNALED_C)
    _run_one(tmp_path, binary, final_state="{signaled: 15}")



BAD_SIGNUM_C = r"""
#include <errno.h>
#include <signal.h>
#include <unistd.h>

int main(void) {
    /* Linux rejects out-of-range signals with EINVAL before delivery
       (kill(2)); a buggy sim would crash on the negative shift. */
    if (kill(getpid(), -1) != -1 || errno != EINVAL) return 1;
    if (kill(getpid(), 70) != -1 || errno != EINVAL) return 2;
    if (kill(0, -7) != -1 || errno != EINVAL) return 3; /* own group */
    /* pid lookup precedes signal validation (check_kill_permission runs
       on a found task): bogus pid + bogus sig is ESRCH, not EINVAL */
    if (kill(-getpid(), -7) != -1 || errno != ESRCH) return 4;
    if (kill(999999, 70) != -1 || errno != ESRCH) return 5;
    if (kill(getpid(), 0) != 0) return 6; /* probe still fine */
    return 0;
}
"""


def test_kill_out_of_range_signal_is_einval(tmp_path):
    """ADVICE r3 (medium): kill(pid, -1) / kill(pid, 70) must return
    EINVAL like Linux instead of crashing the worker via an unchecked
    1 << (sig-1) in deliver_signal."""
    binary = _compile(tmp_path, "bad-signum", BAD_SIGNUM_C)
    _run_one(tmp_path, binary)
