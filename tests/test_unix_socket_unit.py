"""AF_UNIX socket unit tests that need no C compiler (the end-to-end
managed-binary coverage lives in test_unix_signals.py).

Parity: reference `descriptor/socket/unix.rs` buffer/peek semantics.
"""

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager
from shadow_tpu.kernel.socket.unix import make_socketpair

CONFIG = """
general: {stop_time: 1s, seed: 5}
network:
  graph: {type: 1_gbit_switch}
hosts:
  alpha: {network_node_id: 0}
"""


def _host():
    return Manager(load_config_str(CONFIG)).hosts[0]


def test_unix_peek_stream():
    """MSG_PEEK: peeked stream bytes stay queued for the consuming read."""
    a, b = make_socketpair(_host(), stream=True)
    a.send(b"streamdata")
    assert b.recv(6, peek=True) == b"stream"
    assert b.recv(100, peek=True) == b"streamdata"
    assert b.recv(100) == b"streamdata"


def test_unix_peek_dgram():
    """MSG_PEEK: a peeked datagram stays queued, with its sender."""
    da, db = make_socketpair(_host(), stream=False)
    da.send(b"gram")
    data, src = db.recvfrom(100, peek=True)
    assert data == b"gram"
    data2, src2 = db.recvfrom(100)
    assert data2 == b"gram" and src2 == src


def test_unix_dgram_full_datagram_available_for_trunc():
    """The syscall handler learns a clipped datagram's real size by taking
    the whole datagram and clipping itself (MSG_TRUNC support)."""
    da, db = make_socketpair(_host(), stream=False)
    da.send(b"0123456789")
    data, _src = db.recvfrom(1 << 20)
    assert data == b"0123456789"  # untruncated at the socket layer
