"""Per-host filesystem view (file-family syscalls, VERDICT r4 #3):
absolute non-system paths from managed native processes redirect under
the host's data dir, with read-through to the real path for base-layer
files. Reference role: `handler/file.c:1-429` + `fileat.c:1-508` +
`descriptor/regular_file.c` O-flag tracking — re-designed as namespace
redirection because this rebuild's managed fds are real kernel fds.

Real /bin/sh processes drive the paths: open/creat (redirects), cat
(read-through), mkdir/mv/rm (write-class), chdir (mirrored), and the
deterministic strace renders guest-visible path strings.
"""

import os
import tempfile

import pytest

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

SH = "/bin/sh"
pytestmark = pytest.mark.skipif(not os.path.exists(SH), reason="no /bin/sh")


def run_cfg(hosts_yaml: str, data_dir: str, extra_exp: str = "") -> object:
    cfg = load_config_str(
        "general: {stop_time: 10s, seed: 1}\n"
        f"experimental: {{strace_logging_mode: deterministic{extra_exp}}}\n"
        "network:\n  graph: {type: 1_gbit_switch}\n"
        "hosts:\n" + hosts_yaml)
    mgr = Manager(cfg, data_dir=data_dir)
    stats = mgr.run()
    assert stats.process_failures == [], stats.process_failures
    return stats


def sh_host(name: str, script: str, start: str = "1s") -> str:
    return (
        f"  {name}:\n    network_node_id: 0\n    processes:\n"
        f"    - {{path: {SH}, args: ['-c', '{script}'], start_time: {start},\n"
        f"       expected_final_state: {{exited: 0}}}}\n"
    )


def test_absolute_tmp_writes_do_not_collide():
    """Two hosts write the SAME absolute path; each reads back its own
    content (the r4 gap: absolute-path writes collided across hosts)."""
    with tempfile.TemporaryDirectory() as data:
        script = 'echo {tag} > /tmp/shared.log; cat /tmp/shared.log > own.txt'
        run_cfg(
            sh_host("alpha", script.format(tag="from-alpha"))
            + sh_host("beta", script.format(tag="from-beta")),
            data)
        for host, tag in (("alpha", "from-alpha"), ("beta", "from-beta")):
            own = os.path.join(data, "hosts", host, "own.txt")
            with open(own) as fh:
                assert fh.read().strip() == tag
            virt = os.path.join(data, "hosts", host, "root", "tmp",
                                "shared.log")
            with open(virt) as fh:
                assert fh.read().strip() == tag
        assert not os.path.exists("/tmp/shared.log")


def test_base_layer_read_through():
    """A base-layer file (created OUTSIDE the sim) is readable through
    its real absolute path until a host writes its own copy."""
    with tempfile.TemporaryDirectory() as data, \
            tempfile.NamedTemporaryFile("w", suffix=".base",
                                        delete=False) as base:
        base.write("base-content\n")
        base.close()
        try:
            run_cfg(
                sh_host("reader", f"cat {base.name} > got.txt"), data)
            got = os.path.join(data, "hosts", "reader", "got.txt")
            with open(got) as fh:
                assert fh.read() == "base-content\n"
        finally:
            os.unlink(base.name)


def test_mkdir_rename_unlink_are_host_local():
    with tempfile.TemporaryDirectory() as data:
        script = ("mkdir -p /var/myapp && echo x > /var/myapp/a "
                  "&& mv /var/myapp/a /var/myapp/b "
                  "&& rm /var/myapp/b && rmdir /var/myapp "
                  "&& echo done > result.txt")
        run_cfg(sh_host("worker", script), data)
        with open(os.path.join(data, "hosts", "worker",
                               "result.txt")) as fh:
            assert fh.read().strip() == "done"
        assert not os.path.exists("/var/myapp")
        # the whole dance happened under the host's virtual root
        assert not os.path.exists(
            os.path.join(data, "hosts", "worker", "root", "var", "myapp"))


def test_chdir_mirrors_base_dir_and_keeps_writes_local():
    """cd into a base-layer dir then write RELATIVE: the write must land
    in the per-host twin, not the real directory."""
    with tempfile.TemporaryDirectory() as data, \
            tempfile.TemporaryDirectory() as basedir:
        script = f"cd {basedir} && echo local > note.txt"
        run_cfg(sh_host("mover", script), data)
        assert not os.path.exists(os.path.join(basedir, "note.txt"))
        virt = os.path.join(data, "hosts", "mover", "root",
                            basedir.lstrip("/"), "note.txt")
        with open(virt) as fh:
            assert fh.read().strip() == "local"


def test_isolation_can_be_disabled():
    with tempfile.TemporaryDirectory() as data, \
            tempfile.TemporaryDirectory() as shared:
        target = os.path.join(shared, "out.txt")
        run_cfg(sh_host("legacy", f"echo raw > {target}"), data,
                extra_exp=", host_path_isolation: false")
        with open(target) as fh:
            assert fh.read().strip() == "raw"


def test_strace_renders_guest_paths():
    """Deterministic strace shows the GUEST-visible path string for
    file-family syscalls (they were invisible `<ptr>` natives in r4)."""
    with tempfile.TemporaryDirectory() as data:
        run_cfg(sh_host("tracer", "echo hi > /tmp/traced.out"), data)
        host_dir = os.path.join(data, "hosts", "tracer")
        strace_files = [f for f in os.listdir(host_dir)
                        if f.endswith(".strace")]
        assert strace_files
        text = "".join(
            open(os.path.join(host_dir, f)).read() for f in strace_files)
        assert '/tmp/traced.out' in text, text[-2000:]


def test_write_class_open_copies_up_base_content():
    """Appending to a base-layer file must see the base content (the
    overlay copy-up; r5 review finding)."""
    with tempfile.TemporaryDirectory() as data, \
            tempfile.NamedTemporaryFile("w", suffix=".seed",
                                        delete=False) as seed:
        seed.write("seed-line\n")
        seed.close()
        try:
            run_cfg(sh_host(
                "appender",
                f"echo extra >> {seed.name}; cat {seed.name} > all.txt"),
                data)
            with open(os.path.join(data, "hosts", "appender",
                                   "all.txt")) as fh:
                assert fh.read() == "seed-line\nextra\n"
            # the real seed file is untouched
            with open(seed.name) as fh:
                assert fh.read() == "seed-line\n"
        finally:
            os.unlink(seed.name)


def test_dotdot_paths_cannot_escape_the_host_root():
    """/x/../../y normalizes BEFORE layer choice (r5 review finding):
    the write stays inside the host tree, never beside other hosts."""
    with tempfile.TemporaryDirectory() as data:
        script = "echo esc > /zz/../../../escape.txt; echo done > ok.txt"
        run_cfg(sh_host("houdini", script), data)
        with open(os.path.join(data, "hosts", "houdini", "ok.txt")) as fh:
            assert fh.read().strip() == "done"
        # normalized to /escape.txt -> redirected under the host root
        virt = os.path.join(data, "hosts", "houdini", "root",
                            "escape.txt")
        with open(virt) as fh:
            assert fh.read().strip() == "esc"
        assert not os.path.exists("/escape.txt")
        assert not os.path.exists(os.path.join(data, "escape.txt"))


# -- unit: the ENAMETOOLONG verdict (no managed process needed) -----------

def _bare_handler(vfs_root: bytes):
    """A SyscallHandler with just enough state for `_vfs_resolve`."""
    from types import SimpleNamespace

    from shadow_tpu.process.syscall_handler import SyscallHandler

    h = SyscallHandler.__new__(SyscallHandler)
    h.host = SimpleNamespace(vfs_enabled=True, vfs_root=vfs_root,
                             vfs_host_dir=None)
    return h


def test_overlong_guest_path_fails_with_enametoolong(tmp_path):
    """A redirected path longer than VFS_PATH_MAX must FAIL the syscall
    with ENAMETOOLONG — the old silent fall-through to the shared real
    path broke per-host isolation for deep-but-legal guest paths (two
    hosts writing the same long absolute path would collide)."""
    from shadow_tpu.kernel import errors
    from shadow_tpu.process.syscall_handler import VFS_PATH_MAX

    # a real tmp root: the boundary probe below takes the write path,
    # whose copy-up makedirs must never touch the shared filesystem
    root = os.path.join(tmp_path, "root").encode()
    h = _bare_handler(root)
    # a legal guest path (< PATH_MAX) whose REDIRECTED form exceeds the
    # rewrite-event budget: > 399 bytes guest-side on its own
    deep = b"/" + b"/".join([b"d" * 40] * 11)  # 450 bytes, all legal
    assert len(deep) > 399
    for write in (False, True):
        with pytest.raises(errors.SyscallError) as exc:
            h._vfs_resolve(deep, write=write)
        assert exc.value.errno == errors.ENAMETOOLONG
    # the boundary: a path whose redirect lands exactly AT the budget
    # still redirects (write-class — no lexists probe short-circuit)
    room = VFS_PATH_MAX - len(root) - 1
    assert room > 0, "tmp_path too deep for the boundary probe"
    ok = b"/" + b"x" * room
    red = h._vfs_resolve(ok, write=True)
    assert red == root + ok
    assert len(red) == VFS_PATH_MAX


def test_vfs_logging_is_module_scoped():
    """The satellite hoist: the vfs logger is created once at module
    scope, not re-imported per overlong path."""
    import logging

    from shadow_tpu.process import syscall_handler

    assert isinstance(syscall_handler._LOG, logging.Logger)
    assert syscall_handler._LOG.name == "shadow.vfs"
