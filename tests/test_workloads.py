"""Workload plane: scenario DSL, compiler, device generator, runner.

Covers (docs/workloads.md):
- spec parsing/validation and the (spec, seed)-pure fingerprint;
- compile determinism (program_digest) and capacity refusal;
- end-to-end completion for all five pattern families;
- the workload-off parity contract (a world stepped through a driver
  whose workload slot is None is bitwise-identical to one stepped
  without the subsystem at all) and presence-switch invariance
  (metrics/guards threading never perturbs the stream);
- the MULTICHIP parity contract extended to structured workloads: the
  ring_allreduce corpus entry sharded over the mesh produces a
  bitwise-identical canonical digest;
- a fault-injected scenario finishing guards-clean;
- the corpus runner's byte-stable records + golden-corpus diffing;
- the PHOLD respawn relocation (tpu/profiling re-export).
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from shadow_tpu.workloads import (ScenarioError, compile_program,
                                  load_scenario_file, parse_scenario,
                                  program_digest, scenario_fingerprint)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MS = 1_000_000


def _spec(patterns, hosts=8, windows=40, **kw):
    return parse_scenario({"name": "t", "hosts": hosts,
                           "windows": windows, "patterns": patterns,
                           **kw})


# -- spec ------------------------------------------------------------------


def test_spec_validation_errors():
    with pytest.raises(ScenarioError, match="kind"):
        _spec([{"kind": "bittorrent"}])
    with pytest.raises(ScenarioError, match="name"):
        parse_scenario({"hosts": 8,
                        "patterns": [{"kind": "onoff"}]})
    with pytest.raises(ScenarioError, match="patterns"):
        parse_scenario({"name": "t", "hosts": 8, "patterns": []})
    with pytest.raises(ScenarioError, match="unknown"):
        _spec([{"kind": "incast", "count": 4, "think_ns": 5}])
    with pytest.raises(ScenarioError, match="out of range"):
        _spec([{"kind": "incast", "first": 2, "count": 8}])
    with pytest.raises(ScenarioError, match="unknown option"):
        parse_scenario({"name": "t", "hosts": 8, "bogus": 1,
                        "patterns": [{"kind": "onoff"}]})
    with pytest.raises(ScenarioError, match="family"):
        parse_scenario({"name": "t", "hosts": 8, "family": "nope",
                        "patterns": [{"kind": "onoff"}]})


def test_spec_disjoint_host_ranges():
    with pytest.raises(ScenarioError, match="disjoint"):
        _spec([{"kind": "incast", "first": 0, "count": 5},
               {"kind": "onoff", "first": 4, "count": 2}])
    # adjacent ranges are fine
    s = _spec([{"kind": "incast", "first": 0, "count": 5},
               {"kind": "onoff", "first": 5, "count": 3}])
    assert len(s.patterns) == 2


def test_fingerprint_pure_in_spec_and_seed():
    raw = {"name": "fp", "hosts": 8, "seed": 5,
           "patterns": [{"kind": "all_to_all", "count": 8}]}
    a = scenario_fingerprint(parse_scenario(raw))
    b = scenario_fingerprint(parse_scenario(dict(raw)))
    assert a == b
    c = scenario_fingerprint(parse_scenario({**raw, "seed": 6}))
    assert c != a
    d = scenario_fingerprint(parse_scenario(
        {**raw, "patterns": [{"kind": "all_to_all", "count": 8,
                              "bytes": 777}]}))
    assert d != a
    # the seed= override wins over the spec's own
    assert scenario_fingerprint(parse_scenario(raw, seed=6)) == c


def test_scenario_wrapper_key_accepted():
    s = parse_scenario({"scenario": {
        "name": "w", "hosts": 4,
        "patterns": [{"kind": "onoff", "count": 4}]}})
    assert s.name == "w"


# -- compile ---------------------------------------------------------------


def test_compile_deterministic_and_seeded():
    raw = {"name": "c", "hosts": 8, "seed": 5,
           "patterns": [{"kind": "onoff", "count": 8, "burst": 2,
                         "rounds": 3}]}
    p1 = compile_program(parse_scenario(raw))
    p2 = compile_program(parse_scenario(raw))
    assert program_digest(p1) == program_digest(p2)
    p3 = compile_program(parse_scenario({**raw, "seed": 6}))
    assert program_digest(p3) != program_digest(p1)


def test_compile_shapes_ring():
    spec = _spec([{"kind": "ring_allreduce", "count": 8, "rounds": 2}])
    prog = compile_program(spec)
    # 2 rounds x 2*(8-1) hops, every participant
    assert prog.max_phases == 2 * 14
    assert (prog.n_phases[:8] == 28).all()
    assert prog.max_sends == 1
    # each phase: one send to the ring successor, dep 1
    assert (prog.dep[:8, :28] == 1).all()
    assert prog.send_peer[0, 0, 0] == 1
    assert prog.send_peer[7, 0, 0] == 0


def test_onoff_burst_delay_budget_validated():
    # per-field-valid knobs whose PRODUCT overflows the int32 delay
    # table must die as a ScenarioError at parse, not a numpy
    # OverflowError at compile (or a silent wrap on older numpy)
    with pytest.raises(ScenarioError, match="delay budget"):
        _spec([{"kind": "onoff", "count": 8, "burst": 256,
                "gap_ns": 100_000_000}])


def test_single_host_onoff_avoids_claimed_hosts():
    """A count-1 onoff's fleet-fallback peer pool must exclude other
    patterns' participants: deliveries credit the receiver's current
    phase anonymously, so a stray CBR packet would stand in for a
    collective chunk."""
    spec = _spec([{"kind": "ring_allreduce", "first": 0, "count": 8},
                  {"kind": "onoff", "first": 8, "count": 1,
                   "rounds": 4}], hosts=12)
    prog = compile_program(spec)
    peers = prog.send_peer[8][prog.send_peer[8] >= 0]
    assert len(peers) and (peers >= 9).all(), peers
    # and when every other host is claimed, compile refuses
    with pytest.raises(ScenarioError, match="unclaimed"):
        compile_program(_spec(
            [{"kind": "ring_allreduce", "first": 0, "count": 7},
             {"kind": "onoff", "first": 7, "count": 1}], hosts=8))


def test_compile_refuses_overflowing_fanout():
    # an incast sink's ack phase emits fan-in messages at once; a ring
    # smaller than that is a guaranteed overflow — refused at compile
    with pytest.raises(ScenarioError, match="egress_cap"):
        compile_program(_spec(
            [{"kind": "incast", "count": 10}], hosts=10,
            egress_cap=4))


# -- device generator ------------------------------------------------------


def _run_spec(spec, *, metrics=False, guards=False, faults=None,
              windows=None):
    """Minimal driver loop over the scenario world (the runner's loop,
    inlined so tests can thread switches selectively)."""
    import jax
    import jax.numpy as jnp

    from shadow_tpu.guards import make_guards
    from shadow_tpu.telemetry import make_metrics
    from shadow_tpu.tpu.plane import window_step
    from shadow_tpu.workloads import device as wd
    from shadow_tpu.workloads import runner

    prog = compile_program(spec)
    state, params = runner.build_scenario_world(spec)
    wl = wd.to_device(prog)
    ws = wd.make_workload_state(prog)
    m = make_metrics(spec.n_hosts) if metrics else None
    g = make_guards(spec.n_hosts) if guards else None
    out = wd.prime(wl, ws, state, metrics=m, guards=g)
    state, ws, rest = out[0], out[1], out[2:]
    if metrics:
        m, rest = rest[0], rest[1:]
    if guards:
        g = rest[0]
    key = jax.random.key(spec.seed)
    window = jnp.int32(spec.window_ns)

    @jax.jit
    def step(state, ws, m, g, faults, shift, ridx):
        out = window_step(state, params, key, shift, window,
                          rr_enabled=False, faults=faults, metrics=m,
                          guards=g)
        state, delivered = out[0], out[1]
        rest = out[3:]
        if m is not None:
            m, rest = rest[0], rest[1:]
        if g is not None:
            g = rest[0]
        out = wd.workload_step(wl, ws, state, delivered, ridx, window,
                               metrics=m, guards=g)
        state, ws, rest = out[0], out[1], out[2:]
        if m is not None:
            m, rest = rest[0], rest[1:]
        if g is not None:
            g = rest[0]
        return state, ws, m, g

    R = windows if windows is not None else spec.windows
    for r in range(R):
        fa = None
        if faults is not None:
            faults.advance((r + 1) * spec.window_ns)
            fa = faults.device_arrays()
        shift = jnp.int32(0 if r == 0 else spec.window_ns)
        state, ws, m, g = step(state, ws, m, g, fa, shift,
                               jnp.int32(r))
    jax.block_until_ready(state)
    return prog, state, ws, m, g, wl


FAMILY_SPECS = {
    "ring_allreduce": ({"kind": "ring_allreduce", "count": 8,
                        "bytes": 4096, "rounds": 1}, 8, 36, 14 * 8),
    "all_to_all": ({"kind": "all_to_all", "count": 8, "bytes": 2048,
                    "rounds": 2}, 8, 36, 14 * 8),
    "incast": ({"kind": "incast", "count": 8, "bytes": 8000,
                "rounds": 3}, 8, 24, 3 * 7 * 2),
    "rpc_fanout": ({"kind": "rpc_fanout", "count": 8, "bytes": 900,
                    "rounds": 3, "think_ns": 3 * MS,
                    "think_jitter_ns": MS}, 8, 30, 3 * 7 * 2),
    "onoff": ({"kind": "onoff", "count": 8, "burst": 3, "rounds": 4,
               "off_mean_ns": 15 * MS}, 8, 40, 8 * 4 * 3),
}


@pytest.mark.parametrize("family", sorted(FAMILY_SPECS))
def test_family_completes(family):
    """Every pattern family runs to completion with the exact send
    count its structure implies and zero ring overflow."""
    from shadow_tpu.workloads import device as wd

    pat, hosts, windows, want_sent = FAMILY_SPECS[family]
    spec = _spec([pat], hosts=hosts, windows=windows)
    prog, state, ws, _m, _g, wl = _run_spec(spec)
    assert bool(wd.all_done(wl, ws)), np.asarray(ws.phase)
    assert int(np.asarray(state.n_sent).sum()) == want_sent
    assert int(np.asarray(state.n_overflow_dropped).sum()) == 0
    # every left phase stamped a completion window, in phase order
    done = wd.completion_windows(ws)
    for h in range(hosts):
        np_h = int(prog.n_phases[h])
        wins = done[h, :np_h]
        assert (wins < 2**31 - 1).all()
        assert (np.diff(wins) >= 0).all()


def test_rpc_think_time_delays_completion():
    # think must span multiple windows to be visible: pacing is
    # window-quantized (docs/workloads.md "Determinism contract"), so
    # a sub-window think hides in the delivery clamp
    mk = lambda think: _spec(
        [{"kind": "rpc_fanout", "count": 8, "rounds": 2,
          "think_ns": think}], hosts=8, windows=40)
    from shadow_tpu.workloads import device as wd

    _, _, ws_fast, _, _, _ = _run_spec(mk(0))
    _, _, ws_slow, _, _, _ = _run_spec(mk(45 * MS))
    fast = wd.completion_windows(ws_fast)[0]
    slow = wd.completion_windows(ws_slow)[0]
    # the root's last round closes later when children think longer
    assert slow[1] > fast[1]


def test_workload_off_world_bitwise_unchanged():
    """The parity contract: a PHOLD-style world stepped through the
    runner-shaped loop with NO workload threaded is bitwise-identical
    to stepping window_step directly — the subsystem's presence (its
    import, its None slot in a driver) can never perturb a world that
    doesn't use it."""
    import jax
    import jax.numpy as jnp

    from shadow_tpu.tpu import profiling
    from shadow_tpu.tpu.plane import window_step

    world = profiling.build_world(32, warmup_windows=0)
    params, key, window = world["params"], world["rng_root"], \
        world["window"]

    def raw_loop(state):
        step = jax.jit(lambda st, sh: window_step(
            st, params, key, sh, window, rr_enabled=False))
        for r in range(6):
            state, _d, _n = step(
                state, jnp.int32(0 if r == 0 else int(window)))
        return state

    def none_slot_loop(state):
        # the runner's step shape with the workload branch compiled out
        @jax.jit
        def step(st, sh):
            st, d, n = window_step(st, params, key, sh, window,
                                   rr_enabled=False)
            return st, d, n

        for r in range(6):
            state, _d, _n = step(
                state, jnp.int32(0 if r == 0 else int(window)))
        return state

    a = raw_loop(world["state"])
    b = none_slot_loop(profiling.build_world(32, warmup_windows=0)["state"])
    for name, la, lb in zip(a._fields, a, b):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), name


def test_presence_switches_bitwise_invisible():
    """metrics/guards threaded through prime + workload_step never
    perturb the stream (the standing presence-switch contract), and a
    clean scenario reports clean guards."""
    from shadow_tpu.guards import summarize

    spec = _spec([{"kind": "incast", "count": 8, "rounds": 2}],
                 hosts=8, windows=20)
    _, plain, ws_a, _, _, _ = _run_spec(spec)
    _, switched, ws_b, m, g, _ = _run_spec(spec, metrics=True,
                                           guards=True)
    for name, la, lb in zip(plain._fields, plain, switched):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), name
    for name, la, lb in zip(ws_a._fields, ws_a, ws_b):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), name
    assert summarize(g)["clean"]
    assert int(np.asarray(m.pkts_out).sum()) > 0


def test_fault_injected_scenario_guards_clean():
    """A scenario with the default fault schedule threaded (crash,
    link degrade, corruption burst) must finish with ZERO guard
    violations — injected failure is simulation input, not invariant
    breakage (docs/workloads.md)."""
    from shadow_tpu.guards import summarize
    from shadow_tpu.workloads import runner

    spec = _spec([{"kind": "onoff", "count": 8, "burst": 2,
                   "rounds": 3, "off_mean_ns": 10 * MS}],
                 hosts=8, windows=24)
    schedule = runner.default_fault_schedule(spec)
    _, state, _ws, m, g, _ = _run_spec(spec, metrics=True, guards=True,
                                       faults=schedule)
    assert summarize(g)["clean"], summarize(g)
    # the schedule actually bit: fault drops were recorded
    assert int(np.asarray(state.n_fault_dropped).sum()) > 0


# -- runner + corpus -------------------------------------------------------


def test_runner_record_byte_stable(tmp_path):
    from shadow_tpu.workloads import runner

    spec = _spec([{"kind": "all_to_all", "count": 8, "rounds": 1}],
                 hosts=8, windows=20)
    a = runner.run_scenario(spec)
    b = runner.run_scenario(spec)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    assert a["all_done"]
    assert a["fingerprint"] == scenario_fingerprint(spec)
    assert a["drops"] == {"ring_full": 0, "qdisc": 0, "loss": 0,
                          "fault": 0}
    # phase completion: monotone, window-quantized virtual ns
    times = [t for t in a["phase_completion_ns"] if t is not None]
    assert times == sorted(times) and times
    assert all(t % spec.window_ns == 0 for t in times)
    assert a["host_completion"]["max_ns"] >= a["host_completion"]["min_ns"]


def test_golden_corpus_checking(tmp_path):
    from shadow_tpu.workloads import runner

    spec = _spec([{"kind": "incast", "count": 6, "rounds": 2}],
                 hosts=8, windows=20)
    rec = runner.run_scenario(spec)
    golden = {rec["name"]: runner.golden_entry(rec)}
    assert runner.check_against_golden([rec], golden) == []
    # a digest drift names the scenario and the key
    tampered = {rec["name"]: {**golden[rec["name"]],
                              "canonical_digest": "0" * 64}}
    problems = runner.check_against_golden([rec], tampered)
    assert problems and "canonical_digest" in problems[0]
    # unknown / missing entries both surface
    assert runner.check_against_golden([rec], {})
    assert runner.check_against_golden(
        [], golden) == [f"{rec['name']}: in the golden corpus but not "
                        f"run"]


def test_ring_corpus_entry_sharded_parity():
    """The MULTICHIP parity contract extended to structured workloads
    (docs/determinism.md): the ring_allreduce CORPUS entry run
    host-axis-sharded over the 8-device test mesh produces a canonical
    digest bitwise-identical to the single-device run."""
    from shadow_tpu.workloads import runner

    spec = load_scenario_file(
        os.path.join(REPO, "scenarios", "ring_allreduce.yaml"))
    single = runner.run_scenario(spec)
    sharded = runner.run_scenario(spec, mesh_devices=8)
    assert sharded["canonical_digest"] == single["canonical_digest"]
    assert sharded["all_done"] and single["all_done"]


def test_corpus_entry_matches_golden():
    """One corpus entry against the checked-in golden digests (the CI
    gate runs the full corpus; this pins the plumbing in tier-1)."""
    from shadow_tpu.workloads import runner

    spec = load_scenario_file(
        os.path.join(REPO, "scenarios", "incast.yaml"))
    rec = runner.run_scenario(spec)
    golden = runner.load_golden(
        os.path.join(REPO, "scenarios", "GOLDEN.json"))
    assert runner.check_against_golden([rec], {
        rec["name"]: golden[rec["name"]]}) == []


def test_run_scenarios_config_block(tmp_path):
    """`run_scenarios.py --config` consumes the sim config's
    `workload:` block: scenario path resolved relative to the config
    file, seed override applied (the fingerprint shifts with it)."""
    import runpy

    mod = runpy.run_path(
        os.path.join(REPO, "tools", "run_scenarios.py"),
        run_name="run_scenarios")
    scen = tmp_path / "scen.yaml"
    scen.write_text(
        "scenario:\n  name: cfg-driven\n  hosts: 8\n  windows: 16\n"
        "  seed: 1\n"
        "  patterns:\n    - {kind: onoff, count: 8, rounds: 2}\n")
    cfg = tmp_path / "sim.yaml"
    cfg.write_text(
        "general: {stop_time: 1s}\n"
        "workload: {scenario: scen.yaml, seed: 9}\n"
        "hosts:\n  h0: {network_node_id: 0}\n")
    out = tmp_path / "rec.json"
    assert mod["main"](["--config", str(cfg), "-o", str(out)]) == 0
    rec = json.load(open(out))["records"][0]
    spec_seeded = parse_scenario(
        {"name": "cfg-driven", "hosts": 8, "windows": 16, "seed": 1,
         "patterns": [{"kind": "onoff", "count": 8, "rounds": 2}]},
        seed=9)
    assert rec["fingerprint"] == scenario_fingerprint(spec_seeded)
    # a block that names no scenario is a loud exit-2, not a silent
    # fleet-wide corpus run
    cfg2 = tmp_path / "sim2.yaml"
    cfg2.write_text("general: {stop_time: 1s}\nworkload: on\n"
                    "hosts:\n  h0: {network_node_id: 0}\n")
    assert mod["main"](["--config", str(cfg2),
                        "-o", str(tmp_path / "r2.json")]) == 2


@pytest.mark.slow
def test_full_corpus_matches_golden(tmp_path):
    import subprocess
    import sys

    out = tmp_path / "scenarios.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_scenarios.py"),
         "--check", "-o", str(out)],
        capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stderr
    assert "match the golden corpus" in proc.stderr


def test_runner_telemetry_annotations(tmp_path):
    """Phase completions ride the heartbeat stream as annotations
    (docs/observability.md)."""
    from shadow_tpu.telemetry import TelemetryHarvester
    from shadow_tpu.workloads import runner

    spec = _spec([{"kind": "incast", "count": 6, "rounds": 2}],
                 hosts=8, windows=20)
    sink = tmp_path / "hb.jsonl"
    h = TelemetryHarvester(interval_ns=spec.window_ns, sink=str(sink))
    # a cadence that does NOT divide the window count: the loop ticks
    # at 6/12/18 and the runner's trailing tick covers the remainder
    runner.run_scenario(spec, telemetry=h, telemetry_every=6)
    h.finalize()
    lines = [json.loads(ln) for ln in open(sink)]
    assert lines[-1]["time_ns"] == spec.windows * spec.window_ns
    annos = [a for ln in lines for a in ln.get("annotations", ())]
    phases = [a for a in annos if a["kind"] == "workload_phase"]
    assert phases, lines
    assert phases[0]["scenario"] == spec.name
    assert all(p["time_ns"] % spec.window_ns == 0 for p in phases)
    assert [p["phase"] for p in phases] == sorted(
        p["phase"] for p in phases)


# -- relocation + config wiring -------------------------------------------


def test_phold_respawn_relocated_with_reexport():
    """PHOLD moved to the workload plane; the profiler keeps a
    back-compat re-export and is otherwise measurement-only."""
    import shadow_tpu.tpu.profiling as profiling
    from shadow_tpu.workloads import phold

    assert profiling.respawn_batch is phold.respawn_batch
    import inspect

    src = inspect.getsource(profiling)
    assert "def respawn_batch" not in src


def test_manager_workload_warns_and_strict_refuses(caplog):
    """A Manager-driven run never executes scenario programs: the
    `workload:` block warns loudly and `strict: true` refuses."""
    import logging

    from shadow_tpu.core.config import ConfigError, load_config_str
    from shadow_tpu.core.manager import Manager

    mk = lambda blk: (f"general: {{stop_time: 1s, seed: 1}}\n{blk}\n"
                      "network:\n  graph:\n    type: 1_gbit_switch\n"
                      "hosts:\n  h0:\n    network_node_id: 0\n")
    # both spellings a user would reach for: enabled-flag only, and a
    # bare scenario path (enabled left default) — each must warn
    for blk in ("workload: {enabled: true}",
                "workload: {scenario: scenarios/incast.yaml}"):
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="shadow_tpu.manager"):
            Manager(load_config_str(mk(blk)))
        assert any("workload" in r.message and "run_scenarios"
                   in r.message for r in caplog.records), blk
        with pytest.raises(ConfigError, match="strict mode"):
            Manager(load_config_str("strict: true\n" + mk(blk)))
    # an explicitly-off block stays silent
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="shadow_tpu.manager"):
        Manager(load_config_str(mk("workload: off")))
    assert not any("workload" in r.message for r in caplog.records)
