#!/usr/bin/env python3
"""Device flow-engine benchmark: the rung-3 workload shape executed
entirely on device (`shadow_tpu.tpu.floweng`).

975 flows (the rung-3 client count), 256 KiB each, one-way latencies
20-200 ms — the same transfer work rung 3 performs through the CPU
object plane, here with both TCP endpoints, the wire, timers, and the
app model advancing inside `lax.scan` windows on the TPU. Flows run
concurrently (the flow engine has no reason to stagger them), so the
comparison is JOB-level: wall seconds to simulate all N transfers to
completion, and TCP segments simulated per wall second.

Round-4 numbers (tunneled v5e, warm compile cache, honest —
device_get-terminated; `block_until_ready` does NOT synchronize on this
tunneled backend and early async-measured numbers were 10x+ optimistic):
  device: all 975 flows complete in ~205 s wall (~1.7k segments/s)
  CPU object plane (rung 3): same 975 transfers in ~29 s wall
  (~7.5k packets/s)
The TCP event kernel itself costs ~0.9 ms per vmapped step (flat in C
from 200 to 2000 connections — the scaling headroom is real); the
DRIVER (ring gathers/scatters + event selection in `_inner_step`) adds
~6-9 ms per step and is the round-5 optimization target. Dispatches are
chunked (25 windows each) because the tunneled TPU worker kills
long-running kernels.

Usage: python tools/bench_flows.py [n_flows] [size_bytes]
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np

MS = 1000  # us per ms


def main():
    n_flows = int(sys.argv[1]) if len(sys.argv) > 1 else 975
    size = int(sys.argv[2]) if len(sys.argv) > 2 else 262_144

    import jax

    from shadow_tpu.tpu import floweng

    rng = np.random.default_rng(7)
    lats = rng.integers(20, 200, n_flows) * MS
    sizes = np.full(n_flows, size)

    world = floweng.make_flow_world(lats, sizes, queue_slots=128)
    chunk, window_us = 25, 20 * MS
    run = jax.jit(lambda w: floweng.run_windows(w, chunk, window_us))

    t0 = time.monotonic()
    sim_windows = 0
    # run until every flow completes (one-scalar probe per simulated
    # second; pulling more costs seconds over a tunneled link)
    for _ in range(40):
        for _ in range(2):  # 2 chunks = 1 simulated second
            world, _ev = run(world)
            sim_windows += chunk
        if floweng.all_complete(world):
            break
    wall = time.monotonic() - t0
    res = floweng.flow_results(world)
    done = int((res["bytes_read"] == res["bytes_expected"]).sum())
    sim_s = sim_windows * window_us / 1e6

    out = {
        "bench": "device_flow_engine",
        "flows": n_flows,
        "bytes_per_flow": size,
        "flows_complete": done,
        "sim_seconds": sim_s,
        "wall_seconds": round(wall, 2),
        "segments": res["segments"],
        "segments_per_sec": round(res["segments"] / wall, 1),
        "retransmits": res["retransmits"],
        "queue_drops": res["queue_drops"],
    }
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    main()
