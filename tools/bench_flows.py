#!/usr/bin/env python3
"""Device flow-engine benchmark: the rung-3 workload shape executed
entirely on device (`shadow_tpu.tpu.floweng`).

975 flows (the rung-3 client count), 256 KiB each, one-way latencies
20-200 ms — the same transfer work rung 3 performs through the CPU
object plane, here with both TCP endpoints, the wire, timers, and the
app model advancing inside `lax.scan` windows on the TPU. Flows run
concurrently (the flow engine has no reason to stagger them), so the
comparison is JOB-level: wall seconds to simulate all N transfers to
completion, and TCP segments simulated per wall second.

Round-5 numbers (tunneled v5e, honest — device_get-terminated;
`block_until_ready` does NOT synchronize on this tunneled backend):
  device, warm XLA cache: all 975 flows complete in ~8-11 s wall
  device, cold (first-ever run, includes one ~60 s XLA compile): ~70 s
  CPU object plane (rung 3): same 975 transfers in ~29 s wall
The round-4 engine took ~205 s (one while-iteration per micro-event x
~6 ms of kernel per iteration); round 5 fused the driver (sched_batch
arrivals/timers per step, inline app work, convergent pull loop) and
cut the kernel's sequential 128-slot loops to log-depth/convergent
forms. The persistent XLA cache (~/.cache/shadow_tpu_xla) makes every
run after the machine's first pay only the run cost, like any compiled
simulator pays its build once.

Usage: python tools/bench_flows.py [n_flows] [size_bytes]
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np

MS = 1000  # us per ms


def main():
    n_flows = int(sys.argv[1]) if len(sys.argv) > 1 else 975
    size = int(sys.argv[2]) if len(sys.argv) > 2 else 262_144

    from shadow_tpu.tpu import enable_compilation_cache, floweng
    enable_compilation_cache()

    rng = np.random.default_rng(7)
    lats = rng.integers(20, 200, n_flows) * MS
    sizes = np.full(n_flows, size)

    world = floweng.make_flow_world(lats, sizes, queue_slots=128)
    window_us = 20 * MS

    t0 = time.monotonic()
    world, sim_s, retries = floweng.run_to_completion(
        world, window_us, max_sim_s=40.0, chunk_windows=25,
        probe_every=2)
    wall = time.monotonic() - t0
    res = floweng.flow_results(world)
    done = int((res["bytes_read"] == res["bytes_expected"]).sum())

    out = {
        "bench": "device_flow_engine",
        "flows": n_flows,
        "bytes_per_flow": size,
        "flows_complete": done,
        "sim_seconds": sim_s,
        "wall_seconds": round(wall, 2),
        "segments": res["segments"],
        "segments_per_sec": round(res["segments"] / wall, 1),
        "retransmits": res["retransmits"],
        "queue_drops": res["queue_drops"],
        "saturation_retries": retries,
    }
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    main()
