#!/usr/bin/env python3
"""BASELINE.md benchmark ladder: end-to-end runs, rungs 1-4.

Rung 1: REAL binaries (python http.server + curl) over a 1 Gbit switch.
Rung 2: tgen traffic mesh, 100 hosts, single-vertex graph (1_gbit_switch) —
        BASELINE.md row 2, reference `src/test/tgen/` shape.
Rung 3: 1k-host tgen over an Atlas-style GML with latency + loss —
        BASELINE.md row 3 (`3f` = identical YAML on the device flow engine).
Rung 4: Tor-SHAPED workload — 99 real onion-relay processes, 3-hop
        circuits over a lossy GML, heartbeats verified via parse_shadow.
interpose: N real compiled processes under the seccomp+preload shim.

Reports sim-sec/wall-sec, absolute event rate, and packet counts per rung as
JSON lines. These are the HONEST end-to-end numbers (full syscall + network
object planes), distinct from bench.py's device-plane PHOLD throughput.

Usage: python tools/bench_ladder.py [1|2|3|3f|4|interpose|all]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, ".")

import numpy as np

from shadow_tpu.core.config import load_config_str
from shadow_tpu.core.manager import Manager

MS = 1_000_000


def run_rung(name: str, cfg_text: str, data_dir: str | None = None) -> dict:
    cfg = load_config_str(cfg_text)
    mgr = Manager(cfg, data_dir=data_dir)
    t0 = time.monotonic()
    stats = mgr.run()
    wall = time.monotonic() - t0
    out = {
        "rung": name,
        "sim_seconds": stats.sim_time_ns / 1e9,
        "wall_seconds": round(wall, 3),
        "sim_per_wall": round(stats.sim_time_ns / 1e9 / wall, 3),
        "events_per_sec": round(stats.events_executed / wall, 1),
        "events": stats.events_executed,
        "packets": stats.packets_sent,
        "failures": len(stats.process_failures),
    }
    print(json.dumps(out), flush=True)
    return out


def make_gml(n_nodes: int, lat_lo: int, lat_hi: int, loss_lo: float,
             loss_hi: float, seed: int) -> str:
    """Full-mesh GML with per-edge latency/loss draws (rungs 3 and 4)."""
    rng = np.random.default_rng(seed)
    lines = ["graph [", "  directed 0"]
    for i in range(n_nodes):
        lines.append(f"  node [ id {i} host_bandwidth_up \"1 Gbit\""
                     f" host_bandwidth_down \"1 Gbit\" ]")
    for i in range(n_nodes):
        for j in range(i, n_nodes):
            lat = int(rng.integers(lat_lo, lat_hi)) if i != j else 5
            loss = float(rng.uniform(loss_lo, loss_hi)) if i != j else 0.0
            lines.append(f"  edge [ source {i} target {j} latency"
                         f" \"{lat} ms\" packet_loss {loss:.4f} ]")
    lines.append("]")
    return "\n".join("      " + ln for ln in lines)


def rung2(n_hosts: int = 100, size: int = 1_048_576) -> dict:
    """100-host tgen mesh: one server, 99 clients each pulling 1 MiB."""
    hosts = ["  server:\n    network_node_id: 0\n    processes:\n"
             "    - {path: tgen-server, args: ['8888'], start_time: 1s,\n"
             "       expected_final_state: running}"]
    for i in range(n_hosts - 1):
        hosts.append(
            f"  client{i}:\n    network_node_id: 0\n    processes:\n"
            f"    - {{path: tgen-client, args: ['server', '8888', "
            f"'{size}', '1'], start_time: 2s}}"
        )
    cfg = ("general: {stop_time: 60s, seed: 1}\n"
           "network:\n  graph:\n    type: 1_gbit_switch\n"
           "hosts:\n" + "\n".join(hosts))
    return run_rung("rung2_tgen_mesh_100", cfg)


def rung3(n_hosts: int = 1000, n_nodes: int = 40,
          size: int = 262_144, use_flow_engine: bool = False) -> dict:
    """1k hosts spread over an Atlas-style GML: full node mesh with
    20-200 ms latencies and 0.1-1% loss; 25 tgen servers, 975 clients.
    With use_flow_engine=True the identical YAML runs on the device
    flow engine (`experimental.use_flow_engine`)."""
    gml = make_gml(n_nodes, 20, 200, 0.001, 0.01, seed=7)

    n_servers = 25
    hosts = []
    for s in range(n_servers):
        hosts.append(
            f"  server{s}:\n    network_node_id: {s % n_nodes}\n"
            f"    processes:\n"
            f"    - {{path: tgen-server, args: ['8888'], start_time: 1s,\n"
            f"       expected_final_state: running}}"
        )
    for i in range(n_hosts - n_servers):
        server = f"server{i % n_servers}"
        hosts.append(
            f"  client{i}:\n    network_node_id: {i % n_nodes}\n"
            f"    processes:\n"
            f"    - {{path: tgen-client, args: ['{server}', '8888', "
            f"'{size}', '1'], start_time: {2 + (i % 10)}s}}"
        )
    flag = ("experimental: {use_flow_engine: true}\n"
            if use_flow_engine else "")
    cfg = ("general: {stop_time: 120s, seed: 1}\n" + flag +
           "network:\n  graph:\n    type: gml\n    inline: |\n" + gml +
           "\nhosts:\n" + "\n".join(hosts))
    name = "rung3_tgen_atlas_1k" + ("_floweng" if use_flow_engine else "")
    return run_rung(name, cfg)


def rung1(size: int = 10 * 1024 * 1024) -> dict:
    """BASELINE rung 1 with REAL binaries: python3 -m http.server serving
    a 10 MiB file to two real curl clients over a 1 Gbit switch — the
    reference's literal getting-started example
    (`examples/docs/basic-file-transfer/shadow.yaml`)."""
    import shutil
    import tempfile

    py = shutil.which("python3")
    curl = shutil.which("curl")
    if py is None or curl is None:
        print(json.dumps({"rung": "rung1_real_binaries",
                          "skipped": "python3/curl missing"}))
        return {}
    tmp = tempfile.mkdtemp(prefix="rung1-")
    with open(f"{tmp}/data.bin", "wb") as fh:
        fh.write(bytes(range(256)) * (size // 256))
    clients = "\n".join(
        f"""  client{i}:
    network_node_id: 0
    processes:
    - {{path: {curl}, args: ["-s", "-o", "{tmp}/out{i}.bin",
        "http://server:8000/data.bin"], start_time: {3 + i}s,
       expected_final_state: {{exited: 0}}}}"""
        for i in range(2))
    cfg = f"""
general: {{stop_time: 120s, seed: 1}}
network:
  graph: {{type: 1_gbit_switch}}
hosts:
  server:
    network_node_id: 0
    processes:
    - {{path: {py}, args: ["-m", "http.server", "8000",
        "--bind", "0.0.0.0", "--directory", "{tmp}"], start_time: 1s,
       expected_final_state: running}}
{clients}
"""
    out = run_rung("rung1_real_binaries", cfg, data_dir=f"{tmp}/data")
    for i in range(2):
        # absolute -o paths live in each client's per-host filesystem
        # view (experimental.host_path_isolation, round 5)
        with open(f"{tmp}/data/hosts/client{i}/root{tmp}/out{i}.bin",
                  "rb") as fh:
            got = fh.read()
        assert len(got) == size, f"client{i} fetched {len(got)} != {size}"
    return out


def rung4(n_relays: int = 66, n_clients: int = 33, n_nodes: int = 10,
          size: int = 32_768) -> dict:
    """Rung 4, the Tor-SHAPED workload (BASELINE ladder row 4; reference
    `src/test/tor/minimal/tor-minimal.yaml` — no tor binary exists on
    this image, so the shape is rebuilt): ~100 REAL compiled processes —
    onion relays doing layered store-and-forward over a latency+loss
    GML — each client pushing a payload through a 3-hop circuit
    (guard -> middle -> exit) and waiting for the ack to ride back.
    The run's log is fed through tools/parse_shadow.py to verify the
    tornettools heartbeat contract end-to-end."""
    import logging
    import subprocess
    import tempfile


    tmp = tempfile.mkdtemp(prefix="rung4-onion-")
    here = os.path.dirname(os.path.abspath(__file__))
    for name in ("relay", "client"):
        subprocess.run(["gcc", "-O1", "-o", f"{tmp}/{name}",
                        os.path.join(here, "onion", f"{name}.c")],
                       check=True)

    gml = make_gml(n_nodes, 20, 80, 0.0005, 0.003, seed=11)

    relay_ip = lambda r: f"10.4.{r // 200}.{r % 200 + 1}"
    client_ip = lambda c: f"10.5.{c // 200}.{c % 200 + 1}"
    hosts = []
    for r in range(n_relays):
        hosts.append(
            f"  relay{r}:\n    network_node_id: {r % n_nodes}\n"
            f"    ip_addr: {relay_ip(r)}\n    processes:\n"
            f"    - {{path: {tmp}/relay, args: ['7000'], start_time: 1s,\n"
            f"       expected_final_state: running}}"
        )
    third = n_relays // 3
    for c in range(n_clients):
        # Tor-style role partition (guards / middles / exits in disjoint
        # thirds): forward edges only ever cross guard->middle->exit, so
        # the circuit graph is ACYCLIC — the single-threaded blocking
        # relays cannot form a circular wait (r5 review finding)
        g = c % third
        m = third + (c % third)
        e = 2 * third + (c % (n_relays - 2 * third))
        hosts.append(
            f"  client{c}:\n    network_node_id: {c % n_nodes}\n"
            f"    ip_addr: {client_ip(c)}\n    processes:\n"
            f"    - {{path: {tmp}/client, args: ['{relay_ip(g)}', '7000',"
            f" '{relay_ip(m)}', '7000', '{relay_ip(e)}', '7000',"
            f" '{size}'], start_time: {2 + (c % 5)}s,\n"
            f"       expected_final_state: {{exited: 0}}}}"
        )
    cfg = ("general: {stop_time: 30s, seed: 1}\n"
           "network:\n  graph:\n    type: gml\n    inline: |\n" + gml +
           "\nhosts:\n" + "\n".join(hosts))

    # capture the log stream so parse_shadow.py can verify the
    # tornettools heartbeat contract on this very run
    log_path = f"{tmp}/shadow.log"
    handler = logging.FileHandler(log_path)
    handler.setFormatter(logging.Formatter("%(message)s"))
    # scope to the simulator's loggers (tracker heartbeats live under
    # "shadow_tpu.*" and the rusage/meminfo lines under the manager's) —
    # never the ROOT level, which would flood any console handler the
    # caller configured (r5 review finding)
    targets = [logging.getLogger("shadow_tpu"),
               logging.getLogger("shadow")]
    saved = [(t, t.level) for t in targets]
    for t in targets:
        t.addHandler(handler)
        t.setLevel(logging.INFO)
    try:
        out = run_rung(f"rung4_onion_{n_relays + n_clients}_procs", cfg,
                       data_dir=f"{tmp}/data")
    finally:
        for t, lvl in saved:
            t.removeHandler(handler)
            t.setLevel(lvl)
        handler.close()
    parsed = subprocess.run(
        [sys.executable, os.path.join(here, "parse_shadow.py"), log_path,
         "-p", tmp],
        check=True, capture_output=True, text=True).stdout
    with open(f"{tmp}/stats.shadow.json") as fh:
        stats = json.load(fh)
    n_hb_hosts = len(stats.get("nodes", {}))
    assert n_hb_hosts >= n_relays + n_clients, \
        f"heartbeat contract: {n_hb_hosts} hosts in parse_shadow output"
    out["heartbeat_hosts"] = n_hb_hosts
    print(json.dumps({"rung": out["rung"],
                      "heartbeat_hosts": n_hb_hosts,
                      "parse_shadow": parsed.strip()}), flush=True)
    return out


def rung_interpose(n_pairs: int = 50, size: int = 262_144) -> dict:
    """Interposition-plane scale: 2*n_pairs REAL compiled binaries (the
    TCP transfer pair from tests/test_managed_network.py), each under the
    seccomp+LD_PRELOAD shim with its own IPC channel, futex-channel
    shmem, and pidfd watcher — the reference's headline claim shape
    ('thousands of network-connected processes', README.md:20-23),
    previously exercised only at N<=3 in tests. Reports sim-sec/wall-sec
    and peak simulator RSS."""
    import re
    import resource
    import subprocess
    import tempfile

    src = open("tests/test_managed_network.py").read()
    server_c = re.search(r'SERVER_C = r"""(.*?)"""', src, re.S).group(1)
    client_c = re.search(r'CLIENT_C = r"""(.*?)"""', src, re.S).group(1)
    tmp = tempfile.mkdtemp(prefix="interpose-bench-")
    for name, code in (("server", server_c), ("client", client_c)):
        with open(f"{tmp}/{name}.c", "w") as fh:
            fh.write(code)
        subprocess.run(["gcc", "-O1", "-o", f"{tmp}/{name}",
                        f"{tmp}/{name}.c"], check=True)

    hosts = []
    for i in range(n_pairs):
        hosts.append(
            f"  srv{i}:\n    network_node_id: 0\n    ip_addr: 10.9.{i // 250}.{i % 250 + 1}\n"
            f"    processes:\n"
            f"    - {{path: {tmp}/server, args: ['9000', '{size}'],\n"
            f"       start_time: 1s, expected_final_state: {{exited: 0}}}}"
        )
        hosts.append(
            f"  cli{i}:\n    network_node_id: 0\n    ip_addr: 10.9.{i // 250 + 100}.{i % 250 + 1}\n"
            f"    processes:\n"
            f"    - {{path: {tmp}/client, args: ['10.9.{i // 250}.{i % 250 + 1}', "
            f"'9000', '{size}'],\n"
            f"       start_time: 2s, expected_final_state: {{exited: 0}}}}"
        )
    cfg = ("general: {stop_time: 30s, seed: 1}\n"
           "network:\n  graph:\n    type: 1_gbit_switch\n"
           "hosts:\n" + "\n".join(hosts))
    out = run_rung(f"rung_interpose_{2 * n_pairs}_procs", cfg)
    peak_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    out["peak_rss_mib"] = round(peak_kb / 1024, 1)
    print(json.dumps({"rung": out["rung"],
                      "peak_rss_mib": out["peak_rss_mib"]}), flush=True)
    return out


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("1", "all"):
        rung1()
    if which in ("2", "all"):
        rung2()
    if which in ("3", "all"):
        rung3()
    if which in ("3f", "all"):
        rung3(use_flow_engine=True)
    if which in ("4", "all"):
        rung4()
    if which in ("interpose", "all"):
        rung_interpose()


if __name__ == "__main__":
    main()
