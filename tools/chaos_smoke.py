#!/usr/bin/env python3
"""Chaos smoke: a fault-injected device-plane sim that survives a kill.

The CI end-to-end for the fault plane (docs/robustness.md): run the
PHOLD bench world with an ACTIVE fault schedule (host crash + reboot,
link degradation, corruption burst, iface flap) threaded through
`window_step(..., faults=)`, checkpointing every few windows; kill the
process mid-run; resume from the checkpoint and prove the final state
is BITWISE-identical to an uninterrupted run of the same seed.

Usage:
  python tools/chaos_smoke.py --hosts 256 --windows 48 \
      --checkpoint-dir chaos/ --checkpoint-every 8        # full run
  python tools/chaos_smoke.py ... --kill-at 20            # dies at w20
  python tools/chaos_smoke.py ... --resume chaos/ckpt-000000000016
                                                          # continues
Each invocation prints ONE JSON line with the final state digest,
drop-bucket totals, and fallback/fault bookkeeping; CI compares the
digest of the resumed run against the uninterrupted one.

`--kernel pallas` drives the step through the self-healing
`KernelFallback`: the Pallas egress kernel cannot fuse the fault gate,
so the driver demotes to the bitwise-identical XLA path, loudly — the
run completes and the JSON records `fell_back: true`.

`--guards warn|abort` threads the guard plane (`shadow_tpu/guards/`,
docs/robustness.md) through every window: the JSON gains a `guards`
summary, a clean fault-injected run must report zero violations, and
under `abort` any violation exits with the CLI guard code (5).
`--tamper-at W` deliberately corrupts the device state after window W
(a phantom ring slot) — the guards-catch-it proof CI runs.

`--capacity elastic` (with `--egress-cap/--ingress-cap/--max-doublings`)
drives the elastic capacity plane (docs/robustness.md "Elastic
capacity"): each window snapshots the pre-window state, and on
ring-full overflow the offending ring doubles and the window
RE-EXECUTES from the snapshot — the JSON gains the capacity trajectory,
`drops.ring_full` must be ZERO, and `canonical_digest` must equal a
run pre-provisioned at the final capacity (the CI proof). `--capacity
strict` exits with the CLI capacity code (6) on the first overflow.

`--telemetry DIR` (matching tools/run_scenarios.py) threads the
log2 latency/depth histograms and writes heartbeat JSONL +
`trace.json` into DIR every `--harvest-every` windows — fault-injected
runs emit the same observability surface as bench-driven ones.
`--sample-every K` additionally threads the flight recorder
(docs/observability.md "Distributions and the flight recorder"):
sampled per-packet hops land in DIR/hops.jsonl and as Perfetto flow
spans in the trace; the JSON gains `telemetry` (recorded hops,
ring-overwrite count, fleet latency percentiles). Histogram and
trace-ring state ride checkpoints, so a resumed run keeps its
distributions; under `--capacity elastic` a drain that reports
overwritten hops doubles the trace ring (bounded by --max-doublings).

`--memo` threads the steady-state memo plane (tpu/memo.py) through
the fault-injected driver — the SAFETY smoke, not a perf feature:
PHOLD respawn traffic is round-indexed, so every key folds the
absolute start round and a single run can never hit its own cache
(expect hits=0, misses=spans). What the run proves is the opt-out
discipline: every span key carries the fault schedule's
`span_fingerprint`, so a fault-injected span can only ever replay
against a recording whose masks AND in-span events match exactly —
and the final digest must equal the non-memo twin's byte-for-byte
(the CI assertion). Refused with --capacity elastic/strict (a hit
would skip the overflow readback the growth decision reads).

`--memo` composes with checkpoint/resume: the checkpoint's fault
masks are recomputed from the schedule position at the cut (sound on
the execute AND memo-replay paths), the recorded spans spill into the
checkpoint and are absorbed on restore, and a run killed mid-flight
and resumed must report the same final digest as the uninterrupted
memoized run — the resumed-memoized smoke CI gates on.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MS = 1_000_000


def default_schedule(n_hosts: int, n_windows: int, window_ns: int):
    """The built-in chaos scenario, scaled to the run length: crash one
    host for the middle quarter, degrade a link 4x, corrupt another
    host's egress, flap a third's NIC. Compiled through the REAL
    `faults:` schedule path (config dataclass -> compile_schedule)."""
    from shadow_tpu.core.config import FaultsOptions
    from shadow_tpu.faults.schedule import compile_schedule

    w = lambda k: f"{max(1, k) * window_ns}ns"
    q = max(2, n_windows // 4)
    events = [
        {"at": w(q), "kind": "host_crash", "host": "h1"},
        {"at": w(2 * q), "kind": "host_reboot", "host": "h1"},
        {"at": w(q // 2), "kind": "link_degrade", "src_node": 0,
         "dst_node": 1, "latency_mult": 4, "duration": w(2 * q)},
        {"at": w(q), "kind": "corrupt_burst", "host": f"h{n_hosts - 1}",
         "p": 0.3, "duration": w(q)},
        {"at": w(2 * q), "kind": "iface_down", "host": "h2"},
        {"at": w(2 * q + q // 2), "kind": "iface_up", "host": "h2"},
        {"at": w(q), "kind": "host_degrade", "host": "h0",
         "bandwidth_div": 8, "duration": w(q)},
    ]
    opts = FaultsOptions(events=events)
    return compile_schedule(
        opts, host_names=[f"h{i}" for i in range(n_hosts)],
        n_nodes=64, seed=1234, stop_time_ns=(n_windows + 1) * window_ns)


def state_digest(*pytrees) -> str:
    # ONE digest definition for every bitwise-parity contract (the
    # golden scenario corpus shares it): shadow_tpu/workloads/runner.py
    from shadow_tpu.workloads.runner import digest_pytrees

    return digest_pytrees(*pytrees)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--hosts", type=int, default=256)
    ap.add_argument("--windows", type=int, default=48)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--checkpoint-every", type=int, default=8)
    ap.add_argument("--kill-at", type=int, default=None,
                    help="exit abruptly (no cleanup) after this window")
    ap.add_argument("--resume", default=None,
                    help="checkpoint directory to restore and continue")
    ap.add_argument("--kernel", choices=["xla", "pallas"], default="xla")
    ap.add_argument("--no-faults", action="store_true",
                    help="neutral masks only (the overhead-gate twin)")
    ap.add_argument("--guards", choices=["off", "warn", "abort"],
                    default="off",
                    help="thread the runtime invariant plane through "
                         "every window (abort: violations exit 5)")
    ap.add_argument("--tamper-at", type=int, default=None,
                    help="corrupt the device state after this window "
                         "(a phantom ring slot) — guards must catch it")
    ap.add_argument("--capacity", choices=["fixed", "strict", "elastic"],
                    default="fixed",
                    help="ring-capacity policy (docs/robustness.md "
                         "'Elastic capacity'): elastic grows + "
                         "re-executes overflowing windows; strict exits "
                         "6 on the first ring-full drop")
    ap.add_argument("--egress-cap", type=int, default=16)
    ap.add_argument("--ingress-cap", type=int, default=32)
    ap.add_argument("--max-doublings", type=int, default=4)
    ap.add_argument("--telemetry", default=None, metavar="DIR",
                    help="write heartbeats.jsonl + trace.json (and "
                         "hops.jsonl with --sample-every) into DIR; "
                         "threads the latency/depth histograms")
    ap.add_argument("--harvest-every", type=int, default=8,
                    help="windows between telemetry harvests "
                         "(default 8)")
    ap.add_argument("--sample-every", type=int, default=None,
                    metavar="K",
                    help="thread the flight recorder: tag ~1/K packets "
                         "and trace their hops (requires --telemetry)")
    ap.add_argument("--trace-ring", type=int, default=2048,
                    help="flight-recorder trace-ring capacity "
                         "(default 2048)")
    ap.add_argument("--chain-len", type=int, default=8,
                    help="windows per device-resident chain (the "
                         "shared driver's host-sync cadence; harvest/"
                         "checkpoint/tamper/kill instants cut chains "
                         "regardless). MUST match across runs whose "
                         "digests are compared under --capacity "
                         "elastic: the chain is the growth-decision "
                         "unit (default 8)")
    ap.add_argument("--memo", action="store_true",
                    help="thread the steady-state memo plane "
                         "(tpu/memo.py) — the fault-plane safety "
                         "smoke: span keys fold the schedule "
                         "fingerprint (fault spans never replay "
                         "against different masks/events) and the "
                         "final digest must match a non-memo run")
    ap.add_argument("--trace", default=None, metavar="PATH",
                    help="shadowscope run ledger: write the per-span "
                         "JSONL (wall split, span modes, capacity "
                         "growth, fault-span fingerprints, tamper/"
                         "harvest/checkpoint annotations) to PATH; "
                         "presence-invisible — digests are unchanged")
    args = ap.parse_args(argv)
    if args.sample_every is not None and not args.telemetry:
        ap.error("--sample-every requires --telemetry DIR (the hop "
                 "drain needs somewhere to land)")
    if args.memo and args.capacity != "fixed":
        ap.error("--memo requires --capacity fixed: a memo hit skips "
                 "the chain execution whose overflow readback the "
                 "capacity policy decides growth from")
    import jax
    import jax.numpy as jnp

    from shadow_tpu.faults import (KernelFallback, load_plane_checkpoint,
                                   neutral_faults, save_plane_checkpoint)
    from shadow_tpu.guards import make_guards, summarize
    from shadow_tpu.guards.plane import GuardState
    from shadow_tpu.telemetry import make_metrics
    from shadow_tpu.tpu import elastic, ingest_rows, profiling
    from shadow_tpu.tpu.elastic import CapacityError
    from shadow_tpu.tpu.plane import unpack_planes, window_step
    from shadow_tpu.workloads.phold import respawn_batch

    EXIT_GUARD = 5  # shadow_tpu.cli.EXIT_GUARD (docs/robustness.md)
    EXIT_CAPACITY = 6  # shadow_tpu.cli.EXIT_CAPACITY

    N, R = args.hosts, args.windows
    tracer = None
    if args.trace:
        from shadow_tpu.telemetry import RunTracer

        tracer = RunTracer(
            "chaos_smoke",
            meta={"hosts": N, "windows": R, "kernel": args.kernel,
                  "capacity": args.capacity,
                  "chain_len": args.chain_len,
                  "faults": not args.no_faults,
                  "memo": bool(args.memo)})
    world = profiling.build_world(N, warmup_windows=0,
                                  egress_cap=args.egress_cap,
                                  ingress_cap=args.ingress_cap)
    window = world["window"]
    window_ns = int(window)
    schedule = (None if args.no_faults
                else default_schedule(N, R, window_ns))
    use_guards = args.guards != "off"
    policy = None
    if args.capacity != "fixed":
        policy = elastic.RingPolicy(
            mode=args.capacity, max_doublings=args.max_doublings,
            egress_cap=args.egress_cap, ingress_cap=args.ingress_cap,
            plane="chaos_smoke")

    def build_chain(kernel: str):
        def round_fn(carry, xs):
            (state, metrics, guards, hist, fr, spawn_seq, eg_acc,
             in_acc) = carry
            round_idx, faults = xs
            ci = state.in_src.shape[1]
            state0 = state
            shift = jnp.where(round_idx == 0, jnp.int32(0), window)
            out = window_step(state, world["params"], world["rng_root"],
                              shift, window, rr_enabled=False,
                              kernel=kernel, faults=faults,
                              metrics=metrics, guards=guards,
                              hist=hist, flightrec=fr)
            (state, delivered, _next), metrics, guards, hist, fr = \
                unpack_planes(out, metrics=metrics, guards=guards,
                              hist=hist, flightrec=fr)
            # ingress-ring overflow: the routing stage's ring-full drops
            in_acc = in_acc + (state.n_overflow_dropped
                               - state0.n_overflow_dropped)
            state1 = state
            mask, dst, nbytes, seq, ctrl = respawn_batch(
                delivered, spawn_seq, round_idx, N, ci)
            # dead/flapped hosts generate no respawn traffic
            mask = mask & (faults.host_alive & faults.link_up)[:, None]
            out = ingest_rows(
                state, dst, nbytes, seq, seq, ctrl, valid=mask,
                metrics=metrics, guards=guards, hist=hist, flightrec=fr)
            (state,), metrics, guards, hist, fr = unpack_planes(
                out, metrics=metrics, guards=guards, hist=hist,
                flightrec=fr, n_lead=1)
            # egress-ring overflow: the respawn append's ring-full drops
            eg_acc = eg_acc + (state.n_overflow_dropped
                               - state1.n_overflow_dropped)
            return ((state, metrics, guards, hist, fr,
                     spawn_seq + mask.sum(axis=1, dtype=jnp.int32),
                     eg_acc, in_acc), None)

        @jax.jit
        def chain(state, metrics, guards, hist, fr, spawn_seq, rids,
                  faults_stack):
            # K windows device-resident per dispatch: the fault masks
            # ride as PER-ROUND scan inputs (so a schedule transition
            # mid-chain is bitwise-identical to the per-window loop it
            # replaced), every presence plane rides the carry, and the
            # per-ring overflow the capacity policy reads accumulates
            # alongside. Ring shapes come from the state itself
            # (trace-time), so elastic growth retraces this chain per
            # ring size — bounded at log2 by the power-of-two growth,
            # asserted in CI via the jit cache size (the PR-1 recompile
            # discipline).
            zeros = jnp.zeros((N,), jnp.int32)
            carry, _ = jax.lax.scan(
                round_fn,
                (state, metrics, guards, hist, fr, spawn_seq, zeros,
                 zeros),
                (rids, faults_stack))
            return carry
        return chain

    driver = KernelFallback(args.kernel, build_chain)

    start_w = 0
    state = world["state"]
    metrics = make_metrics(N)
    guards = make_guards(N) if use_guards else None
    hist = fr = harvester = recorder = None
    if args.telemetry:
        from shadow_tpu.telemetry import (TelemetryHarvester,
                                          make_histograms)
        from shadow_tpu.telemetry import flightrec as frmod

        os.makedirs(args.telemetry, exist_ok=True)
        hist = make_histograms(N)
        harvester = TelemetryHarvester(
            interval_ns=args.harvest_every * window_ns,
            sink=os.path.join(args.telemetry, "heartbeats.jsonl"))
        if args.sample_every:
            # seeded like the fault schedule: the sampling mask is a
            # pure function of (seed, src, seq) — two identical runs
            # record byte-identical hop streams
            fr = frmod.make_flightrec(
                1234, sample_every=args.sample_every,
                ring=args.trace_ring)
            recorder = frmod.FlightRecorder(
                window_ns=window_ns,
                sink=os.path.join(args.telemetry, "hops.jsonl"))
    spawn_seq = jnp.full((N,), 10_000, jnp.int32)
    memo_obj = memo_salt_fn = None
    if args.memo:
        from shadow_tpu.tpu import memo as memomod

        # the static salt folds everything the chain closure captures
        # that the carry cannot show: world shape/caps (the params +
        # rng root are pure functions of them), the kernel choice, and
        # the respawn constants
        memo_obj = memomod.ChainMemo(salt="|".join([
            "chaos-memo-v1", f"hosts={N}", f"kernel={args.kernel}",
            f"egcap={args.egress_cap}", f"incap={args.ingress_cap}",
            f"faults={int(schedule is not None)}",
        ]).encode())  # default key_extra: folds r0 ALWAYS — respawn
        # traffic is round-indexed, so round translation is never safe

        if schedule is not None:
            def memo_salt_fn(r0, r1):
                # keep the schedule position current across hits
                # (per_round, which normally advances it, is skipped);
                # advancing to r0 is a no-op on the miss path
                schedule.advance(r0 * window_ns)
                return schedule.span_fingerprint(
                    r0 * window_ns, r1 * window_ns).encode()
        else:
            memo_salt_fn = lambda r0, r1: b"neutral"
    if args.resume:
        restored = load_plane_checkpoint(
            args.resume, state_template=state,
            faults_template=neutral_faults(N, 64),
            metrics_template=metrics)
        state = restored["state"]
        metrics = restored["metrics"]
        spawn_seq = jnp.asarray(restored["extra"]["spawn_seq"])
        if use_guards and "guards.violations" in restored["extra"]:
            guards = GuardState(**{
                f: jnp.asarray(restored["extra"][f"guards.{f}"])
                for f in GuardState._fields})
        if hist is not None and "hist.hist_qdepth" in restored["extra"]:
            # the distributions ride the checkpoint: a resumed run
            # reports the same histograms an uninterrupted one would
            from shadow_tpu.telemetry.histo import PlaneHistograms

            hist = PlaneHistograms(**{
                f: jnp.asarray(restored["extra"][f"hist.{f}"])
                for f in PlaneHistograms._fields})
        if fr is not None and "flightrec.cursor" in restored["extra"]:
            from shadow_tpu.telemetry.flightrec import FlightRecArrays

            fr = FlightRecArrays(**{
                f: jnp.asarray(restored["extra"][f"flightrec.{f}"])
                for f in FlightRecArrays._fields})
            # the prior run drained everything up to the checkpointed
            # cursor; the resumed recorder starts its window there
            recorder.seed_cursor(int(np.asarray(fr.cursor)))
        start_w = int(restored["meta"]["window_index"])
        if policy is not None and "capacity" in restored["meta"]:
            # the growth history rides the checkpoint: a resumed
            # elastic run continues from the grown capacity (the state
            # arrays already restored at their grown shapes) with the
            # same remaining growth budget, drop dedup, and trajectory
            policy.restore_meta(restored["meta"]["capacity"])
        got = state_digest(state, spawn_seq)
        want = restored["meta"].get("state_digest")
        if want and got != want:
            raise SystemExit(
                f"chaos_smoke: restored state digest {got[:12]} != "
                f"checkpointed {want[:12]} — restore is not faithful")
        if schedule is not None:
            # replay the schedule's mask state up to the restore point
            # (the schedule is a pure function of config — cheap)
            schedule.advance(start_w * window_ns)
        if memo_obj is not None and "memo" in restored["meta"]:
            # the recorded spans outlive the kill: absorb the spilled
            # cache so the resumed run reports the same memo census
            # (salt mismatch — different world/kernel — is refused)
            n = memo_obj.absorb(restored["meta"]["memo"],
                                restored["extra"], prefix="memo.",
                                source=args.resume, restore=True)
            print(f"chaos_smoke: absorbed {n} memoized span(s)",
                  file=sys.stderr)
        print(f"chaos_smoke: resumed at window {start_w} from "
              f"{args.resume}", file=sys.stderr)

    checkpoints = []
    # the shared chained-window driver (the ONE loop bench.py and the
    # scenario corpus runner also use): K windows device-resident per
    # dispatch with the fault-mask stack riding as per-round scan
    # inputs; the host regains control only at chain ends — the
    # harvest/checkpoint cadences and the tamper/kill instants, which
    # register as explicit boundaries below
    last_faults = [neutral_faults(N, 64)]
    neutral_stacks: dict[int, object] = {}

    def per_round(r0, r1):
        if schedule is None:
            # schedule-less runs feed the SAME neutral masks to every
            # window: build one stack per span length, not per chain
            k = r1 - r0
            if k not in neutral_stacks:
                neutral_stacks[k] = jax.tree.map(
                    lambda x: jnp.stack([x] * k), last_faults[0])
            return neutral_stacks[k]
        stack = []
        for r in range(r0, r1):
            schedule.advance((r + 1) * window_ns)
            stack.append(schedule.device_arrays())
        last_faults[0] = stack[-1]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *stack)

    def chain_fn(state, extras, rids, faults_stack):
        metrics, guards, hist, fr, spawn_seq = extras
        (state, metrics, guards, hist, fr, spawn_seq, eg, inn) = driver(
            state, metrics, guards, hist, fr, spawn_seq, rids,
            faults_stack)
        return state, (metrics, guards, hist, fr, spawn_seq), eg, inn

    if tracer is not None and memo_salt_fn is None \
            and schedule is not None:
        # trace-only runs still stamp fault-span fingerprints on the
        # ledger; advance-to-r0 is a no-op mid-run (per_round already
        # moved the schedule there), so digests are untouched
        def memo_salt_fn(r0, r1):
            schedule.advance(r0 * window_ns)
            return schedule.span_fingerprint(
                r0 * window_ns, r1 * window_ns).encode()

    def on_chain(r1, state, extras):
        metrics, guards, hist, fr, spawn_seq = extras
        replaced = False
        if args.tamper_at is not None and r1 == args.tamper_at:
            # deliberate corruption: a phantom valid slot at the back
            # of one ingress ring (carrying the idle sentinel) — the
            # exact single-slot damage batched execution would hide
            print(f"chaos_smoke: tampering with the device state at "
                  f"window {r1}", file=sys.stderr)
            state = state._replace(
                in_valid=state.in_valid.at[
                    1, state.in_src.shape[1] - 1].set(True))
            replaced = True
            if tracer is not None:
                tracer.annotate("tamper", r=int(r1))
        if harvester is not None and r1 % args.harvest_every == 0:
            if tracer is not None:
                tracer.annotate("harvest", r=int(r1),
                                time_ns=int(r1) * window_ns)
            harvester.tick(r1 * window_ns,
                           device={**metrics._asdict(),
                                   **hist._asdict()})
            if recorder is not None:
                recorder.tick(fr)
                if args.capacity == "elastic" and recorder.want_growth():
                    # the trace ring participates in elastic growth:
                    # an overwriting drain doubles it (power of two,
                    # bounded like every ring by --max-doublings)
                    from shadow_tpu.telemetry import flightrec as frmod

                    cur = fr.ev_kind.shape[0]
                    cap_max = args.trace_ring << args.max_doublings
                    if cur < cap_max:
                        fr = frmod.grow_ring(fr, min(cur * 2, cap_max))
                        recorder.note_grown()
                        replaced = True
                        print(f"chaos_smoke: trace ring grown to "
                              f"{fr.ev_kind.shape[0]}", file=sys.stderr)
        if args.checkpoint_dir and args.checkpoint_every \
                and r1 % args.checkpoint_every == 0 and r1 < R:
            path = os.path.join(args.checkpoint_dir,
                                f"ckpt-{r1:012d}")
            extra = {"spawn_seq": spawn_seq}
            if use_guards:
                # the guard accumulator rides the checkpoint so a
                # resumed run reports the same violation history
                extra.update({f"guards.{f}": getattr(guards, f)
                              for f in GuardState._fields})
            if hist is not None:
                # distributions + trace ring ride checkpoints too: a
                # resumed run keeps its histograms and hop stream
                extra.update({f"hist.{f}": getattr(hist, f)
                              for f in hist._fields})
            if fr is not None:
                extra.update({f"flightrec.{f}": getattr(fr, f)
                              for f in fr._fields})
            meta = {"window_index": r1, "hosts": N,
                    "state_digest": state_digest(state, spawn_seq)}
            if hist is not None:
                from shadow_tpu.telemetry import flightrec as frmod

                meta["telemetry"] = {
                    "histograms": True,
                    "flight_recorder": (frmod.flightrec_meta(fr)
                                        if fr is not None else None),
                }
            if policy is not None:
                meta["capacity"] = policy.to_meta()
            if memo_obj is not None:
                # the cache rides the checkpoint: spill the recorded
                # spans alongside the plane arrays so a resumed run
                # absorbs them (ChainMemo.spill/absorb)
                memo_meta, memo_arrays = memo_obj.spill(prefix="memo.")
                meta["memo"] = memo_meta
                extra.update(memo_arrays)
            if schedule is not None:
                # recompute the masks AT the cut from the schedule —
                # last_faults is only maintained by per_round, which a
                # memo hit skips; advance() is a no-op on the execute
                # path (per_round already walked the cursor to r1)
                schedule.advance(r1 * window_ns)
                faults_now = schedule.device_arrays()
            else:
                faults_now = last_faults[0]
            save_plane_checkpoint(
                path, state=state, clock_ns=r1 * window_ns,
                rng_key_data=jax.random.key_data(world["rng_root"]),
                faults=faults_now, metrics=metrics,
                extra_arrays=extra, meta=meta)
            checkpoints.append(path)
            if tracer is not None:
                tracer.annotate("checkpoint", r=int(r1), path=path)
        if args.kill_at is not None and r1 >= args.kill_at:
            if tracer is not None:
                tracer.annotate("kill", r=int(r1))
            print(f"chaos_smoke: simulating a crash at window {r1}",
                  file=sys.stderr)
            sys.stderr.flush()
            os._exit(137)  # abrupt: no atexit, like a SIGKILL'd run
        if replaced:
            return state, (metrics, guards, hist, fr, spawn_seq)

    boundaries = set()
    if harvester is not None:
        boundaries.update(range(args.harvest_every, R,
                                args.harvest_every))
    if args.checkpoint_dir and args.checkpoint_every:
        boundaries.update(range(args.checkpoint_every, R,
                                args.checkpoint_every))
    if args.tamper_at is not None:
        boundaries.add(args.tamper_at)
    if args.kill_at is not None:
        boundaries.add(args.kill_at)
    try:
        state, extras = elastic.drive_chained_windows(
            state, (metrics, guards, hist, fr, spawn_seq), chain_fn,
            n_rounds=R, chain_len=args.chain_len, start_round=start_w,
            boundaries=boundaries, per_round=per_round, policy=policy,
            window_ns=window_ns,
            host_names=[f"h{i}" for i in range(N)],
            on_chain=on_chain,
            memo=memo_obj, memo_span_salt=memo_salt_fn,
            tracer=tracer)
    except CapacityError as e:
        print(f"chaos_smoke: capacity abort: {e}", file=sys.stderr)
        # the driver stamps the failing chain [r0, r1) on the error:
        # under chained execution overflow is observed per chain, so
        # the span is the precise blame unit (the offending window is
        # somewhere inside it)
        span = getattr(e, "chain_span", None)
        if tracer is not None:
            # the partial ledger is the abort postmortem: every span
            # that completed before the blamed chain is on it
            tracer.annotate("capacity-abort", error=str(e),
                            chain_span=list(span) if span else None)
            tracer.close()
            tracer.write(args.trace)
        print(json.dumps({
            "capacity_error": str(e),
            "mode": policy.mode,
            "window": span[0] if span else None,
            "chain_span": list(span) if span else None,
            "egress_cap": policy.egress_cap,
            "ingress_cap": policy.ingress_cap,
        }))
        return EXIT_CAPACITY
    metrics, guards, hist, fr, spawn_seq = extras

    jax.block_until_ready(state)
    telemetry_out = None
    if harvester is not None:
        from shadow_tpu.telemetry import export
        from shadow_tpu.telemetry.histo import HIST_PREFIX, percentiles

        if R % args.harvest_every != 0:
            # the loop's cadence did not harvest the final instant
            harvester.tick(R * window_ns,
                           device={**metrics._asdict(),
                                   **hist._asdict()})
            if recorder is not None:
                recorder.tick(fr)
        harvester.finalize()
        if recorder is not None:
            recorder.tick(fr)
            recorder.finalize()
        trace_path = os.path.join(args.telemetry, "trace.json")
        trace_info = export.write_perfetto_trace(
            harvester.heartbeats, trace_path,
            hops=recorder.hops if recorder is not None else None)
        h = jax.device_get(hist)
        telemetry_out = {
            "dir": args.telemetry,
            "heartbeats": harvester.emitted,
            "trace": trace_info,
            "latency": {
                name[len(HIST_PREFIX):]: percentiles(
                    np.asarray(arr, np.int64).sum(axis=0))
                for name, arr in h._asdict().items()},
        }
        if recorder is not None:
            telemetry_out["flight_recorder"] = recorder.summary()
            telemetry_out["trace_ring"] = int(fr.ev_kind.shape[0])
    m = jax.device_get(metrics)
    out = {
        "hosts": N,
        "windows": R,
        "resumed_from": args.resume,
        "kernel": driver.kernel,
        "fell_back": driver.fell_back,
        "faults_active": schedule is not None,
        "state_digest": state_digest(state, spawn_seq),
        # dead-lane payload differs between a mid-run-grown world and a
        # pre-provisioned one (each permuted its own history's
        # compaction garbage); the canonical digest normalizes those
        # don't-care lanes, so elastic-vs-pre-provisioned parity is
        # canonical_digest equality (docs/determinism.md "Growth is
        # bitwise-invisible")
        "canonical_digest": state_digest(
            elastic.canonical_state(state), spawn_seq),
        "egress_cap": int(state.eg_dst.shape[1]),
        "ingress_cap": int(state.in_src.shape[1]),
        "drops": {
            "ring_full": int(np.asarray(m.drop_ring_full).sum()),
            "qdisc": int(np.asarray(m.drop_qdisc).sum()),
            "loss": int(np.asarray(m.drop_loss).sum()),
            "fault": int(np.asarray(m.drop_fault).sum()),
        },
        "events": int(np.asarray(m.events)),
        "checkpoints": checkpoints,
    }
    if telemetry_out is not None:
        out["telemetry"] = telemetry_out
    if memo_obj is not None:
        out["memo"] = memo_obj.stats()
    if policy is not None:
        # the jit cache size of the step IS the compile count: one
        # entry per ring shape stepped, so elastic recompiles must stay
        # within 1 + growth events (the log2 bound CI gates on)
        jit_step = getattr(driver, "_driver", None)
        cache_size = getattr(jit_step, "_cache_size", lambda: None)()
        out["capacity"] = {
            "mode": policy.mode,
            "initial": {"egress_cap": args.egress_cap,
                        "ingress_cap": args.ingress_cap},
            "final": {"egress_cap": policy.egress_cap,
                      "ingress_cap": policy.ingress_cap},
            "growth_events": len(policy.trajectory.growth_events()),
            "events": list(policy.trajectory.events),
            "step_recompiles": cache_size,
        }
    if use_guards:
        gsum = summarize(guards)
        out["guards"] = gsum
    if tracer is not None:
        if memo_obj is not None:
            tracer.memo_close(memo_obj)
        if use_guards:
            # the end-of-run guard pull rides the ledger: the delta
            # from a clean run is the per-class violation census
            tracer.annotate("guards", summary=out["guards"])
        tracer.close()
        tracer.write(args.trace)
        out["trace"] = args.trace
    if use_guards and not out["guards"]["clean"]:
        print("chaos_smoke: guard violations: "
              + json.dumps(out["guards"]["by_class"]), file=sys.stderr)
        if args.guards == "abort":
            print(json.dumps(out))
            return EXIT_GUARD
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
