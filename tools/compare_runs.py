#!/usr/bin/env python3
"""Determinism harness: run a config repeatedly — and across scheduler /
parallelism variants — and diff the deterministic artifacts.

Parity: reference determinism CI (`src/test/determinism/CMakeLists.txt` —
determinism1a/1b run identical sims twice and diff; determinism2 repeats
with `--scheduler thread-per-host` to prove event order is independent of
the parallelization strategy). Here the deterministic artifacts are
sim-stats.json (minus wall_seconds), the per-host pcap captures (exact
packet timing and content), process stdout/stderr, and — when the config
sets `experimental.strace_logging_mode: deterministic` — every managed
process's full .strace syscall trace, the reference CI's own diff target
(every hosts/ file is hashed, so strace coverage is automatic).

Usage:
  python tools/compare_runs.py <config.yaml> [--runs 2]       # repeat-diff
  python tools/compare_runs.py <config.yaml> --matrix         # vary
      scheduler (serial / thread-per-core / thread-per-host) and
      parallelism (1 / 2 / 4) and require identical artifacts across all
  python tools/compare_runs.py --bench BEFORE.json AFTER.json # diff two
      bench.py records: headline events/s plus the per-section ms deltas
      (the `sections` field), so the BENCH_r*.json trajectory shows WHERE
      time went (docs/performance.md)
Exit 0 when all runs match bit-for-bit (--bench: always); 1 otherwise.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_once(config: str, data_dir: str,
             extra_args: "Sequence[str]" = ()) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", config, "-d", data_dir, "--force",
         *extra_args],
        env=env, capture_output=True, text=True,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, file=sys.stderr)
        raise SystemExit(f"run failed (exit {proc.returncode})")
    with open(os.path.join(data_dir, "sim-stats.json")) as fh:
        stats = json.load(fh)
    stats.pop("wall_seconds", None)  # legitimately nondeterministic
    # the round COUNT is loop progress, not simulation state: a managed
    # process death is posted by the wall-clock watcher thread, so the
    # round boundary it drains at may differ while every simulated
    # observable (packets, syscalls, strace bytes, final states) is
    # identical
    stats.pop("rounds", None)
    digest = {"sim-stats": stats}
    hosts_dir = os.path.join(data_dir, "hosts")
    if os.path.isdir(hosts_dir):
        for host in sorted(os.listdir(hosts_dir)):
            for f in sorted(os.listdir(os.path.join(hosts_dir, host))):
                path = os.path.join(hosts_dir, host, f)
                with open(path, "rb") as fh:
                    digest[f"{host}/{f}"] = hashlib.sha256(fh.read()).hexdigest()
    return digest


# scheduler × parallelism variants for --matrix (determinism2 analogue);
# parallelism is pinned explicitly so a single-core runner cannot silently
# collapse the threaded variants to SerialScheduler (parallelism auto =
# min(cores, hosts))
MATRIX = [
    ("serial-p1", ["--scheduler", "serial", "--parallelism", "1"]),
    ("tpc-p2", ["--scheduler", "thread-per-core", "--parallelism", "2"]),
    ("tpc-p4", ["--scheduler", "thread-per-core", "--parallelism", "4"]),
    ("tph-p4", ["--scheduler", "thread-per-host", "--parallelism", "4"]),
]


def _load_bench(path: str) -> dict:
    with open(path) as fh:
        rec = json.load(fh)
    return rec.get("parsed", rec)  # the PR driver wraps the JSON line


def bench_delta(before_path: str, after_path: str) -> int:
    """Print the headline + per-section deltas between two bench.py JSON
    records (informational — always exits 0)."""
    before, after = _load_bench(before_path), _load_bench(after_path)
    v0, v1 = float(before.get("value", 0)), float(after.get("value", 0))
    speedup = (v1 / v0) if v0 else float("nan")
    print(f"events/s: {v0:,.0f} -> {v1:,.0f}  ({speedup:.2f}x)"
          f"  [hosts {before.get('hosts')} -> {after.get('hosts')}]")
    s0 = before.get("sections") or {}
    s1 = after.get("sections") or {}
    if not (s0 or s1):
        print("(no `sections` field in either record — re-run bench.py "
              "without BENCH_SECTIONS=0 to record the breakdown)")
        return 0
    names = sorted(set(s0) | set(s1),
                   key=lambda n: -float(s0.get(n, s1.get(n, 0))))
    print(f"{'section':<24} {'before ms':>10} {'after ms':>10} {'ratio':>7}")
    for name in names:
        a, b = s0.get(name), s1.get(name)
        ratio = (f"{a / b:.2f}x" if a and b else "-")
        fmt = lambda x: f"{x:.2f}" if x is not None else "-"
        print(f"{name:<24} {fmt(a):>10} {fmt(b):>10} {ratio:>7}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config", nargs="?")
    ap.add_argument("--runs", type=int, default=None,
                    help="repeat count (incompatible with --matrix)")
    ap.add_argument(
        "--matrix", action="store_true",
        help="vary scheduler and parallelism instead of repeating",
    )
    ap.add_argument(
        "--bench", nargs=2, metavar=("BEFORE", "AFTER"), default=None,
        help="diff two bench.py JSON records (headline + section deltas) "
             "instead of running the determinism harness",
    )
    args = ap.parse_args(argv)
    if args.bench is not None:
        if args.config or args.matrix or args.runs is not None:
            ap.error("--bench takes exactly two bench JSONs and no config")
        return bench_delta(*args.bench)
    if args.config is None:
        ap.error("config is required (or use --bench)")
    if args.matrix and args.runs is not None:
        ap.error("--runs and --matrix are mutually exclusive")

    variants = (
        MATRIX if args.matrix
        else [(f"run{i}", []) for i in range(args.runs or 2)]
    )
    digests = []
    with tempfile.TemporaryDirectory() as tmp:
        for name, extra in variants:
            digests.append(
                (name, run_once(args.config, os.path.join(tmp, name), extra))
            )
    base_name, base = digests[0]
    ok = True
    for name, d in digests[1:]:
        if d != base:
            ok = False
            for key in sorted(set(base) | set(d)):
                if base.get(key) != d.get(key):
                    print(f"MISMATCH {base_name} vs {name}: {key}")
                    print(f"  {base_name}: {base.get(key)}")
                    print(f"  {name}: {d.get(key)}")
    print("DETERMINISTIC" if ok else "NONDETERMINISTIC")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
