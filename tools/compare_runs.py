#!/usr/bin/env python3
"""Determinism harness: run a config repeatedly — and across scheduler /
parallelism variants — and diff the deterministic artifacts.

Parity: reference determinism CI (`src/test/determinism/CMakeLists.txt` —
determinism1a/1b run identical sims twice and diff; determinism2 repeats
with `--scheduler thread-per-host` to prove event order is independent of
the parallelization strategy). Here the deterministic artifacts are
sim-stats.json (minus wall_seconds), the per-host pcap captures (exact
packet timing and content), process stdout/stderr, and — when the config
sets `experimental.strace_logging_mode: deterministic` — every managed
process's full .strace syscall trace, the reference CI's own diff target
(every hosts/ file is hashed, so strace coverage is automatic).

Usage:
  python tools/compare_runs.py <config.yaml> [--runs 2]       # repeat-diff
  python tools/compare_runs.py <config.yaml> --matrix         # vary
      scheduler (serial / thread-per-core / thread-per-host) and
      parallelism (1 / 2 / 4) and require identical artifacts across all
  python tools/compare_runs.py --bench BEFORE.json AFTER.json # diff two
      bench.py records: headline events/s plus the per-section ms deltas
      (the `sections` field), so the BENCH_r*.json trajectory shows WHERE
      time went (docs/performance.md)
  python tools/compare_runs.py --scenarios BEFORE.json AFTER.json # diff
      two tools/run_scenarios.py records: headline completion-time
      deltas per scenario family plus per-scenario event/completion
      tables (docs/workloads.md)
  python tools/compare_runs.py --memo BEFORE.json AFTER.json # diff two
      tools/run_scenarios.py --memo-report files: per-scenario cache
      economics (hits / misses / fast-forwarded windows / bytes), with
      the loud MEANINGLESS banner when the backend fingerprints differ
      (docs/performance.md "Steady-state memoization")
  python tools/compare_runs.py --trace BEFORE.json AFTER.json # diff two
      tools/run_scenarios.py --trace-report files: per-scenario wall-
      time attribution deltas (total / dispatch / memo / hook, span
      modes) from the shadowscope run ledgers, with the loud
      MEANINGLESS banner when the backend fingerprints differ —
      wall-clock numbers never compare across containers
      (docs/observability.md "Run ledger")
Exit 0 when all runs match bit-for-bit (--bench/--scenarios: always);
1 otherwise.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_once(config: str, data_dir: str,
             extra_args: "Sequence[str]" = ()) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", config, "-d", data_dir, "--force",
         *extra_args],
        env=env, capture_output=True, text=True,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, file=sys.stderr)
        raise SystemExit(f"run failed (exit {proc.returncode})")
    with open(os.path.join(data_dir, "sim-stats.json")) as fh:
        stats = json.load(fh)
    stats.pop("wall_seconds", None)  # legitimately nondeterministic
    # the round COUNT is loop progress, not simulation state: a managed
    # process death is posted by the wall-clock watcher thread, so the
    # round boundary it drains at may differ while every simulated
    # observable (packets, syscalls, strace bytes, final states) is
    # identical
    stats.pop("rounds", None)
    digest = {"sim-stats": stats}
    hosts_dir = os.path.join(data_dir, "hosts")
    if os.path.isdir(hosts_dir):
        for host in sorted(os.listdir(hosts_dir)):
            for f in sorted(os.listdir(os.path.join(hosts_dir, host))):
                path = os.path.join(hosts_dir, host, f)
                with open(path, "rb") as fh:
                    digest[f"{host}/{f}"] = hashlib.sha256(fh.read()).hexdigest()
    return digest


# scheduler × parallelism variants for --matrix (determinism2 analogue);
# parallelism is pinned explicitly so a single-core runner cannot silently
# collapse the threaded variants to SerialScheduler (parallelism auto =
# min(cores, hosts))
MATRIX = [
    ("serial-p1", ["--scheduler", "serial", "--parallelism", "1"]),
    ("tpc-p2", ["--scheduler", "thread-per-core", "--parallelism", "2"]),
    ("tpc-p4", ["--scheduler", "thread-per-core", "--parallelism", "4"]),
    ("tph-p4", ["--scheduler", "thread-per-host", "--parallelism", "4"]),
]


def _load_bench(path: str) -> dict:
    with open(path) as fh:
        rec = json.load(fh)
    return rec.get("parsed", rec)  # the PR driver wraps the JSON line


def _delta_table(label: str, s0: dict, s1: dict, width: int = 24,
                 unit: str = "ms"):
    """The shared per-key before/after/ratio printer (bench sections,
    scenario completion tables, and the static cost reports all use
    the same shape; `unit` labels the value columns)."""
    names = sorted(set(s0) | set(s1),
                   key=lambda n: -float(s0.get(n, s1.get(n, 0)) or 0))
    print(f"{label:<{width}} {'before ' + unit:>12} "
          f"{'after ' + unit:>12} {'ratio':>7}")
    for name in names:
        a, b = s0.get(name), s1.get(name)
        ratio = (f"{a / b:.2f}x" if a and b else "-")
        fmt = lambda x: f"{x:.2f}" if x is not None else "-"
        print(f"{name:<{width}} {fmt(a):>12} {fmt(b):>12} {ratio:>7}")


def bench_delta(before_path: str, after_path: str) -> int:
    """Print the headline + per-section deltas between two bench.py JSON
    records (informational — always exits 0). Records measured on
    mismatched backends (the `backend` platform/device-kind
    fingerprint bench.py stamps) get a LOUD warning and no speedup
    verdict: a CPU-container number against an accelerator-container
    number is how the PR-7 false regression happened
    (docs/performance.md)."""
    before, after = _load_bench(before_path), _load_bench(after_path)
    b0, b1 = before.get("backend"), after.get("backend")
    if b0 != b1:
        print("=" * 70)
        print(f"WARNING: backend fingerprints differ — before={b0} "
              f"after={b1}.")
        print("Cross-container throughput ratios are MEANINGLESS; the "
              "delta below is\nprinted for completeness only. "
              "Re-measure both records on one container.")
        print("=" * 70)
    v0, v1 = float(before.get("value", 0)), float(after.get("value", 0))
    speedup = (v1 / v0) if v0 else float("nan")
    verdict = ("  (MISMATCHED BACKENDS — not a speedup)"
               if b0 != b1 else "")
    print(f"events/s: {v0:,.0f} -> {v1:,.0f}  ({speedup:.2f}x){verdict}"
          f"  [hosts {before.get('hosts')} -> {after.get('hosts')}]")
    s0 = dict(before.get("sections") or {})
    s1 = dict(after.get("sections") or {})
    # windows_per_sync is a dimensionless driver ratio riding in
    # `sections` for the trajectory record — print it as one, never as
    # a millisecond row in the table below
    w0 = s0.pop("windows_per_sync", None)
    w1 = s1.pop("windows_per_sync", None)
    if w0 is not None or w1 is not None:
        print(f"windows/sync: {w0} -> {w1}")
    if not (s0 or s1):
        print("(no `sections` field in either record — re-run bench.py "
              "without BENCH_SECTIONS=0 to record the breakdown)")
        return 0
    _delta_table("section", s0, s1)
    return 0


def _scenario_completions(path: str) -> tuple[dict, dict, dict]:
    """Load a run_scenarios.py record file -> (per-family completion ms,
    per-scenario completion ms, per-scenario fingerprints)."""
    with open(path) as fh:
        records = json.load(fh).get("records", [])
    family: dict[str, float] = {}
    per_scenario: dict[str, float] = {}
    fps: dict[str, str] = {}
    for rec in records:
        hc = rec.get("host_completion") or {}
        done_ms = (hc.get("max_ns") / 1e6
                   if hc.get("max_ns") is not None else None)
        fps[rec["name"]] = rec.get("fingerprint", "")
        if done_ms is None:
            continue  # incomplete scenario: no headline time
        per_scenario[rec["name"]] = done_ms
        fam = rec.get("family", "?")
        family[fam] = max(family.get(fam, 0.0), done_ms)
    return family, per_scenario, fps


def scenarios_delta(before_path: str, after_path: str) -> int:
    """Print headline completion-time deltas per scenario family, then
    the per-scenario table, between two tools/run_scenarios.py record
    files (informational — always exits 0). Completion times are the
    virtual host_completion.max_ns headline (straggler-inclusive); a
    fingerprint mismatch is flagged since the delta then compares two
    DIFFERENT scenarios, not two runs of one."""
    f0, s0, fp0 = _scenario_completions(before_path)
    f1, s1, fp1 = _scenario_completions(after_path)
    print("scenario-family completion (virtual ms, max over family):")
    _delta_table("family", f0, f1)
    print()
    print("per-scenario completion (virtual ms):")
    _delta_table("scenario", s0, s1)
    for name in sorted(set(fp0) & set(fp1)):
        if fp0[name] != fp1[name]:
            print(f"NOTE: {name}: scenario fingerprint changed between "
                  f"the records — this is a different scenario, not a "
                  f"behavior delta")
    return 0


def _memo_report(path: str) -> tuple[dict | None, dict]:
    """Load a run_scenarios.py --memo-report file -> (backend
    fingerprint, scenario name -> memo stats dict)."""
    with open(path) as fh:
        rec = json.load(fh)
    return rec.get("backend"), dict(rec.get("scenarios") or {})


def memo_delta(before_path: str, after_path: str) -> int:
    """Print per-scenario memo cache-economics deltas (hits, misses,
    fast-forwarded windows, cached bytes) between two run_scenarios.py
    --memo-report files (informational — always exits 0). Reports from
    mismatched backends get the loud banner: memo keys digest device
    bytes, so two containers legitimately populate DIFFERENT caches —
    a hit-rate regression across containers is a fingerprint delta,
    not a memo-plane one (the bench backend-fingerprint rule,
    docs/performance.md)."""
    b0, s0 = _memo_report(before_path)
    b1, s1 = _memo_report(after_path)
    if b0 != b1:
        print("=" * 70)
        print(f"WARNING: backend fingerprints differ — before={b0} "
              f"after={b1}.")
        print("Memo keys digest device bytes, so cross-container "
              "hit-rate deltas are\nMEANINGLESS; the tables below are "
              "printed for completeness only.\nRe-measure both "
              "reports on one container.")
        print("=" * 70)

    def table(metric, unit="count"):
        t0 = {k: v.get(metric) for k, v in s0.items()
              if v.get(metric) is not None}
        t1 = {k: v.get(metric) for k, v in s1.items()
              if v.get(metric) is not None}
        if t0 or t1:
            _delta_table(f"scenario ({metric})", t0, t1, width=32,
                         unit=unit)
            print()

    table("hits")
    table("misses")
    table("fast_forwarded_windows", "windows")
    table("unstable_skips")
    table("bytes_cached", "B")
    return 0


def _trace_report(path: str) -> tuple[dict | None, dict]:
    """Load a run_scenarios.py --trace-report file -> (backend
    fingerprint, scenario name -> phase-totals dict)."""
    with open(path) as fh:
        rec = json.load(fh)
    return rec.get("backend"), dict(rec.get("scenarios") or {})


def trace_delta(before_path: str, after_path: str) -> int:
    """Print per-scenario wall-time attribution deltas between two
    run_scenarios.py --trace-report files (informational — always
    exits 0). Every value here is wall clock, so this mode carries the
    loudest version of the backend-fingerprint rule: a ledger from a
    different container times a different machine, and the banner says
    so before any table prints (docs/observability.md "Run ledger")."""
    b0, s0 = _trace_report(before_path)
    b1, s1 = _trace_report(after_path)
    if b0 != b1:
        print("=" * 70)
        print(f"WARNING: backend fingerprints differ — before={b0} "
              f"after={b1}.")
        print("Every number on a run ledger is wall clock, so "
              "cross-container deltas are\nMEANINGLESS; the tables "
              "below are printed for completeness only.\nRe-trace "
              "both runs on one container.")
        print("=" * 70)

    def table(metric, unit="ms"):
        t0 = {k: v.get(metric) for k, v in s0.items()
              if v.get(metric) is not None}
        t1 = {k: v.get(metric) for k, v in s1.items()
              if v.get(metric) is not None}
        if t0 or t1:
            _delta_table(f"scenario ({metric})", t0, t1, width=32,
                         unit=unit)
            print()

    table("wall_ms")
    table("dispatch_ms")
    table("memo_ms")
    table("hook_ms")
    table("replay_ms")
    table("ffwd_ms")
    table("spans", "count")
    table("growth_events", "count")
    return 0


def _slo_report(path: str) -> tuple[dict | None, dict]:
    """Load a run_scenarios.py --slo-report file -> (backend
    fingerprint, scenario name -> {compute, slo} dict)."""
    with open(path) as fh:
        rec = json.load(fh)
    return rec.get("backend"), dict(rec.get("scenarios") or {})


def slo_delta(before_path: str, after_path: str) -> int:
    """Print per-scenario serving-SLO deltas between two
    run_scenarios.py --slo-report files (informational — always exits
    0): request-sojourn/wait percentiles in virtual ms plus the
    compute-plane served/queued/overflow totals. The values are
    VIRTUAL time (deterministic), so no backend banner — but a missed
    SLO target in the AFTER record is called out per scenario
    (docs/workloads.md 'SLO record schema')."""
    _, s0 = _slo_report(before_path)
    _, s1 = _slo_report(after_path)

    def table(pick, label, unit="ms"):
        t0 = {k: pick(v) for k, v in s0.items() if pick(v) is not None}
        t1 = {k: pick(v) for k, v in s1.items() if pick(v) is not None}
        if t0 or t1:
            _delta_table(f"scenario ({label})", t0, t1, width=32,
                         unit=unit)
            print()

    for q in ("p99", "p999"):
        table(lambda v, q=q: (v["slo"]["sojourn_ns"].get(q, 0) / 1e6
                              if "slo" in v else None),
              f"sojourn {q}")
        table(lambda v, q=q: (v["slo"]["wait_ns"].get(q, 0) / 1e6
                              if "slo" in v else None),
              f"wait {q}")
    for metric in ("served", "queued", "overflow"):
        table(lambda v, m=metric: (v.get("compute") or {}).get(m),
              metric, unit="count")
    missed = [(name, q, t)
              for name, v in sorted(s1.items())
              for q, t in (v.get("slo", {}).get("targets") or {}).items()
              if not t.get("met", True)]
    for name, q, t in missed:
        print(f"SLO MISS (after): {name} {q} measured "
              f"{t['measured_ns']} ns > target {t['target_ns']} ns")
    return 0


def _cost_metrics(path: str) -> tuple[str | None, dict]:
    """Load a shadowlint --cost-report record -> (platform key,
    entry short-name -> metrics dict)."""
    with open(path) as fh:
        rec = json.load(fh)
    per_entry = {}
    for section in rec.get("entries", []):
        short = section["entry"].rsplit(":", 1)[-1]
        per_entry[short] = dict(section.get("metrics") or {})
    return rec.get("platform"), per_entry


def cost_delta(before_path: str, after_path: str) -> int:
    """Print per-entry flops / bytes-accessed / fusion-count deltas
    between two shadowlint cost reports (informational — always exits
    0). Reports whose PLATFORM keys differ get the loud banner: the
    static-analysis twin of the bench backend-fingerprint rule — an
    accelerator compile diffed against a CPU compile is a different
    program, not a cost delta (docs/performance.md)."""
    p0, e0 = _cost_metrics(before_path)
    p1, e1 = _cost_metrics(after_path)
    if p0 != p1:
        print("=" * 70)
        print(f"WARNING: platform keys differ — before={p0} "
              f"after={p1}.")
        print("The two reports budget DIFFERENT compiled programs; "
              "the deltas below are\nprinted for completeness only. "
              "Regenerate both reports on one platform.")
        print("=" * 70)

    def table(metric, unit):
        s0 = {k: v.get(metric) for k, v in e0.items()
              if v.get(metric) is not None}
        s1 = {k: v.get(metric) for k, v in e1.items()
              if v.get(metric) is not None}
        if s0 or s1:
            _delta_table(f"entry ({metric})", s0, s1, width=32,
                         unit=unit)
            print()

    table("flops", "flops")
    table("bytes_accessed", "B")
    table("fusions", "count")
    table("big_boundaries", "count")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config", nargs="?")
    ap.add_argument("--runs", type=int, default=None,
                    help="repeat count (incompatible with --matrix)")
    ap.add_argument(
        "--matrix", action="store_true",
        help="vary scheduler and parallelism instead of repeating",
    )
    ap.add_argument(
        "--bench", nargs=2, metavar=("BEFORE", "AFTER"), default=None,
        help="diff two bench.py JSON records (headline + section deltas) "
             "instead of running the determinism harness",
    )
    ap.add_argument(
        "--scenarios", nargs=2, metavar=("BEFORE", "AFTER"), default=None,
        help="diff two tools/run_scenarios.py record files (completion-"
             "time deltas per scenario family) instead of running the "
             "determinism harness",
    )
    ap.add_argument(
        "--cost", nargs=2, metavar=("BEFORE", "AFTER"), default=None,
        help="diff two shadowlint --cost-report records (per-entry "
             "flops/bytes/fusion-count deltas; loud banner when the "
             "platform keys differ) instead of running the "
             "determinism harness",
    )
    ap.add_argument(
        "--memo", nargs=2, metavar=("BEFORE", "AFTER"), default=None,
        help="diff two tools/run_scenarios.py --memo-report files "
             "(per-scenario hit/miss/fast-forward/bytes deltas; loud "
             "banner when the backend fingerprints differ) instead "
             "of running the determinism harness",
    )
    ap.add_argument(
        "--trace", nargs=2, metavar=("BEFORE", "AFTER"), default=None,
        help="diff two tools/run_scenarios.py --trace-report files "
             "(per-scenario wall-time attribution deltas from the run "
             "ledgers; loud banner when the backend fingerprints "
             "differ) instead of running the determinism harness",
    )
    ap.add_argument(
        "--slo", nargs=2, metavar=("BEFORE", "AFTER"), default=None,
        help="diff two tools/run_scenarios.py --slo-report files "
             "(per-scenario serving sojourn/wait percentile + "
             "compute-plane totals deltas; SLO misses in the AFTER "
             "record are called out) instead of running the "
             "determinism harness",
    )
    args = ap.parse_args(argv)
    modes = [m for m in (args.bench, args.scenarios, args.cost,
                         args.memo, args.trace, args.slo)
             if m is not None]
    if len(modes) > 1:
        ap.error("--bench/--scenarios/--cost/--memo/--trace/--slo are "
                 "mutually exclusive")
    if args.bench is not None:
        if args.config or args.matrix or args.runs is not None:
            ap.error("--bench takes exactly two bench JSONs and no config")
        return bench_delta(*args.bench)
    if args.scenarios is not None:
        if args.config or args.matrix or args.runs is not None:
            ap.error("--scenarios takes exactly two scenario record "
                     "files and no config")
        return scenarios_delta(*args.scenarios)
    if args.cost is not None:
        if args.config or args.matrix or args.runs is not None:
            ap.error("--cost takes exactly two cost reports and no "
                     "config")
        return cost_delta(*args.cost)
    if args.memo is not None:
        if args.config or args.matrix or args.runs is not None:
            ap.error("--memo takes exactly two memo reports and no "
                     "config")
        return memo_delta(*args.memo)
    if args.trace is not None:
        if args.config or args.matrix or args.runs is not None:
            ap.error("--trace takes exactly two trace reports and no "
                     "config")
        return trace_delta(*args.trace)
    if args.slo is not None:
        if args.config or args.matrix or args.runs is not None:
            ap.error("--slo takes exactly two slo reports and no "
                     "config")
        return slo_delta(*args.slo)
    if args.config is None:
        ap.error("config is required (or use --bench)")
    if args.matrix and args.runs is not None:
        ap.error("--runs and --matrix are mutually exclusive")

    variants = (
        MATRIX if args.matrix
        else [(f"run{i}", []) for i in range(args.runs or 2)]
    )
    digests = []
    with tempfile.TemporaryDirectory() as tmp:
        for name, extra in variants:
            digests.append(
                (name, run_once(args.config, os.path.join(tmp, name), extra))
            )
    base_name, base = digests[0]
    ok = True
    for name, d in digests[1:]:
        if d != base:
            ok = False
            for key in sorted(set(base) | set(d)):
                if base.get(key) != d.get(key):
                    print(f"MISMATCH {base_name} vs {name}: {key}")
                    print(f"  {base_name}: {base.get(key)}")
                    print(f"  {name}: {d.get(key)}")
    print("DETERMINISTIC" if ok else "NONDETERMINISTIC")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
