#!/usr/bin/env python3
"""Determinism harness: run a config twice and diff the deterministic
artifacts.

Parity: reference determinism CI (`src/test/determinism/CMakeLists.txt` —
run identical sims twice, strip nondeterministic lines with
`strip_log_for_compare.py`, diff). Here the deterministic artifacts are
sim-stats.json (minus wall_seconds) and the per-host pcap captures, which
encode exact packet timing and content.

Usage: python tools/compare_runs.py <config.yaml> [--runs 2]
Exit 0 when all runs match bit-for-bit; 1 otherwise.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_once(config: str, data_dir: str) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "shadow_tpu", config, "-d", data_dir, "--force"],
        env=env, capture_output=True, text=True,
    )
    if proc.returncode != 0:
        print(proc.stdout, proc.stderr, file=sys.stderr)
        raise SystemExit(f"run failed (exit {proc.returncode})")
    with open(os.path.join(data_dir, "sim-stats.json")) as fh:
        stats = json.load(fh)
    stats.pop("wall_seconds", None)  # the one legitimately nondeterministic field
    digest = {"sim-stats": stats}
    hosts_dir = os.path.join(data_dir, "hosts")
    if os.path.isdir(hosts_dir):
        for host in sorted(os.listdir(hosts_dir)):
            for f in sorted(os.listdir(os.path.join(hosts_dir, host))):
                path = os.path.join(hosts_dir, host, f)
                with open(path, "rb") as fh:
                    digest[f"{host}/{f}"] = hashlib.sha256(fh.read()).hexdigest()
    return digest


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config")
    ap.add_argument("--runs", type=int, default=2)
    args = ap.parse_args(argv)

    digests = []
    with tempfile.TemporaryDirectory() as tmp:
        for i in range(args.runs):
            digests.append(run_once(args.config, os.path.join(tmp, f"run{i}")))
    base = digests[0]
    ok = True
    for i, d in enumerate(digests[1:], start=2):
        if d != base:
            ok = False
            for key in sorted(set(base) | set(d)):
                if base.get(key) != d.get(key):
                    print(f"MISMATCH run1 vs run{i}: {key}")
                    print(f"  run1: {base.get(key)}")
                    print(f"  run{i}: {d.get(key)}")
    print("DETERMINISTIC" if ok else "NONDETERMINISTIC")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
