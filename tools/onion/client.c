/* Onion client for the rung-4 Tor-shaped workload: builds a layered
 * frame for a 3-hop circuit (guard -> middle -> exit) and sends the
 * payload through it, waiting for the ack to ride back. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

static int write_full(int fd, const void *buf, size_t n) {
    const char *p = buf;
    while (n) {
        ssize_t r = write(fd, p, n);
        if (r <= 0) return -1;
        p += r; n -= (size_t)r;
    }
    return 0;
}

static size_t wrap(unsigned char *dst, uint32_t ip_net, uint16_t port_net,
                   const unsigned char *inner, size_t inner_len) {
    uint32_t len_be = htonl((uint32_t)inner_len);
    memcpy(dst, &ip_net, 4);
    memcpy(dst + 4, &port_net, 2);
    memcpy(dst + 6, &len_be, 4);
    memcpy(dst + 10, inner, inner_len);
    return inner_len + 10;
}

int main(int argc, char **argv) {
    /* argv: g_ip g_port m_ip m_port e_ip e_port payload_bytes */
    if (argc < 8) return 2;
    struct in_addr g, m, e;
    if (!inet_aton(argv[1], &g) || !inet_aton(argv[3], &m)
            || !inet_aton(argv[5], &e)) return 2;
    uint16_t gp = htons((uint16_t)atoi(argv[2]));
    uint16_t mp = htons((uint16_t)atoi(argv[4]));
    uint16_t ep = htons((uint16_t)atoi(argv[6]));
    size_t payload = (size_t)atol(argv[7]);
    static unsigned char a[1 << 20], b[1 << 20];
    if (payload > sizeof a - 64) return 2;
    memset(a, 0x5a, payload);
    size_t n = wrap(b, 0, 0, a, payload);          /* exit layer */
    n = wrap(a, e.s_addr, ep, b, n);               /* middle -> exit */
    n = wrap(b, m.s_addr, mp, a, n);               /* guard -> middle */

    int s = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in dst = {0};
    dst.sin_family = AF_INET;
    dst.sin_addr = g;
    dst.sin_port = gp;
    if (connect(s, (struct sockaddr *)&dst, sizeof dst)) {
        perror("client connect");
        return 1;
    }
    if (write_full(s, b, n)) return 1;
    unsigned char ack;
    ssize_t r = read(s, &ack, 1);
    if (r != 1 || ack != 'A') return 1;
    printf("circuit complete: %zu bytes through 3 hops\n", payload);
    return 0;
}
