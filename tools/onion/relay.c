/* Onion-style store-and-forward relay for the rung-4 Tor-shaped
 * workload (BASELINE.md ladder; reference analogue:
 * src/test/tor/minimal/tor-minimal.yaml, which this image cannot run —
 * no tor binary exists here, so the SHAPE is rebuilt: real compiled
 * relay processes doing layered store-and-forward over a latency/loss
 * GML, with acks riding the circuit back).
 *
 * Protocol per connection (all big-endian):
 *   [4B next_ip][2B next_port][4B len][len bytes inner frame]
 * next_ip == 0 marks the exit: consume the payload, send 1-byte ack.
 * Otherwise STORE the whole inner frame, then FORWARD it to the next
 * hop, wait for its ack, and relay the ack backward. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

static int read_full(int fd, void *buf, size_t n) {
    char *p = buf;
    while (n) {
        ssize_t r = read(fd, p, n);
        if (r <= 0) return -1;
        p += r; n -= (size_t)r;
    }
    return 0;
}

static int write_full(int fd, const void *buf, size_t n) {
    const char *p = buf;
    while (n) {
        ssize_t r = write(fd, p, n);
        if (r <= 0) return -1;
        p += r; n -= (size_t)r;
    }
    return 0;
}

int main(int argc, char **argv) {
    if (argc < 2) return 2;
    int port = atoi(argv[1]);
    int circuits = argc > 2 ? atoi(argv[2]) : -1; /* -1: serve forever */
    int lst = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(lst, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_addr.s_addr = INADDR_ANY;
    a.sin_port = htons((uint16_t)port);
    if (bind(lst, (struct sockaddr *)&a, sizeof a) || listen(lst, 64)) {
        perror("relay bind/listen");
        return 1;
    }
    static char buf[1 << 20];
    for (int served = 0; circuits < 0 || served < circuits; served++) {
        int c = accept(lst, 0, 0);
        if (c < 0) return 1;
        unsigned char hdr[10];
        if (read_full(c, hdr, 10)) { close(c); continue; }
        uint32_t ip; uint16_t nport; uint32_t len;
        memcpy(&ip, hdr, 4);
        memcpy(&nport, hdr + 4, 2);
        memcpy(&len, hdr + 6, 4);
        len = ntohl(len);
        if (len > sizeof buf || read_full(c, buf, len)) { close(c); continue; }
        unsigned char ack = 'A';
        if (ip == 0) { /* exit node: payload consumed */
            if (write_full(c, &ack, 1)) { close(c); continue; }
        } else {
            int n = socket(AF_INET, SOCK_STREAM, 0);
            struct sockaddr_in nx = {0};
            nx.sin_family = AF_INET;
            nx.sin_addr.s_addr = ip; /* already network order */
            nx.sin_port = nport;
            if (connect(n, (struct sockaddr *)&nx, sizeof nx)
                    || write_full(n, buf, len)
                    || read_full(n, &ack, 1)) { close(n); close(c); continue; }
            close(n);
            write_full(c, &ack, 1); /* ack rides the circuit back */
        }
        close(c);
    }
    return 0;
}
