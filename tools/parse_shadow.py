#!/usr/bin/env python3
"""Parse a shadow_tpu simulation log into plottable JSON.

Parity: reference `src/tools/parse-shadow.py` — extracts per-host tracker
heartbeats and manager rusage/meminfo heartbeats from the log stream and
writes `stats.shadow.json`, without ever materialising a decompressed log
on disk (xz input and stdin are supported).

Usage:
  python tools/parse_shadow.py shadow.log          # or shadow.log.xz
  cat shadow.log | python tools/parse_shadow.py -
  python tools/parse_shadow.py shadow.log -p outdir
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

OUTPUT_NAME = "stats.shadow.json"

# tracker heartbeats: "... heartbeat host=alpha time_ns=1000000000 {json}"
HEARTBEAT_RE = re.compile(
    r"heartbeat host=(\S+) time_ns=(\d+) (\{.*\})\s*$")
# the tornettools-contract rusage line (`manager.rs:692-707`)
RUSAGE_RE = re.compile(
    r"Process resource usage at simtime (\d+) reported by getrusage\(\): "
    r"ru_maxrss=([\d.]+) GiB, ru_utime=([\d.]+) minutes, "
    r"ru_stime=([\d.]+) minutes, ru_nvcsw=(\d+), ru_nivcsw=(\d+)")
MEMINFO_RE = re.compile(
    r"System memory usage in bytes at simtime (\d+) ns reported by "
    r"/proc/meminfo: (\{.*\})\s*$")


def open_log(path: str):
    if path == "-":
        return sys.stdin
    if path.endswith(".xz"):
        import lzma

        return lzma.open(path, "rt")
    return open(path)


def parse_stream(stream) -> dict:
    nodes: dict[str, dict] = {}
    rusage: list[dict] = []
    meminfo: list[dict] = []
    for line in stream:
        m = HEARTBEAT_RE.search(line)
        if m:
            host, time_ns, payload = m.group(1), int(m.group(2)), m.group(3)
            try:
                counters = json.loads(payload)
            except json.JSONDecodeError:
                continue
            entry = nodes.setdefault(host, {"time_ns": [], "counters": []})
            entry["time_ns"].append(time_ns)
            entry["counters"].append(counters)
            continue
        m = RUSAGE_RE.search(line)
        if m:
            rusage.append({
                "time_ns": int(m.group(1)),
                "maxrss_gib": float(m.group(2)),
                "utime_minutes": float(m.group(3)),
                "stime_minutes": float(m.group(4)),
                "nvcsw": int(m.group(5)),
                "nivcsw": int(m.group(6)),
            })
            continue
        m = MEMINFO_RE.search(line)
        if m:
            try:
                fields = json.loads(m.group(2))
            except json.JSONDecodeError:
                continue
            meminfo.append({"time_ns": int(m.group(1)), **fields})
    return {"nodes": nodes, "rusage": rusage, "meminfo": meminfo}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logpath", metavar="PATH",
                    help="shadow log file, '.xz' for compressed, '-' for stdin")
    ap.add_argument("-p", "--prefix", default=".",
                    help="output directory for " + OUTPUT_NAME)
    args = ap.parse_args(argv)

    stream = open_log(args.logpath)
    try:
        stats = parse_stream(stream)
    finally:
        if stream is not sys.stdin:
            stream.close()

    os.makedirs(args.prefix, exist_ok=True)
    out_path = os.path.join(args.prefix, OUTPUT_NAME)
    with open(out_path, "w") as fh:
        json.dump(stats, fh, indent=2)
    n_hosts = len(stats["nodes"])
    n_ticks = sum(len(v["time_ns"]) for v in stats["nodes"].values())
    print(f"wrote {out_path}: {n_hosts} hosts, {n_ticks} heartbeats, "
          f"{len(stats['rusage'])} rusage samples")
    return 0


if __name__ == "__main__":
    sys.exit(main())
