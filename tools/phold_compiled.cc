// Compiled PHOLD object-plane microbenchmark: an honest price for
// "compiled-Shadow-class" per-event cost on THIS machine.
//
// The reference's hot loop (/root/reference/src/main/host/host.rs:810-865)
// is compiled Rust: pop the next event, run its packet through
// router/interface bookkeeping, draw randomness, schedule the successor.
// This ~200-line C++ twin prices the same SHAPE of work — binary-heap
// pop/push, xoshiro256++ draws (loss + destination + think time), a
// node-level latency lookup, and a per-host FIFO hop — with none of the
// reference's remaining overheads (no sockets, no syscalls, no qdisc
// variants, no refcounting). It is therefore an OPTIMISTIC baseline: a
// real compiled simulator pays MORE per event than this floor, so a
// `vs_compiled` ratio against it understates the rebuild, never flatters
// it. bench.py builds and runs this and reports the ratio alongside the
// Python-object-plane one (methodology: BASELINE.md).
//
// Usage: phold_compiled [n_hosts] [n_nodes] [events_millions]
// Output: one JSON line {"events": N, "wall_s": W, "events_per_sec": R}

#include <ctime>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <queue>
#include <vector>

namespace {

struct Xoshiro {
    uint64_t s[4];
    static uint64_t rotl(uint64_t x, int k) {
        return (x << k) | (x >> (64 - k));
    }
    explicit Xoshiro(uint64_t seed) {
        // splitmix64 init, like core/rng.py
        uint64_t z = seed;
        for (auto &w : s) {
            z += 0x9e3779b97f4a7c15ULL;
            uint64_t t = z;
            t = (t ^ (t >> 30)) * 0xbf58476d1ce4e5b9ULL;
            t = (t ^ (t >> 27)) * 0x94d049bb133111ebULL;
            w = t ^ (t >> 31);
        }
    }
    uint64_t next() {
        uint64_t result = rotl(s[0] + s[3], 23) + s[0];
        uint64_t t = s[1] << 17;
        s[2] ^= s[0];
        s[3] ^= s[1];
        s[1] ^= s[2];
        s[0] ^= s[3];
        s[2] ^= t;
        s[3] = rotl(s[3], 45);
        return result;
    }
};

struct Event {
    int64_t time_ns;
    uint64_t seq;  // FIFO tie-break, like core/event.py ordering
    int32_t host;
    bool operator>(const Event &o) const {
        if (time_ns != o.time_ns) return time_ns > o.time_ns;
        return seq > o.seq;
    }
};

}  // namespace

int main(int argc, char **argv) {
    const int n_hosts = argc > 1 ? std::atoi(argv[1]) : 64;
    const int n_nodes = argc > 2 ? std::atoi(argv[2]) : 64;
    const int64_t target =
        (argc > 3 ? std::atoll(argv[3]) : 20) * 1'000'000LL;

    Xoshiro rng(1);
    // node-level latency table, the shape the GML topologies have
    std::vector<int32_t> lat(static_cast<size_t>(n_nodes) * n_nodes);
    for (auto &v : lat) v = 1'000'000 + static_cast<int32_t>(rng.next() % 49'000'000);
    std::vector<int32_t> host_node(n_hosts);
    for (int i = 0; i < n_hosts; i++) host_node[i] = i % n_nodes;

    // per-host RNG streams + in-flight FIFO depth (the interface hop)
    std::vector<Xoshiro> host_rng;
    host_rng.reserve(n_hosts);
    for (int i = 0; i < n_hosts; i++) host_rng.emplace_back(1000 + i);
    std::vector<int32_t> fifo_depth(n_hosts, 0);

    std::priority_queue<Event, std::vector<Event>, std::greater<Event>> q;
    uint64_t seq = 0;
    for (int i = 0; i < n_hosts; i++)
        for (int k = 0; k < 4; k++)
            q.push({1'000'000, seq++, i});

    int64_t events = 0;
    uint64_t losses = 0;
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    while (events < target) {
        Event ev = q.top();
        q.pop();
        events++;
        Xoshiro &r = host_rng[ev.host];
        // loss draw (1%), like the worker's per-packet Bernoulli
        if ((r.next() >> 11) < (uint64_t)(0.01 * (1ULL << 53))) {
            losses++;
            // lost packets respawn at the source so the population holds
        }
        // pick the successor destination + think time
        int32_t dst = static_cast<int32_t>(r.next() % n_hosts);
        int32_t l = lat[static_cast<size_t>(host_node[ev.host]) * n_nodes +
                        host_node[dst]];
        int32_t think = static_cast<int32_t>(r.next() % 1'000'000);
        fifo_depth[ev.host] = (fifo_depth[ev.host] + 1) & 15;  // qdisc hop
        q.push({ev.time_ns + l + think, seq++, dst});
    }
    clock_gettime(CLOCK_MONOTONIC, &t1);
    double wall = (t1.tv_sec - t0.tv_sec) + (t1.tv_nsec - t0.tv_nsec) / 1e9;
    // losses participates in output so the loss draw cannot be DCE'd
    std::printf(
        "{\"events\": %lld, \"wall_s\": %.3f, \"events_per_sec\": %.0f, "
        "\"losses\": %llu}\n",
        static_cast<long long>(events), wall, events / wall,
        static_cast<unsigned long long>(losses));
    return 0;
}
