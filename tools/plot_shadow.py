#!/usr/bin/env python3
"""Plot stats.shadow.json files produced by parse_shadow.py.

Parity: reference `src/tools/plot-shadow.py` — per-host throughput over
simulated time and simulator rusage over time, one page per metric,
multiple datasets overlaid for comparisons.

Usage:
  python tools/plot_shadow.py -d run1/stats.shadow.json run1 \
                              -d run2/stats.shadow.json run2 \
                              -p comparison
"""

from __future__ import annotations

import argparse
import json
import sys


def _throughput_series(node: dict, key: str):
    """Per-interval deltas of a cumulative counter, in bytes/sec."""
    times, values = node["time_ns"], node["counters"]
    xs, ys = [], []
    prev_t, prev_v = None, None
    for t, c in zip(times, values):
        v = c.get(key, 0)
        if prev_t is not None and t > prev_t:
            xs.append(t / 1e9)
            ys.append((v - prev_v) / ((t - prev_t) / 1e9))
        prev_t, prev_v = t, v
    return xs, ys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-d", "--data", nargs=2, action="append", required=True,
                    metavar=("PATH", "LABEL"),
                    help="stats.shadow.json and a label; repeatable")
    ap.add_argument("-p", "--prefix", default="shadow.plot",
                    help="output file prefix")
    ap.add_argument("--format", default="pdf", choices=("pdf", "png"))
    args = ap.parse_args(argv)

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib is not available; install it to plot",
              file=sys.stderr)
        return 1

    datasets = []
    for path, label in args.data:
        with open(path) as fh:
            datasets.append((label, json.load(fh)))

    pages = [
        ("bytes_out", "sent bytes/s"),
        ("bytes_in", "received bytes/s"),
        ("packets_dropped", "cumulative dropped packets"),
    ]
    for key, title in pages:
        fig, ax = plt.subplots(figsize=(8, 5))
        for label, stats in datasets:
            for host, node in sorted(stats["nodes"].items()):
                if key.startswith("bytes"):
                    xs, ys = _throughput_series(node, key)
                else:
                    xs = [t / 1e9 for t in node["time_ns"]]
                    ys = [c.get(key, 0) for c in node["counters"]]
                ax.plot(xs, ys, label=f"{label}:{host}", alpha=0.8)
        ax.set_xlabel("simulated seconds")
        ax.set_ylabel(title)
        ax.set_title(title)
        if sum(len(s["nodes"]) for _l, s in datasets) <= 12:
            ax.legend(fontsize=7)
        out = f"{args.prefix}.{key}.{args.format}"
        fig.savefig(out, bbox_inches="tight")
        plt.close(fig)
        print("wrote", out)

    # simulator resource usage over simulated time
    fig, ax = plt.subplots(figsize=(8, 5))
    plotted = False
    for label, stats in datasets:
        ru = stats.get("rusage", [])
        if not ru:
            continue
        ax.plot([r["time_ns"] / 1e9 for r in ru],
                [r["maxrss_gib"] for r in ru], label=label)
        plotted = True
    if plotted:
        ax.set_xlabel("simulated seconds")
        ax.set_ylabel("ru_maxrss (GiB)")
        ax.set_title("simulator memory usage")
        ax.legend(fontsize=8)
        out = f"{args.prefix}.rusage.{args.format}"
        fig.savefig(out, bbox_inches="tight")
        print("wrote", out)
    plt.close(fig)
    return 0


if __name__ == "__main__":
    sys.exit(main())
