#!/usr/bin/env python3
"""Profile the device-plane window step per section.

Times every section of `plane.window_step` (qdisc sort, RR tensors,
loss+latency gathers, routing scatter — split into its routing_rank /
routing_place sub-sections — ingress compaction, CoDel drain, ...) as
isolated jitted micro-kernels at one or more bench-ladder shapes, and
emits a JSON cost breakdown. This is the measurement substrate for
every window-step optimization claim: run it with `--legacy-sort` to
price the pre-diet variadic sorts against the packed-key default.

    python tools/profile_plane.py                       # default shapes
    python tools/profile_plane.py --hosts 1024 --reps 5
    python tools/profile_plane.py --legacy-sort -o before.json
    python tools/profile_plane.py --kernel pallas       # fused kernels
    python tools/profile_plane.py \
        --sections routing_scatter,routing_rank,routing_place

See docs/performance.md for the cost model the sections map onto.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--hosts", default="1024,8192",
                    help="comma-separated host counts (default 1024,8192)")
    ap.add_argument("--reps", type=int, default=20,
                    help="timed repetitions per section (default 20)")
    ap.add_argument("--egress-cap", type=int, default=16)
    ap.add_argument("--ingress-cap", type=int, default=32)
    ap.add_argument("--nodes", type=int, default=64,
                    help="graph nodes for the path tables (default 64)")
    ap.add_argument("--rr", action="store_true",
                    help="profile with the RR qdisc compiled in")
    ap.add_argument("--legacy-sort", action="store_true",
                    help="time the pre-diet variadic sorts "
                         "(packed_sort=False) for before/after comparison")
    ap.add_argument("--kernel",
                    choices=("xla", "pallas", "pallas_fused"),
                    default="xla",
                    help="window_step plane kernel (default xla; "
                         "pallas = two-dispatch egress+route fusion, "
                         "pallas_fused = the single rank→place→egress "
                         "pipeline)")
    ap.add_argument("--sections", default=None,
                    help="comma-separated subset of sections to time")
    ap.add_argument("-o", "--out", default=None,
                    help="also write the JSON report to this path")
    args = ap.parse_args(argv)

    from shadow_tpu.tpu import profiling

    shapes = []
    for n in (int(h) for h in args.hosts.split(",") if h.strip()):
        shapes.append(profiling.profile_sections(
            n, reps=args.reps, rr_enabled=args.rr,
            packed_sort=not args.legacy_sort, kernel=args.kernel,
            n_nodes=args.nodes, egress_cap=args.egress_cap,
            ingress_cap=args.ingress_cap,
            sections=(args.sections.split(",") if args.sections else None),
        ))
    report = {"metric": "plane_section_ms", "shapes": shapes}
    text = json.dumps(report, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
