#!/usr/bin/env python3
"""Run the workload scenario corpus and diff against golden digests.

The workload plane's CI surface (docs/workloads.md): every checked-in
scenario under `scenarios/` executes through the corpus runner
(`shadow_tpu/workloads/runner.py`), producing one JSON record per
scenario — canonical digest, per-phase completion virtual times,
traffic/drop totals — with no wall-clock anywhere, so two runs of the
same corpus are byte-identical.

Usage:
  python tools/run_scenarios.py                       # run corpus,
      write scenarios.json
  python tools/run_scenarios.py --check               # also diff
      digests against scenarios/GOLDEN.json (exit 1 on mismatch)
  python tools/run_scenarios.py --update-golden       # rewrite the
      golden file from this run (review the diff!)
  python tools/run_scenarios.py scenarios/incast.yaml # subset
  python tools/run_scenarios.py --config sim.yaml     # the sim
      config's `workload:` block names the scenario (+ seed override)
  python tools/run_scenarios.py --shard 8             # host-axis
      sharded over 8 devices; digests must not change
  python tools/run_scenarios.py --faults --guards     # fault-injected
      run with the guard plane threaded (must finish guards-clean)
  python tools/run_scenarios.py --telemetry DIR       # heartbeat
      JSONL with workload_phase annotations
  python tools/run_scenarios.py --memo --check        # memoized run;
      digests must STILL match golden (replay is parity-pinned)
  python tools/run_scenarios.py --memo \\
      --memo-report memo.json                         # cache stats
      (hits/misses/fast-forwarded windows/bytes) per scenario
  python tools/run_scenarios.py --trace DIR --check   # shadowscope:
      run-ledger JSONL + two-clock Chrome trace per scenario in DIR;
      tracing is presence-invisible, so --check must still pass —
      that IS the CI trace-parity gate
  python tools/run_scenarios.py --trace DIR \\
      --trace-report trace.json                       # per-scenario
      wall-time phase totals + the backend fingerprint (the
      compare_runs --trace artifact)
  python tools/run_scenarios.py --checkpoint-dir D \\
      --checkpoint-every 16                           # full-run
      checkpoints at chain boundaries (faults/runstate.py); a killed
      run resumes with --resume and the output file is byte-identical
      to the uninterrupted run (the kill/resume CI gate)
  python tools/run_scenarios.py --checkpoint-dir D --resume
                                                      # continue from
      the newest checkpoint per scenario (cold start if none)
  python tools/run_scenarios.py --checkpoint-dir D \\
      --kill-at 32                                    # CI crash
      point: exit 137 right after the round-32 checkpoint is durable
  python tools/run_scenarios.py --memo --memo-cache D --check
                                                      # persistent
      memo cache: DIR/<name>.memo.npz loaded before + saved after
      each scenario; a second invocation replays from the persisted
      entries (persisted_hits > 0 — the cross-run cache gate)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS_DIR = os.path.join(REPO, "scenarios")
GOLDEN = os.path.join(CORPUS_DIR, "GOLDEN.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("scenarios", nargs="*",
                    help="scenario YAMLs (default: scenarios/*.yaml)")
    ap.add_argument("--config", default=None, metavar="SIM_YAML",
                    help="read the scenario path + seed override from "
                         "a simulation config's `workload:` block "
                         "(docs/workloads.md) instead of listing "
                         "scenario files")
    ap.add_argument("--check", action="store_true",
                    help="diff digests against the golden corpus "
                         "(exit 1 on any mismatch)")
    ap.add_argument("--update-golden", action="store_true",
                    help="rewrite scenarios/GOLDEN.json from this run")
    ap.add_argument("-o", "--out", default="scenarios.json",
                    help="record output path (default scenarios.json)")
    ap.add_argument("--golden", default=GOLDEN)
    ap.add_argument("--shard", type=int, default=None, metavar="N",
                    help="host-axis shard over N devices (digest parity)")
    ap.add_argument("--faults", action="store_true",
                    help="thread the default fault schedule per scenario")
    ap.add_argument("--guards", action="store_true",
                    help="thread the runtime invariant plane; exits 1 "
                         "when any scenario reports a violation")
    ap.add_argument("--telemetry", default=None, metavar="DIR",
                    help="write heartbeat JSONL (with workload_phase "
                         "annotations) per scenario into DIR")
    ap.add_argument("--sample-every", type=int, default=None,
                    metavar="K",
                    help="thread the flight recorder: tag ~1/K packets "
                         "and record per-hop traces (seeded from the "
                         "scenario seed); with --telemetry DIR the "
                         "sampled hops land in DIR/<name>.hops.jsonl")
    ap.add_argument("--trace-ring", type=int, default=4096,
                    help="flight-recorder trace-ring capacity "
                         "(default 4096; overflow is counted loudly)")
    ap.add_argument("--memo", action="store_true",
                    help="memoize steady-state chain spans "
                         "(tpu/memo.py); replay is parity-pinned, so "
                         "--check must still pass — that IS the CI "
                         "memo-parity gate")
    ap.add_argument("--memo-report", default=None, metavar="PATH",
                    help="write per-scenario memo cache stats (hits/"
                         "misses/fast-forwarded windows/entry sizes) "
                         "+ the backend fingerprint as JSON")
    ap.add_argument("--trace", default=None, metavar="DIR",
                    help="shadowscope run ledger: write "
                         "DIR/<name>.ledger.jsonl + the two-clock "
                         "Chrome trace DIR/<name>.trace.json per "
                         "scenario (presence-invisible: digests are "
                         "unchanged)")
    ap.add_argument("--trace-report", default=None, metavar="PATH",
                    help="write per-scenario wall-time phase totals + "
                         "the backend fingerprint as JSON (needs "
                         "--trace)")
    ap.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="write full-run checkpoints (runstate format: "
                         "carry + fault-schedule position + memo "
                         "cache, atomic single-file) into DIR")
    ap.add_argument("--checkpoint-every", type=int, default=16,
                    metavar="K",
                    help="checkpoint cadence in windows (default 16); "
                         "must match across the killed run and its "
                         "--resume for identical chain partitions")
    ap.add_argument("--resume", action="store_true",
                    help="resume each scenario from its newest "
                         "checkpoint in --checkpoint-dir (cold start "
                         "when none exists); the output file is "
                         "byte-identical to the uninterrupted run — "
                         "resume provenance rides the "
                         "<out>.provenance.json sidecar + the ledger")
    ap.add_argument("--kill-at", type=int, default=None, metavar="R",
                    help="exit 137 immediately after the checkpoint "
                         "at round R lands (the CI kill/resume "
                         "gate's deterministic preemption; needs "
                         "--checkpoint-dir, R a multiple of "
                         "--checkpoint-every)")
    ap.add_argument("--slo-report", default=None, metavar="PATH",
                    help="write the per-scenario SLO summary (compute-"
                         "plane totals + wait/sojourn percentiles + "
                         "target verdicts, scenarios with a `compute:` "
                         "block only) + the backend fingerprint as "
                         "JSON — the compare_runs --slo artifact")
    ap.add_argument("--memo-cache", default=None, metavar="DIR",
                    help="persist the memo cache across invocations: "
                         "DIR/<name>.memo.npz is loaded before and "
                         "saved after each scenario (needs --memo)")
    args = ap.parse_args(argv)

    from shadow_tpu.workloads import load_scenario_file
    from shadow_tpu.workloads import runner

    seed_override = None
    flow_emit_cap = flow_recv_wnd = None
    flows_enabled = False
    memo_cfg = None
    if args.config is not None:
        if args.scenarios:
            ap.error("--config and positional scenarios are mutually "
                     "exclusive")
        from shadow_tpu.core.config import ConfigError, load_config_file

        try:
            cfg = load_config_file(args.config)
        except ConfigError as e:
            print(f"run_scenarios: {args.config}: {e}", file=sys.stderr)
            return 2
        if cfg.workload.scenario in (None, "off"):
            print(f"run_scenarios: {args.config}: the `workload:` "
                  f"block names no scenario (workload.scenario is "
                  f"{cfg.workload.scenario!r})", file=sys.stderr)
            return 2
        paths = [os.path.join(os.path.dirname(os.path.abspath(
            args.config)), cfg.workload.scenario)
            if not os.path.isabs(cfg.workload.scenario)
            else cfg.workload.scenario]
        seed_override = cfg.workload.seed
        # the `flows:` block's validated knobs govern flow-transport
        # scenarios run through this config (docs/robustness.md
        # "Flow plane"); scenarios without `transport: flows` never
        # consult them
        flow_emit_cap = cfg.flows.emit_cap
        flow_recv_wnd = cfg.flows.recv_wnd
        flows_enabled = cfg.flows.enabled
        memo_cfg = cfg.memo
    else:
        paths = args.scenarios or sorted(
            glob.glob(os.path.join(CORPUS_DIR, "*.yaml")))
    if not paths:
        print("run_scenarios: no scenarios found", file=sys.stderr)
        return 2
    if (args.faults or args.guards) and (args.check
                                         or args.update_golden):
        # the golden corpus is the FAULT-FREE contract; a fault run's
        # digests are a different world by design
        print("run_scenarios: --faults/--guards runs cannot be "
              "checked against (or written to) the golden corpus",
              file=sys.stderr)
        return 2
    # --memo + --check is NOT refused: replay is parity-pinned, so a
    # memoized run must match the same golden digests — running that
    # combination is the memo-parity gate
    if args.memo_report and not (args.memo or memo_cfg is not None
                                 and memo_cfg.enabled):
        print("run_scenarios: --memo-report needs --memo (or a config "
              "with memo.enabled)", file=sys.stderr)
        return 2
    if args.trace_report and not args.trace:
        print("run_scenarios: --trace-report needs --trace",
              file=sys.stderr)
        return 2
    if args.resume and not args.checkpoint_dir:
        print("run_scenarios: --resume needs --checkpoint-dir",
              file=sys.stderr)
        return 2
    if args.kill_at is not None:
        if not args.checkpoint_dir:
            print("run_scenarios: --kill-at needs --checkpoint-dir "
                  "(the kill fires after a durable checkpoint)",
                  file=sys.stderr)
            return 2
        if args.kill_at % max(1, args.checkpoint_every) != 0 \
                or args.kill_at < args.checkpoint_every:
            print(f"run_scenarios: --kill-at {args.kill_at} is not a "
                  f"checkpoint instant (must be a positive multiple "
                  f"of --checkpoint-every {args.checkpoint_every})",
                  file=sys.stderr)
            return 2
    if args.memo_cache and not (args.memo or (memo_cfg is not None
                                              and memo_cfg.enabled)):
        print("run_scenarios: --memo-cache needs --memo (or a config "
              "with memo.enabled)", file=sys.stderr)
        return 2
    memo_arg = None
    if args.memo or (memo_cfg is not None and memo_cfg.enabled):
        from shadow_tpu.core.config import MemoOptions

        memo_arg = memo_cfg if memo_cfg is not None \
            else MemoOptions(enabled=True)
        if not memo_arg.enabled:  # CLI flag flips the parsed block on
            memo_arg = MemoOptions(enabled=True,
                                   max_bytes=memo_arg.max_bytes,
                                   min_repeat=memo_arg.min_repeat,
                                   chain_len=memo_arg.chain_len)

    records = []
    memo_reports = {}
    trace_summaries = {}
    provenance_all = {}
    guards_dirty = False
    if args.trace:
        os.makedirs(args.trace, exist_ok=True)
    if args.memo_cache:
        os.makedirs(args.memo_cache, exist_ok=True)
    for path in paths:
        spec = load_scenario_file(path, seed=seed_override)
        if flows_enabled and spec.transport != "flows":
            # the config opted into the flow plane but the scenario
            # governs the transport: say so loudly instead of the
            # silently-ignored-opt-in failure mode the `flows:` block
            # exists to prevent (docs/robustness.md "Flow plane")
            print(f"run_scenarios: flows.enabled is set but scenario "
                  f"{spec.name!r} declares transport: "
                  f"{spec.transport} — the flow plane only runs for "
                  f"`transport: flows` scenarios; this run proceeds "
                  f"on the direct transport", file=sys.stderr)
        harvester = None
        hops_sink = None
        if args.telemetry:
            from shadow_tpu.telemetry import TelemetryHarvester

            os.makedirs(args.telemetry, exist_ok=True)
            harvester = TelemetryHarvester(
                interval_ns=spec.window_ns,
                sink=os.path.join(args.telemetry,
                                  f"{spec.name}.jsonl"))
            if args.sample_every:
                hops_sink = os.path.join(args.telemetry,
                                         f"{spec.name}.hops.jsonl")
        tracer_obj = None
        ledger_path = None
        if args.trace:
            from shadow_tpu.telemetry import tracer as tracermod

            ledger_path = os.path.join(args.trace,
                                       f"{spec.name}.ledger.jsonl")
            # under checkpointing the ledger STREAMS (each record
            # flushed + fsynced) so a SIGKILL preserves it; a resume
            # appends to the killed run's stream instead of truncating
            resuming = bool(
                args.resume and args.checkpoint_dir
                and os.path.isfile(ledger_path))
            tracer_obj = tracermod.RunTracer(
                spec.name, meta={"family": spec.family,
                                 "hosts": spec.n_hosts,
                                 "windows": spec.windows,
                                 "memo": memo_arg is not None,
                                 "faults": bool(args.faults)},
                sink=ledger_path if args.checkpoint_dir else None,
                resume=resuming)
        prov = {}
        rec = runner.run_scenario(
            spec, guards=args.guards,
            use_default_faults=args.faults,
            mesh_devices=args.shard,
            telemetry=harvester,
            sample_every=args.sample_every,
            trace_ring=args.trace_ring,
            hops_sink=hops_sink,
            flow_emit_cap=flow_emit_cap,
            flow_recv_wnd=flow_recv_wnd,
            memo=memo_arg,
            tracer=tracer_obj,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            resume=args.resume,
            kill_at=args.kill_at,
            memo_cache=(os.path.join(args.memo_cache,
                                     f"{spec.name}.memo.npz")
                        if args.memo_cache else None),
            provenance=prov)
        if args.checkpoint_dir:
            provenance_all[spec.name] = prov
        if harvester is not None:
            harvester.finalize()
        if tracer_obj is not None:
            tracer_obj.close()
            tracer_obj.write(ledger_path)
            heartbeats = None
            if args.telemetry:
                from shadow_tpu.telemetry import export

                with open(os.path.join(args.telemetry,
                                       f"{spec.name}.jsonl")) as fh:
                    heartbeats = export.read_heartbeats(fh)
            # a resumed tracer holds only THIS segment in memory; the
            # streamed file has the whole stitched history — report
            # and export from the file of record
            ledger_records = (tracermod.load_ledger(ledger_path)
                              if tracer_obj.sink_path is not None
                              else tracer_obj.records)
            tracermod.write_chrome_trace(
                ledger_records,
                os.path.join(args.trace, f"{spec.name}.trace.json"),
                heartbeats=heartbeats)
            trace_summaries[spec.name] = tracermod.phase_totals(
                ledger_records)
        records.append(rec)
        g = rec.get("guards")
        status = ("done" if rec["all_done"]
                  else f"{rec['completed_hosts']}/{rec['participants']}")
        gtxt = ""
        if g is not None:
            gtxt = " guards=clean" if g["clean"] else " guards=DIRTY"
            guards_dirty |= not g["clean"]
        mtxt = ""
        if "memo" in rec:
            memo_reports[spec.name] = rec["memo"]
            mtxt = (f" memo={rec['memo']['hits']}h/"
                    f"{rec['memo']['misses']}m/"
                    f"{rec['memo']['fast_forwarded_windows']}ffwd")
        print(f"{spec.name:<24} [{rec['family']}] {status:>8}  "
              f"events={rec['events']:<8} "
              f"digest={rec['canonical_digest'][:12]}{gtxt}{mtxt}",
              file=sys.stderr)

    with open(args.out, "w") as fh:
        json.dump({"records": records}, fh, sort_keys=True, indent=1)
        fh.write("\n")
    print(f"run_scenarios: {len(records)} scenario(s) -> {args.out}",
          file=sys.stderr)

    if provenance_all:
        # resume provenance rides a SIDECAR, never the record file:
        # the record artifact is byte-identical between a resumed run
        # and its uninterrupted twin BY CONTRACT (the CI gate cmp's
        # them), so "where did this run restart" is stamped next to
        # it, and on the run ledger's `resume` annotation
        sidecar = args.out + ".provenance.json"
        with open(sidecar, "w") as fh:
            json.dump({"schema": "runprov-v1",
                       "checkpoint_dir": args.checkpoint_dir,
                       "checkpoint_every": args.checkpoint_every,
                       "scenarios": provenance_all},
                      fh, sort_keys=True, indent=1)
            fh.write("\n")
        resumed = sorted(n for n, p in provenance_all.items()
                         if p.get("resumed_from"))
        print(f"run_scenarios: provenance -> {sidecar}"
              + (f" (resumed: {', '.join(resumed)})" if resumed else ""),
              file=sys.stderr)

    if args.memo_report:
        # the cache-economics artifact: per-scenario stats + the
        # backend fingerprint (PR-11 discipline — a memo speedup is
        # only comparable within one container identity)
        import bench

        with open(args.memo_report, "w") as fh:
            json.dump({"backend": bench.backend_fingerprint(),
                       "scenarios": memo_reports},
                      fh, sort_keys=True, indent=1)
            fh.write("\n")
        print(f"run_scenarios: memo report -> {args.memo_report}",
              file=sys.stderr)

    if args.slo_report:
        # the serving-SLO artifact (compare_runs --slo): compute-plane
        # totals + percentile/target blocks per scenario, stamped with
        # the backend fingerprint like every cross-run report (the
        # values are virtual-time ints — byte-stable — but the stamp
        # keeps the artifact family uniform)
        import bench

        slo_summary = {
            rec["name"]: {"compute": rec["compute"], "slo": rec["slo"]}
            for rec in records if "slo" in rec}
        with open(args.slo_report, "w") as fh:
            json.dump({"backend": bench.backend_fingerprint(),
                       "scenarios": slo_summary},
                      fh, sort_keys=True, indent=1)
            fh.write("\n")
        print(f"run_scenarios: slo report -> {args.slo_report} "
              f"({len(slo_summary)} scenario(s) with a compute plane)",
              file=sys.stderr)

    if args.trace_report:
        # the wall-attribution artifact (compare_runs --trace): phase
        # totals per scenario + the backend fingerprint — wall numbers
        # are only comparable within one container identity
        import bench
        from shadow_tpu.telemetry import tracer as tracermod

        with open(args.trace_report, "w") as fh:
            json.dump({"backend": bench.backend_fingerprint(),
                       "schema": tracermod.RUNLEDGER_SCHEMA,
                       "scenarios": trace_summaries},
                      fh, sort_keys=True, indent=1)
            fh.write("\n")
        print(f"run_scenarios: trace report -> {args.trace_report}",
              file=sys.stderr)

    if args.update_golden:
        golden = {rec["name"]: runner.golden_entry(rec)
                  for rec in records}
        with open(args.golden, "w") as fh:
            json.dump(golden, fh, sort_keys=True, indent=1)
            fh.write("\n")
        print(f"run_scenarios: golden corpus rewritten: {args.golden}",
              file=sys.stderr)
    if args.check:
        try:
            golden = runner.load_golden(args.golden)
        except OSError as e:
            print(f"run_scenarios: no golden corpus: {e}",
                  file=sys.stderr)
            return 1
        problems = runner.check_against_golden(records, golden)
        for p in problems:
            print(f"GOLDEN MISMATCH: {p}", file=sys.stderr)
        if problems:
            return 1
        print(f"run_scenarios: {len(records)} scenario(s) match the "
              f"golden corpus", file=sys.stderr)
    if guards_dirty:
        print("run_scenarios: guard violations reported",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
