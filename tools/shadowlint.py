#!/usr/bin/env python3
"""shadowlint: the determinism + JAX-kernel static analysis suite.

Pass 1 lints every Python file under the given paths with the AST
rules (SL1xx determinism + SL4xx hazards + SL503 donation safety);
pass 2 abstract-evals the jitted ``tpu/`` kernel entry points and
audits their jaxprs (SL2xx); pass 3 runs the proofs over the same
traced graphs (SL501 presence-invisibility, SL502 op-budget ledger,
SL504 row-local shard fence, SL505 cond branch-equivalence, SL506
integer ranges) and can emit the SL504/SL505/SL506 artifacts; pass 4
(shadowcost) lowers the cached jaxprs through XLA and fences the
COMPILED artifacts (SL601 cost budgets + watermark extrapolation,
SL602 fusion-boundary census, SL603 driver-loop host-sync fence) with
the ``--cost-report`` artifact; pass 5 (shadowbatch) re-traces every
entry under ``jax.vmap`` and proves the ensemble contract (SL701
world-isolation, SL702 RNG stream disjointness, SL703
vmap-traceability census) with the ``--batch-report`` artifact. All
traced passes share one per-process jaxpr cache
(``jaxpr_audit.traced`` — the batch pass adds ``@vmapW{w}`` key
variants), and the cost pass shares one lower+compile memo on top of
it (``jaxpr_audit.compiled``), so each audited entry traces once per
world count and compiles once. Exit code is nonzero when any
unsuppressed finding (or malformed suppression comment) exists.

Usage::

    python tools/shadowlint.py                  # all passes, text report
    python tools/shadowlint.py --json           # machine-readable report
    python tools/shadowlint.py --no-jaxpr       # AST pass only (no jax)
    python tools/shadowlint.py --only SL501,SL502,SL503,SL504,SL505,SL506
    python tools/shadowlint.py --only SL601,SL602,SL603  # cost fences
    python tools/shadowlint.py --only SL701,SL702,SL703  # world proofs
    python tools/shadowlint.py --list-rules     # rule inventory
    python tools/shadowlint.py --write-op-budgets  # regen the SL502 ledger
    python tools/shadowlint.py --write-cost-budgets  # regen the SL6xx one
    python tools/shadowlint.py --shard-report sl504.json  # SL504 artifact
    python tools/shadowlint.py --condeq-report sl505.json # SL505 artifact
    python tools/shadowlint.py --range-report sl506.json  # SL506 artifact
    python tools/shadowlint.py --cost-report cost.json    # SL6xx artifact
    python tools/shadowlint.py --batch-report batch.json  # SL7xx artifact
    python tools/shadowlint.py --recompile      # + jit-cache sweep
    python tools/shadowlint.py shadow_tpu/core  # explicit paths

Suppression: ``# shadowlint: disable=SL101 -- <why this is safe>`` on
the offending line or the line above. The justification is mandatory.
Rule IDs and the invariants they protect: docs/determinism.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from shadow_tpu.analysis import rules as _rules  # noqa: E402
from shadow_tpu.analysis.astlint import lint_source  # noqa: E402

DEFAULT_PATHS = ("shadow_tpu", "tools", "bench.py")

#: which pass serves each rule family (drives --only skipping)
AST_RULES = frozenset({"SL101", "SL102", "SL103", "SL104", "SL105",
                       "SL301", "SL401", "SL402", "SL403", "SL405",
                       "SL503"})
JAXPR_RULES = frozenset({"SL201", "SL202", "SL203", "SL204", "SL205"})
# SL504's row-local fence gates alongside the proof rules; its full
# per-entry report stays an artifact (--shard-report)
PROOF_RULES = frozenset({"SL501", "SL502", "SL504", "SL505", "SL506"})
# pass 4 (analysis/costmodel.py): SL601/SL602 compile the registered
# cost entries; SL603 is an AST fence over the driver-loop modules but
# gates with its family (it rides the same registry + report)
COST_RULES = frozenset({"SL601", "SL602", "SL603"})
# pass 5 (analysis/batchdim.py): the world-axis independence proofs
# over the vmapped audit surface (SL701 isolation, SL702 RNG
# disjointness, SL703 traceability census + refusal hygiene)
BATCH_RULES = frozenset({"SL701", "SL702", "SL703"})


def _iter_py_files(paths):
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        if not os.path.isdir(path):
            raise FileNotFoundError(path)
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames if d not in ("__pycache__",))
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def run_ast_pass(paths):
    findings, malformed = [], []
    for path in _iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(path), _REPO).replace(
            os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        sup = _rules.parse_suppressions(source)
        findings.extend(lint_source(source, rel, suppressions=sup))
        for lineno, text in sup.malformed:
            malformed.append((rel, lineno, text))
    return findings, malformed


def _force_cpu():
    # tracing needs a backend for the concrete example arrays; force CPU
    # exactly like tests/conftest.py (the env var is already cached by
    # sitecustomize, so the config update is the only working override)
    import jax

    jax.config.update("jax_platforms", "cpu")


def run_jaxpr_pass():
    _force_cpu()

    from shadow_tpu.analysis.jaxpr_audit import audit_all

    return audit_all()


def _build_condeq_report():
    """(findings, report) for the SL505 gate surface — the ONE place
    the report shape is spelled, shared by the proof pass and the
    `--condeq-report`-without-SL505 fallback."""
    _force_cpu()

    from shadow_tpu.analysis import condeq

    gate_findings, gate_proofs = condeq.check_all_gates()
    return gate_findings, {
        "version": 1,
        "rule": "SL505",
        "gates": [p.to_json() for p in gate_proofs],
    }


def _build_range_report():
    """(findings, report) for the SL506 range surface (same sharing)."""
    _force_cpu()

    from shadow_tpu.analysis import ranges

    return ranges.check_all_ranges()


def run_cost_pass(selected):
    """Pass 4: the shadowcost fences (SL601 compiled-cost budgets +
    watermarks, SL602 fusion boundaries, SL603 host-sync fence).
    Returns (findings, cost_deltas, cost_report); the report is None
    when no compiled family was selected. SL601/SL602 need jax (they
    compile); a pure-SL603 selection is AST-only."""
    from shadow_tpu.analysis import costmodel

    if {"SL601", "SL602"} & selected:
        _force_cpu()
    return costmodel.run_cost_pass(selected & COST_RULES)


def _build_cost_report():
    """Report fallback for a `--cost-report`-without-SL6xx run (one
    spelling of the artifact, shared with run_cost_pass)."""
    _force_cpu()

    from shadow_tpu.analysis import costmodel

    return costmodel.build_cost_report()


def run_batch_pass(selected):
    """Pass 5: the shadowbatch world-axis proofs. Returns
    (findings, batch_report) — the report is the ``--batch-report``
    artifact and the json-v2 ``batch`` section."""
    _force_cpu()

    from shadow_tpu.analysis import batchdim

    return batchdim.check_all_batch(selected & BATCH_RULES)


def _build_batch_report():
    """Report fallback for a `--batch-report`-without-SL7xx run (one
    spelling of the artifact, shared with run_batch_pass)."""
    _f, report = run_batch_pass(BATCH_RULES)
    return report


def run_proof_pass(selected):
    """Pass 3: the dataflow/interval proofs — SL501 invisibility,
    SL502 budget diff, SL504 row-local fence, SL505 branch-equivalence,
    SL506 integer ranges. Returns (findings, budget_deltas,
    condeq_report, range_report); the reports are None for deselected
    families."""
    _force_cpu()

    from shadow_tpu.analysis import proofs

    findings, deltas = [], []
    condeq_report = range_report = None
    if "SL501" in selected:
        findings.extend(proofs.check_all_invisibility())
    if "SL502" in selected:
        budget_findings, deltas = proofs.check_op_budgets()
        findings.extend(budget_findings)
    if "SL504" in selected:
        findings.extend(proofs.check_row_local_fence())
    if "SL505" in selected:
        gate_findings, condeq_report = _build_condeq_report()
        findings.extend(gate_findings)
    if "SL506" in selected:
        range_findings, range_report = _build_range_report()
        findings.extend(range_findings)
    return findings, deltas, condeq_report, range_report


def list_rules() -> str:
    lines = []
    for rid, info in sorted(_rules.RULES.items()):
        fixture = (f"tests/lint_fixtures/{info.fixture}"
                   if info.fixture else "-")
        lines.append(f"{rid}  {info.name:<24} scope: {info.scope}")
        lines.append(f"       fixture: {fixture}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="shadowlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint; default: shadow_tpu, "
                         "tools, and bench.py, resolved against the "
                         "repo root so the gate works from any cwd")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip passes 2+3 (jaxpr audit + dataflow "
                         "proofs; no jax import)")
    ap.add_argument("--only", metavar="SLnnn[,SLnnn]",
                    help="run/report only these rule IDs (passes whose "
                         "whole family is deselected are skipped "
                         "entirely — `--only SL501,SL502,SL503` is the "
                         "fast CI proof gate)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule inventory (id, name, scope, "
                         "fixture) and exit")
    ap.add_argument("--write-op-budgets", action="store_true",
                    help="regenerate analysis/op_budgets.json from the "
                         "live tree (the explicit-ledger-update step "
                         "for a justified op-cost change) and exit")
    ap.add_argument("--write-cost-budgets", action="store_true",
                    help="regenerate THIS platform's section of "
                         "analysis/cost_budgets.json from the live "
                         "compiled entries (other platforms' budgets "
                         "are preserved) and exit")
    ap.add_argument("--shard-report", metavar="FILE",
                    help="write the SL504 shardability report "
                         "(host-local vs cross-host primitives per "
                         "audited section) to FILE")
    ap.add_argument("--condeq-report", metavar="FILE",
                    help="write the SL505 branch-equivalence report "
                         "(per-gate proof mode + lattice coverage) to "
                         "FILE")
    ap.add_argument("--range-report", metavar="FILE",
                    help="write the SL506 range report (per-entry "
                         "output-interval tables + the assumption "
                         "inventory) to FILE")
    ap.add_argument("--cost-report", metavar="FILE",
                    help="write the SL6xx cost report (per-entry "
                         "compiled costs, the ranked fusion-boundary "
                         "worklist ROADMAP-4 consumes, watermark "
                         "extrapolations, host-sync scan) to FILE")
    ap.add_argument("--batch-report", metavar="FILE",
                    help="write the SL7xx batch report (per-entry "
                         "world-isolation proofs + batched-op census, "
                         "vmap refusals with rationales, RNG "
                         "fold-chain proofs) to FILE")
    ap.add_argument("--recompile", action="store_true",
                    help="also run the jit-cache sweep over the "
                         "bench-ladder shapes (slow: compiles kernels)")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(list_rules())
        return 0

    if args.write_op_budgets:
        _force_cpu()

        from shadow_tpu.analysis import proofs

        doc = proofs.write_op_budgets()
        print(f"wrote {proofs.budget_path()} "
              f"({len(doc['budgets'])} entries)")
        return 0

    if args.write_cost_budgets:
        _force_cpu()

        from shadow_tpu.analysis import costmodel

        doc = costmodel.write_cost_budgets()
        plats = {p: len(v) for p, v in doc["platforms"].items()}
        print(f"wrote {costmodel.cost_budget_path()} "
              f"(entries per platform: {plats})")
        return 0

    if args.only:
        selected = {r.strip().upper() for r in args.only.split(",")
                    if r.strip()}
        unknown = selected - set(_rules.RULES)
        if unknown:
            print(f"shadowlint: unknown rule id(s): "
                  f"{', '.join(sorted(unknown))} (see --list-rules)",
                  file=sys.stderr)
            return 2
    else:
        selected = set(_rules.RULES)

    if args.no_jaxpr and (args.shard_report or args.condeq_report
                          or args.range_report or args.cost_report
                          or args.batch_report):
        # the reports ARE traced passes; per the help text --no-jaxpr
        # promises "no jax import", so the combination is a
        # contradiction, not a preference
        print("shadowlint: --shard-report/--condeq-report/"
              "--range-report/--cost-report/--batch-report trace the "
              "audit registry (needs jax); drop --no-jaxpr",
              file=sys.stderr)
        return 2
    if args.no_jaxpr:
        dropped = sorted(selected
                         & (JAXPR_RULES | PROOF_RULES | COST_RULES
                            | BATCH_RULES))
        if dropped and not (selected & AST_RULES):
            # a "gate" that runs nothing must never report green
            print("shadowlint: --no-jaxpr skips every selected rule "
                  f"({', '.join(dropped)}): nothing would be checked",
                  file=sys.stderr)
            return 2
        if dropped and args.only:
            print(f"shadowlint: note: --no-jaxpr skips "
                  f"{', '.join(dropped)} of the selected rules",
                  file=sys.stderr)

    paths = args.paths or [os.path.join(_REPO, p) for p in DEFAULT_PATHS]
    findings, malformed = [], []
    if selected & AST_RULES:
        try:
            findings, malformed = run_ast_pass(paths)
        except FileNotFoundError as exc:
            print(f"shadowlint: no such file or directory: "
                  f"{exc.args[0]}", file=sys.stderr)
            return 2
    budget_deltas = []
    cost_deltas = []
    condeq_report = range_report = cost_report = batch_report = None
    if not args.no_jaxpr:
        if selected & JAXPR_RULES:
            findings.extend(run_jaxpr_pass())
        if selected & PROOF_RULES:
            (proof_findings, budget_deltas, condeq_report,
             range_report) = run_proof_pass(selected)
            findings.extend(proof_findings)
        if selected & COST_RULES:
            cost_findings, cost_deltas, cost_report = \
                run_cost_pass(selected)
            findings.extend(cost_findings)
        if selected & BATCH_RULES:
            batch_findings, batch_report = run_batch_pass(selected)
            findings.extend(batch_findings)

    findings = [f for f in findings if f.rule in selected]

    shard_report = None
    if args.shard_report:
        _force_cpu()

        from shadow_tpu.analysis import proofs

        shard_report = proofs.build_shard_report()
        with open(args.shard_report, "w", encoding="utf-8") as fh:
            json.dump(shard_report, fh, indent=2)
            fh.write("\n")
    if args.condeq_report:
        if condeq_report is None:  # SL505 deselected: report-only run
            _f, condeq_report = _build_condeq_report()
        with open(args.condeq_report, "w", encoding="utf-8") as fh:
            json.dump(condeq_report, fh, indent=2)
            fh.write("\n")
    if args.range_report:
        if range_report is None:  # SL506 deselected: report-only run
            _f, range_report = _build_range_report()
        with open(args.range_report, "w", encoding="utf-8") as fh:
            json.dump(range_report, fh, indent=2)
            fh.write("\n")
    if args.cost_report:
        if cost_report is None:  # SL601/602 deselected: report-only
            cost_report = _build_cost_report()
        with open(args.cost_report, "w", encoding="utf-8") as fh:
            json.dump(cost_report, fh, indent=2)
            fh.write("\n")
    if args.batch_report:
        if batch_report is None:  # SL7xx deselected: report-only run
            batch_report = _build_batch_report()
        with open(args.batch_report, "w", encoding="utf-8") as fh:
            json.dump(batch_report, fh, indent=2)
            fh.write("\n")

    recompile_report = None
    if args.recompile:
        from shadow_tpu.analysis.recompile import sweep_window_step

        recompile_report = sweep_window_step()

    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    failed = bool(active or malformed) or bool(
        recompile_report and recompile_report["unexpected_misses"])

    if args.json:
        hits: dict[str, dict[str, int]] = {}
        for f in findings:
            slot = hits.setdefault(f.rule, {"active": 0, "suppressed": 0})
            slot["suppressed" if f.suppressed else "active"] += 1
        json.dump({
            "version": 2,
            "rules": {rid: {
                "name": info.name,
                "summary": info.summary,
                "invariant": info.invariant,
                "scope": info.scope,
                "fixture": (f"tests/lint_fixtures/{info.fixture}"
                            if info.fixture else None),
                "selected": rid in selected,
                "hits": hits.get(rid, {"active": 0, "suppressed": 0}),
            } for rid, info in sorted(_rules.RULES.items())},
            "findings": [f.to_json() for f in findings],
            "malformed_suppressions": [
                {"path": p, "line": ln, "text": t}
                for p, ln, t in malformed
            ],
            "op_budget_deltas": budget_deltas,
            "cost_budget_deltas": cost_deltas,
            "cost": ({
                "platform": cost_report["platform"],
                "summary": cost_report["summary"],
                "watermarks": cost_report["watermarks"],
                # head only — the FULL ranked list is the
                # --cost-report artifact (no silent caps)
                "fusion_worklist_total":
                    len(cost_report["fusion_worklist"]),
                "fusion_worklist": cost_report["fusion_worklist"][:20],
                "entries": [{
                    "entry": s["entry"],
                    "metrics": s["metrics"],
                    "temp_bytes": s["temp_bytes"],
                } for s in cost_report["entries"]],
            } if cost_report is not None else None),
            "condeq": condeq_report,
            "ranges": ({
                "caveat": range_report["caveat"],
                "summary": range_report["summary"],
                "entries": [{
                    "entry": s["entry"],
                    "findings": s["findings"],
                    "suppressed": s["suppressed"],
                    "unmodeled": s["unmodeled"],
                } for s in range_report["entries"]],
            } if range_report is not None else None),
            "batch": ({
                "caveat": batch_report["caveat"],
                "summary": batch_report["summary"],
                "world_counts": batch_report["world_counts"],
                "refusals": batch_report["refusals"],
                "rng": [{
                    "obligation": r["obligation"],
                    "ok": r["ok"],
                    "seed_domain": r["seed_domain"],
                } for r in batch_report["rng"]],
                "entries": [{
                    "entry": e["entry"],
                    "proved": e["proved"],
                    "findings": e["findings"],
                } for e in batch_report["entries"]],
            } if batch_report is not None else None),
            "recompile": recompile_report,
            "summary": {
                "active": len(active),
                "suppressed": len(suppressed),
                "malformed_suppressions": len(malformed),
                "ok": not failed,
            },
        }, sys.stdout, indent=2)
        print()
        return 1 if failed else 0

    for f in active:
        print(f)
    if condeq_report is not None:
        print("-- SL505 branch-equivalence proofs:")
        for g in condeq_report["gates"]:
            cov = (f", lattice {g['gated_points']}/{g['lattice_points']}"
                   if g["lattice_points"] else "")
            print(f"   {g['gate']}: "
                  f"{'PROVEN' if g['ok'] else 'FAILED'} "
                  f"[{g['mode']}{cov}] {g['detail']}")
    if range_report is not None:
        s = range_report["summary"]
        print(f"-- SL506 integer ranges: {s['entries']} entries, "
              f"{s['active_findings']} active, "
              f"{s['suppressed_findings']} suppressed-with-"
              "justification")
    if cost_report is not None:
        s = cost_report["summary"]
        wm = cost_report["watermarks"]
        print(f"-- SL601/SL602 compiled-cost fences "
              f"[{cost_report['platform']}]: {s['entries']} entries, "
              f"{s['budget_deltas']} over budget, "
              f"{s['watermark_failures']}/{len(wm)} watermark "
              f"failure(s), worklist {s['worklist']} boundaries")
        for w in cost_report["fusion_worklist"][:3]:
            print(f"   worklist: {w['bytes']:>6} B  {w['producer']} -> "
                  f"{', '.join(w['consumers'])[:40]}  "
                  f"[{w['entry'].rsplit(':', 1)[-1]}]")
    if batch_report is not None:
        s = batch_report["summary"]
        print(f"-- SL701/SL702/SL703 world-axis proofs "
              f"[W={'/'.join(map(str, batch_report['world_counts']))}]"
              f": {s['proved']}/{s['entries']} entries proved, "
              f"{s['refused']} written refusal(s), "
              f"{s['rng_obligations']} RNG obligation(s), "
              f"{s['active_findings']} active finding(s)")
    if budget_deltas:
        from shadow_tpu.analysis import proofs

        print("-- op budget vs actual (SL502):")
        print(proofs.format_budget_delta(budget_deltas))
    if cost_deltas:
        from shadow_tpu.analysis import costmodel

        print("-- compiled cost budget vs actual (SL601/SL602):")
        print(costmodel.format_cost_delta(cost_deltas))
    for path, lineno, text in malformed:
        print(f"{path}:{lineno}:1: malformed suppression (missing "
              f"`-- justification`): {text}")
    if suppressed:
        print(f"-- {len(suppressed)} suppressed finding(s):")
        for f in suppressed:
            print(f"   {f}  ({f.justification})")
    if shard_report is not None:
        s = shard_report["summary"]
        print(f"-- SL504 shardability report: {s['sections']} sections, "
              f"{s['cross_host_ops']} cross-host op(s), "
              f"{s['opaque_kernels']} opaque kernel(s) -> "
              f"{args.shard_report}")
    if recompile_report is not None:
        print(f"-- recompile sweep: {recompile_report['total_compiles']} "
              f"compiles over {len(recompile_report['shapes'])} ladder "
              f"shapes x {recompile_report['repeats']} sweeps, "
              f"{recompile_report['unexpected_misses']} unexpected "
              "cache misses")
    print(("FAIL" if failed else "OK")
          + f": {len(active)} active finding(s), "
          f"{len(suppressed)} suppressed, "
          f"{len(malformed)} malformed suppression(s)")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
