#!/usr/bin/env python3
"""shadowlint: the determinism + JAX-kernel static analysis suite.

Pass 1 lints every Python file under the given paths with the AST
determinism rules (SL1xx); pass 2 abstract-evals the jitted ``tpu/``
kernel entry points and audits their jaxprs (SL2xx). Exit code is
nonzero when any unsuppressed finding (or malformed suppression
comment) exists.

Usage::

    python tools/shadowlint.py                  # both passes, text report
    python tools/shadowlint.py --json           # machine-readable report
    python tools/shadowlint.py --no-jaxpr       # AST pass only (no jax)
    python tools/shadowlint.py --recompile      # + jit-cache sweep
    python tools/shadowlint.py shadow_tpu/core  # explicit paths

Suppression: ``# shadowlint: disable=SL101 -- <why this is safe>`` on
the offending line or the line above. The justification is mandatory.
Rule IDs and the invariants they protect: docs/determinism.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from shadow_tpu.analysis import rules as _rules  # noqa: E402
from shadow_tpu.analysis.astlint import lint_source  # noqa: E402

DEFAULT_PATHS = ("shadow_tpu", "tools")


def _iter_py_files(paths):
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        if not os.path.isdir(path):
            raise FileNotFoundError(path)
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames if d not in ("__pycache__",))
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def run_ast_pass(paths):
    findings, malformed = [], []
    for path in _iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(path), _REPO).replace(
            os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        sup = _rules.parse_suppressions(source)
        findings.extend(lint_source(source, rel, suppressions=sup))
        for lineno, text in sup.malformed:
            malformed.append((rel, lineno, text))
    return findings, malformed


def run_jaxpr_pass():
    # tracing needs a backend for the concrete example arrays; force CPU
    # exactly like tests/conftest.py (the env var is already cached by
    # sitecustomize, so the config update is the only working override)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from shadow_tpu.analysis.jaxpr_audit import audit_all

    return audit_all()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="shadowlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint; default: shadow_tpu and "
                         "tools, resolved against the repo root so the "
                         "gate works from any cwd")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable JSON report on stdout")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip pass 2 (jaxpr audit of tpu/ kernels)")
    ap.add_argument("--recompile", action="store_true",
                    help="also run the jit-cache sweep over the "
                         "bench-ladder shapes (slow: compiles kernels)")
    args = ap.parse_args(argv)

    paths = args.paths or [os.path.join(_REPO, p) for p in DEFAULT_PATHS]
    try:
        findings, malformed = run_ast_pass(paths)
    except FileNotFoundError as exc:
        print(f"shadowlint: no such file or directory: {exc.args[0]}",
              file=sys.stderr)
        return 2
    if not args.no_jaxpr:
        findings.extend(run_jaxpr_pass())

    recompile_report = None
    if args.recompile:
        from shadow_tpu.analysis.recompile import sweep_window_step

        recompile_report = sweep_window_step()

    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    failed = bool(active or malformed) or bool(
        recompile_report and recompile_report["unexpected_misses"])

    if args.json:
        json.dump({
            "version": 1,
            "rules": {rid: {
                "name": info.name,
                "summary": info.summary,
                "invariant": info.invariant,
            } for rid, info in sorted(_rules.RULES.items())},
            "findings": [f.to_json() for f in findings],
            "malformed_suppressions": [
                {"path": p, "line": ln, "text": t}
                for p, ln, t in malformed
            ],
            "recompile": recompile_report,
            "summary": {
                "active": len(active),
                "suppressed": len(suppressed),
                "malformed_suppressions": len(malformed),
                "ok": not failed,
            },
        }, sys.stdout, indent=2)
        print()
        return 1 if failed else 0

    for f in active:
        print(f)
    for path, lineno, text in malformed:
        print(f"{path}:{lineno}:1: malformed suppression (missing "
              f"`-- justification`): {text}")
    if suppressed:
        print(f"-- {len(suppressed)} suppressed finding(s):")
        for f in suppressed:
            print(f"   {f}  ({f.justification})")
    if recompile_report is not None:
        print(f"-- recompile sweep: {recompile_report['total_compiles']} "
              f"compiles over {len(recompile_report['shapes'])} ladder "
              f"shapes x {recompile_report['repeats']} sweeps, "
              f"{recompile_report['unexpected_misses']} unexpected "
              "cache misses")
    print(("FAIL" if failed else "OK")
          + f": {len(active)} active finding(s), "
          f"{len(suppressed)} suppressed, "
          f"{len(malformed)} malformed suppression(s)")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
