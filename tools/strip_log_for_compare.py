#!/usr/bin/env python3
"""Strip wall-clock-dependent lines from a shadow_tpu log so two runs of
the same config can be diffed byte-for-byte.

Parity: reference `src/tools/strip_log_for_compare.py`, used by the
determinism CMake harness before diffing. Removed content: the manager's
getrusage/meminfo heartbeats (real resource readings), wall-seconds
summaries, and any leading wall-clock timestamp the non-deterministic log
format prepends.

Usage:  python tools/strip_log_for_compare.py shadow.log > stripped.log
        diff <(... run1) <(... run2)
"""

from __future__ import annotations

import re
import sys

# lines whose content is real-time, not simulated-time
DROP = (
    "reported by getrusage()",
    "reported by /proc/meminfo",
    "simulation finished:",  # carries "%.2fs wall"
    "Unable to check",  # watchdog probe errors are environment-dependent
)
# WALL_FORMAT prepends "YYYY-MM-DD HH:MM:SS,mmm " before the sim timestamp
ASCTIME_RE = re.compile(r"^\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2},\d{3} ")


def strip(lines):
    for line in lines:
        if any(marker in line for marker in DROP):
            continue
        yield ASCTIME_RE.sub("", line)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    stream = open(argv[0]) if argv else sys.stdin
    try:
        for line in strip(stream):
            sys.stdout.write(line)
    finally:
        if stream is not sys.stdin:
            stream.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
