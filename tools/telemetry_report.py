#!/usr/bin/env python3
"""Turn a telemetry heartbeat JSONL stream into a summary, a Perfetto
trace, and plot-pipeline stats.

Input is the `telemetry.jsonl` a run writes (or a raw shadow log — lines
are matched on their embedded JSON, so `grep telemetry shadow.log |
telemetry_report.py -` works too). See docs/observability.md for the
heartbeat schema.

Usage:
  python tools/telemetry_report.py run/telemetry.jsonl
  python tools/telemetry_report.py run/telemetry.jsonl --trace trace.json
  python tools/telemetry_report.py run/telemetry.jsonl --stats-dir out/
      # writes out/stats.shadow.json for tools/plot_shadow.py
  cat run/telemetry.jsonl | python tools/telemetry_report.py - --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from shadow_tpu.telemetry import export  # noqa: E402


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


def _print_table(summary: dict) -> None:
    print(f"heartbeats: {summary['heartbeats']}  "
          f"harvests: {summary['harvests']}  hosts: {summary['hosts']}  "
          f"last virtual time: {summary['last_time_ns'] / 1e9:.3f} s")
    for k in ("windows", "events", "sort_occupancy"):
        if k in summary:
            print(f"  {k}: {summary[k]}")
    totals = summary["totals"]
    if totals:
        print("totals:")
        for k in sorted(totals):
            v = totals[k]
            shown = _fmt_bytes(v) if k.startswith("bytes") else v
            print(f"  {k:>18}: {shown}")
        # drop taxonomy (docs/robustness.md): the `fault` bucket holds
        # INJECTED losses (crashes, corruption bursts) so an operator
        # never misreads a scheduled outage as wire loss
        drops = {k[len("drop_"):]: v for k, v in totals.items()
                 if k.startswith("drop_")}
        if any(drops.values()):
            total_drops = sum(drops.values())
            parts = ", ".join(f"{k}={v}" for k, v in sorted(drops.items()))
            print(f"drop breakdown ({total_drops} total): {parts}")
            if drops.get("fault"):
                print(f"  note: {drops['fault']} drop(s) are INJECTED "
                      "faults (faults: schedule), not wire loss")
    if summary["top_talkers"]:
        print("top talkers (bytes out / in):")
        for t in summary["top_talkers"]:
            print(f"  {t['host']:>16}  {_fmt_bytes(t['bytes_out']):>12}  "
                  f"{_fmt_bytes(t['bytes_in']):>12}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", metavar="PATH",
                    help="heartbeat JSONL (or a shadow log; '-' = stdin)")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as JSON instead of a table")
    ap.add_argument("--trace", metavar="OUT",
                    help="also write a Perfetto/Chrome trace.json")
    ap.add_argument("--trace-max-hosts", type=int, default=256,
                    help="counter-track cap for the trace (default 256)")
    ap.add_argument("--stats-dir", metavar="DIR",
                    help="also write DIR/stats.shadow.json for "
                         "tools/plot_shadow.py")
    ap.add_argument("--top", type=int, default=10,
                    help="top talkers to list (default 10)")
    args = ap.parse_args(argv)

    if args.jsonl == "-":
        heartbeats = export.read_heartbeats(sys.stdin)
    else:
        with open(args.jsonl) as fh:
            heartbeats = export.read_heartbeats(fh)
    if not heartbeats:
        print("telemetry_report: no heartbeat records found",
              file=sys.stderr)
        return 1

    summary = export.summarize(heartbeats, top=args.top)
    if args.trace:
        summary["trace"] = export.write_perfetto_trace(
            heartbeats, args.trace, max_hosts=args.trace_max_hosts)
    if args.stats_dir:
        os.makedirs(args.stats_dir, exist_ok=True)
        stats_path = os.path.join(args.stats_dir, "stats.shadow.json")
        with open(stats_path, "w") as fh:
            json.dump(export.to_plot_stats(heartbeats), fh, indent=2)
        summary["stats"] = stats_path

    if args.json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        _print_table(summary)
        if args.trace:
            print(f"wrote {args.trace} "
                  f"({summary['trace']['events']} events)")
        if args.stats_dir:
            print(f"wrote {summary['stats']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
